"""The federation front door: one NDJSON endpoint over many engines.

A :class:`FedRouter` is wire-compatible with :class:`~kaboodle_tpu.serve.
server.ServeServer` — the same ops, the same structured errors, the same
``ServeClient`` works against either — but behind it every request is
placed onto one of M member engines and tracked under a ROUTER request id
(the member's rid never leaks to clients, so a request can move engines
without its identity changing).

Placement: ``preference(key)`` order on the consistent-hash ring
(key = ``tenant:n_class:seed``, so a tenant's repeats of one shape land
on the same warmed lanes), filtered to members whose pools serve the
request's N-class, tie-broken by router-tracked inflight load — the
ring's choice stands unless it is ``load_slack`` requests busier than
the least-loaded candidate (N-class-aware load scoring).

Failover: every engine namespaces its journal and spill files under its
engine-id in SHARED roots. When any op's connection to a member breaks,
the router declares it dead exactly once and replays its journal
read-only: routes whose last journaled op carries a result (or a
terminal cancel) are served from the fold and NEVER re-run; routes whose
last durable state is a spill file are ``adopt``-ed onto a survivor
(the file keeps the dead engine's owner stamp — the checkpoint guard's
sanctioned handover path); everything else re-submits from its seed with
its cumulative tick budget. Clients parked in ``wait`` ride through: the
wait loop re-resolves the route and re-issues against the survivor, so
the caller sees latency (bounded by ``retry_after_s`` backoff rounds),
never a lost result, and never a second completion for a journaled one.

Concurrency discipline: the router is single-threaded asyncio — every
table below is event-loop confined (``# conc: event-loop``), and the one
shared resource per member (its control connection) is serialized by an
``asyncio.Lock`` so concurrent ops cannot interleave frames on one
socket.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json
import os

from kaboodle_tpu.serve.client import ServeClient, ServeError
from kaboodle_tpu.serve.federation.ring import HashRing
from kaboodle_tpu.serve.journal import replay_journal
from kaboodle_tpu.serve.obsplane import MetricsRegistry

# Request fields forwarded verbatim on submit/adopt (mirrors
# server._SUBMIT_FIELDS without importing the jax-heavy engine module).
_REQ_FIELDS = ("n", "seed", "mode", "ticks", "drop_rate", "scenario",
               "keep", "tenant", "priority")

# How long a client should back off when an op lands mid-failover.
_RETRY_AFTER_S = 0.25

# Period of the background member-stats poll feeding the lane-occupancy
# gauges (a pull gauge must not RPC inside collect(), which is sync).
_STATS_POLL_S = 0.25


def _lane_n_class(n: int) -> int:
    """pow2 lane class >= 8 (serve.pool.lane_n_class without the jax
    import — the router must stay importable on a jax-free front door)."""
    return max(8, 1 << (int(n) - 1).bit_length())


@dataclasses.dataclass(frozen=True)
class EngineMember:
    """One member engine's address. ``engine_id`` must match the id the
    engine itself was started with (it names the journal/spill
    namespaces the failover replay reads)."""

    engine_id: str
    host: str
    port: int


def _error_response(e: Exception) -> dict:
    """Server.py's error mapping plus pass-through of a member's
    structured :class:`ServeError` (kind and retry-after survive the
    hop)."""
    resp = {"ok": False, "error": str(e) or type(e).__name__}
    if isinstance(e, ServeError):
        resp["kind"] = e.kind
        if e.retry_after_s:
            resp["retry_after_s"] = e.retry_after_s
    elif isinstance(e, (ValueError, KeyError, TypeError)):
        resp["kind"] = "bad_request"
    else:
        resp["kind"] = "internal"
    return resp


class FedRouter:
    """Consistent-hash request router over member :class:`ServeServer`s.

    ``journal_root`` / ``spill_root`` are the SHARED roots the members
    were started with (each member namespaces itself one level down);
    without a journal root, failover can only re-queue from seeds.
    """

    def __init__(
        self,
        members: list[EngineMember],
        host: str = "127.0.0.1",
        port: int = 0,
        journal_root: str | None = None,
        spill_root: str | None = None,
        vnodes: int = 64,
        load_slack: int = 4,
        metrics_port: int | None = None,
    ) -> None:
        if not members:
            raise ValueError("need at least one member engine")
        self.members = {m.engine_id: m for m in members}
        if len(self.members) != len(members):
            raise ValueError("duplicate engine_id among members")
        self.host = host
        self.port = port
        self.journal_root = journal_root
        self.spill_root = spill_root
        self.load_slack = int(load_slack)
        self.metrics_port = metrics_port
        self.ring = HashRing(vnodes=vnodes)  # members join on attach
        # -- event-loop confined tables (single-threaded asyncio) ----------
        self.alive: set[str] = set()  # conc: event-loop
        self._conns: dict[str, ServeClient] = {}  # conc: event-loop
        # One lock per member control connection: ServeClient is strictly
        # sequential request/response, so every forwarded op holds the
        # member's lock across its whole round trip.
        self._conn_locks: dict[str, asyncio.Lock] = {}
        self._classes: dict[str, set[int]] = {}  # conc: event-loop
        self._routes: dict[int, dict] = {}  # conc: event-loop
        self._next_rid = 0
        self._inflight: dict[str, int] = {}  # conc: event-loop
        self._lane_stats: dict[str, dict] = {}  # conc: event-loop
        self._failing: dict[str, asyncio.Future] = {}  # conc: event-loop
        self._closed = asyncio.Event()
        self._server: asyncio.base_events.Server | None = None
        self._metrics_server: asyncio.base_events.Server | None = None
        self._poll_task: asyncio.Task | None = None
        self.metrics = MetricsRegistry()
        self._bind_metrics()

    # -- metrics -----------------------------------------------------------

    def _bind_metrics(self) -> None:
        m = self.metrics
        m.register_gauge("fed_ring_members", lambda: len(self.alive))
        m.register_gauge("fed_ring_size", lambda: self.ring.size)
        m.register_gauge(
            "fed_routes_open",
            lambda: sum(1 for r in self._routes.values() if r["open"]),
        )
        m.register_multi_gauge(
            "fed_engine_inflight",
            lambda: {
                (("engine", mid),): cnt
                for mid, cnt in self._inflight.items()
            },
        )
        for stat in ("lanes_occupied", "lanes_active"):
            m.register_multi_gauge(
                f"fed_engine_{stat}",
                lambda stat=stat: {
                    (("engine", mid),): snap.get(stat, 0)
                    for mid, snap in self._lane_stats.items()
                },
            )

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        """Attach every member (control connection + served-classes
        probe), then open the front-door listener. A member that is down
        at start is declared failed immediately — the federation serves
        with whoever answered."""
        for mid, member in self.members.items():
            try:
                await self._attach(mid, member)
            except (ConnectionError, OSError):
                self.metrics.inc("fed_failovers_total")
                continue
        if not self.alive:
            raise ConnectionError("no member engine reachable")
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        if self.metrics_port is not None:
            self._metrics_server = await asyncio.start_server(
                self._handle_metrics_http, self.host, self.metrics_port
            )
            self.metrics_port = (
                self._metrics_server.sockets[0].getsockname()[1]
            )
        self._poll_task = asyncio.create_task(self._poll_stats())

    async def _attach(self, mid: str, member: EngineMember) -> None:
        conn = await ServeClient.connect(member.host, member.port)
        self._conns[mid] = conn
        self._conn_locks[mid] = asyncio.Lock()
        stats = await conn.stats()
        self._classes[mid] = {int(n) for n in stats["pools"]}
        self._lane_stats[mid] = self._fold_lane_stats(stats)
        self._inflight.setdefault(mid, 0)
        self.alive.add(mid)
        self.ring.add(mid)

    async def serve_forever(self) -> None:
        await self._closed.wait()

    async def close(self) -> None:
        self._closed.set()
        if self._poll_task is not None:
            self._poll_task.cancel()
            try:
                await self._poll_task
            except asyncio.CancelledError:
                pass
        for srv in (self._server, self._metrics_server):
            if srv is not None:
                srv.close()
                await srv.wait_closed()
        for conn in self._conns.values():
            await conn.close()

    @staticmethod
    def _fold_lane_stats(stats: dict) -> dict:
        occ = act = 0
        for snap in stats.get("pools", {}).values():
            occ += int(snap.get("occupied", 0))
            act += int(snap.get("active", 0))
        return {"lanes_occupied": occ, "lanes_active": act}

    async def _poll_stats(self) -> None:
        """Background refresh of the per-engine lane gauges (collect()
        is synchronous, so gauges read this cache, never the wire)."""
        while not self._closed.is_set():
            for mid in list(self.alive):
                try:
                    async with self._conn_locks[mid]:
                        stats = await self._conns[mid].stats()
                    self._lane_stats[mid] = self._fold_lane_stats(stats)
                except (ConnectionError, OSError, ServeError):
                    await self._fail_member(mid)
            await asyncio.sleep(_STATS_POLL_S)

    # -- placement ---------------------------------------------------------

    def _placement_key(self, fields: dict) -> str:
        return (f"{fields.get('tenant', 'default')}:"
                f"{_lane_n_class(fields.get('n', 0))}:"
                f"{fields.get('seed', 0)}")

    def _place(self, key: str, n_class: int) -> str:
        """Ring preference walk filtered by N-class, load-scored: the
        ring's pick keeps the key unless it is ``load_slack`` inflight
        requests busier than the least-loaded serving candidate."""
        prefs = [
            mid for mid in self.ring.preference(key)
            if n_class in self._classes.get(mid, ())
        ]
        if not prefs:
            raise ValueError(
                f"no live engine serves N-class {n_class}"
            )
        least = min(prefs, key=lambda m: (self._inflight[m], m))
        if self._inflight[prefs[0]] - self._inflight[least] >= self.load_slack:
            return least
        return prefs[0]

    # -- forwarded ops -----------------------------------------------------

    async def _member_rpc(self, mid: str, **op) -> dict:
        """One op on a member's control connection (serialized); a broken
        pipe fails the member over and re-raises for the caller's retry
        loop."""
        conn = self._conns.get(mid)
        if conn is None:  # lost a race with an in-progress failover
            await self._await_failover(mid)
            raise ConnectionError(f"engine {mid} is down")
        try:
            async with self._conn_locks[mid]:
                return await conn._rpc(**op)
        except (ConnectionError, OSError, asyncio.IncompleteReadError):
            await self._fail_member(mid)
            raise ConnectionError(f"engine {mid} died mid-op") from None

    async def _submit(self, op: dict) -> dict:
        fields = {k: op[k] for k in _REQ_FIELDS if k in op}
        if "n" not in fields:
            raise ValueError("submit needs n")
        n_class = _lane_n_class(fields["n"])
        key = self._placement_key(fields)
        while True:
            if not self.alive:
                raise ConnectionError("no live engine")
            mid = self._place(key, n_class)
            try:
                resp = await self._member_rpc(mid, op="submit", **fields)
            except ConnectionError:
                continue  # re-place on the survivors
            rid = self._next_rid
            self._next_rid += 1
            self._routes[rid] = {
                "member": mid, "member_rid": int(resp["request_id"]),
                "fields": fields, "key": key, "n_class": n_class,
                "cached": None, "open": True,
            }
            self._inflight[mid] += 1
            self.metrics.inc("fed_submits_total", engine=mid)
            return {"ok": True, "request_id": rid}

    def _route(self, op: dict) -> tuple[int, dict]:
        rid = int(op["request_id"])
        route = self._routes.get(rid)
        if route is None:
            raise KeyError(f"unknown request {rid}")
        return rid, route

    def _translate(self, rid: int, route: dict, row: dict | None) -> dict | None:
        """A member status row under the router's rid. Only TERMINAL rows
        are cached: a kept request's harvested-but-parked row still
        changes state (park -> spill -> restore), so caching it would
        serve stale rows — and would hide it from failover adoption."""
        if row is None:
            return None
        row = dict(row)
        row["request_id"] = rid
        row["engine"] = route["member"]
        if row["state"] in ("done", "cancelled"):
            route["cached"] = row
            self._settle(route)
        return row

    def _settle(self, route: dict) -> None:
        if route["open"]:
            route["open"] = False
            mid = route["member"]
            if mid in self._inflight and self._inflight[mid] > 0:
                self._inflight[mid] -= 1

    async def _wait(self, op: dict) -> dict:
        rid, route = self._route(op)
        while True:
            if route["cached"] is not None:
                return {"ok": True, "status": route["cached"]}
            mid = route["member"]
            if mid not in self.alive:
                await self._await_failover(mid)
                continue
            member = self.members[mid]
            try:
                # A wait parks for the request's whole service time: it
                # gets its own connection so the member's control channel
                # stays free for short ops (loadgen's pattern).
                c = await ServeClient.connect(member.host, member.port)
                try:
                    row = await c.wait(route["member_rid"])
                finally:
                    await c.close()
            except (ConnectionError, OSError, asyncio.IncompleteReadError):
                await self._fail_member(mid)
                continue
            if route["member"] != mid:
                continue  # moved while we waited; re-issue on the survivor
            return {"ok": True, "status": self._translate(rid, route, row)}

    async def _status(self, op: dict) -> dict:
        if op.get("request_id") is None:
            rows = []
            for rid, route in self._routes.items():
                rows.append(route["cached"] or {
                    "request_id": rid, "state": "remote",
                    "engine": route["member"],
                })
            return {"ok": True, "status": rows}
        rid, route = self._route(op)
        if route["cached"] is not None:
            return {"ok": True, "status": route["cached"]}
        mid = route["member"]
        if mid not in self.alive:
            await self._await_failover(mid)
            return await self._status(op)
        try:
            resp = await self._member_rpc(
                mid, op="status", request_id=route["member_rid"]
            )
        except ConnectionError:
            return await self._status(op)
        return {"ok": True,
                "status": self._translate(rid, route, resp["status"])}

    async def _forward_simple(self, name: str, op: dict) -> dict:
        """cancel/restore/resume: forward under the member rid; a dead
        member triggers failover and the op retries on the new route."""
        rid, route = self._route(op)
        while True:
            mid = route["member"]
            if mid not in self.alive:
                await self._await_failover(mid)
                if route["member"] not in self.alive:
                    # Failover resolved this route from the journal (or
                    # had no survivor): there is no live lane to act on.
                    if name == "cancel":
                        return {"ok": True, "cancelled": False}
                    raise ValueError(
                        f"request {rid} resolved from a dead engine's "
                        f"journal; nothing to {name}"
                    )
                continue
            kw = {k: op[k] for k in ("mode", "ticks") if k in op}
            try:
                resp = await self._member_rpc(
                    mid, op=name, request_id=route["member_rid"], **kw
                )
            except ConnectionError:
                continue
            if name == "cancel" and resp.get("cancelled"):
                route["cached"] = {
                    "request_id": rid, "state": "cancelled",
                    "engine": mid,
                }
                self._settle(route)
            if name == "resume":
                # The continuation's harvest replaces any cached result.
                route["cached"] = None
                if not route["open"]:
                    route["open"] = True
                    self._inflight[mid] += 1
            resp.pop("request_id", None)
            return resp

    async def _stats(self) -> dict:
        per_member = {}
        for mid in list(self.alive):
            try:
                resp = await self._member_rpc(mid, op="stats")
                per_member[mid] = resp["stats"]
            except ConnectionError:
                continue
        return {"ok": True, "stats": {
            "router": True,
            "members": sorted(self.members),
            "alive": sorted(self.alive),
            "routes": len(self._routes),
            "inflight": dict(self._inflight),
            "per_member": per_member,
        }}

    # -- failover ----------------------------------------------------------

    async def _await_failover(self, mid: str) -> None:
        fut = self._failing.get(mid)
        if fut is not None:
            await fut

    async def _fail_member(self, mid: str) -> None:
        """Declare ``mid`` dead exactly once and re-home its routes.

        Concurrent callers (every op that hit the broken socket) await
        the one in-progress failover future instead of racing the
        replay."""
        if mid not in self.alive:
            await self._await_failover(mid)
            return
        fut = asyncio.get_running_loop().create_future()
        self._failing[mid] = fut
        try:
            self.alive.discard(mid)
            self.ring.remove(mid)
            self._lane_stats.pop(mid, None)
            self.metrics.inc("fed_failovers_total")
            conn = self._conns.pop(mid, None)
            if conn is not None:
                await conn.close()
            table: dict[int, dict] = {}
            if self.journal_root is not None:
                try:
                    table, _ = replay_journal(
                        os.path.join(self.journal_root, mid)
                    )
                except (OSError, ValueError):
                    table = {}
            for rid, route in list(self._routes.items()):
                if route["member"] != mid or route["cached"] is not None:
                    continue
                await self._rehome(rid, route, table.get(route["member_rid"]))
        finally:
            fut.set_result(None)
            del self._failing[mid]

    async def _rehome(self, rid: int, route: dict, jrow: dict | None) -> None:
        """One dead route's disposition, from the dead engine's journal:
        journaled results are final (replayed-never), durable spills are
        adopted, the rest re-runs from seed with cumulative ticks."""
        dead = route["member"]
        jrow = jrow or {}
        result = jrow.get("result")
        # 1. Terminal cancel in the journal: final, never re-run.
        if jrow.get("op") in ("cancelled", "shed"):
            route["cached"] = {"request_id": rid, "state": "cancelled",
                              "engine": dead}
            self._settle(route)
            return
        self._settle(route)  # the dead engine's inflight slot is gone
        # 2. Durable spill: adopt the file onto a survivor. A kept
        # request may carry BOTH a harvested result and a spill file —
        # the result answers the outstanding wait, the adoption keeps
        # restore/resume live on the survivor, so both are applied.
        req = jrow.get("req") or dict(route["fields"])
        spill_path = jrow.get("spill_path")
        if spill_path and os.path.exists(spill_path):
            owner = jrow.get("spill_owner") or dead
            try:
                mid = self._place(route["key"], route["n_class"])
                resp = await self._member_rpc(
                    mid, op="adopt", spill_path=spill_path,
                    saved_run=jrow.get("saved_run"), owner=owner,
                    **{k: v for k, v in req.items() if k in _REQ_FIELDS},
                )
                route.update(member=mid, member_rid=int(resp["request_id"]),
                             open=False)
                self.metrics.inc("fed_rebalance_moves_total")
                if result is not None:
                    route["cached"] = {
                        "request_id": rid, "state": "done", "engine": mid,
                        "n": route["fields"].get("n"),
                        "n_class": route["n_class"], "result": result,
                    }
                return
            except (ConnectionError, ServeError, ValueError):
                pass  # fall through: the result (if any) is still final
        # 3. Harvested result without an adoptable file: the answer is in
        # the journal — serve it forever, never recompute it.
        if result is not None:
            route["cached"] = {
                "request_id": rid, "state": "done", "engine": dead,
                "n": route["fields"].get("n"),
                "n_class": route["n_class"], "result": result,
            }
            return
        # 4. Lost with the process: re-run from the seed, cumulative budget.
        fields = {k: v for k, v in req.items() if k in _REQ_FIELDS}
        extra = int(jrow.get("extra_ticks", 0))
        if extra:
            fields["ticks"] = int(fields.get("ticks", 64)) + extra
        while self.alive:
            try:
                mid = self._place(route["key"], route["n_class"])
                resp = await self._member_rpc(mid, op="submit", **fields)
            except ConnectionError:
                continue
            except ValueError:
                break  # no survivor serves this class
            route.update(member=mid, member_rid=int(resp["request_id"]),
                         open=True)
            self._inflight[mid] += 1
            self.metrics.inc("fed_rebalance_moves_total")
            self.metrics.inc("fed_submits_total", engine=mid)
            return
        route["cached"] = {"request_id": rid, "state": "cancelled",
                           "engine": dead, "error": "no survivor"}

    # -- wire front door ---------------------------------------------------

    async def _handle(self, reader, writer) -> None:
        try:
            while not self._closed.is_set():
                line = await reader.readline()
                if not line:
                    break
                try:
                    op = json.loads(line)
                    if not isinstance(op, dict):
                        raise ValueError(f"op must be an object, got {op!r}")
                    resp = await self._dispatch(op)
                except ConnectionError as e:
                    # Mid-failover: tell the client when to come back
                    # rather than holding its whole line behind a replay.
                    resp = {"ok": False, "error": str(e),
                            "kind": "failover",
                            "retry_after_s": _RETRY_AFTER_S}
                except Exception as e:
                    resp = _error_response(e)
                writer.write(json.dumps(resp).encode() + b"\n")
                await writer.drain()
        finally:
            writer.close()

    async def _dispatch(self, op: dict) -> dict:
        name = op.get("op")
        if name == "submit":
            return await self._submit(op)
        if name == "wait":
            return await self._wait(op)
        if name == "status":
            return await self._status(op)
        if name in ("cancel", "restore", "resume"):
            return await self._forward_simple(name, op)
        if name == "stats":
            return await self._stats()
        if name == "metrics":
            return {"ok": True, "metrics": self.metrics.collect()}
        if name == "shutdown":
            self._closed.set()
            return {"ok": True, "bye": True}
        return {"ok": False, "error": f"unknown op {name!r}",
                "kind": "bad_request"}

    async def _handle_metrics_http(self, reader, writer) -> None:
        """Prometheus text scrape, server.py's stdlib-only shape."""
        try:
            while (await reader.readline()).strip():
                pass
            body = self.metrics.to_prometheus().encode()
            writer.write(
                b"HTTP/1.0 200 OK\r\n"
                b"Content-Type: text/plain; version=0.0.4\r\n"
                b"Content-Length: " + str(len(body)).encode() + b"\r\n"
                b"\r\n" + body
            )
            await writer.drain()
        except (ConnectionResetError, BrokenPipeError):
            pass
        finally:
            writer.close()


def parse_members(spec: str) -> list[EngineMember]:
    """``e0=127.0.0.1:7501,e1=127.0.0.1:7502`` -> members (the
    ``serve --federated`` flag grammar)."""
    out = []
    for tok in spec.split(","):
        tok = tok.strip()
        if not tok:
            continue
        eid, _, addr = tok.partition("=")
        host, _, port = addr.rpartition(":")
        if not eid or not host or not port:
            raise ValueError(
                f"bad member {tok!r} (want id=host:port)"
            )
        out.append(EngineMember(eid, host, int(port)))
    return out
