"""Write-ahead journal of request lifecycle transitions (crash recovery).

A crashed PR 10 engine forgot every in-flight request: the host-side
``_requests`` table was the only record. The journal makes the table
reconstructible: every lifecycle transition is appended to
``<dir>/wal.jsonl`` (one JSON object per line, flushed per append) BEFORE
the engine acts on it, and a periodic compaction snapshots the folded
table into ``<dir>/manifest.json`` (atomic fsync-then-rename) and
truncates the WAL — replay cost stays O(live transitions), not O(service
lifetime).

Record shape: ``{"op": <transition>, "rid": <id>, ...fields}``. The ops
the engine writes: ``submitted`` (full request fields), ``admitted``,
``harvested`` (terminal event + result), ``resumed``,
``spill_begin``, ``spilled`` (path + the host run counters frozen at
spill time), ``spill_failed``, ``restored``, ``cancelled``, ``shed``,
``requeued``.

:meth:`ServeJournal.replay` folds manifest + WAL into a per-rid table of
last-known states; ``ServeEngine.recover`` turns that into a live request
table — terminal rows keep their results (nothing replays twice), spilled
rows re-attach to their files, everything whose lane state died with the
process re-queues from its seed. A half-written last WAL line (the crash
landed mid-append) is ignored, not fatal.
"""

from __future__ import annotations

import json
import os
import time

JOURNAL_VERSION = 1


def _write_json_atomic(path: str, obj) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(obj, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


class ServeJournal:
    """Append-ahead request-lifecycle log + compacted manifest snapshot.

    ``fsync=True`` fsyncs every append (true write-ahead durability);
    default flushes to the OS per append — a process crash loses nothing,
    a power cut may lose the tail, which recovery treats as re-queueable.
    """

    def __init__(
        self,
        journal_dir: str,
        fsync: bool = False,
        compact_every: int = 256,
        owner: str | None = None,
    ) -> None:
        self.dir = os.fspath(journal_dir)
        os.makedirs(self.dir, exist_ok=True)
        self.wal_path = os.path.join(self.dir, "wal.jsonl")
        self.manifest_path = os.path.join(self.dir, "manifest.json")
        self.owner = owner
        if owner is not None:
            # Claim (or re-claim) the directory: a federation engine that
            # opens another engine's journal for WRITING is a deployment
            # bug — refused loudly here, before any append could interleave
            # two engines' transitions. Read-side failover replay goes
            # through :func:`replay_journal`, which claims nothing.
            owner_path = os.path.join(self.dir, "owner.json")
            existing = journal_owner(self.dir)
            if existing is not None and existing != owner:
                raise ValueError(
                    f"journal {self.dir} is owned by alien engine "
                    f"{existing!r} (this engine: {owner!r})"
                )
            _write_json_atomic(owner_path, {"owner": owner})
        self.fsync = bool(fsync)
        self.compact_every = int(compact_every)
        self._appends_since_compact = 0
        # Timeline origin for ``ts_us`` stamps. The engine overwrites this
        # with the observability plane's epoch after construction so journal
        # timestamps and trace spans share one monotonic axis.
        self.epoch_ns = time.monotonic_ns()
        self._seq = self._restore_seq()
        self._f = open(self.wal_path, "a")

    def _restore_seq(self) -> int:
        """Resume the sequence counter past everything already on disk, so
        seq stays strictly increasing across process restarts (pre-seq
        records simply don't participate in the max)."""
        seq = 0
        if os.path.exists(self.manifest_path):
            try:
                with open(self.manifest_path) as f:
                    seq = int(json.load(f).get("next_seq", 0))
            except (json.JSONDecodeError, ValueError, OSError):
                seq = 0
        if os.path.exists(self.wal_path):
            with open(self.wal_path) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        break  # torn tail: same tolerance as replay()
                    if "seq" in rec:
                        seq = max(seq, int(rec["seq"]) + 1)
        return seq

    # -- write side --------------------------------------------------------

    def append(self, op: str, rid: int, **fields) -> None:  # conc: event-loop
        rec = {
            "op": op,
            "rid": int(rid),
            "seq": self._seq,
            "ts_us": (time.monotonic_ns() - self.epoch_ns) // 1000,
        }
        self._seq += 1
        rec.update(fields)
        self._f.write(json.dumps(rec) + "\n")
        self._f.flush()
        if self.fsync:
            os.fsync(self._f.fileno())
        self._appends_since_compact += 1

    def should_compact(self) -> bool:
        return self._appends_since_compact >= self.compact_every

    def compact(self, table: dict[int, dict], next_rid: int) -> None:  # conc: event-loop
        """Snapshot the folded table to ``manifest.json`` and truncate the
        WAL. The snapshot lands atomically BEFORE the WAL is cut, so a
        crash between the two replays some transitions twice into the same
        folded rows — idempotent by construction."""
        snap = {
            "version": JOURNAL_VERSION,
            "next_rid": int(next_rid),
            "next_seq": self._seq,
            "requests": {str(rid): row for rid, row in table.items()},
        }
        if self.owner is not None:
            snap["owner"] = self.owner
        _write_json_atomic(self.manifest_path, snap)
        self._f.close()
        self._f = open(self.wal_path, "w")
        if self.fsync:
            os.fsync(self._f.fileno())
        self._appends_since_compact = 0

    def close(self) -> None:
        self._f.close()

    # -- read side ---------------------------------------------------------

    def replay(self) -> tuple[dict[int, dict], int]:
        """Fold manifest snapshot + WAL into ``(table, next_rid)``.

        ``table`` maps rid -> a journal-row dict: ``{"op": <last
        transition>, "req": {...}, "result": ..., "spill_path": ...,
        "saved_run": ..., "extra_ticks": <sum of ticks-mode resume
        budgets>}`` — everything recover needs, nothing engine-internal."""
        return _replay_paths(self.manifest_path, self.wal_path)

    @staticmethod
    def _fold(table: dict[int, dict], rec: dict) -> None:
        rid = int(rec["rid"])
        row = table.setdefault(
            rid,
            {
                "op": None,
                "req": None,
                "result": None,
                "spill_path": None,
                "saved_run": None,
                "extra_ticks": 0,
            },
        )
        op = rec["op"]
        row["op"] = op
        # Ordering metadata (absent from pre-seq journals; recover() falls
        # back to rid order when missing).
        if "seq" in rec:
            row["seq"] = int(rec["seq"])
        if "ts_us" in rec:
            row["ts_us"] = int(rec["ts_us"])
        if op == "submitted":
            row["req"] = rec.get("req")
        elif op == "adopted":
            # A failover handover: submitted + spilled in one record, the
            # spill file still owned (stamped) by the dead engine.
            row["req"] = rec.get("req")
            row["spill_path"] = rec.get("path")
            row["saved_run"] = rec.get("saved_run")
            row["spill_owner"] = rec.get("owner")
        elif op == "harvested":
            row["result"] = rec.get("result")
            row["event"] = rec.get("event")
        elif op == "resumed":
            # Cumulative continuation budget: a re-queued request re-runs
            # its whole trajectory, original budget plus every resume.
            if rec.get("mode") == "ticks":
                row["extra_ticks"] += int(rec.get("ticks", 0))
        elif op == "spilled":
            row["spill_path"] = rec.get("path")
            row["saved_run"] = rec.get("saved_run")
        elif op == "spill_failed":
            pass  # lane still held (or cache retried); last op stands


def _replay_paths(
    manifest_path: str, wal_path: str
) -> tuple[dict[int, dict], int]:
    table: dict[int, dict] = {}
    next_rid = 0
    if os.path.exists(manifest_path):
        with open(manifest_path) as f:
            snap = json.load(f)
        if snap.get("version") != JOURNAL_VERSION:
            raise ValueError(
                f"journal manifest version {snap.get('version')!r} != "
                f"{JOURNAL_VERSION}"
            )
        table = {int(rid): row for rid, row in snap["requests"].items()}
        next_rid = int(snap["next_rid"])
    if os.path.exists(wal_path):
        with open(wal_path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    break  # torn tail write: the crash point
                ServeJournal._fold(table, rec)
                next_rid = max(next_rid, int(rec["rid"]) + 1)
    return table, next_rid


def replay_journal(journal_dir: str) -> tuple[dict[int, dict], int]:
    """Read-only fold of an engine's journal — no append handle, no owner
    claim, no compaction. The router's failover path replays a DEAD
    engine's directory through here; constructing a :class:`ServeJournal`
    instead would steal the directory (owner claim) and truncate evidence
    a post-mortem might want."""
    d = os.fspath(journal_dir)
    return _replay_paths(
        os.path.join(d, "manifest.json"), os.path.join(d, "wal.jsonl")
    )


def journal_owner(journal_dir: str) -> str | None:
    """The engine-id that owns ``journal_dir`` (``None`` when unclaimed or
    unreadable — a single-engine era journal)."""
    path = os.path.join(os.fspath(journal_dir), "owner.json")
    try:
        with open(path) as f:
            return json.load(f).get("owner")
    except (OSError, json.JSONDecodeError, ValueError, AttributeError):
        return None


def read_journal_records(journal_dir: str) -> list[dict]:
    """Raw WAL records in replay order, for the trace exporter.

    Records carrying ``seq`` (post-PR-14 journals) are ordered by it —
    that is the crash-recovery order even when compaction interleaved
    writes.  Pre-seq records keep file order (stable sort, missing seq
    sorts first in encounter order).  Torn tails are skipped exactly like
    :meth:`ServeJournal.replay`.
    """
    path = os.path.join(os.fspath(journal_dir), "wal.jsonl")
    records: list[dict] = []
    if os.path.exists(path):
        with open(path) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    records.append(json.loads(line))
                except json.JSONDecodeError:
                    break
    records.sort(key=lambda r: int(r.get("seq", -1)))
    return records
