"""Closed+open-loop load driver for the serve server (BENCH_serve.json).

Drives a real in-process :class:`ServeServer` over TCP loopback — the
measured path includes the wire protocol, the engine round loop and every
device dispatch, exactly what a remote client would see minus network
flight time.

Two phases, both after a warmup wave that is excluded from measurement and
from the compile gate:

- **closed loop**: ``--concurrency`` workers each run submit→wait back to
  back until the request budget is spent — the saturation throughput shape
  (offered load adapts to service rate).
- **open loop**: submissions arrive on a fixed schedule at ``--rate``
  req/s regardless of completions — the latency-under-load shape (queueing
  shows up in the tail instead of throttling the arrivals).

Every completion latency is submit-to-harvest. The steady phase runs under
the KB405 compile counter and the banked report pins ``compiles_steady ==
0`` — the zero-recompile-after-warmup acceptance gate, measured on the
serving path itself.

``--overload`` (BENCH_serve_overload.json) swaps the phases for an
admission-control study: a closed-loop calibration measures capacity, then
open-loop phases offer 2x / 5x / 10x that rate with mixed tenants and
priorities against a bounded queue. Submits are pipelined raw (a rejection
is a response, not an exception), so the offered schedule really is
open-loop; the report banks goodput, shed rate (aggregate and broken down
per tenant and per priority class) and admitted-latency percentiles per
phase — the overload curves — plus the same ``compiles_steady == 0`` pin
across every phase.

``--slo`` (BENCH_serve_slo.json) is the servescope campaign (ROADMAP item
1(d)): an observability-enabled server takes open-loop waves at multiples
of calibrated capacity, and every level banks latency percentiles plus
*where the time went* — per-request queue/run span percentiles from the
traced manifest and the round profiler's admit/dispatch/harvest/spill/
journal segment deltas from the ``metrics`` RPC — alongside a Perfetto
trace artifact showing lanes, leaps, spills and journal writes on one
timeline.
"""

from __future__ import annotations

import asyncio
import json
import time

import numpy as np


def _mix_fields(i: int) -> dict:
    """The load mix: converge-mode boots and horizon-mode steady runs
    interleaved (the two service shapes; odd = warp-eligible)."""
    if i % 2:
        return {"seed": i, "mode": "ticks", "ticks": 40, "scenario": "steady"}
    return {"seed": i, "mode": "converge", "ticks": 40, "scenario": "boot"}


def _latency_stats(lat_s: list[float]) -> dict:
    # Host-list stats on the harvested latencies, not a device fetch.
    a = np.asarray(lat_s, dtype=np.float64) * 1e3  # noqa: KB501
    return {
        "p50_ms": float(np.percentile(a, 50)),
        "p99_ms": float(np.percentile(a, 99)),
        "mean_ms": float(a.mean()),
        "max_ms": float(a.max()),
    }


async def _closed_loop(client_factory, n: int, requests: int, concurrency: int):
    lat: list[float] = []
    issued = 0

    async def worker(wid: int) -> None:
        nonlocal issued
        client = await client_factory()
        try:
            while True:
                if issued >= requests:
                    return
                i = issued
                issued += 1
                t0 = time.perf_counter()
                rid = await client.submit(n, **_mix_fields(i))
                await client.wait(rid)
                lat.append(time.perf_counter() - t0)
        finally:
            await client.close()

    t0 = time.perf_counter()
    await asyncio.gather(*(worker(w) for w in range(concurrency)))
    elapsed = time.perf_counter() - t0
    return lat, elapsed


async def _open_loop(client_factory, n: int, requests: int, rate: float):
    lat: list[float] = []
    client = await client_factory()
    waiters: list[asyncio.Task] = []

    async def complete(rid: int, t0: float) -> None:
        # One wait op needs its own connection (the shared one is busy
        # submitting on schedule).
        c = await client_factory()
        try:
            await c.wait(rid)
            lat.append(time.perf_counter() - t0)
        finally:
            await c.close()

    start = time.perf_counter()
    try:
        for i in range(requests):
            due = start + i / rate
            delay = due - time.perf_counter()
            if delay > 0:
                await asyncio.sleep(delay)
            t0 = time.perf_counter()
            rid = await client.submit(n, **_mix_fields(i))
            waiters.append(asyncio.create_task(complete(rid, t0)))
        await asyncio.gather(*waiters)
    finally:
        await client.close()
    elapsed = time.perf_counter() - start
    return lat, elapsed


async def _overload_phase(client_factory, port: int, n: int,
                          rate: float, requests: int) -> dict:
    """One open-loop overload phase: ``requests`` submits offered at
    ``rate`` req/s on a raw pipelined connection (when the schedule is
    behind, lines go out back to back with no response roundtrip — a
    closed-loop client can never outrun the engine), mixed tenants and
    priorities. Rejections arrive as structured error responses; every
    admitted rid gets a waiter, and a shed admission counts against
    goodput just like a rejection."""
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    submit_t: list[float] = []
    lat: list[float] = []
    waiters: list[asyncio.Task] = []
    counts = {"completed": 0, "shed": 0, "rejected": 0}
    # Per-class fate breakdown: submit i's tenant/priority are functions
    # of i (the offer loop's mix), so every response and waiter outcome
    # attributes deterministically without echoing fields over the wire.
    by_tenant: dict[str, dict] = {}
    by_priority: dict[str, dict] = {}

    def _classes(i: int) -> tuple[dict, dict]:
        zero = {"offered": 0, "rejected": 0, "shed": 0, "completed": 0}
        return (by_tenant.setdefault(f"t{i % 3}", dict(zero)),
                by_priority.setdefault(str(i % 3), dict(zero)))

    def _count(i: int, fate: str) -> None:
        for bucket in _classes(i):
            bucket[fate] += 1

    async def complete(i: int, rid: int, t0: float) -> None:
        c = await client_factory()
        try:
            row = await c.wait(rid)
            if row["state"] == "done":
                counts["completed"] += 1
                _count(i, "completed")
                lat.append(time.perf_counter() - t0)
            else:
                counts["shed"] += 1
                _count(i, "shed")
        finally:
            await c.close()

    async def read_responses() -> None:
        for i in range(requests):
            resp = json.loads(await reader.readline())
            if resp.get("ok"):
                # submit_t[i] exists: the server can only respond to a
                # line written after its timestamp was appended.
                waiters.append(asyncio.create_task(
                    complete(i, resp["request_id"], submit_t[i])))
            else:
                counts["rejected"] += 1
                _count(i, "rejected")

    async def offer() -> None:
        start = time.perf_counter()
        for i in range(requests):
            delay = start + i / rate - time.perf_counter()
            if delay > 0:
                await writer.drain()
                await asyncio.sleep(delay)
            op = {"op": "submit", "n": n, "tenant": f"t{i % 3}",
                  "priority": i % 3, **_mix_fields(i)}
            _count(i, "offered")
            submit_t.append(time.perf_counter())
            writer.write(json.dumps(op).encode() + b"\n")
        await writer.drain()

    t0 = time.perf_counter()
    await asyncio.gather(offer(), read_responses())
    await asyncio.gather(*waiters)
    elapsed = time.perf_counter() - t0
    writer.close()
    admitted = requests - counts["rejected"]

    def _finish(buckets: dict[str, dict]) -> dict:
        for b in buckets.values():
            b["shed_rate"] = round(
                (b["rejected"] + b["shed"]) / max(b["offered"], 1), 3)
        return dict(sorted(buckets.items()))

    return {
        "offered_rps": round(rate, 2),
        "requests": requests,
        "admitted": admitted,
        "rejected": counts["rejected"],
        "shed": counts["shed"],
        "completed": counts["completed"],
        "goodput_rps": round(counts["completed"] / elapsed, 2),
        "shed_rate": round(
            (counts["rejected"] + counts["shed"]) / requests, 3),
        "by_tenant": _finish(by_tenant),
        "by_priority": _finish(by_priority),
        "elapsed_s": round(elapsed, 3),
        "latency": _latency_stats(lat) if lat else None,
    }


async def _slo_level(client_factory, n: int, requests: int, rate: float):
    """One open-loop SLO level: like :func:`_open_loop` but keeps the rid
    of every submit, so the post-run manifest pass can attribute each
    level's queue/run time from its own span records."""
    lat: list[float] = []
    rids: list[int] = []
    client = await client_factory()
    waiters: list[asyncio.Task] = []

    async def complete(rid: int, t0: float) -> None:
        c = await client_factory()
        try:
            await c.wait(rid)
            lat.append(time.perf_counter() - t0)
        finally:
            await c.close()

    start = time.perf_counter()
    try:
        for i in range(requests):
            delay = start + i / rate - time.perf_counter()
            if delay > 0:
                await asyncio.sleep(delay)
            t0 = time.perf_counter()
            rid = await client.submit(n, **_mix_fields(i))
            rids.append(rid)
            waiters.append(asyncio.create_task(complete(rid, t0)))
        await asyncio.gather(*waiters)
    finally:
        await client.close()
    return lat, time.perf_counter() - start, rids


def _span_stats(spans: list[dict], rids: set[int], phase: str) -> dict | None:
    durs = sorted(
        int(s["dur_us"]) for s in spans
        if int(s["request_id"]) in rids and s["span"] == phase
    )
    if not durs:
        return None
    pick = lambda q: durs[min(int(q * len(durs)), len(durs) - 1)]  # noqa: E731
    return {"count": len(durs), "p50_us": pick(0.50), "p90_us": pick(0.90),
            "p99_us": pick(0.99), "max_us": durs[-1],
            "total_us": sum(durs)}


def _segment_totals(metrics: dict) -> dict[str, int]:
    hists = metrics["histograms"].get("serve_round_segment_us", {})
    return {
        key.split("=", 1)[1]: int(snap["total_us"])
        for key, snap in hists.items()
    }


async def _run_slo(args) -> dict:
    """The SLO-attribution campaign (``--slo``): ROADMAP item 1(d).

    An obs-enabled server (tracing + profiler + metrics + journal +
    spill) takes a closed-loop calibration, then open-loop waves at
    ``--slo-levels`` multiples of measured capacity. Each level banks its
    latency percentiles AND where the time went, from two independent
    instruments: per-request ``queued``/``running`` span percentiles out
    of the manifest, and the round profiler's segment totals (admit /
    dispatch / harvest / spill / journal) deltaed over the level via the
    ``metrics`` RPC. A keep-wave parks lanes so the trace artifact shows
    spill + restore + journal activity on the shared timeline; the whole
    steady phase runs under the KB405 compile gate."""
    import os
    import tempfile

    from kaboodle_tpu.analysis.ir.surface import (
        assert_counter_live,
        compile_counter,
    )
    from kaboodle_tpu.serve.client import ServeClient
    from kaboodle_tpu.serve.engine import ServeEngine
    from kaboodle_tpu.serve.obsplane import ObsPlane
    from kaboodle_tpu.serve.pool import LanePool
    from kaboodle_tpu.serve.server import ServeServer

    assert_counter_live()
    base = args.out[:-5] if args.out.endswith(".json") else args.out
    manifest_path = f"{base}.manifest.jsonl"
    trace_path = f"{base}.trace.json"
    scratch = tempfile.mkdtemp(prefix="kaboodle-slo-")
    os.makedirs(os.path.join(scratch, "spill"), exist_ok=True)
    pool = LanePool(args.n, args.lanes, chunk=args.chunk)
    engine = ServeEngine(
        [pool], warp=not args.no_warp, max_leap=args.max_leap,
        spill_after=2, spill_dir=os.path.join(scratch, "spill"),
        journal_dir=os.path.join(scratch, "journal"),
        obs=ObsPlane(trace=True),
    )
    server = ServeServer(engine, port=0, manifest_path=manifest_path)
    t0 = time.perf_counter()
    engine.warmup()
    warmup_s = time.perf_counter() - t0
    await server.start()

    async def client_factory():
        return await ServeClient.connect(port=server.port)

    warm_client = await client_factory()
    for i in range(2 * args.lanes):
        rid = await warm_client.submit(args.n, **_mix_fields(i))
        await warm_client.wait(rid)
    await warm_client.close()

    levels: dict[str, dict] = {}
    with compile_counter() as box:
        cal_lat, cal_s = await _closed_loop(
            client_factory, args.n, args.requests, args.concurrency
        )
        capacity_rps = len(cal_lat) / cal_s
        # Keep-wave: parked lanes that idle out and spill mid-campaign,
        # putting spill + journal events on the trace timeline.
        keeper = await client_factory()
        kept = []
        for i in range(2):
            rid = await keeper.submit(args.n, seed=100 + i, mode="ticks",
                                      ticks=8, scenario="steady", keep=True)
            kept.append(rid)
            await keeper.wait(rid)
        probe = await client_factory()
        for mult in args.slo_levels:
            before = _segment_totals(await probe.metrics())
            lat, elapsed, rids = await _slo_level(
                client_factory, args.n, args.requests,
                rate=capacity_rps * mult,
            )
            after = _segment_totals(await probe.metrics())
            levels[f"{mult:g}x"] = {
                "offered_rps": round(capacity_rps * mult, 2),
                "requests": len(lat),
                "elapsed_s": round(elapsed, 3),
                "throughput_rps": round(len(lat) / elapsed, 2),
                "latency": _latency_stats(lat),
                "rids": rids,
                "segments_us": {
                    seg: after.get(seg, 0) - before.get(seg, 0)
                    for seg in after
                },
            }
        # Bring one kept lane back through restore->resume so the trace
        # shows the full spilled->parked->running arc.
        for rid in kept:
            row = await keeper.status(rid)
            if row and row["state"] == "spilled":
                await keeper.restore(rid)
                await keeper.resume(rid, mode="ticks", ticks=4)
                await keeper.wait(rid)
                break
        await keeper.close()
    compiles = box.count

    final_metrics = await probe.metrics()
    await probe.shutdown()
    await server.close()

    # Post-run: per-level queue/run attribution from the span records the
    # server streamed to the manifest, then the shared-timeline trace.
    from kaboodle_tpu.serve.journal import read_journal_records
    from kaboodle_tpu.telemetry.manifest import read_manifest
    from kaboodle_tpu.telemetry.trace import (
        journal_trace_events,
        serve_trace_events,
        write_chrome_trace,
    )

    records = list(read_manifest(manifest_path))
    spans = [r for r in records if r["kind"] == "serve_span"]
    for name, lvl in levels.items():
        rids = set(lvl.pop("rids"))
        seg = lvl["segments_us"]
        queued = _span_stats(spans, rids, "queued")
        running = _span_stats(spans, rids, "running")
        lvl["per_request_us"] = {"queued": queued, "running": running}
        # The four-way attribution the SLO table cites: queue time is the
        # requests' own wait, the rest is round-loop wall split by the
        # profiler (compute = dispatch+harvest, spill = poll+pacing).
        lvl["attribution_us"] = {
            "queue": queued["total_us"] if queued else 0,
            "compute": seg.get("dispatch", 0) + seg.get("harvest", 0),
            "spill": seg.get("poll", 0) + seg.get("spill", 0),
            "journal": seg.get("journal", 0),
            "admit": seg.get("admit", 0),
        }
    n_events = write_chrome_trace(
        trace_path, {},
        metadata={"bench": "serve-slo", "manifest": manifest_path},
        extra_events=(serve_trace_events(records)
                      + journal_trace_events(
                          read_journal_records(os.path.join(scratch,
                                                            "journal")))),
    )

    return {
        "bench": "serve-slo",
        "n": args.n,
        "lanes": args.lanes,
        "chunk": args.chunk,
        "warp": not args.no_warp,
        "warmup_s": round(warmup_s, 3),
        "compiles_steady": compiles,
        "compiles_steady_gauge": final_metrics["gauges"]
                                              .get("compiles_steady", {}),
        "capacity_rps": round(capacity_rps, 2),
        "calibration_latency": _latency_stats(cal_lat),
        "levels": levels,
        "round_profile": {
            seg: snap
            for seg, snap in (
                (k.split("=", 1)[1], v) for k, v in final_metrics[
                    "histograms"].get("serve_round_segment_us", {}).items()
            )
        },
        "manifest": manifest_path,
        "trace": trace_path,
        "trace_events": n_events,
    }


async def _run_overload(args) -> dict:
    from kaboodle_tpu.analysis.ir.surface import (
        assert_counter_live,
        compile_counter,
    )
    from kaboodle_tpu.serve.admission import AdmissionController
    from kaboodle_tpu.serve.client import ServeClient
    from kaboodle_tpu.serve.engine import ServeEngine
    from kaboodle_tpu.serve.pool import LanePool
    from kaboodle_tpu.serve.server import ServeServer

    assert_counter_live()
    pool = LanePool(args.n, args.lanes, chunk=args.chunk)
    admission = AdmissionController(max_queue=args.max_queue)
    engine = ServeEngine([pool], warp=not args.no_warp,
                         max_leap=args.max_leap, admission=admission)
    server = ServeServer(engine, port=0)
    t0 = time.perf_counter()
    engine.warmup()
    warmup_s = time.perf_counter() - t0
    await server.start()

    async def client_factory():
        return await ServeClient.connect(port=server.port)

    warm_client = await client_factory()
    for i in range(2 * args.lanes):
        rid = await warm_client.submit(args.n, **_mix_fields(i))
        await warm_client.wait(rid)
    await warm_client.close()

    with compile_counter() as box:
        cal_lat, cal_s = await _closed_loop(
            client_factory, args.n, args.requests, args.concurrency
        )
        capacity_rps = len(cal_lat) / cal_s
        phases = {}
        for mult in (2, 5, 10):
            phases[f"{mult}x"] = await _overload_phase(
                client_factory, server.port, args.n,
                rate=capacity_rps * mult, requests=args.requests,
            )
    compiles = box.count

    probe = await client_factory()
    stats = await probe.stats()
    await probe.shutdown()
    await server.close()

    return {
        "bench": "serve-overload",
        "n": args.n,
        "lanes": args.lanes,
        "chunk": args.chunk,
        "warp": not args.no_warp,
        "max_queue": args.max_queue,
        "warmup_s": round(warmup_s, 3),
        "compiles_steady": compiles,
        "capacity_rps": round(capacity_rps, 2),
        "calibration_latency": _latency_stats(cal_lat),
        "phases": phases,
        "engine_rounds": stats["round"],
    }


async def _run(args) -> dict:
    from kaboodle_tpu.analysis.ir.surface import (
        assert_counter_live,
        compile_counter,
    )
    from kaboodle_tpu.serve.client import ServeClient
    from kaboodle_tpu.serve.engine import ServeEngine
    from kaboodle_tpu.serve.pool import LanePool
    from kaboodle_tpu.serve.server import ServeServer

    assert_counter_live()
    pool = LanePool(args.n, args.lanes, chunk=args.chunk)
    engine = ServeEngine([pool], warp=not args.no_warp, max_leap=args.max_leap)
    server = ServeServer(engine, port=0)
    t0 = time.perf_counter()
    engine.warmup()
    warmup_s = time.perf_counter() - t0
    await server.start()

    async def client_factory():
        return await ServeClient.connect(port=server.port)

    # Warmup wave: one request per lane per mode shape, uncounted.
    warm_client = await client_factory()
    for i in range(2 * args.lanes):
        rid = await warm_client.submit(args.n, **_mix_fields(i))
        await warm_client.wait(rid)
    await warm_client.close()

    with compile_counter() as box:
        closed_lat, closed_s = await _closed_loop(
            client_factory, args.n, args.requests, args.concurrency
        )
        open_lat, open_s = await _open_loop(
            client_factory, args.n, args.requests, args.rate
        )
    compiles = box.count

    stats = None
    probe = await client_factory()
    stats = await probe.stats()
    await probe.shutdown()
    await server.close()

    return {
        "bench": "serve",
        "n": args.n,
        "lanes": args.lanes,
        "chunk": args.chunk,
        "warp": not args.no_warp,
        "warmup_s": round(warmup_s, 3),
        "compiles_steady": compiles,
        "closed": {
            "requests": len(closed_lat),
            "concurrency": args.concurrency,
            "elapsed_s": round(closed_s, 3),
            "throughput_rps": round(len(closed_lat) / closed_s, 2),
            "latency": _latency_stats(closed_lat),
        },
        "open": {
            "requests": len(open_lat),
            "offered_rps": args.rate,
            "elapsed_s": round(open_s, 3),
            "throughput_rps": round(len(open_lat) / open_s, 2),
            "latency": _latency_stats(open_lat),
        },
        "engine_rounds": stats["round"],
    }


def main(argv=None) -> int:
    """``python -m kaboodle_tpu serve-load`` — load-test the service."""
    import argparse

    parser = argparse.ArgumentParser(
        prog="kaboodle-tpu serve-load",
        description="closed+open-loop load driver over an in-process server",
    )
    parser.add_argument("--n", type=int, default=16, help="request N-class")
    parser.add_argument("--lanes", type=int, default=8)
    parser.add_argument("--chunk", type=int, default=8)
    parser.add_argument("--requests", type=int, default=64,
                        help="measured requests per phase")
    parser.add_argument("--concurrency", type=int, default=8,
                        help="closed-loop workers")
    parser.add_argument("--rate", type=float, default=50.0,
                        help="open-loop offered req/s")
    parser.add_argument("--max-leap", type=int, default=64)
    parser.add_argument("--no-warp", action="store_true")
    parser.add_argument("--overload", action="store_true",
                        help="admission-control study: calibrate capacity, "
                             "then offer 2x/5x/10x against a bounded queue")
    parser.add_argument("--slo", action="store_true",
                        help="SLO-attribution study on an obs-enabled "
                             "server: per-level latency percentiles + "
                             "queue/compute/spill/journal attribution, "
                             "plus a Perfetto trace artifact")
    parser.add_argument("--slo-levels", default="0.5,0.9,1.3",
                        help="comma-separated load multiples of calibrated "
                             "capacity for --slo")
    parser.add_argument("--max-queue", type=int, default=None,
                        help="admission queue bound for --overload "
                             "(default 2*lanes)")
    parser.add_argument("--out", default=None)
    args = parser.parse_args(argv)
    if args.max_queue is None:
        args.max_queue = 2 * args.lanes
    if args.out is None:
        args.out = ("BENCH_serve_overload.json" if args.overload
                    else "BENCH_serve_slo.json" if args.slo
                    else "BENCH_serve.json")
    args.slo_levels = [float(tok) for tok in args.slo_levels.split(",")]

    report = asyncio.run(
        _run_overload(args) if args.overload
        else _run_slo(args) if args.slo
        else _run(args))
    with open(args.out, "w") as f:
        json.dump(report, f, indent=2)
        f.write("\n")
    print(json.dumps(report))
    if report["compiles_steady"] != 0:
        print(f"FAIL: {report['compiles_steady']} fresh compiles in the "
              "steady phase (zero-recompile gate)")
        return 1
    return 0
