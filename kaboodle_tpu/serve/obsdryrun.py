"""The servescope CI lane: traced lifecycle, metrics plane, overhead A/B.

``make serve-obs-dryrun`` (= ``python -m kaboodle_tpu serve --obs-dryrun``)
boots the full observability stack — obs-enabled engine, server with
manifest + Prometheus endpoint — and asserts the plane's contracts:

1. **zero fresh compiles with the plane attached**: the whole traced
   lifecycle (admit, leap and chunk rounds, park, spill, restore, resume,
   cancel) runs under the KB405 compile counter AND the plane's own
   ``compiles_steady`` gauge, both pinned to 0 — observability must not
   perturb the zero-recompile serving contract;
2. **exposition works end to end**: the ``metrics`` RPC returns the
   registry snapshot, the HTTP endpoint serves Prometheus text with the
   expected families, and the streamed manifest passes the schema gate,
   the ``--serve-report`` waterfall and the Perfetto export (with the
   journal track) — every consumer surface, exercised;
3. **observer purity + <= 5 % overhead**: an obs-on engine and an obs-off
   engine driven through the identical scripted workload end bit-exact
   (host vectors and device member state leaf-for-leaf), and the obs-on
   median round time stays within 5 % of obs-off (same bar tickscope set
   for the on-device counter plane).

Prints a one-line JSON tail for the CI log.
"""

from __future__ import annotations

import asyncio
import json
import os
import tempfile
import time

_WAIT_S = 30.0


async def _traced_lifecycle(report: dict, tmp: str) -> str:
    """Phase 1+2: full lifecycle over an obs server; returns the manifest
    path for the exporter phase."""
    from kaboodle_tpu.analysis.ir.surface import (
        assert_counter_live,
        compile_counter,
    )
    from kaboodle_tpu.serve.client import ServeClient
    from kaboodle_tpu.serve.engine import ServeEngine
    from kaboodle_tpu.serve.pool import LanePool
    from kaboodle_tpu.serve.server import ServeServer

    assert_counter_live()
    manifest_path = os.path.join(tmp, "obs.manifest.jsonl")
    engine = ServeEngine(
        [LanePool(16, 4, chunk=8)], warp=True, max_leap=64,
        spill_after=2, spill_dir=tmp,
        journal_dir=os.path.join(tmp, "journal"),
        obs=True,
    )
    server = ServeServer(engine, port=0, manifest_path=manifest_path,
                         metrics_port=0)
    engine.warmup()
    await server.start()
    client = await ServeClient.connect(port=server.port)

    with compile_counter() as box:
        rids = []
        for i in range(8):
            horizon = bool(i % 2)
            rids.append(await client.submit(
                16, seed=i,
                mode="ticks" if horizon else "converge",
                ticks=40,
                scenario="steady" if horizon else "boot",
                keep=(i == 0),
            ))
        for rid in rids:
            await asyncio.wait_for(client.wait(rid), _WAIT_S)

        kept = rids[0]

        async def _await_state(rid: int, state: str) -> dict:
            while True:
                row = await client.status(rid)
                if row["state"] == state:
                    return row
                await asyncio.sleep(0.01)

        await asyncio.wait_for(_await_state(kept, "spilled"), _WAIT_S)
        assert await client.restore(kept)
        await client.resume(kept, mode="ticks", ticks=8)
        await asyncio.wait_for(client.wait(kept), _WAIT_S)
        await client.cancel(kept)

        # -- the metrics RPC, under the counter: a scrape costs no compile.
        metrics = await client.metrics()
    report["compiles_lifecycle"] = box.count
    gauge = metrics["gauges"]["compiles_steady"][""]
    report["compiles_steady_gauge"] = gauge
    assert gauge == 0, metrics["gauges"]
    assert box.count == 0, box.count
    counters = metrics["counters"]["serve_events_total"]
    for needed in ("event=admitted", "event=spilled", "event=restored",
                   "event=resumed", "event=cancelled"):
        assert needed in counters, (needed, sorted(counters))
    segs = metrics["histograms"]["serve_round_segment_us"]
    assert segs["segment=round"]["count"] > 0, segs
    report["rounds_profiled"] = segs["segment=round"]["count"]

    # -- Prometheus endpoint: one real HTTP scrape.
    reader, writer = await asyncio.open_connection(
        "127.0.0.1", server.metrics_port)
    writer.write(b"GET /metrics HTTP/1.0\r\n\r\n")
    await writer.drain()
    raw = await asyncio.wait_for(reader.read(), _WAIT_S)
    writer.close()
    head, _, body = raw.partition(b"\r\n\r\n")
    assert b"200 OK" in head, head
    text = body.decode()
    for family in ("# TYPE serve_events_total counter",
                   "# TYPE compiles_steady gauge",
                   "# TYPE serve_round_segment_us summary"):
        assert family in text, (family, text.splitlines()[:5])
    report["prometheus_lines"] = len(text.splitlines())

    await client.shutdown()
    await server.close()
    return manifest_path


def _script_engine(obs):
    """One engine + the scripted workload both A/B sides run verbatim.

    Dense (no-warp) horizon runs over a 16-tick chunk: every measured
    round is a real serve-step dispatch, so the overhead ratio compares
    the plane's cost against the work a busy round actually does — idle
    rounds are microseconds of bookkeeping where a fixed ~tens-of-us
    tracing cost would swamp the ratio while being irrelevant to service
    latency."""
    from kaboodle_tpu.serve.engine import ServeEngine, ServeRequest
    from kaboodle_tpu.serve.pool import LanePool

    engine = ServeEngine([LanePool(16, 4, chunk=16)], warp=False, obs=obs)
    engine.warmup()
    for i in range(12):
        engine.submit(ServeRequest(
            n=16, seed=i, mode="ticks", ticks=128, scenario="steady",
        ))
    return engine


def _ab_purity_and_overhead(report: dict) -> None:
    """Phase 3: identical workloads, obs on vs off — bit-exact state,
    median busy-round overhead <= 5 %."""
    import jax
    import numpy as np

    def run(obs):
        engine = _script_engine(obs)
        times = []
        while engine.busy:  # busy rounds only: real dispatch per sample
            t0 = time.perf_counter_ns()
            engine.step()
            times.append(time.perf_counter_ns() - t0)
        pool = engine.pools[16]
        host = {
            name: np.array(getattr(pool, name))
            for name in ("occupied", "active", "until_conv", "remaining",
                         "ticks_run", "conv_tick", "generation")
        }
        members = [pool.member(lane) for lane in range(pool.lanes)]
        table = {
            rid: {k: row[k] for k in ("state", "result", "pool", "lane")}
            for rid, row in engine._requests.items()
        }
        engine.close()
        return times, host, members, table

    # A transient host-load spike during either arm inflates the apparent
    # overhead but can never deflate it below the true cost, so the bound
    # is gated on the best of up to 3 paired attempts — any attempt within
    # the bar proves the plane's cost is within the bar. Bit-exactness is
    # deterministic; one check suffices.
    overheads: list[float] = []
    for attempt in range(3):
        times_off, host_off, members_off, table_off = run(obs=False)
        times_on, host_on, members_on, table_on = run(obs=True)

        if attempt == 0:
            assert table_on == table_off, (
                "request tables diverged under tracing")
            for name in host_off:
                assert np.array_equal(host_off[name], host_on[name]), (
                    f"pool.{name} diverged under tracing")
            for lane, (a, b) in enumerate(zip(members_off, members_on)):
                la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
                assert len(la) == len(lb)
                for x, y in zip(la, lb):
                    x, y = np.asarray(x), np.asarray(y)
                    eq = np.issubdtype(x.dtype, np.floating)
                    assert np.array_equal(x, y, equal_nan=eq), (
                        f"lane {lane} member state diverged under tracing")
            report["bitexact_ab"] = True

        assert len(times_off) == len(times_on), (
            len(times_off), len(times_on))
        report["ab_rounds"] = len(times_off)
        med_off = sorted(times_off)[len(times_off) // 2]
        med_on = sorted(times_on)[len(times_on) // 2]
        overheads.append(med_on / med_off - 1.0)
        if overheads[-1] <= 0.05:
            report["round_median_off_us"] = med_off // 1000
            report["round_median_on_us"] = med_on // 1000
            break
    overhead = min(overheads)
    report["obs_overhead_pct"] = round(overhead * 100, 2)
    report["ab_attempts"] = len(overheads)
    assert overhead <= 0.05, (
        f"observability overhead {overhead:.1%} > 5% on every attempt "
        f"({[round(o * 100, 1) for o in overheads]}%)")


def _exporters(report: dict, manifest_path: str, tmp: str) -> None:
    """Phase 2 (continued): every downstream consumer of the manifest."""
    from kaboodle_tpu.telemetry.summary import main as telemetry_main

    trace_path = os.path.join(tmp, "obs.trace.json")
    assert telemetry_main([manifest_path, "--check"]) == 0
    assert telemetry_main([manifest_path, "--serve-report"]) == 0
    assert telemetry_main([
        manifest_path, "--serve-report",
        "--trace", trace_path, "--phase-program", "off",
        "--journal", os.path.join(tmp, "journal"),
    ]) == 0
    with open(trace_path) as f:
        doc = json.load(f)
    names = [e["name"] for e in doc["traceEvents"]]
    assert any(n.startswith("leap x") for n in names), "no leap slices"
    assert any(n.startswith("r") and ":" in n for n in names), \
        "no request spans"
    assert any(n.startswith("spill") for n in names), "no spill events"
    procs = {e["args"]["name"] for e in doc["traceEvents"]
             if e["name"] == "process_name"}
    assert "serve journal (WAL)" in procs, procs
    report["trace_events"] = len(doc["traceEvents"])


def run_obs_dryrun() -> int:
    report: dict = {"dryrun": "serve-obs"}
    tmp = tempfile.mkdtemp(prefix="kaboodle-obs-dryrun-")
    os.makedirs(os.path.join(tmp, "journal"), exist_ok=True)
    manifest_path = asyncio.run(_traced_lifecycle(report, tmp))
    _exporters(report, manifest_path, tmp)
    _ab_purity_and_overhead(report)
    report["ok"] = True
    print(json.dumps(report))
    return 0
