"""servescope — the serve stack's observability plane (ISSUE 14).

PR 5 (tickscope) made the *protocol* observable on-device; this module
makes the *service* observable on the host. Three instruments, one plane:

- **Per-request lifecycle tracing** — the engine drives
  :meth:`ObsPlane.transition` at every lifecycle edge the journal already
  witnesses, and each closing phase yields one ``serve_span`` record
  (``kaboodle-telemetry/1``) carrying ``request_id``, the phase name
  (``queued`` / ``running`` / ``parked`` / ``spilling`` / ``spilled``),
  monotonic ``t0_us`` / ``dur_us`` relative to the plane's epoch, and the
  terminal ``fate``. The engine adds pool-level ``advance`` spans (leap
  rounds annotated per lane with the Warp 2.0 signature class) and
  ``round`` spans (the profiler's segment split), all on the SAME
  monotonic timeline — telemetry/trace.py renders them as per-lane
  Perfetto tracks where leaps, spills and journal writes line up.
- **Round-loop profiler** — :class:`RoundProfiler` accumulates
  ``perf_counter_ns`` laps into a fixed set of segments (spill poll,
  admission, dispatch, harvest, spill pacing, journal append) and folds
  each finished round into preallocated log2-microsecond
  :class:`Histogram` buckets. Nothing is allocated per round — the
  accumulators are numpy vectors written in place — so the steady-state
  cost is a handful of clock reads (asserted <= 5 % by the obs dryrun,
  same bar tickscope set for the counter plane).
- **Metrics registry + exposition** — :class:`MetricsRegistry` holds
  counters (event totals, per-tenant sheds, spill failures), pull-model
  gauges (queue depth, lane occupancy by N-class, spill-writer queue
  depth, journal lag, warp leap cache hits, the ``compiles_steady``
  gauge riding the KB405 compile-event stream) and the profiler
  histograms; ``collect()`` feeds the server's ``metrics`` RPC and
  ``to_prometheus()`` the text endpoint.

The plane is an OBSERVER: it never touches pool or mesh state, so an
engine with tracing on is bit-identical to one with it off (pinned by
tests/test_obsplane.py). Everything here is host-side stdlib + numpy;
nothing is traced and nothing compiles — the KB405 surface stays flat.
"""

from __future__ import annotations

import contextlib
import time

import numpy as np

from kaboodle_tpu.telemetry.manifest import run_record

# Histogram buckets are log2 microseconds: bucket b holds durations whose
# bit_length is b, i.e. [2^(b-1), 2^b - 1] us (bucket 0 holds 0 us). 28
# buckets cap at ~134 s — far past any round segment worth resolving.
N_BUCKETS = 28

# Round-loop segments, in execution order. ``round`` is the whole-loop
# envelope the others subdivide (journal includes per-append WAL writes
# plus compaction).
SEGMENTS = ("poll", "admit", "dispatch", "harvest", "spill", "journal",
            "round")
(SEG_POLL, SEG_ADMIT, SEG_DISPATCH, SEG_HARVEST, SEG_SPILL, SEG_JOURNAL,
 SEG_ROUND) = range(len(SEGMENTS))


class Histogram:
    """Fixed log2-us buckets; in-place increments, no per-observe allocation."""

    __slots__ = ("buckets", "count", "total_us", "max_us")

    def __init__(self) -> None:
        self.buckets = np.zeros((N_BUCKETS,), dtype=np.int64)
        self.count = 0
        self.total_us = 0
        self.max_us = 0

    def observe(self, us: int) -> None:
        us = int(us)
        self.buckets[min(us.bit_length(), N_BUCKETS - 1)] += 1
        self.count += 1
        self.total_us += us
        if us > self.max_us:
            self.max_us = us

    def quantile(self, q: float) -> int:
        """Upper bound (us) of the bucket holding the q-quantile sample.

        Bucket resolution is a factor of 2 — the right precision for "did
        p99 move a decade", which is what SLO curves ask."""
        if self.count == 0:
            return 0
        target = q * self.count
        cum = 0
        for b in range(N_BUCKETS):
            cum += int(self.buckets[b])
            if cum >= target:
                return (1 << b) - 1 if b else 0
        return self.max_us

    def snapshot(self) -> dict:
        out = {
            "count": self.count,
            "total_us": self.total_us,
            "max_us": self.max_us,
            "p50_us": self.quantile(0.50),
            "p90_us": self.quantile(0.90),
            "p99_us": self.quantile(0.99),
        }
        if self.count:
            out["mean_us"] = round(self.total_us / self.count, 1)
        return out


def _label_key(labels: dict) -> tuple:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


def _label_str(key: tuple) -> str:
    return ",".join(f"{k}={v}" for k, v in key)


def _prom_labels(key: tuple) -> str:
    if not key:
        return ""
    return "{" + ",".join(f'{k}="{v}"' for k, v in key) + "}"


class MetricsRegistry:
    """Counters, gauges and histograms with flat ``name{label=value}`` keys.

    Counters are push-model (the plane bumps them as events fan out);
    gauges are PULL-model — registered once as zero-arg callables and
    evaluated only at :meth:`collect` / :meth:`to_prometheus` time, so a
    gauge costs the round loop nothing. ``register_multi_gauge`` covers
    dynamic label sets (per-tenant quota levels) with one callable
    returning ``{label_dict: value}``.
    """

    def __init__(self) -> None:
        self._counters: dict[str, dict[tuple, float]] = {}
        self._gauges: dict[str, dict[tuple, object]] = {}
        self._multi: dict[str, object] = {}
        self._hists: dict[str, dict[tuple, Histogram]] = {}

    # -- write side --------------------------------------------------------

    def inc(self, name: str, value: float = 1, **labels) -> None:
        series = self._counters.setdefault(name, {})
        key = _label_key(labels)
        series[key] = series.get(key, 0) + value

    def set_gauge(self, name: str, value: float, **labels) -> None:
        self._gauges.setdefault(name, {})[_label_key(labels)] = value

    def register_gauge(self, name: str, fn, **labels) -> None:
        """A zero-arg callable evaluated at collection time."""
        self._gauges.setdefault(name, {})[_label_key(labels)] = fn

    def register_multi_gauge(self, name: str, fn) -> None:
        """``fn() -> {label_dict: value}`` — dynamic label sets."""
        self._multi[name] = fn

    def histogram(self, name: str, **labels) -> Histogram:
        series = self._hists.setdefault(name, {})
        key = _label_key(labels)
        h = series.get(key)
        if h is None:
            h = series[key] = Histogram()
        return h

    def attach_histogram(self, name: str, hist: Histogram, **labels) -> None:
        """Expose an externally-owned :class:`Histogram` (the round
        profiler's segment histograms) under this registry's namespace —
        shared object, no copying, so collection always sees live totals."""
        self._hists.setdefault(name, {})[_label_key(labels)] = hist

    # -- read side ---------------------------------------------------------

    def _gauge_items(self):
        for name, series in self._gauges.items():
            for key, v in series.items():
                yield name, key, float(v() if callable(v) else v)
        for name, fn in self._multi.items():
            for labels, v in fn().items():
                yield name, _label_key(dict(labels)), float(v)

    def collect(self) -> dict:  # conc: event-loop
        """JSON-able snapshot (the ``metrics`` RPC payload)."""
        return {
            "counters": {
                name: {_label_str(k): v for k, v in series.items()}
                for name, series in self._counters.items()
            },
            "gauges": self._collected_gauges(),
            "histograms": {
                name: {_label_str(k): h.snapshot() for k, h in series.items()}
                for name, series in self._hists.items()
            },
        }

    def _collected_gauges(self) -> dict:
        out: dict[str, dict] = {}
        for name, key, v in self._gauge_items():
            out.setdefault(name, {})[_label_str(key)] = v
        return out

    def to_prometheus(self) -> str:
        """Prometheus text exposition (counters, gauges, summary quantiles)."""
        lines: list[str] = []
        for name in sorted(self._counters):
            lines.append(f"# TYPE {name} counter")
            for key, v in sorted(self._counters[name].items()):
                lines.append(f"{name}{_prom_labels(key)} {v:g}")
        gauges: dict[str, list] = {}
        for name, key, v in self._gauge_items():
            gauges.setdefault(name, []).append((key, v))
        for name in sorted(gauges):
            lines.append(f"# TYPE {name} gauge")
            for key, v in sorted(gauges[name]):
                lines.append(f"{name}{_prom_labels(key)} {v:g}")
        for name in sorted(self._hists):
            lines.append(f"# TYPE {name} summary")
            for key, h in sorted(self._hists[name].items()):
                for q in (0.5, 0.9, 0.99):
                    qkey = key + (("quantile", f"{q:g}"),)
                    lines.append(
                        f"{name}{_prom_labels(qkey)} {h.quantile(q)}"
                    )
                lines.append(f"{name}_sum{_prom_labels(key)} {h.total_us}")
                lines.append(f"{name}_count{_prom_labels(key)} {h.count}")
        return "\n".join(lines) + "\n"


class RoundProfiler:
    """Per-round segment timing: preallocated accumulators, log2 histograms.

    The engine brackets each round-loop section with :meth:`mark` /
    :meth:`lap`; :meth:`round_end` folds the round's accumulated
    nanoseconds into one :class:`Histogram` per segment. All per-round
    state is two preallocated int64 vectors written in place.
    """

    def __init__(self) -> None:
        self.hist = tuple(Histogram() for _ in SEGMENTS)
        self._acc = np.zeros((len(SEGMENTS),), dtype=np.int64)  # ns
        self.last_us = np.zeros((len(SEGMENTS),), dtype=np.int64)
        self._t_round = 0
        self.rounds = 0

    @staticmethod
    def mark() -> int:
        return time.perf_counter_ns()

    def lap(self, seg: int, t0: int) -> int:
        """Charge now - t0 ns to ``seg``; returns now (the next mark)."""
        now = time.perf_counter_ns()
        self._acc[seg] += now - t0
        return now

    def add_ns(self, seg: int, dns: int) -> None:
        self._acc[seg] += dns

    def round_begin(self) -> int:
        self._acc[:] = 0
        self._t_round = time.perf_counter_ns()
        return self._t_round

    def round_end(self) -> None:  # conc: event-loop
        self._acc[SEG_ROUND] = time.perf_counter_ns() - self._t_round
        np.floor_divide(self._acc, 1000, out=self.last_us)
        for i, h in enumerate(self.hist):
            h.observe(int(self.last_us[i]))
        self.rounds += 1

    def last_segments(self) -> dict[str, int]:
        """This round's per-segment microseconds (the ``round`` span args)."""
        return {
            SEGMENTS[i]: int(self.last_us[i]) for i in range(SEG_ROUND)
        }

    def snapshot(self) -> dict:
        return {SEGMENTS[i]: h.snapshot() for i, h in enumerate(self.hist)}

    def totals_us(self) -> dict[str, int]:
        return {SEGMENTS[i]: h.total_us for i, h in enumerate(self.hist)}


class ObsPlane:
    """The engine-side observability plane: tracer + profiler + registry.

    Construct one per engine and pass it as ``ServeEngine(obs=...)`` (or
    ``obs=True`` for the defaults). ``trace=False`` keeps the profiler and
    metrics but emits no span records. ``clock_ns`` is injectable for
    deterministic tests; all span timestamps are microseconds relative to
    ``epoch_ns`` (the engine shares this epoch with its journal, so WAL
    ``ts_us`` and span ``t0_us`` live on one timeline).
    """

    def __init__(self, trace: bool = True, clock_ns=time.monotonic_ns) -> None:
        self.trace = bool(trace)
        self.metrics = MetricsRegistry()
        self.profiler = RoundProfiler()
        self._clock_ns = clock_ns
        self.epoch_ns = clock_ns()
        # rid -> (phase, t0_us, pool_n, lane): the one open span per request.
        self._open: dict[int, tuple] = {}
        self._stack = contextlib.ExitStack()
        self._compiles = None
        self.engine = None

    def now_us(self) -> int:
        return (self._clock_ns() - self.epoch_ns) // 1000

    # -- lifecycle tracing -------------------------------------------------

    def transition(self, rid: int, span: str | None, pool_n: int = -1,
                   lane: int = -1, **extra):
        """Close ``rid``'s open span and open ``span`` (None = terminal).

        Returns the closing ``serve_span`` record (or None when nothing
        was open / tracing is off); ``extra`` fields (``fate``,
        ``ticks_run``) land on the closing record. The caller fans the
        record out — the plane never writes manifests itself."""
        if not self.trace:
            return None
        now = self.now_us()
        prev = self._open.pop(rid, None)
        rec = None
        if prev is not None:
            pspan, pt0, ppool, plane = prev
            rec = run_record(
                "serve_span", span=pspan, request_id=rid, pool_n=ppool,
                lane=plane, t0_us=pt0, dur_us=now - pt0, **extra,
            )
        if span is not None:
            self._open[rid] = (span, now, pool_n, lane)
        return rec

    def flush_spans(self) -> list[dict]:
        """Close every still-open span (engine shutdown): the trace shows
        requests that were parked/spilled when the service stopped."""
        if not self.trace:
            return []
        now = self.now_us()
        out = [
            run_record("serve_span", span=pspan, request_id=rid,
                       pool_n=ppool, lane=plane, t0_us=pt0,
                       dur_us=now - pt0, open=True)
            for rid, (pspan, pt0, ppool, plane) in sorted(self._open.items())
        ]
        self._open.clear()
        return out

    # -- event-driven counters ---------------------------------------------

    def on_record(self, rec: dict) -> None:
        """Fold one engine-emitted manifest record into the counters."""
        kind = rec.get("kind")
        m = self.metrics
        if kind == "serve_event":
            ev = rec.get("event", "?")
            m.inc("serve_events_total", event=ev)
            if ev == "shed":
                m.inc("serve_shed_total", tenant=rec.get("tenant", "?"),
                      priority=rec.get("priority", "?"))
            elif ev == "rejected":
                m.inc("serve_rejected_total", tenant=rec.get("tenant", "?"),
                      reason=rec.get("reason", "?"))
            elif ev in ("spill_failed", "spill_deferred", "restore_failed"):
                m.inc("serve_spill_incidents_total", kind=ev)
        elif kind == "serve_round":
            eng = rec.get("engine", "?")
            m.inc("serve_rounds_total", engine=eng)
            m.inc("serve_ticks_total", value=rec.get("ticks", 0), engine=eng)

    # -- engine binding ----------------------------------------------------

    def bind(self, engine) -> None:
        """Attach to an engine: register the pull gauges over its live
        state and arm the fresh-compile gauge (the KB405 event stream).

        The gauges close over host bookkeeping only — evaluating them
        never touches the device, so a metrics scrape costs no dispatch."""
        from kaboodle_tpu.analysis.ir.surface import compile_counter

        self.engine = engine
        self._compiles = self._stack.enter_context(compile_counter())
        m = self.metrics

        def _state_count(state):
            return lambda: sum(
                1 for row in engine._requests.values()
                if row["state"] == state
            )

        for state in ("queued", "running", "parked", "spilling", "spilled",
                      "done", "cancelled"):
            m.register_gauge("serve_requests", _state_count(state),
                             state=state)
        m.register_gauge(
            "serve_queue_depth", _state_count("queued"))
        for n, pool in engine.pools.items():
            m.register_gauge("serve_lanes_occupied",
                             (lambda p: lambda: p.occupancy()[0])(pool),
                             pool=n)
            m.register_gauge("serve_lanes_active",
                             (lambda p: lambda: p.occupancy()[1])(pool),
                             pool=n)
        m.register_gauge(
            "serve_spill_queue_depth",
            lambda: engine._spiller.pending() if engine._spiller else 0,
        )
        m.register_gauge(
            "serve_journal_lag_appends",
            lambda: (engine.journal._appends_since_compact
                     if engine.journal is not None else 0),
        )
        m.register_gauge("serve_engine_round", lambda: engine.round)

        def _leap_cache(field):
            def read():
                from kaboodle_tpu.warp.runner import leap_cache

                return leap_cache.stats()[field]

            return read

        m.register_gauge("warp_leap_cache_hits", _leap_cache("hits"))
        m.register_gauge("warp_leap_cache_misses", _leap_cache("misses"))
        m.register_gauge("warp_leap_cache_programs", _leap_cache("programs"))

        # Warp 3.0 span memo (signature-keyed state deltas). Reads the
        # engine's bound memo — engines without one report zeros, so the
        # gauge set is stable across configurations.
        def _span_memo(field):
            def read():
                memo = getattr(engine, "warp_memo", None)
                return memo.stats()[field] if memo is not None else 0

            return read

        m.register_gauge("warp_span_memo_hits", _span_memo("hits"))
        m.register_gauge("warp_span_memo_misses", _span_memo("misses"))
        m.register_gauge("warp_span_memo_entries", _span_memo("entries"))
        m.register_gauge("warp_span_memo_bytes", _span_memo("bytes"))
        m.register_gauge("warp_span_memo_evictions", _span_memo("evictions"))

        def _cache_kind_hit_rates():
            from kaboodle_tpu.warp.runner import leap_cache

            return {
                (("kind", kind),): st["hit_rate"]
                for kind, st in leap_cache.stats()["per_kind"].items()
            }

        # Per-class cache hit rates (strict / hybrid / fleet programs) and
        # the why-dense histogram (ISSUE 15): which signature terms forced
        # leap->chunk fallbacks, labeled by blocking term combo.
        m.register_multi_gauge(
            "warp_leap_cache_hit_rate", _cache_kind_hit_rates)

        def _blocked(field):
            def read():
                return {
                    (("term", term),): agg[field]
                    for term, agg in
                    engine.warp_ledger.blocked_histogram().items()
                }

            return read

        m.register_multi_gauge("warp_blocked_ticks", _blocked("ticks"))
        m.register_multi_gauge("warp_blocked_spans", _blocked("spans"))
        m.register_gauge("compiles_steady", lambda: self._compiles.count)
        for i, seg in enumerate(SEGMENTS):
            m.attach_histogram("serve_round_segment_us",
                               self.profiler.hist[i], segment=seg)
        if engine.admission is not None:
            m.register_multi_gauge(
                "admission_tokens",
                lambda: {
                    (("tenant", t),): v["tokens"]
                    for t, v in engine.admission.snapshot()["tenants"].items()
                },
            )

    def reset_compiles(self) -> None:
        """Zero the fresh-compile gauge — the engine calls this when
        warmup finishes, so ``compiles_steady`` means what it says."""
        if self._compiles is not None:
            self._compiles.count = 0

    def close(self) -> None:
        """Detach the compile listener box (idempotent)."""
        self._stack.close()
        self._compiles = None
