"""The lane pool: a resident fixed-shape ``[E]`` fleet served lane by lane.

A pool owns one ``FleetState`` whose ensemble axis is reinterpreted as E
*lanes*: independent request slots multiplexed through the one compiled
serve step program (phasegraph/derive.py ``make_serve_step``). Everything a
request varies — seed, drop knob, mode, tick budget — is TRACED, so the
pool's whole lifecycle (admit, tick, retire, re-seed) re-dispatches the
same warmed programs forever:

- **re-seed** (:meth:`LanePool.admit`): a jitted scatter writes
  ``init_state(n, seed)`` into lane ``e`` (both traced — one program for
  every lane/seed/knob combination) and bumps the lane's on-device
  generation counter, so lane ``e`` holds exactly the state a standalone
  run of that seed would start from. Bit-exactness of the subsequent
  trajectory is the fleet parity contract (fleet/core.py): the serve step
  advances lanes through the same vmapped tick, freezing everything else.
- **generation counters** (int32 ``[E]``, on device): bumped by every
  re-seed/insert, checkpointed with the fleet (checkpoint.save_fleet), and
  stamped into every harvest event — a lane's (index, generation) pair
  names one served request's trajectory unambiguously across spills and
  restores.
- **N-classes**: requests are bucketed to power-of-two mesh sizes
  (:func:`lane_n_class`) exactly like the warp ProgramCache's chunk
  buckets — each pow2 class is one resident pool / one program family, so
  arbitrary request sizes never mint fresh programs.

The pool is deliberately host-bookkeeping-light: occupancy and per-lane
run counters live as numpy vectors fed to (and fetched from) the step
program each round; only the mesh, the drop knob vector and the generation
counters are resident on device.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from kaboodle_tpu.config import SwimConfig
from kaboodle_tpu.fleet.core import FleetState, init_fleet
from kaboodle_tpu.sim.runner import state_agreement
from kaboodle_tpu.sim.state import init_state

MIN_LANE_N = 8  # smallest served mesh class

# Request "scenario" -> init_state shape kwargs (static per compiled reseed
# program; both variants are warmed, so scenario choice never recompiles).
# "boot": a fresh mesh that must gossip/broadcast its way to agreement.
# "steady": a converged, already-announced mesh — the steady-state service
# shape horizon-mode requests (and the warp fast-forward) start from.
SCENARIOS = {
    "boot": {},
    "steady": lambda n: {"ring_contacts": n - 1, "announced": True},
}


def lane_n_class(n: int) -> int:
    """The pow2 mesh-size class serving a request for ``n`` peers.

    Mirrors the warp ProgramCache's power-of-two chunk vocabulary: one
    resident pool (= one compiled program family) per class, whatever
    sizes clients ask for. Requests run AT class size — the class is part
    of the service contract (a request's standalone-equivalent run is the
    class-sized one)."""
    if n < 1:
        raise ValueError("need n >= 1")
    return max(MIN_LANE_N, 1 << (int(n) - 1).bit_length())


def make_reseed_fn(n: int, scenario: str = "boot", **state_kwargs):
    """The on-device retire/re-seed program (jit me with lane/seed traced).

    ``reseed(mesh, generation, drop_rate, lane, seed, drop)`` scatters a
    fresh ``init_state(n, seed)`` into lane ``lane`` of the stacked mesh,
    bumps that lane's generation counter and sets its drop knob — all via
    traced-index updates, so ONE compiled program re-seeds any lane with
    any request. The written member is leaf-for-leaf what the standalone
    init would build (same kwargs; the PRNG key is ``PRNGKey(seed)``
    traced), which is what makes mid-flight admission bit-exact."""
    shape_kw = SCENARIOS[scenario]
    kw = dict(shape_kw(n) if callable(shape_kw) else shape_kw)
    kw.update(state_kwargs)

    def reseed(mesh, generation, drop_rate, lane, seed, drop):
        fresh = init_state(n, seed=seed, **kw)
        mesh = jax.tree.map(lambda leaf, f: leaf.at[lane].set(f), mesh, fresh)
        generation = generation.at[lane].add(1)
        drop_rate = drop_rate.at[lane].set(drop)
        return mesh, generation, drop_rate

    return reseed


def make_insert_fn():
    """Traced-lane member scatter: restore a spilled/checkpointed member.

    Same contract as the reseed program but the member state comes from the
    caller (checkpoint.load) instead of ``init_state`` — the restore half
    of the lane spill path. Bumps the generation counter too: a restored
    occupancy is a new generation of that lane."""

    def insert(mesh, generation, lane, member):
        mesh = jax.tree.map(lambda leaf, f: leaf.at[lane].set(f), mesh, member)
        generation = generation.at[lane].add(1)
        return mesh, generation

    return insert


@functools.lru_cache(maxsize=None)
def _step_program(cfg, chunk: int, faulty: bool, telemetry: bool):
    """The jitted serve step, shared process-wide: two pools of the same
    (cfg, chunk, faulty, telemetry) signature — or a pool rebuilt after a
    restore — reuse one compiled program instead of re-jitting."""
    from kaboodle_tpu.phasegraph.derive import make_serve_step

    return jax.jit(
        make_serve_step(cfg, chunk, faulty=faulty, telemetry=telemetry)
    )


@functools.lru_cache(maxsize=None)
def _reseed_program(n: int, scenario: str, state_kwargs_items: tuple):
    return jax.jit(
        make_reseed_fn(n, scenario=scenario, **dict(state_kwargs_items))
    )


@functools.lru_cache(maxsize=None)
def _insert_program():
    return jax.jit(make_insert_fn())


@functools.lru_cache(maxsize=None)
def _agree_program():
    return jax.jit(jax.vmap(state_agreement))


@functools.lru_cache(maxsize=None)
def _member_fetch():
    """Traced-lane member gather (the spill path's read side): one compiled
    program whatever lane is fetched — eager ``leaf[e]`` indexing would
    mint one program per lane index and break the zero-recompile budget."""

    def fetch(mesh, lane):
        return jax.tree.map(
            lambda x: jax.lax.dynamic_index_in_dim(x, lane, 0, keepdims=False),
            mesh,
        )

    return jax.jit(fetch)


class LanePool:
    """E lanes of one N-class: device state + the warmed program set.

    Host-side per-lane run vectors (``active``, ``until_conv``,
    ``remaining``, ``ticks_run``, ``conv_tick`` — numpy) ride into the
    serve step as traced inputs and come back as its outputs; the mesh,
    drop knobs and generation counters stay on device. ``occupied`` is the
    host occupancy map (a lane can be occupied but inactive: parked).
    """

    def __init__(
        self,
        n: int,
        lanes: int,
        cfg: SwimConfig | None = None,
        faulty: bool = False,
        telemetry: bool = False,
        chunk: int = 8,
        **state_kwargs,
    ) -> None:
        if n != lane_n_class(n):
            raise ValueError(
                f"pool n={n} is not a pow2 lane class (use lane_n_class)"
            )
        if lanes < 1:
            raise ValueError("need lanes >= 1")
        self.n = n
        self.lanes = lanes
        self.cfg = cfg if cfg is not None else SwimConfig(deterministic=True)
        self.faulty = faulty
        self.telemetry = telemetry
        self.chunk = int(chunk)
        self.state_kwargs = dict(state_kwargs)

        fleet = init_fleet(n, lanes, **self.state_kwargs)
        self.mesh = fleet.mesh
        self.drop = fleet.drop_rate
        self.generation = jnp.zeros((lanes,), jnp.int32)

        # Host-side per-lane run state (serve-step inputs/outputs).
        self.occupied = np.zeros((lanes,), dtype=bool)
        self.active = np.zeros((lanes,), dtype=bool)
        self.until_conv = np.zeros((lanes,), dtype=bool)
        self.remaining = np.zeros((lanes,), dtype=np.int32)
        self.ticks_run = np.zeros((lanes,), dtype=np.int32)
        self.conv_tick = np.full((lanes,), -1, dtype=np.int32)
        # Accumulated per-lane observability (reset at admission): unicast
        # deliveries always; full ProtocolCounters totals in telemetry mode.
        # Both count densely executed ticks (a leaped span is event-free by
        # construction — its closed-form ping/ack totals live in the warp
        # telemetry path, not here).
        self.messages = np.zeros((lanes,), dtype=np.int64)
        self.counter_totals: dict[str, np.ndarray] | None = None
        if telemetry:
            from kaboodle_tpu.telemetry.counters import FIELDS

            self.counter_totals = {
                name: np.zeros((lanes,), dtype=np.int64) for name in FIELDS
            }

        # Program set, process-cached: state_kwargs must be hashable
        # (init_state shape knobs — ints/bools), which the tuple() enforces.
        self._bind_programs(tuple(sorted(self.state_kwargs.items())))

    def _bind_programs(self, kw_items: tuple) -> None:
        """Look up (building on first use) the pool's warmed program set.
        The sharded pool overrides this with the GSPMD twins — the one
        seam between the two pool kinds; every lifecycle method above
        dispatches through these bindings."""
        self._step = _step_program(
            self.cfg, self.chunk, self.faulty, self.telemetry
        )
        self._reseed = {
            name: _reseed_program(self.n, name, kw_items)
            for name in SCENARIOS
        }
        self._insert = _insert_program()
        self._agree = _agree_program()

    # -- lifecycle ---------------------------------------------------------

    def free_lane(self) -> int | None:
        free = np.flatnonzero(~self.occupied)
        return int(free[0]) if free.size else None

    def occupancy(self) -> tuple[int, int, int]:
        """(occupied, active, lanes) — the cheap per-pool numbers the
        metrics plane polls every scrape, without building stats()."""
        return int(self.occupied.sum()), int(self.active.sum()), self.lanes

    def admit(
        self,
        lane: int,
        seed: int,
        drop_rate: float = 0.0,
        until_conv: bool = True,
        budget: int = 64,
        scenario: str = "boot",
    ) -> int:
        """Re-seed lane ``lane`` with a request; returns its new generation.

        One dispatch of the warmed reseed program — the retired occupant's
        state is overwritten in place on device. The lane starts active
        with a fresh tick budget; its trajectory from here is bit-exact
        with a standalone run of ``init_state(n, seed)`` under the same
        knobs (pinned in tests/test_fleet.py and the admission fuzz)."""
        if self.occupied[lane]:
            raise ValueError(f"lane {lane} is occupied")
        if drop_rate and not self.faulty:
            raise ValueError(
                "nonzero drop_rate needs a faulty=True pool (the fault-free "
                "program compiles the knob out, silently ignoring it)"
            )
        self.mesh, self.generation, self.drop = self._reseed[scenario](
            self.mesh, self.generation, self.drop,
            jnp.int32(lane), jnp.int32(seed), jnp.float32(drop_rate),
        )
        self.occupied[lane] = True
        self.active[lane] = True
        self.until_conv[lane] = bool(until_conv)
        self.remaining[lane] = int(budget)
        self.ticks_run[lane] = 0
        self.conv_tick[lane] = -1
        self.messages[lane] = 0
        if self.counter_totals is not None:
            for col in self.counter_totals.values():
                col[lane] = 0
        return int(np.asarray(self.generation)[lane])

    def insert(self, lane: int, member) -> int:
        """Scatter a restored member state into a free lane (spill return).

        The lane comes back PARKED (occupied, inactive): the caller decides
        whether to resume it with a fresh budget via :meth:`resume`."""
        if self.occupied[lane]:
            raise ValueError(f"lane {lane} is occupied")
        self.mesh, self.generation = self._insert(
            self.mesh, self.generation, jnp.int32(lane), member
        )
        self.occupied[lane] = True
        self.active[lane] = False
        return int(np.asarray(self.generation)[lane])

    def resume(self, lane: int, until_conv: bool, budget: int) -> None:
        """Re-activate a parked lane with a fresh budget (run counters keep
        accumulating across the park/spill boundary)."""
        if not self.occupied[lane]:
            raise ValueError(f"lane {lane} is free")
        self.active[lane] = True
        self.until_conv[lane] = bool(until_conv)
        self.remaining[lane] = int(budget)

    def park(self, lane: int) -> None:
        self.active[lane] = False

    def run_counters(self, lane: int) -> dict:
        """Lane ``lane``'s host run counters, frozen for a spill. Restoring
        them via :meth:`set_run_counters` is what makes a restore into a
        DIFFERENT lane a true continuation — the husk vectors of the new
        lane belong to whoever ran there last, not to this request."""
        return {
            "ticks_run": int(self.ticks_run[lane]),
            "conv_tick": int(self.conv_tick[lane]),
            "messages": int(self.messages[lane]),
            "until_conv": bool(self.until_conv[lane]),
            "remaining": int(self.remaining[lane]),
        }

    def set_run_counters(self, lane: int, counters: dict) -> None:
        """Write spilled run counters back into lane ``lane`` (the restore
        half of :meth:`run_counters`)."""
        self.ticks_run[lane] = int(counters["ticks_run"])
        self.conv_tick[lane] = int(counters["conv_tick"])
        self.messages[lane] = int(counters["messages"])
        self.until_conv[lane] = bool(counters["until_conv"])
        self.remaining[lane] = int(counters["remaining"])

    def release(self, lane: int) -> None:
        """Retire a lane: mark it free. The husk state stays resident (and
        frozen — inactive lanes never advance) until the next re-seed
        overwrites it."""
        self.occupied[lane] = False
        self.active[lane] = False

    def member(self, lane: int):
        """Lane ``lane``'s mesh as a standalone ``MeshState`` (device) via
        the traced-lane gather — safe inside the zero-recompile phase."""
        return _member_fetch()(self.mesh, jnp.int32(lane))

    def member_snapshot(self, lane: int):
        """A zero-arg thunk for :meth:`member` bound to the CURRENT mesh.

        The mesh pytree reference is captured now (its buffers are
        immutable — later rounds rebind ``self.mesh`` to fresh outputs,
        they never mutate these), and the warmed gather program is looked
        up now, so the thunk can execute on a background thread without
        touching the pool or the program cache. This is how spills get the
        gather itself off the round loop, not just the disk write."""
        mesh = self.mesh
        fetch = _member_fetch()
        return lambda: fetch(mesh, jnp.int32(lane))

    # -- stepping ----------------------------------------------------------

    def step(self):
        """One serve-step chunk dispatch; updates host run vectors.

        Returns the fetched :class:`~kaboodle_tpu.phasegraph.derive.
        ServeStepOut` as a numpy pytree (``done`` is the program's view —
        mask with ``occupied & active`` for harvest decisions)."""
        self.mesh, out = self._step(
            self.mesh, self.drop, self.active, self.until_conv,
            self.remaining, self.ticks_run, self.conv_tick,
        )
        out = jax.tree.map(np.asarray, out)
        self.remaining = out.remaining.astype(np.int32)
        self.ticks_run = out.ticks_run.astype(np.int32)
        self.conv_tick = out.conv_tick.astype(np.int32)
        self.messages += out.messages.astype(np.int64)
        if self.counter_totals is not None and out.counters is not None:
            for name, col in self.counter_totals.items():
                col += np.asarray(getattr(out.counters, name), dtype=np.int64)
        return out

    def counters_row(self, lane: int) -> dict[str, int] | None:
        """Lane ``lane``'s accumulated ProtocolCounters totals (telemetry
        pools only), as a plain dict ready for a manifest record."""
        if self.counter_totals is None:
            return None
        return {k: int(v[lane]) for k, v in self.counter_totals.items()}

    def advance_leaped(self, k_m: np.ndarray) -> None:
        """Account a leap round: per-lane budgets/counters move by ``k_m``
        (the mesh itself was advanced by the masked fleet leap)."""
        k = k_m.astype(np.int32)
        self.remaining = self.remaining - k
        self.ticks_run = self.ticks_run + k

    # -- warp dispatch hooks -----------------------------------------------

    def signature(self):
        """Device ``[E]`` Warp 2.0 signature rows (one vmapped fetch).

        The engine's leap classifier reads these; routing the fetch
        through the pool lets the sharded pool serve it from its own
        placement without the engine knowing which kind it drives."""
        from kaboodle_tpu.warp.runner import _fleet_signature

        return _fleet_signature(self.cfg)(self.mesh)

    def leap(self, K: int, k_m: np.ndarray, memo=None) -> tuple[int, bool]:
        """One masked fleet-leap dispatch (bucket ``K``): every lane
        advances its own ``k_m[e] <= K`` ticks; ``k_m[e] == 0`` freezes
        the lane bit-exactly. Host budget accounting is the caller's
        :meth:`advance_leaped` — this moves only the device mesh.

        With a Warp 3.0 ``SpanMemo``, the round goes through
        :func:`~kaboodle_tpu.warp.runner.memo_fleet_leap`: per-lane span
        deltas are keyed by (entry-row digest, ``k_m[e]``), so a drain one
        lane already computed replays as a host XOR on every other lane —
        and when ALL leaping lanes hit, the dispatch is skipped outright.
        Returns ``(memo_hits, dispatched)`` (``(0, True)`` without a
        memo)."""
        from kaboodle_tpu.warp.runner import _get_fleet_leap, memo_fleet_leap

        prog = _get_fleet_leap(self.cfg, K)
        if memo is None:
            self.mesh = prog(self.mesh, jnp.asarray(k_m))
            return 0, True
        family = repr((self.cfg, "serve"))
        self.mesh, hits, dispatched = memo_fleet_leap(
            family, self.mesh, np.asarray(k_m), memo, prog
        )
        return hits, dispatched

    def agreement(self):
        """Vmapped end-state agreement rows ``(converged, fp_min, fp_max,
        n_alive)`` — the harvest statistics fetch (one dispatch)."""
        return tuple(np.asarray(x) for x in self._agree(self.mesh))

    def fleet_state(self) -> FleetState:
        """The resident as a ``FleetState`` (checkpoint.save_fleet input)."""
        return FleetState(mesh=self.mesh, drop_rate=self.drop)

    def load_fleet_state(self, fleet: FleetState, generation) -> None:
        """Adopt a checkpointed resident (checkpoint.load_fleet output)."""
        if fleet.n != self.n or fleet.ensemble != self.lanes:
            raise ValueError(
                f"checkpoint shape [{fleet.ensemble}]xN{fleet.n} != pool "
                f"[{self.lanes}]xN{self.n}"
            )
        self.mesh = fleet.mesh
        self.drop = fleet.drop_rate
        self.generation = jnp.asarray(generation, dtype=jnp.int32)

    # -- warmup ------------------------------------------------------------

    def warmup(self) -> None:
        """Compile the pool's whole program set with state-preserving
        dispatches: the serve step with every lane inactive (the masked
        while_loop exits at entry, mesh untouched), each reseed scenario on
        lane 0 (lane 0 is free pre-admission; the husk is overwritten),
        the insert program re-writing lane 0 with its own member state
        (bit-identical), and the gather/agreement fetches. After this, the
        serving loop's chunk/admit/harvest path compiles nothing."""
        if self.occupied.any():
            raise ValueError("warm up before admitting requests")
        self.step()
        for name in SCENARIOS:
            self.mesh, self.generation, self.drop = self._reseed[name](
                self.mesh, self.generation, self.drop,
                jnp.int32(0), jnp.int32(0), jnp.float32(0.0),
            )
        member0 = self.member(0)
        self.mesh, self.generation = self._insert(
            self.mesh, self.generation, jnp.int32(0), member0
        )
        self.agreement()

    def stats(self) -> dict:
        return {
            "n": self.n,
            "lanes": self.lanes,
            "occupied": int(self.occupied.sum()),
            "active": int(self.active.sum()),
            "faulty": self.faulty,
            "telemetry": self.telemetry,
            "chunk": self.chunk,
            "generation": np.asarray(self.generation).tolist(),
        }


@dataclasses.dataclass
class HarvestRow:
    """One finished lane's harvest statistics (host-side, event material)."""

    lane: int
    generation: int
    ticks_run: int
    conv_tick: int
    converged: bool
    fp_min: int
    fp_max: int
    n_alive: int
