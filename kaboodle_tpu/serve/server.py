"""asyncio JSON-over-TCP front end for the serve engine.

Wire protocol: newline-delimited JSON both ways. A client sends one op
object per line and reads one response object per line:

    {"op": "submit", "n": 16, "seed": 7, "mode": "converge", ...}
    -> {"ok": true, "request_id": 0}

Ops: ``submit`` / ``status`` / ``cancel`` / ``wait`` / ``restore`` /
``resume`` / ``stats`` / ``stream`` / ``shutdown``. ``wait`` parks the
response until the request reaches a terminal state (race-free completion
latency for the load driver — no polling). ``stream`` switches the
connection into live-event mode: every manifest record the engine emits
from then on is written to it as its own JSONL line (the same
``kaboodle-telemetry/1`` records the manifest file gets), until the client
disconnects.

The engine round loop runs as an asyncio task in the server process:
requests wake it, idleness parks it on an event with a short timeout (so
host-side lifecycle like spill countdowns still advances). Engine compute
is dispatched inline on the event loop — rounds are single bounded-chunk
device dispatches by construction, which is exactly what makes the service
responsive without threads.
"""

from __future__ import annotations

import asyncio
import dataclasses
import json

from kaboodle_tpu.errors import CheckpointError
from kaboodle_tpu.serve.admission import AdmissionError
from kaboodle_tpu.serve.engine import (
    CANCELLED,
    DONE,
    PARKED,
    SPILLING,
    ServeEngine,
    ServeRequest,
)
from kaboodle_tpu.telemetry.manifest import ManifestWriter, run_record


def _wait_done(row: dict) -> bool:
    """``wait`` resolves when the submitter's answer is in: the run was
    harvested (result present — a kept lane may already be parked or even
    spilled by then), finished outright, or cancelled. A resumed
    continuation clears the old result, so waiting on it blocks until ITS
    harvest."""
    return row["state"] in (DONE, CANCELLED) or row.get("result") is not None

_SUBMIT_FIELDS = frozenset(
    f.name for f in dataclasses.fields(ServeRequest)
)

# How long an idle engine loop sleeps between lifecycle polls (spill
# countdowns advance per poll; submissions interrupt it immediately).
_IDLE_POLL_S = 0.02


class _Subscriber:
    """One stream connection's bounded event queue.

    A consumer that stops reading cannot wedge the server: past
    ``maxsize`` buffered records, new events are counted instead of
    queued, and the count surfaces as one ``stream_gap`` record the
    moment the queue has room again — the subscriber KNOWS it lost
    records, and every other connection is unaffected."""

    def __init__(self, maxsize: int) -> None:
        self.q: asyncio.Queue = asyncio.Queue(maxsize=maxsize)
        self.dropped = 0

    def push(self, rec) -> None:  # conc: event-loop
        if self.q.full():
            self.dropped += 1
            return
        if self.dropped:
            self.q.put_nowait(run_record("stream_gap", dropped=self.dropped))
            self.dropped = 0
            if self.q.full():
                self.dropped = 1  # `rec` itself no longer fits
                return
        self.q.put_nowait(rec)

    def push_sentinel(self) -> None:
        if self.q.full():  # make room: the close sentinel must land
            self.q.get_nowait()
        self.q.put_nowait(None)


def _error_response(e: Exception) -> dict:
    """Map an op failure to a structured error a client can act on."""
    resp = {"ok": False, "error": str(e) or type(e).__name__}
    if isinstance(e, AdmissionError):
        resp["kind"] = e.kind  # queue_full | quota
        resp["retry_after_s"] = e.retry_after_s
    elif isinstance(e, CheckpointError):
        resp["kind"] = "checkpoint"
    elif isinstance(e, (ValueError, KeyError, TypeError)):
        resp["kind"] = "bad_request"  # includes malformed JSON lines
    else:
        resp["kind"] = "internal"
    return resp


class ServeServer:
    """One engine + one TCP listener + the live event fan-out."""

    def __init__(
        self,
        engine: ServeEngine,
        host: str = "127.0.0.1",
        port: int = 0,
        manifest_path: str | None = None,
        stream_queue: int = 256,
        metrics_port: int | None = None,
    ) -> None:
        self.engine = engine
        self.host = host
        self.port = port
        self.stream_queue = int(stream_queue)
        # Prometheus-style text endpoint (requires engine.obs). None = off;
        # 0 = ephemeral port, read back after start().
        self.metrics_port = metrics_port
        self.manifest = (
            ManifestWriter(manifest_path, stream=True) if manifest_path else None
        )
        engine.on_event = self._on_event
        self._subscribers: set[_Subscriber] = set()
        self._conn_writers: set = set()  # conc: event-loop
        self._waiters: dict[int, list[asyncio.Future]] = {}
        self._wake = asyncio.Event()
        self._closed = asyncio.Event()
        self._server: asyncio.base_events.Server | None = None
        self._metrics_server: asyncio.base_events.Server | None = None
        self._loop_task: asyncio.Task | None = None

    # -- event fan-out -----------------------------------------------------

    def _on_event(self, rec: dict) -> None:  # conc: event-loop
        if self.manifest is not None:
            self.manifest.write_record(rec)
        for sub in self._subscribers:
            sub.push(rec)

    def _resolve_waiters(self) -> None:  # conc: event-loop
        for rid in list(self._waiters):
            row = self.engine.status(rid)
            if row is not None and _wait_done(row):
                for fut in self._waiters.pop(rid):
                    if not fut.done():
                        fut.set_result(row)

    # -- lifecycle ---------------------------------------------------------

    async def start(self) -> None:
        self._server = await asyncio.start_server(
            self._handle, self.host, self.port
        )
        self.port = self._server.sockets[0].getsockname()[1]
        if self.metrics_port is not None:
            if self.engine.obs is None:
                raise ValueError("metrics_port needs an engine with obs")
            self._metrics_server = await asyncio.start_server(
                self._handle_metrics_http, self.host, self.metrics_port
            )
            self.metrics_port = (
                self._metrics_server.sockets[0].getsockname()[1]
            )
        self._loop_task = asyncio.create_task(self._engine_loop())

    async def serve_forever(self) -> None:
        await self._closed.wait()

    async def close(self) -> None:
        self._closed.set()
        self._wake.set()
        if self._server is not None:
            self._server.close()
            await self._server.wait_closed()
        if self._metrics_server is not None:
            self._metrics_server.close()
            await self._metrics_server.wait_closed()
        if self._loop_task is not None:
            await self._loop_task
        for sub in self._subscribers:
            sub.push_sentinel()
        for futs in self._waiters.values():
            for fut in futs:
                if not fut.done():
                    fut.cancel()
        self._waiters.clear()
        self.engine.close()  # join spill I/O, release the journal handle
        if self.manifest is not None:
            self.manifest.close()

    async def kill(self) -> None:
        """Die like a crashed process (the federation chaos hook): stop
        the listener, abort every open connection mid-op, cancel the
        round loop — WITHOUT closing the engine, flushing spill I/O, or
        releasing the journal. Whatever the WAL and spill files hold at
        this instant is exactly what a failover replay gets to see."""
        self._closed.set()
        self._wake.set()
        for srv in (self._server, self._metrics_server):
            if srv is not None:
                srv.close()
        if self._loop_task is not None:
            self._loop_task.cancel()
            try:
                await self._loop_task
            except asyncio.CancelledError:
                pass
        for writer in list(self._conn_writers):
            transport = writer.transport
            if transport is not None:
                transport.abort()  # RST, not FIN: clients see a break
        for sub in self._subscribers:
            sub.push_sentinel()
        for futs in self._waiters.values():
            for fut in futs:
                if not fut.done():
                    fut.cancel()
        self._waiters.clear()

    async def _engine_loop(self) -> None:
        while not self._closed.is_set():
            if self.engine.busy:
                self.engine.step()
                self._resolve_waiters()
                await asyncio.sleep(0)  # let connections progress
                continue
            self._resolve_waiters()
            # Idle: park until a submit wakes us (short timeout so parked-
            # lane spill countdowns keep ticking via engine.step()).
            self._wake.clear()
            try:
                await asyncio.wait_for(self._wake.wait(), _IDLE_POLL_S)
            except asyncio.TimeoutError:
                pass
            if not self.engine.busy and (
                self.engine.spilling  # fold in-flight write completions
                or (
                    self.engine.spill_after is not None
                    and any(
                        row["state"] in (PARKED, SPILLING)
                        for row in self.engine.status()
                    )
                )
            ):
                self.engine.step()
                self._resolve_waiters()

    async def _handle_metrics_http(self, reader, writer) -> None:
        """One-shot Prometheus text scrape: any GET path gets the full
        exposition (stdlib-only HTTP/1.0 — a scraper, not a web server)."""
        try:
            while (await reader.readline()).strip():
                pass  # drain request line + headers; path is irrelevant
            body = self.engine.obs.metrics.to_prometheus().encode()
            writer.write(
                b"HTTP/1.0 200 OK\r\n"
                b"Content-Type: text/plain; version=0.0.4\r\n"
                b"Content-Length: " + str(len(body)).encode() + b"\r\n"
                b"\r\n" + body
            )
            await writer.drain()
        except (ConnectionResetError, BrokenPipeError):
            pass
        finally:
            writer.close()

    # -- connections -------------------------------------------------------

    async def _handle(self, reader, writer) -> None:
        self._conn_writers.add(writer)
        try:
            while not self._closed.is_set():
                line = await reader.readline()
                if not line:
                    break
                try:
                    op = json.loads(line)
                    if not isinstance(op, dict):
                        raise ValueError(f"op must be an object, got {op!r}")
                    resp = await self._dispatch(op, writer)
                except Exception as e:  # op errors are responses, not crashes
                    resp = _error_response(e)
                if resp is None:  # stream mode took the connection over
                    return
                writer.write(json.dumps(resp).encode() + b"\n")
                await writer.drain()
        finally:
            self._conn_writers.discard(writer)
            writer.close()

    async def _dispatch(self, op: dict, writer):
        name = op.get("op")
        if name == "submit":
            kw = {k: op[k] for k in _SUBMIT_FIELDS if k in op}
            rid = self.engine.submit(ServeRequest(**kw))
            self._wake.set()
            return {"ok": True, "request_id": rid}
        if name == "adopt":
            # Federation failover handover: take over a dead engine's
            # spilled request (its checkpoint file, its saved run
            # counters, its owner stamp) as a fresh rid on this engine.
            kw = {k: op[k] for k in _SUBMIT_FIELDS if k in op}
            rid = self.engine.adopt(
                ServeRequest(**kw), op["spill_path"],
                op.get("saved_run"), op.get("owner"),
            )
            self._wake.set()
            return {"ok": True, "request_id": rid}
        if name == "status":
            return {"ok": True, "status": self.engine.status(op.get("request_id"))}
        if name == "cancel":
            return {"ok": True, "cancelled": self.engine.cancel(op["request_id"])}
        if name == "wait":
            rid = int(op["request_id"])
            row = self.engine.status(rid)
            if row is None:
                return {"ok": False, "error": f"unknown request {rid}",
                        "kind": "bad_request"}
            if not _wait_done(row):
                fut = asyncio.get_running_loop().create_future()
                self._waiters.setdefault(rid, []).append(fut)
                row = await fut
            return {"ok": True, "status": row}
        if name == "restore":
            ok = self.engine.restore(op["request_id"])
            self._wake.set()
            return {"ok": True, "restored": ok}
        if name == "resume":
            self.engine.resume(
                op["request_id"],
                mode=op.get("mode", "ticks"),
                ticks=op.get("ticks", 16),
            )
            self._wake.set()
            return {"ok": True}
        if name == "stats":
            return {"ok": True, "stats": self.engine.stats()}
        if name == "metrics":
            if self.engine.obs is None:
                return {"ok": False, "kind": "bad_request",
                        "error": "engine has no observability plane "
                                 "(start the server with --obs)"}
            return {"ok": True, "metrics": self.engine.obs.metrics.collect()}
        if name == "stream":
            await self._stream(writer)
            return None
        if name == "shutdown":
            writer.write(json.dumps({"ok": True, "bye": True}).encode() + b"\n")
            await writer.drain()
            self._closed.set()
            self._wake.set()
            return None
        return {"ok": False, "error": f"unknown op {name!r}",
                "kind": "bad_request"}

    async def _stream(self, writer) -> None:
        sub = _Subscriber(self.stream_queue)
        self._subscribers.add(sub)
        # Ack so the subscriber KNOWS it is attached before it triggers the
        # events it wants to see (no submit-vs-subscribe race).
        writer.write(
            json.dumps({"ok": True, "streaming": True}).encode() + b"\n"
        )
        await writer.drain()
        try:
            while True:
                rec = await sub.q.get()
                if rec is None:  # server close sentinel
                    break
                writer.write(json.dumps(rec).encode() + b"\n")
                await writer.drain()
        except (ConnectionResetError, BrokenPipeError):
            pass
        finally:
            self._subscribers.discard(sub)


def main(argv=None) -> int:
    """``python -m kaboodle_tpu serve`` — run the service."""
    import argparse

    parser = argparse.ArgumentParser(
        prog="kaboodle-tpu serve",
        description="gossip-as-a-service: resident lane-pool simulation server",
    )
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--port", type=int, default=7447)
    parser.add_argument(
        "--classes", default="16",
        help="comma-separated pow2 N-classes to serve (one pool each)",
    )
    parser.add_argument("--lanes", type=int, default=8, help="lanes per pool")
    parser.add_argument("--chunk", type=int, default=8,
                        help="serve-step dense chunk length")
    parser.add_argument("--max-leap", type=int, default=256)
    parser.add_argument("--no-warp", action="store_true",
                        help="disable horizon-lane fast-forward")
    parser.add_argument("--no-warp-memo", action="store_true",
                        help="disable Warp 3.0 span-delta memoization (on "
                             "by default: a lane pool entering a banked "
                             "state replays the delta host-side instead of "
                             "dispatching the leap — bit-identical, "
                             "warp_span_memo_* gauges on --obs)")
    parser.add_argument("--warp-mode", choices=["exact", "distributional"],
                        default="exact",
                        help="leap tier: 'exact' (default) is bit-exact "
                             "with dense ticking; 'distributional' admits "
                             "live-A2 drain spans to the hybrid leap — "
                             "distribution-pinned, NOT bit-exact")
    parser.add_argument("--telemetry", action="store_true",
                        help="per-lane protocol counter totals (disables warp)")
    parser.add_argument("--manifest", default=None,
                        help="stream manifest records to this JSONL path")
    parser.add_argument("--spill-after", type=int, default=None,
                        help="spill parked lanes idle this many rounds")
    parser.add_argument("--spill-dir", default=None)
    parser.add_argument("--sync-spill", action="store_true",
                        help="blocking spill writes on the round loop "
                             "(the pre-hardening baseline; for A/B only)")
    parser.add_argument("--journal-dir", default=None,
                        help="write-ahead journal directory (crash recovery)")
    parser.add_argument("--engine-id", default=None,
                        help="federation member identity: namespaces spill "
                             "and journal paths one level down and stamps "
                             "every checkpoint, so engines can share roots")
    parser.add_argument("--federated", action="store_true",
                        help="run the federation ROUTER instead of an "
                             "engine (needs --members; optional "
                             "--journal-root enables WAL failover)")
    parser.add_argument("--members", default=None,
                        help="federation members as id=host:port,... "
                             "(each id must match that engine's "
                             "--engine-id)")
    parser.add_argument("--journal-root", default=None,
                        help="the members' SHARED --journal-dir root, for "
                             "failover replay")
    parser.add_argument("--spill-root", default=None,
                        help="the members' SHARED --spill-dir root (spill "
                             "files must be reachable for adoption)")
    parser.add_argument("--vnodes", type=int, default=64,
                        help="virtual nodes per member on the placement "
                             "ring")
    parser.add_argument("--recover", action="store_true",
                        help="replay --journal-dir before serving: re-queue "
                             "lost requests, re-attach spilled ones")
    parser.add_argument("--max-queue", type=int, default=None,
                        help="bound the submit queue (enables admission "
                             "control: priorities, shedding, retry-after)")
    parser.add_argument("--obs", action="store_true",
                        help="attach the observability plane: request "
                             "tracing, round profiler, metrics registry")
    parser.add_argument("--metrics-port", type=int, default=None,
                        help="serve Prometheus text metrics on this port "
                             "(0 = ephemeral; implies --obs)")
    parser.add_argument("--dryrun", action="store_true",
                        help="run the in-process CI exercise and exit")
    parser.add_argument("--chaos-dryrun", action="store_true",
                        help="run the seeded fault-injection scenarios and "
                             "exit")
    parser.add_argument("--obs-dryrun", action="store_true",
                        help="run the observability-plane CI exercise "
                             "(traced lifecycle, metrics, report, trace "
                             "export, overhead A/B) and exit")
    args = parser.parse_args(argv)

    if args.dryrun:
        from kaboodle_tpu.serve.dryrun import run_dryrun

        return run_dryrun()
    if args.chaos_dryrun:
        from kaboodle_tpu.serve.chaos import run_chaos_dryrun

        return run_chaos_dryrun()
    if args.obs_dryrun:
        from kaboodle_tpu.serve.obsdryrun import run_obs_dryrun

        return run_obs_dryrun()

    if args.federated:
        if not args.members:
            parser.error("--federated needs --members id=host:port,...")
        from kaboodle_tpu.serve.federation.router import (
            FedRouter,
            parse_members,
        )

        async def run_router() -> None:
            router = FedRouter(
                parse_members(args.members), host=args.host, port=args.port,
                journal_root=args.journal_root, spill_root=args.spill_root,
                vnodes=args.vnodes, metrics_port=args.metrics_port,
            )
            await router.start()
            print(f"federation router on {router.host}:{router.port} "
                  f"(members {sorted(router.alive)})", flush=True)
            if router.metrics_port is not None:
                print(f"metrics on http://{router.host}:"
                      f"{router.metrics_port}/metrics", flush=True)
            try:
                await router.serve_forever()
            finally:
                await router.close()

        try:
            asyncio.run(run_router())
        except KeyboardInterrupt:
            pass
        return 0

    from kaboodle_tpu.serve.pool import LanePool, lane_n_class

    pools = []
    for tok in args.classes.split(","):
        n = int(tok)
        if n != lane_n_class(n):
            parser.error(f"--classes entry {n} is not a pow2 class >= 8")
        pools.append(
            LanePool(n, args.lanes, chunk=args.chunk,
                     telemetry=args.telemetry)
        )
    admission = None
    if args.max_queue is not None:
        from kaboodle_tpu.serve.admission import AdmissionController

        admission = AdmissionController(max_queue=args.max_queue)
    engine = ServeEngine(
        pools, warp=not args.no_warp, max_leap=args.max_leap,
        warp_memo=not args.no_warp_memo, warp_mode=args.warp_mode,
        spill_after=args.spill_after, spill_dir=args.spill_dir,
        sync_spill=args.sync_spill, journal_dir=args.journal_dir,
        admission=admission, engine_id=args.engine_id,
        obs=args.obs or args.metrics_port is not None,
    )
    if args.recover:
        if args.journal_dir is None:
            parser.error("--recover needs --journal-dir")
        counts = engine.recover()
        print(f"recovered: {counts}", flush=True)

    async def run() -> None:
        server = ServeServer(
            engine, host=args.host, port=args.port,
            manifest_path=args.manifest,
            metrics_port=args.metrics_port,
        )
        print("warming up...", flush=True)
        engine.warmup()
        await server.start()
        print(f"serving on {server.host}:{server.port} "
              f"(classes {sorted(engine.pools)})", flush=True)
        if server.metrics_port is not None:
            print(f"metrics on http://{server.host}:{server.metrics_port}/"
                  f"metrics", flush=True)
        try:
            await server.serve_forever()
        finally:
            await server.close()

    try:
        asyncio.run(run())
    except KeyboardInterrupt:
        pass
    return 0
