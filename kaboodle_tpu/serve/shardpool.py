"""The GSPMD lane pool: one pool's ``[E]`` lanes spread over a device mesh.

A :class:`ShardedLanePool` is a :class:`~kaboodle_tpu.serve.pool.LanePool`
whose resident fleet lives on a ``fleet.sharding`` device mesh instead of
one chip: the ``[E]`` lane axis splits across the ``ensemble`` mesh axis
and — on a 2-D ``E x peers`` mesh — each lane's ``[N]`` peer rows split
across the ``peers`` axis, so ONE pool serves big-N requests whose state
exceeds a single device while the small-N classes keep packing one chip
each. The admission protocol is untouched: same host run vectors, same
traced-lane reseed/insert/gather, same serve-step contract — the pool
overrides exactly one seam (``_bind_programs``) plus the warp dispatch
hooks, swapping in the sharded twins:

- **serve step** — ``phasegraph.derive.make_sharded_serve_step``: the
  masked converge chunk with its lane-pool carry constrained back onto the
  mesh after every tick, so XLA partitions every while_loop iteration
  identically (lanes tick device-locally; only ``any(~done)`` and, on 2-D,
  the per-member row collectives cross the ICI).
- **reseed / insert** — the same scatter programs with outputs pinned to
  the fleet layout. Without the pin, XLA would pick each output's sharding
  per program and the drifted mesh would hand the NEXT dispatch a fresh
  input sharding — a recompile. Restored members are ``device_put`` onto a
  canonical placement first, so a warmup insert and a disk restore
  dispatch the same executable.
- **fleet leap** — the masked Warp 2.0 span program, vmapped then
  constrained, cached in the warp ``leap_cache`` under a mesh-distinct
  family key (same pow2 bucket vocabulary, same exact-composition
  semantics).

Bit-exactness vs the single-device pool on the same admission schedule is
pinned by tests/test_fedserve.py; ``with_sharding_constraint`` moves
bytes, never values, and every per-lane computation stays member-local.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from kaboodle_tpu.config import SwimConfig
from kaboodle_tpu.fleet.core import FleetState
from kaboodle_tpu.fleet.sharding import (
    _check_fleet_divisible,
    _named,
    fleet_vector_sharding,
    make_fleet_constrainer,
    shard_fleet,
)
from kaboodle_tpu.parallel.mesh import PEER_AXIS, state_specs
from kaboodle_tpu.serve.pool import (
    SCENARIOS,
    LanePool,
    make_insert_fn,
    make_reseed_fn,
)


def member_sharding(device_mesh: Mesh, member):
    """Canonical placement for ONE member's ``MeshState`` on the pool's
    device mesh: peer-layer row sharding when the mesh has a ``peers``
    axis, fully replicated otherwise. Both the warmup insert and a disk
    restore pin members here before dispatch, so the insert program sees
    one input sharding forever."""
    peers = PEER_AXIS in device_mesh.axis_names
    specs = state_specs(member)
    if not peers:
        specs = jax.tree.map(
            lambda s: P(), specs, is_leaf=lambda x: isinstance(x, P)
        )
    return _named(device_mesh, specs)


@functools.lru_cache(maxsize=None)
def _sharded_step_program(
    cfg, chunk: int, faulty: bool, telemetry: bool, device_mesh: Mesh
):
    """The jitted sharded serve step, shared process-wide (the sharded
    twin of ``pool._step_program``; ``jax.sharding.Mesh`` hashes, so the
    device mesh rides in the cache key)."""
    from kaboodle_tpu.phasegraph.derive import make_sharded_serve_step

    return jax.jit(
        make_sharded_serve_step(
            cfg, chunk, device_mesh, faulty=faulty, telemetry=telemetry
        )
    )


@functools.lru_cache(maxsize=None)
def _sharded_reseed_program(
    n: int, scenario: str, state_kwargs_items: tuple, device_mesh: Mesh
):
    base = make_reseed_fn(n, scenario=scenario, **dict(state_kwargs_items))
    constrain = make_fleet_constrainer(device_mesh)
    vec = fleet_vector_sharding(device_mesh)

    def reseed(mesh, generation, drop_rate, lane, seed, drop):
        mesh, generation, drop_rate = base(
            mesh, generation, drop_rate, lane, seed, drop
        )
        mesh = constrain(mesh)
        generation = jax.lax.with_sharding_constraint(generation, vec)
        drop_rate = jax.lax.with_sharding_constraint(drop_rate, vec)
        return mesh, generation, drop_rate

    return jax.jit(reseed)


@functools.lru_cache(maxsize=None)
def _sharded_insert_jit(device_mesh: Mesh):
    base = make_insert_fn()
    constrain = make_fleet_constrainer(device_mesh)
    vec = fleet_vector_sharding(device_mesh)

    def insert(mesh, generation, lane, member):
        mesh, generation = base(mesh, generation, lane, member)
        return constrain(mesh), jax.lax.with_sharding_constraint(
            generation, vec
        )

    return jax.jit(insert)


def _sharded_insert_program(device_mesh: Mesh):
    """The restore scatter with a placement prologue: the member pytree is
    pinned to :func:`member_sharding` BEFORE the jitted dispatch, so a
    host-loaded checkpoint member and the warmup's gathered member hit the
    same compiled executable (jit keys on input shardings — letting them
    differ would mint a steady-state compile on the first real restore)."""
    jitted = _sharded_insert_jit(device_mesh)

    def insert(mesh, generation, lane, member):
        member = jax.device_put(member, member_sharding(device_mesh, member))
        return jitted(mesh, generation, lane, member)

    return insert


def _sharded_fleet_leap(cfg, K: int, device_mesh: Mesh):
    """The masked fleet leap constrained onto the device mesh, cached in
    the warp ``leap_cache`` under a mesh-distinct family key — the pow2
    bucket vocabulary (and the cache's reuse accounting) is shared with
    the single-device fleet family."""
    from kaboodle_tpu.phasegraph.derive import make_warp_leap
    from kaboodle_tpu.warp.runner import leap_cache

    def build():
        leap = jax.vmap(make_warp_leap(cfg, K, hybrid=True, masked=True))
        constrain = make_fleet_constrainer(device_mesh)

        def sharded_leap(mesh, k_m):
            return constrain(leap(mesh, k_m))

        return jax.jit(sharded_leap)

    return leap_cache.get((cfg, "fleet-sharded", device_mesh), "hybrid", K, build)


class ShardedLanePool(LanePool):
    """A lane pool resident on a GSPMD device mesh (see module docstring).

    ``device_mesh`` is a ``fleet.sharding.make_fleet_mesh`` mesh — 1-D
    ``ensemble`` (each lane whole on one chip) or 2-D ``E x peers`` (each
    lane's rows split too). ``lanes`` must divide by the ensemble mesh
    size and ``n`` by the peer mesh size, exactly like ``shard_fleet``.
    """

    def __init__(
        self,
        n: int,
        lanes: int,
        cfg: SwimConfig | None = None,
        faulty: bool = False,
        telemetry: bool = False,
        chunk: int = 8,
        device_mesh: Mesh | None = None,
        **state_kwargs,
    ) -> None:
        if device_mesh is None:
            from kaboodle_tpu.fleet.sharding import make_fleet_mesh

            device_mesh = make_fleet_mesh()
        self.device_mesh = device_mesh
        super().__init__(
            n, lanes, cfg=cfg, faulty=faulty, telemetry=telemetry,
            chunk=chunk, **state_kwargs,
        )
        _check_fleet_divisible(lanes, n, device_mesh)
        # Re-place the freshly initialized resident onto the mesh; the
        # host run vectors stay host numpy, exactly like the base pool.
        fleet = shard_fleet(
            FleetState(mesh=self.mesh, drop_rate=self.drop), device_mesh
        )
        self.mesh = fleet.mesh
        self.drop = fleet.drop_rate
        self.generation = jax.device_put(
            self.generation, fleet_vector_sharding(device_mesh)
        )

    def _bind_programs(self, kw_items: tuple) -> None:
        self._step = _sharded_step_program(
            self.cfg, self.chunk, self.faulty, self.telemetry,
            self.device_mesh,
        )
        self._reseed = {
            name: _sharded_reseed_program(
                self.n, name, kw_items, self.device_mesh
            )
            for name in SCENARIOS
        }
        self._insert = _sharded_insert_program(self.device_mesh)
        # The agreement fetch reads [E] rows to host — no mesh output, so
        # the shared vmapped program just compiles a sharded-input
        # executable at warmup; same for the signature/member gathers.
        from kaboodle_tpu.serve.pool import _agree_program

        self._agree = _agree_program()

    def member_snapshot(self, lane: int):
        """A zero-arg thunk for the spill writer, with the gather program
        dispatched HERE, on the round-loop thread. The sharded gather
        contains collectives (it reassembles a member from its shards);
        dispatching it from the background spill thread would interleave
        its rendezvous with the concurrently running step program's and
        deadlock the device set. Dispatch is asynchronous — the round loop
        pays a program launch, the worker thread pays the device->host
        transfer, same split of blocking work as the base pool."""
        member = self.member(lane)
        return lambda: member

    # -- warp dispatch hooks -----------------------------------------------

    def leap(self, K: int, k_m, memo=None) -> tuple[int, bool]:
        # The Warp 3.0 span memo is deliberately inert here: keying a lane
        # requires digesting its rows on the host, and fetching a
        # GSPMD-sharded mesh back every round would serialize the exact
        # cross-device reassembly the sharded pool exists to avoid. Rounds
        # always dispatch; the base pool is the memo tier.
        self.mesh = _sharded_fleet_leap(self.cfg, K, self.device_mesh)(
            self.mesh, jnp.asarray(k_m)
        )
        return 0, True

    # -- warmup ------------------------------------------------------------

    def warmup(self) -> None:
        """The base warmup plus the sharded pool's host-FETCH programs:
        reading a sharded array back to host compiles a per-(shape,
        sharding) assembly program (jax's ``_multi_slice``), which counts
        against the zero-recompile budget exactly like a dispatch. The
        base warmup covers the step/signature/agreement outputs by running
        them; the two fetches it never performs are the generation-counter
        read (every admit does one) and the member-leaf reads (every spill
        write does one per ``MeshState`` field), so both are exercised
        here. The mirror direction needs warming too: a restore's
        checkpoint-loaded member arrives as single-device arrays, and
        SPLITTING each leaf onto the mesh is another per-(shape, sharding)
        program — exercised by re-inserting lane 0's own state through a
        host round-trip (bit-identical, state-preserving)."""
        super().warmup()
        np.asarray(self.generation)
        host_member = jax.tree.map(
            lambda x: jnp.asarray(np.asarray(x)), self.member(0)
        )
        self.mesh, self.generation = self._insert(
            self.mesh, self.generation, jnp.int32(0), host_member
        )

    # -- checkpoint adoption -----------------------------------------------

    def load_fleet_state(self, fleet: FleetState, generation) -> None:
        """Adopt a checkpointed resident, re-placing it onto the mesh (a
        host-loaded fleet arrives unsharded)."""
        super().load_fleet_state(fleet, generation)
        placed = shard_fleet(
            FleetState(mesh=self.mesh, drop_rate=self.drop), self.device_mesh
        )
        self.mesh = placed.mesh
        self.drop = placed.drop_rate
        self.generation = jax.device_put(
            self.generation, fleet_vector_sharding(self.device_mesh)
        )

    def stats(self) -> dict:
        out = super().stats()
        out["device_mesh"] = {
            axis: int(size) for axis, size in self.device_mesh.shape.items()
        }
        return out
