"""Async spill/restore: checkpoint I/O off the engine round loop.

PR 10's spill path was synchronous ``checkpoint.save`` inside the round
loop — every idle-lane spill stalled EVERY active lane for one full npz
write (milliseconds of fsync against a round budget of microseconds). The
:class:`SpillManager` moves the disk work onto a background writer thread:

- **double-buffered device→host copies**: the engine hands the manager a
  zero-arg thunk (:meth:`LanePool.member_snapshot`) binding the warmed
  traced-lane gather to the current mesh snapshot — the mesh buffers are
  immutable, so the worker thread can execute the gather and the
  device→host transfer itself (a device fetch, never a fresh compile)
  while the round loop moves straight on. The bounded submit queue
  (default depth 4) is the double buffer: at most ``depth`` spills are in
  flight before ``submit_write`` reports backpressure and the engine
  retries next round (``spill_deferred``).
- **durability**: writes go through ``checkpoint.save(..., atomic=True)``
  — same-directory temp file, fsync, rename — so a crash mid-spill leaves
  either the previous complete file or the new complete file, never a
  truncated archive for recovery to trip over.
- **the host tree IS the request until the write is durable**: the cache
  entry is dropped only when the writer reports success. A failed write
  (disk full, injected chaos fault) leaves the cache intact, so an evicted
  lane's state is never lost — the engine retries or degrades, loudly.
- **restore prefetch**: ``prefetch`` reads a spill file back on the same
  worker thread into the cache, so a planned restore's ``checkpoint.load``
  cost is off the round loop too.

Everything here is host-side stdlib threading; no traced code.
"""

from __future__ import annotations

import dataclasses
import queue
import threading

from kaboodle_tpu.analysis.conc.sanitizer import make_lock


@dataclasses.dataclass(frozen=True)
class SpillResult:
    """One finished background I/O: ``op`` is ``"write"`` or ``"read"``."""

    rid: int
    path: str
    op: str
    ok: bool
    error: str | None = None


class SpillManager:
    """Bounded-queue background writer/reader for lane spills.

    ``depth`` bounds the number of in-flight host trees (the double
    buffer); completions are polled by the engine at round start — the
    worker thread never touches engine state directly, so the round loop
    stays single-threaded from the device's point of view.
    """

    def __init__(self, depth: int = 4, owner: str | None = None) -> None:
        # Writer identity stamped into every spill archive (federation
        # engine-id): restores can then refuse alien engines' files.
        self.owner = owner
        self._work: queue.Queue = queue.Queue(maxsize=max(1, int(depth)))
        # KB506 waiver: fed only by the bounded _work queue (one completion
        # per submitted item) and drained to empty by the engine's
        # _poll_spills at EVERY round start, so occupancy is bounded by
        # depth + one round's completions.
        self._done: queue.Queue = queue.Queue()  # noqa: KB506
        self._cache: dict[int, object] = {}  # guarded_by: _lock
        # Sanitized under the chaos/test harnesses (dynamic lock-order
        # graph), a plain threading.Lock in production — see make_lock.
        self._lock = make_lock("SpillManager._lock")
        self._fail_next = 0  # guarded_by: _lock
        self._closed = False
        self._thread = threading.Thread(
            target=self._run, name="kaboodle-spill-writer", daemon=True
        )
        self._thread.start()

    # -- engine-facing API (round-loop thread) -----------------------------

    def submit_write(self, rid: int, path: str, member) -> bool:  # conc: event-loop
        """Queue a durable write of ``member`` to ``path``. ``member`` is
        a state tree OR a zero-arg thunk producing one (the worker
        materializes it off the round loop). Returns False — try again
        next round — when the bounded queue is full. The tree (or thunk)
        is cached until the write succeeds."""
        with self._lock:
            self._cache[rid] = member
        try:
            self._work.put_nowait(("write", rid, path, member))
        except queue.Full:
            return False
        return True

    def prefetch(self, rid: int, path: str) -> bool:
        """Queue a background read of ``path`` into the cache (restore
        warm-up). Returns False when the queue is full."""
        try:
            self._work.put_nowait(("read", rid, path, None))
        except queue.Full:
            return False
        return True

    def poll(self) -> list[SpillResult]:  # conc: event-loop
        """Drain completed background I/Os (non-blocking)."""
        out: list[SpillResult] = []
        while True:
            try:
                out.append(self._done.get_nowait())
            except queue.Empty:
                return out

    def cached(self, rid: int):
        """The state tree for ``rid`` if still resident (write not yet
        durable, or a completed prefetch), else None. A still-deferred
        thunk is materialized here (both threads may race to do so; the
        results are identical by construction)."""
        with self._lock:
            member = self._cache.get(rid)
        if callable(member):
            member = member()
            with self._lock:
                if rid in self._cache:
                    self._cache[rid] = member
        return member

    def drop_cache(self, rid: int) -> None:
        with self._lock:
            self._cache.pop(rid, None)

    def pending(self) -> int:
        """Writes/reads still queued or in flight (approximate)."""
        return self._work.qsize()

    def fail_next(self, k: int = 1) -> None:
        """Chaos hook: the next ``k`` writes fail deterministically
        (before touching disk), as if the target volume were full."""
        with self._lock:
            self._fail_next += int(k)

    def flush(self) -> None:
        """Block until every queued I/O has completed. Completions stay in
        the done queue — the engine's ``_poll_spills`` must still fold
        them (draining here would swallow the lane-state transitions)."""
        self._work.join()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._work.put(None)
        self._thread.join(timeout=10.0)

    # -- worker thread -----------------------------------------------------

    def _run(self) -> None:
        from kaboodle_tpu import checkpoint

        while True:
            item = self._work.get()
            if item is None:
                self._work.task_done()
                return
            op, rid, path, member = item
            try:
                if op == "write":
                    with self._lock:
                        inject = self._fail_next > 0
                        if inject:
                            self._fail_next -= 1
                    if inject:
                        raise OSError("injected spill-write failure")
                    if callable(member):
                        member = member()
                        with self._lock:
                            if rid in self._cache:
                                self._cache[rid] = member
                    checkpoint.save(path, member, atomic=True,
                                    owner=self.owner)
                    # Durable: the file supersedes the host copy.
                    with self._lock:
                        self._cache.pop(rid, None)
                else:
                    loaded = checkpoint.load(path)
                    with self._lock:
                        self._cache[rid] = loaded
                self._done.put(SpillResult(rid, path, op, ok=True))
            except Exception as e:  # surfaces as a poll()ed failure record
                self._done.put(
                    SpillResult(rid, path, op, ok=False, error=str(e))
                )
            finally:
                self._work.task_done()
