"""Vectorized lockstep SWIM simulator: the TPU tick kernel and its runners."""

from kaboodle_tpu.sim.state import MeshState, TickInputs, TickMetrics, init_state, idle_inputs

__all__ = [
    "MeshState",
    "TickInputs",
    "TickMetrics",
    "init_state",
    "idle_inputs",
    "make_tick_fn",
    "make_chunked_tick_fn",
    "simulate",
    "run_until_converged",
    "Scenario",
    "baseline_scenario",
]

# Lazy (PEP 562, same idiom as the package root): the kernel names are
# shims over kaboodle_tpu.phasegraph, and phasegraph's engine modules
# import sim.state — which triggers THIS __init__. Resolving the shim
# names on first attribute access (instead of at package-init time) lets
# either side be imported first without a half-initialized-module cycle.
_LAZY = {
    "make_tick_fn": "kaboodle_tpu.sim.kernel",
    "make_chunked_tick_fn": "kaboodle_tpu.sim.chunked",
    "simulate": "kaboodle_tpu.sim.runner",
    "run_until_converged": "kaboodle_tpu.sim.runner",
    "Scenario": "kaboodle_tpu.sim.scenario",
    "baseline_scenario": "kaboodle_tpu.sim.scenario",
}


def __getattr__(name):
    if name in _LAZY:
        import importlib

        value = getattr(importlib.import_module(_LAZY[name]), name)
        globals()[name] = value  # cache: __getattr__ runs once per name
        return value
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
