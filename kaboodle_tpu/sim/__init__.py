"""Vectorized lockstep SWIM simulator: the TPU tick kernel and its runners."""

from kaboodle_tpu.sim.state import MeshState, TickInputs, TickMetrics, init_state, idle_inputs
from kaboodle_tpu.sim.kernel import make_tick_fn
from kaboodle_tpu.sim.chunked import make_chunked_tick_fn
from kaboodle_tpu.sim.runner import simulate, run_until_converged
from kaboodle_tpu.sim.scenario import Scenario, baseline_scenario

__all__ = [
    "MeshState",
    "TickInputs",
    "TickMetrics",
    "init_state",
    "idle_inputs",
    "make_tick_fn",
    "make_chunked_tick_fn",
    "simulate",
    "run_until_converged",
    "Scenario",
    "baseline_scenario",
]
