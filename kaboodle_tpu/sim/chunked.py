"""Row-blocked tick kernel — a shim over the phase-graph derivation.

The row-blocked implementation that lived here moved to
:mod:`kaboodle_tpu.phasegraph.blocked`, where it executes the op graph's
``blocked`` program (same ops and order as the dense engine, O(block·N)
transients — see ``kaboodle_tpu/phasegraph/__init__.py``). This module
keeps the historical import path for every call site, scale-proof script,
and test.
"""

from kaboodle_tpu.phasegraph.blocked import make_chunked_tick_fn

__all__ = ["make_chunked_tick_fn"]
