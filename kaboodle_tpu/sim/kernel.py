"""The dense SWIM tick — a shim over the phase-graph derivation.

The tick implementation that lived here (the hand-specialized dense kernel
with its own ``_fast``/``_rest`` split) moved to
:mod:`kaboodle_tpu.phasegraph.exec`, where it is composed from the phase-op
graph's planned programs (see ``kaboodle_tpu/phasegraph/__init__.py`` for
the derivation story: one op graph, five derived engines). This module
keeps the historical import path — every call site, test, and registry
entry that says ``from kaboodle_tpu.sim.kernel import make_tick_fn`` keeps
working and gets the derived build.
"""

from kaboodle_tpu.phasegraph.exec import make_tick_fn

__all__ = ["make_tick_fn"]
