"""Rolling the tick kernel: fixed-length scans and convergence-bounded runs.

The reference's ``run()`` loop (kaboodle.rs:781-786) ticks until cancelled;
the simulator's equivalents are:

- :func:`simulate` — ``lax.scan`` over a stacked ``TickInputs`` pytree,
  returning the final state plus per-tick metrics (the structured-metrics
  subsystem SURVEY.md §5 calls for).
- :func:`run_until_converged` — ``lax.while_loop`` that stops as soon as all
  alive peers agree on the mesh fingerprint (the reference's convergence
  signal, README.md:19-29), up to ``max_ticks``. Fault-free dynamics only
  (while_loop carries no per-tick inputs); used by the benchmark driver.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from kaboodle_tpu.config import SwimConfig
from kaboodle_tpu.sim.kernel import make_tick_fn
from kaboodle_tpu.sim.state import MeshState, TickInputs, TickMetrics, idle_inputs
from kaboodle_tpu.telemetry.counters import add_counters, zero_counters
from kaboodle_tpu.telemetry.recorder import init_recorder, record_tick


def simulate(
    state: MeshState,
    inputs: TickInputs,
    cfg: SwimConfig,
    faulty: bool = True,
) -> tuple[MeshState, TickMetrics]:
    """Scan the tick kernel over ``inputs`` stacked along a leading [T] axis."""
    tick = make_tick_fn(cfg, faulty=faulty)
    return jax.lax.scan(tick, state, inputs)


def simulate_with_telemetry(
    state: MeshState,
    inputs: TickInputs,
    cfg: SwimConfig,
    faulty: bool = True,
    recorder_len: int = 0,
):
    """The :func:`simulate` scan with the telemetry plane on.

    Returns ``(final_state, metrics, counters, recorder)``: per-tick
    ``TickMetrics`` and ``ProtocolCounters`` stacked ``[T]``, and — when
    ``recorder_len > 0`` — a :class:`~kaboodle_tpu.telemetry.recorder.
    FlightRecorder` ring carried through the scan holding the last
    ``recorder_len`` ticks' counters + per-member fingerprint digests
    (``None`` otherwise). The state trajectory is bit-identical to
    :func:`simulate`'s; everything here is added outputs.
    """
    tick = make_tick_fn(cfg, faulty=faulty, telemetry=True)
    if recorder_len:
        rec0 = init_recorder(recorder_len, state.n)

        def body(carry, inp):
            st, rec = carry
            st, out = tick(st, inp)
            rec = record_tick(rec, st.tick - 1, out)
            return (st, rec), (out.metrics, out.counters)

        (final, rec), (metrics, counters) = jax.lax.scan(
            body, (state, rec0), inputs
        )
        return final, metrics, counters, rec

    def body(st, inp):
        st, out = tick(st, inp)
        return st, (out.metrics, out.counters)

    final, (metrics, counters) = jax.lax.scan(body, state, inputs)
    return final, metrics, counters, None


def state_agreement(state: MeshState):
    """Fingerprint agreement of ``state`` as-is, without a tick.

    The same reduction the tick kernel folds into its end-of-tick metrics
    (``fp_count`` + ``fingerprint_agreement``) as a standalone read — the
    ONE definition shared by :func:`converge_loop`'s entry test, the warp
    runner's horizon checks, and ``parallel.sharded_convergence_check``
    (which delegates here, so the predicate cannot drift between the dense
    and sharded paths). Returns ``(converged, fp_min, fp_max, n_alive)``.
    """
    from kaboodle_tpu.ops.hashing import fingerprint_agreement, membership_fingerprint

    fp = membership_fingerprint(
        state.state > 0,
        state.id_view if state.id_view is not None else state.identity,
    )
    return fingerprint_agreement(state.alive, fp)


def state_converged(state: MeshState) -> jax.Array:
    """bool ``[]``: the agreement flag alone (see :func:`state_agreement`)."""
    return state_agreement(state)[0]


def converge_loop(
    state: MeshState,
    tick,
    max_ticks: int,
) -> tuple[MeshState, jax.Array, jax.Array]:
    """``lax.while_loop`` of ``tick`` until fingerprint agreement or ``max_ticks``.

    The single loop implementation shared by the single-device and sharded
    entry points (kaboodle_tpu.parallel wraps its mesh-constrained tick around
    this). Returns ``(final_state, ticks_run, converged)``; convergence is
    evaluated on end-of-tick state, matching ``LockstepMesh.converged()``.
    Fingerprint agreement is also checked at loop entry, so an
    already-converged mesh reports ``ticks_run == 0`` with its state
    untouched instead of paying one full tick to rediscover agreement.
    """
    idle = idle_inputs(state.n)

    def cond(carry):
        st, i, conv = carry
        return (~conv) & (i < max_ticks)

    def body(carry):
        st, i, _ = carry
        st, m = tick(st, idle)
        return st, i + 1, m.converged

    return jax.lax.while_loop(cond, body, (state, jnp.int32(0), state_converged(state)))


@functools.partial(jax.jit, static_argnames=("cfg", "max_ticks"))
def run_until_converged(
    state: MeshState,
    cfg: SwimConfig,
    max_ticks: int = 64,
) -> tuple[MeshState, jax.Array, jax.Array]:
    """Tick the fault-free kernel until fingerprint agreement or ``max_ticks``."""
    return converge_loop(state, make_tick_fn(cfg, faulty=False), max_ticks)


@functools.partial(
    jax.jit, static_argnames=("cfg", "max_ticks", "recorder_len")
)
def run_until_converged_telemetry(
    state: MeshState,
    cfg: SwimConfig,
    max_ticks: int = 64,
    recorder_len: int = 32,
):
    """:func:`run_until_converged` with the telemetry plane on.

    A ``while_loop`` cannot stack per-tick outputs, so this is exactly the
    flight recorder's home turf: the carry accumulates run-total
    ``ProtocolCounters`` plus the last ``recorder_len`` ticks' ring, and
    whether the run converged or hit ``max_ticks``, one host fetch dumps
    what the tail of the run was doing — no rerun. Returns
    ``(final_state, ticks_run, converged, totals, recorder)``; the state /
    ticks / converged triple is bit-identical to the plain runner's
    (entry agreement short-circuits at zero ticks the same way).
    """
    tick = make_tick_fn(cfg, faulty=False, telemetry=True)
    idle = idle_inputs(state.n)

    def cond(carry):
        _, i, conv, _, _ = carry
        return (~conv) & (i < max_ticks)

    def body(carry):
        st, i, _, rec, tot = carry
        st, out = tick(st, idle)
        rec = record_tick(rec, st.tick - 1, out)
        return st, i + 1, out.metrics.converged, rec, add_counters(tot, out.counters)

    st, i, conv, rec, tot = jax.lax.while_loop(
        cond,
        body,
        (
            state,
            jnp.int32(0),
            state_converged(state),
            init_recorder(recorder_len, state.n),
            zero_counters(),
        ),
    )
    return st, i, conv, tot, rec
