"""Declarative fault/churn scenarios compiled to stacked per-tick inputs.

The reference has no fault injection at all (SURVEY.md §5): churn is a human
killing zellij panes, partitions and message drop are untestable. Here the
whole fault surface is data — a :class:`Scenario` is a schedule of kill /
revive / partition / drop / manual-ping events that compiles to a
``TickInputs`` pytree stacked along a leading ``[T]`` axis, ready for
``lax.scan`` (sim.runner.simulate) or the sharded twin
(parallel.mesh.simulate_sharded).

Schedules are built host-side with NumPy (they are scenario *inputs*, not
device work) and are fully deterministic for a given seed: random churn tracks
the aliveness trajectory while building, so for a sole churn schedule kills
always hit live peers and revives always resurrect dead ones (overlapping
schedules guarantee the weaker contract: the exact alive mask the kernel will
compute is still known in advance — :meth:`Scenario.alive_trajectory`).

The five driver configs (BASELINE.json / BASELINE.md) are provided as named
constructors via :func:`baseline_scenario`.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from kaboodle_tpu.sim.state import TickInputs


@dataclasses.dataclass
class Scenario:
    """A mutable schedule of fault events over ``ticks`` ticks for ``n`` peers.

    Build with the ``kill_at`` / ``revive_at`` / ``churn`` / ``partition_at`` /
    ``heal_at`` / ``drop`` / ``manual_ping_at`` methods (each returns ``self``
    for chaining), then :meth:`build` to get scan-ready ``TickInputs``.
    """

    n: int
    ticks: int
    seed: int = 0

    def __post_init__(self) -> None:
        if self.n < 1 or self.ticks < 1:
            raise ValueError("need n >= 1 and ticks >= 1")
        T, n = self.ticks, self.n
        self._kill = np.zeros((T, n), dtype=bool)
        self._revive = np.zeros((T, n), dtype=bool)
        self._partition = np.zeros((T, n), dtype=np.int32)
        self._drop_rate = np.zeros((T,), dtype=np.float32)
        self._manual = np.full((T, n), -1, dtype=np.int32)
        self._initial_alive = np.ones((n,), dtype=bool)
        self._rng = np.random.default_rng(self.seed)

    # ---- explicit events ---------------------------------------------------

    def start_dead(self, peers) -> "Scenario":
        """Peers that begin the run dead (joined later via revive_at/churn)."""
        self._initial_alive[np.asarray(peers)] = False
        return self

    def kill_at(self, tick: int, peers) -> "Scenario":
        """Silent leave (quirk Q8: no departure announcement) at ``tick``."""
        self._kill[tick, np.asarray(peers)] = True
        return self

    def revive_at(self, tick: int, peers) -> "Scenario":
        """Rejoin-with-reset at ``tick`` — the peer restarts knowing only
        itself and re-broadcasts Join (kaboodle.rs:144-152, 228-251)."""
        self._revive[tick, np.asarray(peers)] = True
        return self

    def churn(
        self,
        rate: float,
        start: int = 0,
        stop: int | None = None,
        protect=(),
    ) -> "Scenario":
        """Random join+leave churn: each tick in [start, stop) every live peer
        dies w.p. ``rate`` and every dead peer rejoins w.p. ``rate`` (the
        BASELINE config-3 "5%/tick join+leave" schedule). ``protect`` peers
        never die (keeps at least a stable core so convergence is defined)."""
        stop = self.ticks if stop is None else stop
        alive = self._alive_before(start)
        prot = np.zeros((self.n,), dtype=bool)
        prot[np.asarray(protect, dtype=np.int64)] = True
        for t in range(start, stop):
            # Pre-existing events are never rewritten (an explicit revive_at
            # of an alive peer is a deliberate restart-with-reset); churn only
            # draws for peers with no event this tick, and tracks aliveness
            # with the kernel's own revive-wins (alive & ~kill) | revive rule
            # so alive_trajectory() stays exact under composition.
            untouched = ~self._kill[t] & ~self._revive[t]
            cur = (alive & ~self._kill[t]) | self._revive[t]
            u = self._rng.random(self.n)
            self._kill[t] |= cur & untouched & ~prot & (u < rate)
            self._revive[t] |= ~cur & untouched & (u < rate)
            alive = (alive & ~self._kill[t]) | self._revive[t]
        return self

    def partition_at(self, tick: int, groups, until: int | None = None) -> "Scenario":
        """Assign partition group ids from ``tick`` until ``until`` (exclusive;
        default: end of run). Messages cross groups only if ids match."""
        until = self.ticks if until is None else until
        self._partition[tick:until] = np.asarray(groups, dtype=np.int32)[None, :]
        return self

    def heal_at(self, tick: int) -> "Scenario":
        """Remove all partitions from ``tick`` onward."""
        self._partition[tick:] = 0
        return self

    def drop(self, rate: float, start: int = 0, stop: int | None = None) -> "Scenario":
        """Uniform random per-edge message drop probability over [start, stop)."""
        stop = self.ticks if stop is None else stop
        self._drop_rate[start:stop] = rate
        return self

    def manual_ping_at(self, tick: int, src: int, dst: int) -> "Scenario":
        """One manual ping (the `ping_addrs` API, lib.rs:268-297)."""
        self._manual[tick, src] = dst
        return self

    # ---- derived views -----------------------------------------------------

    def _alive_before(self, tick: int) -> np.ndarray:
        alive = self._initial_alive.copy()
        for t in range(tick):
            alive = (alive & ~self._kill[t]) | self._revive[t]
        return alive

    def initial_alive(self) -> np.ndarray:
        """Alive mask to pass to ``init_state`` (bool [N])."""
        return self._initial_alive.copy()

    def alive_trajectory(self) -> np.ndarray:
        """bool [T, N]: the post-tick alive mask the kernel will compute."""
        out = np.zeros((self.ticks, self.n), dtype=bool)
        alive = self._initial_alive.copy()
        for t in range(self.ticks):
            alive = (alive & ~self._kill[t]) | self._revive[t]
            out[t] = alive
        return out

    def build(self) -> TickInputs:
        """Compile to scan-ready ``TickInputs`` stacked along [T]."""
        import jax.numpy as jnp

        return TickInputs(
            kill=jnp.asarray(self._kill),
            revive=jnp.asarray(self._revive),
            partition=jnp.asarray(self._partition),
            drop_rate=jnp.asarray(self._drop_rate),
            manual_target=jnp.asarray(self._manual),
            drop_ok=None,
        )


def all_fault_paths_scenario(
    n: int, ticks: int = 1, drop_rate: float = 0.1, revive: bool = True
) -> Scenario:
    """Every fault path live in one schedule: kill, revive/restart, 2-way
    partition over the first half, random drop, and a manual ping per tick.

    The single source for "exercise the whole faulty program" shapes — used by
    the driver dry run (__graft_entry__.dryrun_multichip) and the sharded
    scale proof (scripts/sharded_scale_proof.py) so the two validate the same
    program. ``revive=False`` drops only the revive event (whose rejoin runs
    the join-gossip path — the working set that exceeds the emulating host at
    N=65,536; the proof script's ``--no-revive``).
    """
    if n < 4:
        raise ValueError("need n >= 4 to exercise every path")
    sc = Scenario(n, ticks).kill_at(0, [1]).drop(drop_rate)
    if revive:
        # Revive exercises rejoin-with-reset; on a 1-tick run reviving the
        # killed peer would cancel the kill (revive wins in the kernel), so
        # restart a live peer instead.
        sc.revive_at(ticks - 1, [3] if ticks == 1 else [1])
    sc.partition_at(0, np.arange(n, dtype=np.int32) % 2, until=max(1, ticks // 2))
    for t in range(ticks):
        sc.manual_ping_at(t, 0, 2)
    return sc


def baseline_scenario(config: int, n: int | None = None, ticks: int | None = None, seed: int = 0) -> Scenario:
    """The five driver configs from BASELINE.json as scenarios.

    ``n``/``ticks`` override the driver-specified scale (tests run scaled-down
    replicas of the same shapes). Config numbers are 1-based as in BASELINE.md.

    1. 4-peer demo mesh, fault-free (the 2x2 zellij demo, justfile:10-15).
    2. 1,024 peers, no churn (ticks-to-convergence measurement).
    3. 8,192 peers, 5%/tick join+leave churn for the first half, then calm
       (exercises the suspicion / indirect-ping / removal path).
    4. 65,536 peers, fault-free (run sharded: ICI all-reduce fingerprint check).
    5. 65,536 peers, 10% random message drop + a 2-way partition over the
       middle third; both faults heal at the final third and the mesh
       re-converges.

    Two protocol properties bound what config 5 can assert (both faithful to
    the reference, verified against the kernel):

    - *Sustained drop precludes instantaneous agreement.* In faithful mode a
      forwarded indirect Ack marks the **proxy** Known, not the suspect
      (quirk Q11, kaboodle.rs:408-415 applies to the datagram's sender), so a
      suspicion only clears if the suspect happens to message the suspector
      directly within the timeout. Under p=10% loss each peer-tick has ~2%
      chance of a false removal (later healed by any datagram from the
      removed peer, Q1) — at N=65,536 that is ~10^3 membership flips per
      tick, so the convergence predicate (min fingerprint == max) is
      essentially never true *while* drop is active. Hence the fault window
      closes before convergence is measured.
    - *Partitions must heal before mutual purge completes.* Removal is purely
      local timeout (Failed broadcasts are inert, Q3) and only lonely peers
      rebroadcast Join (kaboodle.rs:228-251), so if two sides fully purge
      each other there is no re-merge path — in the reference exactly as
      here. Purge throughput is ~1 removal/peer/tick after the pipeline
      fills, so the partition window must be < (peers behind the partition)
      ticks. At the driver's scale (32,768 behind the cut, 32-tick window)
      this holds by 3 orders of magnitude; scaled-down replicas must scale
      the window too (see tests/test_scenario.py).
    """
    if config == 1:
        sc = Scenario(n or 4, ticks or 16, seed)
    elif config == 2:
        sc = Scenario(n or 1024, ticks or 32, seed)
    elif config == 3:
        sc = Scenario(n or 8192, ticks or 64, seed)
        sc.churn(0.05, start=1, stop=sc.ticks // 2, protect=[0])
    elif config == 4:
        sc = Scenario(n or 65536, ticks or 32, seed)
    elif config == 5:
        sc = Scenario(n or 65536, ticks or 96, seed)
        third = sc.ticks // 3
        sc.drop(0.10, stop=2 * third)
        groups = (np.arange(sc.n) % 2).astype(np.int32)
        sc.partition_at(third, groups)
        sc.heal_at(2 * third)
    else:
        raise ValueError(f"unknown baseline config {config!r} (want 1-5)")
    return sc
