"""Mesh state, per-tick inputs, and per-tick metrics for the tick kernel.

The whole mesh — N simulated SWIM peers — is a handful of dense tensors. Row i
is peer i's private view of the mesh, the tensor analogue of the reference's
per-process ``Arc<Mutex<KnownPeers>>`` (lib.rs:66, structs.rs:14):

- ``state[i, j]``  what peer i believes about peer j (spec codes: NOT_MEMBER /
  KNOWN / WAITING_FOR_PING / WAITING_FOR_INDIRECT_PING — structs.rs:27-41).
- ``timer[i, j]``  the tick stamp stored inside the reference's ``PeerState``
  variants (``Instant``): last-heard for Known, sent-at for the waiting states.

Everything else is O(N): aliveness, identity words, join-broadcast throttling
state (kaboodle.rs:102-103), and the carried-over anti-entropy candidate from
the previous tick's KnownPeersRequest deliveries (kaboodle.rs:707-740).

All fields are plain arrays so the pytree shards trivially: the row axis (axis
0 of the ``[N, N]`` tensors, the only axis of the ``[N]`` vectors) is the data-
parallel axis that `kaboodle_tpu.parallel` distributes across chips.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from kaboodle_tpu.spec import KNOWN


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class MeshState:
    """Complete simulator state for N peers. See module docstring."""

    state: jax.Array  # int8  [N, N] spec state codes
    timer: jax.Array  # int32 [N, N] tick stamps (int16 in lean mode, MEMORY_PLAN.md)
    alive: jax.Array  # bool  [N]    silent-leave churn (quirk Q8)
    identity: jax.Array  # uint32 [N] identity word per peer (lib.rs:88-92)
    never_broadcast: jax.Array  # bool [N]  true until the first Join broadcast
    last_broadcast: jax.Array  # int32 [N] tick of last Join (kaboodle.rs:102)
    # The previous tick's anti-entropy request, stored at the *sender*:
    # peer s sent KnownPeersRequest{kpr_fp[s], kpr_n[s]} to kpr_partner[s]
    # (-1: none / dropped). Receivers turn these into this tick's first-priority
    # sync candidates (kaboodle.rs:448-512 records them; resolution is D2).
    kpr_partner: jax.Array  # int32 [N]
    kpr_fp: jax.Array  # uint32 [N]
    kpr_n: jax.Array  # int32 [N]
    tick: jax.Array  # int32 scalar
    key: jax.Array  # PRNG key (counter-based; the ChaChaRng analogue, kaboodle.rs:164)
    # Per-edge latency EWMA in ticks (kaboodle.rs:789-817, weight 0.8 newest;
    # NaN = no sample yet, the reference's Option::None). None compiles the
    # tracking out (a memory/bandwidth saver for throughput benches).
    latency: jax.Array | None = None  # float32 [N, N]
    # id_view[i, j]: the identity word peer i last saw for peer j — carried by
    # every envelope (structs.rs:77-83) and applied at the Q1 mark, so a
    # set_identity spreads via traffic exactly like the reference
    # (lib.rs:323-336). None = the D-API1 instant-visibility fast mode: all
    # rows read the global ``identity`` vector.
    id_view: jax.Array | None = None  # uint32 [N, N]

    @property
    def n(self) -> int:
        return self.state.shape[-1]


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TickInputs:
    """Per-tick scenario inputs. Stack along a leading [T] axis to scan.

    ``drop_ok[s, d]`` gates delivery of every unicast and broadcast from s to d
    this tick (the simulator's fault-injection surface; the reference has no
    equivalent — SURVEY.md §5). ``partition[s]`` is a group id; messages cross
    groups only if the ids match. ``manual_target`` injects one manual ping per
    peer (the `ping_addrs` API, lib.rs:268-297), -1 for none.
    """

    kill: jax.Array  # bool [N] silent leave this tick (Q8)
    revive: jax.Array  # bool [N] reset + rejoin this tick
    partition: jax.Array  # int32 [N] partition group ids (all equal = no partition)
    drop_rate: jax.Array  # float32 [] random per-edge drop probability
    manual_target: jax.Array  # int32 [N] manual ping target or -1
    drop_ok: jax.Array | None = None  # bool [N, N] explicit delivery gate (tests)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TickMetrics:
    """Per-tick observability — free tensor reductions (SURVEY.md §5)."""

    messages_delivered: jax.Array  # int32 [] unicasts delivered this tick
    converged: jax.Array  # bool [] all alive peers agree on the fingerprint
    agree_fraction: jax.Array  # float32 [] fraction of alive peers at the min fingerprint
    mean_membership: jax.Array  # float32 [] mean map size over alive peers
    fingerprint_min: jax.Array  # uint32 []
    fingerprint_max: jax.Array  # uint32 []


def init_state(
    n: int,
    identities: jax.Array | None = None,
    seed: int = 0,
    alive: jax.Array | None = None,
    ring_contacts: int = 0,
    track_latency: bool = True,
    instant_identity: bool = False,
    timer_dtype=jnp.int32,
    announced: bool = False,
) -> MeshState:
    """Fresh mesh: every peer knows only itself (kaboodle.rs:144-152) and will
    broadcast Join on its first active phase (kaboodle.rs:228-251).

    ``announced=True`` clears the never-broadcast flags: the state models a
    mesh that already announced itself — the right pairing for converged
    inits (``ring_contacts=n-1``), where leaving the flags set would fire a
    spurious all-N Join re-announce (zero new joiners) on the first tick
    and skew steady-state measurements.

    ``ring_contacts=c`` additionally seeds peer i with Known entries for
    peers (i+1..i+c) mod n — out-of-band bootstrap contacts for the gossip
    boot (``SwimConfig(join_broadcast_enabled=False)``), where membership must
    spread via traffic + anti-entropy instead of the broadcast domain.
    ``track_latency=False`` / ``instant_identity=True`` drop the optional
    [N, N] tensors (see MeshState) for throughput/memory-bound runs.

    ``timer_dtype=jnp.int16`` halves the timer tensor (the biggest lean-state
    resident — MEMORY_PLAN.md) and is safe for runs under ~32k ticks: every
    kernel write stays in the timer's dtype, ages compute in int32, and the
    only negative stamps (Q6 back-dating) are small. Caller's contract: the
    tick count must stay below ``iinfo(timer_dtype).max``.
    """
    idx = jnp.arange(n, dtype=jnp.int32)
    eye = idx[:, None] == idx[None, :]
    if identities is None:
        # LockstepMesh's default: identity word = index + 1.
        identities = (idx + 1).astype(jnp.uint32)
    identities = jnp.asarray(identities, dtype=jnp.uint32)
    member = eye
    if ring_contacts:
        if ring_contacts >= n:
            raise ValueError("ring_contacts must be < n")
        delta = (idx[None, :] - idx[:, None]) % n
        member = member | (delta <= ring_contacts)
    return MeshState(
        state=jnp.where(member, jnp.int8(KNOWN), jnp.int8(0)),
        timer=jnp.zeros((n, n), dtype=timer_dtype),
        alive=jnp.ones((n,), dtype=bool) if alive is None else alive,
        identity=identities,
        never_broadcast=jnp.zeros((n,), dtype=bool) if announced
        else jnp.ones((n,), dtype=bool),
        last_broadcast=jnp.zeros((n,), dtype=jnp.int32),
        kpr_partner=jnp.full((n,), -1, dtype=jnp.int32),
        kpr_fp=jnp.zeros((n,), dtype=jnp.uint32),
        kpr_n=jnp.zeros((n,), dtype=jnp.int32),
        tick=jnp.int32(0),
        key=jax.random.PRNGKey(seed),
        latency=None if not track_latency else jnp.full((n, n), jnp.nan, dtype=jnp.float32),
        # Seed identity views with the boot identities: entries are only read
        # for members, and every membership-creating path rewrites them, so
        # this just fixes the view of self + bootstrap contacts.
        id_view=None if instant_identity else jnp.broadcast_to(identities[None, :], (n, n)),
    )


def idle_inputs(n: int, ticks: int | None = None) -> TickInputs:
    """No-fault inputs; with ``ticks`` set, stacked [T, ...] for lax.scan."""

    def shp(*s):
        return (ticks, *s) if ticks is not None else s

    return TickInputs(
        kill=jnp.zeros(shp(n), dtype=bool),
        revive=jnp.zeros(shp(n), dtype=bool),
        partition=jnp.zeros(shp(n), dtype=jnp.int32),
        drop_rate=jnp.zeros(shp(), dtype=jnp.float32),
        manual_target=jnp.full(shp(n), -1, dtype=jnp.int32),
        drop_ok=None,
    )
