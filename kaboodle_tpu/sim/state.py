"""Mesh state, per-tick inputs, and per-tick metrics for the tick kernel.

The whole mesh — N simulated SWIM peers — is a handful of dense tensors. Row i
is peer i's private view of the mesh, the tensor analogue of the reference's
per-process ``Arc<Mutex<KnownPeers>>`` (lib.rs:66, structs.rs:14):

- ``state[i, j]``  what peer i believes about peer j (spec codes: NOT_MEMBER /
  KNOWN / WAITING_FOR_PING / WAITING_FOR_INDIRECT_PING — structs.rs:27-41).
- ``timer[i, j]``  the tick stamp stored inside the reference's ``PeerState``
  variants (``Instant``): last-heard for Known, sent-at for the waiting states.

Everything else is O(N): aliveness, identity words, join-broadcast throttling
state (kaboodle.rs:102-103), and the carried-over anti-entropy candidate from
the previous tick's KnownPeersRequest deliveries (kaboodle.rs:707-740).

All fields are plain arrays so the pytree shards trivially: the row axis (axis
0 of the ``[N, N]`` tensors, the only axis of the ``[N]`` vectors) is the data-
parallel axis that `kaboodle_tpu.parallel` distributes across chips.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from kaboodle_tpu.spec import KNOWN


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class MeshState:
    """Complete simulator state for N peers. See module docstring."""

    state: jax.Array  # int8  [N, N] spec state codes
    timer: jax.Array  # int32 [N, N] tick stamps
    alive: jax.Array  # bool  [N]    silent-leave churn (quirk Q8)
    identity: jax.Array  # uint32 [N] identity word per peer (lib.rs:88-92)
    never_broadcast: jax.Array  # bool [N]  true until the first Join broadcast
    last_broadcast: jax.Array  # int32 [N] tick of last Join (kaboodle.rs:102)
    # The previous tick's anti-entropy request, stored at the *sender*:
    # peer s sent KnownPeersRequest{kpr_fp[s], kpr_n[s]} to kpr_partner[s]
    # (-1: none / dropped). Receivers turn these into this tick's first-priority
    # sync candidates (kaboodle.rs:448-512 records them; resolution is D2).
    kpr_partner: jax.Array  # int32 [N]
    kpr_fp: jax.Array  # uint32 [N]
    kpr_n: jax.Array  # int32 [N]
    tick: jax.Array  # int32 scalar
    key: jax.Array  # PRNG key (counter-based; the ChaChaRng analogue, kaboodle.rs:164)

    @property
    def n(self) -> int:
        return self.state.shape[-1]


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TickInputs:
    """Per-tick scenario inputs. Stack along a leading [T] axis to scan.

    ``drop_ok[s, d]`` gates delivery of every unicast and broadcast from s to d
    this tick (the simulator's fault-injection surface; the reference has no
    equivalent — SURVEY.md §5). ``partition[s]`` is a group id; messages cross
    groups only if the ids match. ``manual_target`` injects one manual ping per
    peer (the `ping_addrs` API, lib.rs:268-297), -1 for none.
    """

    kill: jax.Array  # bool [N] silent leave this tick (Q8)
    revive: jax.Array  # bool [N] reset + rejoin this tick
    partition: jax.Array  # int32 [N] partition group ids (all equal = no partition)
    drop_rate: jax.Array  # float32 [] random per-edge drop probability
    manual_target: jax.Array  # int32 [N] manual ping target or -1
    drop_ok: jax.Array | None = None  # bool [N, N] explicit delivery gate (tests)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TickMetrics:
    """Per-tick observability — free tensor reductions (SURVEY.md §5)."""

    messages_delivered: jax.Array  # int32 [] unicasts delivered this tick
    converged: jax.Array  # bool [] all alive peers agree on the fingerprint
    agree_fraction: jax.Array  # float32 [] fraction of alive peers at the min fingerprint
    mean_membership: jax.Array  # float32 [] mean map size over alive peers
    fingerprint_min: jax.Array  # uint32 []
    fingerprint_max: jax.Array  # uint32 []


def init_state(
    n: int,
    identities: jax.Array | None = None,
    seed: int = 0,
    alive: jax.Array | None = None,
) -> MeshState:
    """Fresh mesh: every peer knows only itself (kaboodle.rs:144-152) and will
    broadcast Join on its first active phase (kaboodle.rs:228-251)."""
    idx = jnp.arange(n, dtype=jnp.int32)
    eye = idx[:, None] == idx[None, :]
    if identities is None:
        # LockstepMesh's default: identity word = index + 1.
        identities = (idx + 1).astype(jnp.uint32)
    return MeshState(
        state=jnp.where(eye, jnp.int8(KNOWN), jnp.int8(0)),
        timer=jnp.zeros((n, n), dtype=jnp.int32),
        alive=jnp.ones((n,), dtype=bool) if alive is None else alive,
        identity=jnp.asarray(identities, dtype=jnp.uint32),
        never_broadcast=jnp.ones((n,), dtype=bool),
        last_broadcast=jnp.zeros((n,), dtype=jnp.int32),
        kpr_partner=jnp.full((n,), -1, dtype=jnp.int32),
        kpr_fp=jnp.zeros((n,), dtype=jnp.uint32),
        kpr_n=jnp.zeros((n,), dtype=jnp.int32),
        tick=jnp.int32(0),
        key=jax.random.PRNGKey(seed),
    )


def idle_inputs(n: int, ticks: int | None = None) -> TickInputs:
    """No-fault inputs; with ``ticks`` set, stacked [T, ...] for lax.scan."""

    def shp(*s):
        return (ticks, *s) if ticks is not None else s

    return TickInputs(
        kill=jnp.zeros(shp(n), dtype=bool),
        revive=jnp.zeros(shp(n), dtype=bool),
        partition=jnp.zeros(shp(n), dtype=jnp.int32),
        drop_rate=jnp.zeros(shp(), dtype=jnp.float32),
        manual_target=jnp.full(shp(n), -1, dtype=jnp.int32),
        drop_ok=None,
    )
