"""Blocked-sparse membership planes for million-peer worlds.

The dense engines keep the bit-exact ``[N, N]`` formulation; this package
holds its ``blocked_topk`` twin: each row's membership view lives in a
``[N, K]`` top-K-neighbor block (int32 neighbor-index plane + int8 state
plane + timer plane), and every uniform draw is counter-based threefry
keyed ``(seed, cursor, stream, row, slot)`` so no ``[N, N]`` tensor is ever
materialized.  The tick kernel is derived from the same phasegraph op
table as the dense engines (``build_graph(..., layout="blocked_topk")`` +
``plan(graph, "sparse")``); parity with the dense oracle is pinned on
distribution statistics, not bits (tests/test_fuzz_parity.py).
"""

from kaboodle_tpu.sparseplane.state import (
    SparseSpec,
    SparseState,
    SparseTickInputs,
    SparseTickMetrics,
    init_sparse_state,
    sparse_idle_inputs,
    sparse_fingerprint,
)
from kaboodle_tpu.sparseplane.kernel import make_sparse_tick_fn
from kaboodle_tpu.sparseplane.rng import (
    STREAM_ACK,
    STREAM_CHAIN,
    STREAM_DRAW,
    STREAM_GOSSIP,
    STREAM_PING,
    STREAM_PROXY,
    stream_table,
)
from kaboodle_tpu.sparseplane.runner import (
    simulate_sparse,
    run_sparse_until_converged,
)

__all__ = [
    "STREAM_ACK",
    "STREAM_CHAIN",
    "STREAM_DRAW",
    "STREAM_GOSSIP",
    "STREAM_PING",
    "STREAM_PROXY",
    "stream_table",
    "SparseSpec",
    "SparseState",
    "SparseTickInputs",
    "SparseTickMetrics",
    "init_sparse_state",
    "sparse_idle_inputs",
    "sparse_fingerprint",
    "make_sparse_tick_fn",
    "simulate_sparse",
    "run_sparse_until_converged",
]
