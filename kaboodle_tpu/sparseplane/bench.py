"""``bench.py --sparse`` — the million-peer bench, banks BENCH_sparse.json.

The ISSUE 18 acceptance run: boot N >= 1,000,000 peers single-host in the
blocked_topk layout, advance real ticks, and bank:

- **per-peer cost** — seconds/tick and ns/peer/tick over warmed steady
  chunks, with ``compiles_steady`` counted across the timed window (the
  zero-recompile gate, same counter as KB405);
- **convergence curves** — block_fill and mean_membership per banked
  chunk boundary from the cold boot (at K << N the mesh converges to
  full blocks and a full alive count, not to fingerprint agreement — the
  full-agreement predicate is the toy-N stat lane's job);
- **sub-quadratic evidence** — AOT bytes-accessed of the same steady tick
  at N=1024 vs N=8192 (an 8x N step): the ratio must sit far below the
  dense 64x, and is banked next to the costscope registry entries
  (phasegraph.tick.sparse) that gate it per-commit.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _tick_bytes(cfg, spec, n: int) -> int:
    import jax

    from kaboodle_tpu.sparseplane import (
        init_sparse_state,
        make_sparse_tick_fn,
        sparse_idle_inputs,
    )

    comp = (
        jax.jit(make_sparse_tick_fn(cfg, spec))
        .lower(init_sparse_state(n, spec, seed=0), sparse_idle_inputs(n))
        .compile()
    )
    ca = comp.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return int(ca.get("bytes accessed", 0))


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="bench.py --sparse",
        description="million-peer blocked_topk bench; writes BENCH_sparse.json",
    )
    p.add_argument("--n", type=int, default=1 << 20,
                   help="mesh size (default: 2^20 = 1,048,576 peers)")
    p.add_argument("--k", type=int, default=16, help="block width K")
    p.add_argument("--boot", type=int, default=3, help="boot ring contacts")
    p.add_argument("--ticks", type=int, default=24,
                   help="total ticks from boot (banked in chunks)")
    p.add_argument("--chunk", type=int, default=8,
                   help="scan chunk length (one compiled program)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--out", default="BENCH_sparse.json")
    args = p.parse_args(argv)

    import jax
    import numpy as np

    from kaboodle_tpu.analysis.ir.surface import (
        assert_counter_live,
        compile_counter,
    )
    from kaboodle_tpu.config import SwimConfig
    from kaboodle_tpu.sparseplane import (
        SparseSpec,
        init_sparse_state,
        simulate_sparse,
        sparse_idle_inputs,
    )

    assert_counter_live()
    cfg = SwimConfig(join_broadcast_enabled=False)
    spec = SparseSpec(k=args.k, gossip_fanout=4, boot_contacts=args.boot)
    n, chunk = args.n, args.chunk
    chunks = max(args.ticks // chunk, 2)

    print(f"sparse-bench: boot n={n} k={spec.k} ({chunks}x{chunk} ticks)")
    st = init_sparse_state(n, spec, seed=args.seed)
    inp = sparse_idle_inputs(n, ticks=chunk)

    curve = []
    times = []
    compiles_steady = 0
    for c in range(chunks):
        t0 = time.perf_counter()
        if c == 0:
            # chunk 0 pays the compile; everything after is the steady
            # window and must compile nothing
            st, m = simulate_sparse(st, inp, cfg, spec)
            jax.block_until_ready(st.nbr_idx)
        else:
            with compile_counter() as box:
                st, m = simulate_sparse(st, inp, cfg, spec)
                jax.block_until_ready(st.nbr_idx)
            compiles_steady += box.count
            times.append(time.perf_counter() - t0)
        curve.append({
            "tick": int(st.tick),
            "block_fill": float(np.asarray(m.block_fill)[-1]),
            "mean_membership": float(np.asarray(m.mean_membership)[-1]),
        })
        print(f"sparse-bench: tick {int(st.tick):4d} "
              f"fill={curve[-1]['block_fill']:.3f} "
              f"({time.perf_counter() - t0:.1f}s)")

    s_per_tick = sum(times) / (len(times) * chunk)
    small, big = _tick_bytes(cfg, spec, 1024), _tick_bytes(cfg, spec, 8192)
    record = {
        "metric": "sparse_bench",
        "n": n, "k": spec.k, "boot_contacts": args.boot,
        "ticks": chunks * chunk, "chunk": chunk, "seed": args.seed,
        "s_per_tick": s_per_tick,
        "ns_per_peer_tick": 1e9 * s_per_tick / n,
        "compiles_steady": compiles_steady,
        "curve": curve,
        "sub_quadratic": {
            "bytes_accessed_n1024": small,
            "bytes_accessed_n8192": big,
            "ratio_8x_n": big / max(small, 1),
            "dense_ratio_would_be": 64.0,
        },
    }
    with open(args.out, "w") as f:
        json.dump(record, f, indent=2)
        f.write("\n")
    print(f"sparse-bench: {s_per_tick * 1e3:.0f} ms/tick "
          f"({record['ns_per_peer_tick']:.0f} ns/peer), "
          f"compiles_steady={compiles_steady}, "
          f"bytes ratio {record['sub_quadratic']['ratio_8x_n']:.1f}x "
          f"-> {args.out}")
    return 0 if compiles_steady == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
