"""``python -m kaboodle_tpu sparse --dryrun`` — the sparseplane CI lane.

Two legs, seconds-to-minutes on CPU:

1. **Toy-N stat check** — the blocked_topk engine against the dense oracle
   on a matched-seed full-view boot (k >= n-1, so "converged" is the same
   fingerprint-agreement predicate the dense runner tests): both arms must
   converge, the sparse convergence tick must sit in the calibrated band
   around the dense one, the converged steady tick must emit exactly the
   dense steady counter means (n pings, 2n delivered, agreement 1.0), and
   a warmed steady window must compile NOTHING fresh.

2. **Capped million-peer smoke** — boot N=2^20 peers (or ``--smoke-n``),
   run a few real ticks, and report per-peer per-tick cost; the smoke
   proves the [N, K] layout actually holds a million-peer world in memory
   and advances it, not just that the program traces. ``--skip-smoke``
   drops this leg for fast local iteration.

The at-scale numbers (longer runs, convergence curves, banked JSON) live
in ``bench.py --sparse`` / BENCH_sparse.json; this is the wiring gate.
"""

from __future__ import annotations

import argparse
import json
import sys
import time


def _stat_check(seed: int) -> dict:
    import numpy as np

    from kaboodle_tpu.analysis.ir.surface import (
        assert_counter_live,
        compile_counter,
    )
    from kaboodle_tpu.config import SwimConfig
    from kaboodle_tpu.sim.runner import run_until_converged
    from kaboodle_tpu.sim.state import init_state
    from kaboodle_tpu.sparseplane import (
        SparseSpec,
        init_sparse_state,
        run_sparse_until_converged,
        simulate_sparse,
        sparse_idle_inputs,
    )

    assert_counter_live()
    n, boot = 24, 2
    cfg = SwimConfig(join_broadcast_enabled=False)
    spec = SparseSpec(k=32, gossip_fanout=4, boot_contacts=boot)

    _, dticks, dconv = run_until_converged(
        init_state(n, seed=seed, ring_contacts=boot), cfg, max_ticks=96
    )
    sst = init_sparse_state(n, spec, seed=seed)
    fin, sticks, sconv = run_sparse_until_converged(
        sst, cfg, spec, max_ticks=96
    )
    d, s = int(dticks), int(sticks)
    checks = {
        "dense_converged": bool(dconv),
        "sparse_converged": bool(sconv),
        # the calibrated band: empirically ~2.1x at gossip_fanout=4
        "band": bool(dconv) and bool(sconv) and d // 2 <= s <= 4 * d + 10,
    }

    # steady counter means from the converged mesh, zero drops
    _, m = simulate_sparse(fin, sparse_idle_inputs(n, ticks=8), cfg, spec)
    checks["steady_pings"] = bool((np.asarray(m.pings_sent) == n).all())
    checks["steady_delivered"] = bool(
        (np.asarray(m.messages_delivered) == 2 * n).all()
    )
    checks["steady_agreement"] = bool(
        (np.asarray(m.agree_fraction) == 1.0).all()
    )

    # zero fresh compiles re-dispatching the warmed steady window
    with compile_counter() as box:
        simulate_sparse(fin, sparse_idle_inputs(n, ticks=8), cfg, spec)
    checks["compiles_steady_zero"] = box.count == 0

    return {
        "n": n, "k": spec.k, "dense_ticks": d, "sparse_ticks": s,
        "compiles_steady": box.count, "checks": checks,
    }


def _smoke(n: int, ticks: int, seed: int) -> dict:
    import jax

    from kaboodle_tpu.config import SwimConfig
    from kaboodle_tpu.sparseplane import (
        SparseSpec,
        init_sparse_state,
        simulate_sparse,
        sparse_idle_inputs,
    )

    cfg = SwimConfig(join_broadcast_enabled=False)
    spec = SparseSpec(k=16, gossip_fanout=4, boot_contacts=3)
    st = init_sparse_state(n, spec, seed=seed)
    inp = sparse_idle_inputs(n, ticks=ticks)
    # compile + one warm pass, then the timed pass
    st2, _ = simulate_sparse(st, inp, cfg, spec)
    jax.block_until_ready(st2.nbr_idx)
    t0 = time.perf_counter()
    st3, m = simulate_sparse(st2, inp, cfg, spec)
    jax.block_until_ready(st3.nbr_idx)
    dt = time.perf_counter() - t0
    import numpy as np

    return {
        "n": n, "k": spec.k, "ticks": ticks,
        "s_per_tick": dt / ticks,
        "ns_per_peer_tick": 1e9 * dt / ticks / n,
        "block_fill": float(np.asarray(m.block_fill)[-1]),
        "advanced": int(st3.tick) == 2 * ticks,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="kaboodle_tpu sparse",
        description="sparseplane dryrun: toy-N stat check vs the dense "
                    "oracle + capped million-peer smoke",
    )
    p.add_argument("--dryrun", action="store_true",
                   help="accepted for symmetry with the other CI lanes "
                        "(this tool IS the dryrun)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--smoke-n", type=int, default=1 << 20,
                   help="smoke mesh size (default: 2^20 peers)")
    p.add_argument("--smoke-ticks", type=int, default=4,
                   help="timed smoke ticks after one warm pass")
    p.add_argument("--skip-smoke", action="store_true",
                   help="stat check only (fast local iteration)")
    args = p.parse_args(argv)

    stat = _stat_check(args.seed)
    ok = all(stat["checks"].values())
    for name, good in stat["checks"].items():
        print(f"sparse: {name:22s} {'ok' if good else 'FAIL'}")
    print(f"sparse: convergence dense={stat['dense_ticks']} "
          f"sparse={stat['sparse_ticks']} ticks")

    out = {"metric": "sparse_dryrun", "stat": stat}
    if not args.skip_smoke:
        smoke = _smoke(args.smoke_n, args.smoke_ticks, args.seed)
        ok = ok and smoke["advanced"]
        out["smoke"] = smoke
        print(f"sparse: smoke n={smoke['n']} "
              f"{smoke['s_per_tick'] * 1e3:.0f} ms/tick "
              f"({smoke['ns_per_peer_tick']:.0f} ns/peer), "
              f"fill {smoke['block_fill']:.3f}")
    out["ok"] = ok
    print(json.dumps(out))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
