"""The blocked-sparse SWIM tick: segment gather/scatter over ``[N, K]`` blocks.

Derived from the same phasegraph op table as the dense engines
(``build_graph(cfg, layout="blocked_topk")`` + ``plan(graph, "sparse")``);
the tail pass grouping below mirrors the planner's output one-to-one and is
pinned against it in tests/test_sparseplane.py:

  expiry    suspicion twin: WaitingForIndirectPing slots age out, the oldest
            timed-out WaitingForPing slot per row escalates to an indirect
            ping chain over counter-drawn proxy slots.
  draw      probe-draw twin: uniform pick among the oldest-k Known slots
            (the same ``choose_one_of_oldest_k`` primitive the dense kernel
            uses, over ``[N, K]`` instead of ``[N, N]``).
  exchange  ping/ack delivery: the ack refreshes the armed slot; the ping
            sender-marks the sender inside the *target's* block (the one
            cross-row scatter of the tick, conflict-free by slot identity).
  gossip    anti-entropy twin: each delivered ack piggybacks
            ``gossip_fanout`` random sharable records from the target's
            block (Known, heard strictly within MAX_PEER_SHARE_AGE — the
            dense reply filter verbatim).
  repair    bounded block edits (sparseplane/repair.py): fold the tick's
            insert candidates into empty slots, static shapes only.
  finish    fingerprint + metrics + counter advance.

Every uniform is a counter-threefry draw keyed ``(seed, cursor, stream)``
with the element position supplying ``(row, slot)`` — no ``[N, N]`` tensor
exists anywhere in the tick (sparseplane/rng.py).

Semantics match the dense oracle distributionally, not bitwise; the known
deviations are bounded and documented here so the stat-pin harness
(tests/test_fuzz_parity.py) is comparing what it thinks it is:

- proxy picks draw with replacement (dense: distinct Gumbel-top-k) — only
  distinguishable when a row knows fewer than ``num_indirect_ping_peers``
  live peers;
- the ping-req leg does not sender-mark the requester at the proxy (a
  secondary dense spread path; gossip piggyback dominates it);
- at most one ping sender-mark *insert* lands per receiver per tick (the
  dense kernel can absorb one per sender) — extra senders retry next tick;
- revived rows re-enter via ring boot contacts instead of the join
  broadcast, which has no domain in a blocked world.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from kaboodle_tpu.config import SwimConfig
from kaboodle_tpu.ops.hashing import fingerprint_agreement
from kaboodle_tpu.ops.sampling import choose_one_of_oldest_k
from kaboodle_tpu.sparseplane import rng as sprng
from kaboodle_tpu.sparseplane.repair import repair_blocks, reseed_revived
from kaboodle_tpu.sparseplane.state import (
    SparseSpec,
    SparseState,
    SparseTickInputs,
    SparseTickMetrics,
    sparse_fingerprint,
)
from kaboodle_tpu.spec import (
    KNOWN,
    WAITING_FOR_INDIRECT_PING,
    WAITING_FOR_PING,
)

# The planner's tail grouping for mode="sparse" — kept here so the kernel
# and plan.py can never drift silently (pinned in tests/test_sparseplane.py).
SPARSE_TAIL_PASSES = ("expiry", "draw", "exchange", "gossip", "repair", "finish")


def _validate(cfg: SwimConfig) -> None:
    if cfg.join_broadcast_enabled:
        raise ValueError(
            "blocked_topk layout has no broadcast domain: build the config "
            "with join_broadcast_enabled=False (gossip boot via ring "
            "contacts replaces the join broadcast)"
        )
    if not cfg.faithful_failed_broadcast:
        raise ValueError(
            "intended-semantics failed-broadcast replay is dense-only "
            "([N, N, N] delivery replay); blocked_topk requires "
            "faithful_failed_broadcast=True"
        )
    if not cfg.faithful_indirect_ack:
        raise ValueError(
            "blocked_topk implements only the faithful indirect-ack "
            "attribution (forwarded ack refreshes the proxy, quirk Q11); "
            "set faithful_indirect_ack=True"
        )


def _rank_pick(mask: jax.Array, want: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Slot of the ``want``-th True per row: mask ``[N, K]``, want ``[N, D]``.

    Returns ``(slot [N, D] int32, ok [N, D] bool)`` — ``ok`` is False where
    the requested rank exceeds the row's population (which is also how the
    deterministic arange-ranks mode degrades to "first min(D, count)").
    """
    rank = jnp.cumsum(mask.astype(jnp.int32), axis=1) - 1  # [N, K]
    sel = mask[:, None, :] & (rank[:, None, :] == want[:, :, None])  # [N, D, K]
    slot = jnp.argmax(sel, axis=-1).astype(jnp.int32)
    ok = jnp.any(sel, axis=-1)
    return slot, ok


def make_sparse_tick_fn(
    cfg: SwimConfig, spec: SparseSpec, faulty: bool = True
):
    """Build the blocked-sparse tick: ``(SparseState, SparseTickInputs) ->
    (SparseState, SparseTickMetrics)``.  cfg/spec are static (hashable)."""
    _validate(cfg)
    timeout = int(cfg.ping_timeout_ticks)
    share_age = int(cfg.max_peer_share_age_ticks)
    n_proxy = int(cfg.num_indirect_ping_peers)
    kc = int(cfg.num_candidate_target_peers)
    g = int(spec.gossip_fanout)
    det = bool(cfg.deterministic)
    backdate = share_age if cfg.backdate_gossip_inserts else 0

    # The closure is traced from ANOTHER module (runner's lax.scan /
    # while_loop and the jax.jit call sites in tests), which per-module
    # reachability can't see — the pragma keeps the KB2xx tracer rules live
    # on the tick body without tainting the builder's static cfg/spec reads.
    def tick(st: SparseState, inp: SparseTickInputs):  # graftlint: traced
        n, k = st.nbr_idx.shape
        tdt = st.nbr_timer.dtype
        rows = jnp.arange(n, dtype=jnp.int32)
        slots = jnp.arange(k, dtype=jnp.int32)
        t32 = st.tick
        now_t = t32.astype(tdt)
        seed, cur = st.seed, st.cursor

        nbr_idx, nbr_state, nbr_timer = st.nbr_idx, st.nbr_state, st.nbr_timer
        alive = st.alive

        # -- churn (prologue): alive flips; revived rows gossip-boot fresh.
        if faulty:
            revived = inp.revive & ~alive
            alive = (alive | inp.revive) & ~inp.kill
            nbr_idx, nbr_state, nbr_timer = reseed_revived(
                nbr_idx, nbr_state, nbr_timer, revived, spec.boot_contacts, now_t
            )
            drop = inp.drop_rate
        else:
            drop = jnp.float32(0.0)

        # -- expiry: age suspicion timers, escalate the oldest timed-out
        # WaitingForPing slot per row through an indirect-ping chain.
        age = t32 - nbr_timer.astype(jnp.int32)  # [N, K]
        act = alive[:, None]
        wfip_exp = (nbr_state == WAITING_FOR_INDIRECT_PING) & (age >= timeout) & act
        wfp_timed = (nbr_state == WAITING_FOR_PING) & (age >= timeout) & act

        esc_score = jnp.where(wfp_timed, age, jnp.int32(-(1 << 30)))
        esc_slot = jnp.argmax(esc_score, axis=1).astype(jnp.int32)
        has_timed = jnp.any(wfp_timed, axis=1)
        esc_oh = (slots[None, :] == esc_slot[:, None]) & has_timed[:, None]

        known = nbr_state == KNOWN
        pcnt = jnp.sum(known, axis=1, dtype=jnp.int32)
        escalate = has_timed & (pcnt > 0)
        insta = has_timed & (pcnt == 0)  # no proxies: remove instantly

        if det:
            want_p = jnp.broadcast_to(
                jnp.arange(n_proxy, dtype=jnp.int32)[None, :], (n, n_proxy)
            )
        else:
            u_p = sprng.stream_uniform(seed, cur, sprng.STREAM_PROXY, (n, n_proxy))
            want_p = jnp.clip(
                jnp.floor(u_p * pcnt[:, None].astype(jnp.float32)).astype(jnp.int32),
                0,
                jnp.maximum(pcnt - 1, 0)[:, None],
            )
        pslot, p_ok = _rank_pick(known, want_p)  # [N, P]
        pj = jnp.take_along_axis(nbr_idx, pslot, axis=1)
        pj_c = jnp.clip(pj, 0, n - 1)
        suspect = jnp.take_along_axis(nbr_idx, esc_slot[:, None], axis=1)[:, 0]
        suspect_c = jnp.clip(suspect, 0, n - 1)

        if faulty:
            u_ch = sprng.stream_uniform(
                seed, cur, sprng.STREAM_CHAIN, (n, n_proxy, 4)
            )
            legs = jnp.all(u_ch >= drop, axis=-1)  # all 4 unicast legs land
        else:
            legs = jnp.ones((n, n_proxy), bool)
        chain_ok = escalate[:, None] & p_ok & legs & alive[pj_c] & alive[suspect_c][:, None]

        remove = wfip_exp | (esc_oh & insta[:, None])
        to_wfip = esc_oh & escalate[:, None]
        # Faithful indirect-ack (quirk Q11): the forwarded ack refreshes the
        # PROXY slot at the requester; the suspect stays WaitingForIndirectPing.
        refresh_p = jnp.any(
            (slots[None, None, :] == pslot[:, :, None]) & chain_ok[:, :, None],
            axis=1,
        )
        nbr_state = jnp.where(remove, jnp.int8(0), nbr_state)
        nbr_idx = jnp.where(remove, jnp.int32(-1), nbr_idx)
        nbr_state = jnp.where(to_wfip, jnp.int8(WAITING_FOR_INDIRECT_PING), nbr_state)
        nbr_timer = jnp.where(to_wfip, now_t, nbr_timer)
        nbr_state = jnp.where(refresh_p, jnp.int8(KNOWN), nbr_state)
        nbr_timer = jnp.where(refresh_p, now_t, nbr_timer)
        chain_msgs = jnp.int32(4) * jnp.sum(chain_ok, dtype=jnp.int32)

        # -- draw: ping target = uniform among the oldest-kc Known slots,
        # the dense primitive applied to [N, K] scores.
        known2 = nbr_state == KNOWN
        tslot = choose_one_of_oldest_k(
            nbr_timer,
            known2,
            kc,
            sprng.stream_key(seed, cur, sprng.STREAM_DRAW),
            deterministic=det,
            method=cfg.oldest_k_method,
        )
        has_ping = alive & (tslot >= 0)
        tslot_c = jnp.clip(tslot, 0, k - 1)
        tgt = jnp.take_along_axis(nbr_idx, tslot_c[:, None], axis=1)[:, 0]
        tgt_c = jnp.clip(tgt, 0, n - 1)
        arm = (slots[None, :] == tslot_c[:, None]) & has_ping[:, None]
        nbr_state = jnp.where(arm, jnp.int8(WAITING_FOR_PING), nbr_state)
        nbr_timer = jnp.where(arm, now_t, nbr_timer)

        # -- exchange: counter-draw bernoullis replace the dense [N, N]
        # delivery gate; the ack closes the probe, the ping sender-marks.
        if faulty:
            u_ping = sprng.stream_uniform(seed, cur, sprng.STREAM_PING, (n,))
            u_ack = sprng.stream_uniform(seed, cur, sprng.STREAM_ACK, (n,))
            del_ping = has_ping & alive[tgt_c] & (u_ping >= drop)
            del_ack = del_ping & (u_ack >= drop)
        else:
            del_ping = has_ping & alive[tgt_c]
            del_ack = del_ping

        ackref = arm & del_ack[:, None]
        nbr_state = jnp.where(ackref, jnp.int8(KNOWN), nbr_state)
        nbr_timer = jnp.where(ackref, now_t, nbr_timer)

        # Sender-mark inside the target's block: slot identified by matching
        # the sender id, so concurrent senders write disjoint (row, slot)
        # pairs; undelivered pings are routed to row n and dropped.
        blk_t = nbr_idx[tgt_c]  # [N, K] gather of target blocks
        occ_t = nbr_state[tgt_c] > 0
        eq = (blk_t == rows[:, None]) & occ_t
        mfound = jnp.any(eq, axis=1)
        mslot = jnp.argmax(eq, axis=1).astype(jnp.int32)
        mark_rows = jnp.where(del_ping & mfound, tgt_c, jnp.int32(n))
        nbr_state = nbr_state.at[mark_rows, mslot].set(jnp.int8(KNOWN), mode="drop")
        nbr_timer = nbr_timer.at[mark_rows, mslot].set(now_t, mode="drop")

        # Unknown sender: becomes an insert candidate at the receiver (max
        # keeps exactly one per receiver per tick, deterministically).
        pc_rows = jnp.where(del_ping & ~mfound, tgt_c, jnp.int32(n))
        ping_cand = (
            jnp.full((n,), -1, jnp.int32).at[pc_rows].max(rows, mode="drop")
        )
        exch_msgs = jnp.sum(del_ping, dtype=jnp.int32) + jnp.sum(
            del_ack, dtype=jnp.int32
        )

        # -- gossip: each delivered ack piggybacks g random sharable records
        # from the target's block (dense reply filter: Known, heard strictly
        # within MAX_PEER_SHARE_AGE; self never in a block by invariant).
        share_ok = (nbr_state == KNOWN) & (
            (t32 - nbr_timer.astype(jnp.int32)) < share_age
        )
        srow = share_ok[tgt_c]  # [N, K] sharable mask of my ping target
        scnt = jnp.sum(srow, axis=1, dtype=jnp.int32)
        if det:
            want_g = jnp.broadcast_to(jnp.arange(g, dtype=jnp.int32)[None, :], (n, g))
        else:
            u_g = sprng.stream_uniform(seed, cur, sprng.STREAM_GOSSIP, (n, g))
            want_g = jnp.clip(
                jnp.floor(u_g * scnt[:, None].astype(jnp.float32)).astype(jnp.int32),
                0,
                jnp.maximum(scnt - 1, 0)[:, None],
            )
        gslot, g_ok = _rank_pick(srow, want_g)
        gcand = jnp.take_along_axis(nbr_idx[tgt_c], gslot, axis=1)
        gcand = jnp.where(del_ack[:, None] & g_ok, gcand, jnp.int32(-1))

        # -- repair: fold the tick's candidates into empty slots.  Ping
        # sender-marks carry a fresh stamp and go first so they win dedup
        # against the same peer arriving backdated via gossip.
        cand = jnp.concatenate([ping_cand[:, None], gcand], axis=1)
        gstamp = now_t - jnp.asarray(backdate, tdt)
        stamps = jnp.concatenate(
            [
                jnp.broadcast_to(now_t, (n, 1)).astype(tdt),
                jnp.broadcast_to(gstamp, (n, g)).astype(tdt),
            ],
            axis=1,
        )
        nbr_idx, nbr_state, nbr_timer = repair_blocks(
            nbr_idx, nbr_state, nbr_timer, cand, stamps
        )

        # -- finish: fingerprint, agreement, counter advance.
        new_st = SparseState(
            nbr_idx=nbr_idx,
            nbr_state=nbr_state,
            nbr_timer=nbr_timer,
            alive=alive,
            identity=st.identity,
            tick=t32 + 1,
            seed=seed,
            cursor=cur + jnp.uint32(1),
        )
        fp = sparse_fingerprint(new_st)
        converged, fp_min, fp_max, n_alive = fingerprint_agreement(alive, fp)
        agree = jnp.sum(alive & (fp == fp_min), dtype=jnp.int32)
        occf = nbr_state > 0
        mem = jnp.int32(1) + jnp.sum(occf, axis=1, dtype=jnp.int32)
        denom = jnp.maximum(n_alive, 1)
        metrics = SparseTickMetrics(
            messages_delivered=exch_msgs + chain_msgs,
            converged=converged,
            agree_fraction=agree.astype(jnp.float32) / denom,
            mean_membership=jnp.sum(jnp.where(alive, mem, 0).astype(jnp.float32))
            / denom,
            fingerprint_min=fp_min,
            fingerprint_max=fp_max,
            pings_sent=jnp.sum(has_ping, dtype=jnp.int32),
            block_fill=jnp.sum(
                jnp.where(alive[:, None], occf, False), dtype=jnp.float32
            )
            / (denom.astype(jnp.float32) * k),
        )
        return new_st, metrics

    return tick
