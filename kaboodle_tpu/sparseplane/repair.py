"""Bounded per-tick neighbor-block repair.

Membership churn in a blocked world is a block *edit* problem: gossip
shares and ping sender-marks produce at most ``C = gossip_fanout + 1``
insert candidates per row per tick, and dead peers free slots when the
suspicion pass expires them.  This pass folds the candidate list into the
block with static shapes only — candidate validation, in-block membership
test, intra-list dedup, and rank-matched placement into empty slots are all
fixed-``[N, C, K]`` tensor ops, so the steady tick stays a single compiled
program (``compiles_steady=0``) no matter how violent the churn.

Overflow policy: candidates beyond the free slots of a row are dropped on
the floor.  SWIM re-offers membership continuously (every ack piggybacks a
fresh share), so a dropped insert is retried by the protocol itself within
a few ticks — bounding work per tick costs convergence latency, never
correctness.  The stat-pin harness runs with ``K >= N - 1`` where no drop
can occur, which is what makes the dense oracle comparison meaningful.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from kaboodle_tpu.spec import KNOWN


def repair_blocks(  # graftlint: traced
    nbr_idx: jax.Array,
    nbr_state: jax.Array,
    nbr_timer: jax.Array,
    cand: jax.Array,
    cand_stamp: jax.Array,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Insert up to ``C`` candidates per row into that row's empty slots.

    ``cand`` is int32 ``[N, C]`` (``-1`` = no candidate), ``cand_stamp`` the
    matching timer stamps in the block's timer dtype (gossip shares arrive
    backdated per ``cfg.backdate_gossip_inserts``; ping sender-marks arrive
    at ``now``).  Earlier columns win dedup ties — callers order candidates
    by provenance priority.  Returns the edited ``(idx, state, timer)``.
    """
    n, k = nbr_idx.shape
    c = cand.shape[1]
    rows = jnp.arange(n, dtype=jnp.int32)

    occ = nbr_state > 0
    valid = (cand >= 0) & (cand != rows[:, None])

    # Already in the block?  [N, C, K] membership test against occupied slots.
    in_block = jnp.any(
        (cand[:, :, None] == nbr_idx[:, None, :]) & occ[:, None, :], axis=-1
    )
    valid &= ~in_block

    # Intra-list dedup: a candidate loses to any identical valid candidate in
    # an earlier column.  C is tiny (gossip_fanout + 1) so the static C^2/2
    # compare loop beats a sort.
    for j in range(1, c):
        dup = jnp.zeros((n,), bool)
        for i in range(j):
            dup |= valid[:, i] & (cand[:, j] == cand[:, i])
        valid = valid.at[:, j].set(valid[:, j] & ~dup)

    # Rank-match placement: the r-th surviving candidate of a row fills the
    # r-th empty slot of that row.  One-hot [N, C, K] product collapses to
    # per-slot fills via a masked sum — conflict-free by construction since
    # ranks are unique within a row.
    cand_rank = jnp.cumsum(valid.astype(jnp.int32), axis=1) - 1  # [N, C]
    empty = ~occ
    slot_rank = jnp.cumsum(empty.astype(jnp.int32), axis=1) - 1  # [N, K]
    place = (
        valid[:, :, None]
        & empty[:, None, :]
        & (cand_rank[:, :, None] == slot_rank[:, None, :])
    )  # [N, C, K]

    filled = jnp.any(place, axis=1)  # [N, K]
    new_idx = jnp.sum(
        jnp.where(place, cand[:, :, None], 0), axis=1, dtype=jnp.int32
    )
    new_stamp = jnp.sum(
        jnp.where(place, cand_stamp[:, :, None], 0),
        axis=1,
        dtype=nbr_timer.dtype,
    )

    nbr_idx = jnp.where(filled, new_idx, nbr_idx)
    nbr_state = jnp.where(filled, jnp.int8(KNOWN), nbr_state)
    nbr_timer = jnp.where(filled, new_stamp, nbr_timer)
    return nbr_idx, nbr_state, nbr_timer


def reseed_revived(  # graftlint: traced
    nbr_idx: jax.Array,
    nbr_state: jax.Array,
    nbr_timer: jax.Array,
    revived: jax.Array,
    boot_contacts: int,
    now_t: jax.Array,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Reset revived rows to fresh ring boot contacts.

    The dense engines re-knit a revived peer via the join broadcast; a
    blocked world has no broadcast domain, so revival re-enters through the
    same gossip boot used at init: clear the block, seed ``boot_contacts``
    ring neighbors at ``now``, and let ack piggybacking rebuild the view.
    """
    n, k = nbr_idx.shape
    b = min(boot_contacts, n - 1, k)
    rows = jnp.arange(n, dtype=jnp.int32)
    slots = jnp.arange(k, dtype=jnp.int32)
    boot_col = slots[None, :] < b  # [1, K] static mask
    ring = (rows[:, None] + 1 + slots[None, :]) % n

    m = revived[:, None]
    nbr_idx = jnp.where(m, jnp.where(boot_col, ring.astype(jnp.int32), -1), nbr_idx)
    nbr_state = jnp.where(
        m, jnp.where(boot_col, jnp.int8(KNOWN), jnp.int8(0)), nbr_state
    )
    nbr_timer = jnp.where(
        m, jnp.where(boot_col, now_t, jnp.zeros((), nbr_timer.dtype)), nbr_timer
    )
    return nbr_idx, nbr_state, nbr_timer
