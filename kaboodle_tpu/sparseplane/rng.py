"""Counter-based threefry streams for the blocked-sparse tick.

The dense engines carry a threefry key through the state and split it each
tick; the blocked layout instead derives every draw on the fly from the
``(seed, cursor)`` counter pair stored in ``SparseState``:

    key(stream) = fold_in(fold_in(PRNGKey(seed), cursor), stream)

and then takes a *shaped* uniform from that key, so the element position
inside the draw supplies the remaining counter words — a ``(N, K)`` draw is
effectively keyed ``(seed, tick, stream, row, slot)``.  Nothing ``[N, N]``
is ever materialized, draws are reproducible from the checkpointable
``cursor`` alone, and distinct ``STREAM_*`` ids keep the per-phase draws
independent (no key reuse across phases — the same discipline KB204
enforces on the dense engines).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

# One id per randomized phase of the sparse tick, in tick order.  New phases
# append — renumbering changes every draw of every banked run.
STREAM_PROXY = 0  # proxy slot picks for ping-req fan-out
STREAM_CHAIN = 1  # the four delivery legs of each indirect-ping chain
STREAM_DRAW = 2  # ping target pick among the oldest-k Known slots
STREAM_PING = 3  # direct ping delivery bernoulli
STREAM_ACK = 4  # ack delivery bernoulli
STREAM_GOSSIP = 5  # piggyback share slot picks


def stream_table() -> dict[str, int]:
    """Live ``{name: id}`` view of every ``STREAM_*`` constant, in id order.

    Read off the module's attributes at call time (not a frozen copy), so
    keyscope's double-entry check (analysis/rng/rules.py
    ``KEYSCOPE_STREAMS``) sees exactly what the kernel will fold in —
    including any renumbering a bad edit (or a mutation test) introduces."""
    import sys

    mod = sys.modules[__name__]
    table = {
        name: getattr(mod, name)
        for name in dir(mod)
        if name.startswith("STREAM_") and isinstance(getattr(mod, name), int)
    }
    return dict(sorted(table.items(), key=lambda kv: (kv[1], kv[0])))


def stream_key(seed: jax.Array, cursor: jax.Array, stream: int) -> jax.Array:
    """Threefry key for one phase of one tick — pure function of the counters."""
    base = jax.random.fold_in(jax.random.PRNGKey(seed), cursor)
    return jax.random.fold_in(base, jnp.uint32(stream))


def stream_uniform(
    seed: jax.Array, cursor: jax.Array, stream: int, shape: tuple[int, ...]
) -> jax.Array:
    """Shaped float32 uniform in [0, 1) for one phase (position = row/slot)."""
    # f32 pinned: draw values feed thresholds and floor(u * count) index
    # math where f64 would shift pick boundaries (same pin as ops/sampling).
    return jax.random.uniform(
        stream_key(seed, cursor, stream), shape, dtype=jnp.float32
    )
