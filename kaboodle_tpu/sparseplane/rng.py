"""Counter-based threefry streams for the blocked-sparse tick (re-export).

Warp 3.0 promoted this module's ``(seed, cursor, stream)`` scheme into the
shared :mod:`kaboodle_tpu.phasegraph.rng` counter-RNG module so the dense
engines could adopt the same discipline (per-``(key, tick, stream)`` keys
instead of the split-chain fork).  The canonical stream table and key
derivations live there now; this module re-exports the sparse-facing names
so kernel code and call sites keep their historical import path.  Mutation
tests and the KB602 double-entry register target the canonical module —
patch/edit ``phasegraph/rng.py``, not this shim.
"""

from __future__ import annotations

from kaboodle_tpu.phasegraph.rng import (  # noqa: F401
    STREAM_ACK,
    STREAM_CHAIN,
    STREAM_DRAW,
    STREAM_GOSSIP,
    STREAM_PING,
    STREAM_PROXY,
    STREAM_TICK_BERN,
    STREAM_TICK_DROP,
    STREAM_TICK_PING,
    STREAM_TICK_PROXY,
    stream_key,
    stream_table,
    stream_uniform,
)

__all__ = [
    "STREAM_PROXY",
    "STREAM_CHAIN",
    "STREAM_DRAW",
    "STREAM_PING",
    "STREAM_ACK",
    "STREAM_GOSSIP",
    "STREAM_TICK_PROXY",
    "STREAM_TICK_PING",
    "STREAM_TICK_BERN",
    "STREAM_TICK_DROP",
    "stream_key",
    "stream_table",
    "stream_uniform",
]
