"""Scan and while-loop drivers for the blocked-sparse tick.

Shapes mirror ``sim/runner.py``: ``simulate_sparse`` scans a scenario with a
leading ticks axis; ``run_sparse_until_converged`` drives a fault-free mesh
to fingerprint agreement under a while_loop (only meaningful when the block
width can hold the full view, ``k >= n - 1`` — the stat-pin configuration).
Both are jitted with cfg/spec static, so a warmed call re-dispatches with
zero compiles — the ``compiles_steady=0`` surface the KB405 exercise and
the fuzz harness pin.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from kaboodle_tpu.config import SwimConfig
from kaboodle_tpu.ops.hashing import fingerprint_agreement
from kaboodle_tpu.sparseplane.kernel import make_sparse_tick_fn
from kaboodle_tpu.sparseplane.state import (
    SparseSpec,
    SparseState,
    SparseTickInputs,
    sparse_fingerprint,
    sparse_idle_inputs,
)


def sparse_converged(state: SparseState) -> jax.Array:
    """Alive rows agree on one membership fingerprint (scalar bool)."""
    converged, _, _, _ = fingerprint_agreement(
        state.alive, sparse_fingerprint(state)
    )
    return converged


@functools.partial(jax.jit, static_argnames=("cfg", "spec", "faulty"))
def simulate_sparse(  # graftlint: traced
    state: SparseState,
    inputs: SparseTickInputs,
    cfg: SwimConfig,
    spec: SparseSpec,
    faulty: bool = True,
):
    """Scan the sparse tick over a scenario with a leading ticks axis."""
    tick = make_sparse_tick_fn(cfg, spec, faulty)
    return jax.lax.scan(tick, state, inputs)


@functools.partial(jax.jit, static_argnames=("cfg", "spec", "max_ticks"))
def run_sparse_until_converged(  # graftlint: traced
    state: SparseState, cfg: SwimConfig, spec: SparseSpec, max_ticks: int
):
    """Idle-tick a fault-free mesh until fingerprint agreement.

    Returns ``(state, ticks_run, converged)`` like ``sim.runner
    .run_until_converged``; a mesh converged at entry runs zero ticks.
    """
    tick = make_sparse_tick_fn(cfg, spec, faulty=False)
    idle = sparse_idle_inputs(state.n)

    def cond(carry):
        st, ticks = carry
        return (~sparse_converged(st)) & (ticks < max_ticks)

    def body(carry):
        st, ticks = carry
        st2, _ = tick(st, idle)
        return st2, ticks + jnp.int32(1)

    st, ticks = jax.lax.while_loop(
        cond, body, (state, jnp.zeros((), jnp.int32))
    )
    return st, ticks, sparse_converged(st)
