"""Blocked-sparse mesh state: ``[N, K]`` neighbor blocks instead of ``[N, N]``.

Layout contract (the ``blocked_topk`` plane layout declared in
``phasegraph/ops.py``):

- ``nbr_idx``   int32 ``[N, K]`` — peer id per slot, ``-1`` for an empty slot.
- ``nbr_state`` int8  ``[N, K]`` — spec state code per slot (``0`` = empty,
  otherwise the same codes the dense ``state`` plane uses: Known /
  WaitingForPing / WaitingForIndirectPing).
- ``nbr_timer`` int32 or int16 ``[N, K]`` — last-heard tick per slot, the
  blocked twin of the dense ``timer`` plane (same lean-int16 option).
- ``seed`` / ``cursor`` uint32 scalars — the counter-RNG plane replacing the
  dense threefry ``key``: every draw is re-derived from
  ``fold_in(fold_in(PRNGKey(seed), cursor), stream)`` and the element
  position inside the shaped draw encodes ``(row, slot)``, so randomness is
  keyed ``(seed, tick, row, slot)`` without materializing ``[N, N]``.

Row ``i``'s membership view is ``{i} ∪ occupied slots`` — self is implicit,
mirroring the dense diagonal.  The fingerprint of a row is therefore the
same commutative ``peer_record_hash`` sum the dense plane computes, and
``fingerprint_agreement`` is shared verbatim with the dense engines.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from kaboodle_tpu.ops.hashing import peer_record_hash
from kaboodle_tpu.spec import KNOWN

_TIMER_DTYPES = ("int32", "int16")


@dataclasses.dataclass(frozen=True)
class SparseSpec:
    """Static knobs of a blocked-sparse mesh (hashable: usable as a jit static).

    ``k`` is the block width (pow2 so block shapes tile cleanly on TPU lanes
    and costscope's N-sweeps stay comparable), ``gossip_fanout`` the number
    of membership records piggybacked on each ack (the blocked twin of the
    dense anti-entropy share), ``boot_contacts`` the ring contacts seeded at
    init/revive (the gossip-boot analogue of the dense join broadcast, which
    has no domain in a blocked world).
    """

    k: int = 16
    gossip_fanout: int = 4
    boot_contacts: int = 3
    timer_dtype: str = "int32"

    def __post_init__(self) -> None:
        if self.k < 2 or (self.k & (self.k - 1)) != 0:
            raise ValueError(f"k must be a power of two >= 2, got {self.k}")
        if not 1 <= self.gossip_fanout <= self.k:
            raise ValueError(
                f"gossip_fanout must be in [1, k={self.k}], got {self.gossip_fanout}"
            )
        if not 1 <= self.boot_contacts <= self.k:
            raise ValueError(
                f"boot_contacts must be in [1, k={self.k}], got {self.boot_contacts}"
            )
        if self.timer_dtype not in _TIMER_DTYPES:
            raise ValueError(
                f"timer_dtype must be one of {_TIMER_DTYPES}, got {self.timer_dtype!r}"
            )

    @property
    def timer_jnp_dtype(self):
        return jnp.int16 if self.timer_dtype == "int16" else jnp.int32


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class SparseState:
    """Pytree of the blocked-sparse planes (see module docstring)."""

    nbr_idx: jax.Array  # int32 [N, K]
    nbr_state: jax.Array  # int8 [N, K]
    nbr_timer: jax.Array  # int32|int16 [N, K]
    alive: jax.Array  # bool [N]
    identity: jax.Array  # uint32 [N]
    tick: jax.Array  # int32 scalar
    seed: jax.Array  # uint32 scalar (counter-RNG base)
    cursor: jax.Array  # uint32 scalar (counter-RNG cursor, +1 per tick)

    @property
    def n(self) -> int:
        return self.nbr_idx.shape[0]

    @property
    def k(self) -> int:
        return self.nbr_idx.shape[1]


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class SparseTickInputs:
    """Per-tick scenario inputs — the blocked twin of ``TickInputs``.

    No ``partition``/``drop_ok`` matrices: edge faults are counter-draw
    bernoullis against the scalar ``drop_rate``, never a materialized gate.
    """

    kill: jax.Array  # bool [N]
    revive: jax.Array  # bool [N]
    drop_rate: jax.Array  # float32 scalar


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class SparseTickMetrics:
    """Per-tick metrics, field-compatible with the dense ``TickMetrics``."""

    messages_delivered: jax.Array  # int32
    converged: jax.Array  # bool
    agree_fraction: jax.Array  # float32
    mean_membership: jax.Array  # float32
    fingerprint_min: jax.Array  # uint32
    fingerprint_max: jax.Array  # uint32
    pings_sent: jax.Array  # int32
    block_fill: jax.Array  # float32 — mean occupied fraction over alive rows


def init_sparse_state(
    n: int,
    spec: SparseSpec,
    seed: int = 0,
    identities: jax.Array | None = None,
    alive: jax.Array | None = None,
    contacts: int | None = None,
) -> SparseState:
    """Fresh blocked mesh with ``contacts`` ring neighbors seeded per row.

    ``contacts`` defaults to ``spec.boot_contacts``; pass ``n - 1`` (with
    ``k >= n - 1``) for a full-view boot, the configuration the stat-pin
    harness uses so the blocked fingerprint can reach exact agreement with
    the dense oracle.
    """
    if n < 2:
        raise ValueError(f"need at least 2 peers, got n={n}")
    b = spec.boot_contacts if contacts is None else contacts
    b = min(b, n - 1, spec.k)
    if b < 1:
        raise ValueError(f"contacts resolves to {b}; need at least 1")
    tdt = spec.timer_jnp_dtype

    rows = np.arange(n, dtype=np.int64)
    slots = np.arange(spec.k, dtype=np.int64)
    # Ring contacts i+1 .. i+b, the same seeding init_state uses for its
    # dense `ring_contacts` — self-reference impossible since b <= n - 1.
    idx = np.where(
        slots[None, :] < b,
        (rows[:, None] + 1 + slots[None, :]) % n,
        -1,
    ).astype(np.int32)
    st = np.broadcast_to(
        np.where(slots[None, :] < b, KNOWN, 0).astype(np.int8), (n, spec.k)
    ).copy()

    if identities is None:
        identities = jnp.zeros((n,), jnp.uint32)
    if alive is None:
        alive = jnp.ones((n,), bool)
    return SparseState(
        nbr_idx=jnp.asarray(idx),
        nbr_state=jnp.asarray(st),
        nbr_timer=jnp.zeros((n, spec.k), tdt),
        alive=alive,
        identity=identities,
        tick=jnp.zeros((), jnp.int32),
        seed=jnp.uint32(seed),
        cursor=jnp.zeros((), jnp.uint32),
    )


def sparse_idle_inputs(n: int, ticks: int | None = None) -> SparseTickInputs:
    """No churn, no drops — leading ``ticks`` axis when scanning."""
    shape = (n,) if ticks is None else (ticks, n)
    zeros = jnp.zeros(shape, bool)
    drop = jnp.zeros(() if ticks is None else (ticks,), jnp.float32)
    return SparseTickInputs(kill=zeros, revive=zeros, drop_rate=drop)


def sparse_fingerprint(st: SparseState) -> jax.Array:
    """Per-row membership fingerprint, uint32 ``[N]``.

    Commutative sum of ``peer_record_hash`` over the implicit self plus every
    occupied slot — identical to ``membership_fingerprint`` of the equivalent
    dense membership matrix, so dense and blocked views of the same world
    hash equal and ``fingerprint_agreement`` applies unchanged.
    """
    n = st.n
    rows = jnp.arange(n, dtype=jnp.int32)
    occ = st.nbr_state > 0
    safe = jnp.clip(st.nbr_idx, 0, n - 1)
    self_h = peer_record_hash(rows, st.identity)
    slot_h = peer_record_hash(safe, st.identity[safe])
    return self_h + jnp.sum(
        jnp.where(occ, slot_h, jnp.uint32(0)), axis=1, dtype=jnp.uint32
    )
