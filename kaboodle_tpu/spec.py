"""The SWIM state machine as data: state codes and message kinds.

This is the shared vocabulary between the three engines:
- the NumPy/Python oracle (``kaboodle_tpu.oracle``) — readable, O(N) loops;
- the JAX tick kernel (``kaboodle_tpu.sim``) — vectorized, ``[N, N]`` tensors;
- the real-network engine over UDP (``kaboodle_tpu.transport``).

Reference mapping (src/structs.rs):
- ``PeerState::{Known, WaitingForPing, WaitingForIndirectPing}`` (structs.rs:27-41)
  each carry an ``Instant``; here the state code and the tick-stamp are stored
  separately (``state`` int8 + ``timer`` int32 in the simulator). A fourth code,
  NOT_MEMBER, encodes absence from the membership map.
- Unicast messages ``SwimMessage::{Ping, PingRequest, Ack, KnownPeers,
  KnownPeersRequest}`` (structs.rs:92-116).
- Broadcasts ``SwimBroadcast::{Join, Failed, Probe}`` (structs.rs:64-73).
"""

from __future__ import annotations

import enum

# Peer-state codes for the `state[N, N]` tensor: state[i, j] is what peer i
# believes about peer j. NOT_MEMBER means j is absent from i's membership map.
NOT_MEMBER = 0
KNOWN = 1  # PeerState::Known(last_heard)           structs.rs:31
WAITING_FOR_PING = 2  # PeerState::WaitingForPing(sent_at)   structs.rs:35
WAITING_FOR_INDIRECT_PING = 3  # PeerState::WaitingForIndirectPing    structs.rs:40

STATE_NAMES = {
    NOT_MEMBER: "NotMember",
    KNOWN: "Known",
    WAITING_FOR_PING: "WaitingForPing",
    WAITING_FOR_INDIRECT_PING: "WaitingForIndirectPing",
}


class UnicastKind(enum.IntEnum):
    """SwimMessage variants, in declaration order (structs.rs:94-115).

    The enum ordinal doubles as the bincode variant index for the wire codec.
    """

    PING = 0
    PING_REQUEST = 1
    ACK = 2
    KNOWN_PEERS = 3
    KNOWN_PEERS_REQUEST = 4


class BroadcastKind(enum.IntEnum):
    """SwimBroadcast variants, in declaration order (structs.rs:65-73)."""

    JOIN = 0
    FAILED = 1
    PROBE = 2
