"""On-device telemetry plane: protocol counters, flight recorder, exporters.

Three layers, from the device out (SURVEY.md §5 grown into a subsystem):

- **Counters** (:mod:`telemetry.counters`): every tick engine — the dense
  kernel, the chunked twin, the warp leap, the vmapped fleet — can emit a
  :class:`ProtocolCounters` pytree of per-tick protocol reductions (pings /
  acks / ping-reqs sent, suspicions raised and refuted, deaths declared,
  joins disseminated, modeled gossip bytes, armed timers) as *pure derived
  values*: the state trajectory is bit-identical with telemetry on or off,
  and the lockstep oracle counts the same events so the randomized
  cross-engine fuzz pins counter parity exactly (tests/test_fuzz_parity.py).
- **Flight recorder** (:mod:`telemetry.recorder`): a fixed-shape on-device
  ring buffer carried through scans and while_loops holding the last K
  ticks of counters + per-member fingerprint digests — dumpable on
  convergence or divergence without rerunning, no host callbacks (the
  graftscan KB402 gate stays clean), no fresh compiles after warmup (the
  KB405 zero-recompile fuzz arm covers a telemetry-enabled run).
- **Export** (:mod:`telemetry.manifest` / :mod:`telemetry.trace` /
  :mod:`telemetry.summary`): one JSONL run-manifest schema shared by
  bench.py, the fleet sweep CLI, and the warp A/B; a Chrome-trace /
  Perfetto JSON exporter over per-tick telemetry; and the
  ``python -m kaboodle_tpu telemetry`` summarizer. Surfaced via
  ``--telemetry [PATH]`` on the sim / fleet / warp CLI paths.
"""

from kaboodle_tpu.telemetry.counters import (
    RECORD_BYTES,
    ProtocolCounters,
    TickTelemetry,
    add_counters,
    counters_table,
    counters_totals,
    leap_counters,
    scale_counters,
    zero_counters,
)
from kaboodle_tpu.telemetry.manifest import (
    MANIFEST_SCHEMA,
    ManifestWriter,
    read_manifest,
    run_record,
    validate_record,
)
from kaboodle_tpu.telemetry.recorder import (
    FlightRecorder,
    init_recorder,
    record_tick,
    recorder_rows,
)
from kaboodle_tpu.telemetry.trace import chrome_trace_events, write_chrome_trace

__all__ = [
    "RECORD_BYTES",
    "ProtocolCounters",
    "TickTelemetry",
    "add_counters",
    "counters_table",
    "counters_totals",
    "leap_counters",
    "scale_counters",
    "zero_counters",
    "MANIFEST_SCHEMA",
    "ManifestWriter",
    "read_manifest",
    "run_record",
    "validate_record",
    "FlightRecorder",
    "init_recorder",
    "record_tick",
    "recorder_rows",
    "chrome_trace_events",
    "write_chrome_trace",
]
