"""ProtocolCounters: per-tick protocol event reductions, engine-agnostic.

Every counter is a *pure derived value* of one tick's delivery masks and
pre/post states — no engine mutates state to count, so a telemetry-on tick
is bit-identical to a telemetry-off tick in everything but its outputs, and
the lockstep oracle (oracle/lockstep.py) can tally the same events from its
message lists for exact cross-engine parity (tests/test_fuzz_parity.py).

Counter definitions (the contract every engine implements; "sent" means the
datagram entered the transport, post the D8 validity filter — delivery may
still drop it; "delivered" masks gate replies exactly as the protocol does):

- ``pings_sent``      random A3 pings + valid manual pings + proxy pings
                      dispatched on a *delivered* PingRequest.
- ``acks_sent``       acks dispatched on a delivered ping (direct, manual,
                      proxy->suspect) + forwarded acks (call-3 coincidence
                      pops and call-4 relays).
- ``ping_reqs_sent``  PingRequests dispatched by escalating suspectors.
- ``suspicions_raised``   rows escalating WaitingForPing ->
                      WaitingForIndirectPing this tick (D1: <= 1 per row).
- ``suspicions_refuted``  cells WaitingForIndirectPing at tick start and
                      Known at tick end (a datagram or gossip resurrected
                      the suspect). Defined on pre/post snapshots, so an
                      in-tick raise-and-refute is not counted — the
                      definition is a pure function of the states the
                      parity pins already compare.
- ``deaths_declared`` cells removed by phase A2 (WaitingForIndirectPing
                      timeouts + no-proxy insta-removals).
- ``joins_disseminated``  Join broadcast deliveries (origin != receiver).
- ``gossip_bytes``    modeled bytes of membership records gossiped:
                      ``RECORD_BYTES`` x (records in KnownPeersRequest
                      replies sent + records in join-response shares sent,
                      the D5-capped share model). uint32, wraps modulo 2^32
                      on pathological uncapped join storms (documented).
- ``armed_timers``    waiting-state cells in alive rows at tick end — the
                      quantity warp's quiescence predicate requires to be
                      zero (warp/horizon.py).
"""

from __future__ import annotations

import dataclasses
from typing import TYPE_CHECKING

import jax
import jax.numpy as jnp
import numpy as np

if TYPE_CHECKING:  # annotation-only: sim.kernel imports this module, so a
    # runtime import of sim.state here would be circular whenever the
    # telemetry package is imported before the sim package.
    from kaboodle_tpu.sim.state import TickMetrics

# Modeled wire size of one gossiped membership record: u32 address word +
# u32 identity word (the simulator's (addr, identity) pair; the reference
# serializes SocketAddr + identity bytes — transport/codec.py — so real
# payloads are larger; this models the O(records) growth, not framing).
RECORD_BYTES = 8


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class ProtocolCounters:
    """One tick's protocol event counts (module docstring for definitions).

    All int32 scalars except ``gossip_bytes`` (uint32, modular). Under the
    fleet vmap every leaf carries the leading ``[E]`` axis; stacked by a
    scan they carry ``[T]``.
    """

    pings_sent: jax.Array  # int32 []
    acks_sent: jax.Array  # int32 []
    ping_reqs_sent: jax.Array  # int32 []
    suspicions_raised: jax.Array  # int32 []
    suspicions_refuted: jax.Array  # int32 []
    deaths_declared: jax.Array  # int32 []
    joins_disseminated: jax.Array  # int32 []
    gossip_bytes: jax.Array  # uint32 [] (RECORD_BYTES x records, modular)
    armed_timers: jax.Array  # int32 []


FIELDS = tuple(f.name for f in dataclasses.fields(ProtocolCounters))


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TickTelemetry:
    """Telemetry-mode tick output: metrics + counters + per-member digests.

    ``fp`` is the end-of-tick per-member membership fingerprint vector
    (uint32 ``[N]``) — the flight recorder's digest plane; the state
    trajectory itself is unchanged by telemetry mode.
    """

    metrics: TickMetrics
    counters: ProtocolCounters
    fp: jax.Array  # uint32 [N]


def zero_counters() -> ProtocolCounters:
    """All-zero counters (the leaped-span identity / accumulator seed)."""
    z = jnp.zeros((), jnp.int32)
    return ProtocolCounters(
        pings_sent=z,
        acks_sent=z,
        ping_reqs_sent=z,
        suspicions_raised=z,
        suspicions_refuted=z,
        deaths_declared=z,
        joins_disseminated=z,
        gossip_bytes=jnp.zeros((), jnp.uint32),
        armed_timers=z,
    )


def add_counters(a: ProtocolCounters, b: ProtocolCounters) -> ProtocolCounters:
    """Leafwise sum — run totals accumulate exactly (uint32 wraps modular)."""
    return jax.tree.map(lambda x, y: x + y, a, b)


def scale_counters(c: ProtocolCounters, k) -> ProtocolCounters:
    """``k`` identical ticks' worth of ``c`` (int multiply per leaf)."""
    return jax.tree.map(lambda x: x * jnp.asarray(k).astype(x.dtype), c)


def leap_counters(n_alive, k) -> ProtocolCounters:
    """Counters of ``k`` quiescent leaped ticks, in closed form.

    Inside a warp span (warp/horizon.py quiescence predicate) each tick's
    surviving protocol traffic is exactly: every alive row pings (membership
    == alive set and ``n_alive >= 2``, so every alive row has candidates),
    every ping is delivered and acked within the tick (fault-free, both
    endpoints alive), anti-entropy never fires (fingerprints agree), and no
    timer survives the tick. So per tick: ``pings_sent == acks_sent ==
    n_alive`` and every other counter is zero — bit-equal to what the dense
    kernel emits on those ticks (the warp arm of the counter-parity fuzz
    pins this).
    """
    per_tick = dataclasses.replace(
        zero_counters(),
        pings_sent=jnp.asarray(n_alive, jnp.int32),
        acks_sent=jnp.asarray(n_alive, jnp.int32),
    )
    return scale_counters(per_tick, jnp.asarray(k, jnp.int32))


def counters_table(counters: ProtocolCounters) -> np.ndarray:
    """Stacked ``[T]`` counters -> structured NumPy table, one row per tick."""
    first = np.atleast_1d(np.asarray(counters.pings_sent))
    out = np.zeros(
        first.shape[0],
        dtype=[("tick", np.int32)]
        + [
            (name, np.uint32 if name == "gossip_bytes" else np.int32)
            for name in FIELDS
        ],
    )
    out["tick"] = np.arange(first.shape[0])
    for name in FIELDS:
        out[name] = np.atleast_1d(np.asarray(getattr(counters, name)))
    return out


def counters_totals(counters: ProtocolCounters) -> dict:
    """Host-side run totals of stacked counters, as Python ints.

    ``armed_timers`` is a gauge, so its total is the tick-integrated value
    (area under the curve); every other field is a plain event count —
    except ``gossip_bytes``, whose total wraps modulo 2^32 exactly like
    the on-device uint32 accumulator (``add_counters`` in a while_loop
    carry), so the two totals APIs can never disagree at any run length.
    """
    out = {
        name: int(np.asarray(getattr(counters, name), dtype=np.int64).sum())
        for name in FIELDS
    }
    out["gossip_bytes"] = int(
        np.asarray(counters.gossip_bytes, dtype=np.uint64).sum() % (1 << 32)
    )
    return out
