"""JSONL run manifests: ONE schema for every lane's machine output.

Before this module, three drivers each formatted their own JSON: bench.py
(``BENCHDOC`` lines + BENCH_last_full.json), the fleet sweep CLI (a compact
tail line), and the warp A/B (a third shape). A manifest is the superset
they all need — a stream of schema-tagged records:

    {"schema": "kaboodle-telemetry/1", "kind": "run",  ...lane fields...}
    {"schema": "kaboodle-telemetry/1", "kind": "tick", "tick": 0, ...}
    {"schema": "kaboodle-telemetry/1", "kind": "recorder", ...}

``kind`` values are open (lanes add their own), but every record carries
the schema tag and every ``tick`` record carries a ``tick`` index, so the
summarizer (``python -m kaboodle_tpu telemetry``) and the Chrome-trace
exporter (telemetry/trace.py) can consume any lane's manifest. Writers are
stdlib-only and host-side — nothing here touches a traced function.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Iterator

import numpy as np

MANIFEST_SCHEMA = "kaboodle-telemetry/1"


def _jsonable(v):
    """NumPy / JAX scalars and arrays -> plain Python (json.dumps-safe)."""
    if isinstance(v, dict):
        return {k: _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    if isinstance(v, (str, bool, int, float)) or v is None:
        return v
    a = np.asarray(v)
    if a.ndim == 0:
        return a.item()
    return a.tolist()


def run_record(kind: str = "run", **fields) -> dict:
    """A schema-tagged manifest record (host values coerced to JSON types)."""
    rec = {"schema": MANIFEST_SCHEMA, "kind": kind}
    rec.update({k: _jsonable(v) for k, v in fields.items()})
    return rec


def validate_record(rec) -> dict:
    """Raise ``ValueError`` unless ``rec`` is a well-formed manifest record."""
    if not isinstance(rec, dict):
        raise ValueError(f"manifest record must be an object, got {type(rec).__name__}")
    if rec.get("schema") != MANIFEST_SCHEMA:
        raise ValueError(
            f"manifest record schema {rec.get('schema')!r} != {MANIFEST_SCHEMA!r}"
        )
    kind = rec.get("kind")
    if not isinstance(kind, str) or not kind:
        raise ValueError("manifest record needs a non-empty string 'kind'")
    if kind == "tick" and not isinstance(rec.get("tick"), int):
        raise ValueError("'tick' records need an integer 'tick' index")
    if kind == "serve_event":
        if not isinstance(rec.get("event"), str) or not rec.get("event"):
            raise ValueError(
                "'serve_event' records need a non-empty string 'event'"
            )
        if not isinstance(rec.get("lane"), int):
            raise ValueError("'serve_event' records need an integer 'lane'")
    if kind == "serve_round" and not isinstance(rec.get("round"), int):
        raise ValueError("'serve_round' records need an integer 'round'")
    if kind == "serve_span":
        if not isinstance(rec.get("span"), str) or not rec.get("span"):
            raise ValueError(
                "'serve_span' records need a non-empty string 'span'"
            )
        for field in ("t0_us", "dur_us", "request_id"):
            if not isinstance(rec.get(field), int):
                raise ValueError(
                    f"'serve_span' records need an integer {field!r}"
                )
    if kind == "warp_blocked":
        # Why-dense attribution rows (warp/runner.py WarpLedger): one per
        # blocking term combo, summed over the run's dense spans.
        if not isinstance(rec.get("term"), str) or not rec.get("term"):
            raise ValueError(
                "'warp_blocked' records need a non-empty string 'term'"
            )
        for field in ("ticks", "spans"):
            if not isinstance(rec.get(field), int):
                raise ValueError(
                    f"'warp_blocked' records need an integer {field!r}"
                )
    if kind == "costscope":
        # Static compiler-plane records (costscope/cli.py --manifest).
        if not isinstance(rec.get("entry"), str) or not rec.get("entry"):
            raise ValueError(
                "'costscope' records need a non-empty string 'entry'"
            )
    return rec


class ManifestWriter:
    """JSONL manifest writer (context manager).

    One record per line; every record is validated before it is written, so
    a manifest can never contain a line the summarizer would reject.

    Default mode TRUNCATES: a manifest names one run, and re-running a CLI
    lane with the same path must replace the old run, not silently merge
    two runs into doubled counter totals and duplicate tick records.
    ``append=True`` opts into accumulation for writers that deliberately
    build a multi-record stream across processes (bench.py ``--manifest``
    appends one ``run`` record per lane invocation).

    ``stream=True`` opts into line-buffered live mode: every record is
    flushed to the file the moment it is written, so a client tailing the
    manifest (the serve server's ``stream`` op, ``tail -f``) sees records
    at event time rather than at close. Batch writers keep the default
    block buffering — a bench run has no live readers.
    """

    def __init__(
        self, path: str, append: bool = False, stream: bool = False
    ) -> None:
        self.path = path
        self.stream = bool(stream)
        self._f = open(path, "a" if append else "w")
        self.records_written = 0

    def write(self, kind: str = "run", **fields) -> dict:
        return self.write_record(run_record(kind, **fields))

    def write_record(self, rec: dict) -> dict:  # conc: event-loop
        """Validate and write an ALREADY-BUILT record (the serve engine
        emits records through ``on_event`` fan-out; the server writes the
        same dict it hands to stream subscribers — i.e. this runs ON the
        event loop, which is why this file is in graftconc's CONC_SCOPE:
        the write/flush here must stay a buffered line append, never an
        fsync or a device fetch)."""
        rec = validate_record(rec)
        self._f.write(json.dumps(rec) + "\n")
        self.records_written += 1
        if self.stream:
            self._f.flush()
        return rec

    def flush(self) -> None:
        """Push buffered records to the file now (no-op cost in stream
        mode, where every write already flushed)."""
        self._f.flush()

    def write_tick_metrics(self, metrics, counters=None, ticks=None) -> int:
        """Stream stacked per-tick ``TickMetrics`` (and optionally stacked
        ``ProtocolCounters``) as ``tick`` records.

        ``ticks`` overrides the tick column (warped runs: the densely
        executed tick indices); default 0..T-1. Returns rows written.
        Zero-tick runs (already converged at entry) write nothing — the
        empty table is valid, not an error.
        """
        from kaboodle_tpu.profiling import tick_stats

        table = tick_stats(metrics)
        ctable = None
        if counters is not None:
            from kaboodle_tpu.telemetry.counters import counters_table

            ctable = counters_table(counters)
        for i, row in enumerate(table):
            fields = {name: row[name] for name in table.dtype.names}
            if ticks is not None:
                fields["tick"] = int(np.asarray(ticks)[i])
            if ctable is not None:
                fields.update(
                    {n: ctable[n][i] for n in ctable.dtype.names if n != "tick"}
                )
            fields["tick"] = int(fields["tick"])
            self.write("tick", **fields)
        return len(table)

    def write_recorder(self, rec) -> dict:
        """Dump a :class:`FlightRecorder` ring as one ``recorder`` record
        (table rows inline; the per-member fp plane as min/max/row digests,
        not the full [K, N] matrix — manifests stay O(K))."""
        from kaboodle_tpu.telemetry.recorder import recorder_rows

        rows = recorder_rows(rec)
        table = rows["table"]
        return self.write(
            "recorder",
            rows=[
                {name: _jsonable(r[name]) for name in table.dtype.names}
                for r in table
            ],
            fp_unique=[int(len(np.unique(f))) for f in rows["fp"]],
        )

    def close(self) -> None:
        self._f.close()

    def __enter__(self) -> "ManifestWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def read_manifest(path: str, validate: bool = True) -> Iterator[dict]:
    """Yield manifest records from a JSONL file (optionally validated)."""
    with open(path) as f:
        for lineno, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError as e:
                raise ValueError(f"{path}:{lineno}: not JSON: {e}") from None
            if validate:
                try:
                    validate_record(rec)
                except ValueError as e:
                    raise ValueError(f"{path}:{lineno}: {e}") from None
            yield rec


def dataclass_fields(obj) -> dict:
    """Flatten a (host-fetched) dataclass pytree into manifest fields."""
    return {
        f.name: _jsonable(getattr(obj, f.name)) for f in dataclasses.fields(obj)
    }
