"""Flight recorder: a fixed-shape on-device ring of the last K ticks.

The recorder rides the scan / while_loop *carry*: every tick writes one
slot (counters + convergence digest + the per-member fingerprint vector)
at ``head % K`` via ``dynamic_update_index_in_dim`` — fixed shapes, no
host callback (graftscan KB402 stays clean), no data-dependent control
flow, so a telemetry-enabled runner compiles once and recompiles never
(the KB405 zero-recompile fuzz arm runs one).

The payoff is post-mortem observability without rerunning: when a run
converges (or diverges, or a parity pin trips) the host dumps the ring
once — :func:`recorder_rows` — and gets the last K ticks' protocol
counters and per-member fingerprint digests in chronological order, the
exact data needed to see *why* the tail of the run looked the way it did.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np

from kaboodle_tpu.telemetry.counters import (
    FIELDS,
    ProtocolCounters,
    TickTelemetry,
    counters_table,
    zero_counters,
)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class FlightRecorder:
    """Ring of the last K recorded ticks (module docstring).

    ``head`` counts records ever written; slot ``head % K`` is written
    next. ``tick`` holds the simulated tick index per slot (-1 = empty).
    """

    tick: jax.Array  # int32 [K], -1 where never written
    converged: jax.Array  # bool [K]
    fp_min: jax.Array  # uint32 [K]
    fp_max: jax.Array  # uint32 [K]
    counters: ProtocolCounters  # leaves [K]
    fp: jax.Array  # uint32 [K, N] per-member fingerprint digests
    head: jax.Array  # int32 []

    @property
    def capacity(self) -> int:
        return self.tick.shape[0]


def init_recorder(k: int, n: int) -> FlightRecorder:
    """Empty K-slot recorder for an N-peer mesh (shapes are static)."""
    if k < 1:
        raise ValueError("need recorder capacity k >= 1")
    zc = jax.tree.map(lambda x: jnp.zeros((k,), x.dtype), zero_counters())
    return FlightRecorder(
        tick=jnp.full((k,), -1, dtype=jnp.int32),
        converged=jnp.zeros((k,), dtype=bool),
        fp_min=jnp.zeros((k,), dtype=jnp.uint32),
        fp_max=jnp.zeros((k,), dtype=jnp.uint32),
        counters=zc,
        fp=jnp.zeros((k, n), dtype=jnp.uint32),
        head=jnp.int32(0),
    )


def record_tick(
    rec: FlightRecorder, tick: jax.Array, out: TickTelemetry
) -> FlightRecorder:
    """Write one tick's telemetry into the ring (pure; jit/scan-safe)."""
    k = rec.capacity
    slot = jax.lax.rem(rec.head, jnp.int32(k))

    def put(buf, val):
        return jax.lax.dynamic_update_index_in_dim(
            buf, jnp.asarray(val, buf.dtype), slot, axis=0
        )

    return FlightRecorder(
        tick=put(rec.tick, jnp.asarray(tick, jnp.int32)),
        converged=put(rec.converged, out.metrics.converged),
        fp_min=put(rec.fp_min, out.metrics.fingerprint_min),
        fp_max=put(rec.fp_max, out.metrics.fingerprint_max),
        counters=jax.tree.map(put, rec.counters, out.counters),
        fp=jax.lax.dynamic_update_index_in_dim(rec.fp, out.fp, slot, axis=0),
        head=rec.head + 1,
    )


def recorder_rows(rec: FlightRecorder) -> dict:
    """ONE host fetch: the ring's valid slots in chronological order.

    Returns ``{"table": structured ndarray (tick, counters..., converged,
    fp_min, fp_max), "fp": uint32 [rows, N]}`` — oldest first, at most K
    rows (fewer when the run was shorter than the ring).
    """
    head = int(np.asarray(rec.head))
    k = rec.capacity
    rows = min(head, k)
    order = [(head - rows + i) % k for i in range(rows)]
    table = counters_table(
        jax.tree.map(lambda x: np.asarray(x)[order], rec.counters)
    )
    merged = np.zeros(
        rows,
        dtype=table.dtype.descr
        + [("converged", bool), ("fp_min", np.uint32), ("fp_max", np.uint32)],
    )
    for name in ("tick",) + FIELDS:
        merged[name] = table[name]
    merged["tick"] = np.asarray(rec.tick)[order]
    merged["converged"] = np.asarray(rec.converged)[order]
    merged["fp_min"] = np.asarray(rec.fp_min)[order]
    merged["fp_max"] = np.asarray(rec.fp_max)[order]
    return {"table": merged, "fp": np.asarray(rec.fp)[order]}
