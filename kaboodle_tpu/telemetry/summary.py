"""``python -m kaboodle_tpu telemetry`` — manifest summarizer / exporter.

Reads one or more JSONL run manifests (telemetry/manifest.py), validates
every record against the schema, and prints a human summary: records by
kind, the run records' headline fields, per-counter totals over the tick
records, and the convergence tail. Ends with the repo's usual compact
single-line JSON (machine consumers take the last line). ``--trace OUT``
additionally exports the tick records as a Chrome-trace/Perfetto JSON.

    python -m kaboodle_tpu telemetry run.jsonl
    python -m kaboodle_tpu telemetry run.jsonl --trace run.trace.json
    python -m kaboodle_tpu telemetry run.jsonl --check   # schema gate (CI)
    python -m kaboodle_tpu telemetry serve.jsonl --serve-report

Serve manifests (PR 14 ``serve_span`` records) get two extras: ``--trace``
renders per-lane request/leap/spill tracks on the wall-clock timeline
(``--journal DIR`` adds the WAL appends as a sibling track), and
``--serve-report`` prints a per-request waterfall plus a per-phase SLO
table (queue vs compute vs spill attribution).
"""

from __future__ import annotations

import argparse
import json
import sys

from kaboodle_tpu.telemetry.counters import FIELDS
from kaboodle_tpu.telemetry.manifest import read_manifest


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="kaboodle_tpu telemetry",
        description="summarize / export kaboodle telemetry run manifests",
    )
    p.add_argument("paths", nargs="+", metavar="MANIFEST.jsonl")
    p.add_argument("--trace", metavar="OUT.json", default=None,
                   help="export tick records as Chrome-trace/Perfetto JSON")
    p.add_argument("--phase-program", default="fused",
                   choices=("fused", "full", "span", "blocked", "off"),
                   help="with --trace: annotate each run track with per-pass "
                        "slices from the phase-graph plan of this mode "
                        "(default-config graph; 'off' disables the track)")
    p.add_argument("--check", action="store_true",
                   help="schema gate: exit nonzero unless every record "
                        "validates and at least one record exists")
    p.add_argument("--serve-report", action="store_true",
                   help="per-request waterfall + per-phase SLO table from "
                        "serve_span records")
    p.add_argument("--journal", metavar="DIR", default=None,
                   help="with --trace: add the serve WAL in DIR as a "
                        "journal-appends track (seq-ordered)")
    return p


def _phase_program(mode: str):
    """The planned phase-graph program whose passes annotate the trace.

    Built from the default deterministic config's op graph (plan/graph are
    pure metadata — no jax import, no tracing): pass membership and pruning
    are decided by the planner per mode, not per run, so the default build's
    plan is the right annotation for any run of that mode. ``span`` plans
    derive from the fault-free graph by definition (a quiescent span carries
    no scheduled events)."""
    from kaboodle_tpu.config import SwimConfig
    from kaboodle_tpu.phasegraph import build_graph, plan

    graph = build_graph(
        SwimConfig(deterministic=True), faulty=(mode != "span"), telemetry=True
    )
    return plan(graph, mode)


def _pct(sorted_us: list[int], q: float) -> int:
    """Exact sample quantile over a sorted list (host-side, small N)."""
    if not sorted_us:
        return 0
    return sorted_us[min(int(q * len(sorted_us)), len(sorted_us) - 1)]


def serve_report(records: list[dict]) -> dict:
    """Fold ``serve_span`` records into the waterfall + SLO structures.

    Returns ``{"requests": {rid: {"phases": [...], "total_us", "fate"}},
    "phases": {span: {count, total_us, p50/p90/p99/max_us}}, "e2e":
    {...}}`` — per-request phase sequences ordered by ``t0_us``, and the
    per-phase latency attribution the SLO table prints. End-to-end
    latency is first ``t0_us`` to last span end, so queue time counts."""
    by_rid: dict[int, list[dict]] = {}
    for rec in records:
        if rec.get("kind") != "serve_span":
            continue
        rid = int(rec["request_id"])
        if rid < 0:
            continue  # round / advance spans: engine-level, not a request
        by_rid.setdefault(rid, []).append(rec)
    requests: dict[int, dict] = {}
    phase_us: dict[str, list[int]] = {}
    e2e: list[int] = []
    for rid in sorted(by_rid):
        spans = sorted(by_rid[rid], key=lambda r: int(r["t0_us"]))
        phases = []
        fate = None
        for s in spans:
            phases.append({
                "span": s["span"], "t0_us": int(s["t0_us"]),
                "dur_us": int(s["dur_us"]), "pool_n": s.get("pool_n", -1),
                "lane": s.get("lane", -1),
            })
            if s.get("fate"):
                fate = s["fate"]
            if s.get("open"):
                fate = fate or "open"
            phase_us.setdefault(s["span"], []).append(int(s["dur_us"]))
        total = (int(spans[-1]["t0_us"]) + int(spans[-1]["dur_us"])
                 - int(spans[0]["t0_us"]))
        requests[rid] = {"phases": phases, "total_us": total,
                         "fate": fate or "done"}
        e2e.append(total)
    phases_out = {}
    for span, durs in sorted(phase_us.items()):
        durs.sort()
        phases_out[span] = {
            "count": len(durs), "total_us": sum(durs),
            "p50_us": _pct(durs, 0.50), "p90_us": _pct(durs, 0.90),
            "p99_us": _pct(durs, 0.99), "max_us": durs[-1],
        }
    e2e.sort()
    return {
        "requests": requests,
        "phases": phases_out,
        "e2e": {
            "count": len(e2e), "p50_us": _pct(e2e, 0.50),
            "p90_us": _pct(e2e, 0.90), "p99_us": _pct(e2e, 0.99),
            "max_us": e2e[-1] if e2e else 0,
        },
    }


_PHASE_GLYPH = {"queued": "q", "running": "R", "parked": "p",
                "spilling": "s", "spilled": "S"}


def print_serve_report(report: dict, width: int = 48, max_rows: int = 30
                       ) -> None:
    """Render the waterfall (one scaled bar per request) + SLO table."""
    reqs = report["requests"]
    if not reqs:
        print("  serve-report: no request spans")
        return
    t_lo = min(p["t0_us"] for r in reqs.values() for p in r["phases"])
    t_hi = max(p["t0_us"] + p["dur_us"]
               for r in reqs.values() for p in r["phases"])
    scale = max(t_hi - t_lo, 1)
    print(f"  serve-report: {len(reqs)} requests, "
          f"timeline {scale} us")
    for i, (rid, row) in enumerate(sorted(reqs.items())):
        if i >= max_rows:
            print(f"    ... {len(reqs) - max_rows} more requests")
            break
        bar = [" "] * width
        for p in row["phases"]:
            a = (p["t0_us"] - t_lo) * width // scale
            b = (p["t0_us"] + p["dur_us"] - t_lo) * width // scale
            g = _PHASE_GLYPH.get(p["span"], "?")
            for j in range(min(a, width - 1), min(max(b, a + 1), width)):
                bar[j] = g
        seq = ">".join(p["span"] for p in row["phases"])
        print(f"    r{rid:<4} |{''.join(bar)}| "
              f"{row['total_us']:>8} us  {row['fate']:<10} {seq}")
    print("    phase       count   p50_us   p90_us   p99_us   max_us")
    rows = dict(report["phases"])
    rows["e2e"] = report["e2e"]
    for span, st in rows.items():
        print(f"    {span:<10} {st['count']:>6} {st['p50_us']:>8} "
              f"{st['p90_us']:>8} {st['p99_us']:>8} {st['max_us']:>8}")


def load_manifests(paths: list[str]) -> dict[str, list[dict]]:
    """Read + validate every manifest ONCE: path -> its records."""
    return {path: list(read_manifest(path)) for path in paths}


def summarize(records: dict[str, list[dict]]) -> dict:
    """Aggregate loaded manifests into the summary dict the CLI prints."""
    kinds: dict[str, int] = {}
    runs: list[dict] = []
    ticks: list[dict] = []
    warp_spans: list[dict] = []
    warp_blocked: list[dict] = []
    serve_events: list[dict] = []
    serve_rounds: list[dict] = []
    for recs in records.values():
        for rec in recs:
            kinds[rec["kind"]] = kinds.get(rec["kind"], 0) + 1
            if rec["kind"] == "run":
                runs.append(rec)
            elif rec["kind"] == "tick":
                ticks.append(rec)
            elif rec["kind"] == "warp_spans":
                warp_spans.append(rec)
            elif rec["kind"] == "warp_blocked":
                warp_blocked.append(rec)
            elif rec["kind"] == "serve_event":
                serve_events.append(rec)
            elif rec["kind"] == "serve_round":
                serve_rounds.append(rec)
    out: dict = {
        "metric": "telemetry_manifest_summary",
        "manifests": len(records),
        "records": int(sum(kinds.values())),
        "kinds": kinds,
        "runs": [
            {k: r[k] for k in ("metric", "value", "unit", "n_peers", "ticks",
                               "backend", "wall_s") if k in r}
            for r in runs
        ],
    }
    if warp_spans:
        # Warp 2.0 per-class leap counters: one row per signature class
        # (strict / hybrid / fleet), aggregated across manifests.
        classes: dict = {}
        for rec in warp_spans:
            agg = classes.setdefault(
                int(rec["class_key"]),
                {"engine": rec.get("engine", ""),
                 "terms": rec.get("terms", []),
                 "spans": 0, "ticks": 0, "dispatches": 0},
            )
            for f in ("spans", "ticks", "dispatches"):
                agg[f] += int(rec.get(f, 0))
        out["leap_classes"] = {str(k): v for k, v in sorted(classes.items())}
    if warp_blocked:
        # Why-dense attribution: which signature terms kept spans off the
        # leap path (plus the 'scheduled_event' / 'short_span' pseudo-terms),
        # aggregated across manifests. Ticks sum to the dense ticks executed.
        terms: dict = {}
        for rec in warp_blocked:
            agg = terms.setdefault(
                str(rec["term"]), {"spans": 0, "ticks": 0, "members": 0}
            )
            for f in ("spans", "ticks", "members"):
                agg[f] += int(rec.get(f, 0))
        out["warp_blocked"] = dict(sorted(terms.items()))
    if serve_events or serve_rounds:
        # Serve-lane aggregation: request lifecycle counts, completed-run
        # tick stats, and per-engine round totals (chunk vs leap ticks —
        # the continuous-batching split the PERF.md serving section cites).
        by_event: dict[str, int] = {}
        for rec in serve_events:
            ev = rec.get("event", "?")
            by_event[ev] = by_event.get(ev, 0) + 1
        finished = [
            r for r in serve_events
            if r.get("event") in ("converged", "completed", "exhausted")
        ]
        engines: dict[str, dict] = {}
        for rec in serve_rounds:
            agg = engines.setdefault(
                rec.get("engine", "?"), {"rounds": 0, "ticks": 0}
            )
            agg["rounds"] += 1
            agg["ticks"] += int(rec.get("ticks", 0))
        serve: dict = {"events": by_event, "round_engines": engines}
        if finished:
            tr = [int(r["ticks_run"]) for r in finished if "ticks_run" in r]
            serve["finished"] = len(finished)
            serve["converged"] = sum(
                1 for r in finished if r.get("converged")
            )
            if tr:
                serve["mean_ticks_run"] = round(sum(tr) / len(tr), 2)
        out["serve"] = serve
    if ticks:
        ticks.sort(key=lambda r: r["tick"])
        totals = {
            name: int(sum(int(r[name]) for r in ticks if name in r))
            for name in FIELDS
            if any(name in r for r in ticks)
        }
        conv = [r for r in ticks if "converged" in r]
        out["tick_records"] = len(ticks)
        out["tick_span"] = [int(ticks[0]["tick"]), int(ticks[-1]["tick"])]
        out["counter_totals"] = totals
        if conv:
            out["final_converged"] = bool(conv[-1]["converged"])
            first = next((r["tick"] for r in conv if r["converged"]), None)
            out["first_converged_tick"] = int(first) if first is not None else -1
    return out


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    try:
        records = load_manifests(args.paths)
        summary = summarize(records)
    except (OSError, ValueError) as e:
        print(f"telemetry: {e}", file=sys.stderr)
        return 1
    if args.check and summary["records"] == 0:
        print("telemetry: --check: manifest has no records", file=sys.stderr)
        return 1

    print(f"telemetry: {summary['manifests']} manifest(s), "
          f"{summary['records']} records "
          f"({', '.join(f'{k}:{v}' for k, v in sorted(summary['kinds'].items()))})")
    for run in summary["runs"]:
        bits = " ".join(f"{k}={run[k]}" for k in run)
        print(f"  run: {bits}")
    if "counter_totals" in summary:
        lo, hi = summary["tick_span"]
        print(f"  ticks {lo}..{hi} ({summary['tick_records']} records)")
        for name, v in summary["counter_totals"].items():
            print(f"    {name:<20} {v}")
        if "final_converged" in summary:
            print(f"  first_converged_tick={summary.get('first_converged_tick')}"
                  f" final_converged={summary.get('final_converged')}")

    if "warp_blocked" in summary:
        total = sum(v["ticks"] for v in summary["warp_blocked"].values())
        print(f"  why-dense ({total} dense ticks):")
        for term, agg in sorted(
            summary["warp_blocked"].items(),
            key=lambda kv: -kv[1]["ticks"],
        ):
            share = 100.0 * agg["ticks"] / max(total, 1)
            print(f"    {term:<32} {agg['ticks']:>7} ticks "
                  f"({share:5.1f}%) over {agg['spans']} spans")

    if "serve" in summary:
        s = summary["serve"]
        ev = ", ".join(f"{k}:{v}" for k, v in sorted(s["events"].items()))
        print(f"  serve: {ev}")
        for eng, agg in sorted(s["round_engines"].items()):
            print(f"    {eng}: {agg['rounds']} rounds, {agg['ticks']} ticks")

    if args.serve_report:
        all_recs = [r for recs in records.values() for r in recs]
        report = serve_report(all_recs)
        print_serve_report(report)
        summary["serve_report"] = {
            "requests": len(report["requests"]),
            "phases": report["phases"],
            "e2e": report["e2e"],
        }

    if args.trace:
        from kaboodle_tpu.telemetry.trace import (
            journal_trace_events, serve_trace_events, write_chrome_trace,
        )

        # One Perfetto process track PER MANIFEST: each manifest is its own
        # run, and pooling runs onto one track would corrupt the leap-gap
        # inference (overlapping tick slices, false/masked leaps).
        groups = {
            path: [r for r in recs if r["kind"] == "tick"]
            for path, recs in records.items()
        }
        program = (
            None if args.phase_program == "off"
            else _phase_program(args.phase_program)
        )
        # Serve manifests additionally render the wall-clock service view:
        # per-lane request/leap tracks per manifest (disjoint pid ranges),
        # plus the WAL appends when --journal points at the journal dir.
        extra: list[dict] = []
        for i, (path, recs) in enumerate(records.items()):
            if any(r["kind"] == "serve_span" for r in recs):
                extra.extend(serve_trace_events(recs, pid_base=10 + 20 * i))
        if args.journal:
            from kaboodle_tpu.serve.journal import read_journal_records

            extra.extend(journal_trace_events(
                read_journal_records(args.journal)))
        n = write_chrome_trace(args.trace,
                               {p: rows for p, rows in groups.items() if rows},
                               metadata={"manifests": args.paths},
                               program=program,
                               extra_events=extra)
        print(f"  trace: {n} events -> {args.trace}")
        summary["trace_events"] = n

    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
