"""Trace export + annotation: Perfetto-loadable JSON and named scopes.

Two complementary halves:

- **Annotations** — :func:`op_scope` (``jax.named_scope``) labels traced
  regions inside kernels so XLA ops in a ``jax.profiler`` capture carry
  ``kaboodle:tick`` / ``kaboodle:leap`` / ``kaboodle:fleet_tick`` name-stack
  prefixes; :func:`host_span` (``jax.profiler.TraceAnnotation``) brackets
  host-driven spans (the warp runner's leap/dense segments) on the profiler
  timeline. Both are metadata-only: numerics and compiled programs are
  unchanged (annotations do not count against the KB405 surface).
- **Export** — :func:`chrome_trace_events` renders per-tick telemetry rows
  (manifest ``tick`` records, or anything shaped like them) into Chrome
  trace events: one ``X`` slice per tick on a "protocol" track (leaped gaps
  become ``leap`` slices), one ``C`` counter series per ProtocolCounters
  field. :func:`phase_slice_events` adds a second thread of **per-pass
  slices sourced from the phase graph**: given a planned
  :class:`~kaboodle_tpu.phasegraph.plan.TickProgram` (or its ``describe()``
  dict), each tick's slice is subdivided into that program's executable
  passes, each pass slice naming the phase ops that landed in it — so a
  fused-program trace shows exactly which of the two passes (draw / update)
  each SWIM phase folded into, and which ops the dispatch predicate pruned.
  :func:`write_chrome_trace` wraps everything in the JSON object format
  that chrome://tracing and https://ui.perfetto.dev load directly. The
  timeline unit is simulated ticks (1 tick == 1 ms display time), not wall
  clock — this is the *protocol* timeline; for device wall time use
  ``profiling.trace`` (the jax profiler capture, already Perfetto-format).
"""

from __future__ import annotations

import contextlib
import json

from kaboodle_tpu.telemetry.counters import FIELDS

_TICK_US = 1000  # 1 simulated tick rendered as 1 ms of trace time


def op_scope(name: str):
    """``jax.named_scope`` under the ``kaboodle:`` prefix (trace-time only)."""
    import jax

    return jax.named_scope(f"kaboodle:{name}")


@contextlib.contextmanager
def host_span(name: str):
    """Host-side profiler span (no-op cost outside an active capture)."""
    import jax

    with jax.profiler.TraceAnnotation(f"kaboodle:{name}"):
        yield


def chrome_trace_events(tick_rows, pid: int = 1, label: str | None = None) -> list[dict]:
    """Per-tick telemetry rows FROM ONE RUN -> Chrome trace events.

    ``tick_rows``: iterable of dicts carrying ``tick`` plus any subset of
    the counter/metric fields (manifest ``tick`` records qualify). Rows
    need not be contiguous — a gap between consecutive ticks is rendered as
    one ``leap`` slice spanning it (the warp runner's leaped spans) — but
    they MUST come from a single run: the gap inference and the one-slice-
    per-tick layout are meaningless over pooled runs. Multiple runs get one
    call each with distinct ``pid``s (``write_chrome_trace`` with a mapping
    does exactly that), so each renders as its own Perfetto process track.
    """
    rows = sorted(tick_rows, key=lambda r: r["tick"])
    events: list[dict] = [
        {"name": "process_name", "ph": "M", "pid": pid,
         "args": {"name": label or "kaboodle protocol timeline"}},
        {"name": "thread_name", "ph": "M", "pid": pid, "tid": 1,
         "args": {"name": "ticks"}},
    ]
    prev_tick = None
    for row in rows:
        t = int(row["tick"])
        if prev_tick is not None and t > prev_tick + 1:
            events.append({
                "name": "leap", "ph": "X", "pid": pid, "tid": 1,
                "ts": (prev_tick + 1) * _TICK_US,
                "dur": (t - prev_tick - 1) * _TICK_US,
                "args": {"leaped_ticks": t - prev_tick - 1},
            })
        args = {
            k: row[k]
            for k in row
            if k not in ("tick", "schema", "kind") and isinstance(row[k], (int, float, bool))
        }
        events.append({
            "name": "tick", "ph": "X", "pid": pid, "tid": 1,
            "ts": t * _TICK_US, "dur": _TICK_US, "args": args,
        })
        for name in FIELDS:
            if name in row:
                events.append({
                    "name": name, "ph": "C", "pid": pid,
                    "ts": t * _TICK_US, "args": {name: row[name]},
                })
        prev_tick = t
    return events


def phase_slice_events(program, tick_rows, pid: int = 1) -> list[dict]:
    """Per-tick **pass** slices derived from a planned phase-graph program.

    ``program`` is a :class:`~kaboodle_tpu.phasegraph.plan.TickProgram` or
    its ``describe()`` dict — the one source of truth for which fused pass
    each phase op landed in. Each tick present in ``tick_rows`` gets its
    1 ms subdivided equally among the program's executable passes (prologue
    then tail, in execution order) on a second thread of the same process
    track; a pass slice's args carry its op names. Pruned ops (the
    rare-phase work the dispatch predicate excludes from the fused program)
    are rendered once as an instant event at the first tick, with the
    predicate terms that guard their absence.

    Equal subdivision is deliberate: this is the *protocol* timeline (pass
    structure and op membership), not a wall-clock profile — per-pass wall
    time lives in the jax profiler capture, where the same op names appear
    as ``kaboodle:`` named scopes.
    """
    desc = program.describe() if hasattr(program, "describe") else program
    passes = desc["passes"]
    events: list[dict] = [
        {"name": "thread_name", "ph": "M", "pid": pid, "tid": 2,
         "args": {"name": f"phase passes ({desc['mode']})"}},
    ]
    ticks = sorted(int(r["tick"]) for r in tick_rows)
    if ticks and desc.get("pruned"):
        events.append({
            "name": "pruned", "ph": "i", "s": "t", "pid": pid, "tid": 2,
            "ts": ticks[0] * _TICK_US,
            "args": {
                "ops": {p["op"]: p["reason"] for p in desc["pruned"]},
                "pred_terms": list(desc.get("pred_terms", ())),
            },
        })
    width = _TICK_US // max(len(passes), 1)
    for t in ticks:
        for j, p in enumerate(passes):
            events.append({
                "name": f"{p['stage']}:{p['name']}", "ph": "X",
                "pid": pid, "tid": 2,
                "ts": t * _TICK_US + j * width, "dur": width,
                "args": {"ops": list(p["ops"])},
            })
    return events


def write_chrome_trace(
    path: str, tick_rows, metadata: dict | None = None, program=None
) -> int:
    """Write rows as a Chrome-trace JSON file; returns the event count.

    ``tick_rows`` is either one run's rows, or a ``{label: rows}`` mapping
    of several runs — each mapping entry gets its own pid (Perfetto process
    track), so independent runs' ticks never interleave into each other's
    leap-gap inference. ``program`` (optional) is a planned phase-graph
    program (or its ``describe()`` dict): each run track then gets a second
    thread of per-pass slices (:func:`phase_slice_events`) showing which
    pass each phase op landed in; the program structure is also embedded in
    ``otherData.phase_program``."""
    if isinstance(tick_rows, dict):
        events = []
        for i, (label, rows) in enumerate(tick_rows.items(), start=1):
            rows = list(rows)
            events.extend(chrome_trace_events(rows, pid=i, label=str(label)))
            if program is not None:
                events.extend(phase_slice_events(program, rows, pid=i))
    else:
        tick_rows = list(tick_rows)
        events = chrome_trace_events(tick_rows)
        if program is not None:
            events.extend(phase_slice_events(program, tick_rows))
    if program is not None:
        desc = program.describe() if hasattr(program, "describe") else program
        metadata = {**(metadata or {}), "phase_program": desc}
    doc = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"schema": "kaboodle-telemetry/1", **(metadata or {})},
    }
    with open(path, "w") as f:
        json.dump(doc, f)
    return len(events)
