"""Trace export + annotation: Perfetto-loadable JSON and named scopes.

Two complementary halves:

- **Annotations** — :func:`op_scope` (``jax.named_scope``) labels traced
  regions inside kernels so XLA ops in a ``jax.profiler`` capture carry
  ``kaboodle:tick`` / ``kaboodle:leap`` / ``kaboodle:fleet_tick`` name-stack
  prefixes; :func:`host_span` (``jax.profiler.TraceAnnotation``) brackets
  host-driven spans (the warp runner's leap/dense segments) on the profiler
  timeline. Both are metadata-only: numerics and compiled programs are
  unchanged (annotations do not count against the KB405 surface).
- **Export** — :func:`chrome_trace_events` renders per-tick telemetry rows
  (manifest ``tick`` records, or anything shaped like them) into Chrome
  trace events: one ``X`` slice per tick on a "protocol" track (leaped gaps
  become ``leap`` slices), one ``C`` counter series per ProtocolCounters
  field. :func:`phase_slice_events` adds a second thread of **per-pass
  slices sourced from the phase graph**: given a planned
  :class:`~kaboodle_tpu.phasegraph.plan.TickProgram` (or its ``describe()``
  dict), each tick's slice is subdivided into that program's executable
  passes, each pass slice naming the phase ops that landed in it — so a
  fused-program trace shows exactly which of the two passes (draw / update)
  each SWIM phase folded into, and which ops the dispatch predicate pruned.
  :func:`write_chrome_trace` wraps everything in the JSON object format
  that chrome://tracing and https://ui.perfetto.dev load directly. The
  timeline unit is simulated ticks (1 tick == 1 ms display time), not wall
  clock — this is the *protocol* timeline; for device wall time use
  ``profiling.trace`` (the jax profiler capture, already Perfetto-format).
"""

from __future__ import annotations

import contextlib
import json

from kaboodle_tpu.telemetry.counters import FIELDS

_TICK_US = 1000  # 1 simulated tick rendered as 1 ms of trace time


def op_scope(name: str):
    """``jax.named_scope`` under the ``kaboodle:`` prefix (trace-time only)."""
    import jax

    return jax.named_scope(f"kaboodle:{name}")


@contextlib.contextmanager
def host_span(name: str):
    """Host-side profiler span (no-op cost outside an active capture)."""
    import jax

    with jax.profiler.TraceAnnotation(f"kaboodle:{name}"):
        yield


def chrome_trace_events(tick_rows, pid: int = 1, label: str | None = None) -> list[dict]:
    """Per-tick telemetry rows FROM ONE RUN -> Chrome trace events.

    ``tick_rows``: iterable of dicts carrying ``tick`` plus any subset of
    the counter/metric fields (manifest ``tick`` records qualify). Rows
    need not be contiguous — a gap between consecutive ticks is rendered as
    one ``leap`` slice spanning it (the warp runner's leaped spans) — but
    they MUST come from a single run: the gap inference and the one-slice-
    per-tick layout are meaningless over pooled runs. Multiple runs get one
    call each with distinct ``pid``s (``write_chrome_trace`` with a mapping
    does exactly that), so each renders as its own Perfetto process track.
    """
    rows = sorted(tick_rows, key=lambda r: r["tick"])
    events: list[dict] = [
        {"name": "process_name", "ph": "M", "pid": pid,
         "args": {"name": label or "kaboodle protocol timeline"}},
        {"name": "thread_name", "ph": "M", "pid": pid, "tid": 1,
         "args": {"name": "ticks"}},
    ]
    prev_tick = None
    for row in rows:
        t = int(row["tick"])
        if prev_tick is not None and t > prev_tick + 1:
            events.append({
                "name": "leap", "ph": "X", "pid": pid, "tid": 1,
                "ts": (prev_tick + 1) * _TICK_US,
                "dur": (t - prev_tick - 1) * _TICK_US,
                "args": {"leaped_ticks": t - prev_tick - 1},
            })
        args = {
            k: row[k]
            for k in row
            if k not in ("tick", "schema", "kind") and isinstance(row[k], (int, float, bool))
        }
        events.append({
            "name": "tick", "ph": "X", "pid": pid, "tid": 1,
            "ts": t * _TICK_US, "dur": _TICK_US, "args": args,
        })
        for name in FIELDS:
            if name in row:
                events.append({
                    "name": name, "ph": "C", "pid": pid,
                    "ts": t * _TICK_US, "args": {name: row[name]},
                })
        prev_tick = t
    return events


def phase_slice_events(program, tick_rows, pid: int = 1) -> list[dict]:
    """Per-tick **pass** slices derived from a planned phase-graph program.

    ``program`` is a :class:`~kaboodle_tpu.phasegraph.plan.TickProgram` or
    its ``describe()`` dict — the one source of truth for which fused pass
    each phase op landed in. Each tick present in ``tick_rows`` gets its
    1 ms subdivided equally among the program's executable passes (prologue
    then tail, in execution order) on a second thread of the same process
    track; a pass slice's args carry its op names. Pruned ops (the
    rare-phase work the dispatch predicate excludes from the fused program)
    are rendered once as an instant event at the first tick, with the
    predicate terms that guard their absence.

    Equal subdivision is deliberate: this is the *protocol* timeline (pass
    structure and op membership), not a wall-clock profile — per-pass wall
    time lives in the jax profiler capture, where the same op names appear
    as ``kaboodle:`` named scopes.
    """
    desc = program.describe() if hasattr(program, "describe") else program
    passes = desc["passes"]
    events: list[dict] = [
        {"name": "thread_name", "ph": "M", "pid": pid, "tid": 2,
         "args": {"name": f"phase passes ({desc['mode']})"}},
    ]
    ticks = sorted(int(r["tick"]) for r in tick_rows)
    if ticks and desc.get("pruned"):
        events.append({
            "name": "pruned", "ph": "i", "s": "t", "pid": pid, "tid": 2,
            "ts": ticks[0] * _TICK_US,
            "args": {
                "ops": {p["op"]: p["reason"] for p in desc["pruned"]},
                "pred_terms": list(desc.get("pred_terms", ())),
            },
        })
    width = _TICK_US // max(len(passes), 1)
    for t in ticks:
        for j, p in enumerate(passes):
            events.append({
                "name": f"{p['stage']}:{p['name']}", "ph": "X",
                "pid": pid, "tid": 2,
                "ts": t * _TICK_US + j * width, "dur": width,
                "args": {"ops": list(p["ops"])},
            })
    return events


def serve_trace_events(records, pid_base: int = 10) -> list[dict]:
    """Serve manifest records -> Chrome trace events on the wall timeline.

    Renders the servescope (PR 14) record kinds into one shared-clock
    view — all timestamps are the engine/journal monotonic microseconds
    (``t0_us`` / ``t_us``), NOT the simulated-tick axis of
    :func:`chrome_trace_events`, so the two families should go in separate
    trace files.

    Layout: one **engine process** (``pid_base``) with the round envelope
    on thread 1 and the profiler's segment split laid out sequentially
    under each round on thread 2 (segments are sub-totals, not contiguous
    wall intervals — the layout shows proportion, the args carry truth);
    one **process per N-class pool** with a thread per lane: request
    phase spans (``queued`` / ``running`` / ``parked`` / ``spilling`` /
    ``spilled``) land on their lane's track (off-lane phases on the
    pool's "queue/off-lane" thread 1), and each ``advance`` span fans
    onto the lanes it moved — leap rounds named with the Warp signature
    class and leap length, chunk rounds with ticks run. ``serve_event``
    records that carry a ``t_us`` stamp (spill lifecycle, shed,
    recovery) become instant markers on the same tracks.
    """
    records = list(records)
    pools = sorted({
        int(r["pool_n"]) for r in records
        if r.get("kind") in ("serve_span", "serve_event")
        and int(r.get("pool_n", -1)) >= 0
    })
    pool_pid = {n: pid_base + 1 + i for i, n in enumerate(pools)}
    events: list[dict] = [
        {"name": "process_name", "ph": "M", "pid": pid_base,
         "args": {"name": "serve engine"}},
        {"name": "thread_name", "ph": "M", "pid": pid_base, "tid": 1,
         "args": {"name": "rounds"}},
        {"name": "thread_name", "ph": "M", "pid": pid_base, "tid": 2,
         "args": {"name": "round segments"}},
    ]
    lanes_seen: set[tuple[int, int]] = set()
    for n, pid in pool_pid.items():
        events.append({"name": "process_name", "ph": "M", "pid": pid,
                       "args": {"name": f"lane pool N={n}"}})
        events.append({"name": "thread_name", "ph": "M", "pid": pid,
                       "tid": 1, "args": {"name": "queue/off-lane"}})

    def lane_tid(pid: int, lane: int) -> int:
        tid = lane + 2
        if (pid, lane) not in lanes_seen:
            lanes_seen.add((pid, lane))
            events.append({"name": "thread_name", "ph": "M", "pid": pid,
                           "tid": tid, "args": {"name": f"lane {lane}"}})
        return tid

    for rec in records:
        kind = rec.get("kind")
        if kind == "serve_span":
            span = rec["span"]
            t0, dur = int(rec["t0_us"]), max(int(rec["dur_us"]), 1)
            if span == "round":
                args = {"round": rec.get("round")}
                args.update(rec.get("segments") or {})
                events.append({
                    "name": f"round {rec.get('round')}", "ph": "X",
                    "pid": pid_base, "tid": 1, "ts": t0, "dur": dur,
                    "args": args,
                })
                off = t0
                for seg, us in (rec.get("segments") or {}).items():
                    if us <= 0:
                        continue
                    events.append({
                        "name": seg, "ph": "X", "pid": pid_base, "tid": 2,
                        "ts": off, "dur": int(us), "args": {"us": int(us)},
                    })
                    off += int(us)
            elif span == "advance":
                pid = pool_pid.get(int(rec.get("pool_n", -1)), pid_base)
                eng = rec.get("engine", "?")
                for c in rec.get("classes") or []:
                    k = int(c.get("k", 0))
                    if eng == "leap":
                        name = f"leap x{k} [{c.get('class_key', '?')}]"
                    else:
                        name = f"run x{k}"
                    events.append({
                        "name": name, "ph": "X", "pid": pid,
                        "tid": lane_tid(pid, int(c["lane"])),
                        "ts": t0, "dur": dur,
                        "args": {**c, "engine": eng,
                                 "round": rec.get("round")},
                    })
            else:
                rid = int(rec["request_id"])
                pool_n = int(rec.get("pool_n", -1))
                lane = int(rec.get("lane", -1))
                pid = pool_pid.get(pool_n, pid_base)
                tid = lane_tid(pid, lane) if lane >= 0 else (
                    1 if pool_n >= 0 else 3)
                args = {k: v for k, v in rec.items()
                        if k not in ("schema", "kind", "span", "t0_us",
                                     "dur_us")}
                events.append({
                    "name": f"r{rid}:{span}", "ph": "X", "pid": pid,
                    "tid": tid, "ts": t0, "dur": dur, "args": args,
                })
        elif kind == "serve_event" and isinstance(rec.get("t_us"), int):
            pool_n = int(rec.get("pool_n", -1))
            lane = int(rec.get("lane", -1))
            pid = pool_pid.get(pool_n, pid_base)
            tid = lane_tid(pid, lane) if lane >= 0 and pid != pid_base else 1
            args = {k: v for k, v in rec.items()
                    if k not in ("schema", "kind", "t_us")}
            events.append({
                "name": rec.get("event", "?"), "ph": "i", "s": "t",
                "pid": pid, "tid": tid, "ts": int(rec["t_us"]),
                "args": args,
            })
    return events


def journal_trace_events(records, pid: int = 9) -> list[dict]:
    """WAL records (``journal.read_journal_records``) -> instant markers.

    Post-PR-14 records carry ``ts_us`` on the engine's shared monotonic
    epoch, so journal writes line up under the serve spans; ``seq`` orders
    them (crash-recovery order). Pre-seq records have no timestamp and are
    skipped — there is nowhere honest to put them on a wall timeline.
    """
    events: list[dict] = [
        {"name": "process_name", "ph": "M", "pid": pid,
         "args": {"name": "serve journal (WAL)"}},
        {"name": "thread_name", "ph": "M", "pid": pid, "tid": 1,
         "args": {"name": "appends"}},
    ]
    for rec in sorted(records, key=lambda r: int(r.get("seq", -1))):
        if not isinstance(rec.get("ts_us"), int):
            continue
        events.append({
            "name": f"{rec.get('op', '?')} r{rec.get('rid')}", "ph": "i",
            "s": "t", "pid": pid, "tid": 1, "ts": int(rec["ts_us"]),
            "args": {"op": rec.get("op"), "rid": rec.get("rid"),
                     "seq": rec.get("seq", None)},
        })
    return events


def write_chrome_trace(
    path: str, tick_rows, metadata: dict | None = None, program=None,
    extra_events: list[dict] | None = None,
) -> int:
    """Write rows as a Chrome-trace JSON file; returns the event count.

    ``tick_rows`` is either one run's rows, or a ``{label: rows}`` mapping
    of several runs — each mapping entry gets its own pid (Perfetto process
    track), so independent runs' ticks never interleave into each other's
    leap-gap inference. ``program`` (optional) is a planned phase-graph
    program (or its ``describe()`` dict): each run track then gets a second
    thread of per-pass slices (:func:`phase_slice_events`) showing which
    pass each phase op landed in; the program structure is also embedded in
    ``otherData.phase_program``. ``extra_events`` (optional) are appended
    verbatim — the summarizer uses this for the serve/journal tracks
    (:func:`serve_trace_events` / :func:`journal_trace_events`), which live
    on their own pids."""
    if isinstance(tick_rows, dict):
        events = []
        for i, (label, rows) in enumerate(tick_rows.items(), start=1):
            rows = list(rows)
            events.extend(chrome_trace_events(rows, pid=i, label=str(label)))
            if program is not None:
                events.extend(phase_slice_events(program, rows, pid=i))
    else:
        tick_rows = list(tick_rows)
        events = chrome_trace_events(tick_rows)
        if program is not None:
            events.extend(phase_slice_events(program, tick_rows))
    if extra_events:
        events.extend(extra_events)
    if program is not None:
        desc = program.describe() if hasattr(program, "describe") else program
        metadata = {**(metadata or {}), "phase_program": desc}
    doc = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"schema": "kaboodle-telemetry/1", **(metadata or {})},
    }
    with open(path, "w") as f:
        json.dump(doc, f)
    return len(events)
