"""Real-network transport: wire-format interop with reference instances.

Three layers (all reference-faithful at the wire, SURVEY.md §2.3):

- :mod:`kaboodle_tpu.transport.codec` — pure-Python bincode-compatible codec.
- :mod:`kaboodle_tpu.transport.native` — ctypes bindings to the C++ engine
  (native/src): UDP broadcast/multicast transport + the real-time SWIM
  protocol loop in a background thread.
- :mod:`kaboodle_tpu.transport.real` — the consumer facade + standalone probe.
"""

from kaboodle_tpu.transport.real import RealKaboodle, discover_mesh_member

__all__ = ["RealKaboodle", "discover_mesh_member"]
