"""Pure-Python bincode-compatible codec for the kaboodle wire format.

Byte-compatible with the reference's ``bincode::serialize`` of the structs in
src/structs.rs (bincode 1.3 legacy config: little-endian fixed-width ints,
u64 sequence/byte lengths, u32 enum variant tags; serde's binary SocketAddr
encoding: enum{V4=0,V6=1} tag + raw octets + u16 port) — and with the C++
codec in native/src/wire.cc, which tests cross-check byte-for-byte.

Addresses are strings in Rust ``SocketAddr`` Display form ("1.2.3.4:56",
"[::1]:56"). Messages are plain dicts with a "kind" field naming the
:class:`kaboodle_tpu.spec.UnicastKind` / ``BroadcastKind`` variant.

Decoders parse a *prefix* and tolerate trailing bytes (quirk Q2 — the
reference deserializes the whole zero-padded receive buffer, and probe
replies rely on it, Q4).
"""

from __future__ import annotations

import ipaddress
import struct

from kaboodle_tpu.spec import BroadcastKind, UnicastKind


class CodecError(ValueError):
    pass


# --- address <-> bytes ----------------------------------------------------


def _encode_addr(addr: str) -> bytes:
    try:
        if addr.startswith("["):
            host, sep, port = addr[1:].rpartition("]:")
            if not sep:
                raise ValueError("missing ]:port")
            ip6 = ipaddress.IPv6Address(host)
            return struct.pack("<I", 1) + ip6.packed + struct.pack("<H", int(port))
        host, sep, port = addr.rpartition(":")
        if not sep:
            raise ValueError("missing :port")
        ip4 = ipaddress.IPv4Address(host)
        return struct.pack("<I", 0) + ip4.packed + struct.pack("<H", int(port))
    except (ValueError, struct.error) as e:
        # Module contract: all malformed input surfaces as CodecError.
        raise CodecError(f"bad address {addr!r}: {e}") from None


class _Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.off = 0

    def take(self, n: int) -> bytes:
        if self.off + n > len(self.data):
            raise CodecError("truncated")
        out = self.data[self.off : self.off + n]
        self.off += n
        return out

    def u16(self) -> int:
        return struct.unpack("<H", self.take(2))[0]

    def u32(self) -> int:
        return struct.unpack("<I", self.take(4))[0]

    def u64(self) -> int:
        return struct.unpack("<Q", self.take(8))[0]

    def bytes_(self) -> bytes:
        return self.take(self.u64())

    def addr(self) -> str:
        tag = self.u32()
        if tag == 0:
            ip = ipaddress.IPv4Address(self.take(4))
            return f"{ip}:{self.u16()}"
        if tag == 1:
            ip = ipaddress.IPv6Address(self.take(16))
            return f"[{ip}]:{self.u16()}"
        raise CodecError(f"bad SocketAddr variant {tag}")


def _u64(v: int) -> bytes:
    return struct.pack("<Q", v)


def _u32(v: int) -> bytes:
    return struct.pack("<I", v)


def _bytes(b: bytes) -> bytes:
    return _u64(len(b)) + b


# --- unicast envelope -----------------------------------------------------


def encode_message(msg: dict) -> bytes:
    kind = UnicastKind[msg["kind"]] if isinstance(msg["kind"], str) else msg["kind"]
    out = _u32(int(kind))
    if kind == UnicastKind.PING:
        pass
    elif kind == UnicastKind.PING_REQUEST:
        out += _encode_addr(msg["peer"])
    elif kind == UnicastKind.ACK:
        out += _encode_addr(msg["peer"]) + _u32(msg["fingerprint"]) + _u32(msg["num_peers"])
    elif kind == UnicastKind.KNOWN_PEERS:
        peers: dict[str, bytes] = msg["peers"]
        out += _u64(len(peers))
        for addr, ident in peers.items():
            out += _encode_addr(addr) + _bytes(ident)
    elif kind == UnicastKind.KNOWN_PEERS_REQUEST:
        out += _u32(msg["fingerprint"]) + _u32(msg["num_peers"])
    else:
        raise CodecError(f"bad kind {kind}")
    return out


def encode_envelope(identity: bytes, msg: dict) -> bytes:
    return _bytes(identity) + encode_message(msg)


def decode_envelope(data: bytes) -> tuple[bytes, dict]:
    r = _Reader(data)
    identity = r.bytes_()
    tag = r.u32()
    if tag > 4:
        raise CodecError(f"bad SwimMessage variant {tag}")
    kind = UnicastKind(tag)
    msg: dict = {"kind": kind.name}
    if kind == UnicastKind.PING_REQUEST:
        msg["peer"] = r.addr()
    elif kind == UnicastKind.ACK:
        msg["peer"] = r.addr()
        msg["fingerprint"] = r.u32()
        msg["num_peers"] = r.u32()
    elif kind == UnicastKind.KNOWN_PEERS:
        msg["peers"] = {r.addr(): r.bytes_() for _ in range(r.u64())}
    elif kind == UnicastKind.KNOWN_PEERS_REQUEST:
        msg["fingerprint"] = r.u32()
        msg["num_peers"] = r.u32()
    return identity, msg


# --- broadcasts -----------------------------------------------------------


def encode_broadcast(msg: dict) -> bytes:
    kind = BroadcastKind[msg["kind"]] if isinstance(msg["kind"], str) else msg["kind"]
    out = _u32(int(kind)) + _encode_addr(msg["addr"])
    if kind == BroadcastKind.JOIN:
        out += _bytes(msg["identity"])
    return out


def decode_broadcast(data: bytes) -> dict:
    r = _Reader(data)
    tag = r.u32()
    if tag > 2:
        raise CodecError(f"bad SwimBroadcast variant {tag}")
    kind = BroadcastKind(tag)
    msg = {"kind": kind.name, "addr": r.addr()}
    if kind == BroadcastKind.JOIN:
        msg["identity"] = r.bytes_()
    return msg


def encode_probe_response(identity: bytes) -> bytes:
    return _bytes(identity)
