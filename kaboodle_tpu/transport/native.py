"""ctypes bindings for the native C++ engine (native/src, libkaboodle_native.so).

The shared library is built on demand with ``make`` on first use (g++ is part
of the environment; no Python build deps). All strings cross the boundary as
UTF-8; peer/event snapshots cross as JSON with hex-encoded identities.
"""

from __future__ import annotations

import ctypes
import json
import pathlib
import subprocess

from kaboodle_tpu.errors import IoError, NoAvailableInterfaces

_NATIVE_DIR = pathlib.Path(__file__).resolve().parents[2] / "native"
_LIB_PATH = _NATIVE_DIR / "build" / "libkaboodle_native.so"

_lib = None


def load_library() -> ctypes.CDLL:
    """Load (building if necessary) the native library."""
    global _lib
    if _lib is not None:
        return _lib
    # Always invoke make: its dependency rules make this a no-op when the
    # library is current, and pick up native/src edits when it is not. A
    # build failure is fatal unless the existing library is newer than every
    # source (i.e. the failure cannot mean "stale code would load").
    try:
        subprocess.run(["make", "-C", str(_NATIVE_DIR)], check=True, capture_output=True)
    except (OSError, subprocess.CalledProcessError) as e:
        out = getattr(e, "stderr", b"") or b""
        stale = not _LIB_PATH.exists() or any(
            src.stat().st_mtime > _LIB_PATH.stat().st_mtime
            for src in (_NATIVE_DIR / "src").glob("*")
        )
        if stale:
            raise IoError(f"native build failed: {out.decode(errors='replace')}") from e
    lib = ctypes.CDLL(str(_LIB_PATH))

    lib.kb_create.restype = ctypes.c_void_p
    lib.kb_create.argtypes = [
        ctypes.c_char_p,  # bind_ip
        ctypes.c_char_p,  # broadcast_ip
        ctypes.c_uint16,  # broadcast_port
        ctypes.c_uint,  # iface_index
        ctypes.c_char_p,  # identity
        ctypes.c_size_t,
        ctypes.c_uint32,  # period_ms
        ctypes.c_uint32,  # ping_timeout_ms
        ctypes.c_uint32,  # share_age_ms
        ctypes.c_uint32,  # rebroadcast_ms
        ctypes.c_uint64,  # rng_seed
    ]
    for name in ("kb_start", "kb_stop", "kb_is_running"):
        getattr(lib, name).restype = ctypes.c_int
        getattr(lib, name).argtypes = [ctypes.c_void_p]
    lib.kb_destroy.restype = None
    lib.kb_destroy.argtypes = [ctypes.c_void_p]
    for name in ("kb_self_addr", "kb_peers_json", "kb_events_json"):
        getattr(lib, name).restype = ctypes.c_void_p  # manual free
        getattr(lib, name).argtypes = [ctypes.c_void_p]
    lib.kb_fingerprint.restype = ctypes.c_uint32
    lib.kb_fingerprint.argtypes = [ctypes.c_void_p]
    lib.kb_ping_addr.restype = ctypes.c_int
    lib.kb_ping_addr.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.kb_set_identity.restype = ctypes.c_int
    lib.kb_set_identity.argtypes = [ctypes.c_void_p, ctypes.c_char_p, ctypes.c_size_t]
    lib.kb_probe.restype = ctypes.c_void_p
    lib.kb_probe.argtypes = [
        ctypes.c_char_p,
        ctypes.c_char_p,
        ctypes.c_uint16,
        ctypes.c_uint,
        ctypes.c_uint32,
        ctypes.c_double,
        ctypes.c_uint32,
        ctypes.c_uint32,
    ]
    lib.kb_best_interface.restype = ctypes.c_void_p
    lib.kb_best_interface.argtypes = []
    lib.kb_list_interfaces.restype = ctypes.c_void_p
    lib.kb_list_interfaces.argtypes = []
    lib.kb_free.restype = None
    lib.kb_free.argtypes = [ctypes.c_void_p]
    lib.kb_codec_roundtrip_envelope.restype = ctypes.c_long
    lib.kb_codec_roundtrip_envelope.argtypes = [
        ctypes.c_char_p,
        ctypes.c_size_t,
        ctypes.c_char_p,
        ctypes.c_size_t,
    ]
    lib.kb_codec_roundtrip_broadcast.restype = ctypes.c_long
    lib.kb_codec_roundtrip_broadcast.argtypes = lib.kb_codec_roundtrip_envelope.argtypes
    lib.kb_crc32.restype = ctypes.c_uint32
    lib.kb_crc32.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
    _lib = lib
    return lib


def _take_string(lib, ptr) -> str:
    if not ptr:
        return ""
    try:
        return ctypes.cast(ptr, ctypes.c_char_p).value.decode()
    finally:
        lib.kb_free(ptr)


def best_interface() -> tuple[str, int]:
    """Reference policy (networking.rs:12-23): first non-loopback IPv6
    interface, else IPv4. Returns (ip, ifindex)."""
    lib = load_library()
    s = _take_string(lib, lib.kb_best_interface())
    if not s:
        raise NoAvailableInterfaces("no non-loopback interface")
    ip, idx = s.rsplit(",", 1)
    return ip, int(idx)


def list_interfaces() -> list[dict]:
    """All non-loopback addresses: {family: 4|6, ip, ifindex, broadcast, name}.

    ``name`` is the OS device name (``eth0``), so ``--interface`` can resolve
    by name like the reference (main.rs:18-36)."""
    lib = load_library()
    out = []
    for line in _take_string(lib, lib.kb_list_interfaces()).splitlines():
        fam, ip, idx, bcast, name = (line.split(",") + [""])[:5]
        out.append(
            {"family": int(fam), "ip": ip, "ifindex": int(idx),
             "broadcast": bcast, "name": name}
        )
    return out


class NativeEngine:
    """Thin OO wrapper over the C API. Timing is injectable so tests can run
    the full protocol at millisecond scale (defaults match the reference)."""

    def __init__(
        self,
        bind_ip: str,
        broadcast_ip: str,
        broadcast_port: int = 7475,
        iface_index: int = 0,
        identity: bytes = b"",
        period_ms: int = 1000,
        ping_timeout_ms: int = 2000,
        share_age_ms: int = 10000,
        rebroadcast_ms: int = 10000,
        rng_seed: int = 0,
    ):
        self._lib = load_library()
        self._h = self._lib.kb_create(
            bind_ip.encode(),
            broadcast_ip.encode(),
            broadcast_port,
            iface_index,
            identity,
            len(identity),
            period_ms,
            ping_timeout_ms,
            share_age_ms,
            rebroadcast_ms,
            rng_seed,
        )
        if not self._h:
            raise IoError(f"kb_create failed for {bind_ip} / {broadcast_ip}")

    def start(self) -> None:
        if self._lib.kb_start(self._h) != 0:
            raise IoError("engine start failed (bind/socket error)")

    def stop(self) -> None:
        self._lib.kb_stop(self._h)

    @property
    def is_running(self) -> bool:
        return bool(self._lib.kb_is_running(self._h))

    def self_addr(self) -> str:
        return _take_string(self._lib, self._lib.kb_self_addr(self._h))

    def fingerprint(self) -> int:
        return int(self._lib.kb_fingerprint(self._h))

    def peers(self) -> dict[str, dict]:
        raw = json.loads(_take_string(self._lib, self._lib.kb_peers_json(self._h)))
        return {
            e["addr"]: {
                "identity": bytes.fromhex(e["identity_hex"]),
                "state": e["state"],
                "latency_ms": e["latency_ms"] if e["latency_ms"] >= 0 else None,
            }
            for e in raw
        }

    def drain_events(self) -> list[dict]:
        events = json.loads(_take_string(self._lib, self._lib.kb_events_json(self._h)))
        for e in events:
            if "identity_hex" in e:
                e["identity"] = bytes.fromhex(e.pop("identity_hex"))
        return events

    def ping_addr(self, addr: str) -> None:
        if self._lib.kb_ping_addr(self._h, addr.encode()) != 0:
            raise IoError(f"bad address {addr!r}")

    def set_identity(self, identity: bytes) -> None:
        self._lib.kb_set_identity(self._h, identity, len(identity))

    def close(self) -> None:
        if self._h:
            self._lib.kb_destroy(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


def probe_mesh(
    bind_ip: str,
    broadcast_ip: str,
    broadcast_port: int = 7475,
    iface_index: int = 0,
    start_ms: int = 1000,
    multiplier: float = 1.25,
    cap_ms: int = 10000,
    total_timeout_ms: int = 30000,
) -> tuple[str, bytes] | None:
    """discover_mesh_member (discovery.rs:30-89): find one mesh member without
    joining. Returns (addr, identity) or None on timeout.
    ``total_timeout_ms=0`` retries forever with the 1 s x1.25 (cap 10 s)
    backoff, like the reference (discovery.rs:51-72)."""
    lib = load_library()
    s = _take_string(
        lib,
        lib.kb_probe(
            bind_ip.encode(),
            broadcast_ip.encode(),
            broadcast_port,
            iface_index,
            start_ms,
            multiplier,
            cap_ms,
            total_timeout_ms,
        ),
    )
    if not s:
        return None
    addr, _, ident_hex = s.partition("|")
    return addr, bytes.fromhex(ident_hex)


def codec_roundtrip_envelope(data: bytes) -> bytes | None:
    """Decode+re-encode through the C++ codec (cross-language golden tests)."""
    lib = load_library()
    out = ctypes.create_string_buffer(len(data) + 64)
    n = lib.kb_codec_roundtrip_envelope(data, len(data), out, len(out))
    return out.raw[:n] if n >= 0 else None


def codec_roundtrip_broadcast(data: bytes) -> bytes | None:
    lib = load_library()
    out = ctypes.create_string_buffer(len(data) + 64)
    n = lib.kb_codec_roundtrip_broadcast(data, len(data), out, len(out))
    return out.raw[:n] if n >= 0 else None


def native_crc32(data: bytes) -> int:
    lib = load_library()
    return int(lib.kb_crc32(data, len(data)))
