"""Real-network ``Kaboodle`` facade over the native C++ engine.

The same consumer surface as :class:`kaboodle_tpu.api.Kaboodle` (which runs on
the simulated mesh), but backed by real UDP sockets: actual wire-format
interop with reference instances on a LAN (lib.rs:78-369). Where the sim
facade's clock is ``SimNetwork.tick()``, here the protocol thread runs on
wall-clock; event streams fill when :meth:`poll_events` drains the engine.
"""

from __future__ import annotations

import collections
import time

from kaboodle_tpu.errors import InvalidOperation
from kaboodle_tpu.transport.native import NativeEngine, best_interface, probe_mesh


class RealKaboodle:
    """One real mesh instance bound to a network interface.

    ``interface_ip``/``broadcast_ip`` default to the reference's interface
    policy (networking.rs:12-23) with IPv4 broadcast; pass a ``ff02::...``
    group + iface index for the IPv6 multicast path. Timing parameters are
    forwarded to the engine (defaults are the reference's wall-clock values).
    """

    def __init__(
        self,
        identity: bytes = b"",
        broadcast_port: int = 7475,
        interface_ip: str | None = None,
        broadcast_ip: str = "255.255.255.255",
        iface_index: int = 0,
        **engine_kwargs,
    ):
        if interface_ip is None:
            interface_ip, iface_index = best_interface()
        self._identity = identity
        self._engine = NativeEngine(
            bind_ip=interface_ip,
            broadcast_ip=broadcast_ip,
            broadcast_port=broadcast_port,
            iface_index=iface_index,
            identity=identity,
            **engine_kwargs,
        )
        self._interface_ip = interface_ip
        self._discover_subs: list[collections.deque] = []
        self._depart_subs: list[collections.deque] = []
        self._fp_subs: list[collections.deque] = []

    # ---- lifecycle (lib.rs:136-183) ---------------------------------------

    def start(self) -> None:
        if self._engine.is_running:
            raise InvalidOperation("already running")
        self._engine.start()

    def stop(self) -> None:
        if not self._engine.is_running:
            raise InvalidOperation("not running")
        self._engine.stop()

    @property
    def is_running(self) -> bool:
        return self._engine.is_running

    def close(self) -> None:
        self._engine.close()

    # ---- addressing --------------------------------------------------------

    def self_addr(self) -> str:
        return self._engine.self_addr()

    def interface(self) -> str:
        return self._interface_ip

    # ---- queries -----------------------------------------------------------

    def peers(self) -> dict[str, bytes]:
        return {a: e["identity"] for a, e in self._engine.peers().items()}

    def peer_states(self) -> dict[str, tuple[str, float | None]]:
        """addr -> (state name, latency EWMA ms) (lib.rs:348-354)."""
        return {
            a: (e["state"], e["latency_ms"]) for a, e in self._engine.peers().items()
        }

    def fingerprint(self) -> int:
        """Reference-exact CRC-32 mesh fingerprint (kaboodle.rs:71-83)."""
        return self._engine.fingerprint()

    # ---- identity / manual pings ------------------------------------------

    def set_identity(self, identity: bytes) -> None:
        self._identity = identity
        self._engine.set_identity(identity)

    def ping_addrs(self, addrs) -> None:
        if not self._engine.is_running:
            raise InvalidOperation("not running")
        for a in addrs:
            self._engine.ping_addr(a)

    # ---- event streams -----------------------------------------------------

    def discover_peers(self):
        q: collections.deque = collections.deque()
        self._discover_subs.append(q)
        return q

    def discover_departures(self):
        q: collections.deque = collections.deque()
        self._depart_subs.append(q)
        return q

    def discover_fingerprint_changes(self):
        q: collections.deque = collections.deque()
        self._fp_subs.append(q)
        return q

    def discover_next_peer(self, timeout_s: float = 64.0):
        """Wait until the next peer discovery; returns (addr, identity) or
        None on timeout (lib.rs:246-260 — wall-clock twin of the sim facade)."""
        if not self._engine.is_running:
            raise InvalidOperation("not running")
        q = self.discover_peers()
        try:
            deadline = time.monotonic() + timeout_s
            while time.monotonic() < deadline:
                self.poll_events()
                if q:
                    return q.popleft()
                time.sleep(0.02)
            return None
        finally:
            self._discover_subs.remove(q)

    def poll_events(self) -> int:
        """Drain engine events into the subscriber streams; returns the count.
        (The CLI calls this once per display refresh, main.rs:144-244.)"""
        events = self._engine.drain_events()
        for e in events:
            if e["type"] == "discovered":
                for q in self._discover_subs:
                    q.append((e["addr"], e["identity"]))
            elif e["type"] == "departed":
                for q in self._depart_subs:
                    q.append(e["addr"])
            elif e["type"] == "fingerprint":
                for q in self._fp_subs:
                    q.append(e["value"])
        return len(events)


def discover_mesh_member(
    broadcast_port: int = 7475,
    interface_ip: str | None = None,
    broadcast_ip: str = "255.255.255.255",
    iface_index: int = 0,
    total_timeout_ms: int = 30000,
    **probe_kwargs,
) -> tuple[str, bytes] | None:
    """Probe for any mesh member without joining (lib.rs:359-368).
    ``total_timeout_ms=0`` retries forever like the reference; the default
    deadline is a library-convenience deviation (PARITY.md)."""
    if interface_ip is None:
        interface_ip, iface_index = best_interface()
    return probe_mesh(
        bind_ip=interface_ip,
        broadcast_ip=broadcast_ip,
        broadcast_port=broadcast_port,
        iface_index=iface_index,
        total_timeout_ms=total_timeout_ms,
        **probe_kwargs,
    )
