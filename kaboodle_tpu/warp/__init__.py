"""Event-horizon fast-forward: advance quiescent tick spans in one pass.

The first subsystem that changes *how many* kernels run rather than how fast
each one is: horizon.py statically + on-device identifies spans where nothing
protocol-relevant can happen, leap.py replays k such ticks as one batched
program (bit-exact with the dense kernel), runner.py interleaves leaps with
dense ticks behind the same contracts as sim/runner.py — single-device,
sharded (GSPMD), and fleet (per-member horizon mask) alike.
"""

from kaboodle_tpu.warp.horizon import (
    make_expiry_fn,
    make_quiescence_fn,
    next_static_event,
    static_event_ticks,
)
from kaboodle_tpu.warp.leap import make_leap_fn
from kaboodle_tpu.warp.runner import (
    fleet_quiescence_mask,
    run_fleet_warped,
    run_warped,
    simulate_warped,
)

__all__ = [
    "make_expiry_fn",
    "make_quiescence_fn",
    "next_static_event",
    "static_event_ticks",
    "make_leap_fn",
    "fleet_quiescence_mask",
    "run_fleet_warped",
    "run_warped",
    "simulate_warped",
]
