"""Signature-classed event-horizon fast-forward (Warp 2.0).

The subsystem that changes *how many* kernels run rather than how fast each
one is: horizon.py statically identifies event-free spans and classes each
span's entry state by an on-device **activity signature** (one int32[4]
fetch: term bits + active-row bucket + earliest timer expiry); leap.py /
phasegraph/span.py replay a span as one batched program — the strict span
program under full quiescence, the HYBRID near-quiescent program (strict
leap + sterile anti-entropy) on armed-timer drain windows up to the
earliest expiry — bit-exact with the dense kernel either way; runner.py
interleaves leaps with dense ticks behind the same contracts as
sim/runner.py — single-device, sharded (GSPMD), and fleet (per-member
horizons: every member leaps to its own next event inside one masked
vmapped dispatch) alike, memoizing compiled span programs in an explicitly
bounded cache.
"""

from kaboodle_tpu.warp.horizon import (
    ActivityClass,
    decode_signature,
    earliest_timer_expiry,
    make_expiry_fn,
    make_quiescence_fn,
    make_signature_fn,
    next_static_event,
    static_event_ticks,
)
from kaboodle_tpu.warp.leap import make_leap_fn
from kaboodle_tpu.warp.runner import (
    WarpLedger,
    fleet_quiescence_mask,
    fleet_signature,
    leap_cache,
    run_fleet_warped,
    run_warped,
    simulate_warped,
)

__all__ = [
    "ActivityClass",
    "decode_signature",
    "earliest_timer_expiry",
    "make_expiry_fn",
    "make_quiescence_fn",
    "make_signature_fn",
    "next_static_event",
    "static_event_ticks",
    "make_leap_fn",
    "WarpLedger",
    "fleet_quiescence_mask",
    "fleet_signature",
    "leap_cache",
    "run_fleet_warped",
    "run_warped",
    "simulate_warped",
]
