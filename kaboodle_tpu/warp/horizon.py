"""Event-horizon computation: how far ahead nothing protocol-relevant happens.

A *warp span* is a run of ticks the leap kernel (warp/leap.py) may replay in
one batched pass instead of k dense tick dispatches. The horizon is the
earliest tick at which anything non-quiescent can happen; it has two parts:

1. **Host-static events** — the ``Scenario`` schedule is declarative data, so
   kill / revive / partition / drop / manual-ping boundaries are known before
   the run starts. :func:`static_event_ticks` reduces a stacked ``TickInputs``
   pytree to a bool ``[T]`` "this tick carries an event" mask, and
   :func:`next_static_event` scans it forward. An all-``True`` ``drop_ok``
   matrix and an all-equal partition vector are correctly classified as
   non-events (they gate nothing).

2. **State-borne activity** — :func:`make_quiescence_fn` builds the on-device
   predicate under which the *dense fault-free tick provably reduces to the
   leap's update*: no suspicion or ping-ack timer can expire (no cell is in a
   waiting state, so nothing ever times out — a fresh ping is always acked
   within its own tick), no membership-changing gossip delivery can occur
   (fingerprints agree and every alive row's map is exactly the alive set, so
   marks move no membership and anti-entropy never fires), and no Join
   rebroadcast is due (nobody is lonely or unannounced). For completeness
   :func:`earliest_timer_expiry` reduces the waiting cells' deadlines from
   the timer tensors — when the mesh is NOT quiescent it tells the runner how
   long the dense stretch must last before a re-check can possibly flip; the
   sentinel ``INT32_MAX`` means "no timer armed".

The quiescence conditions map onto the issue's three horizon sources: the
Scenario boundary is (1); the suspicion/ping-ack expiry source degenerates to
"any waiting cell exists" because inside a span every ping is acked the tick
it is sent; the membership-changing gossip source degenerates to the
convergence + full-membership + anti-entropy-idle test, because with those
holding no delivery can move membership or identity words.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from kaboodle_tpu.config import SwimConfig
from kaboodle_tpu.ops.hashing import fingerprint_agreement, membership_fingerprint
from kaboodle_tpu.sim.state import MeshState, TickInputs
from kaboodle_tpu.spec import KNOWN

_I32MAX = jnp.iinfo(jnp.int32).max


def static_event_ticks(inputs: TickInputs) -> np.ndarray:
    """bool ``[T]``: tick carries a scheduled fault/manual event (host-side).

    Computed once per run on the host from the stacked schedule — these are
    scenario *inputs*, known before any device work (Scenario builds them
    with NumPy in the first place). A tick is eventful iff its inputs can
    change delivery or state relative to the idle fault-free tick: any kill
    or revive, a partition vector that actually splits (non-uniform ids), a
    positive drop rate, any in-range manual ping, or a ``drop_ok`` matrix
    that blocks at least one edge.
    """
    kill = np.asarray(inputs.kill)
    revive = np.asarray(inputs.revive)
    part = np.asarray(inputs.partition)
    drop_rate = np.asarray(inputs.drop_rate)
    manual = np.asarray(inputs.manual_target)
    eventful = (
        kill.any(axis=-1)
        | revive.any(axis=-1)
        | (part != part[:, :1]).any(axis=-1)
        | (drop_rate > 0)
        | (manual >= 0).any(axis=-1)
    )
    if inputs.drop_ok is not None:
        eventful |= ~np.asarray(inputs.drop_ok).all(axis=(-2, -1))
    return eventful


def next_static_event(eventful: np.ndarray, t: int) -> int:
    """Index of the first eventful tick at or after ``t`` (``len`` if none)."""
    T = eventful.shape[0]
    hits = np.nonzero(eventful[t:])[0]
    return int(t + hits[0]) if hits.size else T


@functools.lru_cache(maxsize=None)
def make_quiescence_fn(cfg: SwimConfig):
    """Jitted ``MeshState -> bool[]``: may the leap replay the next ticks?

    True iff every condition below holds — each one is exactly what makes a
    dense-kernel phase a provable no-op inside the span (kernel.py round
    letters in parens):

    - ``n_alive >= 2`` and, with broadcasts enabled, no alive peer still owes
      its first Join — so A1 never broadcasts (nobody is lonely either, by
      the full-membership condition below).
    - no alive row holds a waiting cell — so A2 (escalation/removal) can
      never fire: new WaitingForPing cells created inside the span are acked
      within their own tick (fault-free, both endpoints alive).
    - every alive row's membership is EXACTLY the alive set — so marks move
      no membership (B/c1/c2 deliver only to known-everywhere peers), no
      ping ever targets a dead peer (which would strand a waiting cell), and
      fingerprints cannot move.
    - identity views (when tracked) already hold the senders' current words
      at every member cell — so Q1 marks rewrite nothing and row
      fingerprints stay put.
    - fingerprints agree over alive rows — with the above, they stay agreed,
      so no anti-entropy candidate can match (G is idle).
    - no carried-over KnownPeersRequest from the previous tick can match at
      its receiver (phase-0 candidates, ``_ae_phase01``): a span-entry state
      taken right after convergence can still hold one stale request whose
      recorded fingerprint predates the final agreement; one dense tick
      clears it.
    """

    def quiescent(st: MeshState) -> jax.Array:  # graftlint: traced
        S, alive = st.state, st.alive
        n = S.shape[-1]
        idx = jnp.arange(n, dtype=jnp.int32)
        eye = idx[:, None] == idx[None, :]
        member = S > 0
        arow = alive[:, None]

        no_waiting = ~jnp.any(arow & member & (S != KNOWN))
        full_alive = ~jnp.any(arow & (member != (alive[None, :] | eye)))

        idv = st.id_view
        fp = membership_fingerprint(member, idv if idv is not None else st.identity)
        conv, _, _, n_alive = fingerprint_agreement(alive, fp)

        ident_ok = jnp.bool_(True)
        if idv is not None:
            ident_ok = ~jnp.any(arow & member & (idv != st.identity[None, :]))

        # Phase-0 anti-entropy: last tick's KPR senders. Receiver p must be
        # alive (m0's alive[:, None]); the candidate matches when the
        # recorded fingerprint disagrees with the receiver's and the
        # receiver's map is not larger (kernel.py _ae_phase01).
        n_row = jnp.sum(member, axis=-1, dtype=jnp.int32)
        p = st.kpr_partner
        pc = jnp.clip(p, 0)
        kpr_fires = (
            (p >= 0)
            & alive[pc]
            & (st.kpr_fp != fp[pc])
            & (n_row[pc] <= st.kpr_n)
        )
        no_kpr = ~jnp.any(kpr_fires)

        q = no_waiting & full_alive & conv & (n_alive >= 2) & no_kpr & ident_ok
        if cfg.join_broadcast_enabled:
            q &= ~jnp.any(alive & st.never_broadcast)
        return q

    return jax.jit(quiescent)


@functools.lru_cache(maxsize=None)
def make_expiry_fn(cfg: SwimConfig):
    """Jitted ``MeshState -> int32[]``: earliest waiting-cell deadline.

    The suspicion/ping-ack source of the horizon, reduced from the current
    timer tensors: ``min`` over alive rows' waiting cells of
    ``timer + ping_timeout_ticks`` (the tick at which A2 would escalate or
    remove that entry — kernel.py's ``age >= cfg.ping_timeout_ticks``).
    ``INT32_MAX`` when no timer is armed. Diagnostic companion to the
    quiescence predicate: a non-quiescent mesh with an armed timer cannot
    flip quiescent before its earliest deadline resolves.
    """

    def expiry(st: MeshState) -> jax.Array:  # graftlint: traced
        waiting = st.alive[:, None] & (st.state > 0) & (st.state != KNOWN)
        deadline = st.timer.astype(jnp.int32) + jnp.int32(cfg.ping_timeout_ticks)
        return jnp.min(jnp.where(waiting, deadline, _I32MAX))

    return jax.jit(expiry)


def earliest_timer_expiry(st: MeshState, cfg: SwimConfig) -> int:
    """Host convenience: the earliest tick at which phase A2 could fire.

    ``min`` over alive rows' waiting cells of ``timer + ping_timeout_ticks``
    — the first tick whose dense execution can escalate or remove an entry.
    ``INT32_MAX`` when no timer is armed. This is the suspicion source of
    the event horizon: a hybrid span starting at tick t may cover exactly
    the ticks in ``[t, earliest_timer_expiry)`` (strictly before — the
    expiry tick itself must run dense)."""
    return int(make_expiry_fn(cfg)(st))


# ---------------------------------------------------------------------------
# Warp 2.0: the activity signature (signature-classed fast-forward)

# Signature term bits. The first two are the phase-op activity terms the
# hybrid planner derives from the op graph (``plan(graph, "hybrid")``'s
# ``pred_terms`` — ops.py ``sig_term`` declarations); the rest are the
# state-borne sterility terms the hybrid span program additionally needs.
# ``make_signature_fn`` asserts the planner's terms stay inside this
# vocabulary, so a new rare-phase op with a fresh sig_term fails loudly
# here instead of silently leaping past its activity.
SIG_ANY_A2 = 1 << 0       # a suspicion timer has ALREADY matured (A2 fires now)
SIG_ANY_JOIN = 1 << 1     # a Join broadcast is owed (never_broadcast / lonely)
SIG_ARMED = 1 << 2        # waiting cells exist in alive rows (timers armed)
SIG_WAIT_ALIVE = 1 << 3   # some waiting cell targets an ALIVE peer (refutable)
SIG_KNOWN_DEAD = 1 << 4   # some alive row still Knows a dead peer (unacked ping)
SIG_MISSING = 1 << 5      # some alive row is missing an alive peer (AE inserts)
SIG_FP_DISAGREE = 1 << 6  # fingerprints disagree over alive rows (AE traffic)
SIG_IDENT_STALE = 1 << 7  # an identity view lags the sender's current word
SIG_KPR_LIVE = 1 << 8     # a carried KnownPeersRequest could fire (phase 0)
SIG_TOO_FEW = 1 << 9      # n_alive < 2

_OP_TERM_BITS = {"any_a2": SIG_ANY_A2, "any_join": SIG_ANY_JOIN}

# Bits any leap program (strict or hybrid) refuses: these name activity the
# span programs do not model. The hybrid program models armed-but-unexpired
# timers, disagreeing fingerprints and a live phase-0 ledger exactly
# (phasegraph/span.py), so those three bits stay leapable.
DENSE_BITS = (
    SIG_ANY_A2 | SIG_ANY_JOIN | SIG_WAIT_ALIVE | SIG_KNOWN_DEAD
    | SIG_MISSING | SIG_IDENT_STALE | SIG_TOO_FEW
)
HYBRID_BITS = SIG_ARMED | SIG_FP_DISAGREE | SIG_KPR_LIVE

_BUCKET_SHIFT = 16


@dataclasses.dataclass(frozen=True)
class ActivityClass:
    """Host-side decode of one signature fetch (one int32[4] per span).

    ``key`` is the memoization class: term bits | active-row-count bucket
    (power-of-two buckets, so heterogeneous activity levels share compiled
    programs within a bucket) — the second cache dimension of the warp
    runner's bounded program cache. ``mode`` is the engine the class maps
    to: ``"leap"`` (strictly quiescent — every bit clear), ``"hybrid"``
    (only hybrid-modelable bits set), or ``"dense"``.
    """

    key: int
    expiry: int
    n_alive: int
    tick: int

    @property
    def bits(self) -> int:
        return self.key & ((1 << _BUCKET_SHIFT) - 1)

    @property
    def bucket(self) -> int:
        return self.key >> _BUCKET_SHIFT

    @property
    def mode(self) -> str:
        if self.bits & DENSE_BITS:
            return "dense"
        return "leap" if self.bits == 0 else "hybrid"

    def describe(self) -> dict:
        """JSON-able decode (telemetry ledger / summarizer)."""
        names = {
            SIG_ANY_A2: "any_a2", SIG_ANY_JOIN: "any_join",
            SIG_ARMED: "armed", SIG_WAIT_ALIVE: "waiting_on_alive",
            SIG_KNOWN_DEAD: "known_dead", SIG_MISSING: "missing_alive",
            SIG_FP_DISAGREE: "fp_disagree", SIG_IDENT_STALE: "ident_stale",
            SIG_KPR_LIVE: "kpr_live", SIG_TOO_FEW: "too_few",
        }
        return {
            "key": self.key,
            "mode": self.mode,
            "terms": [v for b, v in names.items() if self.bits & b],
            "active_row_bucket": self.bucket,
        }


def decode_signature(row) -> ActivityClass:
    """``int32[4]`` fetch row -> :class:`ActivityClass`."""
    k, e, a, t = (int(x) for x in np.asarray(row))
    return ActivityClass(key=k, expiry=e, n_alive=a, tick=t)


@functools.lru_cache(maxsize=None)
def make_signature_fn(cfg: SwimConfig):
    """Jitted ``MeshState -> int32[4]``: the on-device activity signature.

    One reduction pass over (S, T) producing ``[class_key,
    earliest_expiry, n_alive, tick]`` — everything the warp runner needs
    to pick a span program and length in ONE scalar-row fetch per span
    decision. ``class_key`` packs the term bits (which phase-op activity
    terms fire — the planner-derived ``any_a2``/``any_join`` — plus the
    state-borne sterility terms) with the active-row count bucketed to
    powers of two. All-bits-clear is exactly :func:`make_quiescence_fn`'s
    predicate (pinned by tests/test_warp.py); the hybrid-modelable bits
    (armed / fp_disagree / kpr_live) admit the near-quiescent span program.
    """
    from kaboodle_tpu.phasegraph.graph import build_graph
    from kaboodle_tpu.phasegraph.plan import plan

    # The op-derived terms must stay inside this module's bit vocabulary.
    hybrid_prog = plan(build_graph(cfg, faulty=False), "hybrid")
    unknown = set(hybrid_prog.pred_terms) - set(_OP_TERM_BITS)
    if unknown:
        raise NotImplementedError(
            f"hybrid plan declares signature terms {sorted(unknown)} the "
            "activity signature does not measure — extend horizon.py's "
            "vocabulary before leaping past them"
        )

    def signature(st: MeshState) -> jax.Array:  # graftlint: traced
        S, T, alive = st.state, st.timer, st.alive
        n = S.shape[-1]
        idx = jnp.arange(n, dtype=jnp.int32)
        eye = idx[:, None] == idx[None, :]
        member = S > 0
        arow = alive[:, None]
        acol = alive[None, :]

        waiting = arow & member & (S != KNOWN)
        armed = jnp.any(waiting)
        wait_alive = jnp.any(waiting & acol)
        known_dead = jnp.any(arow & (S == KNOWN) & ~eye & ~acol)
        missing = jnp.any(arow & acol & ~member & ~eye)

        idv = st.id_view
        fp = membership_fingerprint(member, idv if idv is not None else st.identity)
        conv, _, _, n_alive = fingerprint_agreement(alive, fp)

        ident_stale = jnp.bool_(False)
        if idv is not None:
            ident_stale = jnp.any(arow & member & (idv != st.identity[None, :]))

        n_row = jnp.sum(member, axis=-1, dtype=jnp.int32)
        p = st.kpr_partner
        pc = jnp.clip(p, 0)
        kpr_live = jnp.any(
            (p >= 0)
            & alive[pc]
            & (st.kpr_fp != fp[pc])
            & (n_row[pc] <= st.kpr_n)
        )

        deadline = T.astype(jnp.int32) + jnp.int32(cfg.ping_timeout_ticks)
        expiry = jnp.min(jnp.where(waiting, deadline, _I32MAX))
        any_a2 = armed & (expiry <= st.tick)

        join_owed = jnp.bool_(False)
        if cfg.join_broadcast_enabled:
            # Conservative: a lonely row becomes rebroadcast-due at a
            # data-dependent tick, so loneliness itself forces dense.
            join_owed = jnp.any(alive & st.never_broadcast) | jnp.any(
                alive & (n_row <= 1)
            )

        def bit(flag, b):
            return jnp.where(flag, jnp.int32(b), jnp.int32(0))

        bits = (
            bit(any_a2, SIG_ANY_A2)
            | bit(join_owed, SIG_ANY_JOIN)
            | bit(armed, SIG_ARMED)
            | bit(wait_alive, SIG_WAIT_ALIVE)
            | bit(known_dead, SIG_KNOWN_DEAD)
            | bit(missing, SIG_MISSING)
            | bit(~conv, SIG_FP_DISAGREE)
            | bit(ident_stale, SIG_IDENT_STALE)
            | bit(kpr_live, SIG_KPR_LIVE)
            | bit(n_alive < 2, SIG_TOO_FEW)
        )

        # Active-row count, bucketed to powers of two: bucket b covers
        # (2^(b-2), 2^(b-1)] rows, bucket 0 = none. A cache key, not a
        # correctness input.
        cnt = jnp.sum(jnp.any(waiting, axis=-1), dtype=jnp.int32)
        bucket = jnp.int32(0)
        for j in [0] + [1 << e for e in range(31)]:
            bucket += jnp.where(cnt > j, jnp.int32(1), jnp.int32(0))

        key = bits | (bucket << _BUCKET_SHIFT)
        return jnp.stack([key, expiry, n_alive.astype(jnp.int32), st.tick])

    return jax.jit(signature)
