"""The warp leap kernel — a shim over the phase-graph derivation.

The leap implementation that lived here moved to
:mod:`kaboodle_tpu.phasegraph.span`, where it executes the op graph's
``span`` program (the quiescent-span derivation: invariant ops pruned by
the planner, the survivors batched as one k-tick scan — see
``kaboodle_tpu/phasegraph/__init__.py``). This module keeps the historical
import path for the warp runner, the registry, and the tests.
"""

from kaboodle_tpu.phasegraph.span import make_leap_fn

__all__ = ["make_leap_fn"]
