"""Warp runners: signature-classed fast-forward between dense ticks.

The dense runners (sim/runner.py, parallel/mesh.py, fleet/core.py) dispatch
one full tick kernel per simulated tick, whatever the tick does. These
runners split every run into *spans* bounded by the event horizon
(warp/horizon.py) and pick a span program by the entry state's **activity
signature** (Warp 2.0):

- class ``leap`` (every signature bit clear — strict quiescence): the span
  program (phasegraph/span.py) replays the whole event-free span in
  batched power-of-two dispatches, bit-exact with dense ticking.
- class ``hybrid`` (only the hybrid-modelable bits set: timers armed but
  not expiring in-span, fingerprints disagreeing, a live anti-entropy
  ledger — the churn-drain / near-quiescent regime): the hybrid program
  (the strict span + the sterile anti-entropy pass) leaps to
  ``min(next scheduled event, earliest timer expiry)``; the expiry tick
  itself and everything after re-classify.
- class ``dense`` (any other bit — a Join owed, a refutable suspicion, a
  Known-dead or missing-alive cell, a stale identity view): dense ticks,
  re-checking the signature every ``recheck_every`` ticks.

One signature fetch (a single int32[4] row) decides each span: class key,
earliest expiry, n_alive and the tick counter — the one-fetch-per-span
budget of the original runner, now carrying the whole decision.

**Bounded program cache**: compiled leap programs are memoized in an
explicit :class:`ProgramCache` keyed by (config family, strict/hybrid,
chunk length), where chunk lengths are restricted to powers of two at
least ``MIN_LEAP`` — a span of any length costs at most ``log2(span)``
cached dispatches plus up to ``MIN_LEAP - 1`` dense remainder ticks, and
the cache can hold at most ``len(CHUNK_BUCKETS)`` programs per family *by
construction* (the cache refuses non-bucket keys). graftscan's KB405
compile-surface budget gates the same set end to end; the zero-recompile
fuzz arm (tests/test_fuzz_parity.py) pins that a warmed run compiles
nothing fresh.

**Per-member fleet warp**: ``run_fleet_warped`` replaces the old
all-quiescent lockstep mask with per-member horizons — the signature is
vmapped over the ``[E]`` axis, every leapable member leaps to its OWN next
event inside one vmapped masked-span dispatch (phasegraph/span.py
``masked=True``: the span length is a traced per-member ``k_m``, members
past their horizon freeze), and only members in the dense class ride
dense ticks, with finished/leapable members frozen via
``fleet.core.freeze_members``. A heterogeneous ensemble no longer pays
the lockstep tax: one mid-boot member keeps only itself dense.
"""

from __future__ import annotations

import collections
import dataclasses
import functools
import hashlib

import jax
import jax.numpy as jnp
import numpy as np

from kaboodle_tpu.config import SwimConfig
from kaboodle_tpu.sim.kernel import make_tick_fn
from kaboodle_tpu.sim.runner import state_converged
from kaboodle_tpu.sim.state import MeshState, TickInputs, idle_inputs
from kaboodle_tpu.warp.horizon import (
    HYBRID_BITS,
    SIG_ANY_A2,
    SIG_WAIT_ALIVE,
    ActivityClass,
    decode_signature,
    make_quiescence_fn,
    make_signature_fn,
    next_static_event,
    static_event_ticks,
)
from kaboodle_tpu.warp.leap import make_leap_fn

# The compiled-chunk vocabulary: powers of two >= MIN_LEAP. A span's
# sub-MIN_LEAP remainder runs as dense ticks instead of compiling tiny leap
# programs, so the per-family program count is bounded by len(CHUNK_BUCKETS)
# — the ProgramCache refuses any other key.
MIN_LEAP = 8
CHUNK_BUCKETS = tuple(1 << j for j in range(3, 32))  # 8 .. 2^31


class ProgramCache:
    """Explicit bounded cache of compiled span programs.

    Keys are ``(family, kind, k)`` where ``family`` identifies the build
    (config, mesh, fleet-ness), ``kind`` is ``"strict"``/``"hybrid"`` and
    ``k`` MUST be a :data:`CHUNK_BUCKETS` power of two — enforcing the
    bound structurally: per (family, kind) the cache can never hold more
    than ``len(CHUNK_BUCKETS)`` programs, whatever span lengths the event
    schedule produces. KB405's compile-surface budget measures the same
    set from the outside; ``stats()`` exposes it from the inside (the
    warp2 dryrun asserts it).
    """

    def __init__(self) -> None:
        self._programs: dict = {}
        self.hits = 0
        self.misses = 0
        self._kind_stats: dict[str, list[int]] = {}  # kind -> [hits, misses]

    def get(self, family, kind: str, k: int, build):
        if k not in CHUNK_BUCKETS:
            raise ValueError(
                f"leap chunk {k} is not a power-of-two bucket >= {MIN_LEAP} "
                "— the program cache only admits bucketed span lengths"
            )
        key = (family, kind, k)
        kind_stats = self._kind_stats.setdefault(kind, [0, 0])
        prog = self._programs.get(key)
        if prog is not None:
            self.hits += 1
            kind_stats[0] += 1
            return prog
        self.misses += 1
        kind_stats[1] += 1
        prog = build()
        self._programs[key] = prog
        return prog

    def stats(self) -> dict:
        families = {}
        for family, kind, _k in self._programs:
            fam = families.setdefault(repr((family, kind)), 0)
            families[repr((family, kind))] = fam + 1
        per_kind = {
            kind: {
                "hits": h,
                "misses": m,
                "hit_rate": round(h / (h + m), 4) if h + m else 0.0,
            }
            for kind, (h, m) in sorted(self._kind_stats.items())
        }
        return {
            "programs": len(self._programs),
            "per_family_bound": len(CHUNK_BUCKETS),
            "max_family_programs": max(families.values(), default=0),
            "hits": self.hits,
            "misses": self.misses,
            "per_kind": per_kind,
        }

    def clear(self) -> None:
        self._programs.clear()
        self.hits = self.misses = 0
        self._kind_stats.clear()


leap_cache = ProgramCache()


# ---------------------------------------------------------------------------
# Warp 3.0: signature-keyed span memoization
#
# The counter-keyed RNG (phasegraph/rng.py) makes every span's effect a pure
# function of its entry state: the carried key plane is constant and each
# tick's draws derive from (key, tick, stream), so two spans entering the
# same state at the same tick compute the SAME exit state. SpanMemo exploits
# that purity — it caches the span's state *delta* (byte-XOR of entry vs
# exit leaves, exact for every dtype) keyed by the span identity (program
# family, engine kind, span length, ActivityClass key) plus blake2b digests
# of the entry state (and, for dense spans, the consumed input slice), and
# replays the delta when the same span recurs — across runs, fleet members
# and serve lanes. Replay is host XOR + one device_put per leaf: no
# dispatch, no compile, bit-identical exit state (the digest pins the entry
# bytes; XOR then reproduces the exit bytes exactly), so memo-on == memo-off
# is an invariant the dryrun bit-diffs. The legacy chain-keyed scheme could
# never do this: its key plane encoded the whole draw history, so no two
# spans ever re-entered the same state.


def _host_leaves(tree) -> list[np.ndarray]:
    """Pull a pytree's leaves to host (np views/copies, flatten order)."""
    return [np.asarray(x) for x in jax.tree.leaves(tree)]


def _digest_leaves(leaves) -> bytes:
    """blake2b-128 over the raw bytes of every leaf, in flatten order."""
    h = hashlib.blake2b(digest_size=16)
    for a in leaves:
        h.update(np.ascontiguousarray(a).tobytes())
    return h.digest()


def _xor_delta(entry: np.ndarray, exit_: np.ndarray) -> np.ndarray:
    """uint8 XOR of two same-shape arrays' raw bytes (exact, dtype-blind)."""
    a = np.frombuffer(np.ascontiguousarray(entry).tobytes(), np.uint8)
    b = np.frombuffer(np.ascontiguousarray(exit_).tobytes(), np.uint8)
    return a ^ b


def _apply_xor(entry: np.ndarray, delta: np.ndarray) -> np.ndarray:
    out = np.frombuffer(np.ascontiguousarray(entry).tobytes(), np.uint8) ^ delta
    return np.frombuffer(out.tobytes(), entry.dtype).reshape(entry.shape)


class SpanMemo:
    """Bounded LRU cache of span state-deltas, keyed by span signature.

    Entries: ``key -> (deltas, metrics, nbytes)`` where ``deltas`` is the
    per-leaf uint8 XOR of entry vs exit bytes and ``metrics`` an optional
    list of per-tick host metric pytrees a dense span must re-emit on
    replay. Both bounds are hard: inserting past ``max_bytes`` or
    ``max_entries`` evicts least-recently-used entries first (the warp3
    dryrun asserts the bound holds under churn). Per-kind hit/miss stats
    feed the WarpLedger summary, the serve MetricsRegistry gauges and the
    bench capture. Host-side only — a hit replays the exact exit bytes, so
    memo-on and memo-off runs are bit-identical by construction."""

    def __init__(self, max_bytes: int = 256 << 20, max_entries: int = 4096) -> None:
        self.max_bytes = int(max_bytes)
        self.max_entries = int(max_entries)
        self._entries: collections.OrderedDict = collections.OrderedDict()
        self.bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self._kind_stats: dict[str, list[int]] = {}  # kind -> [hits, misses]

    def get(self, key, kind: str = "span"):
        stats = self._kind_stats.setdefault(kind, [0, 0])
        hit = self._entries.get(key)
        if hit is None:
            self.misses += 1
            stats[1] += 1
            return None
        self._entries.move_to_end(key)
        self.hits += 1
        stats[0] += 1
        return hit[0]

    def put(self, key, value, nbytes: int) -> None:
        if key in self._entries or nbytes > self.max_bytes:
            return
        self._entries[key] = (value, int(nbytes))
        self.bytes += int(nbytes)
        while self._entries and (
            self.bytes > self.max_bytes or len(self._entries) > self.max_entries
        ):
            _, (_, old) = self._entries.popitem(last=False)
            self.bytes -= old
            self.evictions += 1

    def stats(self) -> dict:
        total = self.hits + self.misses
        per_kind = {
            kind: {
                "hits": h,
                "misses": m,
                "hit_rate": round(h / (h + m), 4) if h + m else 0.0,
            }
            for kind, (h, m) in sorted(self._kind_stats.items())
        }
        return {
            "entries": len(self._entries),
            "bytes": self.bytes,
            "max_bytes": self.max_bytes,
            "max_entries": self.max_entries,
            "hits": self.hits,
            "misses": self.misses,
            "evictions": self.evictions,
            "hit_rate": round(self.hits / total, 4) if total else 0.0,
            "per_kind": per_kind,
        }

    def clear(self) -> None:
        self._entries.clear()
        self.bytes = 0
        self.hits = self.misses = self.evictions = 0
        self._kind_stats.clear()


# The default shared instance: CLI runs, the serve engine and the bench
# arms all hit one cache, which is what lets a serve lane replay a drain
# another lane (or an earlier run in the same process) already computed.
span_memo = SpanMemo()


def _memo_store(memo: SpanMemo, key, entry_leaves, exit_state, metrics=None) -> None:
    """Bank one span: per-leaf XOR deltas (+ optional per-tick metrics)."""
    exit_leaves = _host_leaves(exit_state)
    deltas = [_xor_delta(a, b) for a, b in zip(entry_leaves, exit_leaves)]
    nbytes = sum(d.nbytes for d in deltas)
    if metrics is not None:
        nbytes += sum(
            int(l.nbytes) for m in metrics for l in jax.tree.leaves(m)
        )
    memo.put(key, (deltas, metrics), nbytes)


def _memo_replay(state, entry_leaves, deltas):
    """Rebuild the exit state from the entry leaves + banked deltas."""
    leaves, treedef = jax.tree.flatten(state)
    new = [
        jnp.asarray(_apply_xor(a, d)) for a, d in zip(entry_leaves, deltas)
    ]
    assert len(new) == len(leaves)
    return jax.tree.unflatten(treedef, new)


def _span_chunks(k: int) -> tuple[list[int], int]:
    """``(chunks, dense_remainder)``: power-of-two decomposition of a span.

    Chunks are the binary decomposition of ``k`` restricted to
    >= ``MIN_LEAP``; the remainder (< ``MIN_LEAP``) is returned for the
    caller to run dense. Leap composition is exact (``leap(a)`` then
    ``leap(b)`` is bit-equal to ``leap(a + b)`` — the key chain, timers
    and ledger thread through), so the split never changes the result."""
    chunks: list[int] = []
    rem = int(k)
    while rem >= MIN_LEAP:
        p = 1 << (rem.bit_length() - 1)
        chunks.append(p)
        rem -= p
    return chunks, rem


@functools.lru_cache(maxsize=None)
def _dense_tick(cfg: SwimConfig, faulty: bool, mesh=None, telemetry: bool = False):
    if mesh is None:
        return jax.jit(make_tick_fn(cfg, faulty=faulty, telemetry=telemetry))
    from kaboodle_tpu.parallel.mesh import make_sharded_tick

    return jax.jit(make_sharded_tick(cfg, mesh, faulty=faulty, telemetry=telemetry))


def _get_leap(cfg: SwimConfig, k: int, mesh, hybrid: bool):
    """One bucketed span program through the bounded cache."""
    kind = "hybrid" if hybrid else "strict"

    def build():
        if mesh is None:
            return jax.jit(make_leap_fn(cfg, k, hybrid=hybrid))
        from kaboodle_tpu.parallel.mesh import constrain_state, row_matrix_sharding

        sharding = row_matrix_sharding(mesh)

        def constrain(x):
            return jax.lax.with_sharding_constraint(x, sharding)

        leap = make_leap_fn(cfg, k, constrain=constrain, hybrid=hybrid)

        def sharded_leap(st: MeshState) -> MeshState:
            return constrain_state(leap(st), mesh)

        return jax.jit(sharded_leap)

    return leap_cache.get((cfg, mesh), kind, k, build)


@functools.lru_cache(maxsize=None)
def _converged(mesh=None):
    if mesh is None:
        return jax.jit(state_converged)

    def check(st: MeshState):
        from kaboodle_tpu.parallel.mesh import sharded_convergence_check

        return sharded_convergence_check(st)[0]

    return check


def _slice_tick(inputs: TickInputs, t: int) -> TickInputs:
    return jax.tree.map(lambda x: x[t], inputs)


@dataclasses.dataclass
class WarpLedger:
    """Host-side span ledger: what leaped, under which signature class.

    Filled by the runners when passed as ``ledger=``; feeds the telemetry
    summarizer's per-class leap counters and the bench arms' span
    accounting. ``spans`` rows: dict(engine, class_key, class, ticks,
    dispatches).

    **Why-dense attribution** (the evidence base for ROADMAP item 2's RNG
    re-keying): every dense span is also attributed to the signature
    terms that forced it — ``blocked`` rows name the blocking term combo
    (``decode_signature`` names the terms), plus two pseudo-terms the
    signature cannot see: ``scheduled_event`` (the schedule itself made
    the tick dense — recorded WITHOUT a signature fetch, preserving the
    one-fetch-per-span budget) and ``short_span`` (a leapable class whose
    budget was under ``MIN_LEAP``). The histogram is exact by
    construction: summed ``ticks`` equal the dense ticks executed.
    Host-side only — recording never changes what dispatches, so ledger
    on/off runs stay bit-identical."""

    spans: list = dataclasses.field(default_factory=list)
    blocked: list = dataclasses.field(default_factory=list)

    def record(self, cls: ActivityClass, engine: str, ticks: int, dispatches: int) -> None:
        self.spans.append({
            "engine": engine,
            "class_key": cls.key,
            "class": cls.describe(),
            "ticks": int(ticks),
            "dispatches": int(dispatches),
        })

    def record_blocked(
        self,
        cls: ActivityClass | None,
        ticks: int,
        engine: str,
        mode: str = "dense",
        members: int = 1,
    ) -> None:
        """One dense span: which term kept it off the leap path.

        ``cls=None`` marks an eventful tick (no signature was fetched);
        a leapable ``mode`` marks a budget under ``MIN_LEAP``."""
        if cls is None:
            term, key = "scheduled_event", -1
        elif mode != "dense":
            term, key = "short_span", cls.key
        else:
            term, key = "+".join(cls.describe()["terms"]), cls.key
        self.blocked.append({
            "engine": engine,
            "term": term,
            "class_key": key,
            "ticks": int(ticks),
            "spans": 1,
            "members": int(members),
        })

    def blocked_histogram(self) -> dict:
        """``{term: {spans, ticks, members}}`` — the why-dense histogram."""
        out: dict = {}
        for row in self.blocked:
            agg = out.setdefault(
                row["term"], {"spans": 0, "ticks": 0, "members": 0}
            )
            agg["spans"] += row["spans"]
            agg["ticks"] += row["ticks"]
            agg["members"] += row["members"]
        return out

    def per_class(self) -> dict:
        """``{class_key: {engine, terms, spans, ticks, dispatches}}`` totals."""
        out: dict = {}
        for row in self.spans:
            agg = out.setdefault(
                row["class_key"],
                {
                    "engine": row["engine"],
                    "terms": row["class"]["terms"],
                    "active_row_bucket": row["class"]["active_row_bucket"],
                    "spans": 0,
                    "ticks": 0,
                    "dispatches": 0,
                },
            )
            agg["spans"] += 1
            agg["ticks"] += row["ticks"]
            agg["dispatches"] += row["dispatches"]
        return out


# Modes the runners accept. ``exact`` is the default and bit-exact with
# dense ticking. ``distributional`` (Warp 3.0) additionally admits classes
# with live A2 escalation / wait-alive activity to the hybrid program:
# their in-span escalation side effects are approximated by the hybrid
# model (pings delivered and acked), while every timer-expiry tick still
# runs dense, so suspicion maturation, death marking and gossip all happen
# — at statistically, not bit-wise, identical ticks. Pinned by its own
# fuzz arm (convergence-tick band + steady counter means), never by the
# bit-diff suites.
WARP_MODES = ("exact", "distributional")
_DISTRIBUTIONAL_BITS = HYBRID_BITS | SIG_ANY_A2 | SIG_WAIT_ALIVE


def _check_warp_mode(warp_mode: str) -> None:
    if warp_mode not in WARP_MODES:
        raise ValueError(f"warp_mode {warp_mode!r} not in {WARP_MODES}")


def _classify(
    cls: ActivityClass,
    hybrid: bool,
    telemetry: bool = False,
    warp_mode: str = "exact",
) -> str:
    """The engine a span entry state maps to, under the runner's knobs.

    ``hybrid=False`` (the Warp 1.x behavior knob) demotes hybrid-class
    states to dense; telemetry mode does too — hybrid spans carry
    data-dependent anti-entropy gossip bytes with no closed form, so exact
    counter totals require measuring those ticks densely (strictly
    quiescent spans keep the ``leap_counters`` closed form).
    ``warp_mode="distributional"`` promotes dense classes whose only
    extra activity is A2 escalation / wait-alive traffic to the hybrid
    program (module comment above) — classes carrying joins, known-dead
    or missing-alive cells, identity staleness or too-few-known rows stay
    dense in every mode (the hybrid model cannot even approximate their
    effects without stalling convergence)."""
    mode = cls.mode
    if (
        warp_mode == "distributional"
        and mode == "dense"
        and cls.bits
        and not (cls.bits & ~_DISTRIBUTIONAL_BITS)
    ):
        mode = "hybrid"
    if mode == "hybrid" and (not hybrid or telemetry):
        return "dense"
    return mode


def _leap_budget(cls: ActivityClass, mode: str, span: int) -> int:
    """How many of the next ``span`` ticks the class's program may cover."""
    if mode == "leap":
        return span
    return max(0, min(span, cls.expiry - cls.tick))


def simulate_warped(
    state: MeshState,
    inputs: TickInputs,
    cfg: SwimConfig,
    faulty: bool = True,
    recheck_every: int = 16,
    mesh=None,
    on_boundary=None,
    telemetry: bool = False,
    hybrid: bool = True,
    ledger: WarpLedger | None = None,
    memo: SpanMemo | None = None,
    warp_mode: str = "exact",
):
    """Run a stacked ``[T]`` schedule, fast-forwarding (near-)quiescent spans.

    The twin of :func:`kaboodle_tpu.sim.runner.simulate`: same state, same
    schedule, bit-identical final state — but event-free spans whose entry
    signature classes as ``leap`` or ``hybrid`` advance through the span
    programs in bucketed batched dispatches (module docstring). Everything
    else runs dense, re-checking the signature every ``recheck_every``
    ticks. ``mesh`` selects the sharded twins for both the dense ticks and
    the leaps; ``hybrid=False`` restores the strict-quiescence-only
    behavior (the ``--no-warp-hybrid`` CLI knob).

    Returns ``(final_state, dense_ticks, dense_metrics)``: the int32 ``[M]``
    indices of the ticks that executed densely and their stacked
    ``TickMetrics`` (``None`` when every tick leaped). ``on_boundary(t,
    state)``, when given, is called at each leap's entry and exit boundary
    with the tick index about to run / just reached — the hook the parity
    fuzz uses to pin state equality at every event-horizon boundary.
    ``ledger``, when given, accumulates per-span class records
    (:class:`WarpLedger` — the per-class leap counters surface).

    ``telemetry=True`` runs the telemetry-plane dense tick and returns a
    4-tuple ``(final_state, dense_ticks, dense_telemetry, totals)``:
    ``dense_telemetry`` is the densely-executed ticks' stacked
    ``TickTelemetry`` (``None`` if everything leaped) and ``totals`` the
    whole run's ``ProtocolCounters`` sums — dense counters summed plus each
    strictly-quiescent leaped span's closed form
    (``telemetry.counters.leap_counters``: ``k * n_alive`` pings/acks, all
    else zero). Hybrid-class spans run dense under telemetry (their
    anti-entropy gossip bytes have no closed form), so totals stay exact;
    ``n_alive`` rides the signature fetch, keeping the one-fetch-per-span
    budget.

    ``memo``, when given, banks every span's state delta in the
    :class:`SpanMemo` and replays recurring spans (leaped AND dense) from
    it — bit-identical exit states and re-emitted dense metrics, no
    dispatches. ``warp_mode="distributional"`` widens the hybrid class
    (see :func:`_classify`); the default ``"exact"`` stays bit-exact with
    dense ticking.
    """
    from kaboodle_tpu.telemetry.counters import counters_totals, leap_counters
    from kaboodle_tpu.telemetry.trace import host_span

    _check_warp_mode(warp_mode)
    T = int(np.asarray(inputs.kill).shape[0])
    eventful = static_event_ticks(inputs)
    tick = _dense_tick(cfg, faulty, mesh, telemetry)
    signature = make_signature_fn(cfg)
    recheck_every = max(1, int(recheck_every))
    family = repr((cfg, mesh, faulty, telemetry, warp_mode))
    dense_ticks: list[int] = []
    metrics = []
    leap_spans: list[tuple[int, int]] = []  # (span length, n_alive)
    t = 0
    while t < T:
        if not eventful[t]:
            span_end = next_static_event(eventful, t)
            cls = decode_signature(signature(state))
            mode = _classify(cls, hybrid, telemetry, warp_mode)
            k = _leap_budget(cls, mode, span_end - t) if mode != "dense" else 0
            chunks, rem = _span_chunks(k)
            if chunks:
                k -= rem  # the sub-MIN_LEAP tail re-enters the loop densely
                if on_boundary is not None:
                    on_boundary(t, state)
                if telemetry:
                    leap_spans.append((k, cls.n_alive))
                entry_leaves = memo_key = None
                if memo is not None:
                    entry_leaves = _host_leaves(state)
                    memo_key = (
                        "leap", family, mode, k, cls.key,
                        _digest_leaves(entry_leaves),
                    )
                    hit = memo.get(memo_key, kind=mode)
                    if hit is not None:
                        state = _memo_replay(state, entry_leaves, hit[0])
                        if ledger is not None:
                            ledger.record(cls, mode + "+memo", k, 0)
                        t += k
                        if on_boundary is not None:
                            on_boundary(t, state)
                        continue
                with host_span(f"leap_span:{mode}:{k}"):
                    for chunk in chunks:
                        state = _get_leap(cfg, chunk, mesh, mode == "hybrid")(state)
                if memo is not None:
                    _memo_store(memo, memo_key, entry_leaves, state)
                if ledger is not None:
                    ledger.record(cls, mode, k, len(chunks))
                t += k
                if on_boundary is not None:
                    on_boundary(t, state)
                continue
            stop = min(span_end, t + recheck_every)
            blocked_cls = cls
        else:
            stop = t + 1
            cls, mode = None, "dense"
            blocked_cls = None
        if memo is not None and not telemetry:
            # Dense spans memoize too (the Warp 3.0 point: the counter
            # keys make even a drain season's dense quantum a pure
            # function of its entry state + input slice). The key folds
            # in the consumed schedule slice so eventful ticks and
            # differing drop/churn planes never collide.
            entry_leaves = _host_leaves(state)
            in_slice = jax.tree.map(lambda x: x[t:stop], inputs)
            memo_key = (
                "dense", family, stop - t,
                cls.key if cls is not None else -1,
                _digest_leaves(entry_leaves),
                _digest_leaves(_host_leaves(in_slice)),
            )
            hit = memo.get(memo_key, kind="dense")
            if hit is not None:
                state = _memo_replay(state, entry_leaves, hit[0])
                dense_ticks.extend(range(t, stop))
                metrics.extend(hit[1])
                if ledger is not None:
                    # A replayed dense span is NOT blocked — the memo
                    # covered it without a single dense dispatch. The
                    # why-dense histogram shrinks by exactly these rows.
                    if blocked_cls is not None:
                        ledger.record(blocked_cls, "dense+memo", stop - t, 0)
                    else:
                        ledger.spans.append({
                            "engine": "dense+memo",
                            "class_key": -1,
                            "class": {"terms": ["scheduled_event"],
                                      "active_row_bucket": -1},
                            "ticks": stop - t,
                            "dispatches": 0,
                        })
                t = stop
                continue
            span_metrics: list = []
            with host_span("dense_span"):
                while t < stop:
                    state, m = tick(state, _slice_tick(inputs, t))
                    dense_ticks.append(t)
                    mh = jax.tree.map(np.asarray, m)
                    metrics.append(mh)
                    span_metrics.append(mh)
                    t += 1
            _memo_store(memo, memo_key, entry_leaves, state, span_metrics)
            if ledger is not None:
                if blocked_cls is not None:
                    ledger.record_blocked(
                        blocked_cls, len(span_metrics), "sim", mode=mode
                    )
                else:
                    ledger.record_blocked(None, 1, "sim")
            continue
        if ledger is not None:
            if blocked_cls is not None:
                ledger.record_blocked(blocked_cls, stop - t, "sim", mode=mode)
            else:
                # Eventful tick: the schedule forced it dense — no
                # signature fetch (the one-fetch-per-span budget holds).
                ledger.record_blocked(None, 1, "sim")
        with host_span("dense_span"):
            while t < stop:
                state, m = tick(state, _slice_tick(inputs, t))
                dense_ticks.append(t)
                metrics.append(m)
                t += 1
    stacked = (
        jax.tree.map(lambda *xs: jnp.stack(xs), *metrics) if metrics else None
    )
    if not telemetry:
        return state, np.asarray(dense_ticks, dtype=np.int32), stacked
    totals = (
        counters_totals(stacked.counters)
        if stacked is not None
        else counters_totals(leap_counters(0, 0))
    )
    for k, n_alive in leap_spans:
        leap = counters_totals(leap_counters(n_alive, k))
        totals = {name: totals[name] + leap[name] for name in totals}
    return state, np.asarray(dense_ticks, dtype=np.int32), stacked, totals


def run_warped(
    state: MeshState,
    cfg: SwimConfig,
    ticks: int,
    recheck_every: int = 16,
    mesh=None,
    hybrid: bool = True,
    ledger: WarpLedger | None = None,
    memo: SpanMemo | None = None,
    warp_mode: str = "exact",
):
    """Advance a fault-free mesh exactly ``ticks`` ticks, leaping spans.

    The steady-state-service entry point: a converged idle mesh leaps the
    whole budget in bucketed dispatches; a near-quiescent one (armed
    timers draining, fingerprints settling) leaps to each successive
    timer-expiry horizon through the hybrid program and runs only the
    expiry ticks densely; anything else runs dense (re-checking the
    signature every ``recheck_every`` ticks) until its class improves.
    Returns ``(state, ticks_run, converged)`` — the ``run_until_converged``
    contract, with ``ticks_run == ticks`` always (the budget is exact, not
    a bound) and ``converged`` evaluated on the final state.
    """
    _check_warp_mode(warp_mode)
    tick = _dense_tick(cfg, False, mesh)
    signature = make_signature_fn(cfg)
    idle = idle_inputs(state.n)
    recheck_every = max(1, int(recheck_every))
    family = repr((cfg, mesh, "steady", warp_mode))
    t = 0
    while t < ticks:
        cls = decode_signature(signature(state))
        mode = _classify(cls, hybrid, warp_mode=warp_mode)
        k = _leap_budget(cls, mode, ticks - t) if mode != "dense" else 0
        chunks, rem = _span_chunks(k)
        if chunks:
            entry_leaves = memo_key = None
            if memo is not None:
                entry_leaves = _host_leaves(state)
                memo_key = (
                    "leap", family, mode, k - rem, cls.key,
                    _digest_leaves(entry_leaves),
                )
                hit = memo.get(memo_key, kind=mode)
                if hit is not None:
                    state = _memo_replay(state, entry_leaves, hit[0])
                    if ledger is not None:
                        ledger.record(cls, mode + "+memo", k - rem, 0)
                    t += k - rem
                    continue
            for chunk in chunks:
                state = _get_leap(cfg, chunk, mesh, mode == "hybrid")(state)
            if memo is not None:
                _memo_store(memo, memo_key, entry_leaves, state)
            if ledger is not None:
                ledger.record(cls, mode, k - rem, len(chunks))
            t += k - rem
            continue
        stop = min(ticks, t + recheck_every)
        if memo is not None:
            # Idle-input dense window: the schedule is constant, so the
            # entry state alone keys the span.
            entry_leaves = _host_leaves(state)
            memo_key = (
                "dense", family, stop - t, cls.key,
                _digest_leaves(entry_leaves),
            )
            hit = memo.get(memo_key, kind="dense")
            if hit is not None:
                state = _memo_replay(state, entry_leaves, hit[0])
                if ledger is not None:
                    ledger.record(cls, "dense+memo", stop - t, 0)
                t = stop
                continue
            steps = stop - t
            while t < stop:
                state, _ = tick(state, idle)
                t += 1
            _memo_store(memo, memo_key, entry_leaves, state)
            if ledger is not None:
                ledger.record_blocked(cls, steps, "steady", mode=mode)
            continue
        if ledger is not None:
            ledger.record_blocked(cls, stop - t, "steady", mode=mode)
        while t < stop:
            state, _ = tick(state, idle)
            t += 1
    return state, jnp.int32(t), _converged(mesh)(state)


# ---------------------------------------------------------------------------
# fleet integration: per-member horizons


@functools.lru_cache(maxsize=None)
def _fleet_quiescent(cfg: SwimConfig):
    return jax.jit(jax.vmap(make_quiescence_fn(cfg)))


@functools.lru_cache(maxsize=None)
def _fleet_signature(cfg: SwimConfig):
    return jax.jit(jax.vmap(make_signature_fn(cfg)))


@functools.lru_cache(maxsize=None)
def _fleet_converged():
    return jax.jit(jax.vmap(state_converged))


@functools.lru_cache(maxsize=None)
def _masked_fleet_tick(cfg: SwimConfig):
    from kaboodle_tpu.fleet.core import freeze_members, make_fleet_tick_fn

    vtick = make_fleet_tick_fn(cfg, faulty=False)

    def step(mesh, idle, active):
        new, _ = vtick(mesh, idle)
        return freeze_members(active, mesh, new)

    return jax.jit(step)


def _get_fleet_leap(cfg: SwimConfig, K: int):
    """The vmapped masked hybrid span program, through the bounded cache.

    ONE compiled program covers every per-member span length ``k_m <= K``
    (the length is traced — phasegraph/span.py ``masked=True``), and the
    hybrid program degenerates bit-exactly to the strict one on strictly
    quiescent members, so a single (cfg, K) entry serves the whole
    signature-class mix of an ensemble round."""

    def build():
        return jax.jit(jax.vmap(make_leap_fn(cfg, K, hybrid=True, masked=True)))

    return leap_cache.get((cfg, "fleet"), "hybrid", K, build)


def memo_fleet_leap(
    family: str,
    mesh_state,
    k_m: np.ndarray,
    memo: SpanMemo,
    dispatch,
) -> tuple:
    """One masked fleet/serve leap round through the span memo.

    Deltas are banked PER MEMBER — keyed by the member's own ``k_m`` and
    entry-row digest — so a drain one lane computed is a hit for every
    other lane (and every later round) entering the same member state.
    The masked dispatch is all-or-nothing, so it is skipped only when
    every active member hits (the cross-lane steady state); on a partial
    hit the round still dispatches once and banks the fresh members'
    deltas. Members at ``k_m == 0`` are untouched by the masked program
    and never keyed. Returns ``(new_mesh_state, hit_members,
    dispatched)``."""
    leaves, treedef = jax.tree.flatten(mesh_state)
    host = [np.asarray(x) for x in leaves]
    active = [e for e in range(len(k_m)) if k_m[e] > 0]
    keys: dict[int, tuple] = {}
    hits: dict[int, tuple] = {}
    for e in active:
        key = (
            "fleet", family, int(k_m[e]),
            _digest_leaves([h[e] for h in host]),
        )
        keys[e] = key
        hit = memo.get(key, kind="fleet")
        if hit is not None:
            hits[e] = hit
    if active and len(hits) == len(active):
        new_host = [h.copy() for h in host]
        for e, (deltas, _) in hits.items():
            for i, d in enumerate(deltas):
                new_host[i][e] = _apply_xor(host[i][e], d)
        new_leaves = [jnp.asarray(h) for h in new_host]
        return jax.tree.unflatten(treedef, new_leaves), len(hits), False
    out = dispatch(mesh_state, jnp.asarray(k_m, dtype=jnp.int32))
    out_host = [np.asarray(x) for x in jax.tree.leaves(out)]
    for e in active:
        if e in hits:
            continue
        deltas = [
            _xor_delta(a[e], b[e]) for a, b in zip(host, out_host)
        ]
        memo.put(keys[e], (deltas, None), sum(d.nbytes for d in deltas))
    return out, len(hits), True


def fleet_quiescence_mask(fleet, cfg: SwimConfig) -> jax.Array:
    """bool ``[E]``: per-member strict event horizon (Warp 1.x surface).

    The quiescence predicate vmapped over the ensemble axis; computed
    on-device, one bool per member. Superseded by
    :func:`fleet_signature` for the per-member runner but kept as the
    cheap "who could leap right now" probe."""
    return _fleet_quiescent(cfg)(fleet.mesh)


def fleet_signature(fleet, cfg: SwimConfig) -> jax.Array:
    """int32 ``[E, 4]``: per-member activity signature rows.

    Each row is ``[class_key, earliest_expiry, n_alive, tick]`` —
    everything the per-member warp loop needs, one fetch per round."""
    return _fleet_signature(cfg)(fleet.mesh)


def run_fleet_warped(
    fleet,
    cfg: SwimConfig,
    ticks: int,
    recheck_every: int = 16,
    hybrid: bool = True,
    ledger: WarpLedger | None = None,
    memo: SpanMemo | None = None,
    warp_mode: str = "exact",
):
    """Advance every fleet member exactly ``ticks`` fault-free ticks.

    Per-member horizons (Warp 2.0): each round fetches the vmapped
    signature rows and computes, per member, how far it may leap — its
    remaining budget for strictly-quiescent members, its own
    timer-expiry horizon for hybrid-class members, zero for dense-class
    or finished members. If anyone can cover at least ``MIN_LEAP`` ticks,
    the whole ensemble enters ONE vmapped masked-span dispatch in which
    every member leaps exactly its own ``k_m`` (members at ``k_m == 0``
    freeze bit-exactly); otherwise the unfinished members ride dense
    ticks with everyone else frozen (``fleet.core.freeze_members``). The
    old all-quiescent lockstep mask — where a single mid-boot member
    forced the entire ensemble dense — is gone; dense ticks are only paid
    by the members that need them.

    Fault-free only (the span programs' precondition): the per-member
    ``drop_rate`` knob is inert here, exactly as in
    ``run_fleet_until_converged``'s default mode. Returns
    ``(fleet, ticks_run, converged)`` with ``converged`` a per-member
    ``[E]`` bool of the final states; member trajectories are bit-exact
    with standalone :func:`run_warped` runs (tests/test_warp.py).
    """
    from kaboodle_tpu.fleet.core import fleet_idle_inputs

    _check_warp_mode(warp_mode)
    mesh_state = fleet.mesh
    ensemble = fleet.ensemble
    idle = fleet_idle_inputs(fleet.n, ensemble)
    recheck_every = max(1, int(recheck_every))
    family = repr((cfg, "fleet", warp_mode))
    target = None
    while True:
        rows = np.asarray(_fleet_signature(cfg)(mesh_state))  # one [E, 4] fetch
        t_m = rows[:, 3].astype(np.int64)
        if target is None:
            target = t_m + int(ticks)
        remaining = target - t_m
        if (remaining <= 0).all():
            break
        k_m = np.zeros((ensemble,), dtype=np.int64)
        classes = [decode_signature(rows[e]) for e in range(ensemble)]
        for e, cls in enumerate(classes):
            if remaining[e] <= 0:
                continue
            mode = _classify(cls, hybrid, warp_mode=warp_mode)
            if mode != "dense":
                k_m[e] = _leap_budget(cls, mode, int(remaining[e]))
        if k_m.max() >= MIN_LEAP:
            # One vmapped masked dispatch: everyone leaps its own horizon
            # (including sub-MIN_LEAP free riders — they share the program).
            K = 1 << int(k_m.max() - 1).bit_length()
            K = max(K, MIN_LEAP)
            if memo is not None:
                mesh_state, _, dispatched = memo_fleet_leap(
                    family, mesh_state, k_m, memo,
                    _get_fleet_leap(cfg, K),
                )
            else:
                mesh_state = _get_fleet_leap(cfg, K)(
                    mesh_state, jnp.asarray(k_m, dtype=jnp.int32)
                )
                dispatched = True
            if ledger is not None:
                # The whole round is ONE vmapped dispatch: record one row
                # per signature class present among the leapers (ticks
                # summed over that class's members), each carrying the
                # round's single dispatch — never one dispatch per member.
                per_round: dict[int, list] = {}
                for e, cls in enumerate(classes):
                    if k_m[e] > 0:
                        row = per_round.setdefault(cls.key, [cls, 0, 0])
                        row[1] += int(k_m[e])
                        row[2] += 1
                for cls, ticks_sum, members in per_round.values():
                    engine = "fleet-" + _classify(cls, hybrid, warp_mode=warp_mode)
                    ledger.spans.append({
                        "engine": engine if dispatched else engine + "+memo",
                        "class_key": cls.key,
                        "class": cls.describe(),
                        "ticks": ticks_sum,
                        "dispatches": 1 if dispatched else 0,
                        "members": members,
                    })
            continue
        # Nobody can leap a full chunk: dense ticks for unfinished members
        # (leapable-but-short members ride along — dense is bit-identical
        # for them), everyone else frozen.
        steps = int(min(recheck_every, remaining[remaining > 0].min()))
        active = jnp.asarray(remaining > 0)
        if ledger is not None:
            # Attribute the dense round per blocking class: every active
            # member pays ``steps`` dense ticks, aggregated over the class
            # mix (leapable-but-short free riders land on ``short_span``).
            per_round: dict = {}
            for e, cls in enumerate(classes):
                if remaining[e] <= 0:
                    continue
                mode = _classify(cls, hybrid, warp_mode=warp_mode)
                row = per_round.setdefault((cls.key, mode), [cls, mode, 0])
                row[2] += 1
            for cls, mode, members in per_round.values():
                ledger.record_blocked(
                    cls, steps * members, "fleet", mode=mode, members=members
                )
        for _ in range(steps):
            mesh_state = _masked_fleet_tick(cfg)(mesh_state, idle, active)
    converged = _fleet_converged()(mesh_state)
    return dataclasses.replace(fleet, mesh=mesh_state), jnp.int32(ticks), converged
