"""Warp runners: interleave event-horizon leaps with dense ticks.

The dense runners (sim/runner.py, parallel/mesh.py, fleet/core.py) dispatch
one full tick kernel per simulated tick, whatever the tick does. These
runners split every run into *spans* bounded by the event horizon
(warp/horizon.py): a span whose entry state is quiescent and whose schedule
carries no events is replayed by the leap kernel (warp/leap.py) in one
batched dispatch — bit-exact with dense ticking — and everything else runs
dense. The contract mirrors the dense entry points:

- :func:`simulate_warped` — the ``simulate`` twin over a stacked schedule;
  returns the final state plus the metrics of exactly the densely-executed
  ticks (leaped ticks have provably constant metrics — converged, full
  agreement, ``2 * n_alive`` unicasts — so nothing is lost).
- :func:`run_warped` — advance a fault-free mesh exactly ``ticks`` ticks;
  ``(state, ticks_run, converged)``, the converge-loop contract.
- Both accept ``mesh=`` to run the sharded twins: dense ticks through
  ``parallel.make_sharded_tick``, the leap with its scan carries pinned to
  the same GSPMD row layout (``parallel.row_matrix_sharding`` /
  ``parallel.constrain_state``).
- :func:`fleet_quiescence_mask` / :func:`run_fleet_warped` — the ensemble
  integration: the horizon predicate vmapped over the ``[E]`` axis gives a
  per-member mask; while EVERY member is quiescent the whole fleet leaps as
  one vmapped program (each member under its own key chain and timers —
  independent leaps inside one dispatch). A mixed fleet runs dense for
  everyone: under ``vmap`` a per-member branch batches to a select that
  executes both sides, so skipping work for a subset is impossible — the
  lockstep price of batching already documented in fleet/core.py; dense is
  bit-identical for the quiescent members, so nothing diverges.

Spans leap in power-of-two chunks (``_span_chunks``): leap composition is
exact (``leap(a)`` then ``leap(b)`` is bit-equal to ``leap(a + b)`` — the
key chain and timer carry thread through), so a span of any length costs at
most ``log2(span)`` cached dispatches while the compiled-program cache stays
bounded at O(log max_span) entries per config instead of one program per
distinct span length. Dense single-tick programs are cached per config. The
host drives span selection (span lengths are data-dependent); every
decision fetch is one scalar per span, not per tick.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from kaboodle_tpu.config import SwimConfig
from kaboodle_tpu.sim.kernel import make_tick_fn
from kaboodle_tpu.sim.runner import state_converged
from kaboodle_tpu.sim.state import MeshState, TickInputs, idle_inputs
from kaboodle_tpu.warp.horizon import (
    make_quiescence_fn,
    next_static_event,
    static_event_ticks,
)
from kaboodle_tpu.warp.leap import make_leap_fn


@functools.lru_cache(maxsize=None)
def _dense_tick(cfg: SwimConfig, faulty: bool, mesh=None, telemetry: bool = False):
    if mesh is None:
        return jax.jit(make_tick_fn(cfg, faulty=faulty, telemetry=telemetry))
    from kaboodle_tpu.parallel.mesh import make_sharded_tick

    return jax.jit(make_sharded_tick(cfg, mesh, faulty=faulty, telemetry=telemetry))


@functools.lru_cache(maxsize=None)
def _alive_count():
    return jax.jit(lambda st: jnp.sum(st.alive, dtype=jnp.int32))


@functools.lru_cache(maxsize=None)
def _leap(cfg: SwimConfig, k: int, mesh=None):
    if mesh is None:
        return jax.jit(make_leap_fn(cfg, k))
    from kaboodle_tpu.parallel.mesh import constrain_state, row_matrix_sharding

    sharding = row_matrix_sharding(mesh)

    def constrain(x):
        return jax.lax.with_sharding_constraint(x, sharding)

    leap = make_leap_fn(cfg, k, constrain=constrain)

    def sharded_leap(st: MeshState) -> MeshState:
        return constrain_state(leap(st), mesh)

    return jax.jit(sharded_leap)


@functools.lru_cache(maxsize=None)
def _converged(mesh=None):
    if mesh is None:
        return jax.jit(state_converged)

    def check(st: MeshState):
        from kaboodle_tpu.parallel.mesh import sharded_convergence_check

        return sharded_convergence_check(st)[0]

    return check


def _slice_tick(inputs: TickInputs, t: int) -> TickInputs:
    return jax.tree.map(lambda x: x[t], inputs)


def _span_chunks(k: int):
    """Power-of-two decomposition of a span length, largest chunk first.

    Bounds the leap-program cache (one compiled program per power of two,
    not per distinct span length) at the cost of <= log2(k) dispatches per
    span; composition is exact (module docstring)."""
    while k > 0:
        p = 1 << (k.bit_length() - 1)
        yield p
        k -= p


def _leap_span(state, cfg: SwimConfig, k: int, mesh):
    for chunk in _span_chunks(k):
        state = _leap(cfg, chunk, mesh)(state)
    return state


def simulate_warped(
    state: MeshState,
    inputs: TickInputs,
    cfg: SwimConfig,
    faulty: bool = True,
    recheck_every: int = 16,
    mesh=None,
    on_boundary=None,
    telemetry: bool = False,
):
    """Run a stacked ``[T]`` schedule, fast-forwarding quiescent spans.

    The twin of :func:`kaboodle_tpu.sim.runner.simulate`: same state, same
    schedule, bit-identical final state — but spans with no scheduled event
    whose entry state passes the quiescence predicate advance through the
    leap kernel in one dispatch. Non-quiescent event-free stretches (e.g.
    re-convergence after a fault window) run dense, re-checking the
    predicate every ``recheck_every`` ticks. ``mesh`` selects the sharded
    twins for both the dense ticks and the leap.

    Returns ``(final_state, dense_ticks, dense_metrics)``: the int32 ``[M]``
    indices of the ticks that executed densely and their stacked
    ``TickMetrics`` (``None`` when every tick leaped). ``on_boundary(t,
    state)``, when given, is called at each leap's entry and exit boundary
    with the tick index about to run / just reached — the hook the parity
    fuzz uses to pin state equality at every event-horizon boundary.

    ``telemetry=True`` runs the telemetry-plane dense tick and returns a
    4-tuple ``(final_state, dense_ticks, dense_telemetry, totals)``:
    ``dense_telemetry`` is the densely-executed ticks' stacked
    ``TickTelemetry`` (``None`` if everything leaped) and ``totals`` the
    whole run's ``ProtocolCounters`` sums — dense counters summed plus each
    leaped span's closed form (``telemetry.counters.leap_counters``:
    ``k * n_alive`` pings/acks, all else zero — what the dense kernel
    provably emits on quiescent ticks, pinned by the warp counter-parity
    fuzz arm). One extra scalar fetch per leap span (``n_alive``), in
    keeping with the runner's one-fetch-per-span budget.
    """
    from kaboodle_tpu.telemetry.counters import counters_totals, leap_counters
    from kaboodle_tpu.telemetry.trace import host_span

    T = int(np.asarray(inputs.kill).shape[0])
    eventful = static_event_ticks(inputs)
    tick = _dense_tick(cfg, faulty, mesh, telemetry)
    quiescent = make_quiescence_fn(cfg)
    recheck_every = max(1, int(recheck_every))
    dense_ticks: list[int] = []
    metrics = []
    leap_spans: list[tuple[int, int]] = []  # (span length, n_alive)
    t = 0
    while t < T:
        if not eventful[t]:
            span_end = next_static_event(eventful, t)
            if bool(quiescent(state)):
                if on_boundary is not None:
                    on_boundary(t, state)
                if telemetry:
                    leap_spans.append(
                        (span_end - t, int(_alive_count()(state)))
                    )
                with host_span(f"leap_span:{span_end - t}"):
                    state = _leap_span(state, cfg, span_end - t, mesh)
                t = span_end
                if on_boundary is not None:
                    on_boundary(t, state)
                continue
            stop = min(span_end, t + recheck_every)
        else:
            stop = t + 1
        with host_span("dense_span"):
            while t < stop:
                state, m = tick(state, _slice_tick(inputs, t))
                dense_ticks.append(t)
                metrics.append(m)
                t += 1
    stacked = (
        jax.tree.map(lambda *xs: jnp.stack(xs), *metrics) if metrics else None
    )
    if not telemetry:
        return state, np.asarray(dense_ticks, dtype=np.int32), stacked
    totals = (
        counters_totals(stacked.counters)
        if stacked is not None
        else counters_totals(leap_counters(0, 0))
    )
    for k, n_alive in leap_spans:
        leap = counters_totals(leap_counters(n_alive, k))
        totals = {name: totals[name] + leap[name] for name in totals}
    return state, np.asarray(dense_ticks, dtype=np.int32), stacked, totals


def run_warped(
    state: MeshState,
    cfg: SwimConfig,
    ticks: int,
    recheck_every: int = 16,
    mesh=None,
):
    """Advance a fault-free mesh exactly ``ticks`` ticks, leaping spans.

    The steady-state-service entry point: a converged idle mesh leaps the
    whole budget in one dispatch; an unconverged one runs dense (re-checking
    the horizon every ``recheck_every`` ticks) until quiescence, then leaps
    the remainder. Returns ``(state, ticks_run, converged)`` — the
    ``run_until_converged`` contract, with ``ticks_run == ticks`` always
    (the budget is exact, not a bound) and ``converged`` evaluated on the
    final state.
    """
    tick = _dense_tick(cfg, False, mesh)
    quiescent = make_quiescence_fn(cfg)
    idle = idle_inputs(state.n)
    recheck_every = max(1, int(recheck_every))
    t = 0
    while t < ticks:
        if bool(quiescent(state)):
            state = _leap_span(state, cfg, ticks - t, mesh)
            t = ticks
            break
        stop = min(ticks, t + recheck_every)
        while t < stop:
            state, _ = tick(state, idle)
            t += 1
    return state, jnp.int32(t), _converged(mesh)(state)


# ---------------------------------------------------------------------------
# fleet integration


@functools.lru_cache(maxsize=None)
def _fleet_quiescent(cfg: SwimConfig):
    return jax.jit(jax.vmap(make_quiescence_fn(cfg)))


@functools.lru_cache(maxsize=None)
def _fleet_converged():
    return jax.jit(jax.vmap(state_converged))


@functools.lru_cache(maxsize=None)
def _fleet_leap(cfg: SwimConfig, k: int):
    return jax.jit(jax.vmap(make_leap_fn(cfg, k)))


@functools.lru_cache(maxsize=None)
def _fleet_tick(cfg: SwimConfig):
    from kaboodle_tpu.fleet.core import make_fleet_tick_fn

    return jax.jit(make_fleet_tick_fn(cfg, faulty=False))


def fleet_quiescence_mask(fleet, cfg: SwimConfig) -> jax.Array:
    """bool ``[E]``: per-member event horizon — which members could leap now.

    The quiescence predicate vmapped over the ensemble axis; computed
    on-device, one bool per member (feed it to stats or fetch it once per
    span — never per tick)."""
    return _fleet_quiescent(cfg)(fleet.mesh)


def run_fleet_warped(
    fleet,
    cfg: SwimConfig,
    ticks: int,
    recheck_every: int = 16,
):
    """Advance every fleet member exactly ``ticks`` fault-free ticks.

    While the per-member horizon mask is all-quiescent the whole ensemble
    leaps as ONE vmapped program — each member under its own key chain and
    timers, i.e. E independent leaps in a single dispatch. Any unquiescent
    member sends the whole fleet dense for ``recheck_every`` ticks (the
    vmap-lockstep price — see module docstring); dense is bit-identical for
    the members that could have leaped, so per-member trajectories match
    standalone :func:`run_warped` runs either way (tests/test_warp.py).

    Fault-free only (the leap's precondition): the per-member ``drop_rate``
    knob is inert here, exactly as in ``run_fleet_until_converged``'s
    default mode. Returns ``(fleet, ticks_run, converged)`` with
    ``converged`` a per-member ``[E]`` bool of the final states.
    """
    from kaboodle_tpu.fleet.core import fleet_idle_inputs

    mesh_state = fleet.mesh
    idle = fleet_idle_inputs(fleet.n, fleet.ensemble)
    tick = _fleet_tick(cfg)
    recheck_every = max(1, int(recheck_every))
    t = 0
    while t < ticks:
        mask = np.asarray(_fleet_quiescent(cfg)(mesh_state))
        if mask.all():
            for chunk in _span_chunks(ticks - t):
                mesh_state = _fleet_leap(cfg, chunk)(mesh_state)
            t = ticks
            break
        stop = min(ticks, t + recheck_every)
        while t < stop:
            mesh_state, _ = tick(mesh_state, idle)
            t += 1
    converged = _fleet_converged()(mesh_state)
    return dataclasses.replace(fleet, mesh=mesh_state), jnp.int32(t), converged
