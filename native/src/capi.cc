// C API for the native engine — the ctypes boundary consumed by
// kaboodle_tpu.transport.native. Strings in, JSON (malloc'd, kb_free) out;
// identities cross as hex to stay encoding-agnostic.
#include <cstdlib>
#include <cstring>
#include <sstream>

#include "engine.h"

using namespace kaboodle;

namespace {

char* dup_string(const std::string& s) {
  char* out = static_cast<char*>(std::malloc(s.size() + 1));
  if (out) std::memcpy(out, s.c_str(), s.size() + 1);
  return out;
}

const char* state_name(PeerStateKind k) {
  switch (k) {
    case PeerStateKind::Known:
      return "Known";
    case PeerStateKind::WaitingForPing:
      return "WaitingForPing";
    default:
      return "WaitingForIndirectPing";
  }
}

}  // namespace

extern "C" {

struct kb_engine {
  Engine* impl;
};

kb_engine* kb_create(const char* bind_ip, const char* broadcast_ip,
                     uint16_t broadcast_port, unsigned iface_index,
                     const uint8_t* identity, size_t identity_len, uint32_t period_ms,
                     uint32_t ping_timeout_ms, uint32_t share_age_ms,
                     uint32_t rebroadcast_ms, uint64_t rng_seed) {
  auto bip = NetAddr::parse(std::string(bind_ip) + ":0");
  auto mip = NetAddr::parse(std::string(broadcast_ip).find(':') != std::string::npos
                                ? "[" + std::string(broadcast_ip) + "]:0"
                                : std::string(broadcast_ip) + ":0");
  if (!bip || !mip) return nullptr;
  EngineConfig cfg;
  cfg.bind_ip = *bip;
  cfg.broadcast_ip = *mip;
  cfg.broadcast_port = broadcast_port;
  cfg.iface_index = iface_index;
  cfg.identity.assign(identity, identity + identity_len);
  cfg.period_ms = period_ms;
  cfg.ping_timeout_ms = ping_timeout_ms;
  cfg.share_age_ms = share_age_ms;
  cfg.rebroadcast_ms = rebroadcast_ms;
  cfg.rng_seed = rng_seed;
  return new kb_engine{new Engine(std::move(cfg))};
}

int kb_start(kb_engine* h) {
  return h && h->impl->start() ? 0 : -1;
}

int kb_stop(kb_engine* h) {
  if (!h) return -1;
  h->impl->stop();
  return 0;
}

void kb_destroy(kb_engine* h) {
  if (h) {
    delete h->impl;
    delete h;
  }
}

int kb_is_running(kb_engine* h) {
  return h && h->impl->running() ? 1 : 0;
}

char* kb_self_addr(kb_engine* h) {
  if (!h) return dup_string("");
  return dup_string(h->impl->self_addr().to_string());
}

uint32_t kb_fingerprint(kb_engine* h) {
  return h ? h->impl->fingerprint_now() : 0;
}

char* kb_peers_json(kb_engine* h) {
  if (!h) return dup_string("[]");
  std::ostringstream os;
  os << "[";
  bool first = true;
  for (const auto& [addr, e] : h->impl->peers_snapshot()) {
    if (!first) os << ",";
    first = false;
    os << "{\"addr\":\"" << addr.to_string() << "\",\"identity_hex\":\""
       << to_hex(e.identity) << "\",\"state\":\"" << state_name(e.state)
       << "\",\"latency_ms\":" << e.latency_ms << "}";
  }
  os << "]";
  return dup_string(os.str());
}

char* kb_events_json(kb_engine* h) {
  if (!h) return dup_string("[]");
  std::ostringstream os;
  os << "[";
  bool first = true;
  for (const auto& ev : h->impl->drain_events()) {
    if (!first) os << ",";
    first = false;
    switch (ev.kind) {
      case EngineEvent::Discovered:
        os << "{\"type\":\"discovered\",\"addr\":\"" << ev.addr.to_string()
           << "\",\"identity_hex\":\"" << to_hex(ev.identity) << "\"}";
        break;
      case EngineEvent::Departed:
        os << "{\"type\":\"departed\",\"addr\":\"" << ev.addr.to_string() << "\"}";
        break;
      case EngineEvent::FingerprintChanged:
        os << "{\"type\":\"fingerprint\",\"value\":" << ev.fingerprint << "}";
        break;
    }
  }
  os << "]";
  return dup_string(os.str());
}

int kb_ping_addr(kb_engine* h, const char* addr) {
  if (!h) return -1;
  auto a = NetAddr::parse(addr);
  if (!a) return -1;
  h->impl->ping_addr(*a);
  return 0;
}

int kb_set_identity(kb_engine* h, const uint8_t* identity, size_t len) {
  if (!h) return -1;
  h->impl->set_identity(Bytes(identity, identity + len));
  return 0;
}

char* kb_probe(const char* bind_ip, const char* broadcast_ip, uint16_t port,
               unsigned iface_index, uint32_t start_ms, double multiplier,
               uint32_t cap_ms, uint32_t total_timeout_ms) {
  auto bip = NetAddr::parse(std::string(bind_ip) + ":0");
  auto mip = NetAddr::parse(std::string(broadcast_ip).find(':') != std::string::npos
                                ? "[" + std::string(broadcast_ip) + "]:0"
                                : std::string(broadcast_ip) + ":0");
  if (!bip || !mip) return dup_string("");
  return dup_string(probe_mesh(*bip, *mip, port, iface_index, start_ms, multiplier,
                               cap_ms, total_timeout_ms));
}

char* kb_best_interface() {
  return dup_string(best_available_interface());
}

char* kb_list_interfaces() {
  return dup_string(list_interfaces());
}

void kb_free(char* p) {
  std::free(p);
}

// --- codec test hooks: decode + re-encode, for cross-language golden tests.

long kb_codec_roundtrip_envelope(const uint8_t* in, size_t len, uint8_t* out,
                                 size_t cap) {
  auto e = decode_envelope(in, len);
  if (!e) return -1;
  Bytes b = encode_envelope(*e);
  if (b.size() > cap) return -1;
  std::memcpy(out, b.data(), b.size());
  return long(b.size());
}

long kb_codec_roundtrip_broadcast(const uint8_t* in, size_t len, uint8_t* out,
                                  size_t cap) {
  auto b = decode_broadcast(in, len);
  if (!b) return -1;
  Bytes enc = encode_broadcast(*b);
  if (enc.size() > cap) return -1;
  std::memcpy(out, enc.data(), enc.size());
  return long(enc.size());
}

uint32_t kb_crc32(const uint8_t* data, size_t len) {
  return crc32(data, len, 0);
}

}  // extern "C"
