#include "engine.h"

#include <poll.h>

#include <algorithm>
#include <cstdio>

namespace kaboodle {

Engine::Engine(EngineConfig cfg) : cfg_(std::move(cfg)) {
  uint64_t seed = cfg_.rng_seed ? cfg_.rng_seed : std::random_device{}();
  rng_.seed(seed);
}

Engine::~Engine() {
  stop();
}

bool Engine::start() {
  if (running_) return false;
  // Link-local v6 bind addresses need the interface as their scope.
  if (cfg_.bind_ip.is_link_local_v6() && cfg_.bind_ip.scope == 0)
    cfg_.bind_ip.scope = cfg_.iface_index;
  auto us = bind_unicast(cfg_.bind_ip);
  if (!us) return false;
  sock_ = std::move(*us);
  auto la = sock_.local_addr();
  if (!la) return false;
  self_addr_ = *la;

  auto bp = open_broadcast(cfg_.broadcast_ip, cfg_.broadcast_port, cfg_.iface_index);
  if (!bp) return false;
  bcast_ = std::move(*bp);

  {
    // Self goes into the map as Known(now) (kaboodle.rs:144-152).
    std::lock_guard<std::mutex> lk(mu_);
    PeerEntry self;
    self.identity = cfg_.identity;
    self.state = PeerStateKind::Known;
    self.when = Clock::now();
    bool is_new = peers_.find(self_addr_) == peers_.end();
    peers_[self_addr_] = std::move(self);
    if (is_new) {
      EngineEvent ev;
      ev.kind = EngineEvent::Discovered;
      ev.addr = self_addr_;
      ev.identity = cfg_.identity;
      events_.push_back(std::move(ev));
    }
  }
  note_fingerprint_maybe_changed();

  cancel_ = false;
  running_ = true;
  last_broadcast_.reset();
  thread_ = std::thread([this] { run_loop(); });
  return true;
}

void Engine::stop() {
  if (!running_) return;
  cancel_ = true;
  if (thread_.joinable()) thread_.join();
  // Silent leave (Q8): no Failed/departure announcement. Map is kept minus
  // self (lib.rs:167-170).
  {
    std::lock_guard<std::mutex> lk(mu_);
    peers_.erase(self_addr_);
  }
  sock_ = UdpSock();
  bcast_ = BroadcastPair();
  running_ = false;
}

void Engine::run_loop() {
  while (!cancel_) tick();
}

// One protocol period (kaboodle.rs:746-779): the active half then the
// reactive half for the remainder of the period.
void Engine::tick() {
  auto start = Clock::now();
  maybe_broadcast_join(start);
  handle_suspected_peers(start);
  ping_random_peer(start);
  drain_manual_pings();
  auto deadline = start + std::chrono::milliseconds(cfg_.period_ms);
  auto min_wait = Clock::now() + std::chrono::milliseconds(10);
  pump_sockets_until(std::max(deadline, min_wait));
  note_fingerprint_maybe_changed();
}

void Engine::maybe_broadcast_join(Clock::time_point now) {
  // First call always broadcasts; later only while lonely and stale
  // (kaboodle.rs:228-251).
  if (last_broadcast_) {
    std::lock_guard<std::mutex> lk(mu_);
    if (now - *last_broadcast_ < std::chrono::milliseconds(cfg_.rebroadcast_ms) ||
        peers_.size() > 1)
      return;
  }
  last_broadcast_ = now;
  Broadcast b;
  b.kind = BroadcastKind::Join;
  b.addr = self_addr_;
  {
    std::lock_guard<std::mutex> lk(mu_);
    b.identity = cfg_.identity;
  }
  broadcast(b);
}

void Engine::handle_suspected_peers(Clock::time_point now) {
  // Escalate stale WaitingForPing to indirect pings via k proxies; remove
  // stale WaitingForIndirectPing (kaboodle.rs:558-653).
  auto timeout = std::chrono::milliseconds(cfg_.ping_timeout_ms);
  std::vector<NetAddr> removed, escalated;
  std::vector<std::pair<NetAddr, std::vector<NetAddr>>> ping_reqs;
  {
    std::lock_guard<std::mutex> lk(mu_);
    std::vector<NetAddr> known_others;
    for (const auto& [addr, e] : peers_)
      if (!(addr == self_addr_) && e.state == PeerStateKind::Known)
        known_others.push_back(addr);

    for (const auto& [addr, e] : peers_) {
      if (e.state == PeerStateKind::WaitingForPing && now - e.when >= timeout) {
        if (known_others.empty()) {
          removed.push_back(addr);  // no proxies -> drop now (:599-605)
          continue;
        }
        std::vector<NetAddr> proxies = known_others;
        std::shuffle(proxies.begin(), proxies.end(), rng_);
        if (proxies.size() > cfg_.indirect_peers) proxies.resize(cfg_.indirect_peers);
        ping_reqs.emplace_back(addr, std::move(proxies));
        escalated.push_back(addr);
      } else if (e.state == PeerStateKind::WaitingForIndirectPing &&
                 now - e.when >= timeout) {
        removed.push_back(addr);
      }
    }
    for (const auto& addr : escalated) {
      auto it = peers_.find(addr);
      if (it != peers_.end()) {
        it->second.state = PeerStateKind::WaitingForIndirectPing;
        it->second.when = now;
      }
    }
  }
  for (const auto& [suspect, proxies] : ping_reqs) {
    Message m;
    m.kind = MsgKind::PingRequest;
    m.peer = suspect;
    for (const auto& p : proxies) send_msg(p, m);
  }
  for (const auto& addr : removed) {
    remove_peer(addr);
    Broadcast b;
    b.kind = BroadcastKind::Failed;
    b.addr = addr;
    broadcast(b);  // inert at receivers in practice (Q3)
  }
}

void Engine::ping_random_peer(Clock::time_point now) {
  // Random choice among the oldest candidate_peers Known peers
  // (kaboodle.rs:655-703).
  NetAddr target;
  bool have = false;
  {
    std::lock_guard<std::mutex> lk(mu_);
    std::vector<std::pair<Clock::time_point, NetAddr>> cands;
    for (const auto& [addr, e] : peers_)
      if (!(addr == self_addr_) && e.state == PeerStateKind::Known)
        cands.emplace_back(e.when, addr);
    if (cands.empty()) return;
    std::sort(cands.begin(), cands.end(),
              [](const auto& a, const auto& b) { return a.first < b.first; });
    size_t pool = std::min<size_t>(cfg_.candidate_peers, cands.size());
    size_t pick = std::uniform_int_distribution<size_t>(0, pool - 1)(rng_);
    target = cands[pick].second;
    auto it = peers_.find(target);
    it->second.state = PeerStateKind::WaitingForPing;
    it->second.when = now;
    have = true;
  }
  if (have) {
    Message m;
    m.kind = MsgKind::Ping;
    send_msg(target, m);
  }
}

void Engine::drain_manual_pings() {
  std::deque<NetAddr> q;
  {
    std::lock_guard<std::mutex> lk(manual_mu_);
    q.swap(manual_pings_);
  }
  Message m;
  m.kind = MsgKind::Ping;
  for (const auto& a : q) send_msg(a, m);
}

void Engine::pump_sockets_until(Clock::time_point deadline) {
  std::vector<uint8_t> buf(cfg_.buffer_size, 0);
  while (!cancel_) {
    auto now = Clock::now();
    if (now >= deadline) return;
    pollfd fds[2] = {{bcast_.in.fd, POLLIN, 0}, {sock_.fd, POLLIN, 0}};
    int wait_ms = int(
        std::chrono::duration_cast<std::chrono::milliseconds>(deadline - now).count());
    int rv = ::poll(fds, 2, std::min(wait_ms, 50));
    if (rv <= 0) continue;

    NetAddr sender;
    if (fds[0].revents & POLLIN) {
      long n;
      while ((n = bcast_.in.recv_from(buf.data(), buf.size(), &sender)) > 0) {
        // Q2: decode from the zero-padded full buffer, prefix-tolerant.
        std::fill(buf.begin() + n, buf.end(), 0);
        if (auto b = decode_broadcast(buf.data(), buf.size())) handle_broadcast(*b, sender);
      }
    }
    if (fds[1].revents & POLLIN) {
      long n;
      while ((n = sock_.recv_from(buf.data(), buf.size(), &sender)) > 0) {
        std::fill(buf.begin() + n, buf.end(), 0);
        if (auto e = decode_envelope(buf.data(), buf.size())) handle_message(*e, sender);
      }
    }
  }
}

void Engine::handle_broadcast(const Broadcast& b, const NetAddr& sender) {
  switch (b.kind) {
    case BroadcastKind::Failed: {
      if (b.addr == self_addr_) return;
      // Q3: removal requires the *broadcast source address* to be a known
      // member — which it never is (the source is the broadcast socket), so
      // this is faithfully inert.
      std::unique_lock<std::mutex> lk(mu_);
      if (peers_.count(sender)) {
        lk.unlock();
        remove_peer(b.addr);
      }
      break;
    }
    case BroadcastKind::Join: {
      if (b.addr == self_addr_) return;
      bool is_new;
      {
        std::lock_guard<std::mutex> lk(mu_);
        auto it = peers_.find(b.addr);
        is_new = it == peers_.end();
        PeerEntry e;
        e.identity = b.identity;
        e.state = PeerStateKind::Known;
        e.when = Clock::now();
        e.latency_ms = is_new ? -1 : it->second.latency_ms;
        peers_[b.addr] = std::move(e);
        if (is_new) {
          EngineEvent ev;
          ev.kind = EngineEvent::Discovered;
          ev.addr = b.addr;
          ev.identity = b.identity;
          events_.push_back(std::move(ev));
        }
      }
      if (is_new) maybe_send_known_peers(b.addr);
      break;
    }
    case BroadcastKind::Probe: {
      if (should_respond_to_broadcast()) {
        Bytes ident;
        {
          std::lock_guard<std::mutex> lk(mu_);
          ident = cfg_.identity;
        }
        Bytes out = encode_probe_response(ident);
        sock_.send_to(out.data(), out.size(), b.addr);
      }
      break;
    }
  }
}

void Engine::mark_sender_known(const NetAddr& sender, const Bytes& identity) {
  // Q1 (kaboodle.rs:408-415): any inbound datagram resurrects its sender,
  // updating the latency EWMA from a pending ping's send time.
  std::lock_guard<std::mutex> lk(mu_);
  auto now = Clock::now();
  auto it = peers_.find(sender);
  PeerEntry e;
  e.identity = identity;
  e.state = PeerStateKind::Known;
  e.when = now;
  bool is_new = it == peers_.end();
  if (!is_new) {
    e.latency_ms = it->second.latency_ms;
    if (it->second.state != PeerStateKind::Known) {
      double sample =
          std::chrono::duration_cast<std::chrono::duration<double, std::milli>>(
              now - it->second.when)
              .count();
      // 80% weight to the newest sample (kaboodle.rs:789-817).
      e.latency_ms = it->second.latency_ms < 0 ? sample
                                               : sample * 0.8 + it->second.latency_ms * 0.2;
    }
    if (it->second.identity != identity) {
      EngineEvent ev;
      ev.kind = EngineEvent::Discovered;
      ev.addr = sender;
      ev.identity = identity;
      events_.push_back(std::move(ev));
    }
  } else {
    EngineEvent ev;
    ev.kind = EngineEvent::Discovered;
    ev.addr = sender;
    ev.identity = identity;
    events_.push_back(std::move(ev));
  }
  peers_[sender] = std::move(e);
}

void Engine::handle_message(const Envelope& env, const NetAddr& sender) {
  mark_sender_known(sender, env.identity);

  switch (env.msg.kind) {
    case MsgKind::Ack: {
      // Forward to curious observers (indirect-ping relay), then maybe sync
      // (kaboodle.rs:418-447).
      std::vector<NetAddr> observers;
      {
        std::lock_guard<std::mutex> lk(mu_);
        auto it = curious_.find(env.msg.peer);
        if (it != curious_.end()) {
          observers = std::move(it->second);
          curious_.erase(it);
        }
      }
      for (const auto& o : observers) send_msg(o, env.msg);
      maybe_sync_known_peers(env.msg.peer, env.msg.fingerprint, env.msg.num_peers);
      break;
    }
    case MsgKind::KnownPeers: {
      // Gossip inserts are back-dated by share_age so they are never
      // re-shared before direct contact (Q6, kaboodle.rs:448-472).
      std::lock_guard<std::mutex> lk(mu_);
      auto backdated = Clock::now() - std::chrono::milliseconds(cfg_.share_age_ms);
      for (const auto& [addr, ident] : env.msg.known_peers) {
        if (peers_.count(addr)) continue;
        PeerEntry e;
        e.identity = ident;
        e.state = PeerStateKind::Known;
        e.when = backdated;
        peers_[addr] = std::move(e);
        EngineEvent ev;
        ev.kind = EngineEvent::Discovered;
        ev.addr = addr;
        ev.identity = ident;
        events_.push_back(std::move(ev));
      }
      break;
    }
    case MsgKind::KnownPeersRequest: {
      // Reply with Known peers heard within share_age, excluding self and
      // the requester; then maybe sync back (kaboodle.rs:473-512).
      Message reply;
      reply.kind = MsgKind::KnownPeers;
      {
        std::lock_guard<std::mutex> lk(mu_);
        auto now = Clock::now();
        for (const auto& [addr, e] : peers_) {
          if (addr == self_addr_ || addr == sender) continue;
          if (e.state != PeerStateKind::Known) continue;
          if (now - e.when >= std::chrono::milliseconds(cfg_.share_age_ms)) continue;
          reply.known_peers.emplace(addr, e.identity);
        }
      }
      send_msg(sender, reply);
      maybe_sync_known_peers(sender, env.msg.fingerprint, env.msg.num_peers);
      break;
    }
    case MsgKind::Ping: {
      Message ack;
      ack.kind = MsgKind::Ack;
      ack.peer = self_addr_;
      {
        std::lock_guard<std::mutex> lk(mu_);
        std::map<NetAddr, Bytes> m;
        for (const auto& [a, e] : peers_) m.emplace(a, e.identity);
        ack.fingerprint = fingerprint(m);
        ack.num_peers = uint32_t(peers_.size());
      }
      send_msg(sender, ack);
      break;
    }
    case MsgKind::PingRequest: {
      // Record the curious sender, ping the suspect ourselves
      // (kaboodle.rs:533-545).
      {
        std::lock_guard<std::mutex> lk(mu_);
        auto& obs = curious_[env.msg.peer];
        if (std::find(obs.begin(), obs.end(), sender) == obs.end())
          obs.push_back(sender);
      }
      Message ping;
      ping.kind = MsgKind::Ping;
      send_msg(env.msg.peer, ping);
      break;
    }
  }
}

void Engine::maybe_sync_known_peers(const NetAddr& peer, uint32_t their_fp,
                                    uint32_t their_n) {
  // Anti-entropy pull: request their map iff fingerprints differ and ours is
  // not strictly bigger (kaboodle.rs:707-740).
  uint32_t our_fp, our_n;
  {
    std::lock_guard<std::mutex> lk(mu_);
    std::map<NetAddr, Bytes> m;
    for (const auto& [a, e] : peers_) m.emplace(a, e.identity);
    our_fp = fingerprint(m);
    our_n = uint32_t(peers_.size());
  }
  if (our_fp == their_fp || our_n > their_n) return;
  Message m;
  m.kind = MsgKind::KnownPeersRequest;
  m.fingerprint = our_fp;
  m.num_peers = our_n;
  send_msg(peer, m);
}

bool Engine::should_respond_to_broadcast() {
  // max(1, 100 - n^2)% with n = |peers| - 2 (kaboodle.rs:333-354).
  int64_t n;
  {
    std::lock_guard<std::mutex> lk(mu_);
    n = int64_t(peers_.size()) - 2;
  }
  if (n <= 0) return true;
  double pct = double(std::max<int64_t>(1, 100 - n * n)) / 100.0;
  return std::uniform_real_distribution<double>(0.0, 1.0)(rng_) < pct;
}

void Engine::maybe_send_known_peers(const NetAddr& addr) {
  if (!should_respond_to_broadcast()) return;
  // Q5: the join-response shares the whole map (self included, no age
  // filter), trimmed at random until it fits the receive buffer
  // (kaboodle.rs:356-392).
  Message m;
  m.kind = MsgKind::KnownPeers;
  Bytes ident;
  {
    std::lock_guard<std::mutex> lk(mu_);
    for (const auto& [a, e] : peers_) m.known_peers.emplace(a, e.identity);
    ident = cfg_.identity;
  }
  if (m.known_peers.empty()) return;
  Envelope env{ident, m};
  Bytes out = encode_envelope(env);
  while (out.size() >= cfg_.buffer_size && !env.msg.known_peers.empty()) {
    auto it = env.msg.known_peers.begin();
    std::advance(it, std::uniform_int_distribution<size_t>(
                         0, env.msg.known_peers.size() - 1)(rng_));
    env.msg.known_peers.erase(it);
    out = encode_envelope(env);
  }
  sock_.send_to(out.data(), out.size(), addr);
}

void Engine::send_msg(const NetAddr& to, const Message& m) {
  Envelope env;
  {
    std::lock_guard<std::mutex> lk(mu_);
    env.identity = cfg_.identity;
  }
  env.msg = m;
  Bytes out = encode_envelope(env);
  if (!sock_.send_to(out.data(), out.size(), to) && m.kind == MsgKind::Ping) {
    // Q7: a failed ping send removes the target immediately
    // (kaboodle.rs:694-702).
    remove_peer(to);
  }
}

void Engine::broadcast(const Broadcast& b) {
  Bytes out = encode_broadcast(b);
  bcast_.out.send_to(out.data(), out.size(), bcast_.dest);
}

void Engine::remove_peer(const NetAddr& addr) {
  std::lock_guard<std::mutex> lk(mu_);
  if (peers_.erase(addr)) {
    curious_.erase(addr);
    EngineEvent ev;
    ev.kind = EngineEvent::Departed;
    ev.addr = addr;
    events_.push_back(std::move(ev));
  }
}

void Engine::note_fingerprint_maybe_changed() {
  std::lock_guard<std::mutex> lk(mu_);
  std::map<NetAddr, Bytes> m;
  for (const auto& [a, e] : peers_) m.emplace(a, e.identity);
  uint32_t fp = fingerprint(m);
  // Q10: the empty-map fingerprint (0) is never announced.
  if (fp != announced_fp_ && !m.empty()) {
    announced_fp_ = fp;
    EngineEvent ev;
    ev.kind = EngineEvent::FingerprintChanged;
    ev.fingerprint = fp;
    events_.push_back(std::move(ev));
  }
}

uint32_t Engine::fingerprint_now() {
  std::lock_guard<std::mutex> lk(mu_);
  std::map<NetAddr, Bytes> m;
  for (const auto& [a, e] : peers_) m.emplace(a, e.identity);
  return fingerprint(m);
}

std::map<NetAddr, PeerEntry> Engine::peers_snapshot() {
  std::lock_guard<std::mutex> lk(mu_);
  return peers_;
}

std::vector<EngineEvent> Engine::drain_events() {
  std::lock_guard<std::mutex> lk(mu_);
  std::vector<EngineEvent> out(events_.begin(), events_.end());
  events_.clear();
  return out;
}

void Engine::ping_addr(const NetAddr& target) {
  std::lock_guard<std::mutex> lk(manual_mu_);
  manual_pings_.push_back(target);
}

void Engine::set_identity(Bytes identity) {
  std::lock_guard<std::mutex> lk(mu_);
  cfg_.identity = std::move(identity);
  auto it = peers_.find(self_addr_);
  if (it != peers_.end()) it->second.identity = cfg_.identity;
}

std::string probe_mesh(const NetAddr& bind_ip, const NetAddr& bcast_ip, uint16_t port,
                       unsigned iface_index, uint32_t start_ms, double multiplier,
                       uint32_t cap_ms, uint32_t total_timeout_ms) {
  NetAddr bip = bind_ip;
  if (bip.is_link_local_v6() && bip.scope == 0) bip.scope = iface_index;
  auto us = bind_unicast(bip);
  if (!us) return "";
  auto la = us->local_addr();
  if (!la) return "";
  auto bp = open_broadcast(bcast_ip, port, iface_index);
  if (!bp) return "";

  Broadcast probe;
  probe.kind = BroadcastKind::Probe;
  probe.addr = *la;
  Bytes out = encode_broadcast(probe);

  using Clock = std::chrono::steady_clock;
  // total_timeout_ms == 0: retry forever with the same backoff — the
  // reference's discover_mesh_member never gives up (discovery.rs:51-72).
  bool forever = total_timeout_ms == 0;
  auto overall = Clock::now() + std::chrono::milliseconds(
                                    forever ? 1 : total_timeout_ms);
  double interval = start_ms;
  std::vector<uint8_t> buf(1024, 0);  // discovery.rs:16

  while (forever || Clock::now() < overall) {
    bp->out.send_to(out.data(), out.size(), bp->dest);
    auto wait_until = Clock::now() + std::chrono::milliseconds(uint32_t(interval));
    while (Clock::now() < wait_until && (forever || Clock::now() < overall)) {
      pollfd fd{us->fd, POLLIN, 0};
      ::poll(&fd, 1, 20);
      NetAddr sender;
      long n = us->recv_from(buf.data(), buf.size(), &sender);
      if (n > 0) {
        std::fill(buf.begin() + n, buf.end(), 0);
        // Q4: the reply is a raw ProbeResponse but is parsed as an envelope —
        // works because the zero tail decodes as SwimMessage::Ping (Q2).
        if (auto env = decode_envelope(buf.data(), buf.size()))
          return sender.to_string() + "|" + to_hex(env->identity);
      }
    }
    interval = std::min(double(cap_ms), interval * multiplier);
  }
  return "";
}

}  // namespace kaboodle
