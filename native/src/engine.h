// The real-network SWIM engine: reference protocol semantics over real UDP.
//
// This is the native runtime for real-network interop (the reference's whole
// product, kaboodle.rs). Design differs from the reference (no async runtime;
// one poll()-driven thread per instance; codec split out into wire.h) but the
// protocol behavior matches the call stacks in SURVEY.md §3.2-3.4, including
// the load-bearing quirks: any inbound datagram marks its sender Known (Q1),
// Failed-broadcast removal requires a known sender (Q3, making it inert on
// real sockets), join shares are unfiltered and trimmed to the receive buffer
// (Q5), gossip-learned peers are back-dated so they never re-gossip (Q6), a
// failed ping send removes the target immediately (Q7), and stop() leaves
// silently (Q8).
//
// All timing constants are injectable so tests can run at millisecond scale;
// defaults match the reference (kaboodle.rs:38-65).
#pragma once

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <deque>
#include <mutex>
#include <random>
#include <thread>

#include "transport.h"
#include "wire.h"

namespace kaboodle {

struct EngineConfig {
  NetAddr bind_ip{};     // unicast bind address (port ignored; ephemeral)
  NetAddr broadcast_ip;  // v4 broadcast addr or v6 multicast group
  uint16_t broadcast_port = 7475;
  unsigned iface_index = 0;  // for v6 multicast
  Bytes identity;
  uint32_t period_ms = 1000;         // PROTOCOL_PERIOD (kaboodle.rs:38)
  uint32_t ping_timeout_ms = 2000;   // PING_TIMEOUT (kaboodle.rs:62)
  uint32_t share_age_ms = 10000;     // MAX_PEER_SHARE_AGE (kaboodle.rs:49)
  uint32_t rebroadcast_ms = 10000;   // REBROADCAST_INTERVAL (kaboodle.rs:65)
  uint32_t buffer_size = 10240;      // INCOMING_BUFFER_SIZE (kaboodle.rs:43)
  uint32_t indirect_peers = 3;       // NUM_INDIRECT_PING_PEERS
  uint32_t candidate_peers = 5;      // NUM_CANDIDATE_TARGET_PEERS
  uint64_t rng_seed = 0;             // 0 = seed from std::random_device
};

enum class PeerStateKind : uint8_t { Known = 0, WaitingForPing = 1, WaitingForIndirectPing = 2 };

struct PeerEntry {
  Bytes identity;
  PeerStateKind state = PeerStateKind::Known;
  std::chrono::steady_clock::time_point when{};  // last-heard / sent-at
  double latency_ms = -1;                        // EWMA (kaboodle.rs:789-817); <0 none
};

struct EngineEvent {
  enum Kind { Discovered, Departed, FingerprintChanged } kind;
  NetAddr addr{};       // Discovered/Departed
  Bytes identity;       // Discovered
  uint32_t fingerprint = 0;  // FingerprintChanged
};

class Engine {
 public:
  explicit Engine(EngineConfig cfg);
  ~Engine();

  bool start();  // bind sockets, spawn the protocol thread
  void stop();   // silent leave (Q8): cancel thread, close sockets, keep map

  bool running() const { return running_; }
  NetAddr self_addr() const { return self_addr_; }

  uint32_t fingerprint_now();
  std::map<NetAddr, PeerEntry> peers_snapshot();
  std::vector<EngineEvent> drain_events();
  void ping_addr(const NetAddr& target);  // manual ping (lib.rs:268-297)
  void set_identity(Bytes identity);

 private:
  using Clock = std::chrono::steady_clock;

  void run_loop();
  void tick();
  void maybe_broadcast_join(Clock::time_point now);
  void handle_suspected_peers(Clock::time_point now);
  void ping_random_peer(Clock::time_point now);
  void drain_manual_pings();
  void pump_sockets_until(Clock::time_point deadline);
  void handle_broadcast(const Broadcast& b, const NetAddr& sender);
  void handle_message(const Envelope& env, const NetAddr& sender);
  void mark_sender_known(const NetAddr& sender, const Bytes& identity);  // Q1
  void maybe_sync_known_peers(const NetAddr& peer, uint32_t their_fp, uint32_t their_n);
  bool should_respond_to_broadcast();  // max(1, 100-n^2)% (kaboodle.rs:333-354)
  void maybe_send_known_peers(const NetAddr& addr);  // Q5 + 10KiB trim
  void send_msg(const NetAddr& to, const Message& m);
  void broadcast(const Broadcast& b);
  void insert_or_update(const NetAddr& addr, PeerEntry entry);
  void remove_peer(const NetAddr& addr);
  void note_fingerprint_maybe_changed();

  EngineConfig cfg_;
  UdpSock sock_;
  BroadcastPair bcast_;
  NetAddr self_addr_{};
  std::mt19937_64 rng_;

  std::mutex mu_;  // guards peers_, curious_, events_, identity_
  std::map<NetAddr, PeerEntry> peers_;
  std::map<NetAddr, std::vector<NetAddr>> curious_;
  std::deque<EngineEvent> events_;
  uint32_t announced_fp_ = 0;

  std::thread thread_;
  std::atomic<bool> running_{false};
  std::atomic<bool> cancel_{false};
  std::mutex manual_mu_;
  std::deque<NetAddr> manual_pings_;
  std::optional<Clock::time_point> last_broadcast_;
};

// discover_mesh_member (discovery.rs:30-89): broadcast Probe with exponential
// backoff until a unicast reply arrives; returns "addr|identity_hex", or ""
// on timeout.
std::string probe_mesh(const NetAddr& bind_ip, const NetAddr& bcast_ip, uint16_t port,
                       unsigned iface_index, uint32_t start_ms, double multiplier,
                       uint32_t cap_ms, uint32_t total_timeout_ms);

}  // namespace kaboodle
