#include "transport.h"

#include <arpa/inet.h>
#include <fcntl.h>
#include <ifaddrs.h>
#include <net/if.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cerrno>
#include <cstring>

namespace kaboodle {

namespace {

socklen_t to_sockaddr(const NetAddr& a, sockaddr_storage* ss) {
  std::memset(ss, 0, sizeof(*ss));
  if (a.v6) {
    auto* s6 = reinterpret_cast<sockaddr_in6*>(ss);
    s6->sin6_family = AF_INET6;
    s6->sin6_port = htons(a.port);
    std::memcpy(&s6->sin6_addr, a.ip.data(), 16);
    s6->sin6_scope_id = a.scope;  // required for link-local (fe80::) targets
    return sizeof(sockaddr_in6);
  }
  auto* s4 = reinterpret_cast<sockaddr_in*>(ss);
  s4->sin_family = AF_INET;
  s4->sin_port = htons(a.port);
  std::memcpy(&s4->sin_addr, a.ip.data(), 4);
  return sizeof(sockaddr_in);
}

NetAddr from_sockaddr(const sockaddr_storage& ss) {
  NetAddr a;
  if (ss.ss_family == AF_INET6) {
    const auto* s6 = reinterpret_cast<const sockaddr_in6*>(&ss);
    a.v6 = true;
    std::memcpy(a.ip.data(), &s6->sin6_addr, 16);
    a.port = ntohs(s6->sin6_port);
    a.scope = s6->sin6_scope_id;
  } else {
    const auto* s4 = reinterpret_cast<const sockaddr_in*>(&ss);
    a.v6 = false;
    std::memcpy(a.ip.data(), &s4->sin_addr, 4);
    a.port = ntohs(s4->sin_port);
  }
  return a;
}

bool set_nonblocking_reuse(int fd, bool reuse) {
  int one = 1;
  if (reuse) {
    if (setsockopt(fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one)) < 0) return false;
    if (setsockopt(fd, SOL_SOCKET, SO_REUSEPORT, &one, sizeof(one)) < 0) return false;
  }
  int flags = fcntl(fd, F_GETFL);
  return flags >= 0 && fcntl(fd, F_SETFL, flags | O_NONBLOCK) == 0;
}

}  // namespace

UdpSock& UdpSock::operator=(UdpSock&& o) noexcept {
  if (this != &o) {
    if (fd >= 0) close(fd);
    fd = o.fd;
    o.fd = -1;
  }
  return *this;
}

UdpSock::~UdpSock() {
  if (fd >= 0) close(fd);
}

long UdpSock::recv_from(uint8_t* buf, size_t cap, NetAddr* sender) const {
  sockaddr_storage ss;
  socklen_t slen = sizeof(ss);
  ssize_t n = ::recvfrom(fd, buf, cap, 0, reinterpret_cast<sockaddr*>(&ss), &slen);
  if (n < 0) return (errno == EAGAIN || errno == EWOULDBLOCK) ? 0 : -1;
  if (sender) *sender = from_sockaddr(ss);
  return n;
}

bool UdpSock::send_to(const uint8_t* buf, size_t len, const NetAddr& dest) const {
  sockaddr_storage ss;
  socklen_t slen = to_sockaddr(dest, &ss);
  if (::sendto(fd, buf, len, 0, reinterpret_cast<sockaddr*>(&ss), slen) ==
      ssize_t(len))
    return true;
  // Transient buffer pressure is not a send failure: the reference's async
  // send awaits writability, so only hard errors ever surface there — and a
  // "failed" ping send removes the target immediately (Q7). A dropped
  // datagram under pressure is indistinguishable from network loss, which
  // the protocol already tolerates.
  return errno == EAGAIN || errno == EWOULDBLOCK || errno == ENOBUFS;
}

std::optional<NetAddr> UdpSock::local_addr() const {
  sockaddr_storage ss;
  socklen_t slen = sizeof(ss);
  if (getsockname(fd, reinterpret_cast<sockaddr*>(&ss), &slen) < 0) return std::nullopt;
  return from_sockaddr(ss);
}

std::optional<UdpSock> bind_unicast(const NetAddr& ip_only) {
  UdpSock s;
  s.fd = socket(ip_only.v6 ? AF_INET6 : AF_INET, SOCK_DGRAM, IPPROTO_UDP);
  if (!s.valid()) return std::nullopt;
  if (!set_nonblocking_reuse(s.fd, /*reuse=*/false)) return std::nullopt;
  sockaddr_storage ss;
  NetAddr bindaddr = ip_only;
  bindaddr.port = 0;
  socklen_t slen = to_sockaddr(bindaddr, &ss);
  if (bind(s.fd, reinterpret_cast<sockaddr*>(&ss), slen) < 0) return std::nullopt;
  return s;
}

std::optional<BroadcastPair> open_broadcast(const NetAddr& bcast_ip, uint16_t port,
                                            unsigned iface_index) {
  BroadcastPair p;
  p.dest = bcast_ip;
  p.dest.port = port;

  if (!bcast_ip.v6) {
    // IPv4: one socket does both directions (networking.rs:32-67).
    UdpSock s;
    s.fd = socket(AF_INET, SOCK_DGRAM, IPPROTO_UDP);
    if (!s.valid()) return std::nullopt;
    int one = 1;
    if (setsockopt(s.fd, SOL_SOCKET, SO_BROADCAST, &one, sizeof(one)) < 0)
      return std::nullopt;
    if (!set_nonblocking_reuse(s.fd, /*reuse=*/true)) return std::nullopt;
    sockaddr_in any{};
    any.sin_family = AF_INET;
    any.sin_port = htons(port);
    if (bind(s.fd, reinterpret_cast<sockaddr*>(&any), sizeof(any)) < 0)
      return std::nullopt;
    int fd2 = dup(s.fd);
    if (fd2 < 0) return std::nullopt;
    p.in = std::move(s);
    p.out.fd = fd2;
    return p;
  }

  // IPv6: join the multicast group on the interface for inbound; pin the
  // egress interface for outbound (networking.rs:68-119).
  UdpSock in;
  in.fd = socket(AF_INET6, SOCK_DGRAM, IPPROTO_UDP);
  if (!in.valid()) return std::nullopt;
  ipv6_mreq mreq{};
  std::memcpy(&mreq.ipv6mr_multiaddr, bcast_ip.ip.data(), 16);
  mreq.ipv6mr_interface = iface_index;
  if (setsockopt(in.fd, IPPROTO_IPV6, IPV6_JOIN_GROUP, &mreq, sizeof(mreq)) < 0)
    return std::nullopt;
  int one = 1;
  if (setsockopt(in.fd, IPPROTO_IPV6, IPV6_V6ONLY, &one, sizeof(one)) < 0)
    return std::nullopt;
  if (!set_nonblocking_reuse(in.fd, /*reuse=*/true)) return std::nullopt;
  sockaddr_in6 any{};
  any.sin6_family = AF_INET6;
  any.sin6_port = htons(port);
  if (bind(in.fd, reinterpret_cast<sockaddr*>(&any), sizeof(any)) < 0)
    return std::nullopt;

  UdpSock out;
  out.fd = socket(AF_INET6, SOCK_DGRAM, IPPROTO_UDP);
  if (!out.valid()) return std::nullopt;
  if (setsockopt(out.fd, IPPROTO_IPV6, IPV6_MULTICAST_IF, &iface_index,
                 sizeof(iface_index)) < 0)
    return std::nullopt;
  if (!set_nonblocking_reuse(out.fd, /*reuse=*/true)) return std::nullopt;
  sockaddr_in6 any0{};
  any0.sin6_family = AF_INET6;
  if (bind(out.fd, reinterpret_cast<sockaddr*>(&any0), sizeof(any0)) < 0)
    return std::nullopt;

  p.in = std::move(in);
  p.out = std::move(out);
  return p;
}

std::string list_interfaces() {
  // One line per non-loopback address: "family,ip,ifindex,broadcast,name"
  // where broadcast is the v4 subnet broadcast (empty for v6). The name
  // lets --interface resolve by device name like the reference
  // (main.rs:18-36 matches name or IP, uncanonicalized).
  ifaddrs* ifs = nullptr;
  if (getifaddrs(&ifs) != 0) return "";
  std::string out;
  for (ifaddrs* i = ifs; i; i = i->ifa_next) {
    if (!i->ifa_addr || (i->ifa_flags & IFF_LOOPBACK) || !(i->ifa_flags & IFF_UP))
      continue;
    char host[INET6_ADDRSTRLEN] = {0};
    unsigned idx = if_nametoindex(i->ifa_name);
    std::string name = i->ifa_name ? i->ifa_name : "";
    if (i->ifa_addr->sa_family == AF_INET6) {
      auto* s6 = reinterpret_cast<sockaddr_in6*>(i->ifa_addr);
      inet_ntop(AF_INET6, &s6->sin6_addr, host, sizeof(host));
      out += "6," + std::string(host) + "," + std::to_string(idx) + ",," + name + "\n";
    } else if (i->ifa_addr->sa_family == AF_INET) {
      auto* s4 = reinterpret_cast<sockaddr_in*>(i->ifa_addr);
      inet_ntop(AF_INET, &s4->sin_addr, host, sizeof(host));
      char bc[INET_ADDRSTRLEN] = {0};
      if (i->ifa_ifu.ifu_broadaddr && (i->ifa_flags & IFF_BROADCAST)) {
        auto* sb = reinterpret_cast<sockaddr_in*>(i->ifa_ifu.ifu_broadaddr);
        inet_ntop(AF_INET, &sb->sin_addr, bc, sizeof(bc));
      }
      out += "4," + std::string(host) + "," + std::to_string(idx) + "," + bc +
             "," + name + "\n";
    }
  }
  freeifaddrs(ifs);
  return out;
}

std::string best_available_interface() {
  // Reference policy (networking.rs:12-23): first non-loopback IPv6, else v4.
  ifaddrs* ifs = nullptr;
  if (getifaddrs(&ifs) != 0) return "";
  std::string v6_pick, v4_pick;
  for (ifaddrs* i = ifs; i; i = i->ifa_next) {
    if (!i->ifa_addr || (i->ifa_flags & IFF_LOOPBACK) || !(i->ifa_flags & IFF_UP))
      continue;
    char host[INET6_ADDRSTRLEN] = {0};
    unsigned idx = if_nametoindex(i->ifa_name);
    if (i->ifa_addr->sa_family == AF_INET6 && v6_pick.empty()) {
      auto* s6 = reinterpret_cast<sockaddr_in6*>(i->ifa_addr);
      inet_ntop(AF_INET6, &s6->sin6_addr, host, sizeof(host));
      v6_pick = std::string(host) + "," + std::to_string(idx);
    } else if (i->ifa_addr->sa_family == AF_INET && v4_pick.empty()) {
      auto* s4 = reinterpret_cast<sockaddr_in*>(i->ifa_addr);
      inet_ntop(AF_INET, &s4->sin_addr, host, sizeof(host));
      v4_pick = std::string(host) + "," + std::to_string(idx);
    }
  }
  freeifaddrs(ifs);
  return !v6_pick.empty() ? v6_pick : v4_pick;
}

}  // namespace kaboodle
