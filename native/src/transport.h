// UDP transport: unicast socket + broadcast/multicast pair.
//
// Reproduces the wire-level behavior of the reference's networking layer
// (networking.rs:27-121): IPv4 uses one SO_BROADCAST socket bound to
// 0.0.0.0:<port> with SO_REUSEADDR/SO_REUSEPORT sending to a broadcast
// address; IPv6 joins the link-local multicast group ff02::1213:1989 on the
// interface, with a separate outbound socket pinned to the interface index.
// All sockets are non-blocking; the engine multiplexes with poll().
#pragma once

#include <cstdint>
#include <optional>
#include <string>

#include "wire.h"

namespace kaboodle {

struct UdpSock {
  int fd = -1;

  UdpSock() = default;
  UdpSock(const UdpSock&) = delete;
  UdpSock& operator=(const UdpSock&) = delete;
  UdpSock(UdpSock&& o) noexcept : fd(o.fd) { o.fd = -1; }
  UdpSock& operator=(UdpSock&& o) noexcept;
  ~UdpSock();

  bool valid() const { return fd >= 0; }
  // >0: datagram size; 0: would-block; <0: error.
  long recv_from(uint8_t* buf, size_t cap, NetAddr* sender) const;
  bool send_to(const uint8_t* buf, size_t len, const NetAddr& dest) const;
  std::optional<NetAddr> local_addr() const;
};

// Bind a unicast socket on ip:0 (ephemeral port = the peer's address,
// kaboodle.rs:121-140).
std::optional<UdpSock> bind_unicast(const NetAddr& ip_only);

struct BroadcastPair {
  UdpSock in;
  UdpSock out;
  NetAddr dest;  // where broadcasts are sent
};

// `bcast_ip` selects the family: a v4 broadcast address (255.255.255.255 or a
// subnet broadcast) or a v6 multicast group (ff02::...). `iface_index` is
// required for v6 (multicast join + egress pinning).
std::optional<BroadcastPair> open_broadcast(const NetAddr& bcast_ip, uint16_t port,
                                            unsigned iface_index);

// The reference's interface policy (networking.rs:12-23): first non-loopback
// IPv6 interface, else first non-loopback, as "ip,ifindex"; empty on none.
std::string best_available_interface();

// All non-loopback addresses, one "family,ip,ifindex,broadcast" line each.
std::string list_interfaces();

}  // namespace kaboodle
