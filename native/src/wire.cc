#include "wire.h"

#include <arpa/inet.h>

#include <cstdio>

namespace kaboodle {

// --- NetAddr --------------------------------------------------------------

std::string NetAddr::to_string() const {
  char host[INET6_ADDRSTRLEN] = {0};
  char out[INET6_ADDRSTRLEN + 10];
  if (v6) {
    inet_ntop(AF_INET6, ip.data(), host, sizeof(host));
    std::snprintf(out, sizeof(out), "[%s]:%u", host, unsigned(port));
  } else {
    inet_ntop(AF_INET, ip.data(), host, sizeof(host));
    std::snprintf(out, sizeof(out), "%s:%u", host, unsigned(port));
  }
  return out;
}

std::optional<NetAddr> NetAddr::parse(const std::string& s) {
  NetAddr a;
  size_t colon;
  std::string host;
  if (!s.empty() && s[0] == '[') {
    size_t close = s.find("]:");
    if (close == std::string::npos) return std::nullopt;
    host = s.substr(1, close - 1);
    colon = close + 1;
    a.v6 = true;
  } else {
    colon = s.rfind(':');
    if (colon == std::string::npos) return std::nullopt;
    host = s.substr(0, colon);
    a.v6 = host.find(':') != std::string::npos;
  }
  unsigned long p = std::strtoul(s.c_str() + colon + 1, nullptr, 10);
  if (p > 0xFFFF) return std::nullopt;
  a.port = uint16_t(p);
  int af = a.v6 ? AF_INET6 : AF_INET;
  if (inet_pton(af, host.c_str(), a.ip.data()) != 1) return std::nullopt;
  return a;
}

// --- little-endian writer / prefix reader --------------------------------

namespace {

struct Writer {
  Bytes out;
  void u8(uint8_t v) { out.push_back(v); }
  void u16(uint16_t v) {
    out.push_back(v & 0xFF);
    out.push_back(v >> 8);
  }
  void u32(uint32_t v) {
    for (int i = 0; i < 4; i++) out.push_back((v >> (8 * i)) & 0xFF);
  }
  void u64(uint64_t v) {
    for (int i = 0; i < 8; i++) out.push_back((v >> (8 * i)) & 0xFF);
  }
  void raw(const uint8_t* p, size_t n) { out.insert(out.end(), p, p + n); }
  void bytes(const Bytes& b) {  // serde bytes: u64 length + raw
    u64(b.size());
    raw(b.data(), b.size());
  }
  void addr(const NetAddr& a) {  // serde SocketAddr: variant + octets + port
    u32(a.v6 ? 1 : 0);
    raw(a.ip.data(), a.v6 ? 16 : 4);
    u16(a.port);
  }
};

struct Reader {
  const uint8_t* p;
  size_t n;
  bool ok = true;

  bool take(void* dst, size_t k) {
    if (!ok || k > n) return ok = false;
    std::memcpy(dst, p, k);
    p += k;
    n -= k;
    return true;
  }
  uint8_t u8() {
    uint8_t v = 0;
    take(&v, 1);
    return v;
  }
  uint16_t u16() {
    uint8_t b[2] = {};
    take(b, 2);
    return uint16_t(b[0]) | uint16_t(b[1]) << 8;
  }
  uint32_t u32() {
    uint8_t b[4] = {};
    take(b, 4);
    uint32_t v = 0;
    for (int i = 3; i >= 0; i--) v = v << 8 | b[i];
    return v;
  }
  uint64_t u64() {
    uint8_t b[8] = {};
    take(b, 8);
    uint64_t v = 0;
    for (int i = 7; i >= 0; i--) v = v << 8 | b[i];
    return v;
  }
  Bytes bytes() {
    uint64_t k = u64();
    if (!ok || k > n) {
      ok = false;
      return {};
    }
    Bytes b(p, p + k);
    p += k;
    n -= k;
    return b;
  }
  NetAddr addr() {
    NetAddr a;
    uint32_t tag = u32();
    if (tag > 1) ok = false;
    a.v6 = tag == 1;
    take(a.ip.data(), a.v6 ? 16 : 4);
    a.port = u16();
    return a;
  }
};

Message read_message(Reader& r) {
  Message m;
  uint32_t tag = r.u32();
  if (tag > 4) {
    r.ok = false;
    return m;
  }
  m.kind = MsgKind(tag);
  switch (m.kind) {
    case MsgKind::Ping:
      break;
    case MsgKind::PingRequest:
      m.peer = r.addr();
      break;
    case MsgKind::Ack:
      m.peer = r.addr();
      m.fingerprint = r.u32();
      m.num_peers = r.u32();
      break;
    case MsgKind::KnownPeers: {
      uint64_t count = r.u64();
      for (uint64_t i = 0; r.ok && i < count; i++) {
        NetAddr a = r.addr();
        Bytes ident = r.bytes();
        if (r.ok) m.known_peers.emplace(a, std::move(ident));
      }
      break;
    }
    case MsgKind::KnownPeersRequest:
      m.fingerprint = r.u32();
      m.num_peers = r.u32();
      break;
  }
  return m;
}

void write_message(Writer& w, const Message& m) {
  w.u32(uint32_t(m.kind));
  switch (m.kind) {
    case MsgKind::Ping:
      break;
    case MsgKind::PingRequest:
      w.addr(m.peer);
      break;
    case MsgKind::Ack:
      w.addr(m.peer);
      w.u32(m.fingerprint);
      w.u32(m.num_peers);
      break;
    case MsgKind::KnownPeers:
      w.u64(m.known_peers.size());
      for (const auto& [a, ident] : m.known_peers) {
        w.addr(a);
        w.bytes(ident);
      }
      break;
    case MsgKind::KnownPeersRequest:
      w.u32(m.fingerprint);
      w.u32(m.num_peers);
      break;
  }
}

}  // namespace

// --- public codec ---------------------------------------------------------

Bytes encode_envelope(const Envelope& e) {
  Writer w;
  w.bytes(e.identity);
  write_message(w, e.msg);
  return std::move(w.out);
}

Bytes encode_broadcast(const Broadcast& b) {
  Writer w;
  w.u32(uint32_t(b.kind));
  switch (b.kind) {
    case BroadcastKind::Join:
      w.addr(b.addr);
      w.bytes(b.identity);
      break;
    case BroadcastKind::Failed:
    case BroadcastKind::Probe:
      w.addr(b.addr);
      break;
  }
  return std::move(w.out);
}

Bytes encode_probe_response(const Bytes& identity) {
  Writer w;
  w.bytes(identity);
  return std::move(w.out);
}

std::optional<Envelope> decode_envelope(const uint8_t* data, size_t len) {
  Reader r{data, len};
  Envelope e;
  e.identity = r.bytes();
  e.msg = read_message(r);
  if (!r.ok) return std::nullopt;
  return e;
}

std::optional<Broadcast> decode_broadcast(const uint8_t* data, size_t len) {
  Reader r{data, len};
  Broadcast b;
  uint32_t tag = r.u32();
  if (tag > 2) return std::nullopt;
  b.kind = BroadcastKind(tag);
  b.addr = r.addr();
  if (b.kind == BroadcastKind::Join) b.identity = r.bytes();
  if (!r.ok) return std::nullopt;
  return b;
}

// --- CRC-32 (ISO-HDLC, the crc32fast/zlib polynomial) ---------------------

namespace {
struct CrcTable {
  uint32_t t[256];
  CrcTable() {
    for (uint32_t i = 0; i < 256; i++) {
      uint32_t c = i;
      for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      t[i] = c;
    }
  }
};
const CrcTable kCrc;
}  // namespace

uint32_t crc32(const uint8_t* data, size_t len, uint32_t crc) {
  crc = ~crc;
  for (size_t i = 0; i < len; i++) crc = kCrc.t[(crc ^ data[i]) & 0xFF] ^ (crc >> 8);
  return ~crc;
}

std::string to_hex(const Bytes& b) {
  static const char* d = "0123456789abcdef";
  std::string s;
  s.reserve(b.size() * 2);
  for (uint8_t c : b) {
    s.push_back(d[c >> 4]);
    s.push_back(d[c & 15]);
  }
  return s;
}

uint32_t fingerprint(const std::map<NetAddr, Bytes>& members) {
  // std::map iterates in NetAddr order == Rust SocketAddr sort order.
  uint32_t crc = 0;
  for (const auto& [addr, identity] : members) {
    std::string s = addr.to_string();
    crc = crc32(reinterpret_cast<const uint8_t*>(s.data()), s.size(), crc);
    crc = crc32(identity.data(), identity.size(), crc);
  }
  return crc;
}

}  // namespace kaboodle
