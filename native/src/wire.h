// Wire data model + bincode-compatible codec for the kaboodle protocol.
//
// Byte-compatible with the reference's `bincode::serialize` of the structs in
// src/structs.rs (bincode 1.3 legacy config: little-endian, fixed-width ints,
// u64 sequence/byte lengths, u32 enum variant tags; serde's non-human-readable
// SocketAddr encoding: enum{V4,V6} + raw octets + u16 port).
//
// Decoders read a *prefix* of the buffer and tolerate trailing bytes — the
// reference deserializes the whole zero-padded receive buffer (quirk Q2,
// kaboodle.rs:259,397; discovery.rs:81), and probe replies depend on it (Q4).
#pragma once

#include <array>
#include <cstdint>
#include <cstring>
#include <map>
#include <optional>
#include <string>
#include <vector>

namespace kaboodle {

using Bytes = std::vector<uint8_t>;

// A peer address (the reference's `Peer = SocketAddr`). Ordering matches
// Rust's `SocketAddr: Ord` (V4 < V6, then ip octets, then port) — the sort
// the fingerprint depends on (kaboodle.rs:72-73).
struct NetAddr {
  bool v6 = false;
  std::array<uint8_t, 16> ip{};  // v4 uses ip[0..4]
  uint16_t port = 0;
  // v6 scope (interface index) for link-local addresses. NOT part of
  // identity/ordering/wire form — Rust's Display and the serde encoding both
  // omit it — but required by the OS to bind/send fe80:: addresses.
  uint32_t scope = 0;

  bool is_link_local_v6() const { return v6 && ip[0] == 0xfe && (ip[1] & 0xc0) == 0x80; }

  friend bool operator==(const NetAddr& a, const NetAddr& b) {
    return a.v6 == b.v6 && a.port == b.port && a.ip == b.ip;
  }
  friend bool operator<(const NetAddr& a, const NetAddr& b) {
    if (a.v6 != b.v6) return !a.v6;
    size_t n = a.v6 ? 16 : 4;
    int c = std::memcmp(a.ip.data(), b.ip.data(), n);
    if (c != 0) return c < 0;
    return a.port < b.port;
  }

  // Rust `SocketAddr: Display` format: "a.b.c.d:port" / "[v6]:port".
  std::string to_string() const;
  static std::optional<NetAddr> parse(const std::string& s);
};

// SwimMessage variant tags, in declaration order (structs.rs:94-115).
enum class MsgKind : uint32_t {
  Ping = 0,
  PingRequest = 1,
  Ack = 2,
  KnownPeers = 3,
  KnownPeersRequest = 4,
};

// SwimBroadcast variant tags (structs.rs:65-73).
enum class BroadcastKind : uint32_t { Join = 0, Failed = 1, Probe = 2 };

// One decoded unicast message (the payload of a SwimEnvelope). Unused fields
// are empty/zero for variants that do not carry them.
struct Message {
  MsgKind kind = MsgKind::Ping;
  NetAddr peer{};                          // PingRequest / Ack
  uint32_t fingerprint = 0;                // Ack / KnownPeersRequest
  uint32_t num_peers = 0;                  // Ack / KnownPeersRequest
  std::map<NetAddr, Bytes> known_peers{};  // KnownPeers
};

struct Envelope {
  Bytes identity;
  Message msg;
};

struct Broadcast {
  BroadcastKind kind = BroadcastKind::Join;
  NetAddr addr{};  // Join.addr / Failed peer / Probe addr
  Bytes identity;  // Join only
};

// --- codec ---------------------------------------------------------------

Bytes encode_envelope(const Envelope& e);
Bytes encode_broadcast(const Broadcast& b);
Bytes encode_probe_response(const Bytes& identity);

// Prefix decoders (Q2): nullopt only on genuinely malformed/truncated input.
std::optional<Envelope> decode_envelope(const uint8_t* data, size_t len);
std::optional<Broadcast> decode_broadcast(const uint8_t* data, size_t len);

// --- fingerprint (kaboodle.rs:71-83) -------------------------------------

uint32_t crc32(const uint8_t* data, size_t len, uint32_t crc = 0);

// CRC-32 over peers sorted by address order: for each, the Display-format
// address bytes then the raw identity bytes.
uint32_t fingerprint(const std::map<NetAddr, Bytes>& members);

std::string to_hex(const Bytes& b);

}  // namespace kaboodle
