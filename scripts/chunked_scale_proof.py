"""North-star-scale protocol ticks via the chunked (row-blocked) kernel.

The whole-tensor kernel cannot execute any tick at N=65,536 on the
emulating host — eight documented attempts OOM-killed a 125 GiB machine
(SCALE_PROOF.md attempts 1-6, 8): XLA:CPU materializes enough [N, N]
int32-scale temporaries per tick to exceed RAM no matter how the run is
staged. ``make_chunked_tick_fn`` (sim/chunked.py) bounds every pass to
O(block·N) transients, which turns the 65k *full protocol tick* from
impossible into routine on this host.

Single-device by design (the chunked kernel's documented scope): the
sharded story — GSPMD behavior, collectives, multihost — is proven by
scripts/sharded_scale_proof.py at N<=32,768; THIS proof is about executing
the full tick at the north-star N with real fault inputs. The join
avalanche (all-N broadcast boot) remains out of scope at 65k for compute,
not memory: the O(N^3) gossip-union contraction is ~2.8e14 int8-ops, days
on this host's single core (it rides 8 MXUs on the real v5e-8); the
revive/join machinery at scale is scale-proof-32k's job.

Phases (PHASE lines bank incrementally; one final JSON line):
1. Boot. ``--boot converged`` (default): everyone-knows-everyone init,
   asserted through the standalone fingerprint-agreement check
   (parallel.sharded_convergence_check — the same predicate,
   single-device here). ``--boot broadcast``: the REAL join avalanche —
   fresh singleton maps, every peer broadcasts Join at tick 0 — executed
   through the chunked kernel's closed-form avalanche union
   (``boot_union=True``, exact on precisely this tick shape; see
   make_chunked_tick_fn), asserted converged. The closed form is what
   makes this tick compute-feasible on a single core: the dense union is
   ~2.8e14 int8-ops at N=65,536, the closed form is O(N^2) elementwise.
2. ``--ticks`` faulty ticks, stepwise with donated carry: kills at tick 0
   (suspicion -> escalation -> indirect pings fire from tick
   ping_timeout+1 on), a partition window, manual pings each tick.
   Drop stays off (the budget notes in sim/chunked.py; pass --drop-rate
   to exercise the D10 resident at smaller N).
"""

from __future__ import annotations

import argparse
import json
import os
import resource
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _rss_mib() -> float:
    return round(resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024, 1)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--n", type=int, default=65536)
    p.add_argument("--block", type=int, default=2048)
    p.add_argument("--ticks", type=int, default=4)
    p.add_argument("--kill-count", type=int, default=64)
    p.add_argument("--drop-rate", type=float, default=0.0)
    p.add_argument("--boot", choices=["converged", "broadcast"],
                   default="converged")
    p.add_argument("--boot-max-ticks", type=int, default=4,
                   help="broadcast boot: convergence budget (W3: ~1 tick)")
    args = p.parse_args()

    from axon_guard import strip_axon_plugin

    strip_axon_plugin()
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from kaboodle_tpu.config import SwimConfig
    from kaboodle_tpu.parallel import sharded_convergence_check
    from kaboodle_tpu.sim.chunked import make_chunked_tick_fn
    from kaboodle_tpu.sim.state import TickInputs, init_state

    n, ticks, block = args.n, args.ticks, args.block
    line = {
        "n": n,
        "block": block,
        "devices": 1,
        "backend": jax.default_backend(),
        "kernel": "chunked",
        "state_variant": "lean+int16",
    }

    # ---- phase 1: boot, asserted -----------------------------------------
    t0 = time.perf_counter()
    if args.boot == "converged":
        # announced=True: a converged mesh has already broadcast itself —
        # without it every peer re-announces Join at the first faulty tick
        # (an all-N avalanche with zero new joiners; pure waste, and the
        # old dense union made it the dominant cost of that tick).
        st = init_state(n, seed=0, ring_contacts=n - 1,
                        track_latency=False, instant_identity=True,
                        timer_dtype=jnp.int16, announced=True)
        conv, _, _, n_alive = sharded_convergence_check(st)
        assert bool(conv) and int(n_alive) == n
        line["boot"] = {
            "mode": "converged",
            "converged": True,
            "wall_s": round(time.perf_counter() - t0, 3),
        }
    else:
        # The join avalanche itself: fresh singleton maps, everyone
        # broadcasts Join at tick 0 (the boot_union precondition, which
        # only holds for that first tick — later ticks of this loop bear
        # no joins, so the special union branch never runs again).
        from kaboodle_tpu.sim.state import idle_inputs

        st = init_state(n, seed=0, ring_contacts=0,
                        track_latency=False, instant_identity=True,
                        timer_dtype=jnp.int16)
        boot_cfg = SwimConfig()
        boot_tick = jax.jit(
            make_chunked_tick_fn(boot_cfg, faulty=False, block=block,
                                 boot_union=True),
            donate_argnums=0,
        )
        idle = idle_inputs(n)
        boot_ticks = 0
        conv = False
        for _ in range(args.boot_max_ticks):
            st, m = boot_tick(st, idle)
            boot_ticks += 1
            print("PHASE " + json.dumps({
                "boot_tick": boot_ticks,
                "messages_delivered": int(m.messages_delivered),
                "converged": bool(m.converged),
                "mean_membership": round(float(m.mean_membership), 1),
                "wall_s": round(time.perf_counter() - t0, 3),
                "peak_rss_mib": _rss_mib(),
            }), flush=True)
            if bool(m.converged):
                conv = True
                break
        assert conv, f"broadcast boot did not converge in {boot_ticks} ticks"
        line["boot"] = {
            "mode": "broadcast",
            "ticks_to_convergence": boot_ticks,
            "converged": True,
            "wall_s": round(time.perf_counter() - t0, 3),
        }
    print("PHASE " + json.dumps({**line["boot"], "peak_rss_mib": _rss_mib()}),
          flush=True)

    # ---- phase 2: the full faulty tick, stepwise -------------------------
    # Kills at tick 0 so later ticks bear the suspicion -> escalation ->
    # indirect-ping -> removal machinery at full N; partition from tick 1;
    # one manual ping per tick from peer 0.
    cfg = SwimConfig()
    rng = np.random.default_rng(0)
    kill_idx = rng.choice(n, size=min(args.kill_count, n // 2), replace=False)
    drop = args.drop_rate > 0
    tick_fn = jax.jit(
        make_chunked_tick_fn(cfg, faulty=True, block=block, drop=drop),
        donate_argnums=0,
    )

    t0 = time.perf_counter()
    msgs_per_tick = []
    for t in range(ticks):
        kill = np.zeros((n,), bool)
        if t == 0:
            kill[kill_idx] = True
        part = np.zeros((n,), np.int32)
        if t >= 1:
            part[: n // 2] = 1
        man = np.full((n,), -1, np.int32)
        man[0] = 1
        inp = TickInputs(
            kill=jnp.asarray(kill),
            revive=jnp.zeros((n,), bool),
            partition=jnp.asarray(part),
            drop_rate=jnp.float32(args.drop_rate),
            manual_target=jnp.asarray(man),
        )
        st, m = tick_fn(st, inp)
        msgs = int(m.messages_delivered)
        msgs_per_tick.append(msgs)
        print("PHASE " + json.dumps({
            "faulty_tick": t,
            "messages_delivered": msgs,
            "converged": bool(m.converged),
            "mean_membership": round(float(m.mean_membership), 1),
            "wall_s": round(time.perf_counter() - t0, 3),
            "peak_rss_mib": _rss_mib(),
        }), flush=True)
    run_s = time.perf_counter() - t0

    alive = np.asarray(st.alive)
    assert int(alive.sum()) == n - len(kill_idx)
    assert all(m > 0 for m in msgs_per_tick)
    esc_ticks = max(0, ticks - cfg.ping_timeout_ticks)
    if esc_ticks:
        # Direct evidence the suspicion/escalation path executed at this N:
        # survivors escalated timed-out dead-peer entries to
        # WaitingForIndirectPing (removal of those entries takes a further
        # ping_timeout, so with ticks <= timeout + ~2N they must be visible).
        from kaboodle_tpu.spec import WAITING_FOR_INDIRECT_PING

        state = np.asarray(st.state)
        assert (state[alive] == WAITING_FOR_INDIRECT_PING).any(), (
            "no escalation reached WaitingForIndirectPing — the suspicion "
            "path did not execute")
    line.update({
        "ticks": ticks,
        "drop_rate": args.drop_rate,
        "killed": int(len(kill_idx)),
        "run_s": round(run_s, 3),
        "run_includes_compile": True,
        "messages_per_tick": msgs_per_tick,
        "escalation_bearing_ticks": esc_ticks,
        "escalation_asserted": bool(esc_ticks),
        "peak_rss_mib": _rss_mib(),
        "faulty": True,
    })
    print(json.dumps(line))


if __name__ == "__main__":
    main()
