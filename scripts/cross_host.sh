#!/usr/bin/env bash
# Two-machine real-network demo — the tailscale-recipe analogue
# (reference justfile:57-78). Run this on EACH machine on the same L2
# segment or tailnet; the instances find each other with zero configuration
# via the transport's IPv6 link-local multicast group (ff02::1213:1989, the
# reference's group) or IPv4 broadcast.
#
#   ./scripts/cross_host.sh                  # auto: prefer tailscale v6, else v6, else v4
#   ./scripts/cross_host.sh v4               # force IPv4 broadcast
#   ./scripts/cross_host.sh 100.x.y.z        # bind an explicit address
#   ./scripts/cross_host.sh v6 --probe       # one-shot mesh probe instead of joining
#
# Extra args after the interface spec pass through to the CLI
# (`python -m kaboodle_tpu --help` for the list: --port, --identity,
# --period-ms, --ping, --probe, --duration ...).
set -euo pipefail
cd "$(dirname "$0")/.."

SPEC="${1:-}"
[ $# -gt 0 ] && shift

if [ -z "${SPEC}" ]; then
    # Prefer the tailscale IPv6 address when a tailnet is up — same
    # preference as the reference's `just tailscale` recipe — otherwise let
    # the CLI's own best-interface selection pick (v6 first, then v4).
    if hash tailscale 2>/dev/null; then
        TS_ADDR="$(tailscale ip --6 2>/dev/null || true)"
        if [ -n "${TS_ADDR}" ]; then
            SPEC="${TS_ADDR}"
            echo "cross-host: using tailscale IPv6 ${SPEC}" >&2
        fi
    fi
fi

make -s native
if [ -n "${SPEC}" ]; then
    exec python -m kaboodle_tpu --interface "${SPEC}" "$@"
else
    exec python -m kaboodle_tpu "$@"
fi
