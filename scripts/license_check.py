"""Dependency-license gate — the cargo-deny `check licenses` analogue
(reference .github/workflows/main.yml:55-62, deny.toml).

The runtime dependency surface is deliberately tiny (pyproject.toml: jax,
numpy, plus the optional test extra), so the gate is a direct metadata
check: every installed dependency in the transitive closure of our declared
deps must carry an allowed (permissive) license. Fails the build on a
missing or non-permissive license, exactly like cargo-deny's deny-by-default
posture. No network, no extra tooling — importlib.metadata only.
"""

from __future__ import annotations

import sys
from importlib import metadata

# Permissive licenses this project accepts (deny.toml listed the SPDX ids
# the reference allowed; the Python ecosystem spells them many ways).
ALLOWED_SUBSTRINGS = (
    "apache",
    "bsd",
    "mit",
    "psf",
    "python software foundation",
    "isc",
    "unlicense",
    "mpl",  # weak copyleft: allowed as the reference's deny.toml allowed MPL-2.0
    "zlib",
    "public domain",
)

# Declared runtime + test deps (pyproject.toml); their transitive closure is
# resolved live from installed metadata.
ROOTS = ["jax", "numpy", "pytest", "hypothesis"]


def _license_of(dist: metadata.Distribution) -> str:
    md = dist.metadata
    lic = (md.get("License-Expression") or md.get("License") or "").strip()
    # Many wheels leave License empty/UNKNOWN and use trove classifiers.
    if not lic or lic.upper() == "UNKNOWN" or len(lic) > 200:
        for cl in md.get_all("Classifier") or []:
            if cl.startswith("License ::"):
                lic = cl.split("::")[-1].strip()
                break
    return lic


def _requires(name: str) -> list[str]:
    try:
        reqs = metadata.requires(name) or []
    except metadata.PackageNotFoundError:
        return []
    out = []
    for r in reqs:
        try:
            from packaging.requirements import Requirement

            req = Requirement(r)
            # Evaluate plain environment markers for THIS interpreter (a
            # python_version-gated dep that is installed here must be
            # checked); only extra-gated deps are skipped — we install none.
            if req.marker is not None and not req.marker.evaluate({"extra": ""}):
                continue
            out.append(req.name)
        except Exception:
            # No packaging / unparsable requirement: fall back to a bare
            # name split, keeping markerless requirements only.
            if ";" in r:
                continue
            for sep in "<>=!~ ([":
                r = r.split(sep)[0]
            if r:
                out.append(r.strip())
    return out


def main() -> int:
    seen: dict[str, str] = {}
    stack = list(ROOTS)
    while stack:
        name = stack.pop()
        key = name.lower()
        if key in seen:
            continue
        try:
            dist = metadata.distribution(name)
        except metadata.PackageNotFoundError:
            continue  # optional extra not installed in this environment
        seen[key] = _license_of(dist)
        stack.extend(_requires(name))

    bad = {
        name: lic or "<missing>"
        for name, lic in sorted(seen.items())
        if not any(s in lic.lower() for s in ALLOWED_SUBSTRINGS)
    }
    for name, lic in sorted(seen.items()):
        mark = "FAIL" if name in bad else "ok"
        print(f"{mark:4} {name}: {lic or '<missing>'}")
    if bad:
        print(f"\nlicense check FAILED for {len(bad)} package(s): "
              f"{', '.join(bad)}", file=sys.stderr)
        return 1
    print(f"\nlicense check ok: {len(seen)} packages, all permissive")
    return 0


if __name__ == "__main__":
    sys.exit(main())
