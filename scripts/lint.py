"""Minimal static lint gate — the CI clippy/fmt analogue (main.yml:48-52).

The build environment ships no ruff/flake8/pyflakes and installs are not
allowed, so this is a dependency-free AST checker for the two classes of
defect that static analysis catches cheaply and that have actually bitten
this repo:

- **undefined names** (a module-level reference to a deleted/renamed
  function — exactly the round-2 `NameError` that broke HEAD), and
- **unused imports** (the most common dead-code drift).

Scope approximation: names defined *anywhere* in a module (any scope) count
as defined everywhere in it. That misses scope-escape bugs but has no false
positives on idiomatic code, which is the right trade for a `-D warnings`
style gate. Lines containing ``# noqa`` are exempt.

Usage: python scripts/lint.py [paths...]   (default: kaboodle_tpu tests
bench.py __graft_entry__.py scripts)
"""

from __future__ import annotations

import ast
import builtins
import pathlib
import sys

IMPLICIT = {
    "__file__", "__name__", "__doc__", "__package__", "__spec__",
    "__loader__", "__builtins__", "__debug__", "__class__", "__annotations__",
}


def _collect_defined(tree: ast.AST) -> tuple[set, dict]:
    """All names bound anywhere (any scope), plus import bindings -> lineno."""
    defined = set(dir(builtins)) | IMPLICIT
    imports: dict[str, tuple[int, bool]] = {}  # name -> (lineno, is_future)
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                name = (a.asname or a.name).split(".")[0]
                defined.add(name)
                imports.setdefault(name, (node.lineno, False))
        elif isinstance(node, ast.ImportFrom):
            future = node.module == "__future__"
            for a in node.names:
                if a.name == "*":
                    continue
                name = a.asname or a.name
                defined.add(name)
                imports.setdefault(name, (node.lineno, future))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            defined.add(node.name)
        elif isinstance(node, ast.Name) and isinstance(node.ctx, (ast.Store, ast.Del)):
            defined.add(node.id)
        elif isinstance(node, ast.arg):
            defined.add(node.arg)
        elif isinstance(node, ast.ExceptHandler) and node.name:
            defined.add(node.name)
        elif isinstance(node, (ast.Global, ast.Nonlocal)):
            defined.update(node.names)
        elif isinstance(node, (ast.MatchAs, ast.MatchStar)) and node.name:
            defined.add(node.name)
        elif isinstance(node, ast.MatchMapping) and node.rest:
            defined.add(node.rest)
    return defined, imports


def _collect_used(tree: ast.AST) -> tuple[set, list]:
    """Names loaded anywhere + every (lineno, name) load for the checker."""
    used = set()
    loads = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            used.add(node.id)
            loads.append((node.lineno, node.id))
    # __all__ re-export strings count as uses (package __init__ pattern).
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Assign)
            and any(
                isinstance(t, ast.Name) and t.id == "__all__" for t in node.targets
            )
            and isinstance(node.value, (ast.List, ast.Tuple))
        ):
            for elt in node.value.elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                    used.add(elt.value)
    return used, loads


def check_file(path: pathlib.Path) -> list[str]:
    src = path.read_text()
    try:
        tree = ast.parse(src, str(path))
    except SyntaxError as e:
        return [f"{path}:{e.lineno}: syntax error: {e.msg}"]
    src_lines = src.splitlines()

    def noqa(lineno: int) -> bool:
        return 0 < lineno <= len(src_lines) and "noqa" in src_lines[lineno - 1]

    defined, imports = _collect_defined(tree)
    used, loads = _collect_used(tree)

    errors = []
    for lineno, name in loads:
        if name not in defined and not noqa(lineno):
            errors.append(f"{path}:{lineno}: undefined name '{name}'")
    for name, (lineno, future) in imports.items():
        if future or name == "_" or noqa(lineno):
            continue
        if name not in used:
            errors.append(f"{path}:{lineno}: unused import '{name}'")
    return errors


def main(argv: list[str]) -> int:
    targets = argv or [
        "kaboodle_tpu", "tests", "scripts", "bench.py", "__graft_entry__.py"
    ]
    files: list[pathlib.Path] = []
    for t in targets:
        p = pathlib.Path(t)
        files.extend(sorted(p.rglob("*.py")) if p.is_dir() else [p])
    errors = []
    for f in files:
        errors.extend(check_file(f))
    for e in errors:
        print(e)
    print(f"lint: {len(files)} files, {len(errors)} errors", file=sys.stderr)
    return 1 if errors else 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
