"""Shim: the lint gate moved to graftlint (python -m kaboodle_tpu.analysis).

Kept so old invocations (`python scripts/lint.py [paths...]`) still work;
the two original checks live on as rules KB101/KB102 there.
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from kaboodle_tpu.analysis.cli import main  # noqa: E402

if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
