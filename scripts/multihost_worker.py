"""One process of the multi-host proof rig (tests/test_multihost.py).

Each process owns 4 virtual CPU devices; ``make_multihost_mesh`` joins them
into one global 8-device peer-axis mesh (collectives over gloo — the DCN
stand-in), and the full sharded tick runs over it. Prints a trajectory
digest the test compares across processes and against the single-process
run: identical programs over ICI-only and cross-process meshes must produce
identical protocol trajectories (SURVEY.md §2.3 distributed-backend slot).

Usage: multihost_worker.py <process_id> <num_processes> <port> <n> <ticks>
"""

import json
import os
import sys

# Env must be pinned before anything imports jax.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=4"
).strip()

import jax  # noqa: E402
import numpy as np  # noqa: E402

from kaboodle_tpu.config import SwimConfig  # noqa: E402
from kaboodle_tpu.parallel import (  # noqa: E402
    make_multihost_mesh,
    shard_inputs,
    shard_state,
    simulate_sharded,
)
from kaboodle_tpu.sim.state import idle_inputs, init_state  # noqa: E402


def main() -> None:
    pid, nproc, port, n, ticks = (int(a) for a in sys.argv[1:6])
    mesh = make_multihost_mesh(f"127.0.0.1:{port}", num_processes=nproc, process_id=pid)
    # Host-local -> global placement: identical values exist in every process,
    # so device_put just carves out each process's addressable shards. The
    # memory-lean state (the realistic multi-host config, MEMORY_PLAN.md)
    # also avoids the NaN-filled latency tensor, which jax's cross-process
    # device_put equality check would reject (NaN != NaN elementwise).
    st = init_state(n, seed=3, track_latency=False, instant_identity=True)
    st = shard_state(jax.tree.map(np.asarray, st), mesh)
    inp = shard_inputs(idle_inputs(n, ticks=ticks), mesh, stacked=True)
    cfg = SwimConfig(deterministic=True)
    out, m = simulate_sharded(st, inp, cfg, mesh, faulty=False)

    # Metrics are full reductions -> replicated, addressable everywhere.
    digest = {
        "process": pid,
        "n_global_devices": mesh.size,
        "messages": np.asarray(m.messages_delivered).tolist(),
        "fp_min": np.asarray(m.fingerprint_min).tolist(),
        "fp_max": np.asarray(m.fingerprint_max).tolist(),
        "converged": np.asarray(m.converged).tolist(),
        "final_tick": int(out.tick),
    }
    print("MHDIGEST " + json.dumps(digest), flush=True)


if __name__ == "__main__":
    main()
