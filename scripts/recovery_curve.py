"""Churn-recovery curve: agreement fraction per tick after the config-3
churn window closes (VERDICT r4 item 3's PERF.md curve).

Runs the BASELINE config-3 schedule (5%/tick join+leave churn over the
first half) at ``--n``, then keeps scanning calm ticks in chunks, recording
``TickMetrics.agree_fraction`` / ``converged`` per tick until agreement or
the ~2.5N budget. Prints one JSON line with a downsampled curve.

The shape of the curve is the suspicion/removal pipeline in action
(kaboodle.rs:558-653): a long flat head while every survivor's oldest-5
rotation works through its backlog of equal-age entries, then a rapid climb
as removals complete (the reference's ~2N completeness bound, SURVEY §6).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--n", type=int, default=1024)
    p.add_argument("--ticks", type=int, default=64, help="churn-window run length")
    p.add_argument("--chunk", type=int, default=64)
    p.add_argument("--points", type=int, default=64,
                   help="max curve points in the output (downsampled)")
    args = p.parse_args()

    from axon_guard import strip_axon_plugin

    strip_axon_plugin()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")

    import numpy as np

    from bench import _recovery_budget, _scenario_state_and_inputs
    from kaboodle_tpu.config import SwimConfig
    from kaboodle_tpu.sim.runner import simulate
    from kaboodle_tpu.sim.state import idle_inputs

    n, ticks = args.n, args.ticks
    cfg = SwimConfig()
    budget = _recovery_budget(n)
    st, inp = _scenario_state_and_inputs(3, n, ticks, calm_budget=budget)

    # jit once so the calm-chunk loop reuses one compiled scan instead of
    # re-tracing per chunk (bench._bench_churn_recovery's pattern).
    run_churn = jax.jit(lambda s, i: simulate(s, i, cfg, faulty=True))
    run_calm = jax.jit(lambda s, i: simulate(s, i, cfg, faulty=False))

    t0 = time.perf_counter()
    st, m = run_churn(st, inp)
    agree = list(np.asarray(m.agree_fraction))
    conv = list(np.asarray(m.converged))
    memb = list(np.asarray(m.mean_membership))

    calm = idle_inputs(n, ticks=args.chunk)
    while not conv[-1] and len(conv) < ticks + budget:
        st, m = run_calm(st, calm)
        agree.extend(np.asarray(m.agree_fraction))
        conv.extend(np.asarray(m.converged))
        memb.extend(np.asarray(m.mean_membership))
    wall = time.perf_counter() - t0

    stop = ticks // 2  # churn window closes here (baseline_scenario config 3)
    first_true = next((i for i, c in enumerate(conv) if i >= stop and c), None)
    # Downsample the curve for the report; keep the exact endpoints.
    idxs = sorted({0, stop, len(agree) - 1}
                  | set(range(0, len(agree), max(1, len(agree) // args.points))))
    # mean_membership is the readable recovery signal: agreement-with-min is
    # a step function (one peer holds the min until the final removal wave),
    # while mean row membership drains ~linearly as the pipeline completes.
    curve = [[int(i), round(float(agree[i]), 4), round(float(memb[i]), 1)]
             for i in idxs]
    print(json.dumps({
        "n": n,
        "churn_ticks": stop,
        "churn_rate": 0.05,
        "survivors": int(np.asarray(st.alive).sum()),
        "reconverged": bool(conv[-1]),
        "reconverge_tick_abs": first_true,
        "reconverge_ticks_after_churn": (
            (first_true - stop) if first_true is not None else None),
        "completeness_bound_2n": 2 * n,
        "curve_fields": ["tick", "agree_fraction", "mean_membership"],
        "curve": curve,
        "wall_s": round(wall, 2),
    }))


if __name__ == "__main__":
    main()
