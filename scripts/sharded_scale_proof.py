"""Sharded-scale proof: the GSPMD program *behaves* well beyond toy shapes.

Two phases, both under the peer-axis mesh (SURVEY.md §2.3 / BASELINE
configs 4-5), so the proof is behavioral, not just "it executed sharded"
(VERDICT r3 item 5):

1. **Boot to convergence** — ``--boot epidemic``: no broadcast medium, ring
   seed contacts, fresh gossip stamps (the O(log N) epidemic boot); or
   ``--boot broadcast``: the reference's Join-broadcast boot (W3: converges
   in ~1 tick — the only affordable mode at N=65,536 on a single-core
   virtual mesh). Either way the run *asserts* the converged flag computed
   by the sharded fingerprint check (per-shard reduction + all-reduce over
   the peer axis — the ICI all-reduce of BASELINE config 4).
2. **Steady-state faulty scan** — the every-fault-path schedule (kill,
   revive, partition, optional drop, manual pings) for ``--ticks`` ticks,
   asserting the final state stays sharded across the full mesh.

Memory is recorded (peak RSS here; on TPU the bench records
``peak_hbm_mib``) so MEMORY_PLAN.md's budget table gets observed numbers.

Run via ``make scale-proof`` / ``make scale-proof-65k``; results are
recorded in SCALE_PROOF.md. Prints one JSON line.
"""

from __future__ import annotations

import argparse
import json
import os
import resource
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--n", type=int, default=4096)
    p.add_argument("--devices", type=int, default=8)
    p.add_argument("--ticks", type=int, default=8)
    p.add_argument("--boot", choices=["none", "epidemic", "broadcast", "converged"],
                   default="epidemic",
                   help="converged = start from the everyone-knows-everyone "
                        "state (ring_contacts=n-1) and assert it through the "
                        "standalone sharded all-reduce fingerprint check — "
                        "NO protocol tick runs, so this lands at sizes where "
                        "any full tick's 8-shard working set exceeds host "
                        "RAM (N=65,536; the boot-to-convergence and "
                        "full-fault proofs run at N<=32,768)")
    p.add_argument("--boot-max-ticks", type=int, default=512)
    p.add_argument("--drop-rate", type=float, default=0.05,
                   help="faulty-scan drop rate; 0 skips the [N, N] uniform "
                        "draw entirely (the N=65,536 memory budget needs that)")
    p.add_argument("--faulty-runs", type=int, default=2, choices=[1, 2],
                   help="2 = compile run + timed run (compile_s/run_s split); "
                        "1 = a single execution reported as run_s with "
                        "compile included — for sizes where one faulty tick "
                        "costs tens of minutes on the emulating host")
    p.add_argument("--no-revive", action="store_true",
               help="drop the revive event from the faulty schedule so the "
                    "join-gossip path never executes at runtime (its 8-shard "
                    "working set is what OOMs the emulating host at N=65,536)")
    p.add_argument("--stepwise", action="store_true",
                   help="tick-at-a-time host loop with donated carries instead "
                        "of while_loop/scan: every tick's transients are freed "
                        "between steps and the carry is donated, cutting peak "
                        "RSS on the emulating host (the N=65,536 while_loop "
                        "boot OOM-kills a 125 GiB host; implies the "
                        "single-run compile-included timing)")
    args = p.parse_args()

    # Pin the virtual-CPU platform before JAX can initialize any backend
    # (same ordering contract as tests/conftest.py / __graft_entry__.py),
    # and strip the tunnel plugin, whose import hangs while wedged.
    from axon_guard import strip_axon_plugin

    strip_axon_plugin()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", args.devices)

    from kaboodle_tpu.config import SwimConfig
    from kaboodle_tpu.parallel import (
        make_mesh,
        make_sharded_tick,
        run_until_converged_sharded,
        shard_inputs,
        shard_state,
        sharded_convergence_check,
        simulate_sharded,
    )
    from kaboodle_tpu.sim.scenario import all_fault_paths_scenario
    from kaboodle_tpu.sim.state import idle_inputs, init_state

    from bench import LEAN_STATE_MIN_N

    n, ticks = args.n, args.ticks
    mesh = make_mesh(args.devices)
    # MEMORY_PLAN.md policy: large N automatically selects the memory-lean
    # state (no latency EWMA / instant identity) — same rule as bench.py.
    import jax.numpy as jnp

    lean = n >= LEAN_STATE_MIN_N
    # int16 timers only while the run cannot reach the dtype's max tick
    # (init_state contract) — same policy as bench.py. Budget the boot too.
    total_ticks = ticks + (args.boot_max_ticks if args.boot != "none" else 0)
    narrow = lean and total_ticks < jnp.iinfo(jnp.int16).max
    timer_dtype = jnp.int16 if narrow else jnp.int32

    line = {
        "n": n,
        "devices": args.devices,
        "backend": jax.default_backend(),
        "state_variant": ("lean+int16" if narrow else "lean") if lean else "full",
    }

    # ---- phase 1: boot to convergence under GSPMD --------------------------
    if args.boot != "none":
        epidemic = args.boot == "epidemic"
        # fast_path off on the CPU backend: the two-branch fault-free tick
        # roughly doubles XLA:CPU's peak buffer allocation (both cond
        # branches' temporaries), which the memory-bound emulating host
        # cannot afford — the first N=65,536 retry with the split tick
        # OOM-killed in THIS boot phase at ~174 GiB where the single-path
        # build peaked at ~131 GiB (SCALE_PROOF.md attempts 3/5). Non-CPU
        # backends keep the default (on TPU the split tick is faster and
        # showed no memory incident).
        boot_cfg = SwimConfig(
            join_broadcast_enabled=not epidemic,
            backdate_gossip_inserts=not epidemic,
            fast_path=jax.default_backend() != "cpu",
        )
        ring = {"epidemic": 2, "broadcast": 0, "converged": n - 1}[args.boot]
        st0 = shard_state(
            # announced on the converged init only: that state models an
            # already-running mesh (see init_state docstring).
            init_state(n, seed=0, ring_contacts=ring,
                       track_latency=not lean, instant_identity=lean,
                       timer_dtype=timer_dtype,
                       announced=args.boot == "converged"),
            mesh,
        )
        t0 = time.perf_counter()
        if args.boot == "converged":
            # Already-full membership: assert agreement through the
            # standalone sharded fingerprint check (per-shard reduction +
            # peer-axis all-reduce — the config-4 "ICI all-reduce" check)
            # WITHOUT a protocol tick around it. At N=65,536 even one full
            # tick's XLA:CPU working set exceeds this host (~131 GiB,
            # attempts 3/5/6); the check's footprint is one masked read of
            # ``state``, so the converged-init assertion always lands.
            conv, _, _, n_alive = sharded_convergence_check(st0)
            assert int(n_alive) == n
            booted, conv_v, boot_ticks_v = st0, bool(conv), 0
        elif args.stepwise:
            boot_tick = jax.jit(
                make_sharded_tick(boot_cfg, mesh, faulty=False), donate_argnums=0
            )
            idle = shard_inputs(idle_inputs(n), mesh)
            booted, conv_v, boot_ticks_v = st0, False, 0
            for _ in range(args.boot_max_ticks):
                booted, m = boot_tick(booted, idle)
                boot_ticks_v += 1
                if bool(m.converged):  # host fetch syncs the tick
                    conv_v = True
                    break
        else:
            booted, boot_ticks, conv = run_until_converged_sharded(
                st0, boot_cfg, mesh, max_ticks=args.boot_max_ticks
            )
            boot_ticks_v, conv_v = int(boot_ticks), bool(conv)
        boot_wall = time.perf_counter() - t0
        assert conv_v, (
            f"{args.boot} boot failed to converge within "
            f"{args.boot_max_ticks} ticks at N={n}"
        )
        assert len(booted.state.sharding.device_set) == args.devices
        line["boot"] = {
            "mode": args.boot,
            "ticks_to_convergence": boot_ticks_v,
            "converged": conv_v,
            "wall_s": round(boot_wall, 3),
        }
        # Bank the boot result the moment it lands: a multi-hour run killed
        # mid-faulty-phase still leaves the asserted-convergence evidence.
        print("PHASE " + json.dumps({
            **line["boot"],
            "peak_rss_mib": round(
                resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024, 1),
        }), flush=True)
        start = booted  # steady-state scan continues from the converged mesh
    else:
        start = shard_state(
            init_state(n, seed=0, track_latency=not lean, instant_identity=lean,
                       timer_dtype=timer_dtype),
            mesh,
        )

    # ---- phase 2: every-fault-path steady-state scan -----------------------
    # --ticks 0 = boot/assertion proof only (the always-completing
    # scale-proof-65k shape; the faulty tick is the separate best-effort
    # scale-proof-65k-faulty target).
    if ticks == 0:
        peak_rss_mib = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024
        assert len(start.state.sharding.device_set) == args.devices
        line.update({
            "ticks": 0,
            "peak_rss_mib": round(peak_rss_mib, 1),
            "faulty": False,
        })
        print(json.dumps(line))
        return

    cfg = SwimConfig()
    # --no-revive: same schedule minus revive — a revive re-enters through the
    # Join path, whose gossip-share working set is the N=65,536 OOM driver;
    # the revive/join machinery itself is proven at N<=32,768 (and by the
    # driver dry run, which keeps the full schedule).
    sched = all_fault_paths_scenario(
        n, ticks=ticks, drop_rate=args.drop_rate, revive=not args.no_revive
    ).build()

    if args.stepwise:
        ftick = jax.jit(make_sharded_tick(cfg, mesh, faulty=True), donate_argnums=0)
        t0 = time.perf_counter()
        final = start
        for t in range(ticks):
            inp_t = shard_inputs(jax.tree.map(lambda x: x[t], sched), mesh)
            final, m = ftick(final, inp_t)
            print("PHASE " + json.dumps({
                "faulty_tick": t,
                "messages_delivered": int(m.messages_delivered),
                "wall_s": round(time.perf_counter() - t0, 3),
                "peak_rss_mib": round(
                    resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024, 1),
            }), flush=True)
        final.state.block_until_ready()
        first_wall = run_wall = time.perf_counter() - t0  # includes compile
    else:
        inp = shard_inputs(sched, mesh, stacked=True)

        def run(s, i):
            out, _ = simulate_sharded(s, i, cfg, mesh, faulty=True)
            return out

        t0 = time.perf_counter()
        final = run(start, inp)
        final.state.block_until_ready()
        first_wall = time.perf_counter() - t0  # includes compile

        if args.faulty_runs == 2:
            t0 = time.perf_counter()
            final = run(start, inp)
            final.state.block_until_ready()
            run_wall = time.perf_counter() - t0
        else:
            run_wall = first_wall  # single execution: compile not separable

    assert final.state.shape == (n, n)
    assert len(final.state.sharding.device_set) == args.devices, (
        "final state not sharded across the full mesh"
    )

    timed = args.faulty_runs == 2 and not args.stepwise
    peak_rss_mib = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024
    line.update({
        "ticks": ticks,
        "drop_rate": args.drop_rate,
        "compile_s": round(first_wall - run_wall, 3) if timed else None,
        "run_s": round(run_wall, 3),
        "run_includes_compile": not timed,
        "stepwise": args.stepwise,
        # Throughput is only meaningful when compile is excluded; null it in
        # single-run mode so rows stay comparable across SCALE_PROOF.md.
        "peers_ticks_per_sec": round(n * ticks / run_wall, 1) if timed else None,
        "peak_rss_mib": round(peak_rss_mib, 1),
        "faulty": True,
    })
    print(json.dumps(line))


if __name__ == "__main__":
    main()
