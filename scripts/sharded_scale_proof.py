"""Sharded-scale proof: run the faulty GSPMD scan well beyond toy shapes.

Demonstrates that the sharded program (SURVEY.md §2.3 / BASELINE config 4)
scales past the N=32 equivalence tests: N peers over D virtual CPU devices,
full faulty tick (churn + partition + drop + manual pings) under lax.scan,
with wall-clock and peak RSS logged. Run via ``make scale-proof``; results are
recorded in SCALE_PROOF.md.

Prints one JSON line, e.g.:
    {"n": 4096, "devices": 8, "ticks": 8, "compile_s": ..., "run_s": ...,
     "peak_rss_mib": ..., "peers_ticks_per_sec": ...}
"""

from __future__ import annotations

import argparse
import json
import os
import resource
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--n", type=int, default=4096)
    p.add_argument("--devices", type=int, default=8)
    p.add_argument("--ticks", type=int, default=8)
    args = p.parse_args()

    # Pin the virtual-CPU platform before JAX can initialize any backend
    # (same ordering contract as tests/conftest.py / __graft_entry__.py),
    # and strip the tunnel plugin, whose import hangs while wedged.
    from axon_guard import strip_axon_plugin

    strip_axon_plugin()
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_num_cpu_devices", args.devices)

    from kaboodle_tpu.config import SwimConfig
    from kaboodle_tpu.parallel import (
        make_mesh,
        shard_inputs,
        shard_state,
        simulate_sharded,
    )
    from kaboodle_tpu.sim.scenario import all_fault_paths_scenario
    from kaboodle_tpu.sim.state import init_state

    from bench import LEAN_STATE_MIN_N

    n, ticks = args.n, args.ticks
    mesh = make_mesh(args.devices)
    cfg = SwimConfig()
    # MEMORY_PLAN.md policy: large N automatically selects the memory-lean
    # state (no latency EWMA / instant identity) — same rule as bench.py.
    import jax.numpy as jnp

    lean = n >= LEAN_STATE_MIN_N
    # int16 timers only while the run cannot reach the dtype's max tick
    # (init_state contract) — same policy as bench.py.
    narrow = lean and ticks < jnp.iinfo(jnp.int16).max
    st = shard_state(
        init_state(n, seed=0, track_latency=not lean, instant_identity=lean,
                   timer_dtype=jnp.int16 if narrow else jnp.int32),
        mesh,
    )

    # Same every-fault-path schedule the driver dry run validates, at scale.
    inp = shard_inputs(
        all_fault_paths_scenario(n, ticks=ticks, drop_rate=0.05).build(),
        mesh,
        stacked=True,
    )

    def run(s, i):
        out, _ = simulate_sharded(s, i, cfg, mesh, faulty=True)
        return out

    t0 = time.perf_counter()
    final = run(st, inp)
    final.state.block_until_ready()
    first_wall = time.perf_counter() - t0  # includes compile

    t0 = time.perf_counter()
    final = run(st, inp)
    final.state.block_until_ready()
    run_wall = time.perf_counter() - t0

    assert final.state.shape == (n, n)
    assert len(final.state.sharding.device_set) == args.devices, (
        "final state not sharded across the full mesh"
    )

    peak_rss_mib = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024
    line = {
        "n": n,
        "devices": args.devices,
        "ticks": ticks,
        "compile_s": round(first_wall - run_wall, 3),
        "run_s": round(run_wall, 3),
        "peers_ticks_per_sec": round(n * ticks / run_wall, 1),
        "peak_rss_mib": round(peak_rss_mib, 1),
        "backend": jax.default_backend(),
        "faulty": True,
        "state_variant": ("lean+int16" if narrow else "lean") if lean else "full",
    }
    print(json.dumps(line))


if __name__ == "__main__":
    main()
