"""On-TPU A/B of the steady-state pass gating (commit f445533).

The gating commit has no runtime flag (the gates are structural, bit-exact,
and always on), so the A/B runs the SAME measure child twice: once with the
repo at HEAD (gated) and once inside a throwaway git worktree pinned to the
pre-gating parent commit. Both arms measure, at N=16,384 lean+int16 with the
fused kernels on:

- ``tick_converged_ms``: fault-free tick from the everyone-knows-everyone
  agreed state (``ring_contacts=n-1``) — the workload the gating targets
  (every gate provably closed: no suspicion activity, no KPR delivery).
- ``tick_selfonly_ms``: fault-free tick from the self-only boot state — the
  workload of the banked 58.5 ms round-4 capture, for continuity.

Results append to TPU_WATCH.log as ``{"kind": "gate_ab", ...}``; partial
banking via the WATCHPART protocol so a mid-measure wedge keeps the arm
already measured. Decision rule (PERF.md): if the gated converged tick is
not faster, revert f445533.
"""

from __future__ import annotations

import json
import subprocess
import sys
import time
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

LOG = str(REPO_ROOT / "TPU_WATCH.log")
PRE_GATE_REF = "f445533^"
WORKTREE = "/tmp/pregate_wt"
ARM_TIMEOUT_S = 2400

MEASURE = r"""
import json, time
import jax, jax.numpy as jnp

out = {}
class _Partial(dict):
    def __setitem__(self, k, v):
        super().__setitem__(k, v)
        print("WATCHPART " + json.dumps(dict(self)), flush=True)
out = _Partial(out)

def fetch_timeit(f, *a, reps=3):
    # axon block_until_ready does not synchronize; time via scalar fetch.
    r = f(*a); jax.block_until_ready(r)
    leaf = jax.tree.leaves(r)[0]
    float(jnp.asarray(leaf).ravel()[0].astype(jnp.float32))
    t0 = time.perf_counter()
    for _ in range(reps):
        r = f(*a)
    leaf = jax.tree.leaves(r)[0]
    float(jnp.asarray(leaf).ravel()[0].astype(jnp.float32))
    return (time.perf_counter() - t0) / reps

from kaboodle_tpu.config import SwimConfig
from kaboodle_tpu.sim.runner import simulate
from kaboodle_tpu.sim.state import idle_inputs, init_state

n = 16384
kw = dict(use_pallas_fp=True)
try:
    from kaboodle_tpu.ops.fused_oldest_k import fused_oldest_k  # noqa: F401
    from kaboodle_tpu.ops.fused_suspicion import fused_suspicion  # noqa: F401
    kw.update(use_pallas_oldest_k=True, use_pallas_suspicion=True)
except ImportError:
    pass
cfg = SwimConfig(**kw)
inp = idle_inputs(n, ticks=8)

@jax.jit
def run(s, i):
    o, _ = simulate(s, i, cfg, faulty=False)
    return o.timer.sum() + o.tick

for name, ring in (("converged", n - 1), ("selfonly", 0)):
    st = init_state(n, seed=0, ring_contacts=ring, track_latency=False,
                    instant_identity=True, timer_dtype=jnp.int16)
    sec = fetch_timeit(run, st, inp, reps=3)
    out[f"tick_{name}_ms"] = sec / 8 * 1e3

print("WATCHJSON " + json.dumps(dict(out)))
"""


def log(obj) -> None:
    with open(LOG, "a") as f:
        f.write(json.dumps(obj) + "\n")


def _arm(cwd: str) -> dict:
    # Same process-group/hard-timeout discipline as tpu_watch._run_group, but
    # with a caller-chosen cwd (each arm imports kaboodle_tpu from its own
    # checkout) and WATCHPART/WATCHJSON parsing inline.
    import os
    import signal
    import tempfile

    sink = tempfile.TemporaryFile(mode="w+", prefix="gate_ab_")
    proc = subprocess.Popen(
        [sys.executable, "-c", MEASURE], stdout=sink, stderr=subprocess.STDOUT,
        text=True, start_new_session=True, cwd=cwd,
    )
    try:
        proc.wait(timeout=ARM_TIMEOUT_S)
        rc = proc.returncode
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except OSError:
            pass
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            pass
        rc = None
    sink.flush()
    sink.seek(0)
    out = sink.read()
    sink.close()
    for line in reversed(out.splitlines()):
        for tag in ("WATCHJSON ", "WATCHPART "):
            if line.startswith(tag):
                try:
                    return {"rc": rc, **json.loads(line[len(tag):])}
                except json.JSONDecodeError:
                    continue
    return {"rc": rc, "tail": out[-1200:]}


def main() -> None:
    rev = subprocess.run(
        ["git", "rev-parse", "--short", PRE_GATE_REF], cwd=REPO_ROOT,
        capture_output=True, text=True, check=True,
    ).stdout.strip()
    if not Path(WORKTREE).exists():
        subprocess.run(
            ["git", "worktree", "add", "--detach", WORKTREE, PRE_GATE_REF],
            cwd=REPO_ROOT, check=True,
        )
    else:
        # A stale worktree from an earlier run would silently corrupt the
        # pregate arm: force-checkout the pinned rev (covers both HEAD drift
        # and dirty tracked files) AND clean untracked artifacts — the
        # checkout alone leaves stale .pyc/__pycache__/generated results in
        # place (ADVICE r5); recreate the worktree if its metadata is broken
        # (pruned/moved) or the clean fails.
        pinned = subprocess.run(
            ["git", "rev-parse", PRE_GATE_REF], cwd=REPO_ROOT,
            capture_output=True, text=True, check=True,
        ).stdout.strip()
        reset = subprocess.run(
            ["git", "checkout", "--force", "--detach", pinned],
            cwd=WORKTREE, capture_output=True, text=True,
        )
        if reset.returncode == 0:
            reset = subprocess.run(
                ["git", "clean", "-fdx"],
                cwd=WORKTREE, capture_output=True, text=True,
            )
        if reset.returncode != 0:
            import shutil

            shutil.rmtree(WORKTREE, ignore_errors=True)
            subprocess.run(["git", "worktree", "prune"], cwd=REPO_ROOT, check=False)
            subprocess.run(
                ["git", "worktree", "add", "--detach", WORKTREE, PRE_GATE_REF],
                cwd=REPO_ROOT, check=True,
            )
    res = {"ts": time.time(), "kind": "gate_ab", "pre_gate_rev": rev}
    res["gated"] = _arm(str(REPO_ROOT))
    res["pregate"] = _arm(WORKTREE)
    log(res)
    print(json.dumps(res, indent=2))


if __name__ == "__main__":
    main()
