"""Config-3 churn recovery and config-5 partition heal at BASELINE scale
(N=8,192), on the TPU — with CHUNKED calm-phase dispatches.

BENCH_r04_local.json's `churn_recovery` section proves re-convergence at
N=2,048 (where bench's single jitted `run_until_converged` while_loop is
seconds); at N=8,192 the same while_loop is one ~20k-iteration dispatch,
and the first attempt took the axon TPU worker down with it ("TPU worker
process crashed or restarted", TPU_WATCH.log kind=recovery8192 @05:35).
This version keeps every dispatch bounded: the faulty scenario scan runs
as one dispatch (64/48 ticks), then calm recovery proceeds in 256-tick
jitted scan chunks with a host-side convergence check between chunks, so
no single execute exceeds a few seconds and progress banks incrementally.

Appends ``{"kind": "recovery8192_chunked", ...}`` to TPU_WATCH.log.
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

LOG = str(REPO_ROOT / "TPU_WATCH.log")
CHUNK = 256


def _calm_until_converged(st, cfg, n, budget):
    """Fault-free calm ticks in CHUNK-sized scans until every survivor
    agrees. Returns (final_state, ticks_used_or_None, converged)."""
    import jax
    import numpy as np

    from kaboodle_tpu.sim.runner import simulate
    from kaboodle_tpu.sim.state import idle_inputs

    inp = idle_inputs(n, ticks=CHUNK)

    @jax.jit
    def chunk(s, i):
        out, m = simulate(s, i, cfg, faulty=False)
        return out, m.converged

    done = 0
    while done < budget:
        st, conv = chunk(st, inp)
        conv_v = np.asarray(conv)
        if conv_v.any():
            return st, done + int(np.argmax(conv_v)) + 1, True
        done += CHUNK
    return st, None, False


def _run_config(config: int, n: int, ticks: int, stop_tick: int):
    """Faulty scenario scan + chunked calm recovery. ``stop_tick`` is the
    tick inside the scan when the fault schedule ends (churn stop / heal);
    the reported re-convergence count is measured from there, matching
    bench's churn_recovery/partition_heal semantics."""
    import jax
    import numpy as np

    from bench import _recovery_budget, _scenario_state_and_inputs
    from kaboodle_tpu.config import SwimConfig
    from kaboodle_tpu.sim.runner import simulate

    cfg = SwimConfig()
    budget = _recovery_budget(n)
    st, inp = _scenario_state_and_inputs(config, n, ticks, calm_budget=budget)

    @jax.jit
    def run(s, i):
        out, m = simulate(s, i, cfg, faulty=True)
        return out, m.converged

    t0 = time.perf_counter()
    out, conv = run(st, inp)
    conv_v = np.asarray(conv)
    in_window = ticks - stop_tick
    if conv_v[-1]:
        later_false = np.where(~conv_v[stop_tick:])[0]
        reconv = int(later_false[-1] + 1) if later_false.size else 0
        reconverged = True
    else:
        out, extra, reconverged = _calm_until_converged(out, cfg, n, budget)
        reconv = in_window + extra if reconverged else None
    alive = np.asarray(out.alive)
    return {
        "n": n,
        "ticks": ticks,
        "calm_budget": in_window + budget,
        "reconverged": bool(reconverged),
        "reconverge_ticks_after_stop": reconv,
        "survivors": int(alive.sum()),
        "wall_s": round(time.perf_counter() - t0, 3),
    }


def main() -> None:
    out = {"ts": time.time(), "kind": "recovery8192_chunked", "chunk": CHUNK}
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 8192
    # config 3: churn over the first half of a 64-tick window;
    # config 5: drop+partition healed at tick 32 of a 48-tick window.
    for name, config, ticks, stop in (("churn_recovery", 3, 64, 32),
                                      ("partition_heal", 5, 48, 32)):
        try:
            out[name] = _run_config(config, n, ticks, stop)
        except Exception as e:  # bank the failure; the other section may land
            out[f"{name}_error"] = repr(e)[:300]
        with open(LOG, "a") as f:
            f.write(json.dumps(out) + "\n")
    print(json.dumps(out, indent=2))


if __name__ == "__main__":
    main()
