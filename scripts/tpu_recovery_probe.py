"""Config-3 churn recovery at its BASELINE-specified scale, on the TPU.

BENCH_r04_local.json's `churn_recovery` section proves re-convergence at
N=2,048 (CPU); the throughput half (`churn_config3`) runs N=8,192 but its
64-tick window cannot contain the ~1.5N-tick removal pipeline, so
`reconverged_in_window` is false by construction. This probe runs the full
recovery — churn scan + `run_until_converged` (a single jitted while_loop,
so one dispatch for the whole calm phase) — at N=8,192 on the real chip,
where ~13k recovery ticks are minutes, not hours.

Appends ``{"kind": "recovery8192", ...}`` to TPU_WATCH.log; bench.py's
churn-recovery section stays at N=2,048 so the CPU-fallback path never
tries an O(N^3) loop on the host.
"""

from __future__ import annotations

import json
import sys
import time
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

LOG = str(REPO_ROOT / "TPU_WATCH.log")


def main() -> None:
    from bench import _bench_churn_recovery, _bench_partition_heal

    out = {"ts": time.time(), "kind": "recovery8192"}
    for name, fn, n in (("churn_recovery", _bench_churn_recovery, 8192),
                        ("partition_heal", _bench_partition_heal, 8192)):
        try:
            t0 = time.perf_counter()
            out[name] = fn(n)
            out[name]["wall_s"] = round(time.perf_counter() - t0, 3)
        except Exception as e:  # bank the failure; the other section may land
            out[f"{name}_error"] = repr(e)[:300]
        with open(LOG, "a") as f:
            f.write(json.dumps(out) + "\n")
    print(json.dumps(out, indent=2))


if __name__ == "__main__":
    main()
