"""On-TPU stage decomposition with scan-amortized timing.

scripts/tpu_sweep_probe.py showed single-dispatch timings through the axon
tunnel are floored at ~10-25 ms of dispatch/fetch overhead regardless of
tensor size — so per-op costs from the round-4 microbench (TPU_WATCH.log)
are upper bounds, not measurements. This probe wraps each tick stage in a
32-iteration `lax.scan` with a threaded scalar carry (so iterations cannot
be elided or reordered) and reports total/32: tunnel overhead amortizes to
<1 ms and the number is the true on-device stage cost.

Used to decide the next fusion target (PERF.md "remaining time" section).
"""

import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

ITERS = 32
out = {"ts": time.time(), "kind": "stage_probe", "iters": ITERS}


def bank(k, v):
    out[k] = v
    print("STAGEPART " + json.dumps(dict(out)), flush=True)


def timeit_scan(make_body, init_carry, *arrays, reps=2):
    """Time ITERS scanned iterations of body; returns seconds per iteration.

    make_body(*arrays) -> body(carry, _) -> (carry, None). The carry threads
    a data dependence through every iteration. Every [n, n]-sized operand
    MUST be passed via ``*arrays`` (becoming jit arguments the body closes
    over as tracers), never captured as a concrete jnp array: captured
    arrays embed as jaxpr constants in the tunnel's remote-compile request
    and 256 MiB bodies get HTTP 413 (same rule as tpu_watch.MEASURE).
    """

    @jax.jit
    def run(c, *arrs):
        c, _ = lax.scan(make_body(*arrs), c, None, length=ITERS)
        return c

    r = run(init_carry, *arrays)
    jax.block_until_ready(r)
    leaf = jax.tree.leaves(r)[0]
    float(jnp.asarray(leaf).ravel()[0].astype(jnp.float32))  # sync via fetch
    t0 = time.perf_counter()
    for _ in range(reps):
        r = run(init_carry, *arrays)
    leaf = jax.tree.leaves(r)[0]
    float(jnp.asarray(leaf).ravel()[0].astype(jnp.float32))
    return (time.perf_counter() - t0) / (reps * ITERS)


def probe(n):
    sfx = f"_n{n}"
    rng = np.random.default_rng(0)
    S = jnp.asarray(rng.integers(0, 3, (n, n)), jnp.int8)
    T = jnp.asarray(rng.integers(0, 100, (n, n)), jnp.int16)
    rh = jnp.asarray(rng.integers(0, 2**32, n, dtype=np.uint32))
    v = jnp.asarray(rng.integers(0, 2, n), bool)

    from kaboodle_tpu.config import SwimConfig
    from kaboodle_tpu.ops.fused_fp import fused_fp_count
    from kaboodle_tpu.ops.fused_oldest_k import fused_oldest_k
    from kaboodle_tpu.ops.fused_suspicion import fused_suspicion
    from kaboodle_tpu.ops.sampling import choose_one_of_oldest_k

    # -- floor: one elementwise write-sweep of S (read n^2 int8, write n^2)
    def mk_where(S, v):
        def body(c, _):
            o = jnp.where(v[None, :] & (c > 0), jnp.int8(1), S)
            return o[0, 0].astype(jnp.int32) + 1, None
        return body

    bank(f"where_int8{sfx}_ms", timeit_scan(mk_where, jnp.int32(1), S, v) * 1e3)

    # -- floor: one read-reduce of S (no [n, n] write)
    def mk_reduce(S):
        def body(c, _):
            s = (S > (c % 2).astype(jnp.int8)).sum(axis=-1, dtype=jnp.int32)
            return s[0], None
        return body

    bank(f"reduce_int8{sfx}_ms", timeit_scan(mk_reduce, jnp.int32(0), S) * 1e3)

    # -- fingerprint: fused Pallas vs jnp formulation
    def mk_ffp(S, rh):
        def body(c, _):
            fp, cnt = fused_fp_count(S, rh + c)
            return fp[0], None
        return body

    bank(f"fused_fp{sfx}_ms", timeit_scan(mk_ffp, jnp.uint32(0), S, rh) * 1e3)

    def mk_jfp(S, rh):
        def body(c, _):
            m = S > 0
            fp = jnp.sum(jnp.where(m, (rh + c)[None, :], jnp.uint32(0)),
                         axis=-1, dtype=jnp.uint32)
            return fp[0], None
        return body

    bank(f"jnp_fp{sfx}_ms", timeit_scan(mk_jfp, jnp.uint32(0), S, rh) * 1e3)

    # -- oldest-5 draw: jnp iter vs fused Pallas
    def mk_iter(S, T):
        def body(key, _):
            key, sub = jax.random.split(key)
            tgt = choose_one_of_oldest_k(timer=T, eligible=S == 1, key=sub,
                                         k=5, deterministic=False,
                                         method="iter")
            return jax.random.fold_in(key, tgt[0]), None
        return body

    bank(f"oldest5_iter{sfx}_ms",
         timeit_scan(mk_iter, jax.random.PRNGKey(0), S, T) * 1e3)

    alive = jnp.ones((n,), bool)

    def mk_fk(S, T, alive):
        def body(c, _):
            idx, valid = fused_oldest_k(S, T + c.astype(jnp.int16), alive, 5)
            return idx[0, 0] % 2, None
        return body

    try:
        bank(f"fused_oldest_k{sfx}_ms",
             timeit_scan(mk_fk, jnp.int32(0), S, T, alive) * 1e3)
    except Exception as e:
        bank(f"fused_oldest_k{sfx}_error", repr(e)[:200])

    # -- phase-A row statistics: fused suspicion pass
    def mk_fs(S, T, alive):
        def body(c, _):
            r = fused_suspicion(S, T, alive, jnp.int32(50) + c)[:4]
            return r[0][0] % 2, None
        return body

    try:
        bank(f"fused_suspicion{sfx}_ms",
             timeit_scan(mk_fs, jnp.int32(0), S, T, alive) * 1e3)
    except Exception as e:
        bank(f"fused_suspicion{sfx}_error", repr(e)[:200])

    # -- the whole fault-free tick, scan-amortized (the honest per-tick cost)
    from kaboodle_tpu.sim.runner import simulate
    from kaboodle_tpu.sim.state import idle_inputs, init_state

    st = init_state(n, seed=0, track_latency=False, instant_identity=True,
                    timer_dtype=jnp.int16)
    inp = idle_inputs(n, ticks=ITERS)
    for name, kw in (("fused_all", dict(use_pallas_fp=True,
                                        use_pallas_oldest_k=True,
                                        use_pallas_suspicion=True)),
                     ("iter", dict(use_pallas_fp=True,
                                   oldest_k_method="iter")),
                     ("nopallas", dict())):
        # fast_path=False keeps these keys comparable with the r4 captures
        # (full-path timings); the fast/slow A/B lives in tpu_watch.MEASURE.
        cfg = SwimConfig(fast_path=False, **kw)

        @jax.jit
        def run(s, i, cfg=cfg):
            o, _ = simulate(s, i, cfg, faulty=False)
            return o.timer.sum() + o.tick

        try:
            r = run(st, inp)
            jax.block_until_ready(r)
            float(jnp.asarray(r).astype(jnp.float32))
            t0 = time.perf_counter()
            for _ in range(2):
                r = run(st, inp)
            float(jnp.asarray(r).astype(jnp.float32))
            bank(f"tick_{name}{sfx}_ms",
                 (time.perf_counter() - t0) / (2 * ITERS) * 1e3)
        except Exception as e:
            bank(f"tick_{name}{sfx}_error", repr(e)[:200])


def probe_dtype_floors(n):
    """int8/int16/int32 where-sweeps with the MATRIX as the scan carry, so
    the [N, N] write must materialize every iteration (a body that only
    consumes o[0, 0] gets the whole sweep DCE'd — the first where_int8
    number in TPU_WATCH.log has that flaw and reads ~3x high)."""
    sfx = f"_n{n}"
    rng = np.random.default_rng(0)
    v = jnp.asarray(rng.integers(0, 2, n), bool)
    for name, dt in (("int8", jnp.int8), ("int16", jnp.int16),
                     ("int32", jnp.int32)):
        M = jnp.asarray(rng.integers(0, 100, (n, n)), dt)

        def mk(v, dt=dt):
            def body(Mc, _):
                one = jnp.ones((), dt)
                return jnp.where(v[None, :], Mc + one, Mc), None
            return body

        @jax.jit
        def run(Mc, v):
            Mc, _ = lax.scan(mk(v), Mc, None, length=ITERS)
            return Mc

        try:
            r = run(M, v)
            jax.block_until_ready(r)
            float(jnp.asarray(r.ravel()[0]).astype(jnp.float32))
            t0 = time.perf_counter()
            for _ in range(2):
                r = run(M, v)
            float(jnp.asarray(r.ravel()[0]).astype(jnp.float32))
            bank(f"where_carry_{name}{sfx}_ms",
                 (time.perf_counter() - t0) / (2 * ITERS) * 1e3)
        except Exception as e:
            bank(f"where_carry_{name}{sfx}_error", repr(e)[:200])
        del M


def probe_cuts(n, variant="fused_all"):
    """In-context phase decomposition: time the real tick truncated after
    each phase (kernel.make_tick_fn(_cut=...)) on the converged steady
    state — successive diffs are what each phase actually costs inside the
    compiled program, which isolated stage benches mispredict."""
    import jax.numpy as jnp

    from kaboodle_tpu.config import SwimConfig
    from kaboodle_tpu.sim.kernel import make_tick_fn
    from kaboodle_tpu.sim.state import idle_inputs, init_state

    sfx = f"_n{n}"
    kw = {
        "fused_all": dict(use_pallas_fp=True, use_pallas_oldest_k=True,
                          use_pallas_suspicion=True),
        "jnp": dict(),
    }[variant]
    # The cuts truncate the FULL path and the dispatch pred is always False
    # on this converged state, so the _cut=None datapoint must pin
    # fast_path=False too — otherwise it times the lean branch and the
    # successive-diff decomposition is meaningless.
    cfg = SwimConfig(fast_path=False, **kw)
    st = init_state(n, seed=0, ring_contacts=n - 1, track_latency=False,
                    instant_identity=True, timer_dtype=jnp.int16,
                    announced=True)
    idle = idle_inputs(n)

    for cut in ("A", "c1", "c2", "c34", "G", None):
        tick = make_tick_fn(cfg, faulty=False, _cut=cut)

        @jax.jit
        def run(c):
            def body(s, _):
                s2, _m = tick(s, idle)
                return s2, None
            c, _ = lax.scan(body, c, None, length=ITERS)
            return c

        name = cut or "full"
        try:
            r = run(st)
            jax.block_until_ready(r)
            float(jnp.asarray(r.timer.ravel()[0]).astype(jnp.float32))
            t0 = time.perf_counter()
            for _ in range(2):
                r = run(st)
            float(jnp.asarray(r.timer.ravel()[0]).astype(jnp.float32))
            bank(f"cut_{variant}_{name}{sfx}_ms",
                 (time.perf_counter() - t0) / (2 * ITERS) * 1e3)
        except Exception as e:
            bank(f"cut_{variant}_{name}{sfx}_error", repr(e)[:200])


if __name__ == "__main__":
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    if which in ("all", "stages"):
        probe(16384)
    if which in ("all", "dtypes"):
        probe_dtype_floors(16384)
    if which in ("all", "cuts"):
        probe_cuts(16384, "fused_all")
        probe_cuts(16384, "jnp")
    print("STAGEJSON " + json.dumps(out), flush=True)
