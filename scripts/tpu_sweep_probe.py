"""One-off on-TPU probe: measure the raw per-pass (HBM sweep) cost at
N=16,384 and N=32,768 so the tick's 58.5 ms (TPU_WATCH.log, round 4) can be
decomposed against a *measured* floor instead of the analytical 10-20 ms
estimate in PERF.md.

Value-first ordering and flushed incremental prints (the TPU_BENCH_NOTES.md
wedge contract): every line banked is kept even if the tunnel dies
mid-probe. Host-side cost is negligible — compiles go through the tunnel's
remote_compile and execution stays on device — so this is safe to run while
the single-core host grinds the 65k scale proof.
"""

import functools
import json
import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import numpy as np
import jax
import jax.numpy as jnp

out = {"ts": time.time(), "kind": "sweep_probe"}


def bank(k, v):
    out[k] = v
    print("SWEEPPART " + json.dumps(dict(out)), flush=True)


def fetch_timeit(f, *a, reps=3):
    # axon block_until_ready does not synchronize; time via scalar fetch.
    r = f(*a)
    jax.block_until_ready(r)
    leaf = jax.tree.leaves(r)[0]
    float(jnp.asarray(leaf).ravel()[0].astype(jnp.float32))
    t0 = time.perf_counter()
    for _ in range(reps):
        r = f(*a)
    leaf = jax.tree.leaves(r)[0]
    float(jnp.asarray(leaf).ravel()[0].astype(jnp.float32))
    return (time.perf_counter() - t0) / reps


def probe(n):
    sfx = f"_n{n}"
    rng = np.random.default_rng(0)
    S = jnp.asarray(rng.integers(0, 3, (n, n)), jnp.int8)
    T = jnp.asarray(rng.integers(0, 100, (n, n)), jnp.int16)
    v = jnp.asarray(rng.integers(0, 2, n), bool)

    # 1. Pure elementwise sweep: read S (n^2 int8), write S' — the cheapest
    # possible pass shape in the tick's mark-apply chains.
    @jax.jit
    def where_s(S, v):
        o = jnp.where(v[None, :], jnp.int8(1), S)
        return o.sum(dtype=jnp.int32)

    bank(f"where_int8{sfx}_ms", fetch_timeit(where_s, S, v) * 1e3)

    # 2. Same over the int16 timer (2x the bytes).
    @jax.jit
    def where_t(T, v):
        o = jnp.where(v[None, :], jnp.int16(0), T)
        return o.sum(dtype=jnp.int32)

    bank(f"where_int16{sfx}_ms", fetch_timeit(where_t, T, v) * 1e3)

    # 3. Read-only row reduction of S (no [n, n] write) — the floor for any
    # statistics pass.
    @jax.jit
    def reduce_s(S):
        return (S > 0).sum(axis=-1, dtype=jnp.int32).sum()

    bank(f"reduce_int8{sfx}_ms", fetch_timeit(reduce_s, S) * 1e3)

    # 4. Chained where (2 reads of S, 1 write) — does XLA fuse the chain
    # into one sweep or materialize the intermediate?
    @jax.jit
    def where_chain(S, v):
        a = jnp.where(v[None, :], jnp.int8(1), S)
        b = jnp.where(v[:, None], jnp.int8(2), a)
        return b.sum(dtype=jnp.int32)

    bank(f"where_chain{sfx}_ms", fetch_timeit(where_chain, S, v) * 1e3)

    # 5. The components at this n (whole-tick failed to compile at 32k; the
    # per-stage kernels are small programs and may clear the helper).
    if n > 16384:
        from kaboodle_tpu.ops.fused_fp import fused_fp_count
        from kaboodle_tpu.ops.sampling import choose_one_of_oldest_k

        rh = jnp.asarray(rng.integers(0, 2**32, n, dtype=np.uint32))
        elig = S == 1
        key = jax.random.PRNGKey(0)
        try:
            bank(f"fused_fp{sfx}_ms",
                 fetch_timeit(functools.partial(fused_fp_count, S, rh)) * 1e3)
        except Exception as e:  # bank the ceiling evidence, keep going
            bank(f"fused_fp{sfx}_error", repr(e)[:200])
        try:
            f = jax.jit(functools.partial(
                choose_one_of_oldest_k, k=5, deterministic=False,
                method="iter"))
            bank(f"oldest5_iter{sfx}_ms",
                 fetch_timeit(lambda: f(timer=T, eligible=elig, key=key)) * 1e3)
        except Exception as e:
            bank(f"oldest5_iter{sfx}_error", repr(e)[:200])


probe(16384)
probe(32768)
print("SWEEPJSON " + json.dumps(out), flush=True)
