"""Poll the tunneled TPU until a live window opens, then run the on-device
perf suite once: tick-component microbench, kernel A/B (scatter/topk vs
one-hot/iter formulations), and the full bench. Results append to
TPU_WATCH.log as JSON lines.

The axon tunnel wedges intermittently for hours (TPU_BENCH_NOTES.md); every
probe and measurement runs in a subprocess under a hard timeout so a wedge
mid-measurement cannot hang the watcher itself.
"""

from __future__ import annotations

import json
import subprocess
import sys
import time
from pathlib import Path

# Run as `python scripts/tpu_watch.py`: sys.path[0] is scripts/, so the repo
# root (for `from bench import _probe_once`) must be added explicitly.
REPO_ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO_ROOT))

# All artifacts anchor to the repo root, not the cwd: bench.py's wedged-window
# fallback globs BENCH_r*_local.json next to itself, so a watcher started from
# elsewhere must still bank where bench.py reads.
LOG = str(REPO_ROOT / "TPU_WATCH.log")
PROBE_TIMEOUT_S = 150
# 10 whole-tick jit compiles (5 variants x 2 sizes) through the tunnel's
# remote_compile can exceed 40 min; partial WATCHPART banking means a long
# budget risks nothing even if the window closes mid-measure.
MEASURE_TIMEOUT_S = 5400
POLL_INTERVAL_S = 240

MEASURE = r"""
import json, time
import jax, jax.numpy as jnp

out = {"ts": time.time(), "kind": "measure"}

# Dict that re-prints the whole capture (flushed) on every write, so a
# mid-measure wedge still banks everything measured before the kill: the
# watcher logs the last WATCHPART line when no final WATCHJSON landed.
class _Partial(dict):
    def __setitem__(self, k, v):
        super().__setitem__(k, v)
        print("WATCHPART " + json.dumps(dict(self)), flush=True)

out = _Partial(out)

def fetch_timeit(f, *a, reps=3):
    # axon block_until_ready does not synchronize; time via scalar fetch
    # (see .claude/skills/verify/SKILL.md) and report per-rep seconds.
    r = f(*a); jax.block_until_ready(r)
    leaf = jax.tree.leaves(r)[0]
    float(jnp.asarray(leaf).ravel()[0].astype(jnp.float32))
    t0 = time.perf_counter()
    for _ in range(reps):
        r = f(*a)
    leaf = jax.tree.leaves(r)[0]
    float(jnp.asarray(leaf).ravel()[0].astype(jnp.float32))
    return (time.perf_counter() - t0) / reps

# ---- 1. Whole-tick A/B FIRST, most valuable variant first ------------------
# The wedge pattern (TPU_BENCH_NOTES.md) is that a long compile can close the
# window mid-measure; every metric already banked is kept via WATCHPART, so
# order strictly by value: the round-4b composed fast path vs the full path
# at N=16,384 (converged steady state — the headline workload), then the
# fused-stats ablation, then the N=32,768 ceiling.
from kaboodle_tpu.config import SwimConfig
from kaboodle_tpu.sim.runner import simulate
from kaboodle_tpu.sim.state import idle_inputs, init_state

variants = {
    # The composed fast path (kernel.py _fast): defaults.
    "fast": dict(),
    # Fast dispatch + the Pallas phase-A stats pass feeding it.
    "fast_fsusp": dict(use_pallas_suspicion=True),
    # The r4-banked configuration: single full path, all stage kernels.
    "slow_fused": dict(fast_path=False, use_pallas_fp=True,
                       use_pallas_oldest_k=True, use_pallas_suspicion=True),
    # Single full path, pure jnp (the r4 'nopallas' ablation).
    "slow_jnp": dict(fast_path=False),
}

def tick_ab(tick_n, ticks=32):
    inp = idle_inputs(tick_n, ticks=ticks)
    suffix = "" if tick_n == 16384 else f"_n{tick_n}"
    for name, kw in variants.items():
        try:
            cfg = SwimConfig(**kw)
            @jax.jit
            def run(s, i, cfg=cfg):
                o, _ = simulate(s, i, cfg, faulty=False)
                return o.timer.sum() + o.tick
            for ring, label in ((tick_n - 1, ""), (0, "_selfonly")):
                # Converged steady state first (the headline workload);
                # the self-only boot state for continuity with r4 numbers.
                if name in ("fast_fsusp", "slow_jnp") and ring == 0:
                    continue  # ablations only need the headline state
                # announced=True on the converged state: measure pure steady
                # ticks (no tick-0 re-announce); the self-only boot state
                # keeps its flags (the announce IS its workload).
                st = init_state(tick_n, seed=0, ring_contacts=ring,
                                track_latency=False, instant_identity=True,
                                timer_dtype=jnp.int16, announced=ring != 0)
                sec = fetch_timeit(run, st, inp, reps=2)
                out[f"tick_{name}{label}{suffix}_ms"] = sec / ticks * 1e3
        except Exception as e:
            out[f"tick_{name}{suffix}_error"] = repr(e)[:300]
    try:
        stats = jax.local_devices()[0].memory_stats() or {}
        out[f"peak_bytes_in_use{suffix}"] = stats.get("peak_bytes_in_use")
    except Exception:
        pass

tick_ab(16384)

# (The single-dispatch component microbench that used to sit here is
# superseded by the scan-amortized scripts/tpu_stage_probe.py — its numbers
# were dispatch-floor bound; the banked captures remain in TPU_WATCH.log.)

# ---- 2. The chunked kernel on-chip (VERDICT r4 items 2-3) ------------------
# (a) its transient bound on TPU at the headline N; (b) the N=32,768 ceiling:
# every whole-tick 32k compile 500s through the remote compile helper
# (PERF.md); the chunked program is a handful of small lax.map bodies, so it
# probes whether the ceiling is program size.
from kaboodle_tpu.sim.chunked import make_chunked_tick_fn
from kaboodle_tpu.sim.state import TickInputs

def chunked_tick_ms(tick_n, block=2048, reps=4):
    cfg = SwimConfig()
    st = init_state(tick_n, seed=0, ring_contacts=tick_n - 1,
                    track_latency=False, instant_identity=True,
                    timer_dtype=jnp.int16, announced=True)
    idle1 = TickInputs(
        kill=jnp.zeros((tick_n,), bool), revive=jnp.zeros((tick_n,), bool),
        partition=jnp.zeros((tick_n,), jnp.int32),
        drop_rate=jnp.float32(0), manual_target=jnp.full((tick_n,), -1, jnp.int32),
    )
    tick = jax.jit(make_chunked_tick_fn(cfg, faulty=True, block=block, drop=False))

    def run(s):
        o, _ = tick(s, idle1)
        return o

    out = run(st)
    jax.block_until_ready(out)
    float(jnp.asarray(out.timer.ravel()[0]).astype(jnp.float32))
    t0 = time.perf_counter()
    s = out
    for _ in range(reps):
        s = run(s)
    float(jnp.asarray(s.timer.ravel()[0]).astype(jnp.float32))
    return (time.perf_counter() - t0) / reps * 1e3

for cn in (16384, 32768):
    try:
        out[f"chunked_tick_n{cn}_ms"] = chunked_tick_ms(cn)
    except Exception as e:
        out[f"chunked_tick_n{cn}_error"] = repr(e)[:300]

# ---- 3. The single-chip ceiling size last ----------------------------------
tick_ab(32768)

# AOT attempt at the 32k whole-tick ceiling (VERDICT r4 item 3): lower() +
# compile() splits tracing from backend compilation; if the HTTP 500 is in
# the remote compile transport, the failure point (and error text) moves.
try:
    from kaboodle_tpu.sim.runner import simulate as _sim
    cfg32 = SwimConfig()
    st32 = init_state(32768, seed=0, ring_contacts=32767,
                      track_latency=False, instant_identity=True,
                      timer_dtype=jnp.int16, announced=True)
    inp32 = idle_inputs(32768, ticks=8)

    def _run32(s, i):
        o, _ = _sim(s, i, cfg32, faulty=False)
        return o.timer.sum() + o.tick

    t0 = time.perf_counter()
    lowered = jax.jit(_run32).lower(st32, inp32)
    out["aot32k_lower_s"] = round(time.perf_counter() - t0, 1)
    t0 = time.perf_counter()
    compiled = lowered.compile()
    out["aot32k_compile_s"] = round(time.perf_counter() - t0, 1)
    t0 = time.perf_counter()
    r = compiled(st32, inp32)
    float(jnp.asarray(r).astype(jnp.float32))
    out["aot32k_run8_s"] = round(time.perf_counter() - t0, 1)
except Exception as e:
    out["aot32k_error"] = repr(e)[:400]

# What does the axon device report for memory accounting? (bench's
# peak_hbm_mib came back null; record the raw keys so it can be fixed.)
try:
    stats = jax.local_devices()[0].memory_stats() or {}
    out["memory_stats_keys"] = sorted(stats)[:20]
    out["peak_bytes_in_use"] = stats.get("peak_bytes_in_use")
except Exception as e:
    out["memory_stats_error"] = repr(e)[:200]

print("WATCHJSON " + json.dumps(out))
"""


def _run_group(cmd: list[str], timeout_s: int):
    """Run cmd in its own process group with a hard timeout.

    A wedged tunnel helper can inherit our pipes and keep them open past the
    direct child's death, hanging subprocess.run's drain (the failure mode
    bench.py's _probe_once documents); route output through a temp file so a
    group kill on timeout still yields everything written so far (the
    WATCHPART partial-capture contract). Returns (rc, stdout) — rc None on
    timeout.
    """
    import os
    import signal
    import tempfile

    sink = tempfile.TemporaryFile(mode="w+", prefix="tpu_watch_")
    # cwd pins the children to the repo root so `bench.py` resolves and the
    # `-c` measure child gets kaboodle_tpu on its sys.path (not installed).
    proc = subprocess.Popen(
        cmd, stdout=sink, stderr=subprocess.STDOUT, text=True,
        start_new_session=True, cwd=str(REPO_ROOT),
    )

    def _read_sink() -> str:
        sink.flush()
        sink.seek(0)
        out = sink.read()
        sink.close()
        return out

    try:
        proc.wait(timeout=timeout_s)
        return proc.returncode, _read_sink()
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except OSError:
            pass
        try:
            proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            pass
        return None, _read_sink()


def find_metric_line(out: str) -> str | None:
    """The bench's full result document: the BENCHDOC-tagged line (round-5
    output contract), falling back to the last bare JSON line with a
    "metric" key (the compact summary / older builds)."""
    fallback = None
    for ln in reversed(out.splitlines()):
        ln = ln.strip()
        if ln.startswith("BENCHDOC {"):
            return ln[len("BENCHDOC "):]
        if fallback is None and ln.startswith("{"):
            try:
                if "metric" in json.loads(ln):
                    fallback = ln
            except json.JSONDecodeError:
                continue
    return fallback


def probe() -> bool:
    # The probe-under-wedge pattern lives in bench.py (_probe_once: DEVNULL
    # pipes, own session, group kill); reuse it so the two stay in sync.
    from bench import _probe_once

    return _probe_once(PROBE_TIMEOUT_S)


def log(obj) -> None:
    with open(LOG, "a") as f:
        f.write(json.dumps(obj) + "\n")


def main() -> None:
    attempt = 0
    while True:
        attempt += 1
        alive = probe()
        log({"ts": time.time(), "kind": "probe", "attempt": attempt, "alive": alive})
        if alive:
            rc, out = _run_group([sys.executable, "-c", MEASURE], MEASURE_TIMEOUT_S)
            # A SIGKILL mid-write can truncate the last WATCHPART line and
            # stderr shares the fd, so parse defensively: walk candidates
            # newest-first and keep the first intact one.
            banked = None
            for line in reversed(out.splitlines()):
                for tag, kind in (("WATCHJSON ", None), ("WATCHPART ", "measure_partial")):
                    if line.startswith(tag):
                        try:
                            obj = json.loads(line[len(tag):])
                        except json.JSONDecodeError:
                            continue
                        if kind:
                            obj = {**obj, "kind": kind, "rc": rc}
                        banked = obj
                        break
                if banked:
                    break
            if banked:
                log(banked)
            else:
                log({"ts": time.time(), "kind": "measure_failed", "rc": rc,
                     "tail": out[-2000:]})
                time.sleep(POLL_INTERVAL_S)
                continue
            if rc != 0:
                # Timeout kill (rc None) or crash (the round-4 wedges surfaced
                # as raised exceptions, not hangs): the window just proved
                # unhealthy. Partials are banked; don't burn hours running the
                # full bench against a dead tunnel. Back to polling.
                time.sleep(POLL_INTERVAL_S)
                continue
            # Microbench landed; now the full bench in the same window.
            rc, out = _run_group([sys.executable, "bench.py"], MEASURE_TIMEOUT_S)
            result = find_metric_line(out)
            log({"ts": time.time(), "kind": "bench", "rc": rc, "json": result,
                 **({} if result else {"tail": out[-1500:]})})
            if result:
                # A real-TPU bench line is the round's banked local capture
                # (what bench.py attaches as banked_tpu_capture when a later
                # run lands in a wedged window). Bank it unattended — but
                # never let a degraded later window (thrashing host, partial
                # warm-up) overwrite a better already-banked headline.
                try:
                    data = json.loads(result)
                    path = REPO_ROOT / "BENCH_r05_local.json"
                    prev = -1.0
                    try:
                        prev = float(json.loads(path.read_text())["value"])
                    except (OSError, ValueError, KeyError, TypeError):
                        pass
                    try:
                        new = float(data.get("value"))
                    except (ValueError, TypeError):
                        new = -1.0
                    if str(data.get("backend", "")).startswith("tpu") and new > prev:
                        path.write_text(result + "\n")
                except (ValueError, OSError, TypeError):
                    pass
            # Single-chip ceiling attempts (VERDICT r4 item 2): N=65,536 lean
            # is expected to OOM on one 16 GiB chip (MEMORY_PLAN.md says
            # sharded-only) but the attempt + recorded error is the evidence;
            # N=32,768 headline already ran inside the full bench above.
            rc, out = _run_group(
                [sys.executable, "bench.py", "--n", "65536",
                 "--no-gossip", "--no-scenarios", "--no-probe"],
                MEASURE_TIMEOUT_S,
            )
            result = find_metric_line(out)
            log({"ts": time.time(), "kind": "bench_n65536", "rc": rc,
                 "json": result, **({} if result else {"tail": out[-1200:]})})
            # Keep polling at a relaxed cadence: later windows yield fresh
            # captures (the log keeps every one; readers take the newest).
            time.sleep(3600)
            continue
        time.sleep(POLL_INTERVAL_S)


if __name__ == "__main__":
    main()
