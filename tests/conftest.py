"""Test harness: force an 8-device virtual CPU mesh before JAX initializes.

Multi-chip TPU hardware is not available in CI; sharding tests run against
8 virtual CPU devices (the supported JAX pattern for testing pjit/shard_map
programs). Must run before the first `import jax` anywhere in the test
process — pytest imports conftest.py first, so doing it here is sufficient.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
