"""Test harness: force an 8-device virtual CPU mesh before JAX initializes.

Multi-chip TPU hardware is not available in CI; sharding tests run against
8 virtual CPU devices (the supported JAX pattern for testing pjit/shard_map
programs). The environment's sitecustomize may have already imported jax and
registered a TPU plugin with ``jax_platforms`` pinned, so an env-var override
is not enough — update the config directly (backends are created lazily, so
this is still before any device materializes).
"""

import os
import sys
from pathlib import Path

# The axon TPU plugin contacts the device tunnel at import time; while the
# tunnel is wedged that hangs `import jax` even with JAX_PLATFORMS=cpu.
# Tests never touch the real chip — strip the plugin before jax's plugin
# discovery can see it (shared guard; must run before `import jax`).
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
from axon_guard import strip_axon_plugin  # noqa: E402

strip_axon_plugin()

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
