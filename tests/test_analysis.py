"""graftlint (kaboodle_tpu.analysis) — rule fixtures, suppression, CLI.

Pure AST: nothing here traces or imports a backend (the analyzer itself
never imports jax), so the whole module runs in the fast lane. Each rule
gets a positive and a negative fixture; noqa and baseline suppression are
exercised through the same public entry points CI uses.
"""

from __future__ import annotations

import json
import textwrap

from kaboodle_tpu.analysis import analyze_source
from kaboodle_tpu.analysis.cli import main
from kaboodle_tpu.analysis.core import REGISTRY, _load_rules, noqa_codes


def rules_of(src: str, path: str = "module.py") -> list[str]:
    return [f.rule for f in analyze_source(textwrap.dedent(src), path)]


# ---------------------------------------------------------------------------
# KB1xx generic


def test_kb101_undefined_name():
    assert "KB101" in rules_of("x = deleted_function()\n")
    assert "KB101" not in rules_of("def f():\n    return 1\nx = f()\n")


def test_kb102_unused_import():
    assert "KB102" in rules_of("import os\n")
    assert "KB102" not in rules_of("import os\np = os.getcwd()\n")
    # __all__ strings count as uses; __future__ is exempt
    assert "KB102" not in rules_of(
        "from __future__ import annotations\nfrom x import y\n__all__ = ['y']\n"
    )


def test_kb103_mutable_default():
    assert "KB103" in rules_of("def f(a, b=[]):\n    return b\n")
    assert "KB103" in rules_of("def f(a, b=dict()):\n    return b\n")
    assert "KB103" not in rules_of("def f(a, b=None, c=()):\n    return b\n")


def test_kb104_shadowed_builtin():
    assert "KB104" in rules_of("id = 3\n")
    assert "KB104" in rules_of("def f(type):\n    return type\n")
    # annotations are loads, not bindings; benign names don't fire
    assert "KB104" not in rules_of("def f(x: object) -> bytes:\n    return x\n")


# ---------------------------------------------------------------------------
# KB201 — traced branches


def test_kb201_jit_decorated_branch():
    src = """
    import jax
    @jax.jit
    def f(x):
        if x > 0:
            return x
        return -x
    """
    assert rules_of(src).count("KB201") == 1


def test_kb201_static_argnames_exempt():
    src = """
    import functools, jax
    @functools.partial(jax.jit, static_argnames=("cfg",))
    def f(x, cfg):
        if cfg:
            return x
        if x > 0:
            return x
        return -x
    """
    assert rules_of(src).count("KB201") == 1  # only the `if x > 0`


def test_kb201_structural_tests_exempt():
    src = """
    import jax
    @jax.jit
    def f(x, mask):
        if mask is None:
            return x
        if x.shape[0] > 2:
            return x
        return x
    """
    assert "KB201" not in rules_of(src)


def test_kb201_lax_cond_callee_and_untraced_negative():
    src = """
    import jax
    def branch(x):
        if x:
            return x
        return x
    def host_only(y):
        if y:
            return y
        return y
    def outer(a, b):
        return jax.lax.cond(a, branch, branch, b)
    """
    found = analyze_source(textwrap.dedent(src), "m.py")
    kb201 = [f for f in found if f.rule == "KB201"]
    assert len(kb201) == 1 and "branch" in kb201[0].symbol


def test_kb201_distinct_conditions_get_distinct_keys():
    """A baselined `if deterministic:` must not mask a later tracer branch
    added to the same function — the symbol carries the tainted names."""
    src = """
    import jax
    @jax.jit
    def f(x, deterministic):
        if deterministic:
            return x
        if x > 0:
            return x
        return -x
    """
    found = [f for f in analyze_source(textwrap.dedent(src)) if f.rule == "KB201"]
    assert len(found) == 2
    assert len({f.key for f in found}) == 2
    assert any("(deterministic)" in f.symbol for f in found)
    assert any("(x)" in f.symbol for f in found)


def test_kb201_traced_pragma_and_taint_propagation():
    src = """
    def tick(st, inp):  # graftlint: traced
        t = st.tick
        if t > 3:
            return t
        return st
    """
    assert "KB201" in rules_of(src)


# ---------------------------------------------------------------------------
# KB202 — host coercions


def test_kb202_coercions():
    src = """
    import jax
    import numpy as np
    @jax.jit
    def f(x):
        a = float(x)
        b = x.item()
        c = np.asarray(x)
        return a, b, c
    """
    assert rules_of(src).count("KB202") == 3


def test_kb202_static_reads_exempt():
    src = """
    import jax
    @jax.jit
    def f(x):
        n = int(x.shape[0])
        return x + n
    """
    assert "KB202" not in rules_of(src)


# ---------------------------------------------------------------------------
# KB203 — print in jit


def test_kb203_print():
    src = """
    import jax
    @jax.jit
    def f(x):
        print("tracing", x)
        jax.debug.print("x={}", x)
        return x
    """
    assert rules_of(src).count("KB203") == 1


def test_kb203_host_print_ok():
    assert "KB203" not in rules_of("def f(x):\n    print(x)\n    return x\n")


# ---------------------------------------------------------------------------
# KB204 — key reuse


def test_kb204_reuse():
    src = """
    import jax
    def f():
        k = jax.random.key(0)
        a = jax.random.uniform(k, (3,))
        b = jax.random.normal(k, (3,))
        return a, b
    """
    assert rules_of(src).count("KB204") == 1


def test_kb204_split_and_branches_ok():
    src = """
    import jax
    def g():
        k = jax.random.key(0)
        k1, k2 = jax.random.split(k)
        a = jax.random.uniform(k1, (3,))
        b = jax.random.normal(k2, (3,))
        return a, b
    def branches(det):
        k = jax.random.key(0)
        if det:
            return jax.random.uniform(k, (3,))
        else:
            return jax.random.normal(k, (3,))
    """
    assert "KB204" not in rules_of(src)


def test_kb204_sibling_except_arms_ok():
    """Mutually-exclusive except arms are separate execution paths."""
    src = """
    import jax
    def f(k):
        k = jax.random.key(0)
        try:
            x = 1
        except ValueError:
            return jax.random.uniform(k, (3,))
        except KeyError:
            return jax.random.normal(k, (3,))
        return x
    """
    assert "KB204" not in rules_of(src)


def test_kb204_rebind_clears():
    src = """
    import jax
    def f():
        k = jax.random.key(0)
        a = jax.random.uniform(k, (3,))
        k = jax.random.key(1)
        b = jax.random.normal(k, (3,))
        return a, b
    """
    assert "KB204" not in rules_of(src)


# ---------------------------------------------------------------------------
# KB205 — use after donation


def test_kb205_use_after_donation():
    src = """
    import jax
    tick = jax.jit(step, donate_argnums=0)
    def bad(st, inp):
        out = tick(st, inp)
        return st.alive
    def good(st, inp):
        st, m = tick(st, inp)
        return st.alive
    def loop(st, inp):
        for _ in range(4):
            st, m = tick(st, inp)
        return st
    """
    found = analyze_source(textwrap.dedent(src), "m.py")
    kb205 = [f for f in found if f.rule == "KB205"]
    assert len(kb205) == 1 and "bad" in kb205[0].symbol


def test_kb205_donate_argnames_resolved_through_local_def():
    src = """
    import jax
    def step(st, inp):
        return st, inp
    tick = jax.jit(step, donate_argnames="st")
    def bad(st, inp):
        out = tick(st, inp)
        return st
    """
    found = analyze_source(textwrap.dedent(src), "m.py")
    assert [f.rule for f in found].count("KB205") == 1


# ---------------------------------------------------------------------------
# KB3xx — hot-path scoping


HOT_SYNC = """
import jax
import numpy as np
@jax.jit
def f(x):
    y = np.asarray(x)
    x.block_until_ready()
    return jax.device_get(x)
"""


def test_kb301_scoped_to_hot_dirs():
    hot = rules_of(HOT_SYNC, "kaboodle_tpu/sim/foo.py")
    assert hot.count("KB301") == 3
    assert "KB301" not in rules_of(HOT_SYNC, "kaboodle_tpu/transport/foo.py")


def test_kb301_module_level_numpy_ok():
    src = """
    import numpy as np
    TABLE = np.zeros(256, dtype=np.uint32)
    """
    assert "KB301" not in rules_of(src, "kaboodle_tpu/ops/tables.py")


def test_kb302_dtype_discipline():
    src = """
    import jax.numpy as jnp
    def f(n):
        return jnp.arange(n)
    """
    ok = """
    import jax.numpy as jnp
    def f(n):
        return jnp.arange(n, dtype=jnp.int32), jnp.zeros((n,), jnp.uint32)
    """
    assert "KB302" in rules_of(src, "kaboodle_tpu/ops/crc32.py")
    assert "KB302" not in rules_of(ok, "kaboodle_tpu/ops/crc32.py")
    # discipline files only — elsewhere the default dtype is fine
    assert "KB302" not in rules_of(src, "kaboodle_tpu/ops/pallas_util.py")
    assert "KB302" not in rules_of(src, "kaboodle_tpu/transport/codec.py")


# ---------------------------------------------------------------------------
# suppression: noqa + baseline


def test_noqa_codes_parsing():
    assert noqa_codes("x = 1  # noqa") == frozenset({"*"})
    assert noqa_codes("x = 1  # noqa: KB104") == frozenset({"KB104"})
    assert noqa_codes("x = 1  # noqa: KB104, KB201") == frozenset({"KB104", "KB201"})
    # foreign linter codes keep the historical blanket-waiver semantics
    assert noqa_codes("import jax  # noqa: E402") == frozenset({"*"})
    assert noqa_codes("x = 1") == frozenset()


def test_noqa_suppresses_specific_rule():
    assert "KB104" not in rules_of("id = 3  # noqa: KB104\n")
    assert "KB104" in rules_of("id = 3  # noqa: KB101\n")
    assert "KB104" not in rules_of("id = 3  # noqa\n")


def test_baseline_cli_roundtrip(tmp_path, monkeypatch, capsys):
    monkeypatch.chdir(tmp_path)
    (tmp_path / "a.py").write_text("import os\n")  # KB102

    assert main(["a.py"]) == 1
    assert "KB102" in capsys.readouterr().out

    assert main(["--write-baseline", "a.py"]) == 0
    assert main(["a.py"]) == 0  # baselined now
    assert main(["--no-baseline", "a.py"]) == 1  # ignoring it fires again

    # --no-baseline-growth fails on stale entries so debt can only shrink
    data = json.loads((tmp_path / ".graftlint_baseline.json").read_text())
    data["entries"].append({"key": "gone.py::KB102::os", "reason": "stale"})
    (tmp_path / ".graftlint_baseline.json").write_text(json.dumps(data))
    assert main(["a.py"]) == 0  # plain run tolerates the stale entry
    assert main(["--no-baseline-growth", "a.py"]) == 1
    assert "stale baseline entry" in capsys.readouterr().out


def test_baseline_requires_justification(tmp_path, monkeypatch):
    monkeypatch.chdir(tmp_path)
    (tmp_path / "a.py").write_text("x = 1\n")
    (tmp_path / ".graftlint_baseline.json").write_text(
        json.dumps({"entries": [{"key": "a.py::KB102::os"}]})
    )
    assert main(["a.py"]) == 2


def test_syntax_error_is_a_finding(tmp_path, monkeypatch, capsys):
    monkeypatch.chdir(tmp_path)
    (tmp_path / "a.py").write_text("def broken(:\n")
    assert main(["--no-baseline", "a.py"]) == 1
    assert "KB100" in capsys.readouterr().out


# ---------------------------------------------------------------------------
# CLI odds and ends + registry hygiene


def test_cli_explain_and_list(capsys):
    assert main(["--explain", "KB201"]) == 0
    assert "lax.cond" in capsys.readouterr().out
    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in ("KB101", "KB204", "KB302"):
        assert rid in out
    assert main(["--explain", "KB999"]) == 2
    assert main(["--bogus-flag"]) == 2


def test_registry_docs_complete():
    _load_rules()
    expected = {
        "KB101", "KB102", "KB103", "KB104",
        "KB201", "KB202", "KB203", "KB204", "KB205",
        "KB301", "KB302",
    }
    assert expected <= set(REGISTRY)
    for r in REGISTRY.values():
        assert r.title and len(r.explain) > 40


def test_kb302_oracle_and_fleet_stats_in_scope():
    """oracle/ and fleet/stats.py are registered hot-path scope: the
    dtype-discipline rule fires there (the parity oracles define what
    'bit-exact' means — a dtype drift silently re-defines it)."""
    src = "import jax.numpy as jnp\nx = jnp.zeros((4, 4))\n"
    for path in (
        "kaboodle_tpu/oracle/fingerprint.py",
        "kaboodle_tpu/oracle/engine.py",
        "kaboodle_tpu/oracle/lockstep.py",
        "kaboodle_tpu/fleet/stats.py",
        # phasegraph/: the derived-engine bodies every parity pin now
        # compares — the one place a dtype drift lands in all five
        # compiled program families at once.
        "kaboodle_tpu/phasegraph/exec.py",
        "kaboodle_tpu/phasegraph/blocked.py",
        "kaboodle_tpu/phasegraph/span.py",
    ):
        assert "KB302" in rules_of(src, path), path
    # analysis/core.py (outside HOT_DIRS) must not collide with fleet/core.py
    assert "KB302" not in rules_of(src, "kaboodle_tpu/analysis/core.py")


def test_pragma_on_nested_closure():
    """The make_tick_fn idiom the graftscan registry depends on: the
    pragma sits on a def NESTED inside an untraced factory, and tracing
    (plus full-param taint) applies to that closure alone."""
    src = """
    def make_tick(cfg):
        scale = cfg.scale
        def tick(st, inp):  # graftlint: traced
            if st > 0:
                return st * scale
            return inp
        return tick
    """
    found = [f for f in analyze_source(textwrap.dedent(src)) if f.rule == "KB201"]
    assert len(found) == 1 and "tick" in found[0].symbol
    # the factory itself stays untraced: a branch there is host control flow
    src_factory_branch = """
    def make_tick(cfg):
        if cfg.fast:
            def tick(st):  # graftlint: traced
                return st
            return tick
        return None
    """
    assert "KB201" not in rules_of(src_factory_branch)


def test_pragma_on_decorated_function():
    """Decorators stack ABOVE the def line; the pragma lives on the def
    itself (node.lineno points at `def` since py3.8) and must still seed
    tracing through arbitrary non-trace decorators."""
    src = """
    import functools

    def wraps(f):
        return f

    @functools.lru_cache(maxsize=None)
    @wraps
    def tick(st, inp):  # graftlint: traced
        if inp > 0:
            return st
        return inp
    """
    assert "KB201" in rules_of(src)
    # ...and a pragma on the DECORATOR line must NOT seed (it is not the
    # def line — the documented contract)
    src_wrong_line = """
    import functools

    @functools.lru_cache(maxsize=None)  # graftlint: traced
    def tick(st, inp):
        if inp > 0:
            return st
        return inp
    """
    assert "KB201" not in rules_of(src_wrong_line)


def test_pragma_closure_propagates_to_nested_defs():
    """Defs nested inside a pragma'd function are traced transitively
    (they run under the same trace), with their own full params."""
    src = """
    def leap(st):  # graftlint: traced
        def body(carry, x):
            if x > 0:
                return carry, x
            return carry, -x
        return body(st, st)
    """
    found = [f for f in analyze_source(textwrap.dedent(src)) if f.rule == "KB201"]
    assert len(found) == 1 and "body" in found[0].symbol


def test_pragma_async_def_and_trailing_comment():
    """AsyncFunctionDef collection + pragma coexisting with other trailing
    comment text on the def line."""
    src = """
    async def tick(st):  # worker loop  # graftlint: traced
        if st > 0:
            return st
        return -st
    """
    assert "KB201" in rules_of(src)


def test_repo_is_clean_under_baseline(monkeypatch):
    """The acceptance gate: HEAD lints clean over the full default target
    set (baselined findings allowed, baseline not stale). Catches
    regressions the moment a PR adds a finding without justifying it."""
    import pathlib

    repo = pathlib.Path(__file__).resolve().parent.parent
    monkeypatch.chdir(repo)
    assert main(["--no-baseline-growth"]) == 0
