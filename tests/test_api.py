"""The Kaboodle facade: lifecycle, queries, events — the 2x2 demo as a test.

What the reference verifies by eyeballing four zellij panes (SURVEY.md §4:
justfile run2x2, identities top-left..bottom-right, matching fingerprints,
then kill a pane and watch departure detection) is asserted here against the
simulated network.
"""

import pytest

from kaboodle_tpu.api import Kaboodle, SimNetwork
from kaboodle_tpu.errors import InvalidOperation

IDENTITIES = [b"top-left", b"top-right", b"bottom-left", b"bottom-right"]


def _demo_mesh():
    net = SimNetwork(capacity=4, seed=0)
    nodes = [Kaboodle(net, ident) for ident in IDENTITIES]
    for k in nodes:
        k.start()
    return net, nodes


@pytest.mark.slow
def test_2x2_demo_converges_with_matching_fingerprints():
    net, nodes = _demo_mesh()
    ticks = net.tick_until_converged(max_ticks=16)
    fps = {k.fingerprint() for k in nodes}
    assert len(fps) == 1 and 0 not in fps
    assert ticks <= 4
    # Every pane shows all four peers with their consumer identity payloads.
    for k in nodes:
        assert k.peers() == {i: IDENTITIES[i] for i in range(4)}
        states = k.peer_states()
        assert all(s == "Known" for s, _, _ in states.values())


def test_lifecycle_guards():
    net = SimNetwork(capacity=2)
    k = Kaboodle(net, b"a")
    with pytest.raises(InvalidOperation):
        k.stop()  # not started
    k.start()
    with pytest.raises(InvalidOperation):
        k.start()  # double start
    assert k.is_running and k.self_addr() == 0 and k.interface() == "sim"
    k.stop()
    assert not k.is_running
    full = SimNetwork(capacity=1)
    Kaboodle(full, b"only")
    with pytest.raises(InvalidOperation):
        Kaboodle(full, b"overflow")  # network full


@pytest.mark.slow
def test_departure_detection_after_stop():
    """Kill one pane; survivors detect via ping-timeout -> indirect-ping ->
    removal (kaboodle.rs:558-653) and the departure stream fires."""
    net, nodes = _demo_mesh()
    net.tick_until_converged(max_ticks=16)
    departures = [k.discover_departures() for k in nodes[:3]]
    nodes[3].stop()
    # A removed peer can transiently re-enter via anti-entropy gossip until
    # every sharer's last-heard stamp ages past MAX_PEER_SHARE_AGE (Q6), so
    # allow several cycles and assert the net effect, not a single event.
    for _ in range(40):
        net.tick()
        if (
            all(q for q in departures)
            and bool(net.metrics.converged)
            and all(3 not in k.peers() for k in nodes[:3])
        ):
            break
    assert all(set(q) == {3} for q in departures)
    for k in nodes[:3]:
        assert 3 not in k.peers()
    # Stopped instance keeps its (stale) map (lib.rs:167-170).
    assert 3 in nodes[3].peers()


@pytest.mark.slow
def test_discovery_stream_and_next_peer():
    net = SimNetwork(capacity=3)
    a = Kaboodle(net, b"a")
    b = Kaboodle(net, b"b")
    a.start()
    q = a.discover_peers()
    net.tick()
    discovered = {p for p, _ in q}
    assert 0 in discovered  # self insert announced (kaboodle.rs:144-152)
    b.start()
    got = a.discover_next_peer(max_ticks=8)
    assert got is not None and got[0] == 1


@pytest.mark.slow
def test_restart_rejoins_with_reset():
    net, nodes = _demo_mesh()
    net.tick_until_converged(max_ticks=16)
    nodes[0].stop()
    net.tick(2)
    nodes[0].start()
    # The restart's Join is not "new" to peers that still hold node 0, so no
    # join-reply bootstrap fires (kaboodle.rs:284-304); the reset row refills
    # via incoming pings + anti-entropy pulls over the next ticks (faithful).
    for _ in range(24):
        net.tick()
        if bool(net.metrics.converged) and set(nodes[0].peers()) == {0, 1, 2, 3}:
            break
    assert set(nodes[0].peers()) == {0, 1, 2, 3}
    assert bool(net.metrics.converged)


def test_set_identity_reannounces_and_changes_fingerprint():
    net, nodes = _demo_mesh()
    net.tick_until_converged(max_ticks=16)
    fp_before = nodes[1].fingerprint()
    q = nodes[1].discover_peers()
    fq = nodes[1].discover_fingerprint_changes()
    nodes[0].set_identity(b"renamed")
    net.tick()
    assert nodes[1].fingerprint() != fp_before
    assert any(p == 0 for p, _ in q)  # peer 0 re-announced with new identity
    assert fq  # fingerprint change announced
    assert nodes[1].peers()[0] == b"renamed"


@pytest.mark.slow
def test_manual_ping_bootstrap():
    """With broadcasts suppressed by full drop, ping_addrs is the only way to
    meet — the reference's manual bootstrap path (lib.rs:268-297)."""
    net = SimNetwork(capacity=2, seed=1)
    a = Kaboodle(net, b"a")
    b = Kaboodle(net, b"b")
    net.set_drop_rate(1.0)
    a.start()
    b.start()
    net.tick(2)  # joins all dropped
    assert set(a.peers()) == {0} and set(b.peers()) == {1}
    net.set_drop_rate(0.0)
    with pytest.raises(InvalidOperation):
        Kaboodle(net, b"c")  # network full guard

    a.ping_addrs([1])
    net.tick()
    assert set(a.peers()) == {0, 1} and set(b.peers()) == {0, 1}
    net.tick_until_converged(max_ticks=8)
    assert a.fingerprint() == b.fingerprint()


def test_ping_addrs_requires_running():
    net = SimNetwork(capacity=1)
    k = Kaboodle(net, b"x")
    with pytest.raises(InvalidOperation):
        k.ping_addrs([0])


def test_start_stop_before_tick_cancel_cleanly():
    """start();stop() with no tick in between must leave the peer dead (and
    the reverse must leave it alive) — pending ops cancel, they don't race."""
    net = SimNetwork(capacity=2)
    a = Kaboodle(net, b"a")
    b = Kaboodle(net, b"b")
    b.start()
    a.start()
    a.stop()
    net.tick()
    assert not bool(net.state.alive[0]) and bool(net.state.alive[1])
    a.start()
    net.tick()
    assert bool(net.state.alive[0])


@pytest.mark.slow
def test_convergence_timeout_raises():
    from kaboodle_tpu.errors import ConvergenceTimeout

    net = SimNetwork(capacity=2)
    a = Kaboodle(net, b"a")
    b = Kaboodle(net, b"b")
    a.start()
    b.start()
    net.set_drop_rate(1.0)  # nothing can ever be delivered
    with pytest.raises(ConvergenceTimeout):
        net.tick_until_converged(max_ticks=4)


@pytest.mark.slow
def test_peer_states_surfaces_latency_ewma():
    """After a few ticks of traffic, the per-peer latency EWMA is a real
    number (kaboodle.rs:789-817 surfaced via lib.rs:348-354). Self has no
    samples (a peer never pings itself) and reports None."""
    net, nodes = _demo_mesh()
    net.tick(8)
    sampled = [
        lat
        for k in nodes
        for j, (_, _, lat) in k.peer_states().items()
        if j != k.self_addr()
    ]
    assert sampled and any(lat is not None for lat in sampled)
    for lat in sampled:
        assert lat is None or lat >= 0.0
    for k in nodes:
        assert k.peer_states()[k.self_addr()][2] is None


def test_discover_mesh_member_probe_without_joining():
    """The standalone probe (discovery.rs:30-89, lib.rs:359-368): find one
    running member + identity without attaching an instance."""
    net, nodes = _demo_mesh()
    net.tick(2)
    addr, ident = net.discover_mesh_member()
    assert addr in {k.self_addr() for k in nodes if k.is_running}
    assert ident == IDENTITIES[addr]
    empty = SimNetwork(capacity=2)
    Kaboodle(empty, b"idle")  # attached but never started
    with pytest.raises(InvalidOperation):
        empty.discover_mesh_member()


def test_explicit_revive_survives_churn_composition():
    """An explicit revive_at (deliberate restart of an alive peer) must not be
    rewritten by a later churn() call covering the same tick."""
    from kaboodle_tpu.sim import Scenario

    sc = Scenario(n=8, ticks=20, seed=0).revive_at(10, [3]).churn(0.01, protect=[0])
    assert sc._revive[10][3]
