"""Smoke tests for the driver-facing benchmark helpers.

bench.py is the artifact the driver runs on real hardware at end of round; a
broken helper there silently costs a capture window, so the sections are
exercised at tiny N here (full-size numbers come from the real runs).
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from bench import _bench, _bench_churn, _bench_detection, _bench_gossip_boot  # noqa: E402
import pytest


@pytest.mark.slow
def test_bench_throughput_section():
    r = _bench(64, ticks=4)
    assert r["converged"] and r["ticks_to_convergence"] >= 1
    assert r["peers_ticks_per_sec"] > 0
    assert r["state_variant"] == "full"  # below the lean threshold


@pytest.mark.slow
def test_bench_gossip_and_epidemic_sections():
    (g,) = _bench_gossip_boot([48], max_ticks=2048)
    (e,) = _bench_gossip_boot([48], max_ticks=256, backdate=False)
    assert g["converged"] and e["converged"]
    # Epidemic boot (no Q6 back-dating) must beat the reference-faithful
    # gossip boot decisively — that is its whole point.
    assert e["ticks_to_convergence"] < g["ticks_to_convergence"]


@pytest.mark.slow
def test_bench_churn_section():
    r = _bench_churn(64, ticks=16)
    assert r["peers_ticks_per_sec"] > 0
    assert 0.0 <= r["final_agree_fraction"] <= 1.0


@pytest.mark.slow
def test_bench_detection_section():
    r = _bench_detection(48)
    assert r["first_removal_tick"] is not None
    assert r["detection_complete_tick"] is not None
    assert r["within_bound"], r


def test_is_size_ceiling_matches_http_500_only():
    """The step-down trigger must catch the remote-compile helper's HTTP 500
    but NOT a real compile bug whose text merely contains the digits 500
    (a shape dim / line number) — that must surface as a traceback
    (ADVICE r5)."""
    from bench import _is_size_ceiling

    # Real triggers: OOM shapes, the helper by name, status-shaped 500s.
    assert _is_size_ceiling(RuntimeError("RESOURCE_EXHAUSTED: out of memory"))
    assert _is_size_ceiling(RuntimeError("tpu_compile_helper: request failed"))
    assert _is_size_ceiling(RuntimeError("remote compile failed: HTTP 500"))
    assert _is_size_ceiling(RuntimeError("compile request status: 500"))
    assert _is_size_ceiling(
        RuntimeError("compile: 500 Internal Server Error"))
    # Non-triggers: 500 as a shape / line number in a compile error.
    assert not _is_size_ceiling(
        RuntimeError("XLA compile error: dot shape f32[500,512] mismatch"))
    assert not _is_size_ceiling(
        RuntimeError("failed to compile kernel.py:500: bad operand"))
    assert not _is_size_ceiling(RuntimeError("HTTP 500 from unrelated service"))


def test_bench_fastpath_ab_section():
    """The --fastpath-ab lane at toy scale: three bit-exact arms and the
    planner-sourced pass table riding along (full=15 passes incl. the four
    rare-phase ops the fused plan prunes into its dispatch predicate)."""
    from bench import _bench_fastpath_ab

    r = _bench_fastpath_ab(64, 8)
    assert r["bit_exact"] is True
    assert r["passes_full"] > r["passes_fused"]
    assert set(r["pruned"]) == {
        "suspicion", "join_insert", "join_replies", "calls34"
    }
    for k in ("full_wall_s", "dispatched_wall_s", "fused_wall_s", "speedup"):
        assert r[k] > 0
