"""Checkpoint/resume: a resumed trajectory is bit-identical to an unbroken one."""

import jax
import jax.numpy as jnp
import pytest

from kaboodle_tpu import checkpoint
from kaboodle_tpu.config import SwimConfig
from kaboodle_tpu.errors import KaboodleError
from kaboodle_tpu.parallel import make_mesh
from kaboodle_tpu.sim import idle_inputs, init_state, simulate


def _states_equal(a, b):
    import dataclasses

    for f in dataclasses.fields(a):
        va, vb = getattr(a, f.name), getattr(b, f.name)
        if va is None or vb is None:
            assert va is None and vb is None, f.name
        else:
            assert jnp.array_equal(va, vb, equal_nan=True), f.name


@pytest.mark.slow
def test_resume_is_bit_exact(tmp_path):
    n, cfg = 24, SwimConfig()
    st = init_state(n, seed=13)
    mid, _ = simulate(st, idle_inputs(n, ticks=7), cfg)
    unbroken, _ = simulate(mid, idle_inputs(n, ticks=9), cfg)

    path = tmp_path / "mesh.npz"
    checkpoint.save(path, mid)
    resumed_mid = checkpoint.load(path)
    _states_equal(mid, resumed_mid)
    resumed, _ = simulate(resumed_mid, idle_inputs(n, ticks=9), cfg)
    _states_equal(unbroken, resumed)


def test_load_onto_mesh(tmp_path):
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    mesh = make_mesh(8)
    st = init_state(32, seed=2)
    path = tmp_path / "mesh.npz"
    checkpoint.save(path, st)
    sharded = checkpoint.load(path, mesh=mesh)
    assert len(sharded.state.sharding.device_set) == 8
    _states_equal(st, sharded)


@pytest.mark.slow
def test_lean_state_roundtrip(tmp_path):
    """The memory-lean state (track_latency=False, instant_identity=True) —
    what the 65k-peer configs run — must roundtrip with its optional fields
    restored as None, and resume bit-exactly."""
    n, cfg = 16, SwimConfig()
    st = init_state(n, seed=5, track_latency=False, instant_identity=True,
                    timer_dtype=jnp.int16)
    mid, _ = simulate(st, idle_inputs(n, ticks=5), cfg)
    unbroken, _ = simulate(mid, idle_inputs(n, ticks=5), cfg)

    path = tmp_path / "lean.npz"
    checkpoint.save(path, mid)
    resumed_mid = checkpoint.load(path)
    assert resumed_mid.latency is None and resumed_mid.id_view is None
    assert resumed_mid.timer.dtype == jnp.int16  # narrow dtype survives
    _states_equal(mid, resumed_mid)
    resumed, _ = simulate(resumed_mid, idle_inputs(n, ticks=5), cfg)
    _states_equal(unbroken, resumed)


def test_lean_load_onto_mesh(tmp_path):
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    mesh = make_mesh(8)
    st = init_state(32, seed=4, track_latency=False, instant_identity=True)
    path = tmp_path / "lean_mesh.npz"
    checkpoint.save(path, st)
    sharded = checkpoint.load(path, mesh=mesh)
    assert sharded.latency is None and sharded.id_view is None
    assert len(sharded.state.sharding.device_set) == 8
    _states_equal(st, sharded)


@pytest.mark.slow
def test_orbax_async_roundtrip(tmp_path):
    """save_async + load_orbax: background write, bit-exact resume, lean
    fields and narrow dtypes preserved."""
    n, cfg = 16, SwimConfig()
    st = init_state(n, seed=8, track_latency=False, instant_identity=True,
                    timer_dtype=jnp.int16)
    mid, _ = simulate(st, idle_inputs(n, ticks=5), cfg)
    unbroken, _ = simulate(mid, idle_inputs(n, ticks=5), cfg)

    ck = checkpoint.save_async(str(tmp_path / "orbax"), mid)
    ck.wait_until_finished()
    template = init_state(n, track_latency=False, instant_identity=True,
                          timer_dtype=jnp.int16)
    back = checkpoint.load_orbax(str(tmp_path / "orbax"), template)
    assert back.timer.dtype == jnp.int16
    assert back.latency is None and back.id_view is None
    _states_equal(mid, back)
    resumed, _ = simulate(back, idle_inputs(n, ticks=5), cfg)
    _states_equal(unbroken, resumed)


def test_orbax_load_directly_sharded(tmp_path):
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    from jax.sharding import NamedSharding, PartitionSpec as P

    from kaboodle_tpu.parallel import PEER_AXIS

    mesh = make_mesh(8)
    st = init_state(32, seed=6)
    ck = checkpoint.save_async(str(tmp_path / "orbax_mesh"), st)
    ck.wait_until_finished()
    back = checkpoint.load_orbax(
        str(tmp_path / "orbax_mesh"), init_state(32), mesh=mesh
    )
    want = NamedSharding(mesh, P(PEER_AXIS, None))
    assert back.state.sharding.is_equivalent_to(want, back.state.ndim)
    assert len(back.state.sharding.device_set) == 8
    _states_equal(st, back)


def test_version_and_field_guards(tmp_path):
    import numpy as np

    bad = tmp_path / "bad.npz"
    np.savez(bad, __version__=np.int32(99))
    with pytest.raises(KaboodleError):
        checkpoint.load(bad)
    truncated = tmp_path / "trunc.npz"
    np.savez(truncated, __version__=np.int32(1), state=np.zeros((2, 2)))
    with pytest.raises(KaboodleError):
        checkpoint.load(truncated)


def test_fleet_roundtrip_with_generation(tmp_path):
    """ISSUE 10 satellite: a serve pool resident — FleetState + per-lane
    generation counters — round-trips bit-exactly through save_fleet/
    load_fleet, and a resumed fleet's trajectory matches an unbroken one."""
    import numpy as np

    from kaboodle_tpu.fleet.core import (
        fleet_idle_inputs,
        init_fleet,
        simulate_fleet,
    )

    n, e, cfg = 16, 3, SwimConfig(deterministic=True)
    fleet = init_fleet(n, e, drop_rates=jnp.array([0.0, 0.1, 0.2]))
    inputs = fleet_idle_inputs(n, e, ticks=5)
    mid, _ = simulate_fleet(fleet, inputs, cfg, faulty=True)
    generation = jnp.array([4, 0, 7], dtype=jnp.int32)

    path = tmp_path / "fleet.npz"
    checkpoint.save_fleet(path, mid, generation)
    restored, gen2 = checkpoint.load_fleet(path)
    _states_equal(mid.mesh, restored.mesh)
    assert jnp.array_equal(mid.drop_rate, restored.drop_rate)
    assert gen2.dtype == jnp.int32
    assert np.array_equal(np.asarray(gen2), [4, 0, 7])

    unbroken, _ = simulate_fleet(mid, inputs, cfg, faulty=True)
    resumed, _ = simulate_fleet(restored, inputs, cfg, faulty=True)
    _states_equal(unbroken.mesh, resumed.mesh)


def test_fleet_roundtrip_without_generation(tmp_path):
    from kaboodle_tpu.fleet.core import init_fleet

    fleet = init_fleet(8, 2)
    path = tmp_path / "fleet.npz"
    checkpoint.save_fleet(path, fleet)
    restored, gen = checkpoint.load_fleet(path)
    assert gen is None
    _states_equal(fleet.mesh, restored.mesh)


def test_fleet_checkpoint_guards(tmp_path):
    import numpy as np

    from kaboodle_tpu.fleet.core import init_fleet

    # a single-mesh checkpoint is not a fleet checkpoint
    single = tmp_path / "single.npz"
    checkpoint.save(single, init_state(8, seed=0))
    with pytest.raises(KaboodleError, match="not a fleet checkpoint"):
        checkpoint.load_fleet(single)
    # missing mesh fields are loud
    bad = tmp_path / "bad.npz"
    np.savez(bad, __version__=np.int32(1), __fleet__=np.int32(1),
             drop_rate=np.zeros((2,), np.float32))
    with pytest.raises(KaboodleError, match="missing fields"):
        checkpoint.load_fleet(bad)
    # lane spill uses the single-mesh path: a fleet file is not a MeshState
    fleet_path = tmp_path / "fleet.npz"
    checkpoint.save_fleet(fleet_path, init_fleet(8, 2))
    with pytest.raises(KaboodleError, match="missing fields"):
        checkpoint.load(fleet_path)


def test_corrupt_file_guards(tmp_path):
    """ISSUE 12 satellite: torn or alien files surface as CheckpointError
    (never a raw BadZipFile/EOFError leaking out of numpy) from load and
    load_fleet alike — the serve restore path relies on this to turn a
    corrupt spill file into a structured error with the service intact."""
    from kaboodle_tpu.errors import CheckpointError

    st = init_state(8, seed=1)
    good = tmp_path / "good.npz"
    checkpoint.save(good, st)

    data = good.read_bytes()
    torn = tmp_path / "torn.npz"  # the zip central directory is gone
    torn.write_bytes(data[: len(data) // 3])
    with pytest.raises(CheckpointError):
        checkpoint.load(torn)
    with pytest.raises(CheckpointError):
        checkpoint.load_fleet(torn)

    alien = tmp_path / "alien.npz"  # wrong magic: not an archive at all
    alien.write_bytes(b"definitely not a zip archive\n" * 4)
    with pytest.raises(CheckpointError):
        checkpoint.load(alien)

    with pytest.raises(CheckpointError):
        checkpoint.load(tmp_path / "missing.npz")
    # CheckpointError IS a KaboodleError: existing handlers keep working.
    assert issubclass(CheckpointError, KaboodleError)


def test_atomic_save_is_complete_or_absent(tmp_path):
    """atomic=True goes through fsync-then-rename: the final path holds a
    complete archive and no temp file survives."""
    st = init_state(8, seed=3)
    path = tmp_path / "atomic.npz"
    checkpoint.save(path, st, atomic=True)
    _states_equal(st, checkpoint.load(path))
    assert list(tmp_path.iterdir()) == [path]


# ---- sparse (blocked_topk) checkpoints -------------------------------------


def _sparse_cfg_spec():
    from kaboodle_tpu.sparseplane import SparseSpec

    return SwimConfig(join_broadcast_enabled=False), SparseSpec(
        k=16, gossip_fanout=4, boot_contacts=2
    )


def test_sparse_roundtrip_resume_bit_exact(tmp_path):
    """Neighbor-index planes AND the counter-RNG (seed, cursor) round-trip:
    a resumed sparse run replays the exact draw sequence an uninterrupted
    one makes (draws are pure functions of the cursor)."""
    from kaboodle_tpu.sparseplane import (
        init_sparse_state, simulate_sparse, sparse_idle_inputs,
    )

    cfg, spec = _sparse_cfg_spec()
    n = 24
    st = init_sparse_state(n, spec, seed=11)
    mid, _ = simulate_sparse(st, sparse_idle_inputs(n, 5), cfg, spec)
    unbroken, _ = simulate_sparse(mid, sparse_idle_inputs(n, 6), cfg, spec)

    path = tmp_path / "sparse.npz"
    checkpoint.save_sparse(path, mid, atomic=True)
    resumed_mid = checkpoint.load_sparse(path)
    _states_equal(mid, resumed_mid)
    resumed, _ = simulate_sparse(
        resumed_mid, sparse_idle_inputs(n, 6), cfg, spec
    )
    _states_equal(unbroken, resumed)
    assert list(tmp_path.iterdir()) == [path]  # atomic: no temp survives


def test_sparse_checkpoint_guards(tmp_path):
    """Schema marker + torn/alien files: the three checkpoint families can
    never cross-restore, and a torn sparse archive surfaces as
    CheckpointError, not a raw zipfile exception."""
    import numpy as np

    from kaboodle_tpu.errors import CheckpointError
    from kaboodle_tpu.sparseplane import init_sparse_state

    cfg, spec = _sparse_cfg_spec()
    st = init_sparse_state(16, spec, seed=3)
    sp = tmp_path / "sparse.npz"
    checkpoint.save_sparse(sp, st)

    # a sparse archive is not a dense or fleet checkpoint...
    with pytest.raises(CheckpointError):
        checkpoint.load(sp)
    with pytest.raises(CheckpointError):
        checkpoint.load_fleet(sp)
    # ...and a dense archive is not a sparse one
    dense = tmp_path / "dense.npz"
    checkpoint.save(dense, init_state(8, seed=1))
    with pytest.raises(CheckpointError, match="not a sparse checkpoint"):
        checkpoint.load_sparse(dense)

    torn = tmp_path / "torn.npz"
    torn.write_bytes(sp.read_bytes()[: sp.stat().st_size // 3])
    with pytest.raises(CheckpointError):
        checkpoint.load_sparse(torn)
    alien = tmp_path / "alien.npz"
    alien.write_bytes(b"definitely not a zip archive\n" * 4)
    with pytest.raises(CheckpointError):
        checkpoint.load_sparse(alien)
    with pytest.raises(CheckpointError):
        checkpoint.load_sparse(tmp_path / "missing.npz")

    # a sparse archive with a plane deleted names the missing field
    partial = {
        k: np.asarray(v)
        for k, v in np.load(sp).items()
        if k != "sparse.cursor"
    }
    short = tmp_path / "short.npz"
    np.savez(short, **partial)
    with pytest.raises(CheckpointError, match="cursor"):
        checkpoint.load_sparse(short)
