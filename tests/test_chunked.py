"""Chunked (row-blocked) tick vs the whole-tensor kernel: exact parity.

``make_chunked_tick_fn`` re-expresses the tick as lax.map passes over row
blocks so peak transients are O(block·N) — the N=65,536 enabler
(sim/chunked.py docstring). Its contract is bit-exact trajectory equality
with ``make_tick_fn`` whenever only per-row draws are consumed: all of
deterministic mode, and random mode away from the matrix-draw branches
(deviation D10). These tests pin that contract over trajectories that
exercise every phase: the join avalanche, churn kill/revive (revive
re-enters through the join path), partitions, random-but-pinned drop
matrices, manual pings, suspicion escalation, indirect pings, calls 3-4
forwarding, and anti-entropy shares.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kaboodle_tpu.config import SwimConfig
from kaboodle_tpu.sim.chunked import make_chunked_tick_fn
from kaboodle_tpu.sim.kernel import make_tick_fn
from kaboodle_tpu.sim.state import TickInputs, idle_inputs, init_state


def _assert_leaves_equal(tree_a, tree_b, tick=None):
    for a, b in zip(jax.tree.leaves(tree_a), jax.tree.leaves(tree_b)):
        av, bv = np.asarray(a), np.asarray(b)
        if av.dtype == np.float32:  # latency carries NaNs (no sample yet)
            assert ((av == bv) | (np.isnan(av) & np.isnan(bv))).all(), tick
        else:
            assert (av == bv).all(), (tick, (av != bv).sum())


def _fault_schedule(n: int, ticks: int, drop: bool = True) -> TickInputs:
    """Every fault path: kills (-> escalations -> removals), a revive
    (join re-entry), a partition window, manual pings, pinned drop."""
    rng = np.random.default_rng(7)
    kill = np.zeros((ticks, n), bool)
    kill[5, [3, min(7, n - 1)]] = True
    rev = np.zeros((ticks, n), bool)
    rev[12, 3] = True
    part = np.zeros((ticks, n), np.int32)
    part[15:20, : n // 2] = 1
    man = np.full((ticks, n), -1, np.int32)
    man[8, 0] = min(9, n - 1)
    man[22, 4] = min(17, n - 1)
    drop_ok = (rng.random((ticks, n, n)) > 0.15) if drop else np.ones(
        (ticks, n, n), bool)
    return TickInputs(
        kill=jnp.asarray(kill),
        revive=jnp.asarray(rev),
        partition=jnp.asarray(part),
        drop_rate=jnp.zeros((ticks,), jnp.float32),
        manual_target=jnp.asarray(man),
        drop_ok=jnp.asarray(drop_ok),
    )


def _run_parity(st, inp, cfg, faulty, block, ticks):
    tick_a = jax.jit(make_tick_fn(cfg, faulty=faulty))
    tick_b = jax.jit(make_chunked_tick_fn(cfg, faulty=faulty, block=block))
    sa = sb = st
    for t in range(ticks):
        it = jax.tree.map(lambda x: x[t], inp)
        sa, ma = tick_a(sa, it)
        sb, mb = tick_b(sb, it)
        _assert_leaves_equal((sa, ma), (sb, mb), tick=t)
    return sa


@pytest.mark.slow
@pytest.mark.parametrize("lean", [False, True])
def test_chunked_parity_full_fault_schedule(lean):
    """Deterministic faulty trajectory, every fault path, full vs lean
    state planes, block 8 over N=24."""
    n, ticks = 24, 30
    cfg = SwimConfig(deterministic=True)
    st = init_state(n, seed=1, track_latency=not lean, instant_identity=lean,
                    timer_dtype=jnp.int16 if lean else jnp.int32)
    _run_parity(st, _fault_schedule(n, ticks), cfg, True, 8, ticks)


@pytest.mark.slow
def test_chunked_parity_share_cap():
    """D5 cap active (max_share_peers < N): the capped-share branch of the
    blocked gossip union — the branch that actually runs at N=65,536 —
    against the whole-tensor kernel's, over join-bearing ticks."""
    n, ticks = 24, 30
    cfg = SwimConfig(deterministic=True, max_share_peers=8)
    st = init_state(n, seed=4)
    _run_parity(st, _fault_schedule(n, ticks), cfg, True, 8, ticks)


@pytest.mark.slow
@pytest.mark.parametrize("det", [True, False])
def test_chunked_boot_union_closed_form(det):
    """boot_union=True (the closed-form avalanche union) against both the
    dense chunked union and the whole-tensor kernel on its valid shape: a
    fault-free broadcast boot from singleton maps, where tick 0 is the
    only join-bearing tick. Random mode included: the Bernoulli streams
    differ from the flagship kernel (D10) but must agree between the two
    chunked builds, which share them — so the three-way check is dense
    chunked == boot_union chunked (exact, both modes) and, in
    deterministic mode, == make_tick_fn too."""
    n, ticks = 48, 10
    cfg = SwimConfig(deterministic=det)
    st = init_state(n, seed=6)
    inp = idle_inputs(n, ticks=ticks)
    tick_d = jax.jit(make_chunked_tick_fn(cfg, faulty=False, block=16))
    tick_b = jax.jit(make_chunked_tick_fn(cfg, faulty=False, block=16,
                                          boot_union=True))
    tick_k = jax.jit(make_tick_fn(cfg, faulty=False))
    sd = sb = sk = st
    for t in range(ticks):
        it = jax.tree.map(lambda x: x[t], inp)
        sd, md = tick_d(sd, it)
        sb, mb = tick_b(sb, it)
        _assert_leaves_equal((sd, md), (sb, mb), tick=t)
        if det:
            sk, mk = tick_k(sk, it)
            _assert_leaves_equal((sk, mk), (sb, mb), tick=t)
    assert bool(np.asarray(mb.converged))


@pytest.mark.slow
def test_chunked_parity_epidemic_boot():
    """Join broadcasts compiled out (gossip boot, fresh stamps): the
    chunked path with no join machinery at all."""
    n, ticks = 32, 24
    cfg = SwimConfig(deterministic=True, join_broadcast_enabled=False,
                     backdate_gossip_inserts=False)
    st = init_state(n, seed=0, ring_contacts=2)
    inp = idle_inputs(n, ticks=ticks)
    out = _run_parity(st, inp, cfg, False, 8, ticks)
    assert int(out.tick) == ticks


@pytest.mark.slow
def test_chunked_parity_random_mode_vector_draws_only():
    """Random mode is exact while only the per-row ping draw is consumed
    (no joins, no escalation, no random drop): converged-init idle ticks."""
    n, ticks = 32, 12
    cfg = SwimConfig(deterministic=False, join_broadcast_enabled=False)
    st = init_state(n, seed=5, ring_contacts=n - 1)
    inp = idle_inputs(n, ticks=ticks)
    _run_parity(st, inp, cfg, False, 16, ticks)


@pytest.mark.slow
def test_chunked_parity_intended_semantics():
    """Non-default parity flags: Failed broadcasts deliver (the chunked
    blocked contraction replaces kernel.py's O(N^3) matmul) and forwarded
    indirect acks clear suspicion."""
    n, ticks = 24, 30
    cfg = SwimConfig(deterministic=True, faithful_failed_broadcast=False,
                     faithful_indirect_ack=False)
    st = init_state(n, seed=2)
    _run_parity(st, _fault_schedule(n, ticks), cfg, True, 8, ticks)


def test_chunked_single_block_and_bad_block():
    n = 16
    cfg = SwimConfig(deterministic=True)
    st = init_state(n, seed=0)
    inp = idle_inputs(n, ticks=4)
    _run_parity(st, inp, cfg, False, n, 4)  # block == N
    with pytest.raises(ValueError, match="does not divide"):
        jax.jit(make_chunked_tick_fn(cfg, faulty=False, block=5))(
            st, jax.tree.map(lambda x: x[0], inp))


@pytest.mark.slow
def test_chunked_random_drop_converges():
    """D10 smoke: random-mode chunked with drop_rate > 0 uses per-block
    drop streams (distributional, not samplewise, parity) — assert the
    protocol still behaves: a converged mesh stays converged under 10%
    drop and the kill path still removes a dead peer. The budget rides the
    ~2N-tick removal-completeness bound (SURVEY §6) plus drop slack."""
    n, ticks = 32, 96
    cfg = SwimConfig(deterministic=False)
    st = init_state(n, seed=3, ring_contacts=n - 1)
    kill = np.zeros((ticks, n), bool)
    kill[0, 5] = True
    inp = TickInputs(
        kill=jnp.asarray(kill),
        revive=jnp.zeros((ticks, n), bool),
        partition=jnp.zeros((ticks, n), jnp.int32),
        drop_rate=jnp.full((ticks,), 0.1, jnp.float32),
        manual_target=jnp.full((ticks, n), -1, jnp.int32),
    )
    tick = jax.jit(make_chunked_tick_fn(cfg, faulty=True, block=8))
    sb = st
    for t in range(ticks):
        sb, m = tick(sb, jax.tree.map(lambda x: x[t], inp))
    # Every survivor must have dropped the dead peer by ~2N calm ticks.
    state = np.asarray(sb.state)
    alive = np.asarray(sb.alive)
    assert not state[alive][:, 5].any()
    assert bool(np.asarray(m.converged))


def test_boot_union_rejects_faulty_build():
    """boot_union's closed form assumes fault-free delivery on the boot
    tick; combining it with the faulty build is never valid and must fail
    at build time, not silently produce wrong gossip (ADVICE r5)."""
    with pytest.raises(ValueError, match="boot_union"):
        make_chunked_tick_fn(SwimConfig(), faulty=True, boot_union=True)
