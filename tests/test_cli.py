"""CLI demo app (main.rs equivalent): args, interface resolution, live demo."""

import json
import subprocess
import sys

import pytest

from kaboodle_tpu.cli import build_parser, format_peer_table, main, resolve_interface
from kaboodle_tpu.errors import NoAvailableInterfaces
from kaboodle_tpu.transport.native import list_interfaces


def test_parser_flags():
    a = build_parser().parse_args(
        ["--identity", "x", "--port", "7000", "--ping", "1.2.3.4:5", "--ping", "6.7.8.9:1"]
    )
    assert a.identity == "x" and a.port == 7000
    assert a.ping == ["1.2.3.4:5", "6.7.8.9:1"]


def test_resolve_interface():
    ifaces = list_interfaces()
    if not ifaces:
        pytest.skip("no interfaces")
    ip, idx, bcast = resolve_interface(None)  # IPv6-preferred reference policy
    fams = {i["family"] for i in ifaces}
    if 6 in fams:
        assert ":" in ip and bcast == "ff02::1213:1989"
    explicit = resolve_interface(ifaces[0]["ip"])
    assert explicit[0] == ifaces[0]["ip"]
    with pytest.raises(NoAvailableInterfaces):
        resolve_interface("203.0.113.77")


def test_resolve_interface_by_name_and_family_keywords():
    """--interface resolves by device name and ipv4/ipv6 keywords
    (main.rs:18-36: name, IP, or family, uncanonicalized)."""
    ifaces = list_interfaces()
    if not ifaces:
        pytest.skip("no interfaces")
    named = [i for i in ifaces if i["name"]]
    assert named, "list_interfaces must surface the device name"
    first = named[0]
    ip, idx, _ = resolve_interface(first["name"])
    # Name matching returns the first address on that device (like the
    # reference's .find()); assert it belongs to the named device.
    matches = [i for i in ifaces if i["name"] == first["name"]]
    assert any(i["ip"] == ip and i["ifindex"] == idx for i in matches)
    fams = {i["family"] for i in ifaces}
    if 4 in fams:
        assert resolve_interface("ipv4") == resolve_interface("v4")
    if 6 in fams:
        assert resolve_interface("ipv6") == resolve_interface("v6")
    with pytest.raises(NoAvailableInterfaces):
        resolve_interface("no-such-device0")


def test_format_peer_table():
    out = format_peer_table(
        "1.1.1.1:1",
        {"1.1.1.1:1": ("Known", None), "2.2.2.2:2": ("WaitingForPing", 12.5)},
        {"1.1.1.1:1": b"me", "2.2.2.2:2": b"you"},
    )
    assert "(me)" in out and "WaitingForPing" in out and "12.5ms" in out


@pytest.mark.slow
def test_sim_mode(capsys):
    rc = main(["--sim", "64", "--ticks", "8"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0 and out["final_converged"] and out["n_peers"] == 64


def test_sim_scenario_mode(capsys):
    rc = main(["--sim-scenario", "1", "--ticks", "8"])
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0 and out["n_peers"] == 4


@pytest.mark.slow
def test_two_instance_live_demo():
    """The run2x2 demo shape as a subprocess test: two CLI instances find each
    other and report 2 peers with matching fingerprints."""
    v4 = [i for i in list_interfaces() if i["family"] == 4 and i["broadcast"]]
    if not v4:
        pytest.skip("no broadcast-capable IPv4 interface")
    cmd = [
        sys.executable, "-m", "kaboodle_tpu",
        "--interface", v4[0]["ip"], "--port", "18766",
        "--period-ms", "100", "--duration", "5",
    ]
    procs = [
        subprocess.Popen(
            cmd + ["--identity", f"pane-{i}"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        for i in range(2)
    ]
    outs = [p.communicate(timeout=60)[0] for p in procs]
    for out in outs:
        assert "self: " in out
        assert "2 peers" in out, out[-500:]
    # Both ended at the same fingerprint (last reported line).
    fps = {
        [ln for ln in out.splitlines() if "fingerprint" in ln][-1].split()[-1]
        for out in outs
    }
    assert len(fps) == 1


def test_cli_dispatches_phasegraph_subcommand(capsys):
    """`python -m kaboodle_tpu phasegraph` — every derived engine built at
    toy N and bit-diffed against dense, exit 0 on exactness."""
    from kaboodle_tpu.cli import main

    assert main(["phasegraph", "--n", "16", "--ensemble", "2", "--leap", "2"]) == 0
    out = capsys.readouterr().out
    assert "fused" in out and "warp" in out and '"ok": true' in out
