"""graftconc (ISSUE 16): KB5xx rule fixtures, pragmas, CLI lane, sanitizer.

Mirrors tests/test_analysis.py's structure for the concurrency lane: every
KB5xx rule gets positive and negative fixtures at in-scope paths, the
pragma grammar (`# conc: event-loop`, `# guarded_by:`, `# noqa: KB5nn`)
is exercised edge-on, the `--conc` CLI lane round-trips its own baseline,
and three seeded mutations of the REAL serve sources prove the gate turns
red for the bug classes it exists to catch. The runtime sanitizer half
(lock-order graph + loop watchdog) is pinned in isolation here; its
integration runs live under tests/test_serve_robustness.py and the chaos
harness.
"""

from __future__ import annotations

import asyncio
import json
import pathlib
import subprocess
import sys
import textwrap
import threading
import time

import pytest

from kaboodle_tpu.analysis import analyze_source
from kaboodle_tpu.analysis.cli import main
from kaboodle_tpu.analysis.core import REGISTRY, _load_rules

REPO = pathlib.Path(__file__).resolve().parent.parent

# In-scope by default: KB5xx rules only fire on the serve concurrency
# surface (CONC_SCOPE), so fixtures opt in via their path.
SERVE = "kaboodle_tpu/serve/fixture.py"


def conc_of(src: str, path: str = SERVE) -> list[str]:
    """KB5xx rule ids firing on a dedented fixture at an in-scope path
    (non-conc families are filtered out: shared registry, separate lane)."""
    return [
        f.rule
        for f in analyze_source(textwrap.dedent(src), path)
        if f.rule.startswith("KB5")
    ]


# ---------------------------------------------------------------------------
# KB501 — blocking calls on the event loop


def test_kb501_blocking_in_async_def():
    assert "KB501" in conc_of(
        """
        import time
        async def handler():
            time.sleep(1)
        """
    )
    # awaiting the async sleep is the fix
    assert "KB501" not in conc_of(
        """
        import asyncio
        async def handler():
            await asyncio.sleep(1)
        """
    )


def test_kb501_lock_acquire_and_open_are_blocking():
    assert "KB501" in conc_of(
        """
        async def handler(self):
            self._lock.acquire()
        """
    )
    assert "KB501" in conc_of(
        """
        async def handler(path):
            with open(path) as f:
                return f.read()
        """
    )


def test_kb501_interprocedural_reach():
    # the blocking call hides one module-local hop away from the seed
    assert "KB501" in conc_of(
        """
        import os
        def _flush_to_disk(fd):
            os.fsync(fd)
        async def handler(fd):
            _flush_to_disk(fd)
        """
    )


def test_kb501_event_loop_pragma_seeds_sync_def():
    # `# conc: event-loop` marks functions the loop calls cross-module
    # (ServeEngine.step from the asyncio server) — same closure as async def
    src = """
        import time
        def step(self):{pragma}
            time.sleep(0.1)
        """
    assert "KB501" in conc_of(src.format(pragma="  # conc: event-loop"))
    assert "KB501" not in conc_of(src.format(pragma=""))


def test_kb501_executor_offload_is_exempt():
    # the offload ARGUMENT runs off-loop by construction: time.sleep is
    # handed as a function object, never called on the loop
    assert "KB501" not in conc_of(
        """
        import asyncio, time
        async def handler():
            await asyncio.to_thread(time.sleep, 1)
        """
    )


def test_conc_scope_gating():
    src = """
        import time
        async def handler():
            time.sleep(1)
        """
    assert "KB501" in conc_of(src, path="kaboodle_tpu/serve/server.py")
    # outside CONC_SCOPE the whole family is silent
    assert conc_of(src, path="kaboodle_tpu/swim/kernels.py") == []
    assert conc_of(src, path="module.py") == []


# ---------------------------------------------------------------------------
# KB502 — guarded_by lock discipline


def test_kb502_unguarded_access_fires():
    src = """
        import threading
        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._cache = {}  # guarded_by: _lock
            def good(self):
                with self._lock:
                    self._cache[1] = 2
            def bad(self):
                return self._cache
        """
    assert conc_of(src).count("KB502") == 1  # bad() only; good() holds it


def test_kb502_init_is_exempt():
    # construction is single-threaded and the lock may not exist yet when
    # the guarded field is first assigned
    assert "KB502" not in conc_of(
        """
        import threading
        class C:
            def __init__(self):
                self._cache = {}  # guarded_by: _lock
                self._lock = threading.Lock()
            def get(self):
                with self._lock:
                    return self._cache
        """
    )


def test_kb502_guarded_def_on_property():
    src = """
        import threading
        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0  # guarded_by: _lock
            @property
            def n(self):  # guarded_by: _lock
                return self._n
            def peek(self):
                return self.n{suffix}
        """
    # the def pragma asserts the lock at entry: the property body passes,
    # but a lock-less access site is the violation
    bad = textwrap.dedent(src).format(suffix="")
    assert "KB502" in conc_of(bad)
    good = textwrap.dedent(
        """
        import threading
        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._n = 0  # guarded_by: _lock
            @property
            def n(self):  # guarded_by: _lock
                return self._n
            def peek(self):
                with self._lock:
                    return self.n
        """
    )
    assert "KB502" not in conc_of(good)


def test_kb502_helper_inferred_lock_held():
    # a private helper whose EVERY intra-class call site holds the lock is
    # lock-held inside too — no pragma needed
    assert "KB502" not in conc_of(
        """
        import threading
        class C:
            def __init__(self):
                self._lock = threading.Lock()
                self._cache = {}  # guarded_by: _lock
            def outer(self):
                with self._lock:
                    self._evict()
            def _evict(self):
                self._cache.clear()
        """
    )


# ---------------------------------------------------------------------------
# KB503 — device values crossing thread boundaries


def test_kb503_device_value_into_queue():
    assert "KB503" in conc_of(
        """
        import jax.numpy as jnp
        def producer(q):
            dev = jnp.zeros((4,))
            q.put(dev)
        """
    )


def test_kb503_materialization_cuts_taint():
    assert "KB503" not in conc_of(
        """
        import jax.numpy as jnp
        import numpy as np
        def producer(q):
            dev = jnp.zeros((4,))
            q.put(np.asarray(dev))
        """
    )
    assert "KB503" not in conc_of(
        """
        import jax.numpy as jnp
        def producer(q):
            dev = jnp.zeros(())
            q.put(dev.item())
        """
    )


def test_kb503_thread_args():
    assert "KB503" in conc_of(
        """
        import threading
        import jax.numpy as jnp
        def spawn():
            x = jnp.ones((2,))
            t = threading.Thread(target=print, args=(x,))
            t.start()
        """
    )


# ---------------------------------------------------------------------------
# KB504 — durable-write protocol


def test_kb504_replace_without_fsync():
    assert "KB504" in conc_of(
        """
        import os
        def publish(path, data):
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                f.write(data)
            os.replace(tmp, path)
        """
    )


def test_kb504_full_protocol_is_clean():
    assert "KB504" not in conc_of(
        """
        import os
        def publish(path, data):
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                f.write(data)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        """
    )


def test_kb504_serve_checkpoint_save_needs_atomic():
    src = """
        from kaboodle_tpu import checkpoint
        def spill(tree, path):
            checkpoint.save(tree, path{kw})
        """
    assert "KB504" in conc_of(src.format(kw=""))
    assert "KB504" not in conc_of(src.format(kw=", atomic=True"))
    # the atomic arm is serve/-only: checkpoint.py itself IMPLEMENTS save
    assert "KB504" not in conc_of(
        src.format(kw=""), path="kaboodle_tpu/checkpoint.py"
    )


# ---------------------------------------------------------------------------
# KB505 — static lock-order graph


def test_kb505_abba_cycle():
    src = """
        def one():
            with _a:
                with _b:
                    pass
        def two():
            with {x}:
                with {y}:
                    pass
        """
    assert "KB505" in conc_of(src.format(x="_b", y="_a"))
    assert "KB505" not in conc_of(src.format(x="_a", y="_b"))  # same order


def test_kb505_cycle_through_call_under_lock():
    # alpha holds _x and calls a helper that takes _y (edge x->y); beta
    # nests y->x directly — cycle only visible interprocedurally
    assert "KB505" in conc_of(
        """
        class C:
            def alpha(self):
                with self._x:
                    self._grab_y()
            def _grab_y(self):
                with self._y:
                    pass
            def beta(self):
                with self._y:
                    with self._x:
                        pass
        """
    )


# ---------------------------------------------------------------------------
# KB506 — unbounded queues


def test_kb506_unbounded_ctors():
    assert "KB506" in conc_of("import queue\nq = queue.Queue()\n")
    assert "KB506" in conc_of("import asyncio\nq = asyncio.Queue()\n")
    assert "KB506" in conc_of("import collections\nd = collections.deque()\n")
    # SimpleQueue cannot be bounded at all
    assert "KB506" in conc_of("import queue\nq = queue.SimpleQueue()\n")


def test_kb506_bounded_ctors_are_clean():
    assert "KB506" not in conc_of("import queue\nq = queue.Queue(maxsize=8)\n")
    assert "KB506" not in conc_of(
        "import collections\nd = collections.deque([], 64)\n"
    )
    assert "KB506" not in conc_of(
        "import collections\nd = collections.deque(maxlen=64)\n"
    )


# ---------------------------------------------------------------------------
# suppression + CLI lane


def test_noqa_kb5_scoping():
    assert "KB506" not in conc_of("import queue\nq = queue.Queue()  # noqa: KB506\n")
    # a foreign code doesn't suppress
    assert "KB506" in conc_of("import queue\nq = queue.Queue()  # noqa: KB501\n")
    # bare noqa is blanket
    assert "KB506" not in conc_of("import queue\nq = queue.Queue()  # noqa\n")


def _write_mixed_fixture(tmp_path) -> pathlib.Path:
    """A file with one default-lane finding (KB102 unused import) and one
    conc-lane finding (KB506) at an in-scope path."""
    d = tmp_path / "kaboodle_tpu" / "serve"
    d.mkdir(parents=True)
    p = d / "m.py"
    p.write_text("import os\nimport queue\nq = queue.Queue()\n")
    return p


def test_cli_lane_separation(tmp_path, monkeypatch, capsys):
    _write_mixed_fixture(tmp_path)
    monkeypatch.chdir(tmp_path)

    assert main(["--conc", "--no-baseline", "kaboodle_tpu"]) == 1
    cap = capsys.readouterr()
    assert "KB506" in cap.out and "KB102" not in cap.out
    assert "graftconc:" in cap.err  # the lane announces itself (summary)

    assert main(["--no-baseline", "kaboodle_tpu"]) == 1
    cap = capsys.readouterr()
    assert "KB102" in cap.out and "KB506" not in cap.out
    assert "graftlint:" in cap.err


def test_cli_conc_subcommand_alias(tmp_path, monkeypatch, capsys):
    _write_mixed_fixture(tmp_path)
    monkeypatch.chdir(tmp_path)
    assert main(["conc", "--no-baseline", "kaboodle_tpu"]) == 1
    assert "KB506" in capsys.readouterr().out


def test_conc_baseline_roundtrip(tmp_path, monkeypatch, capsys):
    _write_mixed_fixture(tmp_path)
    monkeypatch.chdir(tmp_path)

    assert main(["--conc", "kaboodle_tpu"]) == 1
    assert main(["--conc", "--write-baseline", "kaboodle_tpu"]) == 0
    assert (tmp_path / ".graftconc_baseline.json").exists()
    # the default-lane baseline is untouched: separate debt files
    assert not (tmp_path / ".graftlint_baseline.json").exists()
    assert main(["--conc", "kaboodle_tpu"]) == 0
    assert main(["--conc", "--no-baseline", "kaboodle_tpu"]) == 1
    capsys.readouterr()

    # shrink-only: stale entries fail the growth gate, not the plain run
    bl = tmp_path / ".graftconc_baseline.json"
    data = json.loads(bl.read_text())
    data["entries"].append(
        {"key": "gone.py::KB506::Queue", "reason": "stale"}
    )
    bl.write_text(json.dumps(data))
    assert main(["--conc", "kaboodle_tpu"]) == 0
    assert main(["--conc", "--no-baseline-growth", "kaboodle_tpu"]) == 1
    assert "stale baseline entry" in capsys.readouterr().out


def test_conc_baseline_requires_justification(tmp_path, monkeypatch):
    _write_mixed_fixture(tmp_path)
    monkeypatch.chdir(tmp_path)
    (tmp_path / ".graftconc_baseline.json").write_text(
        json.dumps({"entries": [{"key": "a.py::KB506::Queue"}]})
    )
    assert main(["--conc", "kaboodle_tpu"]) == 2


def test_cli_explain_and_list_cover_kb5():
    _load_rules()
    for rid in ("KB501", "KB502", "KB503", "KB504", "KB505", "KB506"):
        assert rid in REGISTRY
        assert REGISTRY[rid].explain.strip()
    assert main(["--explain", "KB505"]) == 0
    assert main(["--list-rules"]) == 0


def test_repo_is_conc_clean_under_baseline(monkeypatch):
    """The acceptance gate, conc lane: HEAD's serve scope is clean (every
    baselined stall individually justified, baseline not stale)."""
    monkeypatch.chdir(REPO)
    assert main(["--conc", "--no-baseline-growth"]) == 0


# ---------------------------------------------------------------------------
# seeded mutations of the REAL serve sources: the gate must turn red


def _copy_serve(tmp_path, *names) -> pathlib.Path:
    """Copy real serve modules into a bare tmp tree (no __init__.py, so
    the real installed package still wins the import path in subprocesses)."""
    dst = tmp_path / "kaboodle_tpu" / "serve"
    dst.mkdir(parents=True)
    for n in names:
        (dst / n).write_text(
            (REPO / "kaboodle_tpu" / "serve" / n).read_text()
        )
    return dst


MUTANT_ABBA = '''

class _MutantInversion:
    """Seeded KB505: the writer path and the poll path disagree on order."""

    def writer_side(self):
        with self._lock:
            with self._io_lock:
                pass

    def poll_side(self):
        with self._io_lock:
            with self._lock:
                pass
'''

MUTANT_DEVICE = '''

import jax.numpy as _mjnp


def _mutant_handoff(q):
    """Seeded KB503: device handle crosses into the writer thread."""
    dev = _mjnp.zeros((4,), _mjnp.int32)
    q.put(dev)
'''


def test_seeded_lock_order_inversion_turns_gate_red(tmp_path, monkeypatch, capsys):
    d = _copy_serve(tmp_path, "spill.py")
    monkeypatch.chdir(tmp_path)
    assert main(["--conc", "--no-baseline", "kaboodle_tpu"]) == 0  # pristine
    with open(d / "spill.py", "a") as f:
        f.write(MUTANT_ABBA)
    capsys.readouterr()
    assert main(["--conc", "--no-baseline", "kaboodle_tpu"]) == 1
    assert "KB505" in capsys.readouterr().out


def test_seeded_device_handoff_turns_gate_red(tmp_path, monkeypatch, capsys):
    d = _copy_serve(tmp_path, "spill.py")
    monkeypatch.chdir(tmp_path)
    with open(d / "spill.py", "a") as f:
        f.write(MUTANT_DEVICE)
    assert main(["--conc", "--no-baseline", "kaboodle_tpu"]) == 1
    assert "KB503" in capsys.readouterr().out


def test_seeded_fsync_on_round_loop_turns_gate_red(tmp_path, monkeypatch, capsys):
    d = _copy_serve(tmp_path, "engine.py")
    # engine.py carries justified baselined stalls: run against the repo's
    # committed baseline (absent modules' entries are stale, which the
    # plain mode tolerates) so ONLY the mutation is new.
    (tmp_path / ".graftconc_baseline.json").write_text(
        (REPO / ".graftconc_baseline.json").read_text()
    )
    monkeypatch.chdir(tmp_path)
    assert main(["--conc", "kaboodle_tpu"]) == 0  # pristine
    src = (d / "engine.py").read_text()
    marker = "def step(self) -> list[dict]:  # conc: event-loop\n"
    assert marker in src
    (d / "engine.py").write_text(
        src.replace(marker, marker + "        os.fsync(0)\n", 1)
    )
    capsys.readouterr()
    assert main(["--conc", "kaboodle_tpu"]) == 1
    out = capsys.readouterr().out
    assert "KB501" in out and "step" in out


def test_seeded_mutation_red_via_module_entrypoint(tmp_path):
    # the exact invocation CI runs: python -m kaboodle_tpu.analysis --conc
    d = _copy_serve(tmp_path, "spill.py")
    with open(d / "spill.py", "a") as f:
        f.write(MUTANT_ABBA)
    proc = subprocess.run(
        [sys.executable, "-m", "kaboodle_tpu.analysis", "--conc",
         "--no-baseline", "kaboodle_tpu"],
        cwd=tmp_path, capture_output=True, text=True,
        env={**__import__("os").environ, "PYTHONPATH": str(REPO)},
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "KB505" in proc.stdout


# ---------------------------------------------------------------------------
# runtime sanitizer


def test_sanitizer_abba_raises_deterministically():
    from kaboodle_tpu.analysis.conc import sanitizer

    with sanitizer.enabled():
        a = sanitizer.make_lock("A")
        b = sanitizer.make_lock("B")
        with a:
            with b:
                pass
        # ONE thread exercising the reverse order is enough: no deadlock
        # interleaving required
        with pytest.raises(sanitizer.LockOrderError, match="cycle"):
            with b:
                with a:
                    pass


def test_sanitizer_consistent_order_records_graph():
    from kaboodle_tpu.analysis.conc import sanitizer

    with sanitizer.enabled():
        a = sanitizer.make_lock("A")
        b = sanitizer.make_lock("B")
        for _ in range(3):
            with a:
                with b:
                    pass
        assert sanitizer.lock_graph() == {"A": ["B"]}
        rep = sanitizer.report()
        assert rep["locks"] == ["A", "B"]
        assert rep["order_edges"] == 1
        assert rep["loop_violations"] == []
        sanitizer.assert_clean()


def test_sanitizer_same_thread_reacquire_raises():
    from kaboodle_tpu.analysis.conc import sanitizer

    with sanitizer.enabled():
        a = sanitizer.make_lock("L")
        with a:
            with pytest.raises(sanitizer.LockOrderError, match="re-acquiring"):
                a.acquire()


def test_sanitizer_disabled_hands_out_plain_locks():
    from kaboodle_tpu.analysis.conc import sanitizer

    assert not sanitizer.is_enabled()
    lk = sanitizer.make_lock("X")
    assert isinstance(lk, type(threading.Lock()))
    assert not isinstance(lk, sanitizer.SanitizedLock)


def test_sanitizer_loop_watchdog_flags_blocking_callback():
    from kaboodle_tpu.analysis.conc import sanitizer

    async def _main():
        asyncio.get_running_loop().call_soon(time.sleep, 0.1)
        await asyncio.sleep(0.15)

    with sanitizer.enabled(loop_threshold_s=0.02):
        asyncio.run(_main())
        v = sanitizer.loop_violations()
        assert v and max(dt for _cb, dt in v) >= 0.02
        with pytest.raises(AssertionError, match="event loop blocked"):
            sanitizer.assert_clean()


def test_sanitizer_budgeted_callback_is_excused():
    from kaboodle_tpu.analysis.conc import sanitizer

    def _warmup_like():
        # the engine's warmup/recover pattern: a budgeted startup stall
        sanitizer.budget_current_callback()
        time.sleep(0.1)

    async def _main():
        asyncio.get_running_loop().call_soon(_warmup_like)
        await asyncio.sleep(0.15)

    with sanitizer.enabled(loop_threshold_s=0.02):
        asyncio.run(_main())
        assert sanitizer.loop_violations() == []
        sanitizer.assert_clean()
