"""costscope (kaboodle_tpu/costscope) — static cost plane, gate, why-dense.

The acceptance contract is seeded-regression-tested like graftlint's: a
doctored baseline (the seeded regression — the live program looks like it
doubled a buffer) must turn the CLI gate red, and the honest baseline must
pass. The collective walk is pinned two ways: synthetic HLO lines with
known byte counts, and real compiled registry twins — every sharded entry
must show nonzero bytes-on-ICI and every single-device entry exactly zero
(the committed `.costscope_baseline.json` is asserted to satisfy the same
invariant). The why-dense ledger is parity-gated: summed blocked ticks
equal the dense tick count exactly, and a ledger-carrying run ends
bit-identical to a bare one.
"""

from __future__ import annotations

import json
from pathlib import Path

import pytest

import jax

from kaboodle_tpu.costscope.baseline import (
    BASELINE_SCHEMA,
    GATED_FIELDS,
    gate_measurements,
    load_baseline,
    write_baseline,
)
from kaboodle_tpu.costscope.collectives import (
    _ici_bytes,
    parse_collectives,
)
from kaboodle_tpu.costscope.extract import (
    extract_entries,
    static_peak_bytes,
)

REPO = Path(__file__).resolve().parent.parent


# ---------------------------------------------------------------------------
# Collective walk: synthetic HLO with known byte counts.

SYNTH_HLO = """\
HloModule synth

ENTRY main {
  %p0 = u32[32]{0} parameter(0)
  %all-reduce.1 = u32[32]{0} all-reduce(u32[32]{0} %p0), channel_id=1, replica_groups=[1,8]<=[8], to_apply=%min
  %all-gather.2 = u32[64]{0} all-gather(u32[8]{0} %p0), channel_id=2, replica_groups={{0,1,2,3},{4,5,6,7}}, dimensions={0}
  %reduce-scatter.3 = f32[16]{0} reduce-scatter(f32[128]{0} %p0), channel_id=3, replica_groups=[1,8]<=[8], to_apply=%add
  %collective-permute.4 = s8[100]{0} collective-permute(s8[100]{0} %p0), channel_id=4, source_target_pairs={{0,1}}
  %all-reduce-start.5 = u32[4]{0} all-reduce-start(u32[4]{0} %p0), channel_id=5, replica_groups=[1,4]<=[4], to_apply=%min
  %all-reduce-done.6 = u32[4]{0} all-reduce-done(u32[4]{0} %all-reduce-start.5)
}
"""


def test_parse_collectives_synthetic():
    rows = parse_collectives(SYNTH_HLO, n_devices=8)
    by_kind = {}
    for r in rows:
        by_kind.setdefault(r["kind"], []).append(r)

    # all-reduce over u32[32] on a ring of 8: 2 * 128 * 7/8 = 224.
    ar = by_kind["all-reduce"][0]
    assert (ar["result_bytes"], ar["group_size"], ar["ici_bytes"]) == (128, 8, 224)
    # all-gather result u32[64], explicit groups of 4: 256 * 3/4 = 192.
    ag = by_kind["all-gather"][0]
    assert (ag["result_bytes"], ag["group_size"], ag["ici_bytes"]) == (256, 4, 192)
    # reduce-scatter shard f32[16]: 64 * (8-1) = 448.
    rs = by_kind["reduce-scatter"][0]
    assert (rs["result_bytes"], rs["group_size"], rs["ici_bytes"]) == (64, 8, 448)
    # collective-permute moves the whole s8[100] buffer.
    cp = by_kind["collective-permute"][0]
    assert (cp["result_bytes"], cp["ici_bytes"]) == (100, 100)
    # The async pair counts once: the -start carries the transfer, the
    # -done is shape-only and must be skipped.
    assert len(by_kind["all-reduce"]) == 2
    assert by_kind["all-reduce"][1]["group_size"] == 4


def test_ici_ring_formulas():
    assert _ici_bytes("all-reduce", 1024, 8) == int(2 * 1024 * 7 / 8)
    assert _ici_bytes("all-gather", 1024, 8) == int(1024 * 7 / 8)
    assert _ici_bytes("reduce-scatter", 1024, 8) == 1024 * 7
    assert _ici_bytes("all-to-all", 1024, 8) == int(1024 * 7 / 8)
    assert _ici_bytes("collective-permute", 1024, 8) == 1024
    # A degenerate one-participant group moves nothing.
    assert _ici_bytes("all-reduce", 1024, 1) == 0


def test_static_peak_bytes():
    class Mem:
        argument_size_in_bytes = 100
        output_size_in_bytes = 40
        temp_size_in_bytes = 60
        alias_size_in_bytes = 40

    assert static_peak_bytes(Mem()) == 160

    class NoAlias:
        argument_size_in_bytes = 10
        output_size_in_bytes = 10
        temp_size_in_bytes = 0

    assert static_peak_bytes(NoAlias()) == 20


# ---------------------------------------------------------------------------
# Extraction on real registry entries (trace scale; conftest pins the
# 8-device virtual mesh the sharded twins need).


def test_golden_crc32_extract():
    rec = extract_entries(["ops.crc32"])["ops.crc32"]
    assert rec["flops"] > 0
    assert rec["bytes_accessed"] > 0
    assert rec["peak_bytes"] > 0
    assert rec["sharded"] is False
    assert rec["ici_bytes"] == 0 and rec["collectives"] == {}
    # Static extraction is deterministic: a second compile of the same
    # entry yields the identical record.
    assert extract_entries(["ops.crc32"])["ops.crc32"] == rec


def test_sharded_entry_pays_ici_single_device_does_not():
    recs = extract_entries(["phasegraph.tick.sharded", "phasegraph.tick.faulty"])
    sh = recs["phasegraph.tick.sharded"]
    dn = recs["phasegraph.tick.faulty"]
    assert sh["sharded"] and sh["ici_bytes"] > 0
    # The sharded tick's cross-chip traffic is the spec-derived halo
    # exchange + convergence check: all-gather and all-reduce must both
    # appear in the walk.
    assert "all-gather" in sh["collectives"]
    assert "all-reduce" in sh["collectives"]
    assert not dn["sharded"]
    assert dn["ici_bytes"] == 0 and dn["collectives"] == {}


@pytest.mark.slow
def test_full_registry_extract_matches_committed_invariant():
    measured = extract_entries(None)
    from kaboodle_tpu.analysis.ir.registry import ENTRY_POINTS

    assert set(measured) == {e.name for e in ENTRY_POINTS}
    for name, rec in measured.items():
        if rec["sharded"]:
            assert rec["ici_bytes"] > 0, f"{name}: sharded but zero ICI bytes"
        else:
            assert rec["ici_bytes"] == 0, f"{name}: single-device but ICI bytes"


def test_committed_baseline_invariant():
    """The committed baseline satisfies the same sharded/ICI invariant."""
    data = load_baseline(REPO / ".costscope_baseline.json")
    assert data is not None and data["schema"] == BASELINE_SCHEMA
    entries = data["entries"]
    assert len(entries) >= 28
    for name, rec in entries.items():
        if rec["sharded"]:
            assert rec["ici_bytes"] > 0, name
        else:
            assert rec["ici_bytes"] == 0, name


# ---------------------------------------------------------------------------
# Gate semantics on synthetic records.


def _rec(**over):
    base = {
        "flops": 1000,
        "bytes_accessed": 100_000,
        "peak_bytes": 200_000,
        "ici_bytes": 50_000,
        "sharded": True,
    }
    base.update(over)
    return base


def test_gate_unbaselined_entry_fails():
    fails = gate_measurements({"e": _rec()}, None)
    assert len(fails) == 1 and "no baseline" in fails[0]
    fails = gate_measurements(
        {"e": _rec()}, {"schema": BASELINE_SCHEMA, "entries": {}}
    )
    assert len(fails) == 1 and "not in baseline" in fails[0]


def test_gate_within_tolerance_passes():
    baseline = {"schema": BASELINE_SCHEMA, "entries": {"e": _rec()}}
    wobble = _rec(bytes_accessed=102_000, peak_bytes=198_000, ici_bytes=51_000)
    assert gate_measurements({"e": wobble}, baseline) == []
    assert gate_measurements({"e": wobble}, baseline, no_growth=True) == []


def test_gate_growth_fails():
    baseline = {"schema": BASELINE_SCHEMA, "entries": {"e": _rec()}}
    fails = gate_measurements({"e": _rec(bytes_accessed=200_000)}, baseline)
    assert len(fails) == 1 and "grew" in fails[0]
    # Every gated field is watched independently.
    grown = _rec(
        bytes_accessed=200_000, peak_bytes=400_000, ici_bytes=100_000
    )
    assert len(gate_measurements({"e": grown}, baseline)) == len(GATED_FIELDS)


def test_gate_shrink_only_under_no_growth():
    baseline = {"schema": BASELINE_SCHEMA, "entries": {"e": _rec()}}
    shrunk = {"e": _rec(bytes_accessed=50_000)}
    assert gate_measurements(shrunk, baseline) == []
    fails = gate_measurements(shrunk, baseline, no_growth=True)
    assert len(fails) == 1 and "shrank" in fails[0]


def test_gate_stale_entry_under_no_growth():
    baseline = {
        "schema": BASELINE_SCHEMA,
        "entries": {"e": _rec(), "gone": _rec()},
    }
    live = {"e": _rec()}
    assert gate_measurements(live, baseline) == []
    fails = gate_measurements(live, baseline, no_growth=True)
    assert len(fails) == 1 and "stale" in fails[0]
    # --entry subsets are deliberately partial: no stale check.
    assert gate_measurements(live, baseline, no_growth=True, subset=True) == []


def test_baseline_roundtrip_and_bad_schema(tmp_path):
    path = tmp_path / "b.json"
    assert load_baseline(path) is None
    write_baseline(path, {"e": _rec()})
    data = load_baseline(path)
    assert data["entries"]["e"]["bytes_accessed"] == 100_000
    path.write_text(json.dumps({"schema": "wrong/1", "entries": {}}))
    with pytest.raises(ValueError):
        load_baseline(path)


# ---------------------------------------------------------------------------
# Seeded regression through the CLI (the acceptance gate, in-process).


def test_seeded_regression_turns_cli_gate_red(tmp_path, capsys):
    from kaboodle_tpu.costscope.cli import main

    # ops.fused_fp is big enough (~300 KB accessed) that a halved
    # baseline clears the gate's absolute jitter floor.
    honest = extract_entries(["ops.fused_fp"])
    path = tmp_path / "base.json"

    # Honest baseline: the subset gate is green, shrink-ratchet included.
    write_baseline(path, honest)
    rc = main(
        ["--entry", "ops.fused_fp", "--baseline", str(path),
         "--no-baseline-growth"]
    )
    assert rc == 0

    # Seeded regression: the baseline says the program used to touch half
    # the bytes (equivalently, the live program doubled a buffer dtype).
    doctored = {
        "ops.fused_fp": {
            **honest["ops.fused_fp"],
            "bytes_accessed": honest["ops.fused_fp"]["bytes_accessed"] // 2,
            "peak_bytes": honest["ops.fused_fp"]["peak_bytes"] // 2,
        }
    }
    write_baseline(path, doctored)
    rc = main(["--entry", "ops.fused_fp", "--baseline", str(path)])
    assert rc == 1
    assert "grew" in capsys.readouterr().out

    # Unknown entry / corrupt baseline are usage errors, not regressions.
    assert main(["--entry", "no.such.entry", "--baseline", str(path)]) == 2
    path.write_text("{\"schema\": \"wrong/1\"}")
    assert main(["--entry", "ops.fused_fp", "--baseline", str(path)]) == 2


def test_cli_routes_through_package_main(tmp_path):
    """`python -m kaboodle_tpu costscope ...` reaches the same gate."""
    from kaboodle_tpu.cli import main as pkg_main

    honest = extract_entries(["ops.fused_fp"])
    path = tmp_path / "base.json"
    doctored = {
        "ops.fused_fp": {
            **honest["ops.fused_fp"],
            "bytes_accessed": honest["ops.fused_fp"]["bytes_accessed"] // 2,
        }
    }
    write_baseline(path, doctored)
    rc = pkg_main(
        ["costscope", "--entry", "ops.fused_fp", "--baseline", str(path)]
    )
    assert rc == 1


def test_cli_write_baseline_merges_subset(tmp_path):
    from kaboodle_tpu.costscope.cli import main

    path = tmp_path / "base.json"
    write_baseline(path, {"other.entry": _rec()})
    rc = main(
        ["--entry", "ops.crc32", "--baseline", str(path), "--write-baseline"]
    )
    assert rc == 0
    data = load_baseline(path)
    assert set(data["entries"]) == {"other.entry", "ops.crc32"}


# ---------------------------------------------------------------------------
# Roofline: runs from the committed baseline + banked walls, no hardware.


def test_roofline_from_committed_baseline():
    from kaboodle_tpu.costscope.roofline import (
        load_bench_walls,
        render_report,
        roofline_from_baseline,
    )

    baseline = load_baseline(REPO / ".costscope_baseline.json")
    report = roofline_from_baseline(baseline, root=str(REPO))
    rows = {r["entry"]: r for r in report["entries"]}
    assert set(rows) == set(baseline["entries"])
    for name, row in rows.items():
        assert row["hbm_floor_us"] > 0, name
        if baseline["entries"][name]["sharded"]:
            floors = row["ici_floor_us"]
            # The slower bookend (50 GB/s) bounds the floor from above.
            assert floors["50GBps"] > floors["100GBps"] > 0, name
    text = render_report(report)
    assert "phasegraph.tick.sharded" in text
    # Banked walls exist in-repo, so the wall-vs-floor placements render.
    assert load_bench_walls(str(REPO))
    assert report["placements"]


# ---------------------------------------------------------------------------
# ICI microbench: correctness-asserted dryrun on the virtual mesh.


def test_icibench_dryrun_sweep():
    from kaboodle_tpu.costscope.icibench import run_sweep

    out = run_sweep(sizes=(256,), repeats=1, check=True)
    assert out["schema"] == "kaboodle-costscope-ici/1"
    assert out["n_devices"] == 8
    kinds = {r["collective"] for r in out["results"]}
    assert kinds == {"agreement_all_reduce", "union_reduce_scatter"}
    for r in out["results"]:
        assert r["payload_bytes"] > 0
        assert r["ici_bytes_ring"] > 0
        assert r["wall_s_best"] > 0
        assert r["gbps_ring"] > 0


# ---------------------------------------------------------------------------
# Why-dense attribution: parity + obs-neutrality.


def _churn_setup():
    import jax.numpy as jnp  # noqa: F401

    from kaboodle_tpu.config import SwimConfig
    from kaboodle_tpu.sim.scenario import Scenario
    from kaboodle_tpu.sim.state import init_state

    n, ticks = 32, 96
    cfg = SwimConfig(ping_timeout_ticks=16)
    # track_latency=False: the latency trace is float-accumulated and
    # run-to-run jittery in-process (pre-existing; unrelated to the
    # ledger), so the bit-identity arms run without it.
    st = init_state(n, seed=0, ring_contacts=n - 1, announced=True,
                    track_latency=False)
    sc = Scenario(n, ticks, seed=0)
    for i, p in enumerate([5, 11, 17, 23]):
        sc.kill_at(8 + 2 * i, [p])
    return st, sc.build(), cfg


def test_why_dense_histogram_parity():
    from kaboodle_tpu.warp.runner import WarpLedger, simulate_warped

    st, inputs, cfg = _churn_setup()
    ledger = WarpLedger()
    _, dense_ticks, _ = simulate_warped(
        st, inputs, cfg, faulty=True, ledger=ledger
    )
    hist = ledger.blocked_histogram()
    assert hist, "churn drain must leave dense spans to attribute"
    # Exact parity: every dense tick is attributed to exactly one term.
    assert sum(v["ticks"] for v in hist.values()) == int(dense_ticks.size)
    assert sum(v["spans"] for v in hist.values()) == len(ledger.blocked)
    # The attribution is meaningful: blocked terms name signature terms
    # or the two pseudo-terms, never empty strings.
    assert all(t for t in hist)


def test_why_dense_ledger_is_observation_only():
    from kaboodle_tpu.profiling import leaf_equal
    from kaboodle_tpu.warp.runner import WarpLedger, simulate_warped

    st, inputs, cfg = _churn_setup()
    out_bare, ticks_bare, _ = simulate_warped(st, inputs, cfg, faulty=True)
    out_led, ticks_led, _ = simulate_warped(
        st, inputs, cfg, faulty=True, ledger=WarpLedger()
    )
    assert int(ticks_bare.size) == int(ticks_led.size)
    assert all(
        leaf_equal(a, b)
        for a, b in zip(jax.tree.leaves(out_bare), jax.tree.leaves(out_led))
    )


# ---------------------------------------------------------------------------
# Telemetry schema: warp_blocked + costscope records round-trip.


def test_manifest_roundtrip_new_kinds(tmp_path):
    from kaboodle_tpu.telemetry.manifest import (
        ManifestWriter,
        read_manifest,
        validate_record,
    )

    path = str(tmp_path / "m.jsonl")
    with ManifestWriter(path) as w:
        w.write("warp_blocked", term="fp_disagree+missing_alive", ticks=12,
                spans=3, engine="sim", members=1)
        w.write("costscope", entry="ops.crc32", flops=491, bytes_accessed=2714,
                peak_bytes=1042, ici_bytes=0, sharded=False)
    kinds = [r["kind"] for r in read_manifest(path, validate=True)]
    assert kinds == ["warp_blocked", "costscope"]

    with pytest.raises(ValueError):
        validate_record(
            {"schema": "kaboodle-telemetry/1", "kind": "warp_blocked",
             "term": "", "ticks": 1, "spans": 1}
        )
    with pytest.raises(ValueError):
        validate_record(
            {"schema": "kaboodle-telemetry/1", "kind": "warp_blocked",
             "term": "x", "ticks": "1", "spans": 1}
        )
    with pytest.raises(ValueError):
        validate_record(
            {"schema": "kaboodle-telemetry/1", "kind": "costscope"}
        )
