"""CRC-32 kernels vs zlib.crc32 (the same standard CRC crc32fast computes)."""

import zlib

import numpy as np
import jax.numpy as jnp

from kaboodle_tpu.ops import crc32, membership_crc32
from kaboodle_tpu.ops.crc32 import crc32_update_bytes, record_bytes


def test_crc32_matches_zlib_rows():
    rng = np.random.default_rng(0)
    data = rng.integers(0, 256, size=(16, 37), dtype=np.uint8)
    got = np.asarray(crc32(jnp.asarray(data)))
    want = np.array([zlib.crc32(row.tobytes()) for row in data], dtype=np.uint32)
    np.testing.assert_array_equal(got, want)


def test_crc32_empty_and_known_vector():
    # crc32(b"") == 0; crc32(b"123456789") == 0xCBF43926 (standard check value)
    data = np.frombuffer(b"123456789", dtype=np.uint8)[None, :]
    got = np.asarray(crc32(jnp.asarray(data)))
    assert got[0] == 0xCBF43926
    empty = jnp.zeros((3, 0), dtype=jnp.uint8)
    np.testing.assert_array_equal(np.asarray(crc32(empty)), np.zeros(3, dtype=np.uint32))


def test_masked_crc32_skips_bytes():
    rng = np.random.default_rng(1)
    data = rng.integers(0, 256, size=(8, 24), dtype=np.uint8)
    mask = rng.random((8, 24)) < 0.5
    init = jnp.full((8,), 0xFFFFFFFF, dtype=jnp.uint32)
    got = np.asarray(crc32_update_bytes(init, jnp.asarray(data), jnp.asarray(mask))) ^ np.uint32(
        0xFFFFFFFF
    )
    want = np.array(
        [zlib.crc32(row[m].tobytes()) for row, m in zip(data, mask)], dtype=np.uint32
    )
    np.testing.assert_array_equal(got, want)


def test_membership_crc32_matches_python_oracle():
    rng = np.random.default_rng(2)
    n = 13
    member = rng.random((n, n)) < 0.6
    identities = rng.integers(0, 2**32, size=n, dtype=np.uint32)
    got = np.asarray(membership_crc32(jnp.asarray(member), jnp.asarray(identities)))

    recs = np.asarray(
        record_bytes(jnp.arange(n, dtype=jnp.uint32), jnp.asarray(identities))
    )
    want = []
    for i in range(n):
        buf = b"".join(recs[j].tobytes() for j in range(n) if member[i, j])
        want.append(zlib.crc32(buf))
    np.testing.assert_array_equal(got, np.array(want, dtype=np.uint32))


def test_socket_addr_sort_order():
    """crc_fingerprint sorts like Rust SocketAddr Ord: numeric IPs, v4 < v6,
    then port — not lexicographic strings (kaboodle.rs:72-73)."""
    from kaboodle_tpu.oracle.fingerprint import socket_addr_sort_key

    addrs = ["10.0.0.2:80", "9.0.0.1:80", "[fe80::1]:9", "9.0.0.1:7", "[::1]:80"]
    ordered = sorted(addrs, key=socket_addr_sort_key)
    assert ordered == ["9.0.0.1:7", "9.0.0.1:80", "10.0.0.2:80", "[::1]:80", "[fe80::1]:9"]
