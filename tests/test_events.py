"""Event derivation semantics (events.rs:18-125) over tensor diffs."""

import numpy as np

from kaboodle_tpu.config import SwimConfig
from kaboodle_tpu.events import (
    EventTap,
    FingerprintChanged,
    PeerDeparted,
    PeerDiscovered,
    membership_diff,
)
from kaboodle_tpu.oracle.fingerprint import mix_fingerprint
from kaboodle_tpu.sim import init_state, simulate, idle_inputs
import pytest


IDS = np.arange(1, 9, dtype=np.uint32)


def _member(*peers, n=8):
    m = np.zeros(n, dtype=bool)
    m[list(peers)] = True
    return m


def test_initial_feed_announces_everything():
    tap = EventTap()
    ev = tap.feed(_member(0, 2), IDS)
    assert PeerDiscovered(0, 1) in ev and PeerDiscovered(2, 3) in ev
    fps = [e for e in ev if isinstance(e, FingerprintChanged)]
    assert fps == [FingerprintChanged(mix_fingerprint({0: 1, 2: 3}))]


def test_no_change_no_events_and_batching():
    tap = EventTap()
    tap.feed(_member(0, 1), IDS)
    # A remove+re-add inside one batch nets to no change (events.rs:88-99).
    assert tap.feed(_member(0, 1), IDS) == []


def test_departure_and_fingerprint_dedup():
    tap = EventTap()
    tap.feed(_member(0, 1, 2), IDS)
    ev = tap.feed(_member(0, 1), IDS)
    assert PeerDeparted(2) in ev
    assert FingerprintChanged(mix_fingerprint({0: 1, 1: 2})) in ev
    # Going back to the old membership re-announces (differs from last).
    ev2 = tap.feed(_member(0, 1, 2), IDS)
    assert FingerprintChanged(mix_fingerprint({0: 1, 1: 2, 2: 3})) in ev2


def test_identity_change_reannounces():
    tap = EventTap()
    tap.feed(_member(0, 1), IDS)
    ids2 = IDS.copy()
    ids2[1] = 99
    ev = tap.feed(_member(0, 1), ids2)
    assert PeerDiscovered(1, 99) in ev
    assert any(isinstance(e, FingerprintChanged) for e in ev)
    # An identity change of a non-member is ignored (events.rs:80-87).
    ids3 = ids2.copy()
    ids3[5] = 7
    assert tap.feed(_member(0, 1), ids3) == []


def test_empty_map_fingerprint_suppressed():
    """Quirk Q10: fp of an empty map is 0 and never announced."""
    tap = EventTap()
    tap.feed(_member(0), IDS)
    ev = tap.feed(_member(), IDS)
    assert PeerDeparted(0) in ev
    assert not any(isinstance(e, FingerprintChanged) for e in ev)


def test_membership_diff_matches_tap():
    prev, cur = _member(0, 1, 2), _member(0, 2, 4)
    added, removed = membership_diff(prev[None, :], cur[None, :])
    assert np.flatnonzero(added[0]).tolist() == [4]
    assert np.flatnonzero(removed[0]).tolist() == [1]


@pytest.mark.slow
def test_tap_over_simulated_run():
    """Feeding per-tick rows of a real run: observer 0 discovers the whole
    mesh; the last announced fingerprint matches the final converged state."""
    n = 16
    tap = EventTap()
    discovered = set()
    st_t = init_state(n, seed=4)
    ids = np.asarray(st_t.identity)
    seen_fp = None
    for t in range(6):
        st_t, _ = simulate(st_t, idle_inputs(n, ticks=1), SwimConfig(), faulty=False)
        for e in tap.feed(np.asarray(st_t.state[0] > 0), ids):
            if isinstance(e, PeerDiscovered):
                discovered.add(e.peer)
            elif isinstance(e, FingerprintChanged):
                seen_fp = e.fingerprint
    assert discovered == set(range(n))
    want = mix_fingerprint({j: int(ids[j]) for j in range(n)})
    assert seen_fp == want
