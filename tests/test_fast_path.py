"""Fast-path/full-path equivalence for the fault-free tick.

``SwimConfig.fast_path`` compiles the fault-free tick as a two-branch
``lax.cond`` (kernel.py dispatch): a lean path for ticks with no Join
broadcast and no suspicion activity, the full path for everything else.
The contract is BIT-EXACT equality with the single-path build
(``fast_path=False``) on every trajectory — the dispatch pred must route
every tick with surviving full-path-only traffic to the full path, and the
lean path must reproduce the full path's semantics exactly on the rest.

These tests fuzz that contract over boot modes, dtypes, optional state
planes (latency, id_view), deterministic/random draws, and manual pings —
multi-tick trajectories so mid-boot unconverged states, rebroadcast ticks,
and converged steady ticks all appear.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kaboodle_tpu.config import SwimConfig
from kaboodle_tpu.sim.runner import simulate
from kaboodle_tpu.sim.state import idle_inputs, init_state


def _assert_leaves_equal(tree_a, tree_b):
    for a, b in zip(jax.tree.leaves(tree_a), jax.tree.leaves(tree_b)):
        av, bv = np.asarray(a), np.asarray(b)
        if av.dtype == np.float32:  # latency plane carries NaNs (no sample)
            assert ((av == bv) | (np.isnan(av) & np.isnan(bv))).all()
        else:
            assert (av == bv).all(), (av != bv).sum()


def _trajectories_equal(st, inp, cfg):
    fast = jax.jit(lambda s, i: simulate(s, i, cfg, faulty=False))
    slow_cfg = dataclasses.replace(cfg, fast_path=False)
    slow = jax.jit(lambda s, i: simulate(s, i, slow_cfg, faulty=False))
    out_f, m_f = fast(st, inp)
    out_s, m_s = slow(st, inp)
    _assert_leaves_equal((out_f, m_f), (out_s, m_s))
    return m_f


@pytest.mark.parametrize("det", [True, False])
@pytest.mark.parametrize("ring", [0, 2, 63])
def test_fast_path_matches_full_over_boot(det, ring):
    """Broadcast boot (ring=0: join avalanche tick), epidemic-ish partial
    mesh (ring=2), and converged-init (ring=63) trajectories, 24 ticks:
    covers join ticks (full path), unconverged anti-entropy ticks, and
    converged steady ticks (lean path)."""
    n = 64
    cfg = SwimConfig(deterministic=det)
    st = init_state(n, seed=3, ring_contacts=ring)
    inp = idle_inputs(n, ticks=24)
    _trajectories_equal(st, inp, cfg)


@pytest.mark.parametrize("timer_dtype", [jnp.int32, jnp.int16])
@pytest.mark.parametrize("lean", [True, False])
def test_fast_path_matches_full_state_planes(timer_dtype, lean):
    """Optional planes: latency EWMA + per-row identity views on, and the
    lean (instant-identity, no-latency) mode — both must match exactly,
    including the two-wave latency sampling order inside the composed
    write chain."""
    n = 48
    cfg = SwimConfig()
    st = init_state(n, seed=9, ring_contacts=n - 1, track_latency=not lean,
                    instant_identity=lean, timer_dtype=timer_dtype)
    inp = idle_inputs(n, ticks=16)
    _trajectories_equal(st, inp, cfg)


def test_fast_path_matches_full_manual_pings():
    """Manual pings (ping_addrs) flow through the lean path's mark1/mark2
    and the phase-1 anti-entropy candidates; out-of-range and self targets
    are dropped (D8)."""
    n = 32
    cfg = SwimConfig()
    st = init_state(n, seed=5, ring_contacts=4)
    rng = np.random.default_rng(0)
    inp = idle_inputs(n, ticks=12)
    manual = rng.integers(-1, n + 2, size=(12, n)).astype(np.int32)
    inp = dataclasses.replace(inp, manual_target=jnp.asarray(manual))
    _trajectories_equal(st, inp, cfg)


def test_fast_path_routes_suspicion_to_full_path():
    """A trajectory that develops suspicion activity (engineered by aging a
    WaitingForPing cell past the timeout) still matches the single-path
    build — i.e. the dispatch pred catches escalation/removal ticks."""
    n = 32
    cfg = SwimConfig()
    st = init_state(n, seed=7, ring_contacts=n - 1)
    # Age peer 0's view of peer 1 into a timed-out WaitingForPing cell.
    state = np.asarray(st.state).copy()
    timer = np.asarray(st.timer).copy()
    state[0, 1] = 2  # WAITING_FOR_PING
    timer[0, 1] = -10
    st = dataclasses.replace(
        st, state=jnp.asarray(state), timer=jnp.asarray(timer)
    )
    inp = idle_inputs(n, ticks=10)
    m = _trajectories_equal(st, inp, cfg)
    del m


def test_fast_path_default_on():
    assert SwimConfig().fast_path


def test_fast_path_matches_full_sharded():
    """The two-branch tick under GSPMD (the dispatch pred is a global
    reduction the partitioner must all-reduce) produces the same sharded
    trajectory as the single-path build."""
    from kaboodle_tpu.parallel import make_mesh, shard_inputs, shard_state, simulate_sharded

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    n = 64
    mesh = make_mesh(8)
    cfg = SwimConfig()
    slow_cfg = dataclasses.replace(cfg, fast_path=False)
    inp = idle_inputs(n, ticks=12)

    st = shard_state(init_state(n, seed=4, ring_contacts=2), mesh)
    sharded_inp = shard_inputs(inp, mesh, stacked=True)
    out_f, m_f = simulate_sharded(st, sharded_inp, cfg, mesh, faulty=False)
    out_s, m_s = simulate_sharded(st, sharded_inp, slow_cfg, mesh, faulty=False)
    _assert_leaves_equal((out_f, m_f), (out_s, m_s))
