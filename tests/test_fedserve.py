"""fedserve (ISSUE 17): sharded lane pools + federated serving — unit lanes.

The end-to-end federation proof (two engines + router, kill-one-engine
failover, zero lost terminals, zero steady compiles) lives in
``make fedserve-dryrun`` (kaboodle_tpu/serve/federation/fedload.py); this
file pins the pieces in isolation:

- the consistent-hash ring's stability / determinism / affinity contracts,
- the router's N-class-aware load-scored placement (no sockets),
- the sharded lane pool's bit-exact parity with the single-device pool and
  its zero-recompile contract through a full spill/restore engine cycle,
- the engine-id namespace guards (checkpoint owner stamps, journal owner
  claims, torn-WAL tolerance) and the explicit ``adopt`` handover, and
- the client's reconnect-with-backoff resuming a ``wait`` across a server
  kill+restart.
"""

from __future__ import annotations

import asyncio
import os

import jax
import numpy as np
import pytest

from kaboodle_tpu.config import SwimConfig
from kaboodle_tpu.errors import CheckpointError
from kaboodle_tpu.serve.engine import ServeEngine, ServeRequest
from kaboodle_tpu.serve.federation.ring import HashRing, stable_hash
from kaboodle_tpu.serve.federation.router import EngineMember, FedRouter
from kaboodle_tpu.serve.pool import LanePool

CFG = SwimConfig(deterministic=True)
N = 16


def _leaves_equal(a, b) -> bool:
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    if len(la) != len(lb):
        return False
    for x, y in zip(la, lb):
        x, y = np.asarray(x), np.asarray(y)
        eq = np.issubdtype(x.dtype, np.floating)
        if not np.array_equal(x, y, equal_nan=eq):
            return False
    return True


# -- consistent-hash ring ---------------------------------------------------


def test_stable_hash_is_process_independent():
    # Pinned values: blake2b is deterministic across processes and
    # restarts, unlike the salted builtin hash. A changed pin means the
    # whole fleet's placement moved — never do that silently.
    assert stable_hash("default:16:0") == stable_hash("default:16:0")
    assert stable_hash("a") != stable_hash("b")
    assert stable_hash("a") == 0x40F89E395B66422F


def test_ring_determinism_across_instances():
    a = HashRing(["e0", "e1", "e2"])
    b = HashRing(["e2", "e0", "e1"])  # insertion order must not matter
    keys = [f"t{i % 3}:16:{i}" for i in range(500)]
    assert [a.place(k) for k in keys] == [b.place(k) for k in keys]


def test_ring_join_leave_stability():
    members = [f"e{i}" for i in range(5)]
    ring = HashRing(members)
    keys = [f"default:16:{i}" for i in range(2000)]
    before = {k: ring.place(k) for k in keys}

    ring.remove("e2")
    after = {k: ring.place(k) for k in keys}
    moved = [k for k in keys if before[k] != after[k]]
    # Only the dead member's keys move, and it owned ~1/5 of the space.
    assert all(before[k] == "e2" for k in moved)
    assert 0.05 < len(moved) / len(keys) < 0.40

    ring.add("e2")  # a re-join restores the original placement exactly
    assert {k: ring.place(k) for k in keys} == before


def test_ring_preference_walk():
    ring = HashRing(["e0", "e1", "e2"])
    for i in range(50):
        prefs = ring.preference(f"k{i}")
        assert prefs[0] == ring.place(f"k{i}")
        assert sorted(prefs) == ["e0", "e1", "e2"]  # distinct, all members
    assert ring.preference("k0", limit=2) == ring.preference("k0")[:2]
    assert ring.size == 3 * 64


def _placement_router() -> FedRouter:
    """A router with hand-attached members — placement is pure table
    logic, no sockets needed."""
    r = FedRouter([EngineMember("e0", "h", 1), EngineMember("e1", "h", 2),
                   EngineMember("e2", "h", 3)])
    for mid in ("e0", "e1", "e2"):
        r.ring.add(mid)
        r.alive.add(mid)
        r._inflight[mid] = 0
    r._classes = {"e0": {16, 32}, "e1": {16}, "e2": {16}}
    return r


def test_placement_nclass_affinity():
    r = _placement_router()
    # Only e0 serves N-class 32: every 32-key lands there regardless of
    # where the ring would put it.
    for i in range(40):
        assert r._place(f"default:32:{i}", 32) == "e0"
    # At equal load the ring's choice stands (deterministic affinity).
    for i in range(40):
        key = f"default:16:{i}"
        want = [m for m in r.ring.preference(key) if 16 in r._classes[m]][0]
        assert r._place(key, 16) == want


def test_placement_load_slack_overflow():
    r = _placement_router()
    key = next(
        f"default:16:{i}" for i in range(100)
        if r._place(f"default:16:{i}", 16) == "e1"
    )
    r._inflight["e1"] = r.load_slack - 1
    assert r._place(key, 16) == "e1"  # within slack: affinity holds
    r._inflight["e1"] = r.load_slack
    assert r._place(key, 16) != "e1"  # overflow: least-loaded candidate


def test_no_engine_serves_class_raises():
    r = _placement_router()
    with pytest.raises(ValueError, match="N-class 64"):
        r._place("default:64:0", 64)


# -- sharded lane pool ------------------------------------------------------


def _mesh_2d():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    from kaboodle_tpu.fleet.sharding import make_fleet_mesh

    return make_fleet_mesh(4, 2)


def test_sharded_pool_parity_bit_exact():
    """Same admission schedule, same steps: every member leaf and every
    host run vector identical between the single-device pool and the
    sharded pool on a 2-D (ensemble x peers) mesh."""
    from kaboodle_tpu.serve.shardpool import ShardedLanePool

    device_mesh = _mesh_2d()
    kw = dict(n=N, lanes=4, cfg=CFG, chunk=4)
    ref = LanePool(**kw)
    sh = ShardedLanePool(device_mesh=device_mesh, **kw)
    for lane, (seed, conv) in enumerate([(0, True), (1, False), (2, True)]):
        for p in (ref, sh):
            p.admit(lane, seed=seed, drop_rate=0.0, until_conv=conv,
                    budget=16, scenario="boot" if conv else "steady")
    for _ in range(6):
        ref.step()
        sh.step()
    for lane in range(3):
        assert _leaves_equal(ref.member(lane), sh.member(lane)), lane
    for name in ("ticks_run", "conv_tick", "messages"):
        assert np.array_equal(getattr(ref, name), getattr(sh, name)), name


def test_sharded_pool_zero_recompile_through_spill_cycle(tmp_path):
    """The KB405 pin on the sharded pool: a full engine lifecycle —
    submit / drain / park / spill / restore / resume — dispatches ZERO
    fresh compiles after warmup, including the host-fetch and
    mesh-split assembly programs (the two hazards warmup pre-warms)."""
    from kaboodle_tpu.analysis.ir.surface import compile_counter
    from kaboodle_tpu.serve.shardpool import ShardedLanePool

    device_mesh = _mesh_2d()
    pool = ShardedLanePool(n=N, lanes=4, cfg=CFG, chunk=4,
                           device_mesh=device_mesh)
    eng = ServeEngine(
        [pool], max_leap=64, spill_after=1, spill_dir=str(tmp_path),
        journal_dir=str(tmp_path / "wal"), engine_id="e0",
    )
    eng.warmup()
    with compile_counter() as fresh:
        rids = [
            eng.submit(ServeRequest(
                n=N, seed=i, mode="converge" if i % 2 == 0 else "ticks",
                ticks=16, keep=True))
            for i in range(3)
        ]
        eng.drain()
        for _ in range(40):
            eng.step()
            if all(eng.status(r)["state"] == "spilled" for r in rids):
                break
        eng.settle_spills()
        for r in rids:
            assert eng.status(r)["state"] == "spilled", eng.status(r)
            assert eng.restore(r)
            eng.resume(r, mode="ticks", ticks=4)
        eng.drain()
        eng.settle_spills()
    assert fresh.count == 0, f"{fresh.count} fresh compiles after warmup"
    assert pool.stats()["device_mesh"] == {"ensemble": 4, "peers": 2}
    eng.close()


# -- engine-id namespaces and owner guards ----------------------------------


def test_checkpoint_owner_stamp_guards(tmp_path):
    from kaboodle_tpu import checkpoint
    from kaboodle_tpu.sim import init_state

    st = init_state(N, seed=3)
    stamped = tmp_path / "stamped.npz"
    bare = tmp_path / "bare.npz"
    checkpoint.save(stamped, st, owner="e0")
    checkpoint.save(bare, st)

    assert checkpoint.checkpoint_owner(stamped) == "e0"
    assert checkpoint.checkpoint_owner(bare) is None
    _ = checkpoint.load(stamped, expect_owner="e0")  # the sanctioned path
    with pytest.raises(CheckpointError, match="alien engine"):
        checkpoint.load(stamped, expect_owner="e1")
    with pytest.raises(CheckpointError, match="no owner stamp"):
        checkpoint.load(bare, expect_owner="e1")
    # Unstamped-era files stay loadable when no owner is expected.
    _ = checkpoint.load(bare)


def test_journal_owner_claim_refuses_alien_engine(tmp_path):
    from kaboodle_tpu.serve.journal import (
        ServeJournal,
        journal_owner,
        replay_journal,
    )

    d = str(tmp_path / "j")
    j = ServeJournal(d, owner="e0")
    j.append("submitted", 0, req={"n": 16, "seed": 1})
    j.close()
    assert journal_owner(d) == "e0"
    with pytest.raises(ValueError, match="alien engine"):
        ServeJournal(d, owner="e1")
    # Read-side failover replay claims nothing and tolerates a torn tail.
    with open(os.path.join(d, "wal.jsonl"), "a") as f:
        f.write('{"op": "harvested", "rid": 0, "resu')
    table, next_rid = replay_journal(d)
    assert next_rid == 1
    assert table[0]["op"] == "submitted"  # the torn record never folded
    assert journal_owner(d) == "e0"  # replay did not steal the claim


def test_engine_id_namespaces_shared_roots(tmp_path):
    """Two engines pointed at the SAME spill/journal roots land their
    files one engine-id level down — no collisions, and the failover
    replay knows exactly which directory is whose."""
    engines = [
        ServeEngine([LanePool(N, 2, cfg=CFG, chunk=8)], spill_after=1,
                    spill_dir=str(tmp_path / "spill"),
                    journal_dir=str(tmp_path / "wal"), engine_id=eid)
        for eid in ("e0", "e1")
    ]
    try:
        for eng in engines:
            assert eng.journal.dir == str(tmp_path / "wal" / eng.engine_id)
            assert eng.journal.owner == eng.engine_id
            assert eng.spill_dir == str(tmp_path / "spill" / eng.engine_id)
    finally:
        for eng in engines:
            eng.close()


def test_adopt_is_a_journaled_cross_engine_handover(tmp_path):
    """The failover handover without the router: e0 spills a kept
    request, e1 adopts the (file, run counters, owner) triple, restores
    across the owner stamp, and the continuation completes on e1."""
    from kaboodle_tpu.serve.journal import replay_journal

    def _engine(eid: str) -> ServeEngine:
        return ServeEngine(
            [LanePool(N, 2, cfg=CFG, chunk=8)], max_leap=64, spill_after=1,
            spill_dir=str(tmp_path / "spill"),
            journal_dir=str(tmp_path / "wal"), engine_id=eid,
        )

    e0 = _engine("e0")
    e0.warmup()
    rid = e0.submit(ServeRequest(n=N, seed=5, mode="ticks", ticks=8,
                                 keep=True))
    e0.drain()
    for _ in range(40):
        e0.step()
        if e0.status(rid)["state"] == "spilled":
            break
    e0.settle_spills()
    row = e0.status(rid)
    assert row["state"] == "spilled"
    e0.close()  # e0 "dies"; its journal and spill file survive

    table, _ = replay_journal(str(tmp_path / "wal" / "e0"))
    jrow = table[rid]
    assert jrow["spill_path"] and os.path.exists(jrow["spill_path"])

    e1 = _engine("e1")
    e1.warmup()
    req = {k: v for k, v in jrow["req"].items()}
    new_rid = e1.adopt(ServeRequest(**req), jrow["spill_path"],
                       jrow["saved_run"], owner="e0")
    assert e1.status(new_rid)["state"] == "spilled"
    assert e1.restore(new_rid)  # loads across the e0 owner stamp
    e1.resume(new_rid, mode="ticks", ticks=4)
    e1.drain()
    done = e1.status(new_rid)
    assert done["result"] is not None
    # The continuation's counters carried over: total ticks accumulate.
    assert done["result"]["ticks_run"] >= 8
    # Adoption without a pool for the class, or without the file, refuses.
    with pytest.raises(ValueError, match="no pool"):
        e1.adopt(ServeRequest(n=1024), jrow["spill_path"], None, "e0")
    with pytest.raises(CheckpointError, match="missing"):
        e1.adopt(ServeRequest(n=N), str(tmp_path / "gone.npz"), None, "e0")
    e1.close()


# -- client reconnect across a server kill/restart --------------------------


def test_client_reconnect_resumes_wait_across_restart(tmp_path):
    """A ``wait`` parked on a connection the server KILLS mid-flight
    (listener gone, transports RST) resumes transparently on the
    restarted server: request ids are server-side state, the client
    re-dials with backoff and re-sends the idempotent op."""
    from kaboodle_tpu.serve.client import ServeClient
    from kaboodle_tpu.serve.server import ServeServer

    engine = ServeEngine([LanePool(N, 2, cfg=CFG, chunk=8)], max_leap=64)
    engine.warmup()

    async def drive() -> dict:
        server = ServeServer(engine, port=0)
        await server.start()
        port = server.port
        client = await ServeClient.connect(
            port=port, reconnect=True, redial_max=10, redial_backoff=0.05
        )
        # Fill both lanes with parked keepers (no spill_dir, so parked
        # requests hold their lanes); the third request then queues with
        # no lane available and its wait is DETERMINISTICALLY blocked
        # until a keeper is cancelled — which we only do after the
        # kill/restart, so the wait must straddle it.
        keepers = [
            await client.submit(N, seed=s, mode="ticks", ticks=4,
                                scenario="steady", keep=True)
            for s in (9, 10)
        ]
        for _ in range(200):
            rows = [await client.status(k) for k in keepers]
            if all(r["state"] == "parked" for r in rows):
                break
            await asyncio.sleep(0.02)
        rid = await client.submit(N, seed=11, mode="ticks", ticks=4,
                                  scenario="steady")
        waiter = asyncio.create_task(client.wait(rid))
        await asyncio.sleep(0.2)
        assert not waiter.done()
        await server.kill()
        await asyncio.sleep(0)
        assert not waiter.done()  # broken transport, not a lost request

        server2 = ServeServer(engine, host=server.host, port=port)
        await server2.start()
        # Free a lane via a second client; the redialed waiter resolves.
        nudge = await ServeClient.connect(port=port)
        assert await nudge.cancel(keepers[0])
        row = await asyncio.wait_for(waiter, 30.0)
        await nudge.cancel(keepers[1])
        await nudge.close()
        await server2.close()
        return row, rid

    row, rid = asyncio.run(drive())
    assert row["request_id"] == rid
    assert row["result"] is not None


def test_client_never_resends_submit():
    """The reconnect surface must not double-run work: a transport break
    during ``submit`` surfaces as ConnectionError even with reconnect
    enabled (the server may already have admitted the request)."""
    from kaboodle_tpu.serve.client import ServeClient, _IDEMPOTENT

    assert "submit" not in _IDEMPOTENT
    assert "adopt" not in _IDEMPOTENT

    async def drive() -> None:
        async def handler(reader, writer) -> None:
            await reader.readline()
            writer.transport.abort()  # break before any response

        server = await asyncio.start_server(handler, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        client = await ServeClient.connect(port=port, reconnect=True)
        with pytest.raises((ConnectionError, OSError)):
            await client.submit(N, seed=1)
        server.close()
        await server.wait_closed()

    asyncio.run(drive())
