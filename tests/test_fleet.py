"""Fleet (batched ensemble) parity, masking, statistics, and lint gates.

The fleet contract is inheritance: member k of a fleet is BIT-EXACT with a
standalone single-mesh run seeded ``seeds[k]`` (dense and sharded), so every
parity guarantee the single-mesh kernel has (PARITY.md, the oracle pins)
extends to the whole ensemble by sampling members. The masked convergence
loop must freeze each member at exactly its convergence tick, and the stats
layer's device reductions must match NumPy host recomputes.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kaboodle_tpu.config import SwimConfig
from kaboodle_tpu.fleet import (
    fleet_idle_inputs,
    init_fleet,
    make_fleet_mesh,
    make_fleet_tick_fn,
    member_state,
    run_fleet_until_converged,
    run_fleet_until_converged_sharded,
    shard_fleet,
    shard_fleet_inputs,
    simulate_fleet,
    simulate_fleet_sharded,
)
from kaboodle_tpu.fleet.stats import (
    convergence_quantiles,
    knob_marginals,
    knob_quantiles,
    survival_curve,
)
from kaboodle_tpu.sim.kernel import make_tick_fn
from kaboodle_tpu.sim.runner import run_until_converged, simulate
from kaboodle_tpu.sim.state import idle_inputs, init_state


def _assert_states_equal(a, b, ctx=""):
    for name in ("state", "timer", "alive", "identity", "never_broadcast",
                 "last_broadcast", "kpr_partner", "kpr_fp", "kpr_n", "tick",
                 "key"):
        assert jnp.array_equal(getattr(a, name), getattr(b, name)), (ctx, name)
    for name in ("latency", "id_view"):
        va, vb = getattr(a, name), getattr(b, name)
        assert (va is None) == (vb is None), (ctx, name)
        if va is not None:
            assert jnp.array_equal(va, vb, equal_nan=True), (ctx, name)


@pytest.fixture(scope="module")
def emesh8():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    return make_fleet_mesh()


# ---------------------------------------------------------------------------
# member parity — dense


@pytest.mark.parametrize("deterministic", [True, False])
def test_fleet_member_matches_single_mesh_dense(deterministic):
    """Every member of a scanned fleet equals the standalone run bit-exactly
    (state AND metrics), in both protocol-draw modes."""
    n, e, ticks = 32, 4, 8
    cfg = SwimConfig(deterministic=deterministic)
    fleet = init_fleet(n, e)
    out, m = simulate_fleet(fleet, fleet_idle_inputs(n, e, ticks=ticks), cfg,
                            faulty=False)
    for k in range(e):
        ref, rm = simulate(init_state(n, seed=k), idle_inputs(n, ticks=ticks),
                           cfg, faulty=False)
        _assert_states_equal(ref, member_state(out, k), ctx=f"member {k}")
        assert jnp.array_equal(rm.converged, m.converged[:, k])
        assert jnp.array_equal(rm.messages_delivered, m.messages_delivered[:, k])
        assert jnp.array_equal(rm.fingerprint_min, m.fingerprint_min[:, k])
        assert jnp.array_equal(rm.agree_fraction, m.agree_fraction[:, k])


def test_fleet_member_matches_single_mesh_faulty_drop():
    """Per-member drop_rate knobs through the faulty vmapped kernel: each
    member's trajectory equals a standalone faulty run fed the same scalar
    rate (same seed => same key_drop stream => same [N, N] draws)."""
    n, e, ticks = 24, 3, 6
    cfg = SwimConfig()
    rates = jnp.asarray([0.0, 0.15, 0.4], dtype=jnp.float32)
    fleet = init_fleet(n, e, drop_rates=rates)
    inp = fleet_idle_inputs(n, e, ticks=ticks, drop_rate=rates)
    out, m = simulate_fleet(fleet, inp, cfg, faulty=True)
    for k in range(e):
        sin = idle_inputs(n, ticks=ticks)
        sin = dataclasses.replace(
            sin, drop_rate=jnp.full((ticks,), rates[k], dtype=jnp.float32))
        ref, rm = simulate(init_state(n, seed=k), sin, cfg, faulty=True)
        _assert_states_equal(ref, member_state(out, k), ctx=f"member {k}")
        assert jnp.array_equal(rm.messages_delivered, m.messages_delivered[:, k])


# ---------------------------------------------------------------------------
# masked convergence loop


def test_masked_converge_loop_stops_late_members():
    """Members converge at different ticks (epidemic boot, per-seed draws);
    each must freeze at exactly its own convergence tick — conv_tick and
    final state bit-equal to the standalone convergence run."""
    n, e, max_ticks = 32, 8, 64
    cfg = SwimConfig(join_broadcast_enabled=False, backdate_gossip_inserts=False)
    fleet = init_fleet(n, e, ring_contacts=2)
    out, conv_tick, done = run_fleet_until_converged(fleet, cfg,
                                                     max_ticks=max_ticks)
    ct = np.asarray(conv_tick)
    assert bool(np.asarray(done).all())
    # The masking must actually have engaged: an all-equal ensemble would
    # not exercise the freeze (the per-seed epidemic boots do diverge).
    assert np.unique(ct).size >= 2, ct
    for k in range(e):
        ref, ticks_run, conv = run_until_converged(
            init_state(n, seed=k, ring_contacts=2), cfg, max_ticks=max_ticks)
        assert bool(conv)
        assert int(ticks_run) == ct[k], (k, int(ticks_run), ct[k])
        _assert_states_equal(ref, member_state(out, k), ctx=f"member {k}")


def test_converge_loop_unconverged_members_run_to_max_ticks():
    """A member that never converges ticks to max_ticks (like the standalone
    loop) and reports conv_tick == max_ticks with done == False."""
    n, e, max_ticks = 16, 2, 4
    cfg = SwimConfig(join_broadcast_enabled=False)  # Q6 boot: slow by design
    fleet = init_fleet(n, e, ring_contacts=1)
    out, conv_tick, done = run_fleet_until_converged(fleet, cfg,
                                                     max_ticks=max_ticks)
    assert not bool(np.asarray(done).any())
    assert np.array_equal(np.asarray(conv_tick), [max_ticks] * e)
    for k in range(e):
        ref, ticks_run, conv = run_until_converged(
            init_state(n, seed=k, ring_contacts=1), cfg, max_ticks=max_ticks)
        assert not bool(conv) and int(ticks_run) == max_ticks
        _assert_states_equal(ref, member_state(out, k), ctx=f"member {k}")


def test_fleet_drop_knob_converges_through_faulty_loop():
    """The faulty masked loop with a per-member drop grid: every member's
    frozen state matches a standalone faulty tick-by-tick loop with the
    same scalar rate, stopped at its own convergence."""
    n, e, max_ticks = 24, 4, 48
    cfg = SwimConfig()
    rates = jnp.asarray([0.0, 0.05, 0.1, 0.2], dtype=jnp.float32)
    fleet = init_fleet(n, e, drop_rates=rates)
    out, conv_tick, done = run_fleet_until_converged(
        fleet, cfg, max_ticks=max_ticks, faulty=True)
    ct, dn = np.asarray(conv_tick), np.asarray(done)
    tick = jax.jit(make_tick_fn(cfg, faulty=True))
    for k in range(e):
        st = init_state(n, seed=k)
        idle = dataclasses.replace(
            idle_inputs(n), drop_rate=jnp.asarray(rates[k], dtype=jnp.float32))
        i, conv = 0, False
        while not conv and i < max_ticks:
            st, m = tick(st, idle)
            i, conv = i + 1, bool(m.converged)
        assert conv == bool(dn[k]), k
        assert i == ct[k], (k, i, ct[k])
        _assert_states_equal(st, member_state(out, k), ctx=f"member {k}")


# ---------------------------------------------------------------------------
# member parity — sharded


@pytest.mark.slow
def test_fleet_member_matches_single_mesh_sharded(emesh8):
    """1-D ensemble mesh: members split across 8 devices, each bit-equal to
    the standalone run; leaves actually carry the ensemble sharding."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    from kaboodle_tpu.fleet import ENSEMBLE_AXIS

    n, e, ticks = 16, 8, 8
    cfg = SwimConfig()
    fleet = shard_fleet(init_fleet(n, e), emesh8)
    want = NamedSharding(emesh8, P(ENSEMBLE_AXIS, None, None))
    assert fleet.mesh.state.sharding.is_equivalent_to(want, 3)
    inp = shard_fleet_inputs(fleet_idle_inputs(n, e, ticks=ticks), emesh8,
                             stacked=True)
    out, m = simulate_fleet_sharded(fleet, inp, cfg, emesh8, faulty=False)
    assert len(out.mesh.state.sharding.device_set) == 8
    for k in (0, 3, 7):
        ref, rm = simulate(init_state(n, seed=k), idle_inputs(n, ticks=ticks),
                           cfg, faulty=False)
        _assert_states_equal(ref, member_state(out, k), ctx=f"member {k}")
        assert jnp.array_equal(rm.converged, m.converged[:, k])


@pytest.mark.slow
def test_fleet_2d_mesh_converge_matches_dense():
    """E x peers 2-D mesh: the masked convergence loop partitioned over both
    axes equals the dense fleet run bit-exactly."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    n, e, max_ticks = 16, 4, 64
    cfg = SwimConfig(join_broadcast_enabled=False, backdate_gossip_inserts=False)
    mesh2 = make_fleet_mesh(4, 2)
    assert mesh2.axis_names == ("ensemble", "peers")
    fl = shard_fleet(init_fleet(n, e, ring_contacts=2), mesh2)
    sh, ct_sh, done_sh = run_fleet_until_converged_sharded(
        fl, cfg, mesh2, max_ticks=max_ticks)
    dn, ct_dn, done_dn = run_fleet_until_converged(
        init_fleet(n, e, ring_contacts=2), cfg, max_ticks=max_ticks)
    assert np.array_equal(np.asarray(ct_sh), np.asarray(ct_dn))
    assert np.array_equal(np.asarray(done_sh), np.asarray(done_dn))
    _assert_states_equal(sh.mesh, dn.mesh, ctx="2d-mesh fleet")


def test_fleet_shard_divisibility_checks(emesh8):
    with pytest.raises(ValueError):
        shard_fleet(init_fleet(16, 6), emesh8)  # E=6 not divisible by 8
    with pytest.raises(ValueError):
        make_fleet_mesh(7, 2)  # 14 > 8 devices


# ---------------------------------------------------------------------------
# acceptance scale


def test_fleet_acceptance_e256_n256_single_dispatch():
    """ISSUE 2 acceptance: an E=256, N=256 fault-free ensemble converges in
    ONE run_fleet_until_converged dispatch on CPU, member 0 bit-exact
    against the standalone convergence run."""
    n = e = 256
    cfg = SwimConfig()
    fleet = init_fleet(n, e, track_latency=False, instant_identity=True)
    out, conv_tick, done = run_fleet_until_converged(fleet, cfg, max_ticks=16)
    assert bool(np.asarray(done).all())
    ref, ticks_run, conv = run_until_converged(
        init_state(n, seed=0, track_latency=False, instant_identity=True),
        cfg, max_ticks=16)
    assert bool(conv)
    assert int(ticks_run) == int(np.asarray(conv_tick)[0])
    _assert_states_equal(ref, member_state(out, 0), ctx="member 0")
    q = np.asarray(convergence_quantiles(conv_tick, done, qs=(0.5, 0.99)))
    assert np.all(q >= 1)


# ---------------------------------------------------------------------------
# stats vs NumPy recompute


def test_convergence_quantiles_match_numpy():
    rng = np.random.default_rng(0)
    ct = rng.integers(1, 100, size=257).astype(np.int32)
    conv = rng.random(257) < 0.8
    qs = (0.1, 0.5, 0.9, 0.99)
    got = np.asarray(convergence_quantiles(jnp.asarray(ct), jnp.asarray(conv),
                                           qs=qs))
    want = np.quantile(ct[conv].astype(np.float32), qs)
    np.testing.assert_allclose(got, want, rtol=1e-5)
    # empty mask -> NaN
    none = np.asarray(convergence_quantiles(
        jnp.asarray(ct), jnp.zeros((257,), dtype=bool), qs=qs))
    assert np.all(np.isnan(none))


def test_survival_curve_matches_numpy():
    rng = np.random.default_rng(1)
    max_ticks = 40
    ct = rng.integers(1, max_ticks + 1, size=128).astype(np.int32)
    conv = rng.random(128) < 0.7
    got = np.asarray(survival_curve(jnp.asarray(ct), jnp.asarray(conv),
                                    max_ticks=max_ticks))
    t = np.arange(max_ticks + 1)
    want = np.mean(~conv[None, :] | (ct[None, :] > t[:, None]), axis=1)
    np.testing.assert_allclose(got, want.astype(np.float32), rtol=1e-6)
    assert got[0] == 1.0  # convergence is end-of-tick: nothing done at t=0
    np.testing.assert_allclose(got[-1], np.mean(~conv), rtol=1e-6)


def test_knob_marginals_and_quantiles_match_numpy():
    rng = np.random.default_rng(2)
    values = np.linspace(0.0, 0.3, 4, dtype=np.float32)
    knob = np.repeat(values, 32)
    ct = rng.integers(1, 64, size=128).astype(np.int32)
    conv = rng.random(128) < 0.75
    marg = knob_marginals(jnp.asarray(knob), jnp.asarray(values),
                          jnp.asarray(ct), jnp.asarray(conv))
    kq = np.asarray(knob_quantiles(jnp.asarray(knob), jnp.asarray(values),
                                   jnp.asarray(ct), jnp.asarray(conv),
                                   qs=(0.5, 0.9)))
    for b, v in enumerate(values):
        sel = knob == v
        assert int(np.asarray(marg["members"])[b]) == sel.sum()
        np.testing.assert_allclose(
            float(np.asarray(marg["converged_fraction"])[b]),
            conv[sel].mean(), rtol=1e-6)
        sub = ct[sel & conv]
        if sub.size:
            np.testing.assert_allclose(
                float(np.asarray(marg["mean_conv_tick"])[b]), sub.mean(),
                rtol=1e-5)
            np.testing.assert_allclose(
                kq[b], np.quantile(sub.astype(np.float32), (0.5, 0.9)),
                rtol=1e-5)


def test_agree_fraction_trajectory_shapes():
    from kaboodle_tpu.fleet import agree_fraction_trajectory
    from kaboodle_tpu.profiling import fleet_run_stats, fleet_tick_stats

    n, e, ticks = 16, 3, 5
    cfg = SwimConfig()
    fleet = init_fleet(n, e)
    _, m = simulate_fleet(fleet, fleet_idle_inputs(n, e, ticks=ticks), cfg,
                          faulty=False)
    traj = agree_fraction_trajectory(m)
    for key in ("mean", "min", "max", "converged_fraction"):
        assert traj[key].shape == (ticks,)
    assert np.all(np.asarray(traj["min"]) <= np.asarray(traj["mean"]) + 1e-6)
    table = fleet_run_stats(m)
    assert table.shape == (ticks,) and table["converged_members"][-1] == e
    one = fleet_tick_stats(m, 1)
    ref, rm = simulate(init_state(n, seed=1), idle_inputs(n, ticks=ticks),
                       cfg, faulty=False)
    assert np.array_equal(one["converged"], np.asarray(rm.converged))


# ---------------------------------------------------------------------------
# construction / validation / lint


def test_init_fleet_validation_and_pallas_guard():
    with pytest.raises(ValueError):
        init_fleet(16, 0)
    with pytest.raises(ValueError):
        init_fleet(16, 4, seeds=jnp.arange(3))
    with pytest.raises(ValueError):
        init_fleet(16, 4, drop_rates=jnp.zeros((2,)))
    with pytest.raises(ValueError):
        make_fleet_tick_fn(SwimConfig(use_pallas_fp=True), faulty=False)


def test_init_fleet_keys_match_standalone_seeds():
    fleet = init_fleet(8, 3, seeds=jnp.asarray([5, 9, 2]))
    for k, seed in enumerate([5, 9, 2]):
        assert jnp.array_equal(member_state(fleet, k).key,
                               jax.random.PRNGKey(seed)), k


def test_sweep_cli_emits_quantile_table(capsys):
    """One process invocation of the sweep front-end yields the per-knob
    quantile table and the compact JSON tail line."""
    import json

    from kaboodle_tpu.fleet.bench import build_parser, run_sweep

    args = build_parser().parse_args(
        ["--sweep", "drop_rate=0:0.1:2", "--ensemble", "8", "--n", "16",
         "--max-ticks", "24", "--shard", "none"])
    line = run_sweep(args)
    out = capsys.readouterr().out
    assert "drop_rate=0.000" in out and "p50" in out
    assert line["metric"] == "fleet_convergence_quantiles"
    assert line["ensemble"] == 8 and len(line["per_knob"]) == 2
    assert line["per_knob"][0]["converged_fraction"] == 1.0
    json.dumps(line)  # the tail line must be JSON-serializable


def test_sweep_cli_rejects_bad_flag_combinations():
    """Contradictory or under-provisioned sweeps must refuse, not silently
    measure something else (code-review findings on the first cut)."""
    import pytest as _pytest

    from kaboodle_tpu.fleet.bench import build_parser, run_sweep

    with _pytest.raises(SystemExit, match="mutually exclusive"):
        run_sweep(build_parser().parse_args(
            ["--sweep", "drop_rate=0:0.1:2", "--seeds-only", "--ensemble", "4"]))
    with _pytest.raises(SystemExit, match="grid point"):
        run_sweep(build_parser().parse_args(
            ["--sweep", "drop_rate=0:0.1:8", "--ensemble", "4"]))
    with _pytest.raises(SystemExit, match="bad --sweep"):
        run_sweep(build_parser().parse_args(
            ["--sweep", "drop_rate=0:0.1", "--ensemble", "4"]))
    with _pytest.raises(SystemExit, match="unknown sweep knob"):
        run_sweep(build_parser().parse_args(
            ["--sweep", "ping_timeout_ticks=1:3:2", "--ensemble", "4"]))


def test_fleet_graftlint_clean():
    """ISSUE 2 satellite: the fleet subsystem carries no KB2xx/KB3xx debt
    (it is registered in the hot-path scope, so KB301/KB302 apply)."""
    from pathlib import Path

    from kaboodle_tpu.analysis import analyze_path
    from kaboodle_tpu.analysis.core import _load_rules
    from kaboodle_tpu.analysis.rules_hotpath import DTYPE_DISCIPLINE_FILES, HOT_DIRS

    assert "kaboodle_tpu/fleet/" in HOT_DIRS
    assert "core.py" in DTYPE_DISCIPLINE_FILES and "stats.py" in DTYPE_DISCIPLINE_FILES
    _load_rules()
    root = Path(__file__).resolve().parent.parent / "kaboodle_tpu" / "fleet"
    findings = [f for p in sorted(root.glob("*.py")) for f in analyze_path(p)]
    bad = [f for f in findings if f.rule.startswith(("KB2", "KB3"))]
    assert not bad, [(f.path, f.rule, f.line, f.message) for f in bad]


def test_serve_admission_reseed_is_standalone_init():
    """ISSUE 10 admission pin: the serve pool's traced-lane re-seed scatter
    writes EXACTLY ``init_state(n, seed, **scenario_kwargs)`` into the lane
    — leaf for leaf, both scenarios — so the fleet parity contract (member
    k bit-exact with a standalone run) extends to lanes admitted mid-
    flight. The full-trajectory pin lives in tests/test_serve.py."""
    from kaboodle_tpu.serve.pool import SCENARIOS, LanePool

    pool = LanePool(16, 2, cfg=SwimConfig(deterministic=True), chunk=4)
    for lane, (scenario, seed) in enumerate(
        (("boot", 41), ("steady", 42))
    ):
        gen = pool.admit(lane, seed=seed, scenario=scenario)
        assert gen == 1  # fresh pool: first occupancy of this lane
        shape_kw = SCENARIOS[scenario]
        kw = dict(shape_kw(16) if callable(shape_kw) else shape_kw)
        ref = init_state(16, seed=seed, **kw)
        member = pool.member(lane)
        for f in dataclasses.fields(ref):
            a, b = getattr(member, f.name), getattr(ref, f.name)
            if a is None or b is None:
                assert a is None and b is None, f.name
                continue
            a, b = np.asarray(a), np.asarray(b)
            eq = np.issubdtype(a.dtype, np.floating)
            assert np.array_equal(a, b, equal_nan=eq), (
                f"admitted lane {lane} leaf {f.name!r} != standalone "
                f"init_state({scenario})"
            )
