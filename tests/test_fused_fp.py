"""Fused Pallas fingerprint kernel: bit-exact vs the jnp formulation.

Runs in pallas interpreter mode on the CPU test mesh (fused_fp_count
auto-selects interpret off-TPU), so these tests pin semantics everywhere;
TPU runs compile the same kernel for real.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from kaboodle_tpu.config import SwimConfig
from kaboodle_tpu.ops.fused_fp import fused_fp_count, pallas_supported
from kaboodle_tpu.ops.hashing import membership_fingerprint, peer_record_hash
from kaboodle_tpu.sim.kernel import make_tick_fn
from kaboodle_tpu.sim.state import init_state, idle_inputs


def _random_state(rng, n):
    codes = rng.integers(0, 4, size=(n, n), dtype=np.int8)
    np.fill_diagonal(codes, 1)
    return jnp.asarray(codes)


@pytest.mark.parametrize("n", [128, 256, 768])
def test_fused_matches_jnp_id_view_mode(n):
    # n=768 forces a multi-block grid (block rows cap at 512) with a
    # partially out-of-bounds final block — the tiling/padding path every
    # bench-scale run takes on real TPU.
    rng = np.random.default_rng(7)
    state = _random_state(rng, n)
    idv = jnp.asarray(rng.integers(0, 2**32, size=(n, n), dtype=np.uint32))
    fp, cnt = fused_fp_count(state, idv)
    ref_fp = membership_fingerprint(state > 0, idv)
    np.testing.assert_array_equal(np.asarray(fp), np.asarray(ref_fp))
    np.testing.assert_array_equal(
        np.asarray(cnt), np.asarray((state > 0).sum(axis=1, dtype=jnp.int32))
    )


@pytest.mark.parametrize("n", [128, 768])
def test_fused_matches_jnp_hash_mode(n):
    rng = np.random.default_rng(11)
    state = _random_state(rng, n)
    ident = jnp.asarray(rng.integers(0, 2**32, size=(n,), dtype=np.uint32))
    rec_hash = peer_record_hash(jnp.arange(n, dtype=jnp.uint32), ident)
    fp, cnt = fused_fp_count(state, rec_hash)
    ref_fp = membership_fingerprint(state > 0, ident)
    np.testing.assert_array_equal(np.asarray(fp), np.asarray(ref_fp))


def test_unsupported_shape_raises():
    state = jnp.zeros((100, 100), jnp.int8)
    assert not pallas_supported(100)
    with pytest.raises(ValueError):
        fused_fp_count(state, jnp.zeros((100,), jnp.uint32))


@pytest.mark.parametrize("lean", [False, True])
def test_tick_kernel_identical_with_pallas_fp(lean):
    """The whole tick trajectory is bit-identical with the fused pass on
    (pallas, interpret mode here) and off — fingerprints are the convergence
    signal, so any drift would change protocol behavior."""
    n, ticks = 128, 4
    st0 = init_state(n, seed=5, track_latency=not lean, instant_identity=lean)
    inp = idle_inputs(n)
    outs = {}
    for flag in (False, True):
        tick = jax.jit(make_tick_fn(SwimConfig(use_pallas_fp=flag), faulty=False))
        st = st0
        ms = []
        for _ in range(ticks):
            st, m = tick(st, inp)
            ms.append(m)
        outs[flag] = (st, ms)
    a, b = outs[False], outs[True]
    np.testing.assert_array_equal(np.asarray(a[0].state), np.asarray(b[0].state))
    np.testing.assert_array_equal(np.asarray(a[0].timer), np.asarray(b[0].timer))
    for ma, mb in zip(a[1], b[1]):
        assert bool(ma.converged) == bool(mb.converged)
        assert int(ma.fingerprint_min) == int(mb.fingerprint_min)
        assert int(ma.fingerprint_max) == int(mb.fingerprint_max)
        assert int(ma.messages_delivered) == int(mb.messages_delivered)
