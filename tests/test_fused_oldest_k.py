"""Fused Pallas oldest-k: bit-exactness against the jnp formulations.

Runs in pallas interpreter mode on CPU (like tests/test_fused_fp.py); real
Mosaic lowering is exercised on the chip by bench/tpu_watch.
"""

import numpy as np
import jax
import jax.numpy as jnp

from kaboodle_tpu.config import SwimConfig
from kaboodle_tpu.ops.fused_oldest_k import fused_oldest_k
from kaboodle_tpu.ops.sampling import _stable_k_smallest_iter, choose_among_candidates
from kaboodle_tpu.spec import KNOWN
import pytest


def _random_case(rng, n, timer_dtype):
    state = rng.integers(0, 4, (n, n)).astype(np.int8)  # codes 0..3
    timer = rng.integers(-12, 40, (n, n)).astype(timer_dtype)
    alive = rng.random(n) < 0.8
    return jnp.asarray(state), jnp.asarray(timer), jnp.asarray(alive)


def _reference(state, timer, alive, k):
    n = state.shape[-1]
    eye = np.eye(n, dtype=bool)
    elig = np.asarray(alive)[:, None] & (np.asarray(state) == KNOWN) & ~eye
    tmax = jnp.asarray(np.iinfo(timer.dtype).max, dtype=timer.dtype)
    scores = jnp.where(jnp.asarray(elig), timer, tmax)
    return _stable_k_smallest_iter(scores, k, tmax)


@pytest.mark.slow
def test_fused_matches_iter_both_dtypes():
    rng = np.random.default_rng(11)
    for timer_dtype in (np.int16, np.int32):
        for n in (128, 256):
            state, timer, alive = _random_case(rng, n, timer_dtype)
            for k in (1, 5):
                fi, fv = fused_oldest_k(state, timer, alive, k, interpret=True)
                ri, rv = _reference(state, timer, alive, k)
                np.testing.assert_array_equal(np.asarray(fv), np.asarray(rv))
                np.testing.assert_array_equal(
                    np.where(np.asarray(fv), np.asarray(fi), -1),
                    np.where(np.asarray(rv), np.asarray(ri), -1),
                )


def test_fused_timer_at_dtype_max_is_invalid():
    """A real timer pinned at the timer dtype's max must be invalid in both
    formulations: the jnp path cannot tell it from the ineligibility sentinel,
    and the fused kernel excludes it explicitly (ADVICE r3: bit-exactness must
    not hinge on the timers-below-dtype-max contract)."""
    rng = np.random.default_rng(17)
    for timer_dtype in (np.int16, np.int32):
        state, timer, alive = _random_case(rng, 128, timer_dtype)
        tmax = np.iinfo(timer_dtype).max
        timer = np.asarray(timer).copy()
        # Pin whole rows' eligible cells at tmax (row 0) and a scattering.
        timer[0, :] = tmax
        timer[1, ::3] = tmax
        timer = jnp.asarray(timer)
        state = state.at[0, :].set(KNOWN)  # eligible but tmax -> invalid
        alive = alive.at[0].set(True)
        fi, fv = fused_oldest_k(state, timer, alive, 5, interpret=True)
        ri, rv = _reference(state, timer, alive, 5)
        np.testing.assert_array_equal(np.asarray(fv), np.asarray(rv))
        assert not np.asarray(fv)[0].any()  # all-tmax row: nothing valid
        np.testing.assert_array_equal(
            np.where(np.asarray(fv), np.asarray(fi), -1),
            np.where(np.asarray(rv), np.asarray(ri), -1),
        )


def test_fused_non_pow2_lane_aligned_n():
    """N=384: block size must divide N exactly (no padded partial block) —
    the regression class where bn picked by VMEM budget alone left a
    partial last block that never ran in any test."""
    rng = np.random.default_rng(13)
    state, timer, alive = _random_case(rng, 384, np.int16)
    fi, fv = fused_oldest_k(state, timer, alive, 5, interpret=True)
    ri, rv = _reference(state, timer, alive, 5)
    np.testing.assert_array_equal(np.asarray(fv), np.asarray(rv))
    np.testing.assert_array_equal(
        np.where(np.asarray(fv), np.asarray(fi), -1),
        np.where(np.asarray(rv), np.asarray(ri), -1),
    )


def test_fused_selection_identical_draws():
    """Same key => same ping target through either formulation."""
    rng = np.random.default_rng(5)
    state, timer, alive = _random_case(rng, 128, np.int16)
    key = jax.random.key(9)
    fi, fv = fused_oldest_k(state, timer, alive, 5, interpret=True)
    ri, rv = _reference(state, timer, alive, 5)
    for det in (False, True):
        a = choose_among_candidates(fi, fv, key, det)
        b = choose_among_candidates(ri, rv, key, det)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_kernel_trajectory_with_fused_oldest_k():
    """Whole-tick parity: use_pallas_oldest_k=True (interpret) must reproduce
    the default kernel trajectory exactly, random and deterministic modes."""
    from kaboodle_tpu.sim.runner import simulate
    from kaboodle_tpu.sim.state import idle_inputs, init_state

    n, ticks = 128, 6
    for det in (True, False):
        base = SwimConfig(deterministic=det)
        fused = SwimConfig(deterministic=det, use_pallas_oldest_k=True)
        st = init_state(n, seed=2)
        inp = idle_inputs(n, ticks=ticks)
        out_a, m_a = simulate(st, inp, base, faulty=False)
        out_b, m_b = simulate(st, inp, fused, faulty=False)
        np.testing.assert_array_equal(np.asarray(out_a.state), np.asarray(out_b.state))
        np.testing.assert_array_equal(np.asarray(out_a.timer), np.asarray(out_b.timer))
        np.testing.assert_array_equal(
            np.asarray(m_a.fingerprint_min), np.asarray(m_b.fingerprint_min)
        )
        np.testing.assert_array_equal(
            np.asarray(m_a.messages_delivered), np.asarray(m_b.messages_delivered)
        )
