"""Fused Pallas phase-A stats: bit-exactness against the jnp formulation.

Interpreter mode on CPU (like the other fused-kernel suites); real Mosaic
lowering is exercised on the chip by bench/tpu_watch, and bench falls back
to jnp if lowering fails.
"""

import numpy as np
import jax.numpy as jnp

from kaboodle_tpu.config import SwimConfig
from kaboodle_tpu.ops.fused_suspicion import fused_suspicion
from kaboodle_tpu.spec import KNOWN, WAITING_FOR_INDIRECT_PING, WAITING_FOR_PING
import pytest


def _reference(state, timer, alive, thr):
    S = np.asarray(state).astype(np.int32)
    T = np.asarray(timer).astype(np.int32)
    n = S.shape[0]
    al = np.asarray(alive)
    count = (S > 0).sum(axis=1).astype(np.int32)
    timed = al[:, None] & (S == WAITING_FOR_PING) & (T <= int(thr))
    has_timed = timed.any(axis=1)
    jstar = np.full(n, -1, np.int32)
    for i in range(n):
        if has_timed[i]:
            cols = np.nonzero(timed[i])[0]
            jstar[i] = cols[np.argmin(T[i, cols])]  # first min = lowest index
    eye = np.eye(n, dtype=bool)
    has_cand = ((S == KNOWN) & ~eye).any(axis=1)
    wfip = (al[:, None] & (S == WAITING_FOR_INDIRECT_PING) & (T <= int(thr))).any(axis=1)
    return count, jstar, has_timed, has_cand, wfip


def test_fused_matches_reference():
    rng = np.random.default_rng(21)
    for timer_dtype in (np.int16, np.int32):
        for n in (128, 384):
            state = jnp.asarray(rng.integers(0, 4, (n, n)).astype(np.int8))
            timer = jnp.asarray(rng.integers(-12, 30, (n, n)).astype(timer_dtype))
            alive = jnp.asarray(rng.random(n) < 0.85)
            thr = 9
            fc, fj, ft, fk, fw = fused_suspicion(state, timer, alive, thr, interpret=True)
            rc, rj, rt, rk, rw = _reference(state, timer, alive, thr)
            np.testing.assert_array_equal(np.asarray(fc), rc)
            np.testing.assert_array_equal(np.asarray(ft), rt)
            np.testing.assert_array_equal(np.asarray(fk), rk)
            np.testing.assert_array_equal(np.asarray(fj), rj)
            np.testing.assert_array_equal(np.asarray(fw), rw)


@pytest.mark.slow
def test_kernel_trajectory_with_fused_suspicion():
    """Whole-tick parity under drops heavy enough to force escalations: the
    fused phase-A stats must reproduce the default kernel trajectory
    exactly, including suspicion -> indirect ping -> removal."""
    from kaboodle_tpu.sim.kernel import make_tick_fn
    from kaboodle_tpu.sim.state import init_state
    from tests.test_kernel_parity import _inputs

    import jax

    n, ticks = 128, 8
    rng = np.random.default_rng(3)
    # Block most acks so WaitingForPing entries time out and escalate.
    seq = [
        _inputs(n, drop_ok=rng.random((n, n)) >= 0.5)
        for _ in range(ticks)
    ]
    for det in (True, False):
        base_cfg = SwimConfig(deterministic=det)
        fused_cfg = SwimConfig(deterministic=det, use_pallas_suspicion=True)
        tick_a = jax.jit(make_tick_fn(base_cfg, faulty=True))
        tick_b = jax.jit(make_tick_fn(fused_cfg, faulty=True))
        st_a = init_state(n, seed=7)
        st_b = init_state(n, seed=7)
        escalated = False
        for i, inp in enumerate(seq):
            st_a, m_a = tick_a(st_a, inp)
            st_b, m_b = tick_b(st_b, inp)
            np.testing.assert_array_equal(
                np.asarray(st_a.state), np.asarray(st_b.state),
                err_msg=f"state mismatch at tick {i} (det={det})",
            )
            np.testing.assert_array_equal(
                np.asarray(st_a.timer), np.asarray(st_b.timer),
                err_msg=f"timer mismatch at tick {i} (det={det})",
            )
            assert int(m_a.messages_delivered) == int(m_b.messages_delivered)
            escalated |= (np.asarray(st_a.state) == 3).any()
        # The scenario must actually exercise the escalation path — without
        # WaitingForIndirectPing entries the fused jstar/has_cand outputs
        # would never be consequential and this parity test would prove
        # nothing about them.
        assert escalated, "drop scenario produced no escalations; re-tune it"
