"""Randomized cross-engine fuzzing: random scenarios x random protocol flags.

The hand-written parity suite (test_kernel_parity.py) pins specific
transition paths; this file sweeps the *combination space* — random churn /
partition / drop-mask / manual-ping schedules against randomly drawn config
flags (boot mode, Q3/Q11 faithful-vs-intended, share caps, timer width,
state variants) — and requires exact kernel == oracle state every tick.
Seeds are fixed, so failures reproduce.
"""

from __future__ import annotations

import os

import numpy as np
import pytest

import jax.numpy as jnp

from kaboodle_tpu.config import SwimConfig
from kaboodle_tpu.oracle.lockstep import LockstepMesh
from kaboodle_tpu.sim.state import init_state
from tests.test_kernel_parity import _inputs, _run_parity

# Heavy end-to-end lanes (subprocess cluster / randomized fuzzing):
# excluded from `make test-quick`, always run in CI.
pytestmark = pytest.mark.slow

TICKS = 10


def _random_cfg(rng) -> SwimConfig:
    return SwimConfig(
        deterministic=True,
        join_broadcast_enabled=bool(rng.integers(2)),
        backdate_gossip_inserts=bool(rng.integers(2)),
        faithful_failed_broadcast=bool(rng.integers(2)),
        faithful_indirect_ack=bool(rng.integers(2)),
        max_share_peers=int(rng.choice([0, 6, 300])),
    )


def _random_inputs(rng, n, ticks):
    seq = []
    for _ in range(ticks):
        kill = rng.random(n) < 0.06
        revive = (rng.random(n) < 0.06) & ~kill
        # Partitions: occasionally split into 2 groups for a few ticks.
        part = (
            (np.arange(n) % 2).astype(np.int32)
            if rng.random() < 0.2
            else np.zeros(n, np.int32)
        )
        # Deterministic drop mask (keeps oracle parity exact, unlike a rate).
        drop_ok = rng.random((n, n)) >= rng.choice([0.0, 0.0, 0.15])
        manual = np.where(rng.random(n) < 0.08, rng.integers(0, n, n), -1).astype(
            np.int32
        )
        seq.append(
            _inputs(n, kill=kill, revive=revive, partition=part, drop_ok=drop_ok,
                    manual=manual)
        )
    return seq


@pytest.mark.parametrize("seed", range(6))
def test_random_scenario_random_flags(seed):
    rng = np.random.default_rng(1000 + seed)
    n = int(rng.integers(8, 20))
    cfg = _random_cfg(rng)
    ring = int(rng.integers(1, 3)) if not cfg.join_broadcast_enabled else 0
    timer_dtype = jnp.int16 if rng.integers(2) else jnp.int32
    st = init_state(n, seed=seed, ring_contacts=ring, timer_dtype=timer_dtype)
    mesh = LockstepMesh(n, cfg, seed=seed, ring_contacts=ring)
    _run_parity(mesh, st, _random_inputs(rng, n, TICKS), cfg=cfg)


@pytest.mark.parametrize("seed", range(4))
def test_random_sparse_schedule_warp_arm(seed):
    """The warp runner as a fuzz arm: random SPARSE fault schedules (so
    quiescent spans exist for the leap to take) x random protocol flags,
    from a converged init — the warped run must equal the dense tick-by-tick
    trajectory at every event-horizon boundary and at termination, and the
    densely-executed ticks' metrics must equal the dense scan's rows."""
    import jax

    from kaboodle_tpu.sim.kernel import make_tick_fn
    from kaboodle_tpu.sim.state import TickInputs, idle_inputs
    from kaboodle_tpu.warp.runner import simulate_warped

    rng = np.random.default_rng(3000 + seed)
    n = int(rng.integers(10, 24))
    ticks = int(rng.integers(24, 48))
    # Random flags (deterministic not required: both arms run the same
    # kernel program, so random draws agree by the shared counter-based
    # key chain). Warp requires no flag in particular — a config that never
    # quiesces just runs dense, still bit-exact.
    cfg = SwimConfig(
        deterministic=bool(rng.integers(2)),
        backdate_gossip_inserts=bool(rng.integers(2)),
        faithful_indirect_ack=bool(rng.integers(2)),
        max_share_peers=int(rng.choice([0, 6, 300])),
    )
    timer_dtype = jnp.int16 if rng.integers(2) else jnp.int32
    lean = bool(rng.integers(2))
    st = init_state(n, seed=seed, ring_contacts=n - 1, announced=True,
                    track_latency=not lean, instant_identity=lean,
                    timer_dtype=timer_dtype)

    # Sparse events: a few isolated ticks carry faults, the rest are idle.
    idle = idle_inputs(n, ticks=ticks)
    kill = np.zeros((ticks, n), dtype=bool)
    revive = np.zeros((ticks, n), dtype=bool)
    manual = np.full((ticks, n), -1, dtype=np.int32)
    drop_ok = np.ones((ticks, n, n), dtype=bool)
    for t in sorted(rng.choice(ticks, size=3, replace=False)):
        kind = rng.integers(4)
        if kind == 0:
            kill[t, rng.integers(n)] = True
        elif kind == 1:
            dead = ~((~kill[:t + 1]).all(axis=0))
            if dead.any():
                revive[t, np.nonzero(dead)[0][0]] = True
            else:
                manual[t, 0] = int(rng.integers(1, n))
        elif kind == 2:
            manual[t, rng.integers(n)] = int(rng.integers(n))
        else:
            drop_ok[t] = rng.random((n, n)) >= 0.15
    inputs = TickInputs(
        kill=jnp.asarray(kill),
        revive=jnp.asarray(revive),
        partition=idle.partition,
        drop_rate=idle.drop_rate,
        manual_target=jnp.asarray(manual),
        drop_ok=jnp.asarray(drop_ok),
    )

    # Dense arm, tick by tick (states banked for the boundary comparison).
    tick_fn = jax.jit(make_tick_fn(cfg, faulty=True))
    sd = st
    dense_states, dense_metrics = [], []
    for t in range(ticks):
        sd, m = tick_fn(sd, jax.tree.map(lambda x: x[t], inputs))
        dense_states.append(sd)
        dense_metrics.append(m)

    boundaries = []
    wf, dense_ticks, wm = simulate_warped(
        st, inputs, cfg, faulty=True, recheck_every=3,
        on_boundary=lambda t, s: boundaries.append((t, s)),
    )

    def assert_equal(a, b, ctx):
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
            xv, yv = np.asarray(x), np.asarray(y)
            if xv.dtype == np.float32:
                ok = ((xv == yv) | (np.isnan(xv) & np.isnan(yv))).all()
            else:
                ok = (xv == yv).all()
            assert ok, f"warp mismatch {ctx} (seed {seed})"

    assert_equal(sd, wf, "at termination")
    for t, s in boundaries:
        assert_equal(st if t == 0 else dense_states[t - 1], s, f"boundary {t}")
    for j, t in enumerate(dense_ticks):
        assert_equal(
            dense_metrics[t], jax.tree.map(lambda x: x[j], wm),
            f"metrics at tick {t}",
        )


@pytest.mark.parametrize("seed", range(3))
def test_near_quiescent_schedule_hybrid_arm(seed):
    """Warp 2.0 fuzz arm: randomized near-quiescent schedules — sparse
    kills clustered early, long calm spans, a drain-shaped suspicion
    timeout — drive the HYBRID signature class (armed timers on dead
    peers, disagreeing fingerprints, live anti-entropy), and the warped
    run must equal dense tick-by-tick at every event-horizon boundary and
    at termination. Then the zero-recompile check after signature-class
    warmup: re-dispatching the same schedule through the warmed runner
    compiles NOTHING fresh — the per-class memoization holds."""
    import jax

    from kaboodle_tpu.analysis.ir.surface import (
        assert_counter_live,
        compile_counter,
    )
    from kaboodle_tpu.sim.kernel import make_tick_fn
    from kaboodle_tpu.sim.state import TickInputs, idle_inputs
    from kaboodle_tpu.warp.runner import WarpLedger, simulate_warped

    assert_counter_live()

    rng = np.random.default_rng(7000 + seed)
    n = int(rng.integers(14, 22))
    ticks = int(rng.integers(80, 120))
    cfg = SwimConfig(
        deterministic=bool(rng.integers(2)),
        ping_timeout_ticks=int(rng.integers(28, 48)),
    )
    lean = bool(rng.integers(2))
    st = init_state(n, seed=seed, ring_contacts=n - 1, announced=True,
                    track_latency=not lean, instant_identity=lean,
                    timer_dtype=jnp.int16 if lean else jnp.int32)

    # Sparse suspect rows over long calm spans: 1-2 early kills, nothing
    # else — the drain (discovery, waiting windows, expiry seasons) is the
    # whole schedule.
    idle = idle_inputs(n, ticks=ticks)
    kill = np.zeros((ticks, n), dtype=bool)
    for v in rng.choice(np.arange(1, n), size=int(rng.integers(1, 3)),
                        replace=False):
        kill[int(rng.integers(0, 6)), v] = True
    inputs = TickInputs(
        kill=jnp.asarray(kill),
        revive=idle.revive,
        partition=idle.partition,
        drop_rate=idle.drop_rate,
        manual_target=idle.manual_target,
        drop_ok=None,
    )

    tick_fn = jax.jit(make_tick_fn(cfg, faulty=True))
    sd = st
    dense_states = []
    for t in range(ticks):
        sd, _ = tick_fn(sd, jax.tree.map(lambda x: x[t], inputs))
        dense_states.append(sd)

    boundaries = []
    ledger = WarpLedger()
    wf, dense_ticks, _ = simulate_warped(
        st, inputs, cfg, faulty=True, recheck_every=4,
        on_boundary=lambda t, s: boundaries.append((t, s)),
        ledger=ledger,
    )

    def assert_equal(a, b, ctx):
        for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
            xv, yv = np.asarray(x), np.asarray(y)
            if xv.dtype == np.float32:
                ok = ((xv == yv) | (np.isnan(xv) & np.isnan(yv))).all()
            else:
                ok = (xv == yv).all()
            assert ok, f"hybrid warp mismatch {ctx} (seed {seed})"

    assert_equal(sd, wf, "at termination")
    for t, s in boundaries:
        assert_equal(st if t == 0 else dense_states[t - 1], s, f"boundary {t}")
    # The near-quiescent generator must actually drive the hybrid path.
    assert any(r["engine"] == "hybrid" for r in ledger.spans), (
        f"seed {seed}: no hybrid span fired — generator regression"
    )

    # --- zero fresh compiles after signature-class warmup -----------------
    with compile_counter() as box:
        simulate_warped(st, inputs, cfg, faulty=True, recheck_every=4)
    assert box.count == 0, (
        f"{box.count} fresh compiles re-dispatching a warmed near-quiescent "
        f"schedule (seed {seed}) — the signature-class memoization broke"
    )


@pytest.mark.parametrize("seed", range(2))
def test_recompile_counter_zero_after_warmup(seed):
    """The graftscan KB405 property as a fuzz arm: a 64-tick randomized
    dense+warp run triggers ZERO fresh XLA compilations once warmed.

    Warm-up executes the randomized schedule once (dense tick-by-tick AND
    through the warp runner — compiling the tick program, the quiescence/
    convergence predicates, and every power-of-two leap chunk the spans
    decompose into). The measured pass then re-dispatches the same
    schedule — the dense arm from a DIFFERENT initial state (same shapes:
    the tick program must be shape-stable across data) — under the
    compile counter from analysis/ir/surface.py. Any fresh compile is a
    memoization regression: a shape that varies per call, a static arg
    leaking per-tick values, a leap-chunk policy that stopped caching."""
    import jax

    from kaboodle_tpu.analysis.ir.surface import (
        assert_counter_live,
        compile_counter,
    )
    from kaboodle_tpu.sim.kernel import make_tick_fn
    from kaboodle_tpu.sim.state import TickInputs, idle_inputs
    from kaboodle_tpu.warp.runner import simulate_warped

    assert_counter_live()  # a dead event stream would pass this vacuously

    rng = np.random.default_rng(5000 + seed)
    n = int(rng.integers(12, 20))
    ticks = 64
    cfg = SwimConfig(deterministic=bool(rng.integers(2)))
    st = init_state(n, seed=seed, ring_contacts=n - 1, announced=True)

    # Sparse randomized faults (quiescent spans exist for the leap).
    idle = idle_inputs(n, ticks=ticks)
    kill = np.zeros((ticks, n), dtype=bool)
    manual = np.full((ticks, n), -1, dtype=np.int32)
    for t in sorted(rng.choice(ticks, size=3, replace=False)):
        if rng.integers(2):
            kill[t, rng.integers(n)] = True
        else:
            manual[t, rng.integers(n)] = int(rng.integers(n))
    inputs = TickInputs(
        kill=jnp.asarray(kill),
        revive=idle.revive,
        partition=idle.partition,
        drop_rate=idle.drop_rate,
        manual_target=jnp.asarray(manual),
        drop_ok=None,
    )

    # --- warm-up: one full execution of both arms -------------------------
    tick_fn = jax.jit(make_tick_fn(cfg, faulty=True))
    sd = st
    for t in range(ticks):
        sd, _ = tick_fn(sd, jax.tree.map(lambda x: x[t], inputs))
    simulate_warped(st, inputs, cfg, faulty=True, recheck_every=8)

    # A different-data state for the measured dense arm (same shapes).
    st_b = init_state(n, seed=seed + 17, ring_contacts=n - 1, announced=True)

    # --- measured pass: zero fresh compiles -------------------------------
    with compile_counter() as box:
        sb = st_b
        for t in range(ticks):
            sb, _ = tick_fn(sb, jax.tree.map(lambda x: x[t], inputs))
        simulate_warped(st, inputs, cfg, faulty=True, recheck_every=8)
    assert box.count == 0, (
        f"{box.count} fresh compiles in a warmed 64-tick dense+warp run "
        f"(seed {seed}) — a recompilation regression"
    )


@pytest.mark.parametrize("seed", range(4))
def test_random_scenario_counter_parity(seed):
    """ISSUE 6 counter-parity arm: the telemetry ProtocolCounters of the
    dense kernel AND the chunked twin equal the lockstep oracle's per-tick
    tallies bit-exactly, field by field, on random scenarios x random
    flags. The oracle counts events from its message lists (host Python);
    the kernels count them as pure tensor reductions — agreement means the
    counter definitions name real protocol events, not kernel artifacts."""
    import jax

    from kaboodle_tpu.sim.chunked import make_chunked_tick_fn
    from kaboodle_tpu.sim.kernel import make_tick_fn

    rng = np.random.default_rng(1000 + seed)
    n = int(rng.integers(8, 20))
    n += n % 2  # even, so the chunked block = n // 2 divides
    cfg = _random_cfg(rng)
    ring = int(rng.integers(1, 3)) if not cfg.join_broadcast_enabled else 0
    timer_dtype = jnp.int16 if rng.integers(2) else jnp.int32
    st = init_state(n, seed=seed, ring_contacts=ring, timer_dtype=timer_dtype)
    mesh = LockstepMesh(n, cfg, seed=seed, ring_contacts=ring)
    tick_d = jax.jit(make_tick_fn(cfg, faulty=True, telemetry=True))
    tick_c = jax.jit(
        make_chunked_tick_fn(cfg, faulty=True, block=n // 2, telemetry=True)
    )
    sd = sc = st
    for i, inp in enumerate(_random_inputs(rng, n, TICKS)):
        for p in np.nonzero(np.asarray(inp.kill))[0]:
            mesh.kill(int(p))
        for p in np.nonzero(np.asarray(inp.revive))[0]:
            mesh.revive(int(p))
        manual = np.asarray(inp.manual_target)
        for p in np.nonzero(manual >= 0)[0]:
            mesh.engines[p].pending_manual_pings.append(int(manual[p]))
        dok = np.asarray(inp.drop_ok)
        part = np.asarray(inp.partition)
        mesh.delivery_ok = lambda s, r, t, dok=dok, part=part: bool(
            dok[s, r] and part[s] == part[r]
        )
        mesh.tick()
        sd, out_d = tick_d(sd, inp)
        sc, out_c = tick_c(sc, inp)
        from kaboodle_tpu.telemetry.counters import FIELDS

        oracle = mesh.last_tick_counters
        assert set(oracle) == set(FIELDS)
        for name, want in oracle.items():
            dv = int(np.asarray(getattr(out_d.counters, name)))
            cv = int(np.asarray(getattr(out_c.counters, name)))
            assert dv == want, (
                f"dense {name}={dv} != oracle {want} at tick {i} (seed {seed})"
            )
            assert cv == want, (
                f"chunked {name}={cv} != oracle {want} at tick {i} (seed {seed})"
            )


@pytest.mark.parametrize("seed", range(3))
def test_random_sparse_schedule_warp_counter_totals(seed):
    """The warp arm of the counter-parity fuzz: a telemetry warped run's
    counter TOTALS (dense ticks measured + leaped spans' closed form)
    equal the dense telemetry scan's summed counters on random sparse
    schedules — i.e. ``leap_counters``' claim that a quiescent tick emits
    exactly n_alive pings/acks and nothing else is bit-true."""
    import jax

    from kaboodle_tpu.sim.kernel import make_tick_fn
    from kaboodle_tpu.sim.state import TickInputs, idle_inputs
    from kaboodle_tpu.telemetry.counters import add_counters, counters_totals
    from kaboodle_tpu.warp.runner import simulate_warped

    rng = np.random.default_rng(4000 + seed)
    n = int(rng.integers(10, 24))
    ticks = int(rng.integers(24, 48))
    cfg = SwimConfig(deterministic=bool(rng.integers(2)))
    st = init_state(n, seed=seed, ring_contacts=n - 1, announced=True)

    idle = idle_inputs(n, ticks=ticks)
    kill = np.zeros((ticks, n), dtype=bool)
    manual = np.full((ticks, n), -1, dtype=np.int32)
    for t in sorted(rng.choice(ticks, size=3, replace=False)):
        if rng.integers(2):
            kill[t, rng.integers(n)] = True
        else:
            manual[t, rng.integers(n)] = int(rng.integers(n))
    inputs = TickInputs(
        kill=jnp.asarray(kill),
        revive=idle.revive,
        partition=idle.partition,
        drop_rate=idle.drop_rate,
        manual_target=jnp.asarray(manual),
        drop_ok=None,
    )

    tick = jax.jit(make_tick_fn(cfg, faulty=True, telemetry=True))
    sd, tot = st, None
    for t in range(ticks):
        sd, out = tick(sd, jax.tree.map(lambda x: x[t], inputs))
        tot = out.counters if tot is None else add_counters(tot, out.counters)
    dense_totals = counters_totals(tot)

    wf, dense_ticks, _, warp_totals = simulate_warped(
        st, inputs, cfg, faulty=True, recheck_every=4, telemetry=True
    )
    assert warp_totals == dense_totals, (
        f"warp totals diverge (seed {seed}, "
        f"{int(dense_ticks.size)}/{ticks} dense): "
        f"{warp_totals} != {dense_totals}"
    )
    for x, y in zip(jax.tree.leaves(sd), jax.tree.leaves(wf)):
        xv, yv = np.asarray(x), np.asarray(y)
        if xv.dtype == np.float32:
            assert ((xv == yv) | (np.isnan(xv) & np.isnan(yv))).all()
        else:
            assert (xv == yv).all()


@pytest.mark.parametrize("seed", range(2))
def test_recompile_counter_zero_after_warmup_telemetry(seed):
    """The zero-recompile arm with the telemetry plane ON (ISSUE 6): a
    warmed telemetry-enabled run — counter scan + flight recorder through
    the runner, plus a telemetry warped run — triggers ZERO fresh compiles
    on re-dispatch. The recorder ring rides the carry with fixed shapes
    and the counters are added outputs of the same program, so telemetry
    must not cost a single extra compilation after warmup."""
    import jax

    from kaboodle_tpu.analysis.ir.surface import (
        assert_counter_live,
        compile_counter,
    )
    from kaboodle_tpu.sim.runner import (
        run_until_converged_telemetry,
        simulate_with_telemetry,
    )
    from kaboodle_tpu.sim.state import TickInputs, idle_inputs
    from kaboodle_tpu.warp.runner import simulate_warped

    assert_counter_live()

    rng = np.random.default_rng(6000 + seed)
    n = int(rng.integers(12, 20))
    ticks = 64
    cfg = SwimConfig(deterministic=bool(rng.integers(2)))
    st = init_state(n, seed=seed, ring_contacts=n - 1, announced=True)

    idle = idle_inputs(n, ticks=ticks)
    kill = np.zeros((ticks, n), dtype=bool)
    manual = np.full((ticks, n), -1, dtype=np.int32)
    for t in sorted(rng.choice(ticks, size=3, replace=False)):
        if rng.integers(2):
            kill[t, rng.integers(n)] = True
        else:
            manual[t, rng.integers(n)] = int(rng.integers(n))
    inputs = TickInputs(
        kill=jnp.asarray(kill),
        revive=idle.revive,
        partition=idle.partition,
        drop_rate=idle.drop_rate,
        manual_target=jnp.asarray(manual),
        drop_ok=None,
    )

    sim = jax.jit(
        lambda s, i: simulate_with_telemetry(s, i, cfg, recorder_len=8)
    )

    # --- warm-up: every telemetry program once ----------------------------
    jax.block_until_ready(sim(st, inputs)[0])
    run_until_converged_telemetry(st, cfg, max_ticks=16, recorder_len=8)
    simulate_warped(st, inputs, cfg, faulty=True, recheck_every=8,
                    telemetry=True)

    st_b = init_state(n, seed=seed + 17, ring_contacts=n - 1, announced=True)
    with compile_counter() as box:
        jax.block_until_ready(sim(st_b, inputs)[0])
        run_until_converged_telemetry(st_b, cfg, max_ticks=16, recorder_len=8)
        simulate_warped(st, inputs, cfg, faulty=True, recheck_every=8,
                        telemetry=True)
    assert box.count == 0, (
        f"{box.count} fresh compiles in a warmed telemetry-enabled run "
        f"(seed {seed}) — the telemetry plane broke memoization"
    )


@pytest.mark.parametrize("seed", range(3))
def test_random_scenario_chunked_third_engine(seed):
    """The chunked (row-blocked) kernel as a third arm of the same fuzz:
    random scenarios x random flags, exact state equality with the
    whole-tensor kernel every tick (which the fuzz above pins to the
    oracle — so all three engines agree transitively)."""
    import jax

    from kaboodle_tpu.sim.chunked import make_chunked_tick_fn
    from kaboodle_tpu.sim.kernel import make_tick_fn

    rng = np.random.default_rng(2000 + seed)
    n = 2 * int(rng.integers(5, 11))  # even, so block = n // 2 divides
    cfg = _random_cfg(rng)
    ring = int(rng.integers(1, 3)) if not cfg.join_broadcast_enabled else 0
    timer_dtype = jnp.int16 if rng.integers(2) else jnp.int32
    st = init_state(n, seed=seed, ring_contacts=ring, timer_dtype=timer_dtype)
    tick_a = jax.jit(make_tick_fn(cfg, faulty=True))
    tick_b = jax.jit(make_chunked_tick_fn(cfg, faulty=True, block=n // 2))
    sa = sb = st
    for t, inp in enumerate(_random_inputs(rng, n, TICKS)):
        sa, ma = tick_a(sa, inp)
        sb, mb = tick_b(sb, inp)
        for x, y in zip(jax.tree.leaves((sa, ma)), jax.tree.leaves((sb, mb))):
            xv, yv = np.asarray(x), np.asarray(y)
            if xv.dtype == np.float32:
                ok = ((xv == yv) | (np.isnan(xv) & np.isnan(yv))).all()
            else:
                ok = (xv == yv).all()
            assert ok, f"chunked mismatch at tick {t} (seed {seed})"


@pytest.mark.parametrize("seed", range(3))
def test_random_admission_schedule_serve_arm(seed):
    """ISSUE 10 fuzz arm: a RANDOM continuous-batching schedule — random
    interleavings of admissions (random seed/mode/scenario/budget),
    harvests, retire/re-seeds into recycled lanes and cancellations across
    a small lane pool, with the warp on — must (a) harvest every
    converge-mode request bit-exact with a standalone
    ``run_until_converged`` of its (seed, scenario), (b) run every
    horizon-mode request for exactly its budget, and (c) compile NOTHING
    after warmup, whatever order the schedule drew."""
    from kaboodle_tpu.analysis.ir.surface import (
        assert_counter_live,
        compile_counter,
    )
    from kaboodle_tpu.serve.engine import ServeEngine, ServeRequest
    from kaboodle_tpu.serve.pool import LanePool
    from kaboodle_tpu.sim.runner import run_until_converged, state_agreement

    assert_counter_live()
    rng = np.random.default_rng(3000 + seed)
    n = 16
    cfg = SwimConfig(deterministic=True)
    engine = ServeEngine(
        [LanePool(n, 3, cfg=cfg, chunk=4)], warp=True, max_leap=16
    )
    engine.warmup()

    plans: dict[int, ServeRequest] = {}
    pending = 10
    cancelled: set[int] = set()
    with compile_counter() as box:
        while pending or engine.busy:
            burst = int(rng.integers(0, 3))
            for _ in range(min(burst, pending)):
                horizon = bool(rng.integers(2))
                req = ServeRequest(
                    n=n,
                    seed=int(rng.integers(0, 50)),
                    mode="ticks" if horizon else "converge",
                    ticks=int(rng.integers(8, 48)),
                    scenario="steady" if rng.integers(2) else "boot",
                )
                plans[engine.submit(req)] = req
                pending -= 1
            if plans and rng.integers(8) == 0:
                victim = int(rng.choice(list(plans)))
                if engine.cancel(victim):
                    cancelled.add(victim)
            engine.step()
    assert box.count == 0, (
        f"schedule seed {seed}: {box.count} fresh compilations after warmup"
    )

    finished = 0
    for rid, req in plans.items():
        row = engine.status(rid)
        if rid in cancelled:
            assert row["state"] == "cancelled"
            continue
        assert row["state"] == "done", (rid, row)
        res = row["result"]
        finished += 1
        if req.mode == "ticks":
            assert res["ticks_run"] == req.ticks, (rid, req, res)
            continue
        kw = {} if req.scenario == "boot" else {
            "ring_contacts": n - 1, "announced": True}
        ref_state, ref_ticks, ref_conv = run_until_converged(
            init_state(n, seed=req.seed, **kw), cfg, max_ticks=req.ticks
        )
        conv, fp_min, fp_max, n_alive = state_agreement(ref_state)
        assert res["conv_tick"] == int(ref_ticks), (rid, req, res)
        assert res["converged"] == bool(ref_conv)
        assert res["fp_min"] == int(fp_min) and res["fp_max"] == int(fp_max)
        assert res["n_alive"] == int(n_alive)
    assert finished > 0  # the schedule actually served something

@pytest.mark.parametrize("seed", range(2))
def test_random_spill_kill_recover_arm(seed, tmp_path):
    """ISSUE 12 fuzz arm: a RANDOM park/spill schedule is crashed at a
    random point past its spill horizon (engine abandoned mid-service, no
    close) and recovered from the journal into a fresh engine — and every
    request must land exactly where an uninterrupted twin lands: the
    pre-crash completion keeps its result (replayed never), spilled
    continuations restore+resume to bit-identical member states, the
    in-flight request re-runs to the bit-identical final result, and the
    whole kill/recover boundary compiles NOTHING."""
    import jax

    from kaboodle_tpu.analysis.ir.surface import (
        assert_counter_live,
        compile_counter,
    )
    from kaboodle_tpu.serve.engine import ServeEngine, ServeRequest
    from kaboodle_tpu.serve.pool import LanePool

    assert_counter_live()
    rng = np.random.default_rng(8000 + seed)
    n = 16
    cfg = SwimConfig(deterministic=True)
    spill_after = int(rng.integers(0, 2))

    def build(tag: str, spill_after_v):
        # sync_spill: writes land before step() returns, so the kill point
        # is a deterministic function of the drawn schedule.
        os.makedirs(tmp_path / f"{tag}_spill", exist_ok=True)
        return ServeEngine(
            [LanePool(n, 2, cfg=cfg, chunk=8)], warp=False,
            sync_spill=True, spill_after=spill_after_v,
            spill_dir=str(tmp_path / f"{tag}_spill"),
            journal_dir=str(tmp_path / f"{tag}_journal"),
        )

    n_kept = int(rng.integers(1, 3))
    reqs = [
        ServeRequest(n=n, seed=int(rng.integers(0, 50)), mode="ticks",
                     ticks=8 * int(rng.integers(1, 4)), scenario="steady",
                     keep=True)
        for _ in range(n_kept)
    ]
    reqs.append(ServeRequest(n=n, seed=int(rng.integers(0, 50)),
                             mode="converge", ticks=40,
                             scenario="steady" if rng.integers(2) else "boot"))
    # The crash victim: a horizon too long to finish before the kill.
    reqs.append(ServeRequest(n=n, seed=int(rng.integers(0, 50)),
                             mode="ticks", ticks=800, scenario="steady"))
    resume_ticks = 8 * int(rng.integers(1, 4))
    extra_steps = int(rng.integers(0, 4))

    def drive_to_kill_point(eng, kept, conv):
        for _ in range(600):
            eng.step()
            if (eng.status(conv)["state"] == "done" and all(
                    eng.status(r)["state"] == "spilled" for r in kept)):
                break
        else:
            raise AssertionError(f"seed {seed}: kill point never reached")
        for _ in range(extra_steps):
            eng.step()

    def leaves(member):
        return [np.asarray(x) for x in jax.tree.leaves(member)]

    twin = build("twin", spill_after)
    twin.warmup()
    with compile_counter() as box:
        # --- the uninterrupted twin ------------------------------------
        t_rids = [twin.submit(r) for r in reqs]
        t_kept, t_conv, t_long = t_rids[:n_kept], t_rids[-2], t_rids[-1]
        drive_to_kill_point(twin, t_kept, t_conv)
        twin.drain()
        twin.spill_after = None  # continuations park again and hold lanes
        for rid in t_kept:
            assert twin.restore(rid)
            twin.resume(rid, mode="ticks", ticks=resume_ticks)
        twin.drain()
        want_members = {
            rid: leaves(twin.pools[n].member(twin.status(rid)["lane"]))
            for rid in t_kept
        }
        want_conv = twin.status(t_conv)["result"]
        want_long = twin.status(t_long)["result"]

        # --- the victim: same schedule, crashed past the kill point ----
        victim = build("victim", spill_after)
        victim.warmup()
        v_rids = [victim.submit(r) for r in reqs]
        assert v_rids == t_rids  # same submission order, same rids
        drive_to_kill_point(victim, v_rids[:n_kept], t_conv)
        pre_kill_conv = victim.status(t_conv)["result"]
        del victim  # the crash: no close, no flush, no compaction

        # --- recovery into a fresh engine, same process ----------------
        rec = build("victim", None)
        rec.warmup()
        counts = rec.recover()
        assert counts == {"done": 1, "spilled": n_kept, "requeued": 1,
                          "cancelled": 0, "dropped": 0}, counts
        assert rec.status(t_conv)["result"] == pre_kill_conv == want_conv
        # Drain the re-queued request BEFORE re-occupying lanes with the
        # restored continuations (with n_kept == lanes they'd starve it).
        rec.drain()
        # The re-queued in-flight request re-ran its FULL horizon.
        assert rec.status(t_long)["result"] == want_long
        for rid in v_rids[:n_kept]:
            assert rec.restore(rid)
            rec.resume(rid, mode="ticks", ticks=resume_ticks)
        rec.drain()
        for rid in v_rids[:n_kept]:
            got = leaves(rec.pools[n].member(rec.status(rid)["lane"]))
            want = want_members[rid]
            assert len(got) == len(want)
            for x, y in zip(got, want):
                eq = np.issubdtype(x.dtype, np.floating)
                assert np.array_equal(x, y, equal_nan=eq), (
                    f"seed {seed}: recovered continuation {rid} diverged"
                )
        rec.close()
        twin.close()
    assert box.count == 0, (
        f"seed {seed}: {box.count} fresh compilations across the "
        f"kill/recover boundary"
    )


# ---- sparseplane (ISSUE 18): distribution-stat fuzz ------------------------
# The blocked_topk engine is NOT bit-pinned to the dense oracle — counter
# draws replace the [N, N] key grid, so trajectories differ by design. The
# contract is statistical: over matched seeds and randomized scenarios the
# sparse twin must land in calibrated bands around the dense oracle's
# behavior (convergence-tick ratio, steady-tick counter means, fingerprint
# agreement at convergence), and its steady tick must compile nothing
# after warmup.


def _sparse_ctx(rng):
    from kaboodle_tpu.sparseplane import SparseSpec

    n = int(rng.integers(16, 28))
    boot = int(rng.integers(1, 4))
    cfg = SwimConfig(join_broadcast_enabled=False)
    # k >= n-1: full-view blocks, so "converged" means the same predicate
    # the dense runner tests (fingerprint agreement over the full view)
    spec = SparseSpec(k=32, gossip_fanout=4, boot_contacts=boot)
    return n, boot, cfg, spec


@pytest.mark.parametrize("seed", range(4))
def test_random_boot_sparse_vs_dense_convergence_band(seed):
    """Matched-seed boots: dense and sparse both reach full agreement, and
    the sparse convergence tick sits inside the calibrated band around the
    dense one (empirically ~2.1x slower at gossip_fanout=4 vs the dense
    uncapped share; the band is generous because the engines draw from
    different RNG chains by design)."""
    from kaboodle_tpu.sim.runner import run_until_converged
    from kaboodle_tpu.sparseplane import (
        init_sparse_state,
        run_sparse_until_converged,
        sparse_fingerprint,
    )

    rng = np.random.default_rng(9000 + seed)
    n, boot, cfg, spec = _sparse_ctx(rng)

    dst = init_state(n, seed=seed, ring_contacts=boot)
    _, dticks, dconv = run_until_converged(dst, cfg, max_ticks=96)
    assert bool(dconv), f"dense arm failed to converge (seed {seed})"

    sst = init_sparse_state(n, spec, seed=seed)
    fin, sticks, sconv = run_sparse_until_converged(
        sst, cfg, spec, max_ticks=96
    )
    assert bool(sconv), f"sparse arm failed to converge (seed {seed})"
    d, s = int(dticks), int(sticks)
    assert d // 2 <= s <= 4 * d + 10, (
        f"sparse convergence {s} ticks outside the band around dense {d} "
        f"(seed {seed}, n={n}, boot={boot})"
    )
    # agreement at convergence is total, same as the dense predicate
    fp = np.asarray(sparse_fingerprint(fin))
    assert (fp == fp[0]).all()


@pytest.mark.parametrize("seed", range(3))
def test_sparse_steady_tick_counter_means(seed):
    """The steady-state counter pin: a converged sparse mesh with zero
    drops emits EXACTLY n pings and 2n delivered messages per tick (every
    alive peer draws one target; every ping acks; no expiry chains, no
    gossip inserts move membership), at agreement 1.0 and full mean
    membership — the per-tick counter means the dense steady tick shows."""
    from kaboodle_tpu.sparseplane import (
        init_sparse_state,
        run_sparse_until_converged,
        simulate_sparse,
        sparse_idle_inputs,
    )

    rng = np.random.default_rng(9100 + seed)
    n, _, cfg, spec = _sparse_ctx(rng)
    st, _, conv = run_sparse_until_converged(
        init_sparse_state(n, spec, seed=seed), cfg, spec, max_ticks=96
    )
    assert bool(conv)
    _, m = simulate_sparse(st, sparse_idle_inputs(n, ticks=16), cfg, spec)
    assert (np.asarray(m.pings_sent) == n).all()
    assert (np.asarray(m.messages_delivered) == 2 * n).all()
    assert (np.asarray(m.agree_fraction) == 1.0).all()
    assert (np.asarray(m.mean_membership) == float(n)).all()
    assert np.asarray(m.converged).all()


@pytest.mark.parametrize("seed", range(2))
def test_sparse_recompile_counter_zero_after_warmup(seed):
    """The KB405 property on the sparse engine: a warmed 64-tick sparse
    run — randomized churn schedule, nonzero drop rate — triggers ZERO
    fresh compiles on re-dispatch from a different initial state (same
    shapes). The million-peer bench's compiles_steady=0 gate, at toy N."""
    from kaboodle_tpu.analysis.ir.surface import (
        assert_counter_live,
        compile_counter,
    )
    from kaboodle_tpu.sparseplane import (
        init_sparse_state,
        run_sparse_until_converged,
        simulate_sparse,
        sparse_idle_inputs,
    )

    assert_counter_live()
    rng = np.random.default_rng(9200 + seed)
    n, _, cfg, spec = _sparse_ctx(rng)
    ticks = 64
    idle = sparse_idle_inputs(n, ticks=ticks)
    kill = np.zeros((ticks, n), bool)
    revive = np.zeros((ticks, n), bool)
    for t in sorted(rng.choice(ticks, size=3, replace=False)):
        if rng.integers(2):
            kill[t, rng.integers(n)] = True
        else:
            revive[t, rng.integers(n)] = True
    import dataclasses as dc

    inputs = dc.replace(
        idle,
        kill=jnp.asarray(kill),
        revive=jnp.asarray(revive),
        drop_rate=jnp.full((ticks,), 0.05, jnp.float32),
    )

    # warm-up: the scanned tick and the converge runner, once each
    st = init_sparse_state(n, spec, seed=seed)
    simulate_sparse(st, inputs, cfg, spec)
    run_sparse_until_converged(st, cfg, spec, max_ticks=32)

    st_b = init_sparse_state(n, spec, seed=seed + 23)
    with compile_counter() as box:
        simulate_sparse(st_b, inputs, cfg, spec)
        run_sparse_until_converged(st_b, cfg, spec, max_ticks=32)
    assert box.count == 0, (
        f"{box.count} fresh compiles in a warmed 64-tick sparse run "
        f"(seed {seed}) — the sparse engine started minting programs"
    )
