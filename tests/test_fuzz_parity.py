"""Randomized cross-engine fuzzing: random scenarios x random protocol flags.

The hand-written parity suite (test_kernel_parity.py) pins specific
transition paths; this file sweeps the *combination space* — random churn /
partition / drop-mask / manual-ping schedules against randomly drawn config
flags (boot mode, Q3/Q11 faithful-vs-intended, share caps, timer width,
state variants) — and requires exact kernel == oracle state every tick.
Seeds are fixed, so failures reproduce.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax.numpy as jnp

from kaboodle_tpu.config import SwimConfig
from kaboodle_tpu.oracle.lockstep import LockstepMesh
from kaboodle_tpu.sim.state import init_state
from tests.test_kernel_parity import _inputs, _run_parity

# Heavy end-to-end lanes (subprocess cluster / randomized fuzzing):
# excluded from `make test-quick`, always run in CI.
pytestmark = pytest.mark.slow

TICKS = 10


def _random_cfg(rng) -> SwimConfig:
    return SwimConfig(
        deterministic=True,
        join_broadcast_enabled=bool(rng.integers(2)),
        backdate_gossip_inserts=bool(rng.integers(2)),
        faithful_failed_broadcast=bool(rng.integers(2)),
        faithful_indirect_ack=bool(rng.integers(2)),
        max_share_peers=int(rng.choice([0, 6, 300])),
    )


def _random_inputs(rng, n, ticks):
    seq = []
    for _ in range(ticks):
        kill = rng.random(n) < 0.06
        revive = (rng.random(n) < 0.06) & ~kill
        # Partitions: occasionally split into 2 groups for a few ticks.
        part = (
            (np.arange(n) % 2).astype(np.int32)
            if rng.random() < 0.2
            else np.zeros(n, np.int32)
        )
        # Deterministic drop mask (keeps oracle parity exact, unlike a rate).
        drop_ok = rng.random((n, n)) >= rng.choice([0.0, 0.0, 0.15])
        manual = np.where(rng.random(n) < 0.08, rng.integers(0, n, n), -1).astype(
            np.int32
        )
        seq.append(
            _inputs(n, kill=kill, revive=revive, partition=part, drop_ok=drop_ok,
                    manual=manual)
        )
    return seq


@pytest.mark.parametrize("seed", range(6))
def test_random_scenario_random_flags(seed):
    rng = np.random.default_rng(1000 + seed)
    n = int(rng.integers(8, 20))
    cfg = _random_cfg(rng)
    ring = int(rng.integers(1, 3)) if not cfg.join_broadcast_enabled else 0
    timer_dtype = jnp.int16 if rng.integers(2) else jnp.int32
    st = init_state(n, seed=seed, ring_contacts=ring, timer_dtype=timer_dtype)
    mesh = LockstepMesh(n, cfg, seed=seed, ring_contacts=ring)
    _run_parity(mesh, st, _random_inputs(rng, n, TICKS), cfg=cfg)


@pytest.mark.parametrize("seed", range(3))
def test_random_scenario_chunked_third_engine(seed):
    """The chunked (row-blocked) kernel as a third arm of the same fuzz:
    random scenarios x random flags, exact state equality with the
    whole-tensor kernel every tick (which the fuzz above pins to the
    oracle — so all three engines agree transitively)."""
    import jax

    from kaboodle_tpu.sim.chunked import make_chunked_tick_fn
    from kaboodle_tpu.sim.kernel import make_tick_fn

    rng = np.random.default_rng(2000 + seed)
    n = 2 * int(rng.integers(5, 11))  # even, so block = n // 2 divides
    cfg = _random_cfg(rng)
    ring = int(rng.integers(1, 3)) if not cfg.join_broadcast_enabled else 0
    timer_dtype = jnp.int16 if rng.integers(2) else jnp.int32
    st = init_state(n, seed=seed, ring_contacts=ring, timer_dtype=timer_dtype)
    tick_a = jax.jit(make_tick_fn(cfg, faulty=True))
    tick_b = jax.jit(make_chunked_tick_fn(cfg, faulty=True, block=n // 2))
    sa = sb = st
    for t, inp in enumerate(_random_inputs(rng, n, TICKS)):
        sa, ma = tick_a(sa, inp)
        sb, mb = tick_b(sb, inp)
        for x, y in zip(jax.tree.leaves((sa, ma)), jax.tree.leaves((sb, mb))):
            xv, yv = np.asarray(x), np.asarray(y)
            if xv.dtype == np.float32:
                ok = ((xv == yv) | (np.isnan(xv) & np.isnan(yv))).all()
            else:
                ok = (xv == yv).all()
            assert ok, f"chunked mismatch at tick {t} (seed {seed})"
