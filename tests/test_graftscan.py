"""graftscan (kaboodle_tpu.analysis.ir) — passes, surface gate, mutations.

The acceptance contract for the IR lane is mutation-tested: each seeded
regression the ISSUE names — an injected f64 cast, a host callback inside
the tick, a spurious static argument that multiplies the compile surface —
must turn the gate red, and the corresponding clean twin must pass. The
passes run on REAL kernel programs (the dense tick, the warp leap) traced
at toy scale, plus small synthetic jaxprs for the pass-specific corners;
the committed `.graftscan_surface.json` numbers themselves are only
asserted by the fresh-process CLI gate (`make lint` / CI), never
in-process (earlier tests warm eager caches).
"""

from __future__ import annotations

import dataclasses
import json

import pytest

import jax
import jax.numpy as jnp

from kaboodle_tpu.analysis.core import BaselineError
from kaboodle_tpu.analysis.ir import scan as ir_scan
from kaboodle_tpu.analysis.ir import surface as ir_surface
from kaboodle_tpu.analysis.ir.registry import (
    ENTRY_POINTS,
    EntryPoint,
    select_entries,
    trace_entry,
)
from kaboodle_tpu.analysis.ir.walk import terminal_consumers
from kaboodle_tpu.config import SwimConfig
from kaboodle_tpu.sim.kernel import make_tick_fn
from kaboodle_tpu.sim.state import idle_inputs, init_state

N = 16  # trace scale for the mutation entries


def _cfg():
    return SwimConfig(deterministic=True)


def _tick_entry(name: str, wrap=None, **entry_kw) -> EntryPoint:
    """An EntryPoint over the real fault-free dense tick, optionally with a
    mutation ``wrap(tick) -> tick`` applied."""

    def build():
        tick = make_tick_fn(_cfg(), faulty=False)
        fn = wrap(tick) if wrap is not None else tick
        return fn, (init_state(N, seed=0), idle_inputs(N))

    return EntryPoint(name, build, **entry_kw)


def rules_of(findings) -> set[str]:
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# registry


def test_registry_names_unique_and_selectable():
    names = [e.name for e in ENTRY_POINTS]
    assert len(names) == len(set(names))
    assert select_entries(None) == ENTRY_POINTS
    assert [e.name for e in select_entries(["ops.crc32"])] == ["ops.crc32"]
    with pytest.raises(KeyError):
        select_entries(["no.such.entry"])


def test_cheap_entries_trace_both_modes():
    entry = select_entries(["ops.crc32"])[0]
    assert trace_entry(entry, x64=False).jaxpr.eqns
    assert trace_entry(entry, x64=True).jaxpr.eqns


# ---------------------------------------------------------------------------
# KB401 — the seeded f64-cast mutation


def test_clean_tick_has_no_findings():
    findings = ir_scan.scan_entry(_tick_entry("clean.tick"))
    assert findings == []


@pytest.mark.filterwarnings("ignore:Explicitly requested dtype")
def test_mutation_f64_cast_turns_kb401_red():
    """The ISSUE's seeded regression #1: an injected f64 cast of an [N, N]
    resident. Invisible under x32 (the cast silently lands on f32, with
    the warning this test expects); the x64 trace makes it real and KB401
    fires."""

    def wrap(tick):
        def mutated(st, inp):
            st2, m = tick(st, inp)
            wide = st2.timer.astype(jnp.float64)  # the seeded widening
            return dataclasses.replace(
                st2, timer=wide.astype(st2.timer.dtype)
            ), m

        return mutated

    findings = ir_scan.scan_entry(_tick_entry("mut.f64", wrap))
    assert "KB401" in rules_of(findings)
    assert any("float64" in f.message or "x64" in f.message for f in findings)


def test_kb401_lean_widening_detector():
    """int16 widened into a write (select_n) fires; widening that only
    feeds the age-arithmetic allowlist (sub/compares) does not."""

    def bad_build():
        def f(t16):
            w = t16.astype(jnp.int32)
            return jnp.where(w > 0, w, 0)  # widened value written

        return f, (jnp.zeros((8, 8), jnp.int16),)

    def good_build():
        def f(t16, t):
            age = t - t16.astype(jnp.int32)  # the kernel's age idiom
            return age >= 2

        return f, (jnp.zeros((8, 8), jnp.int16), jnp.int32(5))

    bad = ir_scan.scan_entry(EntryPoint("mut.lean", bad_build, lean=True))
    assert "KB401" in rules_of(bad)
    good = ir_scan.scan_entry(EntryPoint("ok.lean", good_build, lean=True))
    assert "KB401" not in rules_of(good)
    # the same program is exempt when the entry is not lean-flagged
    notlean = ir_scan.scan_entry(EntryPoint("ok.fat", bad_build))
    assert "KB401" not in rules_of(notlean)


def test_real_lean_tick_widenings_are_allowlisted():
    """The production lean tick's only int16 widenings are the documented
    age computations — the detector must stay quiet on them."""
    entry = select_entries(["phasegraph.tick.lean"])[0]
    from kaboodle_tpu.analysis.ir.passes import check_kb401_lean_widening

    assert check_kb401_lean_widening(entry, trace_entry(entry)) == []


# ---------------------------------------------------------------------------
# KB402 — the seeded host-callback mutation


def test_mutation_host_callback_turns_kb402_red():
    """The ISSUE's seeded regression #2: a debug callback inside the tick
    (one device->host round trip per scanned tick)."""

    def wrap(tick):
        def mutated(st, inp):
            st2, m = tick(st, inp)
            jax.debug.print("tick {t}", t=st2.tick)
            return st2, m

        return mutated

    findings = ir_scan.scan_entry(_tick_entry("mut.callback", wrap))
    assert "KB402" in rules_of(findings)


def test_clean_tick_has_no_kb402():
    entry = select_entries(["phasegraph.tick.faulty"])[0]
    from kaboodle_tpu.analysis.ir.passes import check_kb402_host_boundary

    assert check_kb402_host_boundary(entry, trace_entry(entry)) == []


# ---------------------------------------------------------------------------
# KB403 — oversized captured constants


def test_kb403_flags_big_capture_not_small():
    big = jnp.arange(64 * 64, dtype=jnp.float32).reshape(64, 64)  # 16 KiB
    small = jnp.arange(64, dtype=jnp.float32)  # 256 B

    def big_build():
        return (lambda x: x + big), (jnp.zeros((64, 64), jnp.float32),)

    def small_build():
        return (lambda x: x + small), (jnp.zeros((64,), jnp.float32),)

    bad = ir_scan.scan_entry(EntryPoint("mut.const", big_build))
    assert "KB403" in rules_of(bad)
    ok = ir_scan.scan_entry(EntryPoint("ok.const", small_build))
    assert "KB403" not in rules_of(ok)


# ---------------------------------------------------------------------------
# KB404 — sharding-spec derivation


def _mesh():
    import numpy as np
    from jax.sharding import Mesh

    return Mesh(np.asarray(jax.devices()), ("peers",))


def test_kb404_hand_rolled_spec_flagged():
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = _mesh()

    def bad_build():
        def f(x):
            return jax.lax.with_sharding_constraint(
                x * 2, NamedSharding(mesh, P(None, "peers"))  # column-sharded!
            )

        return f, (jnp.zeros((8, 8), jnp.float32),)

    findings = ir_scan.scan_entry(EntryPoint("mut.spec", bad_build, sharded=True))
    assert "KB404" in rules_of(findings)


def test_kb404_derived_spec_and_missing_constraints():
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = _mesh()

    def good_build():
        def f(x):
            return jax.lax.with_sharding_constraint(
                x * 2, NamedSharding(mesh, P("peers", None))
            )

        return f, (jnp.zeros((8, 8), jnp.float32),)

    def bare_build():
        return (lambda x: x * 2), (jnp.zeros((8, 8), jnp.float32),)

    good = ir_scan.scan_entry(EntryPoint("ok.spec", good_build, sharded=True))
    assert "KB404" not in rules_of(good)
    # ... and an unsharded entry is out of scope entirely
    assert "KB404" not in rules_of(
        ir_scan.scan_entry(EntryPoint("ok.unsharded", bare_build))
    )
    # a sharded program with NO constraints lost its layout pinning
    missing = ir_scan.scan_entry(EntryPoint("mut.bare", bare_build, sharded=True))
    assert any(
        f.rule == "KB404" and f.symbol == "missing-constraints" for f in missing
    )


def test_real_sharded_entries_pass_kb404():
    from kaboodle_tpu.analysis.ir.passes import check_kb404_sharding_specs

    for name in ("phasegraph.tick.sharded", "phasegraph.leap.sharded"):
        entry = select_entries([name])[0]
        assert check_kb404_sharding_specs(entry, trace_entry(entry)) == []


# ---------------------------------------------------------------------------
# KB405 — the compile-surface budget


def test_compile_counter_counts_fresh_compiles_only():
    f = jax.jit(lambda x: x * 3 + 1)
    a, b = jnp.zeros(7), jnp.ones(7)  # prepped outside (eager fills compile)
    with ir_surface.compile_counter() as box:
        f(a)
        f(b)  # cache hit
    assert box.count == 1
    with ir_surface.compile_counter() as box2:
        f(a)  # still cached
    assert box2.count == 0


def test_mutation_spurious_static_arg_doubles_surface():
    """The ISSUE's seeded regression #3: the dense tick dispatched through
    a jit with a spurious static argument — every input variant then
    compiles its own program, and the measured count exceeds the committed
    budget, turning KB405 red."""
    from kaboodle_tpu.analysis.ir.surface import (
        SurfaceExercise,
        _prep_dense,
        measure_surface,
        surface_findings,
    )

    def prep():
        ctx = _prep_dense()
        raw = make_tick_fn(_cfg(), faulty=True)
        # the mutation: a call counter passed static — one program per call
        ctx["tick_mut"] = jax.jit(
            lambda st, inp, i: raw(st, inp), static_argnums=2
        )
        return ctx

    def run(ctx):
        st = ctx["st"]
        for i, inp in enumerate(ctx["variants"]):
            st, _ = ctx["tick_mut"](st, inp, i)

    measured = measure_surface([SurfaceExercise("dense", prep, run)])
    assert measured["dense"] >= 5  # one program per static-arg value
    committed = {"dense": (3, "the three tick programs")}
    findings = surface_findings(measured, committed)
    assert any(f.rule == "KB405" and "grew" in f.message for f in findings)


def test_surface_findings_gate_semantics():
    committed = {"a": (3, "ok"), "b": (2, "ok"), "gone": (1, "ok")}
    # growth always fails; shrink/orphan only under no-growth
    grow = ir_surface.surface_findings({"a": 4, "b": 2}, committed)
    assert [f.symbol for f in grow] == ["surface:a:growth"]
    clean = ir_surface.surface_findings({"a": 3, "b": 2}, committed)
    assert clean == []
    strict = ir_surface.surface_findings(
        {"a": 2, "b": 2}, committed, no_growth=True
    )
    assert {f.symbol for f in strict} == {
        "surface:a:stale",
        "surface:gone:orphan",
    }
    missing = ir_surface.surface_findings({"new": 1}, {})
    assert [f.symbol for f in missing] == ["surface:new:missing"]


def test_surface_file_roundtrip(tmp_path):
    p = tmp_path / "surface.json"
    assert ir_surface.load_surface(p) == {}
    ir_surface.write_surface(p, {"dense": 3}, {"dense": (9, "old reason")})
    loaded = ir_surface.load_surface(p)
    assert loaded == {"dense": (3, "old reason")}
    p.write_text(json.dumps({"entries": [{"entry": "x", "programs": 1}]}))
    with pytest.raises(BaselineError):
        ir_surface.load_surface(p)  # justification missing
    p.write_text("not json")
    with pytest.raises(BaselineError):
        ir_surface.load_surface(p)


# ---------------------------------------------------------------------------
# walk helpers


def test_terminal_consumers_resolve_through_transparent_ops():
    """A value consumed through broadcast/reshape resolves to the real
    computing primitives, and escaping a scope reports the sentinel."""
    from kaboodle_tpu.analysis.ir.walk import iter_jaxprs

    def f(t16):
        w = t16.astype(jnp.int32)
        wide = jnp.broadcast_to(w[None], (2, *w.shape))
        return wide > 0

    cj = jax.make_jaxpr(f)(jnp.zeros((4, 4), jnp.int16))
    consumer_sets = []
    for j in iter_jaxprs(cj.jaxpr):
        for eqn in j.eqns:
            if eqn.primitive.name == "convert_element_type":
                consumer_sets.append(terminal_consumers(j, eqn.outvars[0]))
    assert consumer_sets, "expected an int16->int32 convert in the trace"
    flat = set().union(*consumer_sets)
    # the broadcast is traversed, pjit bodies are entered: the terminal
    # consumer is the comparison (or the escape sentinel at scope edges)
    assert "gt" in flat
    assert "broadcast_in_dim" not in flat

    def g(t16):
        return t16.astype(jnp.int32)  # escapes as the jaxpr output

    cjg = jax.make_jaxpr(g)(jnp.zeros((4,), jnp.int16))
    escaped = set()
    for j in iter_jaxprs(cjg.jaxpr):
        for eqn in j.eqns:
            if eqn.primitive.name == "convert_element_type":
                escaped |= terminal_consumers(j, eqn.outvars[0])
    assert "<jaxpr-output>" in escaped


# ---------------------------------------------------------------------------
# CLI wiring (canned scan — the real full gate is `make lint` / CI)


def test_cli_explain_and_unknown_entry():
    from kaboodle_tpu.analysis.cli import main

    assert main(["--explain", "KB401"]) == 0
    assert main(["--explain", "KB405"]) == 0
    assert main(["--ir", "--entries", "bogus.entry", "--no-surface"]) == 2


def test_cli_gate_goes_red_on_mutated_registry(monkeypatch):
    """End-to-end through the exact entry `make lint` uses: with a mutated
    entry point in the registry, `python -m kaboodle_tpu.analysis --ir`
    exits 1; the unmutated registry entry exits 0."""
    from kaboodle_tpu.analysis import cli
    from kaboodle_tpu.analysis.ir import registry

    def wrap(tick):
        def mutated(st, inp):
            st2, m = tick(st, inp)
            jax.debug.print("t={t}", t=st2.tick)
            return st2, m

        return mutated

    monkeypatch.setattr(
        registry, "ENTRY_POINTS", (_tick_entry("mut.cli.tick", wrap),)
    )
    assert cli.main(["--ir", "--no-surface", "--no-baseline"]) == 1
    monkeypatch.setattr(
        registry, "ENTRY_POINTS", (_tick_entry("ok.cli.tick"),)
    )
    assert cli.main(["--ir", "--no-surface", "--no-baseline"]) == 0


def test_cli_ir_baseline_filtering(tmp_path, monkeypatch, capsys):
    """IR findings flow through the shared baseline plumbing: unbaselined
    findings fail, justified ones pass, stale entries fail under
    --no-baseline-growth."""
    from kaboodle_tpu.analysis import cli
    from kaboodle_tpu.analysis.core import Finding

    canned = [
        Finding("ir://toy", "KB402", 0, "host boundary 'io_callback'", "t.py:io_callback")
    ]

    def fake_run_scan(entry_names=None, entries=None, with_surface=True, progress=None):
        return ir_scan.ScanResult(list(canned), {}, 1)

    monkeypatch.setattr(ir_scan, "run_scan", fake_run_scan)
    base = tmp_path / "base.json"
    args = ["--ir", "--no-surface", "--baseline", str(base)]
    assert cli.main(args) == 1  # unbaselined finding

    base.write_text(
        json.dumps(
            {"entries": [{"key": canned[0].key, "reason": "known debt"}]}
        )
    )
    assert cli.main(args) == 0  # justified

    canned.clear()
    assert cli.main(args) == 0  # stale entry tolerated without no-growth
    assert cli.main(args + ["--no-baseline-growth"]) == 1  # ...but not with


def test_cli_ir_rejects_positional_paths():
    from kaboodle_tpu.analysis.cli import main

    assert main(["--ir", "kaboodle_tpu/warp", "--no-surface"]) == 2


def test_kb405_findings_are_not_baselineable(tmp_path, monkeypatch):
    """A .graftscan_baseline.json entry keyed at a surface-growth finding
    must NOT suppress it — the justified surface file is the only accepted
    record of the compile surface."""
    from kaboodle_tpu.analysis import cli

    def fake_run_scan(entry_names=None, entries=None, with_surface=True, progress=None):
        return ir_scan.ScanResult([], {"warp": 99}, 1)

    monkeypatch.setattr(ir_scan, "run_scan", fake_run_scan)
    surface = tmp_path / "surface.json"
    ir_surface.write_surface(surface, {"warp": 1}, {"warp": (1, "one program")})
    [growth] = ir_surface.surface_findings({"warp": 99}, {"warp": (1, "x")})
    base = tmp_path / "base.json"
    base.write_text(
        json.dumps({"entries": [{"key": growth.key, "reason": "nope"}]})
    )
    rc = cli.main(
        ["--ir", "--surface", str(surface), "--baseline", str(base)]
    )
    assert rc == 1  # growth still red despite the baseline entry


def test_assert_counter_live_passes_here():
    ir_surface.assert_counter_live()  # this environment's stream is live
