"""Commutative fingerprint: equality semantics of the production hash."""

import numpy as np
import jax.numpy as jnp

from kaboodle_tpu.ops import membership_fingerprint, mix32, peer_record_hash


def test_identical_views_identical_fingerprints():
    n = 32
    rng = np.random.default_rng(0)
    identities = jnp.asarray(rng.integers(0, 2**32, size=n, dtype=np.uint32))
    row = rng.random(n) < 0.5
    member = jnp.asarray(np.tile(row, (n, 1)))
    fp = np.asarray(membership_fingerprint(member, identities))
    assert np.all(fp == fp[0])


def test_differing_views_differ():
    n = 64
    rng = np.random.default_rng(1)
    identities = jnp.asarray(rng.integers(0, 2**32, size=n, dtype=np.uint32))
    member = np.tile(rng.random(n) < 0.5, (n, 1))
    member[3, 7] = not member[3, 7]  # one peer's view differs by one entry
    fp = np.asarray(membership_fingerprint(jnp.asarray(member), identities))
    assert fp[3] != fp[0]
    assert np.all(np.delete(fp, 3) == fp[0])


def test_identity_change_changes_fingerprint():
    n = 16
    identities = jnp.arange(n, dtype=jnp.uint32)
    member = jnp.ones((n, n), dtype=bool)
    fp0 = np.asarray(membership_fingerprint(member, identities))
    identities2 = identities.at[5].set(jnp.uint32(999))
    fp1 = np.asarray(membership_fingerprint(member, identities2))
    assert np.all(fp0 != fp1)  # every view includes peer 5


def test_record_hash_no_trivial_cancellation():
    # (id, identity) pairs must not cancel under the commutative sum:
    # {(a, x), (b, y)} must differ from {(a, y), (b, x)} with overwhelming prob.
    a = peer_record_hash(jnp.uint32(1), jnp.uint32(10)) + peer_record_hash(
        jnp.uint32(2), jnp.uint32(20)
    )
    b = peer_record_hash(jnp.uint32(1), jnp.uint32(20)) + peer_record_hash(
        jnp.uint32(2), jnp.uint32(10)
    )
    assert int(a) != int(b)


def test_mix32_bijective_sample():
    xs = jnp.arange(100000, dtype=jnp.uint32)
    ys = np.asarray(mix32(xs))
    assert len(np.unique(ys)) == len(ys)
