"""Property-based protocol invariants under randomized fault scenarios.

Complements the exact parity pins (test_kernel_parity.py) with properties
that must hold on EVERY trajectory, whatever the faults: these are the
statements one would prove about the reference protocol, checked here by
hypothesis over randomized scenarios on the real kernel.
"""

import functools

import pytest

# Optional dev dependency (the `dev`/`test` extras): without it the module
# must SKIP, not fail collection — tier-1 runs in containers without it.
hypothesis = pytest.importorskip("hypothesis")
import hypothesis.strategies as st
import jax
import jax.numpy as jnp
import numpy as np

from kaboodle_tpu.config import SwimConfig
from kaboodle_tpu.ops.hashing import membership_fingerprint
from kaboodle_tpu.sim import Scenario, init_state, simulate
from kaboodle_tpu.spec import KNOWN

# derandomize: the example stream is fixed per test body, so CI is
# reproducible — a failure at HEAD is a failure on every run of HEAD, never a
# seed lottery. Widen the net when hunting: run with
# ``--hypothesis-seed=random`` and a higher max_examples locally.
SETTINGS = dict(max_examples=12, deadline=None, derandomize=True,
                suppress_health_check=[hypothesis.HealthCheck.too_slow])


# Jitted so that hypothesis examples sharing a shape reuse the compiled scan
# (an eager lax.scan re-traces per call; compiles dominate otherwise).
@functools.partial(jax.jit, static_argnames=("cfg",))
def _run(st0, inp, cfg):
    return simulate(st0, inp, cfg)


@st.composite
def scenarios(draw):
    # Shapes are drawn from a small set so XLA compiles once per shape and the
    # examples vary only in data (seeds, rates, windows) — compile-bound
    # otherwise.
    n = draw(st.sampled_from([12, 16]))
    ticks = draw(st.sampled_from([10, 16]))
    seed = draw(st.integers(0, 2**31 - 1))
    sc = Scenario(n=n, ticks=ticks, seed=seed)
    if draw(st.booleans()):
        sc.churn(draw(st.floats(0.0, 0.3)), protect=[0])
    if draw(st.booleans()):
        sc.drop(draw(st.floats(0.0, 0.5)))
    if draw(st.booleans()):
        groups = (np.arange(n) % draw(st.integers(2, 3))).astype(np.int32)
        start = draw(st.integers(0, max(ticks - 2, 0)))
        sc.partition_at(start, groups, until=draw(st.integers(start, ticks)))
    return sc


@hypothesis.given(scenarios())
@hypothesis.settings(**SETTINGS)
@pytest.mark.slow
def test_core_invariants(sc):
    cfg = SwimConfig()
    st0 = init_state(sc.n, seed=sc.seed, alive=jnp.asarray(sc.initial_alive()))
    final, m = _run(st0, sc.build(), cfg)

    S = np.asarray(final.state)
    T = np.asarray(final.timer)
    alive = np.asarray(final.alive)
    tick = int(final.tick)

    # I1: aliveness follows the schedule exactly.
    assert np.array_equal(alive, sc.alive_trajectory()[-1])

    # I2: every alive peer has itself Known — self is inserted at start and
    # nothing can remove it (kaboodle.rs:144-152; Failed(self) is ignored).
    assert (np.diag(S)[alive] == KNOWN).all()

    # I3: state codes stay in the 4-code alphabet and timers never run ahead
    # of the clock.
    assert S.min() >= 0 and S.max() <= 3
    assert (T <= tick).all()

    # I4: the metrics' convergence flag is exactly fingerprint agreement over
    # alive rows of the final state.
    fps = np.asarray(membership_fingerprint(jnp.asarray(S > 0), final.identity))
    if alive.any():
        agree = len(set(fps[alive].tolist())) == 1
        assert bool(np.asarray(m.converged)[-1]) == agree

    # I5: fingerprint equality <=> identical membership rows (for these sizes
    # a mix-hash collision is ~2^-32; any hit would indicate a real bug).
    rows = {}
    member = S > 0
    for i in np.flatnonzero(alive):
        key = int(fps[i])
        if key in rows:
            assert np.array_equal(member[i], member[rows[key]]), (i, rows[key])
        rows[key] = i


@hypothesis.given(scenarios())
@hypothesis.settings(**SETTINGS)
@pytest.mark.slow
def test_determinism(sc):
    """Same seed + same schedule => bit-identical trajectory (the simulator's
    race-detection substitute, SURVEY.md §5)."""
    cfg = SwimConfig()
    inp = sc.build()
    st0 = init_state(sc.n, seed=sc.seed, alive=jnp.asarray(sc.initial_alive()))
    a, ma = _run(st0, inp, cfg)
    b, mb = _run(st0, inp, cfg)
    assert jnp.array_equal(a.state, b.state)
    assert jnp.array_equal(a.timer, b.timer)
    assert jnp.array_equal(a.key, b.key)
    assert jnp.array_equal(ma.messages_delivered, mb.messages_delivered)


def test_announced_converged_init_is_quiet():
    """init_state(announced=True) models an already-running mesh: no
    never-broadcast flags, so a converged init fires no Join re-announce
    on its first tick (the flags-set default fires N of them)."""
    n = 16
    cfg = SwimConfig()
    quiet = init_state(n, ring_contacts=n - 1, announced=True)
    assert not bool(np.asarray(quiet.never_broadcast).any())
    noisy = init_state(n, ring_contacts=n - 1)
    sched = Scenario(n=n, ticks=1, seed=0).build()
    _, mq = _run(quiet, sched, cfg)
    _, mn = _run(noisy, sched, cfg)
    # The noisy init's tick 0 carries N broadcast replies' worth of extra
    # traffic... none actually: a full mesh has no NEW joiners, so the
    # message counts agree — the waste the announced flag removes is the
    # join-path work itself, not deliveries. Assert behavioral equality.
    assert int(np.asarray(mq.messages_delivered)[0]) == int(
        np.asarray(mn.messages_delivered)[0])
    assert bool(np.asarray(mq.converged)[0])


@hypothesis.given(st.sampled_from([8, 16, 32]), st.integers(0, 2**31 - 1))
@hypothesis.settings(**SETTINGS)
@pytest.mark.slow
def test_faultfree_boot_converges(n, seed):
    """I6: with no faults, a fresh mesh always reaches full membership and
    agreement quickly (every peer broadcasts Join at tick 0; replies bootstrap
    the rest; bound is generous)."""
    cfg = SwimConfig()
    final, m = _run(init_state(n, seed=seed),
                    Scenario(n=n, ticks=8, seed=seed).build(), cfg)
    assert bool(np.asarray(m.converged)[-1])
    assert (np.asarray(final.state) > 0).all()
