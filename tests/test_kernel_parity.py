"""Exact trajectory parity: JAX tick kernel vs the lockstep oracle.

In deterministic mode (SwimConfig.deterministic=True) every random draw is
replaced by a fixed rule both engines implement identically, so the kernel
must reproduce the oracle's full state — state codes, timers, fingerprints,
convergence flag, and delivered-message counts — every tick, including under
churn, message drops, and partitions. This is the simulator's analogue of the
reference's (absent) test suite: the state machine transition table of
SURVEY.md §3.2-3.3 pinned as data.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kaboodle_tpu.config import SwimConfig
from kaboodle_tpu.oracle.lockstep import LockstepMesh
from kaboodle_tpu.sim.kernel import make_tick_fn
from kaboodle_tpu.sim.state import MeshState, TickInputs, init_state

N = 12
CFG = SwimConfig(deterministic=True)


def _inputs(n, kill=None, revive=None, partition=None, drop_ok=None, manual=None):
    return TickInputs(
        kill=jnp.zeros(n, bool) if kill is None else jnp.asarray(kill, bool),
        revive=jnp.zeros(n, bool) if revive is None else jnp.asarray(revive, bool),
        partition=jnp.zeros(n, jnp.int32) if partition is None else jnp.asarray(partition, jnp.int32),
        drop_rate=jnp.float32(0.0),
        manual_target=jnp.full(n, -1, jnp.int32) if manual is None else jnp.asarray(manual, jnp.int32),
        drop_ok=jnp.ones((n, n), bool) if drop_ok is None else jnp.asarray(drop_ok, bool),
    )


def _assert_tick_equal(mesh: LockstepMesh, st: MeshState, metrics, tick: int):
    np.testing.assert_array_equal(
        np.asarray(st.state), mesh.state_matrix(), err_msg=f"state mismatch at tick {tick}"
    )
    # Timers only matter where a state exists.
    ours = np.asarray(st.timer) * (np.asarray(st.state) > 0)
    theirs = mesh.timer_matrix() * (mesh.state_matrix() > 0)
    np.testing.assert_array_equal(ours, theirs, err_msg=f"timer mismatch at tick {tick}")
    alive = np.asarray(st.alive)
    fps = np.array(mesh.fingerprints(), dtype=np.uint64) & 0xFFFFFFFF
    from kaboodle_tpu.ops.hashing import membership_fingerprint

    kfp = np.asarray(membership_fingerprint(st.state > 0, st.identity), dtype=np.uint64)
    np.testing.assert_array_equal(
        kfp[alive], fps[alive], err_msg=f"fingerprint mismatch at tick {tick}"
    )
    assert bool(metrics.converged) == mesh.converged(), f"convergence flag at tick {tick}"
    assert int(metrics.messages_delivered) == mesh.last_tick_messages, (
        f"message count at tick {tick}: kernel {int(metrics.messages_delivered)} "
        f"vs oracle {mesh.last_tick_messages}"
    )


def _run_parity(mesh: LockstepMesh, st: MeshState, inputs_per_tick, cfg=CFG):
    tick_fn = jax.jit(make_tick_fn(cfg, faulty=True))
    for i, inp in enumerate(inputs_per_tick):
        kill = np.asarray(inp.kill)
        revive = np.asarray(inp.revive)
        for p in np.nonzero(kill)[0]:
            mesh.kill(int(p))
        for p in np.nonzero(revive)[0]:
            mesh.revive(int(p))
        manual = np.asarray(inp.manual_target)
        for p in np.nonzero(manual >= 0)[0]:
            mesh.engines[p].pending_manual_pings.append(int(manual[p]))
        dok = np.asarray(inp.drop_ok)
        part = np.asarray(inp.partition)
        mesh.delivery_ok = lambda s, r, t, dok=dok, part=part: bool(
            dok[s, r] and part[s] == part[r]
        )
        mesh.tick()
        st, metrics = tick_fn(st, inp)
        _assert_tick_equal(mesh, st, metrics, i)
    return st


def test_fresh_boot_parity():
    """Boot N peers knowing only themselves; converge via Join broadcasts +
    anti-entropy (BASELINE config 2 dynamics)."""
    mesh = LockstepMesh(N, CFG)
    st = init_state(N)
    _run_parity(mesh, st, [_inputs(N) for _ in range(12)])


def test_churn_parity():
    """Silent kills exercise the WaitingForPing -> indirect-ping -> removal
    path (kaboodle.rs:558-653); a revive exercises re-join."""
    mesh = LockstepMesh(N, CFG)
    st = init_state(N)
    plan = []
    for i in range(20):
        kill = np.zeros(N, bool)
        revive = np.zeros(N, bool)
        if i == 4:
            kill[2] = True
            kill[7] = True
        if i == 14:
            revive[2] = True
        plan.append(_inputs(N, kill=kill, revive=revive))
    _run_parity(mesh, st, plan)


def test_drop_mask_parity():
    """Random (but fixed, shared) delivery-drop masks each tick."""
    rng = np.random.default_rng(42)
    mesh = LockstepMesh(N, CFG)
    st = init_state(N)
    plan = [_inputs(N, drop_ok=rng.random((N, N)) > 0.25) for _ in range(15)]
    _run_parity(mesh, st, plan)


def test_partition_heal_parity():
    """Split-brain then heal (BASELINE config 5 dynamics): two groups converge
    independently, then re-merge after the partition lifts."""
    mesh = LockstepMesh(N, CFG)
    st = init_state(N)
    part = np.zeros(N, np.int32)
    part[N // 2 :] = 1
    plan = []
    for i in range(24):
        kill = np.zeros(N, bool)
        if i == 6:
            kill[1] = True  # churn inside a partition
        plan.append(_inputs(N, partition=part if 2 <= i < 12 else None, kill=kill))
    _run_parity(mesh, st, plan)


def test_manual_ping_parity():
    """ping_addrs (lib.rs:268-297): manual pings mark + ack without state
    transitions at the sender."""
    mesh = LockstepMesh(N, CFG)
    st = init_state(N)
    plan = []
    for i in range(8):
        manual = np.full(N, -1, np.int64)
        if i == 2:
            manual[0] = 5
            manual[3] = 0
        plan.append(_inputs(N, manual=manual))
    _run_parity(mesh, st, plan)


def test_kernel_determinism():
    """Same seed => bitwise-identical trajectory (SURVEY.md §5: the pure-
    functional kernel's answer to race detection)."""
    tick_fn = jax.jit(make_tick_fn(SwimConfig(), faulty=False))
    outs = []
    for _ in range(2):
        st = init_state(N, seed=7)
        inp = _inputs(N)
        inp = TickInputs(
            kill=inp.kill, revive=inp.revive, partition=inp.partition,
            drop_rate=inp.drop_rate, manual_target=inp.manual_target, drop_ok=None,
        )
        for _ in range(6):
            st, _m = tick_fn(st, inp)
        outs.append((np.asarray(st.state), np.asarray(st.timer)))
    np.testing.assert_array_equal(outs[0][0], outs[1][0])
    np.testing.assert_array_equal(outs[0][1], outs[1][1])


def test_random_mode_converges():
    """Random mode (jax.random draws): boot converges and stays converged."""
    tick_fn = jax.jit(make_tick_fn(SwimConfig(), faulty=False))
    st = init_state(32, seed=3)
    inp = TickInputs(
        kill=jnp.zeros(32, bool), revive=jnp.zeros(32, bool),
        partition=jnp.zeros(32, jnp.int32), drop_rate=jnp.float32(0),
        manual_target=jnp.full(32, -1, jnp.int32), drop_ok=None,
    )
    converged_at = None
    for i in range(12):
        st, m = tick_fn(st, inp)
        if bool(m.converged) and converged_at is None:
            converged_at = i
    assert converged_at is not None and converged_at <= 3
    assert bool(m.converged)


def test_intended_failed_broadcast_parity():
    """faithful_failed_broadcast=False (intended SWIM semantics): Failed
    broadcasts actually remove peers, so removal propagates mesh-wide the
    tick the first suspector gives up — including the Join-vs-Failed
    same-tick ordering race (broadcasts resolve in origin order)."""
    cfg = SwimConfig(deterministic=True, faithful_failed_broadcast=False)
    mesh = LockstepMesh(N, cfg)
    st = init_state(N)
    plan = []
    for i in range(22):
        kill = np.zeros(N, bool)
        revive = np.zeros(N, bool)
        if i == 3:
            kill[5] = True
        if i == 9:
            revive[5] = True  # likely to collide with a straggler's Failed(5)
        plan.append(_inputs(N, kill=kill, revive=revive))
    _run_parity(mesh, st, plan, cfg=cfg)


def test_manual_self_ping_dropped():
    """D8: manual self-pings are dropped at the transport in both engines."""
    mesh = LockstepMesh(N, CFG)
    st = init_state(N)
    manual = np.full(N, -1, np.int64)
    manual[4] = 4  # self-ping: must be a no-op
    plan = [_inputs(N, manual=manual if i == 1 else None) for i in range(4)]
    _run_parity(mesh, st, plan)


def test_manual_ping_out_of_range_dropped():
    """An out-of-range manual target (dest >= N) is dropped at the transport,
    like the oracle's ``0 <= dest < n`` guard — clamped gathers must not fake
    an exchange with peer N-1."""
    mesh = LockstepMesh(N, CFG)
    st = init_state(N)
    manual = np.full(N, -1, np.int64)
    manual[0] = N  # out of range: must be a no-op
    manual[2] = N + 7
    plan = [_inputs(N, manual=manual if i == 1 else None) for i in range(4)]
    _run_parity(mesh, st, plan)
