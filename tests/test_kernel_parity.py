"""Exact trajectory parity: JAX tick kernel vs the lockstep oracle.

In deterministic mode (SwimConfig.deterministic=True) every random draw is
replaced by a fixed rule both engines implement identically, so the kernel
must reproduce the oracle's full state — state codes, timers, fingerprints,
convergence flag, and delivered-message counts — every tick, including under
churn, message drops, and partitions. This is the simulator's analogue of the
reference's (absent) test suite: the state machine transition table of
SURVEY.md §3.2-3.3 pinned as data.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kaboodle_tpu.config import SwimConfig
from kaboodle_tpu.oracle.lockstep import LockstepMesh
from kaboodle_tpu.sim.kernel import make_tick_fn
from kaboodle_tpu.sim.state import MeshState, TickInputs, init_state

N = 12
CFG = SwimConfig(deterministic=True)


def _inputs(n, kill=None, revive=None, partition=None, drop_ok=None, manual=None):
    return TickInputs(
        kill=jnp.zeros(n, bool) if kill is None else jnp.asarray(kill, bool),
        revive=jnp.zeros(n, bool) if revive is None else jnp.asarray(revive, bool),
        partition=jnp.zeros(n, jnp.int32) if partition is None else jnp.asarray(partition, jnp.int32),
        drop_rate=jnp.float32(0.0),
        manual_target=jnp.full(n, -1, jnp.int32) if manual is None else jnp.asarray(manual, jnp.int32),
        drop_ok=jnp.ones((n, n), bool) if drop_ok is None else jnp.asarray(drop_ok, bool),
    )


def _assert_tick_equal(mesh: LockstepMesh, st: MeshState, metrics, tick: int):
    np.testing.assert_array_equal(
        np.asarray(st.state), mesh.state_matrix(), err_msg=f"state mismatch at tick {tick}"
    )
    # Timers only matter where a state exists.
    ours = np.asarray(st.timer) * (np.asarray(st.state) > 0)
    theirs = mesh.timer_matrix() * (mesh.state_matrix() > 0)
    np.testing.assert_array_equal(ours, theirs, err_msg=f"timer mismatch at tick {tick}")
    alive = np.asarray(st.alive)
    fps = np.array(mesh.fingerprints(), dtype=np.uint64) & 0xFFFFFFFF
    from kaboodle_tpu.ops.hashing import membership_fingerprint

    kfp = np.asarray(membership_fingerprint(st.state > 0, st.identity), dtype=np.uint64)
    np.testing.assert_array_equal(
        kfp[alive], fps[alive], err_msg=f"fingerprint mismatch at tick {tick}"
    )
    assert bool(metrics.converged) == mesh.converged(), f"convergence flag at tick {tick}"
    assert int(metrics.messages_delivered) == mesh.last_tick_messages, (
        f"message count at tick {tick}: kernel {int(metrics.messages_delivered)} "
        f"vs oracle {mesh.last_tick_messages}"
    )


def _run_parity(mesh: LockstepMesh, st: MeshState, inputs_per_tick, cfg=CFG):
    tick_fn = jax.jit(make_tick_fn(cfg, faulty=True))
    for i, inp in enumerate(inputs_per_tick):
        kill = np.asarray(inp.kill)
        revive = np.asarray(inp.revive)
        for p in np.nonzero(kill)[0]:
            mesh.kill(int(p))
        for p in np.nonzero(revive)[0]:
            mesh.revive(int(p))
        manual = np.asarray(inp.manual_target)
        for p in np.nonzero(manual >= 0)[0]:
            mesh.engines[p].pending_manual_pings.append(int(manual[p]))
        dok = np.asarray(inp.drop_ok)
        part = np.asarray(inp.partition)
        mesh.delivery_ok = lambda s, r, t, dok=dok, part=part: bool(
            dok[s, r] and part[s] == part[r]
        )
        mesh.tick()
        st, metrics = tick_fn(st, inp)
        _assert_tick_equal(mesh, st, metrics, i)
    return st


def test_fresh_boot_parity():
    """Boot N peers knowing only themselves; converge via Join broadcasts +
    anti-entropy (BASELINE config 2 dynamics)."""
    mesh = LockstepMesh(N, CFG)
    st = init_state(N)
    _run_parity(mesh, st, [_inputs(N) for _ in range(12)])


def test_churn_parity():
    """Silent kills exercise the WaitingForPing -> indirect-ping -> removal
    path (kaboodle.rs:558-653); a revive exercises re-join."""
    mesh = LockstepMesh(N, CFG)
    st = init_state(N)
    plan = []
    for i in range(20):
        kill = np.zeros(N, bool)
        revive = np.zeros(N, bool)
        if i == 4:
            kill[2] = True
            kill[7] = True
        if i == 14:
            revive[2] = True
        plan.append(_inputs(N, kill=kill, revive=revive))
    _run_parity(mesh, st, plan)


def test_drop_mask_parity():
    """Random (but fixed, shared) delivery-drop masks each tick."""
    rng = np.random.default_rng(42)
    mesh = LockstepMesh(N, CFG)
    st = init_state(N)
    plan = [_inputs(N, drop_ok=rng.random((N, N)) > 0.25) for _ in range(15)]
    _run_parity(mesh, st, plan)


def test_partition_heal_parity():
    """Split-brain then heal (BASELINE config 5 dynamics): two groups converge
    independently, then re-merge after the partition lifts."""
    mesh = LockstepMesh(N, CFG)
    st = init_state(N)
    part = np.zeros(N, np.int32)
    part[N // 2 :] = 1
    plan = []
    for i in range(24):
        kill = np.zeros(N, bool)
        if i == 6:
            kill[1] = True  # churn inside a partition
        plan.append(_inputs(N, partition=part if 2 <= i < 12 else None, kill=kill))
    _run_parity(mesh, st, plan)


def test_manual_ping_parity():
    """ping_addrs (lib.rs:268-297): manual pings mark + ack without state
    transitions at the sender."""
    mesh = LockstepMesh(N, CFG)
    st = init_state(N)
    plan = []
    for i in range(8):
        manual = np.full(N, -1, np.int64)
        if i == 2:
            manual[0] = 5
            manual[3] = 0
        plan.append(_inputs(N, manual=manual))
    _run_parity(mesh, st, plan)


def test_kernel_determinism():
    """Same seed => bitwise-identical trajectory (SURVEY.md §5: the pure-
    functional kernel's answer to race detection)."""
    tick_fn = jax.jit(make_tick_fn(SwimConfig(), faulty=False))
    outs = []
    for _ in range(2):
        st = init_state(N, seed=7)
        inp = _inputs(N)
        inp = TickInputs(
            kill=inp.kill, revive=inp.revive, partition=inp.partition,
            drop_rate=inp.drop_rate, manual_target=inp.manual_target, drop_ok=None,
        )
        for _ in range(6):
            st, _m = tick_fn(st, inp)
        outs.append((np.asarray(st.state), np.asarray(st.timer)))
    np.testing.assert_array_equal(outs[0][0], outs[1][0])
    np.testing.assert_array_equal(outs[0][1], outs[1][1])


def test_random_mode_converges():
    """Random mode (jax.random draws): boot converges and stays converged."""
    tick_fn = jax.jit(make_tick_fn(SwimConfig(), faulty=False))
    st = init_state(32, seed=3)
    inp = TickInputs(
        kill=jnp.zeros(32, bool), revive=jnp.zeros(32, bool),
        partition=jnp.zeros(32, jnp.int32), drop_rate=jnp.float32(0),
        manual_target=jnp.full(32, -1, jnp.int32), drop_ok=None,
    )
    converged_at = None
    for i in range(12):
        st, m = tick_fn(st, inp)
        if bool(m.converged) and converged_at is None:
            converged_at = i
    assert converged_at is not None and converged_at <= 3
    assert bool(m.converged)


def test_intended_failed_broadcast_parity():
    """faithful_failed_broadcast=False (intended SWIM semantics): Failed
    broadcasts actually remove peers, so removal propagates mesh-wide the
    tick the first suspector gives up — including the Join-vs-Failed
    same-tick ordering race (broadcasts resolve in origin order)."""
    cfg = SwimConfig(deterministic=True, faithful_failed_broadcast=False)
    mesh = LockstepMesh(N, cfg)
    st = init_state(N)
    plan = []
    for i in range(22):
        kill = np.zeros(N, bool)
        revive = np.zeros(N, bool)
        if i == 3:
            kill[5] = True
        if i == 9:
            revive[5] = True  # likely to collide with a straggler's Failed(5)
        plan.append(_inputs(N, kill=kill, revive=revive))
    _run_parity(mesh, st, plan, cfg=cfg)


def test_int16_timer_parity():
    """timer_dtype=int16 (the lean-memory mode, MEMORY_PLAN.md): bit-identical
    trajectory vs the oracle through churn + revive — exercises every timer
    write class (marks, waiting stamps, Q6 negative back-dating, revive
    reset) in the narrow dtype, plus the TMAX sentinel reduction."""
    mesh = LockstepMesh(N, CFG)
    st = init_state(N, timer_dtype=jnp.int16)
    assert st.timer.dtype == jnp.int16
    plan = []
    for i in range(20):
        kill = np.zeros(N, bool)
        revive = np.zeros(N, bool)
        if i == 4:
            kill[2] = True
            kill[7] = True
        if i == 14:
            revive[2] = True
        plan.append(_inputs(N, kill=kill, revive=revive))
    final = _run_parity(mesh, st, plan)
    assert final.timer.dtype == jnp.int16


def test_gossip_boot_parity():
    """Gossip boot (join_broadcast_enabled=False + ring seed contacts):
    membership spreads only via pings + anti-entropy pulls
    (kaboodle.rs:707-740) — no broadcast medium. Exact per-tick parity."""
    cfg = SwimConfig(deterministic=True, join_broadcast_enabled=False)
    mesh = LockstepMesh(N, cfg, ring_contacts=2)
    st = init_state(N, ring_contacts=2)
    _run_parity(mesh, st, [_inputs(N) for _ in range(24)], cfg=cfg)


def test_gossip_boot_churn_parity():
    """Gossip boot under churn: a silent kill must still be detected and
    removed with no broadcast path anywhere."""
    cfg = SwimConfig(deterministic=True, join_broadcast_enabled=False)
    mesh = LockstepMesh(N, cfg, ring_contacts=2)
    st = init_state(N, ring_contacts=2)
    plan = []
    for i in range(20):
        kill = np.zeros(N, bool)
        if i == 6:
            kill[4] = True
        plan.append(_inputs(N, kill=kill))
    _run_parity(mesh, st, plan, cfg=cfg)


def test_epidemic_boot_parity():
    """backdate_gossip_inserts=False (the epidemic-boot extension): learned
    peers re-share immediately, so a broadcast-free boot converges in
    ~O(log N) ticks instead of ~O(N). Exact per-tick parity, and the
    speedup is visible at N=12 already."""
    cfg = SwimConfig(
        deterministic=True,
        join_broadcast_enabled=False,
        backdate_gossip_inserts=False,
    )
    mesh = LockstepMesh(N, cfg, ring_contacts=2)
    st = init_state(N, ring_contacts=2)
    _run_parity(mesh, st, [_inputs(N) for _ in range(10)], cfg=cfg)
    assert mesh.converged(), "epidemic boot should converge within 10 ticks at N=12"


@pytest.mark.slow
def test_epidemic_boot_scales_logarithmically():
    """Convergence ticks for the epidemic boot grow far slower than N —
    the whole point of the extension (random mode, ring seed)."""
    from kaboodle_tpu.sim.runner import run_until_converged

    cfg = SwimConfig(join_broadcast_enabled=False, backdate_gossip_inserts=False)
    ticks_at = {}
    for n in (64, 256):
        st = init_state(n, seed=0, ring_contacts=2)
        _, ticks, conv = run_until_converged(st, cfg, max_ticks=128)
        assert bool(conv), f"N={n} did not converge"
        ticks_at[n] = int(ticks)
    # 4x the peers must cost far less than 4x the ticks (O(N) would be ~4x;
    # allow generous slack above log2(4)=2x for protocol noise).
    assert ticks_at[256] < ticks_at[64] * 3, ticks_at


@pytest.mark.slow
def test_share_cap_parity():
    """D5: the join-response share cap (kernel.py share_base branch; the
    reference's 10 KiB trim, kaboodle.rs:373-383). An isolated peer joins
    late through a single reachable responder, so its bootstrap map is
    exactly that responder's capped share — observably different from the
    uncapped path."""
    n = 24
    cfg = SwimConfig(deterministic=True, max_share_peers=8)
    mesh = LockstepMesh(n, cfg)
    st = init_state(n)

    loner = n - 1
    # Ticks 0-9: loner fully isolated; the rest converge among themselves.
    iso = np.ones((n, n), bool)
    iso[loner, :] = False
    iso[:, loner] = False
    iso[loner, loner] = True
    # Tick 10 (the loner's lonely re-broadcast, rebroadcast_interval_ticks):
    # only the loner<->0 edges are up, so peer 0 is the sole join responder.
    one_edge = iso.copy()
    one_edge[loner, 0] = True
    one_edge[0, loner] = True
    plan = [_inputs(n, drop_ok=iso) for _ in range(10)]
    plan.append(_inputs(n, drop_ok=one_edge))
    st = _run_parity(mesh, st, plan, cfg=cfg)

    # The capped share really was capped: right after the join tick the loner
    # knows exactly itself plus the 8 lowest-index members of responder 0's
    # map (peers 0..7, responder included) — the uncapped path would have
    # given it all 24.
    row = np.asarray(st.state)[loner] > 0
    assert set(np.flatnonzero(row)) == set(range(8)) | {loner}

    # A few fully-open ticks after: parity continues to hold while the loner
    # refills via pings + anti-entropy pulls.
    _run_parity(mesh, st, [_inputs(n) for _ in range(4)], cfg=cfg)


def test_share_cap_inactive_at_small_n_parity():
    """With the cap above N the cap branch compiles out and boot parity
    still holds — guards the static `n > cap` gate itself."""
    cfg = SwimConfig(deterministic=True, max_share_peers=16)
    mesh = LockstepMesh(N, cfg)
    st = init_state(N)
    _run_parity(mesh, st, [_inputs(N) for _ in range(6)], cfg=cfg)


@pytest.mark.slow
def test_large_n_trajectory_parity():
    """N=256 trajectory check (VERDICT r2 item 5): per-tick fingerprints and
    membership counts against the oracle, broadcast boot with an active share
    cap (16 < N) so the D5 path runs at scale."""
    n, ticks = 256, 4
    cfg = SwimConfig(deterministic=True, max_share_peers=16)
    mesh = LockstepMesh(n, cfg)
    st = init_state(n)
    tick_fn = jax.jit(make_tick_fn(cfg, faulty=False))
    from kaboodle_tpu.ops.hashing import membership_fingerprint

    inp = TickInputs(
        kill=jnp.zeros(n, bool), revive=jnp.zeros(n, bool),
        partition=jnp.zeros(n, jnp.int32), drop_rate=jnp.float32(0),
        manual_target=jnp.full(n, -1, jnp.int32), drop_ok=None,
    )
    for t in range(ticks):
        mesh.tick()
        st, m = tick_fn(st, inp)
        kfp = np.asarray(
            membership_fingerprint(st.state > 0, st.identity), dtype=np.uint64
        )
        ofp = np.array(mesh.fingerprints(), dtype=np.uint64) & 0xFFFFFFFF
        np.testing.assert_array_equal(kfp, ofp, err_msg=f"fingerprints at tick {t}")
        kcount = np.asarray((np.asarray(st.state) > 0).sum(axis=1))
        ocount = np.array([e.num_peers() for e in mesh.engines])
        np.testing.assert_array_equal(kcount, ocount, err_msg=f"counts at tick {t}")
        assert bool(m.converged) == mesh.converged(), f"convergence at tick {t}"


def test_id_view_refreshed_on_anti_entropy_insert():
    """Regression: a row re-filled via a KnownPeersRequest reply must adopt
    real identity words in ``id_view``, not keep the revive-reset placeholder
    zeros — otherwise its id_view fingerprint (the kernel's convergence
    metric) can never agree with the mesh."""
    n = 16
    cfg = SwimConfig()
    tick_fn = jax.jit(make_tick_fn(cfg, faulty=True))
    st = init_state(n, seed=2)
    idle = _inputs(n)
    idle = TickInputs(kill=idle.kill, revive=idle.revive, partition=idle.partition,
                      drop_rate=idle.drop_rate, manual_target=idle.manual_target,
                      drop_ok=None)
    m = None
    for t in range(48):
        kill = jnp.zeros(n, bool).at[3].set(t == 2)
        revive = jnp.zeros(n, bool).at[3].set(t == 8)
        inp = TickInputs(kill=kill, revive=revive, partition=idle.partition,
                         drop_rate=idle.drop_rate, manual_target=idle.manual_target,
                         drop_ok=None)
        st, m = tick_fn(st, inp)
    assert bool(m.converged), "mesh never re-converged after revive"
    member = np.asarray(st.state) > 0
    idv = np.asarray(st.id_view)
    ident = np.asarray(st.identity)
    # Every member entry's identity view matches the true identity word.
    np.testing.assert_array_equal(
        np.where(member, idv, 0), np.where(member, ident[None, :], 0)
    )


def test_intended_indirect_ack_parity():
    """faithful_indirect_ack=False (SWIM-paper semantics, quirk Q11 off): a
    forwarded indirect-ping Ack clears the suspect's suspicion instead of
    only resurrecting the proxy. Churn triggers real escalations so the
    forwarded-ack clearing path actually runs in both engines."""
    cfg = SwimConfig(deterministic=True, faithful_indirect_ack=False)
    mesh = LockstepMesh(N, cfg)
    st = init_state(N)
    # Peer 3 is alive but its unicasts to peer 0 are always lost: 0
    # eventually pings 3, times out, escalates — and the proxies' forwarded
    # acks (3 is alive and answers them) clear the suspicion, the branch
    # unique to this mode. A kill exercises true-positive removal alongside.
    dok = np.ones((N, N), bool)
    dok[3, 0] = False
    plan = []
    for i in range(30):
        kill = np.zeros(N, bool)
        if i == 6:
            kill[8] = True
        plan.append(_inputs(N, kill=kill, drop_ok=dok))
    st = _run_parity(mesh, st, plan, cfg=cfg)
    # The false positive was indeed cleared, not removed: 0 still knows 3.
    assert np.asarray(st.state)[0, 3] > 0


def test_manual_self_ping_dropped():
    """D8: manual self-pings are dropped at the transport in both engines."""
    mesh = LockstepMesh(N, CFG)
    st = init_state(N)
    manual = np.full(N, -1, np.int64)
    manual[4] = 4  # self-ping: must be a no-op
    plan = [_inputs(N, manual=manual if i == 1 else None) for i in range(4)]
    _run_parity(mesh, st, plan)


def test_manual_ping_out_of_range_dropped():
    """An out-of-range manual target (dest >= N) is dropped at the transport,
    like the oracle's ``0 <= dest < n`` guard — clamped gathers must not fake
    an exchange with peer N-1."""
    mesh = LockstepMesh(N, CFG)
    st = init_state(N)
    manual = np.full(N, -1, np.int64)
    manual[0] = N  # out of range: must be a no-op
    manual[2] = N + 7
    plan = [_inputs(N, manual=manual if i == 1 else None) for i in range(4)]
    _run_parity(mesh, st, plan)
