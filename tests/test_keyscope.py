"""keyscope (kaboodle_tpu.analysis.rng) — provenance, rules, mutations.

The acceptance contract for the rng lane is mutation-tested, mirroring
the graftscan/graftconc harnesses: each seeded regression the ISSUE
names — (a) key_ping reused for the bern draw, (b) two STREAM_* ids
swapped, (c) a fresh PRNGKey(0) threaded into the sparse kernel past the
cursor — must turn the gate red through BOTH routes: in-process
``cli.main`` (registry traced live, so monkeypatches are visible) and the
``python -m kaboodle_tpu.analysis --rng`` subprocess CI actually runs
(textual mutations of a shadow package tree that wins the import path).
Unit coverage of the provenance engine runs on tiny synthetic jaxprs.
"""

from __future__ import annotations

import os
import pathlib
import shutil
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np

from kaboodle_tpu.analysis.cli import main
from kaboodle_tpu.analysis.rng import rules as rng_rules
from kaboodle_tpu.analysis.rng import scan as rng_scan
from kaboodle_tpu.analysis.rng.provenance import build_provenance
from kaboodle_tpu.phasegraph import ops as pg_ops

REPO = pathlib.Path(__file__).resolve().parent.parent


def _graph(fn, *args, name="test.fn"):
    return build_provenance(name, jax.make_jaxpr(fn)(*args))


def rules_of(findings) -> set[str]:
    return {f.rule for f in findings}


# ---------------------------------------------------------------------------
# satellite: the hoisted KEY_LAYOUT (phasegraph/ops.py)


def test_key_layout_pinned():
    assert pg_ops.KEY_LAYOUT == ("proxy", "ping", "bern", "drop", "next")
    assert (
        pg_ops.KEY_PROXY, pg_ops.KEY_PING, pg_ops.KEY_BERN,
        pg_ops.KEY_DROP, pg_ops.KEY_NEXT,
    ) == (0, 1, 2, 3, 4)


def test_split_tick_keys_matches_raw_split():
    key = jax.random.PRNGKey(7)
    ks = pg_ops.split_tick_keys(key)
    assert len(ks) == len(pg_ops.KEY_LAYOUT)
    np.testing.assert_array_equal(
        np.stack([np.asarray(k) for k in ks]),
        np.asarray(jax.random.split(key, 5)),
    )


# ---------------------------------------------------------------------------
# satellite: the pinned STREAM_* registry (sparseplane)


def test_stream_registry_pinned_and_exported():
    import kaboodle_tpu.phasegraph.rng as pg_rng
    import kaboodle_tpu.sparseplane as sp

    table = pg_rng.stream_table()
    # Double-entry bookkeeping: the live module and keyscope's own table
    # must agree entry-for-entry, in id order.
    assert list(table.items()) == list(rng_rules.KEYSCOPE_STREAMS)
    ids = list(table.values())
    assert ids == list(range(len(ids)))  # dense from 0, append-only order
    assert sp.STREAM_PROXY == 0
    assert sp.STREAM_GOSSIP == 5  # the sparse block; tick streams follow
    assert pg_rng.STREAM_TICK_PROXY == 6
    assert pg_rng.STREAM_TICK_DROP == len(ids) - 1
    # The sparseplane shim re-exports the canonical module verbatim.
    assert sp.stream_table() == table
    assert rng_rules.check_kb602_stream_registry() == []


def test_stream_registry_drift_detected(monkeypatch):
    import kaboodle_tpu.phasegraph.rng as pg_rng

    monkeypatch.setattr(pg_rng, "STREAM_PING", pg_rng.STREAM_ACK)
    findings = rng_rules.check_kb602_stream_registry()
    assert "KB602" in rules_of(findings)


# ---------------------------------------------------------------------------
# provenance engine — synthetic programs


def test_dense_chain_rows_and_classes():
    def f(key):
        kp, kq = jax.random.split(key, 2)
        return jax.random.uniform(kp, (4,)) + jax.random.uniform(kq, (4,))

    g = _graph(f, jax.random.PRNGKey(0))
    assert sorted(s.descr() for s in g.sinks) == [
        "carried_key/split2[0]",
        "carried_key/split2[1]",
    ]
    assert all(rng_rules.classify(s) == rng_rules.CLASS_CHAIN for s in g.sinks)
    assert rng_rules.check_kb601_key_reuse(g) == []


def test_counter_chain_classified_counter_keyed():
    def f(seed, cursor):
        k = jax.random.fold_in(jax.random.fold_in(jax.random.PRNGKey(seed), cursor), jnp.uint32(3))
        return jax.random.uniform(k, (4,))

    g = _graph(f, jnp.uint32(1), jnp.uint32(2))
    (sink,) = g.sinks
    assert sink.descr() == "counter_seed/fold[?]/fold[3]"
    assert rng_rules.classify(sink) == rng_rules.CLASS_COUNTER
    assert rng_rules.check_kb603_resume_impurity(g) == []


def test_kb601_same_key_drawn_twice():
    def f(key):
        return jax.random.uniform(key, (4,)) + jax.random.uniform(key, (4,))

    findings = rng_rules.check_kb601_key_reuse(_graph(f, jax.random.PRNGKey(0)))
    assert rules_of(findings) == {"KB601"}


def test_kb601_cond_branches_are_exclusive():
    # The dispatched dense build's shape: full and fused programs under one
    # lax.cond, both drawing the same key — mutually exclusive, NOT reuse.
    def f(pred, key):
        return jax.lax.cond(
            pred,
            lambda k: jax.random.uniform(k, (4,)),
            lambda k: jax.random.uniform(k, (4,)) * 2.0,
            key,
        )

    g = _graph(f, jnp.bool_(True), jax.random.PRNGKey(0))
    assert len(g.sinks) == 2
    assert rng_rules.check_kb601_key_reuse(g) == []


def test_kb601_loop_invariant_key_in_scan():
    def f(key):
        def body(c, _):
            return c + jnp.sum(jax.random.uniform(key, (4,))), None

        out, _ = jax.lax.scan(body, jnp.float32(0), None, length=3)
        return out

    findings = rng_rules.check_kb601_key_reuse(_graph(f, jax.random.PRNGKey(0)))
    assert rules_of(findings) == {"KB601"}
    assert any("loop-invariant" in f.message for f in findings)


def test_kb601_carried_key_in_scan_is_clean():
    # The span.py shape: split each iteration, draw one row, carry another.
    def f(key):
        def body(k, _):
            ks = jax.random.split(k, 5)
            return ks[4], jax.random.uniform(ks[1], (4,))

        _, ys = jax.lax.scan(body, key, None, length=3)
        return ys

    g = _graph(f, jax.random.PRNGKey(0))
    assert rng_rules.check_kb601_key_reuse(g) == []
    assert all(not s.looped for s in g.sinks)


def test_kb602_colliding_stream_constants():
    def f(seed, cursor):
        base = jax.random.fold_in(jax.random.PRNGKey(seed), cursor)
        a = jax.random.uniform(jax.random.fold_in(base, jnp.uint32(2)), (4,))
        base2 = jax.random.fold_in(jax.random.PRNGKey(seed), cursor)
        b = jax.random.uniform(jax.random.fold_in(base2, jnp.uint32(2)), (4,))
        return a + b

    findings = rng_rules.check_kb602_stream_collision(
        _graph(f, jnp.uint32(1), jnp.uint32(2))
    )
    assert "KB602" in rules_of(findings)
    assert any("collide" in f.symbol for f in findings)


def test_kb602_unregistered_stream_id():
    def f(seed, cursor):
        base = jax.random.fold_in(jax.random.PRNGKey(seed), cursor)
        return jax.random.uniform(jax.random.fold_in(base, jnp.uint32(77)), (4,))

    findings = rng_rules.check_kb602_stream_collision(
        _graph(f, jnp.uint32(1), jnp.uint32(2))
    )
    assert any(f.symbol == "unregistered:77" for f in findings)


def test_kb603_const_seed_draw():
    def f(x):
        return x + jax.random.uniform(jax.random.PRNGKey(0), (4,))

    findings = rng_rules.check_kb603_resume_impurity(
        _graph(f, jnp.zeros((4,), jnp.float32))
    )
    assert rules_of(findings) == {"KB603"}


def test_kb604_group_divergence(monkeypatch):
    def one(key):
        kp, _ = jax.random.split(key, 2)
        return jax.random.uniform(kp, (4,))

    def other(key):
        kp, kq = jax.random.split(key, 2)
        return jax.random.uniform(kp, (4,)) + jax.random.uniform(kq, (4,))

    graphs = {
        "eng.a": _graph(one, jax.random.PRNGKey(0), name="eng.a"),
        "eng.b": _graph(other, jax.random.PRNGKey(0), name="eng.b"),
    }
    monkeypatch.setattr(
        rng_rules, "CHAIN_GROUPS", (("pair", ("eng.a", "eng.b")),)
    )
    findings = rng_rules.check_kb604_chain_divergence(graphs)
    assert rules_of(findings) == {"KB604"}
    # A scoped scan with one member present skips the group.
    assert rng_rules.check_kb604_chain_divergence({"eng.a": graphs["eng.a"]}) == []


# ---------------------------------------------------------------------------
# the leap report


def _toy_graphs():
    def dense(key):
        ks = jax.random.split(key, 5)
        return jax.random.uniform(ks[1], (4,))

    def sparse(seed, cursor):
        base = jax.random.fold_in(jax.random.PRNGKey(seed), cursor)
        return jax.random.uniform(jax.random.fold_in(base, jnp.uint32(3)), (4,))

    return {
        "toy.dense": _graph(dense, jax.random.PRNGKey(0), name="toy.dense"),
        "toy.sparse": _graph(sparse, jnp.uint32(1), jnp.uint32(2), name="toy.sparse"),
    }


def test_leap_report_classifies_and_is_deterministic():
    graphs = _toy_graphs()
    r1 = rng_scan.build_leap_report(graphs)
    r2 = rng_scan.build_leap_report(graphs)
    assert r1 == r2  # byte-deterministic: CI diffs the committed copy
    assert r1["schema"] == rng_scan.LEAP_SCHEMA
    dense = r1["entries"]["toy.dense"]
    assert dense["chain_coupled"] == 1 and dense["counter_keyed"] == 0
    (sink,) = dense["sinks"]
    assert sink["layout_row"] == "ping"
    assert sink["warp_terms"] == ["probe_draw"]
    sparse = r1["entries"]["toy.sparse"]
    assert sparse["counter_keyed"] == 1 and sparse["chain_coupled"] == 0
    assert r1["totals"]["chain_coupled_draw_bytes"] == 16  # f32[4] ping draw


def test_leap_findings_missing_and_stale(tmp_path):
    graphs = _toy_graphs()
    path = tmp_path / "LEAP.json"
    missing = rng_scan.leap_findings(graphs, path)
    assert [f.symbol for f in missing] == ["missing"]

    rng_scan.write_leap_report(rng_scan.build_leap_report(graphs), path)
    assert rng_scan.leap_findings(graphs, path) == []

    del graphs["toy.sparse"]
    stale = rng_scan.leap_findings(graphs, path)
    assert [f.symbol for f in stale] == ["stale"]
    assert all(f.rule == "KB605" for f in stale)


def test_render_leap_report_names_chain_sites():
    text = rng_scan.render_leap_report(rng_scan.build_leap_report(_toy_graphs()))
    assert "chain-coupled sites" in text
    assert "row=ping" in text
    assert "probe_draw" in text


def test_committed_leap_report_schema():
    committed = rng_scan.load_leap_report(REPO / "KEYSCOPE_LEAP.json")
    assert committed is not None
    assert committed["streams"] == dict(rng_rules.KEYSCOPE_STREAMS)
    # Warp 3.0 end state: the item-2 worklist is EMPTY — every engine draw
    # is a counter-keyed pure function of (key, tick, stream) or the sparse
    # (seed, cursor, stream) discipline, and the shrink gate keeps it so.
    assert committed["totals"]["chain_coupled"] == 0
    assert committed["totals"]["chain_coupled_draw_bytes"] == 0
    assert committed["totals"]["counter_keyed"] > 0
    assert committed["totals"]["impure"] == 0


def test_leap_findings_growth_gate(tmp_path):
    # Commit a chain-free report, then grow a chain-coupled sink: the
    # ratchet reds with a dedicated "growth" finding alongside staleness.
    graphs = _toy_graphs()
    sparse_only = {"toy.sparse": graphs["toy.sparse"]}
    path = tmp_path / "LEAP.json"
    rng_scan.write_leap_report(rng_scan.build_leap_report(sparse_only), path)
    assert rng_scan.leap_findings(sparse_only, path) == []
    grown = rng_scan.leap_findings(graphs, path)
    assert [f.symbol for f in grown] == ["growth", "stale"]
    assert all(f.rule == "KB605" for f in grown)
    assert any("chain-coupled sink total grew 0 -> 1" in f.message
               for f in grown)


# ---------------------------------------------------------------------------
# CLI plumbing


def test_lane_flags_are_exclusive(capsys):
    assert main(["--ir", "--rng"]) == 2
    assert main(["--all", "--conc"]) == 2
    assert main(["--all", "--write-baseline"]) == 2
    capsys.readouterr()


def test_rng_subcommand_spelling(capsys):
    # `rng` as first arg == --rng, matching the `conc` subcommand.
    rc = main(["rng", "--entries", "ops.crc32", "--no-baseline"])
    capsys.readouterr()
    assert rc == 0


def test_explain_covers_every_lane(capsys):
    for rid in ("KB101", "KB401", "KB501", "KB601", "KB605"):
        assert main(["--explain", rid]) == 0
        out = capsys.readouterr().out
        assert out.startswith(rid)


# ---------------------------------------------------------------------------
# seeded mutation (a): key_ping reused for the bern draw — in-process route


def test_mutation_ping_reuse_red_inprocess(monkeypatch, capsys):
    import kaboodle_tpu.phasegraph.rng as pg_rng

    # Pristine first: the same scoped invocation is clean.
    assert main(["--rng", "--entries", "phasegraph.tick.random",
                 "--no-baseline"]) == 0
    capsys.readouterr()

    def reused(key, tick):  # bern <- ping (one counter row drawn twice)
        kp = pg_rng.tick_stream_key(key, tick, pg_rng.STREAM_TICK_PROXY)
        kping = pg_rng.tick_stream_key(key, tick, pg_rng.STREAM_TICK_PING)
        kd = pg_rng.tick_stream_key(key, tick, pg_rng.STREAM_TICK_DROP)
        return kp, kping, kping, kd

    monkeypatch.setattr(pg_rng, "tick_draw_keys", reused)
    rc = main(["--rng", "--entries", "phasegraph.tick.random", "--no-baseline"])
    out = capsys.readouterr().out
    assert rc == 1, out
    assert "KB601" in out


# ---------------------------------------------------------------------------
# seeded mutation (b): STREAM_* swap — in-process route


def test_mutation_stream_swap_red_inprocess(monkeypatch, capsys):
    import kaboodle_tpu.phasegraph.rng as pg_rng

    ping, ack = pg_rng.STREAM_PING, pg_rng.STREAM_ACK
    monkeypatch.setattr(pg_rng, "STREAM_PING", ack)
    monkeypatch.setattr(pg_rng, "STREAM_ACK", ping)
    # The swapped ids still trace collision-free (the set is unchanged) —
    # only the registry comparison, which runs on ANY scoped scan, reds.
    rc = main(["--rng", "--entries", "ops.crc32", "--no-baseline"])
    out = capsys.readouterr().out
    assert rc == 1, out
    assert "KB602" in out and "renumber" in out


# ---------------------------------------------------------------------------
# seeded mutation (c): PRNGKey(0) bypassing the cursor — in-process route


def test_mutation_const_key_red_inprocess(monkeypatch, capsys):
    import kaboodle_tpu.phasegraph.rng as pg_rng
    import kaboodle_tpu.sparseplane.rng as sprng

    # Both the canonical module (stream_uniform's resolution) and the
    # sparseplane shim (kernel.py's ``sprng.stream_key`` attr access).
    const = lambda seed, cursor, stream: jax.random.PRNGKey(0)  # noqa: E731
    monkeypatch.setattr(pg_rng, "stream_key", const)
    monkeypatch.setattr(sprng, "stream_key", const)
    rc = main(["--rng", "--entries", "phasegraph.tick.sparse", "--no-baseline"])
    out = capsys.readouterr().out
    assert rc == 1, out
    assert "KB603" in out


# ---------------------------------------------------------------------------
# the same three mutations through the subprocess route CI runs


def _copy_package(tmp_path) -> pathlib.Path:
    """Full kaboodle_tpu shadow copy that WINS the import path (unlike the
    conc harness's bare tree: the rng lane traces imported code, so the
    mutated modules must actually import)."""
    dst = tmp_path / "kaboodle_tpu"
    shutil.copytree(
        REPO / "kaboodle_tpu", dst,
        ignore=shutil.ignore_patterns("__pycache__"),
    )
    return dst


def _run_rng_subprocess(tmp_path, *extra):
    return subprocess.run(
        [sys.executable, "-m", "kaboodle_tpu.analysis", "--rng",
         "--no-baseline", *extra],
        cwd=tmp_path, capture_output=True, text=True,
        env={
            **os.environ,
            "PYTHONPATH": f"{tmp_path}{os.pathsep}{REPO}",
            "JAX_PLATFORMS": "cpu",
        },
    )


def _mutate(path: pathlib.Path, old: str, new: str) -> None:
    src = path.read_text()
    assert old in src, f"mutation anchor missing in {path.name}"
    path.write_text(src.replace(old, new, 1))


def test_mutation_ping_reuse_red_subprocess(tmp_path):
    dst = _copy_package(tmp_path)
    anchor = (
        "key_proxy, key_ping, key_bern, key_drop = "
        "pg_rng.tick_draw_keys(st.key, t)"
    )
    _mutate(dst / "phasegraph" / "exec.py", anchor,
            anchor + "\n        key_bern = key_ping")
    proc = _run_rng_subprocess(
        tmp_path, "--entries", "phasegraph.tick.random"
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "KB601" in proc.stdout


def test_mutation_stream_swap_red_subprocess(tmp_path):
    dst = _copy_package(tmp_path)
    rng_py = dst / "phasegraph" / "rng.py"
    _mutate(rng_py, "STREAM_PING = 3", "STREAM_PING = 4")
    _mutate(rng_py, "STREAM_ACK = 4", "STREAM_ACK = 3")
    proc = _run_rng_subprocess(tmp_path, "--entries", "ops.crc32")
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "KB602" in proc.stdout


def test_mutation_const_key_red_subprocess(tmp_path):
    dst = _copy_package(tmp_path)
    _mutate(
        dst / "phasegraph" / "rng.py",
        "    base = jax.random.fold_in(jax.random.PRNGKey(seed), cursor)\n"
        "    return jax.random.fold_in(base, jnp.uint32(stream))",
        "    return jax.random.PRNGKey(0)  # seeded KB603: cursor bypassed",
    )
    proc = _run_rng_subprocess(
        tmp_path, "--entries", "phasegraph.tick.sparse"
    )
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "KB603" in proc.stdout
