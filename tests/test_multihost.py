"""Multi-process mesh proof: the sharded tick over a real 2-process cluster.

The reference's distributed story is N OS processes exchanging UDP datagrams
(justfile run2x2); this framework's is one SPMD program over a device mesh
that may span hosts (DCN). Here the DCN case actually runs: two OS
processes x 4 virtual CPU devices each, joined by ``make_multihost_mesh``
(jax.distributed + gloo collectives), executing the identical sharded tick
program — the trajectory must match the single-process 8-device run exactly.
"""

import json
import os
import socket
import subprocess
import sys
from pathlib import Path

import numpy as np
import jax

from kaboodle_tpu.config import SwimConfig
from kaboodle_tpu.sim.runner import simulate
from kaboodle_tpu.sim.state import idle_inputs, init_state
import pytest

# Heavy end-to-end lanes (subprocess cluster / randomized fuzzing):
# excluded from `make test-quick`, always run in CI.
pytestmark = pytest.mark.slow

_WORKER = Path(__file__).resolve().parent.parent / "scripts" / "multihost_worker.py"
_N, _TICKS = 64, 8


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_mesh_matches_single_process():
    port = _free_port()
    procs = [
        subprocess.Popen(
            [sys.executable, str(_WORKER), str(pid), "2", str(port), str(_N), str(_TICKS)],
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
            cwd=str(_WORKER.parent.parent),
            env={**os.environ, "PYTHONPATH": str(_WORKER.parent.parent)},
        )
        for pid in range(2)
    ]
    digests = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=600)
            assert p.returncode == 0, f"worker failed:\n{err[-3000:]}"
            lines = [ln for ln in out.splitlines() if ln.startswith("MHDIGEST ")]
            assert lines, f"no digest in worker output:\n{out[-1000:]}\n{err[-1000:]}"
            digests.append(json.loads(lines[0][len("MHDIGEST "):]))
    finally:
        # A worker that failed (or we timed out on) leaves its peer blocked
        # inside a gloo collective waiting forever — reap both regardless.
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.communicate()

    a, b = digests
    assert a["n_global_devices"] == b["n_global_devices"] == 8
    for k in ("messages", "fp_min", "fp_max", "converged", "final_tick"):
        assert a[k] == b[k], f"cross-process divergence in {k}"

    # Single-process oracle of the same run (conftest provides 8 virtual
    # devices, but the unsharded path is the stronger independent pin).
    st = init_state(_N, seed=3, track_latency=False, instant_identity=True)
    _, m = simulate(st, idle_inputs(_N, ticks=_TICKS), SwimConfig(deterministic=True),
                    faulty=False)
    assert a["messages"] == np.asarray(m.messages_delivered).tolist()
    assert a["fp_min"] == np.asarray(m.fingerprint_min).tolist()
    assert a["fp_max"] == np.asarray(m.fingerprint_max).tolist()
    assert a["converged"] == np.asarray(m.converged).tolist()
    assert jax.process_count() == 1  # the cluster lived only in the workers
