"""Servescope (ISSUE 14): observability-plane units + engine contracts.

The plane's one hard promise: it OBSERVES. An engine with tracing +
profiler + metrics attached must end bit-identical to one without, must
never compile anything (``compiles_steady`` pinned to 0 across the full
admit/leap/park/spill/restore lifecycle), and every record it emits must
pass the manifest schema and render through the exporters.
"""

from __future__ import annotations

import json

import jax
import numpy as np
import pytest

from kaboodle_tpu.config import SwimConfig
from kaboodle_tpu.serve.engine import ServeEngine, ServeRequest
from kaboodle_tpu.serve.obsplane import (
    SEG_ADMIT,
    SEG_JOURNAL,
    SEG_ROUND,
    SEGMENTS,
    Histogram,
    MetricsRegistry,
    ObsPlane,
    RoundProfiler,
)
from kaboodle_tpu.serve.pool import LanePool

CFG = SwimConfig(deterministic=True)
N = 16  # shares test_serve.py's compiled set within the pytest process


@pytest.fixture(autouse=True)
def _conc_sanitizer():
    """Obsplane tests run sanitized too: the observability plane must not
    add locks in inconsistent order or block the loop (same bit-exactness
    spirit as the obs-on/off contract, applied to concurrency)."""
    from kaboodle_tpu.analysis.conc import sanitizer

    with sanitizer.enabled(loop_threshold_s=2.0):
        yield
        sanitizer.assert_clean()


def _pool(lanes: int = 3, **kw) -> LanePool:
    return LanePool(N, lanes, cfg=CFG, chunk=4, **kw)


def _leaves_equal(a, b) -> bool:
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    if len(la) != len(lb):
        return False
    for x, y in zip(la, lb):
        x, y = np.asarray(x), np.asarray(y)
        eq = np.issubdtype(x.dtype, np.floating)
        if not np.array_equal(x, y, equal_nan=eq):
            return False
    return True


# -- registry / histogram / profiler units ----------------------------------


def test_histogram_buckets_and_quantiles():
    h = Histogram()
    for us in (0, 1, 3, 100, 100, 100, 5000):
        h.observe(us)
    assert h.count == 7
    assert h.total_us == 5304
    assert h.max_us == 5000
    snap = h.snapshot()
    # log2 buckets: the p50 sample (100us) reports its bucket's upper
    # bound, 127 — factor-of-2 resolution is the documented contract.
    assert snap["p50_us"] == 127
    assert snap["p99_us"] >= 5000 // 2
    assert snap["mean_us"] == pytest.approx(5304 / 7, abs=0.1)


def test_registry_counters_gauges_prometheus():
    m = MetricsRegistry()
    m.inc("reqs_total", event="admitted")
    m.inc("reqs_total", event="admitted")
    m.inc("reqs_total", event="shed")
    m.register_gauge("depth", lambda: 7)
    m.register_multi_gauge(
        "tokens", lambda: {(("tenant", "a"),): 3.5, (("tenant", "b"),): 1.0}
    )
    h = m.histogram("lat_us", phase="run")
    h.observe(10)
    ext = Histogram()
    ext.observe(99)
    m.attach_histogram("seg_us", ext, segment="admit")

    snap = m.collect()
    assert snap["counters"]["reqs_total"]["event=admitted"] == 2
    assert snap["gauges"]["depth"][""] == 7.0
    assert snap["gauges"]["tokens"]["tenant=a"] == 3.5
    assert snap["histograms"]["lat_us"]["phase=run"]["count"] == 1
    # attach_histogram shares the object: later observes are visible.
    ext.observe(1)
    assert snap["histograms"]["seg_us"]["segment=admit"]["count"] == 1
    assert m.collect()["histograms"]["seg_us"]["segment=admit"]["count"] == 2

    text = m.to_prometheus()
    assert '# TYPE reqs_total counter' in text
    assert 'reqs_total{event="admitted"} 2' in text
    assert 'tokens{tenant="a"} 3.5' in text
    assert '# TYPE lat_us summary' in text
    assert 'lat_us_count{phase="run"} 1' in text


def test_round_profiler_accounting():
    p = RoundProfiler()
    p.round_begin()
    t = p.mark()
    t = p.lap(SEG_ADMIT, t)
    p.add_ns(SEG_JOURNAL, 5_000_000)  # 5 ms charged out of band
    p.round_end()
    assert p.rounds == 1
    assert int(p.last_us[SEG_JOURNAL]) == 5000
    assert int(p.last_us[SEG_ROUND]) >= 0
    segs = p.last_segments()
    assert set(segs) == set(SEGMENTS) - {"round"}
    assert p.hist[SEG_JOURNAL].count == 1
    assert p.totals_us()["journal"] == 5000


# -- span tracing units ------------------------------------------------------


def _fake_clock(start=0):
    box = {"t": start}

    def clock():
        return box["t"]

    return box, clock


def test_transition_opens_and_closes_spans():
    box, clock = _fake_clock()
    obs = ObsPlane(trace=True, clock_ns=clock)
    assert obs.transition(0, "queued", pool_n=16) is None  # nothing open
    box["t"] += 5_000_000  # +5 ms
    rec = obs.transition(0, "running", pool_n=16, lane=2)
    assert rec["kind"] == "serve_span"
    assert rec["span"] == "queued"
    assert rec["request_id"] == 0
    assert rec["t0_us"] == 0 and rec["dur_us"] == 5000
    box["t"] += 1_000_000
    rec = obs.transition(0, None, fate="completed", ticks_run=12)
    assert rec["span"] == "running"
    assert rec["lane"] == 2
    assert rec["fate"] == "completed" and rec["ticks_run"] == 12
    assert obs.transition(0, None) is None  # already terminal
    assert obs.flush_spans() == []


def test_flush_spans_marks_open():
    box, clock = _fake_clock()
    obs = ObsPlane(trace=True, clock_ns=clock)
    obs.transition(3, "spilled", pool_n=16)
    box["t"] += 2_000_000
    out = obs.flush_spans()
    assert len(out) == 1
    assert out[0]["span"] == "spilled" and out[0]["open"] is True
    assert out[0]["dur_us"] == 2000


def test_trace_off_is_inert():
    obs = ObsPlane(trace=False)
    assert obs.transition(0, "queued") is None
    assert obs.flush_spans() == []


def test_on_record_folds_counters():
    obs = ObsPlane(trace=False)
    obs.on_record({"kind": "serve_event", "event": "shed",
                   "tenant": "t1", "priority": 0})
    obs.on_record({"kind": "serve_event", "event": "rejected",
                   "tenant": "t2", "reason": "quota"})
    obs.on_record({"kind": "serve_event", "event": "spill_failed"})
    obs.on_record({"kind": "serve_round", "engine": "leap", "ticks": 40})
    c = obs.metrics.collect()["counters"]
    assert c["serve_shed_total"]["priority=0,tenant=t1"] == 1
    assert c["serve_rejected_total"]["reason=quota,tenant=t2"] == 1
    assert c["serve_spill_incidents_total"]["kind=spill_failed"] == 1
    assert c["serve_rounds_total"]["engine=leap"] == 1
    assert c["serve_ticks_total"]["engine=leap"] == 40


def test_serve_span_schema_validation():
    from kaboodle_tpu.telemetry.manifest import run_record, validate_record

    good = run_record("serve_span", span="queued", request_id=1,
                      t0_us=0, dur_us=5, pool_n=16, lane=0)
    validate_record(good)
    with pytest.raises(ValueError):
        validate_record(run_record("serve_span", span="", request_id=1,
                                   t0_us=0, dur_us=5))
    with pytest.raises(ValueError):
        validate_record(run_record("serve_span", span="queued",
                                   request_id=1, t0_us=0))


# -- journal seq/ts satellite ------------------------------------------------


def test_journal_seq_and_ts(tmp_path):
    from kaboodle_tpu.serve.journal import ServeJournal, read_journal_records

    j = ServeJournal(str(tmp_path))
    j.epoch_ns = 0
    j.append("submitted", 0, req={"n": 16})
    j.append("admitted", 0, lane=1)
    j.close()
    recs = read_journal_records(str(tmp_path))
    assert [r["seq"] for r in recs] == [0, 1]
    assert all(isinstance(r["ts_us"], int) and r["ts_us"] > 0 for r in recs)

    # Restart: the counter resumes past everything on disk.
    j2 = ServeJournal(str(tmp_path))
    j2.append("harvested", 0, event="completed")
    j2.close()
    recs = read_journal_records(str(tmp_path))
    assert [r["seq"] for r in recs] == [0, 1, 2]

    table, next_rid = ServeJournal(str(tmp_path)).replay()
    assert table[0]["seq"] == 2  # last transition's ordering metadata
    assert next_rid == 1


def test_journal_backcompat_pre_seq_records(tmp_path):
    """Old journals (no seq/ts) replay and export exactly as before."""
    from kaboodle_tpu.serve.journal import ServeJournal, read_journal_records

    wal = tmp_path / "wal.jsonl"
    wal.write_text(
        json.dumps({"op": "submitted", "rid": 4, "req": {"n": 16}}) + "\n"
        + json.dumps({"op": "admitted", "rid": 4, "lane": 0}) + "\n"
    )
    table, next_rid = ServeJournal(str(tmp_path)).replay()
    assert table[4]["op"] == "admitted"
    assert "seq" not in table[4] and next_rid == 5
    recs = read_journal_records(str(tmp_path))
    assert [r["op"] for r in recs] == ["submitted", "admitted"]  # file order
    # A post-upgrade journal on the same dir starts seq at 0 and appends
    # AFTER the old records; mixed files keep old-first order.
    j = ServeJournal(str(tmp_path))
    j.append("harvested", 4, event="completed")
    j.close()
    recs = read_journal_records(str(tmp_path))
    assert [r["op"] for r in recs][-1] == "harvested"


# -- exporters ---------------------------------------------------------------


def _span(rid, span, t0, dur, pool_n=N, lane=-1, **kw):
    from kaboodle_tpu.telemetry.manifest import run_record

    return run_record("serve_span", span=span, request_id=rid, t0_us=t0,
                      dur_us=dur, pool_n=pool_n, lane=lane, **kw)


def test_serve_trace_events_layout():
    from kaboodle_tpu.telemetry.trace import serve_trace_events

    records = [
        _span(0, "queued", 0, 100),
        _span(0, "running", 100, 900, lane=1),
        _span(-1, "round", 0, 1000, pool_n=-1, round=0,
              segments={"admit": 40, "dispatch": 800}),
        _span(-1, "advance", 120, 500, round=0, engine="leap", bucket=32,
              classes=[{"lane": 1, "k": 32, "mode": "leap",
                        "class_key": 0, "terms": []}]),
    ]
    events = serve_trace_events(records, pid_base=10)
    by_name = {e["name"]: e for e in events}
    assert by_name["r0:queued"]["tid"] == 1  # off-lane -> queue track
    assert by_name["r0:running"]["tid"] == 3  # lane 1 -> tid lane+2
    assert by_name["r0:running"]["pid"] == 11  # first pool pid
    assert by_name["round 0"]["pid"] == 10
    assert by_name["leap x32 [0]"]["tid"] == 3  # fanned onto lane 1
    # segment sub-slices laid out from round t0 in order
    assert by_name["admit"]["ts"] == 0 and by_name["dispatch"]["ts"] == 40


def test_journal_trace_events_order_and_skip():
    from kaboodle_tpu.telemetry.trace import journal_trace_events

    events = journal_trace_events([
        {"op": "admitted", "rid": 0, "seq": 1, "ts_us": 20},
        {"op": "submitted", "rid": 0, "seq": 0, "ts_us": 10},
        {"op": "legacy", "rid": 9},  # pre-seq: no timestamp, skipped
    ])
    inst = [e for e in events if e["ph"] == "i"]
    assert [e["ts"] for e in inst] == [10, 20]  # seq order
    assert len(inst) == 2


def test_serve_report_waterfall():
    from kaboodle_tpu.telemetry.summary import serve_report

    report = serve_report([
        _span(0, "queued", 0, 100),
        _span(0, "running", 100, 900, lane=0, fate="completed",
              ticks_run=40),
        _span(1, "queued", 50, 500),
        _span(1, "running", 550, 200, lane=1, fate="shed"),
    ])
    assert report["requests"][0]["total_us"] == 1000
    assert report["requests"][0]["fate"] == "completed"
    assert report["requests"][1]["fate"] == "shed"
    assert report["phases"]["queued"]["count"] == 2
    assert report["phases"]["queued"]["total_us"] == 600
    assert report["e2e"]["count"] == 2
    assert report["e2e"]["max_us"] == 1000


# -- engine contracts --------------------------------------------------------


def test_compiles_steady_zero_across_lifecycle(tmp_path):
    """The metrics-plane pin: compiles_steady reads 0 over the FULL traced
    lifecycle — admit, leap (warp), chunk, park, spill, restore, resume —
    and the plane's gauge agrees with an outer KB405 counter."""
    from kaboodle_tpu.analysis.ir.surface import compile_counter

    recs: list[dict] = []
    engine = ServeEngine(
        [_pool(lanes=3)], warp=True, max_leap=16,
        spill_after=1, spill_dir=str(tmp_path), obs=True,
    )
    engine.on_event = recs.append
    engine.warmup()
    with compile_counter() as box:
        kept = engine.submit(ServeRequest(n=N, seed=1, mode="ticks",
                                          ticks=40, scenario="steady",
                                          keep=True))
        conv = engine.submit(ServeRequest(n=N, seed=2, mode="converge",
                                          ticks=40))
        for _ in range(120):
            engine.step()
            engine.settle_spills()  # join the async writer, fold results
            if engine.status(kept)["state"] == "spilled":
                break
        assert engine.status(kept)["state"] == "spilled"
        while engine.busy:
            engine.step()
        assert engine.status(conv)["state"] == "done"
        assert engine.restore(kept)
        engine.resume(kept, mode="ticks", ticks=4)
        while engine.busy:
            engine.step()
        gauges = engine.obs.metrics.collect()["gauges"]
    assert box.count == 0
    assert gauges["compiles_steady"][""] == 0.0
    spans = {r["span"] for r in recs if r["kind"] == "serve_span"}
    assert {"queued", "running", "parked", "spilling", "round",
            "advance"} <= spans
    leap = [r for r in recs if r.get("span") == "advance"
            and r.get("engine") == "leap"]
    assert leap and all("class_key" in c for r in leap
                        for c in r["classes"])
    engine.close()


def test_tracing_on_off_bit_identical():
    """Observer purity at the engine level: same scripted workload, obs
    on vs off, member state and host vectors end equal leaf-for-leaf."""
    def run(obs):
        engine = ServeEngine([_pool(lanes=2)], warp=True, max_leap=16,
                             obs=obs)
        engine.warmup()
        for i in range(4):
            engine.submit(ServeRequest(
                n=N, seed=i, mode="ticks" if i % 2 else "converge",
                ticks=16, scenario="steady" if i % 2 else "boot"))
        while engine.busy:
            engine.step()
        pool = engine.pools[N]
        host = {f: np.array(getattr(pool, f))
                for f in ("occupied", "active", "ticks_run", "conv_tick",
                          "remaining", "generation")}
        members = [pool.member(e) for e in range(pool.lanes)]
        results = {rid: row["result"]
                   for rid, row in engine._requests.items()}
        engine.close()
        return host, members, results

    host_a, mem_a, res_a = run(obs=False)
    host_b, mem_b, res_b = run(obs=True)
    assert res_a == res_b
    for f in host_a:
        assert np.array_equal(host_a[f], host_b[f]), f
    for a, b in zip(mem_a, mem_b):
        assert _leaves_equal(a, b)


def test_engine_binds_gauges_and_segments(tmp_path):
    """bind() wires live pull-gauges over engine state: queue depth, lane
    occupancy, journal lag, and the profiler's segment histograms."""
    engine = ServeEngine([_pool(lanes=2)], warp=False,
                         journal_dir=str(tmp_path / "j"), obs=True)
    engine.warmup()
    for seed in range(3):
        engine.submit(ServeRequest(n=N, seed=seed, mode="ticks", ticks=16,
                                   scenario="steady"))
    engine.step()
    snap = engine.obs.metrics.collect()
    g = snap["gauges"]
    assert g["serve_queue_depth"][""] == 1.0  # 2 lanes running, 1 queued
    assert g["serve_lanes_occupied"][f"pool={N}"] == 2.0
    assert g["serve_requests"]["state=running"] == 2.0
    assert g["serve_journal_lag_appends"][""] > 0
    segs = snap["histograms"]["serve_round_segment_us"]
    assert segs["segment=round"]["count"] == 1
    assert engine.obs.metrics.to_prometheus().startswith("# TYPE")
    while engine.busy:
        engine.step()
    engine.close()
    # close() is idempotent and detaches the compile listener.
    engine.obs.close()


def test_engine_exposes_why_dense_and_cache_gauges(tmp_path):
    """The costscope pull-gauges (ISSUE 15): the why-dense histogram and
    per-kind leap-cache hit rates surface through collect()/to_prometheus
    with one bind() wiring — the ledger is host-side, read lazily."""
    engine = ServeEngine([_pool(lanes=2)], warp=False,
                         journal_dir=str(tmp_path / "j"), obs=True)
    engine.warmup()
    # The serve loop records into engine.warp_ledger on leap->chunk
    # fallback; feed the ledger directly so the gauge read is pinned
    # regardless of which rounds this toy workload happens to take.
    engine.warp_ledger.record_blocked(None, 8, "serve")
    snap = engine.obs.metrics.collect()
    g = snap["gauges"]
    assert g["warp_blocked_ticks"]["term=scheduled_event"] == 8.0
    assert g["warp_blocked_spans"]["term=scheduled_event"] == 1.0
    # per-kind hit rates mirror the shared leap cache's stats() map.
    from kaboodle_tpu.warp.runner import leap_cache

    per_kind = leap_cache.stats()["per_kind"]
    rates = g.get("warp_leap_cache_hit_rate", {})
    assert set(rates) == {f"kind={k}" for k in per_kind}
    prom = engine.obs.metrics.to_prometheus()
    assert 'warp_blocked_ticks{term="scheduled_event"} 8' in prom
    engine.close()


def test_recover_emits_spans_in_seq_order(tmp_path):
    """Crash recovery replays the journal and re-opens spans for requeued
    and spilled requests, ordered by journal seq."""
    jdir = str(tmp_path / "j")
    engine = ServeEngine([_pool(lanes=2)], warp=False, journal_dir=jdir)
    engine.warmup()
    rids = [engine.submit(ServeRequest(n=N, seed=i, mode="ticks",
                                       ticks=32, scenario="steady"))
            for i in range(3)]
    engine.step()  # admit + first chunk; then "crash" (no close)
    engine._spiller and engine._spiller.close()

    recs: list[dict] = []
    fresh = ServeEngine([_pool(lanes=2)], warp=False, journal_dir=jdir,
                        obs=True)
    fresh.on_event = recs.append
    fresh.warmup()
    counts = fresh.recover()
    assert counts["requeued"] == len(rids)
    # recover opens queued spans; they close through admit/harvest below.
    while fresh.busy:
        fresh.step()
    assert all(fresh.status(r)["state"] == "done" for r in rids)
    fresh.close()
    flushed = [r for r in recs if r.get("kind") == "serve_span"]
    assert {r["request_id"] for r in flushed if r["span"] == "running"} \
        == set(rids)


def test_pool_occupancy_matches_stats():
    pool = _pool(lanes=3)
    pool.warmup()
    pool.admit(0, seed=0, until_conv=False, budget=8, scenario="steady")
    pool.admit(2, seed=1, until_conv=False, budget=8, scenario="steady")
    pool.park(2)
    occupied, active, lanes = pool.occupancy()
    assert (occupied, active, lanes) == (2, 1, 3)


def test_admission_snapshot():
    from kaboodle_tpu.serve.admission import AdmissionController

    ctl = AdmissionController(max_queue=8,
                              quotas={"t0": (10.0, 4.0)},
                              default_quota=(1.0, 2.0))
    ctl.check_quota("t0")
    ctl.check_quota("anon")
    snap = ctl.snapshot()
    assert snap["max_queue"] == 8
    assert snap["tenants"]["t0"]["rate"] == 10.0
    assert snap["tenants"]["t0"]["burst"] == 4.0
    assert snap["tenants"]["t0"]["tokens"] <= 4.0
    assert snap["tenants"]["anon"]["tokens"] <= 2.0


# -- loadgen satellite -------------------------------------------------------


def test_overload_breakdown_schema():
    """The --overload report's per-tenant / per-priority shed breakdown:
    run one tiny overload phase against a real bounded-queue server and
    check the buckets partition the aggregate counts."""
    import asyncio

    from kaboodle_tpu.serve.admission import AdmissionController
    from kaboodle_tpu.serve.client import ServeClient
    from kaboodle_tpu.serve.loadgen import _overload_phase
    from kaboodle_tpu.serve.server import ServeServer

    async def drive():
        engine = ServeEngine([_pool(lanes=2)], warp=True, max_leap=16,
                             admission=AdmissionController(max_queue=2))
        server = ServeServer(engine, port=0)
        engine.warmup()
        await server.start()

        async def client_factory():
            return await ServeClient.connect(port=server.port)

        phase = await _overload_phase(client_factory, server.port, N,
                                      rate=500.0, requests=12)
        probe = await client_factory()
        await probe.shutdown()
        await server.close()
        return phase

    phase = asyncio.run(drive())
    assert set(phase["by_tenant"]) == {"t0", "t1", "t2"}
    assert set(phase["by_priority"]) == {"0", "1", "2"}
    for dim in ("by_tenant", "by_priority"):
        assert sum(b["offered"] for b in phase[dim].values()) == 12
        assert sum(b["rejected"] for b in phase[dim].values()) \
            == phase["rejected"]
        assert sum(b["shed"] for b in phase[dim].values()) == phase["shed"]
        assert sum(b["completed"] for b in phase[dim].values()) \
            == phase["completed"]
        for b in phase[dim].values():
            assert 0.0 <= b["shed_rate"] <= 1.0
