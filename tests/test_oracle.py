"""Oracle engine + lockstep mesh: convergence, failure detection, quirks."""

import dataclasses

import numpy as np

from kaboodle_tpu.config import SwimConfig
from kaboodle_tpu.oracle import (
    Ack,
    Join,
    KnownPeersMsg,
    KnownPeersRequest,
    LockstepMesh,
    PeerEngine,
    Ping,
    PingRequest,
)
from kaboodle_tpu.spec import KNOWN, WAITING_FOR_INDIRECT_PING, WAITING_FOR_PING


def test_four_peer_convergence():
    """BASELINE config 1 analogue: 4 peers join and converge."""
    mesh = LockstepMesh(4)
    for _ in range(6):
        mesh.tick()
        if mesh.converged() and all(e.num_peers() == 4 for e in mesh.engines):
            break
    assert mesh.converged()
    assert all(e.num_peers() == 4 for e in mesh.engines)


def test_convergence_64_peers():
    mesh = LockstepMesh(64, seed=3)
    for _ in range(12):
        mesh.tick()
        if mesh.converged() and mesh.engines[0].num_peers() == 64:
            break
    assert mesh.converged()
    assert all(e.num_peers() == 64 for e in mesh.engines)


def test_fingerprint_matches_ops_kernel():
    """Oracle mix fingerprint must be bit-exact with the JAX reduction."""
    import jax.numpy as jnp

    from kaboodle_tpu.ops import membership_fingerprint

    mesh = LockstepMesh(16, seed=1)
    mesh.run(4)
    member = mesh.state_matrix() > 0
    ids = jnp.asarray(np.array(mesh.identities, dtype=np.uint32))
    kernel_fp = np.asarray(membership_fingerprint(jnp.asarray(member), ids))
    oracle_fp = np.array(mesh.fingerprints(), dtype=np.uint32)
    np.testing.assert_array_equal(kernel_fp, oracle_fp)


def test_failure_detection_and_reconvergence():
    """Silent leave (Q8) is detected via ping timeout -> indirect ping ->
    removal (kaboodle.rs:558-653), then fingerprints re-converge."""
    mesh = LockstepMesh(8, seed=2)
    mesh.run(8)
    assert mesh.converged()
    mesh.kill(5)
    for t in range(30):
        mesh.tick()
        gone = all(
            5 not in e.known for i, e in enumerate(mesh.engines) if mesh.alive[i]
        )
        if gone and mesh.converged():
            break
    assert gone
    assert mesh.converged()
    assert all(e.num_peers() == 7 for i, e in enumerate(mesh.engines) if mesh.alive[i])


def test_rejoin_after_failure():
    mesh = LockstepMesh(6, seed=4)
    mesh.run(6)
    mesh.kill(2)
    # Detection completeness bound is ~2N ticks (kaboodle.rs:656-660), plus
    # gossip echo can re-insert a removed peer until every direct entry ages
    # past MAX_PEER_SHARE_AGE (quirk Q6 stops re-sharing after that).
    for _ in range(40):
        mesh.tick()
        if all(2 not in e.known for i, e in enumerate(mesh.engines) if mesh.alive[i]):
            break
    assert all(2 not in e.known for i, e in enumerate(mesh.engines) if mesh.alive[i])
    mesh.revive(2)
    for _ in range(15):
        mesh.tick()
        if mesh.converged() and all(
            e.num_peers() == 6 for i, e in enumerate(mesh.engines) if mesh.alive[i]
        ):
            break
    assert mesh.converged()
    assert all(e.num_peers() == 6 for i, e in enumerate(mesh.engines) if mesh.alive[i])


def test_deterministic_mode_reproducible():
    cfg = SwimConfig(deterministic=True)
    a = LockstepMesh(12, cfg=cfg)
    b = LockstepMesh(12, cfg=cfg, seed=99)  # engine RNG seeds must not matter
    a.run(10)
    b.run(10)
    np.testing.assert_array_equal(a.state_matrix(), b.state_matrix())
    np.testing.assert_array_equal(a.timer_matrix(), b.timer_matrix())


def test_short_partition_heals():
    """A partition shorter than the removal pipeline heals: surviving
    cross-half entries get re-pinged, suspicion clears on the first inbound
    datagram (Q1), and anti-entropy repairs any divergence."""
    state = {"partitioned": False}

    def delivery_ok(s, r, t):
        if state["partitioned"]:
            return (s < 4) == (r < 4)
        return True

    mesh = LockstepMesh(8, delivery_ok=delivery_ok, seed=5)
    mesh.run(8)
    assert mesh.converged()
    state["partitioned"] = True
    mesh.run(3)  # shorter than WFP->WFI->removal (2 x ping_timeout)
    state["partitioned"] = False
    for _ in range(40):
        mesh.tick()
        if mesh.converged() and mesh.engines[0].num_peers() == 8:
            break
    assert mesh.converged()
    assert all(e.num_peers() == 8 for e in mesh.engines)


def test_long_partition_splits_permanently_until_new_join():
    """Faithful reference behavior: after both halves fully remove each other
    there is NO reconnection mechanism (Join rebroadcast requires loneliness,
    kaboodle.rs:228-251) — the meshes stay split until some peer (re)joins and
    its Join broadcast bridges them."""
    state = {"partitioned": True}

    def delivery_ok(s, r, t):
        if state["partitioned"]:
            return (s < 4) == (r < 4)
        return True

    mesh = LockstepMesh(8, delivery_ok=delivery_ok, seed=5)
    mesh.run(40)  # converge within halves; cross-half members fully expire
    assert {i for i in mesh.engines[0].known} == {0, 1, 2, 3}
    assert {i for i in mesh.engines[7].known} == {4, 5, 6, 7}
    state["partitioned"] = False
    mesh.run(20)
    # still split: no one is lonely, so no Join broadcasts fire
    assert {i for i in mesh.engines[0].known} == {0, 1, 2, 3}
    # a fresh join bridges the halves: everyone hears the broadcast
    mesh.kill(0)
    mesh.revive(0)
    for _ in range(60):
        mesh.tick()
        if mesh.converged() and mesh.engines[0].num_peers() == 8:
            break
    assert mesh.converged()
    assert all(e.num_peers() == 8 for e in mesh.engines)


# --- quirk-level unit tests --------------------------------------------------


def _engine(addr=0, cfg=None, **kw):
    return PeerEngine(addr, 100 + addr, cfg or SwimConfig(), now=0, **kw)


def test_q1_any_datagram_clears_suspicion():
    e = _engine(0)
    e.known[7] = dataclasses.replace(e.known[0], state=WAITING_FOR_PING, since=0)
    e.on_unicast(7, 107, Ping(), now=1)
    assert e.known[7].state == KNOWN
    assert e.known[7].since == 1


def test_q11_forwarded_ack_does_not_clear_suspect_faithful():
    """kaboodle.rs:408-415 + 417-447: the forwarded Ack resurrects the proxy
    (sender), not the suspect named inside the Ack."""
    e = _engine(0)
    e.known[5] = dataclasses.replace(e.known[0], state=WAITING_FOR_INDIRECT_PING, since=0)
    # proxy 3 forwards an ack about suspect 5
    e.on_unicast(3, 103, Ack(peer=5, mesh_fingerprint=1, num_peers=3), now=1)
    assert e.known[5].state == WAITING_FOR_INDIRECT_PING  # still suspected
    assert e.known[3].state == KNOWN  # proxy resurrected


def test_q11_intended_mode_clears_suspect():
    e = _engine(0, cfg=SwimConfig(faithful_indirect_ack=False))
    e.known[5] = dataclasses.replace(e.known[0], state=WAITING_FOR_INDIRECT_PING, since=0)
    e.on_unicast(3, 103, Ack(peer=5, mesh_fingerprint=1, num_peers=3), now=1)
    assert e.known[5].state == KNOWN


def test_q5_join_share_includes_self_no_age_filter():
    e = _engine(0)
    e.known[1] = dataclasses.replace(e.known[0], since=-100)  # ancient
    out = e.on_broadcast(None, Join(2, 102), now=0)
    assert len(out.unicasts) == 1
    dest, msg = out.unicasts[0]
    assert dest == 2
    shared = dict(msg.peers)
    assert 0 in shared and 1 in shared  # self included, no age filter


def test_kpr_reply_filters_age_self_requester():
    """kaboodle.rs:483-501: Known-state only, < MAX_PEER_SHARE_AGE, excludes
    self and requester."""
    e = _engine(0)
    now = 20
    e.known[1] = dataclasses.replace(e.known[0], state=KNOWN, since=now - 3)
    e.known[2] = dataclasses.replace(e.known[0], state=KNOWN, since=now - 15)  # too old
    e.known[3] = dataclasses.replace(e.known[0], state=WAITING_FOR_PING, since=now - 1)
    e.known[4] = dataclasses.replace(e.known[0], state=KNOWN, since=now - 1)
    out = e.on_unicast(4, 104, KnownPeersRequest(mesh_fingerprint=1, num_peers=9), now=now)
    (dest, msg), = out.unicasts
    assert dest == 4
    shared = dict(msg.peers)
    assert set(shared) == {1}  # not self(0), not stale(2), not suspected(3), not requester(4)


def test_q6_gossip_inserts_backdated():
    e = _engine(0)
    now = 30
    e.on_unicast(1, 101, KnownPeersMsg(((9, 109),)), now=now)
    assert e.known[9].since == now - SwimConfig().max_peer_share_age_ticks
    # ... so peer 9 is never re-shared in a KnownPeersRequest reply:
    out = e.on_unicast(2, 102, KnownPeersRequest(0, 1), now=now)
    (_, msg), = [u for u in out.unicasts if isinstance(u[1], KnownPeersMsg)]
    assert 9 not in dict(msg.peers)


def test_sync_request_fires_only_when_behind():
    """kaboodle.rs:707-740: KPR sent iff fingerprints differ and our map is
    not larger than theirs."""
    e = _engine(0)
    e.known[1] = dataclasses.replace(e.known[0], identity=101)
    fp = e.fingerprint()
    # same fingerprint -> no request
    e.on_unicast(1, 101, Ack(1, fp, 2), now=1)
    assert e.take_sync_request() is None
    # different fingerprint, they know more -> request
    e.on_unicast(1, 101, Ack(1, fp ^ 0xDEAD, 5), now=1)
    partner, req = e.take_sync_request()
    assert partner == 1 and req.num_peers == e.num_peers()
    # different fingerprint, we know more -> they should ask us
    e.on_unicast(1, 101, Ack(1, fp ^ 0xBEEF, 1), now=1)
    assert e.take_sync_request() is None


def test_pingrequest_relays_and_records_curious():
    e = _engine(2)
    out = e.on_unicast(0, 100, PingRequest(target=7), now=1)
    assert (7, Ping()) in [(d, m) for d, m in out.unicasts]
    assert e.curious[7] == [0]
    # target acks -> forward to requester
    out = e.on_unicast(7, 107, Ack(7, 42, 3), now=1)
    fwd = [(d, m) for d, m in out.unicasts if isinstance(m, Ack)]
    assert fwd == [(0, Ack(7, 42, 3, forwarded=True))]  # D7: relays are tagged
    assert 7 not in e.curious


def test_detection_latency_bounds():
    """Failure-detection latency: ~2-4 ticks after last contact for the peer
    that suspects first (BASELINE.md: 2 x PING_TIMEOUT within >= 1 tick each)."""
    cfg = SwimConfig(deterministic=True)
    mesh = LockstepMesh(3, cfg=cfg)
    mesh.run(5)
    assert mesh.converged()
    mesh.kill(2)
    t_kill = mesh.tick_count
    removed_at = None
    for _ in range(12):
        mesh.tick()
        if all(2 not in mesh.engines[i].known for i in (0, 1)):
            removed_at = mesh.tick_count
            break
    assert removed_at is not None
    # ping at t, escalate at t+2, remove at t+4 => within ~4-8 ticks of kill
    assert removed_at - t_kill <= 8
