"""Sharded-vs-unsharded equivalence on an 8-device virtual CPU mesh.

The sharding layer must be a pure layout change: the GSPMD-partitioned tick
(kaboodle_tpu.parallel) computes bit-identical integer state to the single-
device kernel, in both deterministic and random modes (all RNG draws derive
from the replicated key, so values do not depend on the partitioning). The
conftest forces ``--xla_force_host_platform_device_count=8`` — the supported
way to exercise pjit/shard_map programs without TPU hardware.
"""

import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from kaboodle_tpu.config import SwimConfig
from kaboodle_tpu.parallel import (
    PEER_AXIS,
    make_mesh,
    run_until_converged_sharded,
    shard_inputs,
    shard_state,
    simulate_sharded,
)
from kaboodle_tpu.sim.runner import run_until_converged, simulate
from kaboodle_tpu.sim.state import idle_inputs, init_state


@pytest.fixture(scope="module")
def mesh8():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    return make_mesh(8)


def _assert_states_equal(a, b):
    for name in ("state", "timer", "alive", "never_broadcast", "last_broadcast",
                 "kpr_partner", "kpr_fp", "kpr_n", "tick"):
        assert jnp.array_equal(getattr(a, name), getattr(b, name)), name
    for name in ("latency", "id_view"):
        va, vb = getattr(a, name), getattr(b, name)
        assert (va is None) == (vb is None), name
        if va is not None:
            assert jnp.array_equal(va, vb, equal_nan=True), name


@pytest.mark.parametrize("deterministic", [True, False])
@pytest.mark.slow
def test_sharded_simulate_matches_single_device(mesh8, deterministic):
    n, ticks = 32, 12
    cfg = SwimConfig(deterministic=deterministic)
    st = init_state(n, seed=3)
    inp = idle_inputs(n, ticks=ticks)

    ref_final, ref_m = simulate(st, inp, cfg, faulty=False)

    st_sh = shard_state(st, mesh8)
    inp_sh = shard_inputs(inp, mesh8, stacked=True)
    sh_final, sh_m = simulate_sharded(st_sh, inp_sh, cfg, mesh8, faulty=False)

    _assert_states_equal(ref_final, sh_final)
    assert jnp.array_equal(ref_m.converged, sh_m.converged)
    assert jnp.array_equal(ref_m.messages_delivered, sh_m.messages_delivered)
    assert jnp.array_equal(ref_m.fingerprint_min, sh_m.fingerprint_min)
    assert jnp.array_equal(ref_m.fingerprint_max, sh_m.fingerprint_max)


@pytest.mark.parametrize("track_latency", [True, False])
@pytest.mark.parametrize("instant_identity", [True, False])
@pytest.mark.slow
def test_sharded_optional_fields_all_combinations(mesh8, track_latency, instant_identity):
    """The optional [N, N] fields (latency, id_view) must shard as
    P('peers', None) when present and stay None when absent — in all four
    combinations the sharded trajectory equals the single-device one."""
    n, ticks = 16, 8
    cfg = SwimConfig()
    st = init_state(n, seed=9, track_latency=track_latency,
                    instant_identity=instant_identity)
    inp = idle_inputs(n, ticks=ticks)

    ref_final, _ = simulate(st, inp, cfg, faulty=False)

    st_sh = shard_state(st, mesh8)
    row_sharded = NamedSharding(mesh8, P(PEER_AXIS, None))
    if track_latency:
        assert st_sh.latency.sharding.is_equivalent_to(row_sharded, st_sh.latency.ndim)
    else:
        assert st_sh.latency is None
    if instant_identity:
        assert st_sh.id_view is None
    else:
        assert st_sh.id_view.sharding.is_equivalent_to(row_sharded, st_sh.id_view.ndim)

    sh_final, _ = simulate_sharded(
        st_sh, shard_inputs(inp, mesh8, stacked=True), cfg, mesh8, faulty=False
    )
    _assert_states_equal(ref_final, sh_final)
    if track_latency:
        assert sh_final.latency.sharding.is_equivalent_to(row_sharded, 2)


@pytest.mark.slow
def test_sharded_faulty_path_matches_single_device(mesh8):
    """Churn + partition + explicit drop mask through the sharded kernel."""
    n, ticks = 24, 10
    cfg = SwimConfig()
    st = init_state(n, seed=7)
    inp = idle_inputs(n, ticks=ticks)

    kill = inp.kill.at[3, 5].set(True).at[3, 6].set(True)
    revive = inp.revive.at[7, 5].set(True)
    part = inp.partition.at[4].set(jnp.arange(n) % 2)
    drop_ok = jnp.ones((ticks, n, n), dtype=bool).at[2, 0, :].set(False)
    inp = type(inp)(kill=kill, revive=revive, partition=part,
                    drop_rate=inp.drop_rate, manual_target=inp.manual_target,
                    drop_ok=drop_ok)

    ref_final, ref_m = simulate(st, inp, cfg, faulty=True)
    sh_final, sh_m = simulate_sharded(
        shard_state(st, mesh8), shard_inputs(inp, mesh8, stacked=True), cfg, mesh8
    )
    _assert_states_equal(ref_final, sh_final)
    assert jnp.array_equal(ref_m.messages_delivered, sh_m.messages_delivered)


@pytest.mark.slow
def test_sharded_convergence_matches_and_is_sharded(mesh8):
    n = 32
    cfg = SwimConfig()
    st = init_state(n, seed=11)

    f_ref, t_ref, c_ref = run_until_converged(st, cfg, max_ticks=40)
    f_sh, t_sh, c_sh = run_until_converged_sharded(
        shard_state(st, mesh8), cfg, mesh8, max_ticks=40
    )
    assert bool(c_ref) and bool(c_sh)
    assert int(t_ref) == int(t_sh)
    _assert_states_equal(f_ref, f_sh)

    # The result really lives split across the 8 devices, rows on PEER_AXIS.
    want = NamedSharding(mesh8, P(PEER_AXIS, None))
    assert f_sh.state.sharding.is_equivalent_to(want, f_sh.state.ndim)
    assert len(f_sh.state.sharding.device_set) == 8


def test_mesh_divisibility_check(mesh8):
    with pytest.raises(ValueError):
        shard_state(init_state(30), mesh8)
    with pytest.raises(ValueError):
        shard_inputs(idle_inputs(30), mesh8)


def test_multihost_mesh_single_process_fallback():
    from kaboodle_tpu.parallel import make_multihost_mesh

    mesh = make_multihost_mesh()
    assert mesh.axis_names == ("peers",)
    assert mesh.size == len(jax.devices())


@pytest.mark.slow
def test_sharded_epidemic_boot_converges(mesh8):
    """Behavioral GSPMD proof at CI scale (VERDICT r3 item 5): a broadcast-free
    epidemic boot (ring contacts, fresh gossip stamps) must *converge* under
    the sharded program — the per-shard fingerprint reduction + peer-axis
    all-reduce agreeing — not merely execute sharded. The full-scale version
    is scripts/sharded_scale_proof.py --boot epidemic."""
    n = 256
    cfg = SwimConfig(join_broadcast_enabled=False, backdate_gossip_inserts=False)
    st = shard_state(init_state(n, seed=0, ring_contacts=2), mesh8)
    final, ticks, conv = run_until_converged_sharded(st, cfg, mesh8, max_ticks=256)
    assert bool(conv), "epidemic boot did not converge under GSPMD"
    assert 1 < int(ticks) < 256  # genuinely epidemic, not broadcast-instant
    assert len(final.state.sharding.device_set) == 8


@pytest.mark.slow
def test_stepwise_donated_ticks_match_scan(mesh8):
    """The tick-at-a-time host loop with a donated carry (what
    scripts/sharded_scale_proof.py --stepwise runs at N=65,536, where the
    scan/while_loop working set OOMs the emulating host) must reproduce the
    lax.scan trajectory exactly."""
    from kaboodle_tpu.parallel import make_sharded_tick
    from kaboodle_tpu.sim.scenario import all_fault_paths_scenario

    n, ticks = 64, 4
    cfg = SwimConfig()
    sched = all_fault_paths_scenario(n, ticks=ticks, drop_rate=0.0).build()

    scan_final, _ = simulate_sharded(
        shard_state(init_state(n, seed=0), mesh8),
        shard_inputs(sched, mesh8, stacked=True), cfg, mesh8, faulty=True,
    )
    ftick = jax.jit(make_sharded_tick(cfg, mesh8, faulty=True), donate_argnums=0)
    st = shard_state(init_state(n, seed=0), mesh8)
    for t in range(ticks):
        st, _ = ftick(st, shard_inputs(jax.tree.map(lambda x: x[t], sched), mesh8))
    _assert_states_equal(scan_final, st)


def test_sharded_convergence_check_matches_tick(mesh8):
    """The standalone fingerprint-agreement check (what the N=65,536 proof
    asserts its converged-init state through — one masked state read, no
    protocol tick) must agree with the tick kernel's end-of-tick converged
    metric on the same states: converged-init true, self-only boot false,
    and fp_min/fp_max equal to the tick's reported extremes."""
    from kaboodle_tpu.parallel import sharded_convergence_check
    from kaboodle_tpu.sim.runner import simulate
    from kaboodle_tpu.sim.state import idle_inputs

    n = 64
    cfg = SwimConfig()

    for ring, expect in ((n - 1, True), (0, False)):
        st = shard_state(init_state(n, seed=0, ring_contacts=ring), mesh8)
        conv, fp_min, fp_max, n_alive = sharded_convergence_check(st)
        assert bool(conv) is expect
        assert int(n_alive) == n
        # The tick kernel reports the same extremes for the same membership:
        # run one idle tick from the converged state — membership unchanged,
        # so its metrics fingerprint bounds must equal the standalone check's.
        if expect:
            _, m = simulate(st, idle_inputs(n, ticks=1), cfg, faulty=False)
            assert int(m.fingerprint_min[-1]) == int(fp_min)
            assert int(m.fingerprint_max[-1]) == int(fp_max)

    # id_view states (per-row identity words) hash their own views.
    st = shard_state(
        init_state(n, seed=1, ring_contacts=n - 1, instant_identity=False),
        mesh8,
    )
    conv, *_ = sharded_convergence_check(st)
    assert bool(conv)


@pytest.mark.slow
def test_sharded_telemetry_counters_match_dense(mesh8):
    """The telemetry build of the sharded tick (ISSUE 6): GSPMD partitioning
    must not change a single counter — per-tick ProtocolCounters and the fp
    digest plane equal the single-device telemetry tick's bit-for-bit, and
    the carried state stays equal too."""
    from kaboodle_tpu.parallel import make_sharded_tick
    from kaboodle_tpu.sim.kernel import make_tick_fn
    from kaboodle_tpu.telemetry.counters import FIELDS

    n = 32
    cfg = SwimConfig(deterministic=True)
    st = init_state(n, seed=5)
    dense = jax.jit(make_tick_fn(cfg, faulty=True, telemetry=True))
    sharded = jax.jit(make_sharded_tick(cfg, mesh8, faulty=True, telemetry=True))
    sa, sb = st, shard_state(st, mesh8)
    for _ in range(6):
        inp = idle_inputs(n)
        sa, out_a = dense(sa, inp)
        sb, out_b = sharded(sb, inp)
        _assert_states_equal(sa, sb)
        for name in FIELDS:
            assert int(jnp.asarray(getattr(out_a.counters, name))) == int(
                jnp.asarray(getattr(out_b.counters, name))
            ), name
        assert jnp.array_equal(out_a.fp, out_b.fp)
