"""Phase-graph IR: the op table, the planner, and the derived programs.

The metadata layer (ops/graph/plan) is jax-free, so most of this file runs
at AST-adjacent cost; the derivation pins at the end trace/execute the real
programs at toy N. The at-scale bit-exactness contracts live in the parity
suites (test_kernel_parity.py, test_chunked.py, test_warp.py,
test_fleet.py, test_fuzz_parity.py) — all of which now execute
phase-graph-derived engines through the historical shim imports.
"""

import dataclasses

import pytest

from kaboodle_tpu.config import SwimConfig
from kaboodle_tpu.phasegraph import TickGraph, build_graph, plan
from kaboodle_tpu.phasegraph.graph import GraphError
from kaboodle_tpu.phasegraph.ops import PhaseOp
from kaboodle_tpu.phasegraph.plan import MODES


def _cfg(**kw):
    return SwimConfig(deterministic=True, **kw)


def _op(name, stage, **kw):
    kw.setdefault("phase", "-")
    kw.setdefault("doc", name)
    return PhaseOp(
        name=name, stage=stage,
        phase=kw["phase"], doc=kw["doc"],
        reads=frozenset(kw.get("reads", ())),
        writes=frozenset(kw.get("writes", ())),
        inputs=frozenset(kw.get("inputs", ())),
        gives=frozenset(kw.get("gives", ())),
        takes=frozenset(kw.get("takes", ())),
        activity=kw.get("activity", "always"),
        pred_term=kw.get("pred_term"),
        mask_rank=kw.get("mask_rank", 1),
        span=kw.get("span", "invariant"),
        cut=kw.get("cut"),
    )


# ---- op-table / graph validation ------------------------------------------


def test_default_graph_validates_and_orders():
    g = build_graph(_cfg(), faulty=True)
    names = [op.name for op in g.ops]
    assert names.index("rng_streams") < names.index("probe_draw")
    assert names.index("probe_draw") < names.index("call1") < names.index("finish")
    # the dispatch boundary is real: every prologue op precedes every tail op
    last_prologue = max(names.index(o.name) for o in g.prologue)
    first_tail = min(names.index(o.name) for o in g.tail)
    assert last_prologue < first_tail
    # cut labels are the stage-probe vocabulary, unique, on tail ops
    assert set(g.cut_labels) == {"A", "c1", "c2", "c34", "G"}


def test_static_flags_decide_op_presence():
    assert any(o.name == "churn" for o in build_graph(_cfg(), faulty=True).ops)
    assert not any(o.name == "churn" for o in build_graph(_cfg(), faulty=False).ops)
    no_join = build_graph(_cfg(join_broadcast_enabled=False), faulty=True)
    assert not any(o.name.startswith("join") for o in no_join.ops)
    telem = build_graph(_cfg(), faulty=True, telemetry=True)
    assert any(o.name == "counters" for o in telem.ops)
    assert not any(o.name == "counters" for o in build_graph(_cfg()).ops)


def test_graph_rejects_duplicate_op():
    a = _op("a", "prologue", gives=("x",))
    with pytest.raises(GraphError, match="duplicate"):
        TickGraph(ops=(a, a), faulty=False, telemetry=False)


def test_graph_rejects_take_before_give():
    a = _op("a", "prologue", takes=("x",))
    with pytest.raises(GraphError, match="before any op gives"):
        TickGraph(ops=(a,), faulty=False, telemetry=False)


def test_graph_rejects_regive_and_late_prologue():
    a = _op("a", "prologue", gives=("x",))
    b = _op("b", "prologue", gives=("x",))
    with pytest.raises(GraphError, match="re-gives"):
        TickGraph(ops=(a, b), faulty=False, telemetry=False)
    t = _op("t", "tail")
    c = _op("c", "prologue")
    with pytest.raises(GraphError, match="after the dispatch boundary"):
        TickGraph(ops=(a, t, c), faulty=False, telemetry=False)


def test_op_rejects_unknown_fields_and_bad_enums():
    with pytest.raises(ValueError, match="unknown state fields"):
        _op("x", "tail", reads=("no_such_plane",))
    with pytest.raises(ValueError, match="bad stage"):
        _op("x", "middle")
    with pytest.raises(ValueError, match="bad span fate"):
        _op("x", "tail", span="sometimes")


# ---- the planner -----------------------------------------------------------


def test_full_plan_is_one_pass_per_op():
    g = build_graph(_cfg(), faulty=True)
    prog = plan(g, "full")
    assert prog.mode == "full"
    assert len(prog.passes) == len(g.ops)
    assert prog.op_names() == tuple(op.name for op in g.ops)
    assert prog.pruned == () and prog.pred_terms == ()


def test_fused_plan_prunes_rank2_tail_and_derives_predicate():
    g = build_graph(_cfg(), faulty=True)
    prog = plan(g, "fused")
    pruned = {name for name, _ in prog.pruned}
    # exactly the rank-2 tail ops are pruned...
    assert pruned == {o.name for o in g.tail if o.mask_rank == 2}
    assert {"suspicion", "calls34", "join_insert", "join_replies"} <= pruned
    # ...and the dispatch predicate is the union of their pred_terms
    assert set(prog.pred_terms) == {
        o.pred_term for o in g.tail if o.mask_rank == 2
    }
    assert set(prog.pred_terms) == {"any_a2", "any_join"}
    # the tail is exactly the 2-pass shape: draw, then one folded update
    assert [p.name for p in prog.tail] == ["draw", "update"]
    assert "probe_draw" in prog.tail[0].op_names
    assert {"call1", "call2", "anti_entropy", "finish"} <= set(
        prog.tail[1].op_names
    )


def test_fused_plan_without_join_plane_shrinks_predicate():
    g = build_graph(_cfg(join_broadcast_enabled=False), faulty=True)
    prog = plan(g, "fused")
    assert set(prog.pred_terms) == {"any_a2"}


def test_fused_plan_rejects_unexcludable_rank2_op():
    g = build_graph(_cfg(), faulty=False)
    bad = _op("rogue", "tail", mask_rank=2)  # no pred_term
    with pytest.raises(GraphError, match="neither fold nor be excluded"):
        plan(
            TickGraph(ops=g.ops + (bad,), faulty=False, telemetry=False),
            "fused",
        )


def test_span_plan_requires_fault_free_graph():
    with pytest.raises(GraphError, match="fault-free"):
        plan(build_graph(_cfg(), faulty=True), "span")
    prog = plan(build_graph(_cfg(), faulty=False), "span")
    pruned = {name for name, _ in prog.pruned}
    # quiescence prunes the rare phases; the probe draw stays live
    assert "suspicion" in pruned and "calls34" in pruned
    live_ops = {n for p in prog.tail for n in p.op_names}
    assert "probe_draw" in live_ops and "finish" in live_ops


def test_blocked_plan_shares_full_pass_structure():
    g = build_graph(_cfg(), faulty=True)
    full, blocked = plan(g, "full"), plan(g, "blocked")
    assert blocked.mode == "blocked"
    assert blocked.op_names() == full.op_names()
    assert [p.name for p in blocked.passes] == [p.name for p in full.passes]


def test_plan_rejects_unknown_mode():
    g = build_graph(_cfg(), faulty=True)
    with pytest.raises(ValueError, match="unknown plan mode"):
        plan(g, "turbo")
    assert set(MODES) == {"full", "fused", "span", "blocked", "hybrid", "sparse"}


def test_describe_is_jsonable_and_names_passes():
    import json

    prog = plan(build_graph(_cfg(), faulty=True), "fused")
    desc = json.loads(json.dumps(prog.describe()))
    assert desc["mode"] == "fused"
    stages = {p["name"]: p["stage"] for p in desc["passes"]}
    assert stages["draw"] == "tail" and stages["update"] == "tail"
    assert {p["op"] for p in desc["pruned"]} == {n for n, _ in prog.pruned}
    assert prog.pass_of("call1") == "update"
    with pytest.raises(KeyError):
        prog.pass_of("suspicion")  # pruned ops are in no pass


# ---- derivations execute the plans ----------------------------------------


def test_every_build_variant_plans_and_builds():
    """Every static build variant's graph must validate AND have a full
    complement of op bodies in exec.py (make_tick_fn cross-checks the plan
    against its implementation table at build time)."""
    from kaboodle_tpu.phasegraph.exec import make_tick_fn

    for kw in (
        dict(faulty=True),
        dict(faulty=False),
        dict(faulty=True, telemetry=True),
        dict(faulty=False, telemetry=True),
    ):
        make_tick_fn(_cfg(), **kw)
        make_tick_fn(_cfg(join_broadcast_enabled=False), **kw)
    make_tick_fn(SwimConfig(deterministic=False), faulty=True)


def test_tick_fn_exposes_its_graph_and_programs():
    from kaboodle_tpu.phasegraph.exec import make_tick_fn

    tick = make_tick_fn(_cfg(), faulty=True)
    assert {op.name for op in tick.graph.ops} >= {"probe_draw", "call1", "finish"}
    assert set(tick.programs) == {"full", "fused"}
    assert [p.name for p in tick.programs["fused"].tail] == ["draw", "update"]


@pytest.mark.parametrize("faulty", [True, False])
def test_fused_program_matches_dispatched_on_steady_ticks(faulty):
    """The standalone 2-pass fused program equals the dispatched build
    tick-for-tick on a steady lane (the --fastpath-ab bit-check, in
    miniature, both faulty and fault-free builds)."""
    import jax

    from kaboodle_tpu.phasegraph.derive import make_dense_tick, make_fused_tick
    from kaboodle_tpu.sim.state import idle_inputs, init_state

    n = 32
    st_a = st_b = init_state(n, seed=3, ring_contacts=n - 1, announced=True)
    idle = idle_inputs(n)
    dense = jax.jit(make_dense_tick(_cfg(), faulty=faulty))
    fused = jax.jit(make_fused_tick(_cfg(), faulty=faulty))
    for _ in range(4):
        st_a, m_a = dense(st_a, idle)
        st_b, m_b = fused(st_b, idle)
    import numpy as np

    for a, b in zip(jax.tree.leaves((st_a, m_a)), jax.tree.leaves((st_b, m_b))):
        av, bv = np.asarray(a), np.asarray(b)
        if np.issubdtype(av.dtype, np.floating):
            assert ((av == bv) | (np.isnan(av) & np.isnan(bv))).all()
        else:
            assert (av == bv).all()


def test_full_program_build_matches_fast_path_off():
    """program='full' is exactly the cfg.fast_path=False build (the
    pre-refactor multi-pass production shape the A/B baselines against)."""
    import jax

    from kaboodle_tpu.phasegraph.exec import make_tick_fn
    from kaboodle_tpu.sim.state import idle_inputs, init_state

    n = 32
    st = init_state(n, seed=5)
    idle = idle_inputs(n)
    a = jax.jit(make_tick_fn(_cfg(), faulty=True, program="full"))(st, idle)
    off = dataclasses.replace(_cfg(), fast_path=False)
    b = jax.jit(make_tick_fn(off, faulty=True))(st, idle)
    import numpy as np

    for x, y in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        xv, yv = np.asarray(x), np.asarray(y)
        if np.issubdtype(xv.dtype, np.floating):
            assert ((xv == yv) | (np.isnan(xv) & np.isnan(yv))).all()
        else:
            assert (xv == yv).all()
