"""Observability hooks: structured per-tick tables, log lines, trace capture."""

import numpy as np

from kaboodle_tpu.config import SwimConfig
from kaboodle_tpu.profiling import log_run, tick_stats, trace
from kaboodle_tpu.sim import idle_inputs, init_state, simulate
import pytest


def _run(n=16, ticks=6):
    cfg = SwimConfig()
    return simulate(init_state(n, seed=1), idle_inputs(n, ticks=ticks), cfg)


@pytest.mark.slow
def test_tick_stats_table_matches_metrics():
    _, m = _run()
    table = tick_stats(m)
    assert table.shape == (6,)
    np.testing.assert_array_equal(table["tick"], np.arange(6))
    np.testing.assert_array_equal(
        table["messages_delivered"], np.asarray(m.messages_delivered)
    )
    np.testing.assert_array_equal(table["converged"], np.asarray(m.converged))
    # Boot converges at tick 0 (join broadcast) and membership is full.
    assert table["converged"][-1]
    assert table["mean_membership"][-1] == 16.0
    assert (table["fingerprint_min"] == table["fingerprint_max"])[-1]


@pytest.mark.slow
def test_log_run_emits_one_line_per_tick():
    _, m = _run()
    lines = []
    log_run(m, emit=lines.append)
    assert len(lines) == 6
    assert all(line.startswith("tick ") for line in lines)
    assert "CONVERGED" in lines[-1]


@pytest.mark.slow
def test_trace_captures_profile(tmp_path):
    with trace(str(tmp_path)):
        _run(n=8, ticks=2)
    # The JAX profiler writes its plugin tree under the log dir.
    captured = list(tmp_path.rglob("*"))
    assert captured, "profiler trace produced no files"


# ---- warp_stats / warp_summary degenerate shapes (ISSUE 6 satellite) ------
# An already-converged entry state leaps the whole schedule: zero dense
# ticks, ``metrics is None``. Every ratio-style stat must survive that
# without a ZeroDivisionError or a NaN row.


def test_warp_stats_all_leaped_is_empty_table():
    from kaboodle_tpu.profiling import warp_stats

    table = warp_stats(np.zeros((0,), np.int32), None)
    assert table.shape == (0,)
    assert "messages_delivered" in table.dtype.names


def test_warp_stats_rewrites_tick_column():
    from kaboodle_tpu.sim.state import TickMetrics
    from kaboodle_tpu.profiling import warp_stats

    m = TickMetrics(
        messages_delivered=np.asarray([3, 4], np.int32),
        converged=np.asarray([False, True]),
        agree_fraction=np.asarray([0.5, 1.0], np.float32),
        mean_membership=np.asarray([2.0, 2.0], np.float32),
        fingerprint_min=np.asarray([1, 2], np.uint32),
        fingerprint_max=np.asarray([9, 2], np.uint32),
    )
    table = warp_stats(np.asarray([7, 19], np.int32), m)
    np.testing.assert_array_equal(table["tick"], [7, 19])
    np.testing.assert_array_equal(table["messages_delivered"], [3, 4])


def test_warp_summary_all_leaped():
    from kaboodle_tpu.profiling import warp_summary

    s = warp_summary(np.zeros((0,), np.int32), 64, None)
    assert s["dense_ticks"] == 0 and s["leaped_ticks"] == 64
    assert s["dense_fraction"] == 0.0 and s["leaped_fraction"] == 1.0
    assert s["mean_msgs_per_dense_tick"] == 0.0


def test_warp_summary_zero_tick_run():
    from kaboodle_tpu.profiling import warp_summary

    s = warp_summary(np.zeros((0,), np.int32), 0, None)
    assert s["total_ticks"] == 0
    assert s["dense_fraction"] == 0.0 and s["leaped_fraction"] == 0.0


def test_warp_summary_rejects_impossible_counts():
    from kaboodle_tpu.profiling import warp_summary

    with pytest.raises(ValueError):
        warp_summary(np.arange(4), 2, None)


@pytest.mark.slow
def test_warp_summary_matches_warped_run():
    from kaboodle_tpu.profiling import warp_summary
    from kaboodle_tpu.sim.state import init_state
    from kaboodle_tpu.warp.runner import simulate_warped

    n, ticks = 12, 24
    st = init_state(n, seed=0, ring_contacts=n - 1, announced=True)
    sc_inputs = idle_inputs(n, ticks=ticks)
    import dataclasses

    sc_inputs = dataclasses.replace(
        sc_inputs, manual_target=sc_inputs.manual_target.at[10, 0].set(3)
    )
    _, dense_ticks, m = simulate_warped(
        st, sc_inputs, SwimConfig(), faulty=True, recheck_every=4
    )
    s = warp_summary(dense_ticks, ticks, m)
    assert s["dense_ticks"] == int(dense_ticks.size)
    assert s["leaped_ticks"] == ticks - int(dense_ticks.size)
    assert 0.0 < s["dense_fraction"] < 1.0
    assert s["messages_delivered"] == int(np.asarray(m.messages_delivered).sum())
