"""Observability hooks: structured per-tick tables, log lines, trace capture."""

import numpy as np

from kaboodle_tpu.config import SwimConfig
from kaboodle_tpu.profiling import log_run, tick_stats, trace
from kaboodle_tpu.sim import idle_inputs, init_state, simulate
import pytest


def _run(n=16, ticks=6):
    cfg = SwimConfig()
    return simulate(init_state(n, seed=1), idle_inputs(n, ticks=ticks), cfg)


@pytest.mark.slow
def test_tick_stats_table_matches_metrics():
    _, m = _run()
    table = tick_stats(m)
    assert table.shape == (6,)
    np.testing.assert_array_equal(table["tick"], np.arange(6))
    np.testing.assert_array_equal(
        table["messages_delivered"], np.asarray(m.messages_delivered)
    )
    np.testing.assert_array_equal(table["converged"], np.asarray(m.converged))
    # Boot converges at tick 0 (join broadcast) and membership is full.
    assert table["converged"][-1]
    assert table["mean_membership"][-1] == 16.0
    assert (table["fingerprint_min"] == table["fingerprint_max"])[-1]


@pytest.mark.slow
def test_log_run_emits_one_line_per_tick():
    _, m = _run()
    lines = []
    log_run(m, emit=lines.append)
    assert len(lines) == 6
    assert all(line.startswith("tick ") for line in lines)
    assert "CONVERGED" in lines[-1]


@pytest.mark.slow
def test_trace_captures_profile(tmp_path):
    with trace(str(tmp_path)):
        _run(n=8, ticks=2)
    # The JAX profiler writes its plugin tree under the log dir.
    captured = list(tmp_path.rglob("*"))
    assert captured, "profiler trace produced no files"
