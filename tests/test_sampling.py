"""Masked sampling ops: eligibility, fan-out bounds, distributional parity."""

import numpy as np
import jax
import jax.numpy as jnp

import pytest

from kaboodle_tpu.ops import (
    bernoulli_matrix,
    broadcast_reply_prob,
    choose_k_members,
    choose_one_of_oldest_k,
)


def test_choose_one_of_oldest_k_only_oldest_and_eligible():
    n = 10
    rng = np.random.default_rng(0)
    timer = jnp.asarray(rng.integers(0, 100, size=(n, n), dtype=np.int32))
    eligible = jnp.asarray(rng.random((n, n)) < 0.7)
    all_chosen = np.asarray(
        jax.vmap(lambda k: choose_one_of_oldest_k(timer, eligible, 5, k))(
            jax.random.split(jax.random.key(0), 20)
        )
    )
    for chosen in all_chosen:
        for i in range(n):
            elig_i = np.asarray(eligible[i])
            if not elig_i.any():
                assert chosen[i] == -1
                continue
            assert elig_i[chosen[i]]
            # chosen must be among the 5 smallest timers of eligible entries
            cand = sorted(np.asarray(timer[i])[elig_i])[:5]
            assert np.asarray(timer[i])[chosen[i]] <= cand[-1]


def test_choose_one_of_oldest_k_deterministic_picks_oldest():
    timer = jnp.asarray([[5, 3, 9, 3], [1, 1, 1, 1]], dtype=jnp.int32)
    eligible = jnp.asarray([[True, True, True, True], [False, True, True, False]])
    chosen = np.asarray(
        choose_one_of_oldest_k(timer, eligible, 5, jax.random.key(0), deterministic=True)
    )
    assert chosen[0] == 1  # oldest timer=3, tie broken toward lower index
    assert chosen[1] == 1  # lowest eligible index among ties


def test_choose_one_of_oldest_k_uniform_among_candidates():
    # one row, 5 equal-timer candidates among 8 eligible: draws should cover
    # exactly the 5 oldest and be roughly uniform.
    timer = jnp.asarray([[0, 0, 0, 0, 0, 50, 60, 70]], dtype=jnp.int32)
    eligible = jnp.ones((1, 8), dtype=bool)
    cs = np.asarray(
        jax.vmap(lambda k: choose_one_of_oldest_k(timer, eligible, 5, k)[0])(
            jax.random.split(jax.random.key(0), 600)
        )
    )
    counts = np.bincount(cs, minlength=8)
    assert counts[5:].sum() == 0
    assert (counts[:5] > 60).all()  # ~120 each expected


def test_choose_k_members_bounds_and_eligibility():
    n = 12
    rng = np.random.default_rng(3)
    eligible = jnp.asarray(rng.random((n, n)) < 0.4)
    idx, valid = choose_k_members(eligible, 3, jax.random.key(7))
    idx, valid = np.asarray(idx), np.asarray(valid)
    for i in range(n):
        el = np.asarray(eligible[i])
        assert valid[i].sum() == min(3, el.sum())
        sel = idx[i][valid[i]]
        assert len(set(sel.tolist())) == len(sel)  # distinct
        assert el[sel].all()


def test_choose_k_members_uniform_coverage():
    eligible = jnp.ones((1, 6), dtype=bool)
    idx, valid = jax.vmap(lambda k: choose_k_members(eligible, 3, k))(
        jax.random.split(jax.random.key(0), 400)
    )
    counts = np.bincount(np.asarray(idx).ravel(), weights=np.asarray(valid).ravel(), minlength=6)
    # each of 6 columns appears in ~half the draws (3 of 6 chosen)
    assert (counts > 120).all() and (counts < 280).all()


def test_broadcast_reply_prob_curve():
    # reference: n_other = len-2; <=0 -> 1.0; else max(1, 100-n^2)/100
    lens = jnp.asarray([1, 2, 3, 4, 7, 12, 1000, 65536], dtype=jnp.int32)
    p = np.asarray(broadcast_reply_prob(lens))
    np.testing.assert_allclose(p, [1.0, 1.0, 0.99, 0.96, 0.75, 0.01, 0.01, 0.01])


def test_bernoulli_matrix_rate():
    p = jnp.asarray(0.25)
    draws = np.asarray(bernoulli_matrix(jax.random.key(0), p, (200, 200)))
    assert abs(draws.mean() - 0.25) < 0.02
    det = np.asarray(bernoulli_matrix(jax.random.key(0), p, (4, 4), deterministic=True))
    assert det.all()


@pytest.mark.slow
def test_stable_k_smallest_iter_equals_topk():
    """The iterative oldest-k (SwimConfig.oldest_k_method='iter') must agree
    with sort-based top_k exactly: same candidate indices, same validity —
    across dtypes, tie pileups, empty rows, and k > #eligible."""
    from kaboodle_tpu.ops.sampling import (
        _stable_k_smallest_iter,
        _stable_k_smallest_topk,
    )

    rng = np.random.default_rng(7)
    for dtype in (np.int32, np.int16):
        tmax = jnp.asarray(np.iinfo(dtype).max, dtype=dtype)
        for trial in range(8):
            n = int(rng.integers(3, 40))
            # Heavy ties: few distinct timer values, including negatives
            # (Q6 back-dating drives timers below zero near tick 0) and
            # near-dtype-min magnitudes for the int16 widening path.
            lo = -32767 if (dtype == np.int16 and trial % 2) else -12
            timer = rng.integers(lo, lo + 16, size=(n, n)).astype(dtype)
            elig = rng.random((n, n)) < rng.choice([0.0, 0.1, 0.5, 0.9])
            scores = jnp.where(jnp.asarray(elig), jnp.asarray(timer), tmax)
            for k in (1, 3, min(5, n), n):
                ii, vi = _stable_k_smallest_iter(scores, k, tmax)
                it, vt = _stable_k_smallest_topk(scores, k, tmax)
                np.testing.assert_array_equal(np.asarray(vi), np.asarray(vt))
                # Indices must match wherever valid (top_k's invalid tail is
                # also index-ordered, but only validity is contractual there).
                np.testing.assert_array_equal(
                    np.where(np.asarray(vi), np.asarray(ii), -1),
                    np.where(np.asarray(vt), np.asarray(it), -1),
                )


def test_choose_one_of_oldest_k_methods_identical():
    """Both methods give identical draws for identical keys (same candidate
    set, same uniform pick), in random and deterministic modes."""
    rng = np.random.default_rng(3)
    n = 17
    timer = jnp.asarray(rng.integers(0, 6, size=(n, n), dtype=np.int16))
    eligible = jnp.asarray(rng.random((n, n)) < 0.6)
    for det in (False, True):
        for key in jax.random.split(jax.random.key(5), 5):
            a = choose_one_of_oldest_k(timer, eligible, 5, key, det, method="topk")
            b = choose_one_of_oldest_k(timer, eligible, 5, key, det, method="iter")
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_choose_one_of_oldest_k_traces_under_jit():
    """Regression: the topk sentinel test once converted tmax through a numpy
    scalar (``wide.dtype.type(tmax)``), which crashes with
    TracerArrayConversionError the first time the op is traced with int16
    timers — eager tests can't see it. Trace both methods x both dtypes."""
    import functools

    rng = np.random.default_rng(11)
    n = 24
    eligible = jnp.asarray(rng.random((n, n)) < 0.5)
    key = jax.random.key(9)
    for dtype in (np.int16, np.int32):
        timer = jnp.asarray(rng.integers(0, 50, size=(n, n), dtype=dtype))
        picks = {}
        for method in ("topk", "iter"):
            f = jax.jit(functools.partial(
                choose_one_of_oldest_k, k=5, deterministic=False, method=method))
            picks[method] = np.asarray(f(timer=timer, eligible=eligible, key=key))
        np.testing.assert_array_equal(picks["topk"], picks["iter"])
