"""Scenario layer: schedules compile correctly and drive the kernel as declared.

These are the assertable versions of the reference's eyeball checks (SURVEY.md
§4): churn (BASELINE config 3), message drop and partition-heal (config 5)
become deterministic scan runs with asserted convergence behavior.
"""

import jax.numpy as jnp
import numpy as np
import pytest

from kaboodle_tpu.config import SwimConfig
from kaboodle_tpu.sim.runner import simulate
from kaboodle_tpu.sim.scenario import Scenario, baseline_scenario
from kaboodle_tpu.sim.state import init_state


def test_schedule_invariants_and_alive_trajectory():
    sc = Scenario(n=16, ticks=30, seed=1).start_dead([3, 4]).churn(0.2, protect=[0])
    alive = sc.initial_alive()
    # kills only ever hit live peers; revives only dead ones; peer 0 protected.
    for t in range(sc.ticks):
        assert not np.any(sc._kill[t] & ~alive)
        assert not np.any(sc._revive[t] & alive)
        assert not sc._kill[t][0]
        alive = (alive & ~sc._kill[t]) | sc._revive[t]
    assert np.array_equal(alive, sc.alive_trajectory()[-1])

    # The kernel computes exactly the predicted aliveness.
    st = init_state(sc.n, alive=jnp.asarray(sc.initial_alive()))
    final, _ = simulate(st, sc.build(), SwimConfig())
    assert np.array_equal(np.asarray(final.alive), sc.alive_trajectory()[-1])


def test_composed_churn_trajectory_still_exact():
    """Overlapping churn windows: per-event invariants are only guaranteed for
    a sole schedule, but the trajectory prediction and protection must stay
    exact under the kernel's revive-wins (alive & ~kill) | revive rule."""
    sc = (
        Scenario(n=16, ticks=30, seed=1)
        .start_dead([3, 4])
        .churn(0.2, protect=[0])
        .churn(0.3, start=5, stop=25, protect=[0])
    )
    traj = sc.alive_trajectory()
    assert traj[:, 0].all(), "protected peer stays alive"
    st = init_state(sc.n, alive=jnp.asarray(sc.initial_alive()))
    final, _ = simulate(st, sc.build(), SwimConfig())
    assert np.array_equal(np.asarray(final.alive), traj[-1])


@pytest.mark.slow
def test_full_drop_blocks_everything():
    sc = Scenario(n=8, ticks=5).drop(1.0)
    st = init_state(sc.n)
    final, m = simulate(st, sc.build(), SwimConfig())
    assert int(jnp.sum(m.messages_delivered)) == 0
    assert not bool(m.converged[-1])
    # Nobody learned anybody: membership stays the identity.
    assert int(jnp.sum(final.state > 0)) == sc.n


@pytest.mark.slow
def test_churn_then_calm_reconverges():
    """Config-3 shape at test scale: churn storm, then the mesh heals itself
    via the suspicion -> indirect-ping -> removal path (kaboodle.rs:558-653)."""
    n, ticks = 32, 140
    sc = Scenario(n=n, ticks=ticks, seed=5).churn(0.05, start=1, stop=20, protect=[0])
    st = init_state(n, seed=5)
    final, m = simulate(st, sc.build(), SwimConfig())
    assert bool(m.converged[-1]), (
        f"agree={float(m.agree_fraction[-1])} fpmin={int(m.fingerprint_min[-1])} "
        f"fpmax={int(m.fingerprint_max[-1])}"
    )
    # Every alive peer's map contains exactly the alive set (dead peers were
    # detected and removed; revived peers were re-discovered).
    alive = np.asarray(final.alive)
    member = np.asarray(final.state) > 0
    for i in np.flatnonzero(alive):
        assert np.array_equal(member[i], alive), f"peer {i}"


@pytest.mark.slow
def test_partition_diverges_then_heals():
    """Config-5 shape at test scale: converge, partition even/odd (fingerprints
    diverge via cross-group removals), heal, re-converge (Q1: any inbound
    datagram resurrects the sender, kaboodle.rs:408-415)."""
    n = 16
    warm, part, heal_run = 20, 12, 60
    ticks = warm + part + heal_run
    groups = (np.arange(n) % 2).astype(np.int32)
    sc = Scenario(n=n, ticks=ticks, seed=2)
    sc.partition_at(warm, groups, until=warm + part)
    st = init_state(n, seed=2)
    final, m = simulate(st, sc.build(), SwimConfig())

    conv = np.asarray(m.converged)
    assert conv[warm - 1], "should converge before the partition"
    assert not conv[warm + part - 1], "partition should break agreement"
    assert conv[-1], "should re-converge after heal"
    member = np.asarray(final.state) > 0
    assert member.all(axis=1).all(), "every peer re-learns the full mesh"


def test_baseline_scenarios_construct():
    for cfg_id, n in [(1, None), (2, 64), (3, 64), (4, 64), (5, 66)]:
        sc = baseline_scenario(cfg_id, n=n, ticks=12)
        inp = sc.build()
        assert inp.kill.shape == (12, sc.n)
        assert inp.partition.shape == (12, sc.n)
    with pytest.raises(ValueError):
        baseline_scenario(0)


def test_baseline_config5_has_partition_and_drop():
    sc = baseline_scenario(5, n=12, ticks=9)
    inp = sc.build()
    assert float(inp.drop_rate[0]) == pytest.approx(0.10)
    third = 3
    assert int(jnp.max(inp.partition[third])) == 1  # partitioned middle third
    assert int(jnp.max(inp.partition[2 * third])) == 0  # healed
    assert float(inp.drop_rate[2 * third]) == 0.0  # drop window closed too


@pytest.mark.slow
def test_drop_plus_partition_heal_reconverges():
    """Config-5 shape at test scale (windows scaled per the purge bound — see
    scenario.py): 10% drop + even/odd partition, both heal, mesh re-converges
    with full membership."""
    n = 32
    sc = Scenario(n=n, ticks=130, seed=3).drop(0.10, stop=42)
    groups = (np.arange(n) % 2).astype(np.int32)
    sc.partition_at(30, groups).heal_at(42)
    final, m = simulate(init_state(n, seed=3), sc.build(), SwimConfig())
    assert bool(m.converged[-1])
    assert float(m.agree_fraction[-1]) == 1.0
    assert (np.asarray(final.state) > 0).all(), "every peer re-learns the full mesh"


@pytest.mark.slow
def test_partition_heal_reconverges_at_n256():
    """VERDICT r3 item 4: config-5 re-convergence asserted at moderate N.

    N=256 with the bench's own section driver (bench._bench_partition_heal):
    10% drop over two thirds, 2-way partition over the middle third, heal —
    the mesh must re-agree within the ~2.5N calm-tick recovery budget (the
    reference's purge-completeness bound, SURVEY §6)."""
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    from bench import _bench_partition_heal

    out = _bench_partition_heal(256)
    assert out["reconverged"] is True
    assert out["reconverge_ticks_after_heal"] is not None
    assert out["reconverge_ticks_after_heal"] <= out["calm_budget"]


@pytest.mark.slow
def test_churn_recovery_reconverges():
    """VERDICT r3 item 3 (test-scale pin): after the config-3 churn window
    closes, the mesh re-converges within the ~2.5N calm budget and the bench
    section reports the tick count as a number, not null."""
    import sys
    from pathlib import Path

    sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
    from bench import _bench_churn_recovery

    out = _bench_churn_recovery(128)
    assert out["reconverged"] is True
    assert isinstance(out["reconverge_ticks_after_churn"], int)
    assert 0 < out["survivors"] <= 128
