"""Serve subsystem (ISSUE 10): admission bit-exactness, lifecycle, warp
parity, spill/restore continuation, zero-recompile pin, server e2e, lint.

The service contract under test: a request admitted into a lane of the
resident pool — even mid-flight, while other lanes are running — produces
EXACTLY the trajectory a standalone ``run_until_converged`` of the same
(seed, knobs, scenario) would, and the whole lifecycle (admit, advance,
harvest, re-seed, park, spill, restore, resume, cancel) re-dispatches the
warmed program set without ever compiling.
"""

from __future__ import annotations

import asyncio
import json
import os

import jax
import numpy as np
import pytest

from kaboodle_tpu.config import SwimConfig
from kaboodle_tpu.serve.engine import ServeEngine, ServeRequest
from kaboodle_tpu.serve.pool import (
    MIN_LANE_N,
    LanePool,
    lane_n_class,
)
from kaboodle_tpu.sim.runner import run_until_converged, state_agreement
from kaboodle_tpu.sim.state import init_state

CFG = SwimConfig(deterministic=True)
N = 16  # one shared N-class: every pool below reuses one compiled set


def _pool(lanes: int = 3, **kw) -> LanePool:
    return LanePool(N, lanes, cfg=CFG, chunk=4, **kw)


def _leaves_equal(a, b) -> bool:
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    if len(la) != len(lb):
        return False
    for x, y in zip(la, lb):
        x, y = np.asarray(x), np.asarray(y)
        # equal_nan: the latency leaf is NaN until a ping round-trips
        eq = np.issubdtype(x.dtype, np.floating)
        if not np.array_equal(x, y, equal_nan=eq):
            return False
    return True


def _standalone(seed: int, scenario: str = "boot", max_ticks: int = 64):
    kw = {} if scenario == "boot" else {"ring_contacts": N - 1,
                                        "announced": True}
    state, ticks, conv = run_until_converged(
        init_state(N, seed=seed, **kw), CFG, max_ticks=max_ticks
    )
    return state, int(ticks), bool(conv)


# -- classes and validation -------------------------------------------------


def test_lane_n_class():
    assert lane_n_class(1) == MIN_LANE_N
    assert lane_n_class(MIN_LANE_N) == MIN_LANE_N
    assert lane_n_class(9) == 16
    assert lane_n_class(16) == 16
    assert lane_n_class(17) == 32
    with pytest.raises(ValueError, match="n >= 1"):
        lane_n_class(0)
    with pytest.raises(ValueError, match="pow2 lane class"):
        LanePool(12, 2, cfg=CFG)
    with pytest.raises(ValueError, match="lanes >= 1"):
        LanePool(N, 0, cfg=CFG)


def test_request_and_engine_validation():
    with pytest.raises(ValueError, match="unknown mode"):
        ServeRequest(n=16, mode="forever")
    with pytest.raises(ValueError, match="ticks >= 1"):
        ServeRequest(n=16, ticks=0)
    assert ServeRequest(n=9).n_class == 16
    assert ServeRequest(n=16).until_conv
    assert not ServeRequest(n=16, mode="ticks").until_conv

    pool = _pool(lanes=1)
    with pytest.raises(ValueError, match="at least one pool"):
        ServeEngine([])
    with pytest.raises(ValueError, match="duplicate pool"):
        ServeEngine([pool, _pool(lanes=1)])
    with pytest.raises(ValueError, match="max_leap"):
        ServeEngine([pool], max_leap=4)
    eng = ServeEngine([pool], warp=False)
    with pytest.raises(ValueError, match="no pool serves"):
        eng.submit(ServeRequest(n=64))
    with pytest.raises(ValueError, match="unknown scenario"):
        eng.submit(ServeRequest(n=16, scenario="chaos"))
    with pytest.raises(ValueError, match="fault-free"):
        eng.submit(ServeRequest(n=16, drop_rate=0.5))


def test_pool_lifecycle_guards_and_generations():
    pool = _pool(lanes=2)
    assert np.asarray(pool.generation).tolist() == [0, 0]
    g = pool.admit(0, seed=1)
    assert g == 1
    with pytest.raises(ValueError, match="occupied"):
        pool.admit(0, seed=2)
    with pytest.raises(ValueError, match="faulty=True"):
        pool.admit(1, seed=2, drop_rate=0.25)
    with pytest.raises(ValueError, match="is free"):
        pool.resume(1, until_conv=False, budget=4)
    with pytest.raises(ValueError, match="warm up before"):
        pool.warmup()
    pool.release(0)
    assert pool.admit(0, seed=3) == 2  # generations survive retire/re-seed
    member = pool.member(0)
    pool.release(0)
    assert pool.insert(0, member) == 3  # insert bumps the counter too
    assert pool.free_lane() == 1


# -- the headline pin: mid-flight admission is bit-exact --------------------


def test_admission_mid_flight_bit_exact():
    """A lane admitted while another lane is mid-flight converges to the
    leaf-for-leaf SAME state, at the same tick, as a standalone
    ``run_until_converged`` of its (seed, scenario) — the service
    contract that makes the resident pool a simulator, not a sampler."""
    engine = ServeEngine([_pool(lanes=3)], warp=False)
    r0 = engine.submit(ServeRequest(n=N, seed=5, keep=True))
    engine.step()  # r0 admitted and mid-flight before r1 exists
    r1 = engine.submit(ServeRequest(n=N, seed=11, keep=True))
    r2 = engine.submit(ServeRequest(n=N, seed=7, scenario="steady",
                                    keep=True))
    engine.drain()

    for rid, seed, scenario in ((r0, 5, "boot"), (r1, 11, "boot"),
                                (r2, 7, "steady")):
        row = engine.status(rid)
        assert row["state"] == "parked"  # keep=True: member still resident
        ref_state, ref_ticks, ref_conv = _standalone(seed, scenario)
        res = row["result"]
        assert res["conv_tick"] == ref_ticks, (rid, res, ref_ticks)
        assert res["converged"] == ref_conv
        conv, fp_min, fp_max, n_alive = state_agreement(ref_state)
        assert res["fp_min"] == int(fp_min)
        assert res["fp_max"] == int(fp_max)
        assert res["n_alive"] == int(n_alive)
        pool = engine.pools[N]
        assert _leaves_equal(pool.member(row["lane"]), ref_state), (
            f"request {rid} (seed {seed}, {scenario}) diverged from its "
            "standalone run"
        )


def test_retire_reseed_cycle_stays_exact():
    """The second wave through RECYCLED lanes (husk states overwritten by
    the re-seed scatter) is as exact as the first."""
    engine = ServeEngine([_pool(lanes=2)], warp=False)
    first = [engine.submit(ServeRequest(n=N, seed=s)) for s in (0, 1)]
    engine.drain()
    second = [engine.submit(ServeRequest(n=N, seed=s, keep=True))
              for s in (21, 22)]
    engine.drain()
    for rid in first:
        assert engine.status(rid)["state"] == "done"
    for rid, seed in zip(second, (21, 22)):
        row = engine.status(rid)
        _, ref_ticks, _ = _standalone(seed)
        assert row["result"]["conv_tick"] == ref_ticks
        ref_state, _, _ = _standalone(seed)
        assert _leaves_equal(engine.pools[N].member(row["lane"]), ref_state)


# -- warp composition -------------------------------------------------------


def test_horizon_warp_parity():
    """A horizon-mode request served with the fleet warp ON is bit-exact
    with the same request served dense — and the warp engine actually
    leaps (otherwise this pin is vacuous)."""
    results = {}
    for warp in (False, True):
        engine = ServeEngine([_pool(lanes=2)], warp=warp, max_leap=16)
        if warp:
            engine.warmup()
        rid = engine.submit(ServeRequest(n=N, seed=9, mode="ticks",
                                         ticks=40, scenario="steady",
                                         keep=True))
        events = engine.drain()
        row = engine.status(rid)
        assert row["result"]["ticks_run"] == 40
        results[warp] = engine.pools[N].member(row["lane"])
        if warp:
            leaps = [e for e in events if e["kind"] == "serve_round"
                     and e["engine"] == "leap"]
            assert leaps, "warp engine never leaped a quiescent horizon run"
            assert all(e["bucket"] <= 16 for e in leaps)  # max_leap clamp
    assert _leaves_equal(results[False], results[True]), (
        "fleet-warp serving diverged from dense serving"
    )


def test_converge_mode_never_leaps():
    """Converge-mode lanes must run dense even under a warp engine — a
    hybrid leap may skip the first fp-agreement tick."""
    engine = ServeEngine([_pool(lanes=2)], warp=True, max_leap=16)
    engine.warmup()
    rid = engine.submit(ServeRequest(n=N, seed=3, scenario="steady"))
    events = engine.drain()
    assert not [e for e in events if e["kind"] == "serve_round"
                and e["engine"] == "leap"]
    _, ref_ticks, _ = _standalone(3, "steady")
    assert engine.status(rid)["result"]["conv_tick"] == ref_ticks


# -- spill / restore continuation -------------------------------------------


def test_spill_restore_continuation_bit_exact(tmp_path):
    """A horizon run interrupted by park -> spill (checkpoint.save) ->
    restore (checkpoint.load + insert) -> resume lands leaf-for-leaf on
    the state of the same run served without the interruption."""
    straight = ServeEngine([_pool(lanes=1)], warp=False)
    rid = straight.submit(ServeRequest(n=N, seed=13, mode="ticks",
                                       ticks=40, scenario="steady",
                                       keep=True))
    straight.drain()
    want = straight.pools[N].member(straight.status(rid)["lane"])

    # spill_after=2: the harvested lane must stay resident through the
    # final drain round (spill_after=0 would re-spill it immediately).
    engine = ServeEngine([_pool(lanes=1)], warp=False, spill_after=2,
                         spill_dir=str(tmp_path))
    rid = engine.submit(ServeRequest(n=N, seed=13, mode="ticks",
                                     ticks=24, scenario="steady",
                                     keep=True))
    engine.drain()
    while engine.status(rid)["state"] != "spilled":
        engine.step()  # idle rounds tick the parked lane into the spill
    path = engine.status(rid)["spill_path"]
    assert os.path.exists(path)
    assert engine.restore(rid)
    assert engine.status(rid)["state"] == "parked"
    engine.resume(rid, mode="ticks", ticks=16)  # 24 + 16 == 40
    engine.drain()
    row = engine.status(rid)
    assert row["result"]["ticks_run"] == 40  # counters span the boundary
    assert _leaves_equal(engine.pools[N].member(row["lane"]), want), (
        "spill/restore continuation diverged from the uninterrupted run"
    )
    with pytest.raises(ValueError, match="not spilled"):
        engine.restore(rid)


# -- the zero-recompile pin -------------------------------------------------


def test_zero_recompile_after_warmup(tmp_path):
    """After ``ServeEngine.warmup`` the whole lifecycle — mixed admissions,
    leap and chunk rounds, harvests, re-seeds into recycled lanes, park,
    spill, restore, resume, cancel — compiles NOTHING (KB405 counter)."""
    from kaboodle_tpu.analysis.ir.surface import (
        assert_counter_live,
        compile_counter,
    )

    assert_counter_live()
    engine = ServeEngine([_pool(lanes=2)], warp=True, max_leap=16,
                         spill_after=0, spill_dir=str(tmp_path))
    engine.warmup()
    with compile_counter() as box:
        rids = [
            engine.submit(ServeRequest(n=N, seed=0, keep=True)),
            engine.submit(ServeRequest(n=N, seed=1, mode="ticks",
                                       ticks=24, scenario="steady")),
            engine.submit(ServeRequest(n=N, seed=2)),  # recycled lane
        ]
        engine.drain()
        kept = rids[0]
        while engine.status(kept)["state"] != "spilled":
            engine.step()
        assert engine.restore(kept)
        engine.resume(kept, mode="ticks", ticks=8)
        engine.drain()
        assert engine.cancel(kept)
        assert not engine.cancel(kept)  # already terminal
    assert box.count == 0, (
        f"{box.count} fresh compilations after warmup — the zero-recompile "
        "service contract regressed"
    )


# -- telemetry pools --------------------------------------------------------


def test_telemetry_pool_counters():
    """A telemetry pool harvests full ProtocolCounters totals per lane —
    and is excluded from the warp (exact totals need dense ticks)."""
    from kaboodle_tpu.telemetry.counters import FIELDS

    engine = ServeEngine([_pool(lanes=2, telemetry=True)], warp=True,
                         max_leap=16)
    r0 = engine.submit(ServeRequest(n=N, seed=4))
    r1 = engine.submit(ServeRequest(n=N, seed=4, mode="ticks", ticks=16,
                                    scenario="steady"))
    events = engine.drain()
    assert not [e for e in events if e["kind"] == "serve_round"
                and e["engine"] == "leap"]
    res0 = engine.status(r0)["result"]
    assert set(res0["counters"]) == set(FIELDS)
    assert res0["messages"] > 0
    # The horizon run covers enough steady-state ticks for ping traffic.
    res1 = engine.status(r1)["result"]
    assert res1["counters"]["pings_sent"] > 0
    # Same seed, same class: a second engine run of the same request
    # reproduces the counter totals exactly (they are program outputs,
    # not host samples).
    engine2 = ServeEngine([_pool(lanes=2, telemetry=True)], warp=False)
    r0b = engine2.submit(ServeRequest(n=N, seed=4))
    engine2.drain()
    assert engine2.status(r0b)["result"]["counters"] == res0["counters"]
    assert engine2.status(r0b)["result"]["messages"] == res0["messages"]


# -- server / client / manifest ---------------------------------------------


def test_server_client_e2e(tmp_path):
    """Submit/wait/status/stream/cancel/shutdown over real TCP, with the
    manifest fan-out validating every record as it is written."""
    from kaboodle_tpu.serve.client import ServeClient
    from kaboodle_tpu.serve.server import ServeServer
    from kaboodle_tpu.telemetry.manifest import read_manifest

    manifest = str(tmp_path / "serve-manifest.jsonl")
    engine = ServeEngine([_pool(lanes=2)], warp=False)
    server = ServeServer(engine, port=0, manifest_path=manifest)
    engine.warmup()

    async def drive() -> None:
        await server.start()
        client = await ServeClient.connect(port=server.port)
        stream = await client.open_stream()
        streamed: list[dict] = []

        async def pump() -> None:
            async for rec in stream:
                streamed.append(rec)

        pump_task = asyncio.create_task(pump())
        r0 = await client.submit(N, seed=6)
        r1 = await client.submit(N, seed=8, mode="ticks", ticks=12,
                                 scenario="steady")
        row0 = await asyncio.wait_for(client.wait(r0), 30.0)
        row1 = await asyncio.wait_for(client.wait(r1), 30.0)
        _, ref_ticks, _ = _standalone(6)
        assert row0["result"]["conv_tick"] == ref_ticks
        assert row1["result"]["ticks_run"] == 12
        assert not await client.cancel(r0)  # already done
        stats = await client.stats()
        assert stats["requests"] == 2
        assert stats["states"].get("done") == 2
        with pytest.raises(RuntimeError, match="no pool serves"):
            await client.submit(64)
        await client.shutdown()
        await server.close()
        await asyncio.wait_for(pump_task, 30.0)
        assert streamed and all(
            rec["schema"] == "kaboodle-telemetry/1" for rec in streamed
        )

    asyncio.run(drive())
    written = list(read_manifest(manifest, validate=True))
    events = {r.get("event") for r in written if r["kind"] == "serve_event"}
    assert {"warm", "admitted", "converged", "completed"} <= events


def test_manifest_stream_mode_and_serve_schema(tmp_path):
    """``stream=True`` makes records durable per write (a concurrent
    reader sees them before close), and the serve_* kinds are schema-
    checked on the way in."""
    from kaboodle_tpu.telemetry.manifest import (
        ManifestWriter,
        read_manifest,
        run_record,
        validate_record,
    )

    path = str(tmp_path / "stream.jsonl")
    w = ManifestWriter(path, stream=True)
    w.write_record(run_record("serve_event", event="admitted", lane=0))
    w.write_record(run_record("serve_round", round=3, engine="chunk"))
    live = list(read_manifest(path, validate=True))  # BEFORE close
    assert [r["kind"] for r in live] == ["serve_event", "serve_round"]
    with pytest.raises(ValueError, match="'lane'"):
        w.write_record(run_record("serve_event", event="admitted"))
    with pytest.raises(ValueError, match="'round'"):
        w.write_record(run_record("serve_round", engine="chunk"))
    with pytest.raises(ValueError, match="'event'"):
        validate_record({"schema": "kaboodle-telemetry/1",
                         "kind": "serve_event", "lane": 0})
    w.close()
    assert len(list(read_manifest(path, validate=True))) == 2


def test_summary_aggregates_serve_records(tmp_path):
    """``kaboodle telemetry`` folds serve_event/serve_round records into a
    lifecycle + per-engine-rounds summary."""
    from kaboodle_tpu.telemetry.manifest import ManifestWriter
    from kaboodle_tpu.telemetry.summary import load_manifests, summarize

    path = str(tmp_path / "m.jsonl")
    engine = ServeEngine([_pool(lanes=2)], warp=False)
    w = ManifestWriter(path)
    engine.on_event = w.write_record
    engine.submit(ServeRequest(n=N, seed=0))
    engine.submit(ServeRequest(n=N, seed=1, mode="ticks", ticks=8,
                               scenario="steady"))
    engine.drain()
    w.close()
    out = summarize(load_manifests([path]))
    serve = out["serve"]
    assert serve["events"]["admitted"] == 2
    assert serve["events"]["converged"] == 1
    assert serve["events"]["completed"] == 1
    assert serve["finished"] == 2
    assert serve["round_engines"]["chunk"]["rounds"] >= 1
    assert serve["round_engines"]["chunk"]["ticks"] > 0
    assert json.dumps(out)  # summary stays JSON-serializable


# -- lint scope -------------------------------------------------------------


def test_serve_graftlint_clean():
    """ISSUE 10 satellite: serve/ is in the hot-path lint scope (pool.py
    under dtype discipline) and carries no KB2xx/KB3xx debt."""
    from pathlib import Path

    from kaboodle_tpu.analysis import analyze_path
    from kaboodle_tpu.analysis.core import _load_rules
    from kaboodle_tpu.analysis.rules_hotpath import (
        DTYPE_DISCIPLINE_FILES,
        HOT_DIRS,
    )

    assert "kaboodle_tpu/serve/" in HOT_DIRS
    assert "pool.py" in DTYPE_DISCIPLINE_FILES
    assert "engine.py" in DTYPE_DISCIPLINE_FILES
    _load_rules()
    root = Path(__file__).resolve().parent.parent / "kaboodle_tpu" / "serve"
    findings = [f for p in sorted(root.glob("*.py")) for f in analyze_path(p)]
    bad = [f for f in findings if f.rule.startswith(("KB2", "KB3"))]
    assert not bad, [(f.path, f.rule, f.line, f.message) for f in bad]
