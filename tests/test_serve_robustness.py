"""Servefort (ISSUE 12): crash-safe, overload-safe serving — unit lanes.

The deep end-to-end invariants (kill-mid-round bit-exactness, overload
SLOs, spill round-latency A/B) live in the chaos harness
(``make serve-chaos-dryrun``, kaboodle_tpu/serve/chaos.py); this file
pins the pieces in isolation: journal fold/compaction, admission
policy, the spill manager's failure/retry contract, engine recovery,
and the server's structured-error + client timeout/retry surface.
"""

from __future__ import annotations

import asyncio
import json
import os

import jax
import numpy as np
import pytest

from kaboodle_tpu.config import SwimConfig
from kaboodle_tpu.serve.engine import ServeEngine, ServeRequest
from kaboodle_tpu.serve.pool import LanePool

CFG = SwimConfig(deterministic=True)
N = 16


@pytest.fixture(autouse=True)
def _conc_sanitizer():
    """Every test in this module runs under the runtime concurrency
    sanitizer: SpillManager locks become order-recorded wrappers (an ABBA
    raises deterministically) and asyncio callbacks are watchdogged.
    Threshold 2s — warmup/recovery are budgeted, CPU-backend rounds are
    sub-ms, so a trip is a genuine event-loop stall."""
    from kaboodle_tpu.analysis.conc import sanitizer

    with sanitizer.enabled(loop_threshold_s=2.0):
        yield
        sanitizer.assert_clean()


def _pool(lanes: int = 2, **kw) -> LanePool:
    return LanePool(N, lanes, cfg=CFG, chunk=8, **kw)


def _leaves_equal(a, b) -> bool:
    la, lb = jax.tree.leaves(a), jax.tree.leaves(b)
    if len(la) != len(lb):
        return False
    for x, y in zip(la, lb):
        x, y = np.asarray(x), np.asarray(y)
        eq = np.issubdtype(x.dtype, np.floating)
        if not np.array_equal(x, y, equal_nan=eq):
            return False
    return True


# -- journal ----------------------------------------------------------------


def test_journal_replay_folds_lifecycle(tmp_path):
    from kaboodle_tpu.serve.journal import ServeJournal

    j = ServeJournal(str(tmp_path / "j"))
    j.append("submitted", 0, req={"n": 16, "seed": 7, "ticks": 24})
    j.append("admitted", 0)
    j.append("harvested", 0, event="completed", result={"ticks_run": 24})
    j.append("submitted", 1, req={"n": 16, "seed": 8, "ticks": 8})
    j.append("resumed", 1, mode="ticks", ticks=16)
    j.append("resumed", 1, mode="ticks", ticks=8)
    j.append("spilled", 1, path="/spill/1.npz", saved_run={"ticks_run": 32})
    j.append("submitted", 2, req={"n": 16, "seed": 9})
    j.append("cancelled", 2)
    j.close()

    table, next_rid = ServeJournal(str(tmp_path / "j")).replay()
    assert next_rid == 3
    assert table[0]["op"] == "harvested"
    assert table[0]["result"] == {"ticks_run": 24}
    assert table[1]["op"] == "spilled"
    assert table[1]["spill_path"] == "/spill/1.npz"
    assert table[1]["extra_ticks"] == 24  # cumulative resume budgets
    assert table[1]["saved_run"] == {"ticks_run": 32}
    assert table[2]["op"] == "cancelled"


def test_journal_torn_tail_is_crash_point(tmp_path):
    """A half-written last WAL line (crash mid-append) is where replay
    stops — everything before it folds, nothing raises."""
    from kaboodle_tpu.serve.journal import ServeJournal

    j = ServeJournal(str(tmp_path / "j"))
    j.append("submitted", 0, req={"n": 16})
    j.append("submitted", 1, req={"n": 16})
    j.close()
    with open(os.path.join(str(tmp_path / "j"), "wal.jsonl"), "a") as f:
        f.write('{"op": "harvested", "rid": 1, "resu')  # torn

    table, next_rid = ServeJournal(str(tmp_path / "j")).replay()
    assert next_rid == 2
    assert table[1]["op"] == "submitted"  # the torn harvest never happened


def test_journal_compaction_truncates_wal(tmp_path):
    from kaboodle_tpu.serve.journal import ServeJournal

    j = ServeJournal(str(tmp_path / "j"), compact_every=4)
    for rid in range(5):
        j.append("submitted", rid, req={"n": 16, "seed": rid})
    assert j.should_compact()
    table, next_rid = j.replay()
    j.compact(table, next_rid)
    assert not j.should_compact()
    assert os.path.getsize(j.wal_path) == 0  # WAL cut after the snapshot
    j.append("cancelled", 2)
    j.close()

    table2, next2 = ServeJournal(str(tmp_path / "j")).replay()
    assert next2 == 5
    assert {rid: row["op"] for rid, row in table2.items()} == {
        0: "submitted", 1: "submitted", 2: "cancelled",
        3: "submitted", 4: "submitted",
    }


# -- admission --------------------------------------------------------------


def test_token_bucket_quota_and_retry_after():
    from kaboodle_tpu.serve.admission import (
        AdmissionController,
        QuotaError,
    )

    clock = [0.0]
    ctl = AdmissionController(
        max_queue=8, quotas={"metered": (1.0, 2.0)},
        clock=lambda: clock[0],
    )
    ctl.check_quota("metered")
    ctl.check_quota("metered")  # burst of 2
    with pytest.raises(QuotaError) as ei:
        ctl.check_quota("metered")
    assert ei.value.kind == "quota"
    assert 0 < ei.value.retry_after_s <= 1.0
    clock[0] += 1.0  # one token refilled
    ctl.check_quota("metered")
    # Unmetered tenants never throttle.
    for _ in range(50):
        ctl.check_quota("default")


def test_queue_bound_and_retry_after_scales():
    from kaboodle_tpu.serve.admission import (
        AdmissionController,
        QueueFullError,
    )

    ctl = AdmissionController(max_queue=4)
    ctl.check_queue(3)
    with pytest.raises(QueueFullError) as ei:
        ctl.check_queue(4)
    assert ei.value.kind == "queue_full"
    assert ei.value.retry_after_s > 0
    with pytest.raises(QueueFullError) as deeper:
        ctl.check_queue(40)
    assert deeper.value.retry_after_s > ei.value.retry_after_s


def test_priority_preemption_spills_parked_victim(tmp_path):
    """With every lane held by PARKED low-priority requests, a
    higher-priority arrival evicts the least valuable one to disk
    (running lanes are never preempted) and takes its lane."""
    from kaboodle_tpu.serve.admission import AdmissionController

    engine = ServeEngine(
        [_pool(lanes=1)], warp=False, admission=AdmissionController(),
        spill_dir=str(tmp_path), sync_spill=True,
    )
    engine.warmup()
    low = engine.submit(ServeRequest(n=N, seed=3, mode="ticks", ticks=8,
                                     scenario="steady", keep=True,
                                     priority=0))
    engine.drain()
    assert engine.status(low)["state"] == "parked"
    high = engine.submit(ServeRequest(n=N, seed=4, mode="ticks", ticks=8,
                                      scenario="steady", priority=5))
    engine.drain()
    assert engine.status(high)["state"] == "done"
    row = engine.status(low)
    assert row["state"] == "spilled" and os.path.exists(row["spill_path"])
    assert engine.restore(low)  # the preempted request is intact
    engine.close()


# -- spill manager ----------------------------------------------------------


def test_spill_manager_failure_keeps_cache_then_retry(tmp_path):
    from kaboodle_tpu.serve.spill import SpillManager
    from kaboodle_tpu.sim.state import init_state
    from kaboodle_tpu import checkpoint

    member = init_state(8, seed=2)
    path = str(tmp_path / "m.npz")
    sp = SpillManager(depth=2)
    try:
        sp.fail_next(1)
        assert sp.submit_write(7, path, member)
        sp.flush()
        (res,) = sp.poll()
        assert not res.ok and "injected" in res.error
        assert not os.path.exists(path)
        assert sp.cached(7) is member  # the state survived the failure
        assert sp.submit_write(7, path, sp.cached(7))
        sp.flush()
        (res2,) = sp.poll()
        assert res2.ok
        assert sp.cached(7) is None  # durable: the file supersedes it
        assert _leaves_equal(member, checkpoint.load(path))
    finally:
        sp.close()


def test_spill_manager_thunk_and_prefetch(tmp_path):
    """A deferred (thunk) write materializes off the caller's thread, and
    prefetch loads a file back into the cache for restore."""
    from kaboodle_tpu.serve.spill import SpillManager
    from kaboodle_tpu.sim.state import init_state

    member = init_state(8, seed=5)
    path = str(tmp_path / "t.npz")
    sp = SpillManager(depth=2)
    try:
        calls = []

        def thunk():
            calls.append(1)
            return member

        assert sp.submit_write(1, path, thunk)
        sp.flush()
        assert sp.poll()[0].ok and calls
        assert os.path.exists(path)

        assert sp.cached(2) is None
        assert sp.prefetch(2, path)
        sp.flush()
        assert sp.poll()[0].ok
        assert _leaves_equal(member, sp.cached(2))
    finally:
        sp.close()


# -- engine recovery --------------------------------------------------------


def test_recover_requeues_reattaches_and_compacts(tmp_path):
    """A journaled engine abandoned mid-service (no close — a crash)
    recovers into a fresh engine: the completed request keeps its result,
    the spilled one re-attaches to its file, the in-flight one re-queues
    and re-runs; the journal is compacted on the way in."""
    jdir, sdir = str(tmp_path / "j"), str(tmp_path / "s")
    os.makedirs(sdir)

    def build(**kw):
        e = ServeEngine([_pool(lanes=2)], warp=False, sync_spill=True,
                        journal_dir=jdir, spill_dir=sdir, **kw)
        e.warmup()
        return e

    victim = build(spill_after=0)
    kept = victim.submit(ServeRequest(n=N, seed=13, mode="ticks", ticks=16,
                                      scenario="steady", keep=True))
    done = victim.submit(ServeRequest(n=N, seed=2, mode="converge", ticks=40))
    flight = victim.submit(ServeRequest(n=N, seed=5, mode="ticks", ticks=800,
                                        scenario="steady"))
    for _ in range(200):
        victim.step()
        if (victim.status(kept)["state"] == "spilled"
                and victim.status(done)["state"] == "done"):
            break
    else:
        raise AssertionError("kill point never reached")
    done_result = victim.status(done)["result"]
    del victim  # the crash: no close, no flush

    rec = build()
    counts = rec.recover()
    assert counts == {"done": 1, "spilled": 1, "requeued": 1,
                      "cancelled": 0, "dropped": 0}
    assert rec.status(done)["state"] == "done"
    assert rec.status(done)["result"] == done_result  # replayed never
    assert rec.status(kept)["state"] == "spilled"
    assert rec.restore(kept)
    assert rec.status(flight)["state"] == "queued"
    rec.drain()
    assert rec.status(flight)["result"]["ticks_run"] == 800
    # Recovery compacted: the WAL holds only post-recovery transitions.
    with open(os.path.join(jdir, "wal.jsonl")) as f:
        ops = [json.loads(line) for line in f if line.strip()]
    assert not any(r["rid"] == done for r in ops)
    rec.close()


def test_recover_refuses_live_table_and_requires_journal(tmp_path):
    engine = ServeEngine([_pool()], warp=False)
    with pytest.raises(ValueError, match="journal_dir"):
        engine.recover()
    j = ServeEngine([_pool()], warp=False,
                    journal_dir=str(tmp_path / "j"))
    j.submit(ServeRequest(n=N, seed=1, mode="ticks", ticks=8,
                          scenario="steady"))
    with pytest.raises(ValueError, match="empty"):
        j.recover()
    j.close()


# -- server structured errors + client timeout/retry ------------------------


def test_server_structured_errors_keep_connection_alive():
    """Malformed JSON, non-object ops, unknown ops and bad arguments all
    come back as ``{"ok": false, "kind": ...}`` responses on a connection
    that keeps serving — and the client surfaces them as ServeError with
    the kind attached."""
    from kaboodle_tpu.serve.client import ServeClient, ServeError
    from kaboodle_tpu.serve.server import ServeServer

    engine = ServeEngine([_pool()], warp=False)
    server = ServeServer(engine, port=0)
    engine.warmup()

    async def drive() -> None:
        await server.start()
        reader, writer = await asyncio.open_connection("127.0.0.1",
                                                       server.port)
        for bad_line in (b"this is not json\n", b'[1, 2, 3]\n',
                         b'{"op": "no-such-op"}\n'):
            writer.write(bad_line)
            await writer.drain()
            resp = json.loads(await reader.readline())
            assert resp["ok"] is False
            assert resp["kind"] == "bad_request", resp
        # ...and the SAME connection still serves real ops.
        writer.write(json.dumps({"op": "stats"}).encode() + b"\n")
        await writer.drain()
        assert json.loads(await reader.readline())["ok"] is True
        writer.close()

        client = await ServeClient.connect(port=server.port)
        with pytest.raises(ServeError) as ei:
            await client.wait(999, timeout=5.0)
        assert ei.value.kind == "bad_request"
        with pytest.raises(ServeError) as ei:
            await client.restore(999, timeout=5.0)
        assert ei.value.kind == "bad_request"
        await client.shutdown()
        await server.close()

    asyncio.run(drive())


def test_client_timeout_desyncs_and_retry_backoff_rides_queue_full():
    """A timed-out wait raises builtin TimeoutError and poisons that
    connection (request/response pairing is broken); a fresh client works.
    submit(retries=) rides queue_full rejections with the server's
    retry-after until capacity frees."""
    from kaboodle_tpu.serve.admission import AdmissionController
    from kaboodle_tpu.serve.client import ServeClient, ServeError
    from kaboodle_tpu.serve.server import ServeServer

    engine = ServeEngine(
        [_pool(lanes=1)], warp=False,
        admission=AdmissionController(max_queue=1),
    )
    server = ServeServer(engine, port=0)
    engine.warmup()

    async def drive() -> None:
        await server.start()
        client = await ServeClient.connect(port=server.port)
        long = await client.submit(N, seed=1, mode="ticks", ticks=4000,
                                   scenario="steady")
        with pytest.raises(TimeoutError):
            await client.wait(long, timeout=0.05)
        with pytest.raises(ConnectionError, match="desynchronized"):
            await client.stats()
        await client.close()

        client = await ServeClient.connect(port=server.port)
        queued = await client.submit(N, seed=2, mode="ticks", ticks=8,
                                     scenario="steady")
        with pytest.raises(ServeError) as ei:  # lane + queue slot both held
            await client.submit(N, seed=3, mode="ticks", ticks=8,
                                scenario="steady")
        assert ei.value.kind == "queue_full"
        assert ei.value.retry_after_s > 0

        async def free_capacity() -> None:
            await asyncio.sleep(0.05)
            c = await ServeClient.connect(port=server.port)
            assert await c.cancel(queued)
            assert await c.cancel(long)
            await c.close()

        freer = asyncio.create_task(free_capacity())
        rid = await client.submit(N, seed=4, mode="ticks", ticks=8,
                                  scenario="steady", retries=10,
                                  backoff=0.05)
        await freer
        row = await client.wait(rid, timeout=30.0)
        assert row["state"] == "done"
        await client.shutdown()
        await server.close()

    asyncio.run(drive())
