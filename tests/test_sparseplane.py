"""sparseplane — the blocked_topk [N, K] engine (ISSUE 18).

Pins the sparse plane at three levels: the IR (a blocked_topk graph plans
into exactly the six-pass sparse program, and every other mode refuses
it), the kernel mechanics (counter-RNG determinism, block repair under
churn, convergence on small worlds), and the scaling claim (per-tick
bytes grow ~linearly in N at fixed K — the sub-quadratic contract the
million-peer bench is built on). The sparse-vs-dense DISTRIBUTION pins
(convergence-tick bands, stat agreement over matched seeds) live in the
fuzz suite (test_fuzz_parity.py); bit-exactness is not the contract here
— the dense engines stay the oracle, the sparse twins are stat-pinned.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kaboodle_tpu.config import SwimConfig
from kaboodle_tpu.spec import KNOWN, WAITING_FOR_PING
from kaboodle_tpu.sparseplane import (
    SparseSpec,
    SparseState,
    init_sparse_state,
    make_sparse_tick_fn,
    run_sparse_until_converged,
    simulate_sparse,
    sparse_fingerprint,
    sparse_idle_inputs,
)
from kaboodle_tpu.sparseplane.kernel import SPARSE_TAIL_PASSES
from kaboodle_tpu.sparseplane.repair import repair_blocks, reseed_revived
from kaboodle_tpu.sparseplane.rng import (
    STREAM_DRAW,
    STREAM_PING,
    stream_uniform,
)


def _cfg(**kw):
    kw.setdefault("join_broadcast_enabled", False)
    return SwimConfig(**kw)


def _spec(**kw):
    kw.setdefault("k", 16)
    kw.setdefault("gossip_fanout", 4)
    kw.setdefault("boot_contacts", 2)
    return SparseSpec(**kw)


# ---- the IR layout axis ----------------------------------------------------


def test_blocked_graph_plans_into_the_sparse_pass_order():
    from kaboodle_tpu.phasegraph import build_graph, plan

    g = build_graph(_cfg(deterministic=True), layout="blocked_topk")
    prog = plan(g, "sparse")
    assert prog.mode == "sparse"
    # the planned tail is the kernel's pass order (subset-order: every
    # planned pass appears in SPARSE_TAIL_PASSES, in the same sequence)
    names = [p.name for p in prog.tail]
    order = [n for n in SPARSE_TAIL_PASSES if n in names]
    assert names == order and "repair" in names and "finish" in names
    # dense-only ops are pruned WITH reasons, never silently
    pruned = dict(prog.pruned)
    assert "delivery_gate" in pruned
    assert all(why.strip() for why in pruned.values())
    assert "block_repair" not in pruned


def test_layout_and_mode_guards_refuse_cross_derivation():
    from kaboodle_tpu.phasegraph import build_graph, plan
    from kaboodle_tpu.phasegraph.graph import GraphError

    dense_g = build_graph(_cfg(deterministic=True))
    blocked_g = build_graph(_cfg(deterministic=True), layout="blocked_topk")
    with pytest.raises(GraphError, match="blocked_topk"):
        plan(dense_g, "sparse")
    for mode in ("full", "fused", "blocked"):
        with pytest.raises(GraphError, match="dense-layout"):
            plan(blocked_g, mode)
    # op_table screens the layout before TickGraph ever sees it
    with pytest.raises(ValueError, match="unknown layout"):
        build_graph(_cfg(), layout="csr")


def test_blocked_graph_rejects_unsupported_protocol_flags():
    from kaboodle_tpu.phasegraph import build_graph

    with pytest.raises(ValueError, match="join"):
        build_graph(SwimConfig(join_broadcast_enabled=True),
                    layout="blocked_topk")
    with pytest.raises(ValueError, match="faithful_indirect_ack"):
        build_graph(_cfg(faithful_indirect_ack=False), layout="blocked_topk")
    with pytest.raises(ValueError, match="telemetry"):
        build_graph(_cfg(), layout="blocked_topk", telemetry=True)


def test_make_sparse_tick_derives_from_the_graph():
    from kaboodle_tpu.phasegraph.derive import make_sparse_tick

    cfg, spec = _cfg(deterministic=True), _spec(k=8)
    tick = make_sparse_tick(cfg, spec)
    assert tick.graph.layout == "blocked_topk"
    assert set(tick.programs) == {"sparse"}
    n = 12
    st = init_sparse_state(n, spec, seed=0)
    st2, m = jax.jit(tick)(st, dataclasses.replace(
        sparse_idle_inputs(n), drop_rate=jnp.float32(0.0)))
    assert int(st2.tick) == 1 and int(st2.cursor) == 1
    assert 0.0 <= float(m.block_fill) <= 1.0


# ---- init + counter-RNG ----------------------------------------------------


def test_init_ring_contacts_and_fill():
    spec = _spec(k=8, boot_contacts=3)
    n = 10
    st = init_sparse_state(n, spec, seed=0)
    idx, s = np.asarray(st.nbr_idx), np.asarray(st.nbr_state)
    occ = s > 0
    assert occ.sum(axis=1).tolist() == [3] * n
    for i in range(n):
        assert sorted(idx[i, occ[i]]) == sorted(
            (i + j) % n for j in range(1, 4)
        )
    assert (s[occ] == KNOWN).all()
    assert (idx[~occ] == -1).all()


def test_counter_rng_is_positional_and_replayable():
    u = stream_uniform(7, 3, STREAM_DRAW, (5, 4))
    assert u.dtype == jnp.float32 and ((u >= 0) & (u < 1)).all()
    # same (seed, cursor, stream, position) -> same draw, always
    assert (np.asarray(u) == np.asarray(
        stream_uniform(7, 3, STREAM_DRAW, (5, 4)))).all()
    # any coordinate change decorrelates
    assert (np.asarray(u) != np.asarray(
        stream_uniform(7, 4, STREAM_DRAW, (5, 4)))).any()
    assert (np.asarray(u) != np.asarray(
        stream_uniform(7, 3, STREAM_PING, (5, 4)))).any()
    assert (np.asarray(u) != np.asarray(
        stream_uniform(8, 3, STREAM_DRAW, (5, 4)))).any()


def test_sparse_run_is_deterministic_replay():
    """No state outside SparseState: two runs from the same (seed, cursor)
    are bit-identical — the property the checkpoint resume leans on."""
    cfg, spec = _cfg(), _spec(k=8)
    n = 20
    inp = sparse_idle_inputs(n, ticks=8)
    a, ma = simulate_sparse(init_sparse_state(n, spec, seed=5), inp, cfg, spec)
    b, mb = simulate_sparse(init_sparse_state(n, spec, seed=5), inp, cfg, spec)
    for x, y in zip(jax.tree.leaves((a, ma)), jax.tree.leaves((b, mb))):
        xv, yv = np.asarray(x), np.asarray(y)
        if np.issubdtype(xv.dtype, np.floating):
            assert ((xv == yv) | (np.isnan(xv) & np.isnan(yv))).all()
        else:
            assert (xv == yv).all()
    # a different seed takes a different trajectory
    c, _ = simulate_sparse(init_sparse_state(n, spec, seed=6), inp, cfg, spec)
    assert (np.asarray(c.nbr_idx) != np.asarray(a.nbr_idx)).any()


# ---- block repair ----------------------------------------------------------


def _tiny_blocks():
    # 3 rows, K=4: row 0 has peer 1; row 1 full; row 2 empty.
    idx = np.array([[1, -1, -1, -1], [0, 2, 3, 4], [-1, -1, -1, -1]],
                   np.int32)
    s = np.where(idx >= 0, KNOWN, 0).astype(np.int8)
    t = np.where(idx >= 0, 5, 0).astype(np.int32)
    return jnp.asarray(idx), jnp.asarray(s), jnp.asarray(t)


def test_repair_inserts_dedups_and_skips():
    idx, s, t = _tiny_blocks()
    cand = jnp.asarray(np.array([
        [2, 1, 0, 2],    # row 0: new, already-in-block, self, duplicate
        [7, -1, -1, -1],  # row 1: full block -> overflow drop
        [-1, -1, -1, -1],
    ], np.int32))
    stamp = jnp.full(cand.shape, 9, jnp.int32)
    ni, ns, nt = repair_blocks(idx, s, t, cand, stamp)
    ni, ns, nt = np.asarray(ni), np.asarray(ns), np.asarray(nt)
    # row 0 gained exactly one entry: peer 2, KNOWN, stamped 9; the
    # in-block 1, the self 0 and the duplicate 2 were all dropped
    assert sorted(ni[0][ni[0] >= 0].tolist()) == [1, 2]
    slot = int(np.nonzero(ni[0] == 2)[0][0])
    assert ns[0, slot] == KNOWN and nt[0, slot] == 9
    # the pre-existing entry is untouched
    old = int(np.nonzero(ni[0] == 1)[0][0])
    assert nt[0, old] == 5
    # row 1 is full: the candidate is dropped, the block unchanged
    assert (ni[1] == np.array([0, 2, 3, 4])).all() and (nt[1] == 5).all()
    # row 2 untouched (no candidates)
    assert (ni[2] == -1).all() and (ns[2] == 0).all()


def test_repair_fills_multiple_slots_rank_matched():
    idx = jnp.full((1, 4), -1, jnp.int32)
    s = jnp.zeros((1, 4), jnp.int8)
    t = jnp.zeros((1, 4), jnp.int32)
    cand = jnp.asarray(np.array([[3, 1, 4, 1]], np.int32))
    stamp = jnp.asarray(np.array([[10, 11, 12, 13]], np.int32))
    ni, ns, nt = repair_blocks(idx, s, t, cand, stamp)
    ni, nt = np.asarray(ni), np.asarray(nt)
    got = {int(i): int(st) for i, st in zip(ni[0], nt[0]) if i >= 0}
    # three distinct candidates land, each with ITS OWN stamp; the
    # duplicate 1 keeps the earlier column's stamp
    assert got == {3: 10, 1: 11, 4: 12}


def test_reseed_revived_clears_and_reboots():
    spec = _spec(k=8, boot_contacts=2)
    n = 6
    st = init_sparse_state(n, spec, seed=0)
    # dirty row 3 with a WFP entry, then revive it
    idx = st.nbr_idx.at[3, 5].set(0)
    s = st.nbr_state.at[3, 5].set(WAITING_FOR_PING)
    revived = jnp.zeros((n,), bool).at[3].set(True)
    ni, ns, nt = reseed_revived(
        idx, s, st.nbr_timer, revived, 2, jnp.int32(40))
    ni, ns, nt = np.asarray(ni), np.asarray(ns), np.asarray(nt)
    assert sorted(ni[3][ni[3] >= 0].tolist()) == [4, 5]
    assert (ns[3][ni[3] >= 0] == KNOWN).all()
    assert (nt[3][ni[3] >= 0] == 40).all()
    assert (ns[3][ni[3] < 0] == 0).all()
    # un-revived rows keep their planes bit-for-bit (incl. the dirty WFP)
    others = np.arange(n) != 3
    assert (ni[others] == np.asarray(idx)[others]).all()
    assert (ns[others] == np.asarray(s)[others]).all()


# ---- end-to-end behavior ---------------------------------------------------


def test_sparse_boot_converges_to_full_agreement():
    # k >= n-1: full-view blocks, so fingerprint agreement is reachable
    # (at k < n-1 rows hold different subsets and "converged" is a
    # distribution property, pinned in the fuzz suite instead)
    cfg, spec = _cfg(), _spec(k=32, boot_contacts=2)
    n = 24
    st = init_sparse_state(n, spec, seed=1)
    fin, ticks, conv = run_sparse_until_converged(st, cfg, spec, max_ticks=64)
    assert bool(conv) and 0 < int(ticks) <= 64
    fp = np.asarray(sparse_fingerprint(fin))
    assert (fp == fp[0]).all()
    # every alive row's block is full of KNOWN entries at convergence
    occ = np.asarray(fin.nbr_state) > 0
    assert (occ.sum(axis=1) == min(spec.k, n - 1)).all()


def test_killed_peers_expire_from_every_block():
    cfg, spec = _cfg(ping_timeout_ticks=2), _spec(k=32, boot_contacts=2)
    n = 20
    st, _, conv = run_sparse_until_converged(
        init_sparse_state(n, spec, seed=2), cfg, spec, max_ticks=64)
    assert bool(conv)
    dead = [3, 11]
    kill = np.zeros((40, n), bool)
    kill[0, dead] = True
    inp = dataclasses.replace(
        sparse_idle_inputs(n, ticks=40), kill=jnp.asarray(kill))
    fin, _ = simulate_sparse(st, inp, cfg, spec)
    idx = np.asarray(fin.nbr_idx)
    occ = np.asarray(fin.nbr_state) > 0
    alive = np.asarray(fin.alive)
    assert not alive[dead].any()
    for i in np.nonzero(alive)[0]:
        assert not np.isin(idx[i, occ[i]], dead).any(), (
            f"row {i} still carries a dead peer after the expiry window"
        )
    # the survivors re-agree on the shrunken membership
    fp = np.asarray(sparse_fingerprint(fin))[alive]
    assert (fp == fp[0]).all()


def test_revived_peer_rejoins_through_repair():
    cfg, spec = _cfg(ping_timeout_ticks=2), _spec(k=16, boot_contacts=2)
    n = 16
    st, _, _ = run_sparse_until_converged(
        init_sparse_state(n, spec, seed=3), cfg, spec, max_ticks=64)
    ticks = 56
    kill = np.zeros((ticks, n), bool)
    revive = np.zeros((ticks, n), bool)
    kill[0, 5] = True
    revive[20, 5] = True
    inp = dataclasses.replace(
        sparse_idle_inputs(n, ticks=ticks),
        kill=jnp.asarray(kill), revive=jnp.asarray(revive))
    fin, _ = simulate_sparse(st, inp, cfg, spec)
    alive = np.asarray(fin.alive)
    assert alive.all()
    # the revived peer's gossip re-spreads it into every row's block
    idx, occ = np.asarray(fin.nbr_idx), np.asarray(fin.nbr_state) > 0
    carries = np.array([(idx[i, occ[i]] == 5).any() for i in range(n)])
    assert carries[np.arange(n) != 5].all()


def test_sparse_state_is_a_pytree_of_static_shapes():
    spec = _spec(k=8)
    st = init_sparse_state(12, spec, seed=0)
    leaves = jax.tree.leaves(st)
    assert len(leaves) == len(dataclasses.fields(SparseState))
    flat, treedef = jax.tree.flatten(st)
    assert jax.tree.unflatten(treedef, flat).n == 12
    assert st.nbr_idx.dtype == jnp.int32
    assert st.nbr_state.dtype == jnp.int8
    assert init_sparse_state(
        12, _spec(k=8, timer_dtype="int16"), seed=0
    ).nbr_timer.dtype == jnp.int16


# ---- the scaling contract --------------------------------------------------


@pytest.mark.slow
def test_sparse_tick_bytes_scale_sub_quadratically():
    """The million-peer claim, statically: AOT bytes-accessed of the
    steady sparse tick at N=8192 over N=1024 must sit far below the dense
    64x (8x data). The dense tick's [N, N] planes make the same ratio
    ~64x; a materialized [N, N] temp sneaking into the sparse kernel
    would send this ratio straight back there."""
    cfg, spec = _cfg(), _spec(k=16)

    def tick_bytes(n: int) -> int:
        tick = make_sparse_tick_fn(cfg, spec)
        comp = (
            jax.jit(tick)
            .lower(init_sparse_state(n, spec, seed=0), sparse_idle_inputs(n))
            .compile()
        )
        ca = comp.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        return int(ca.get("bytes accessed", 0))

    small, big = tick_bytes(1024), tick_bytes(8192)
    assert small > 0 and big > 0
    ratio = big / small
    assert ratio < 16, (
        f"sparse tick bytes grew {ratio:.1f}x over an 8x N step — "
        "sub-quadratic contract broken (dense is 64x)"
    )
