"""The telemetry plane: counters, flight recorder, manifests, trace export.

Cross-ENGINE counter parity (kernel == chunked == oracle, warp totals) lives
in tests/test_fuzz_parity.py with the other randomized arms; this file pins
the telemetry plane's own contracts — the pure-derived-values guarantee
(state bit-identical with telemetry on or off), the ring-buffer mechanics,
the manifest schema, and the exporters/summarizer.
"""

from __future__ import annotations

import dataclasses
import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kaboodle_tpu.config import SwimConfig
from kaboodle_tpu.sim.kernel import make_tick_fn
from kaboodle_tpu.sim.state import TickMetrics, idle_inputs, init_state
from kaboodle_tpu.telemetry import (
    RECORD_BYTES,
    ManifestWriter,
    ProtocolCounters,
    add_counters,
    chrome_trace_events,
    counters_table,
    counters_totals,
    init_recorder,
    leap_counters,
    read_manifest,
    record_tick,
    recorder_rows,
    run_record,
    scale_counters,
    validate_record,
    write_chrome_trace,
    zero_counters,
)
from kaboodle_tpu.telemetry.counters import FIELDS, TickTelemetry

CFG = SwimConfig()


# ---- counters helpers ------------------------------------------------------


def test_zero_counters_dtypes():
    z = zero_counters()
    for name in FIELDS:
        leaf = getattr(z, name)
        want = jnp.uint32 if name == "gossip_bytes" else jnp.int32
        assert leaf.dtype == want, name
        assert int(leaf) == 0


def test_add_and_scale_counters():
    a = dataclasses.replace(zero_counters(), pings_sent=jnp.int32(3))
    b = dataclasses.replace(zero_counters(), pings_sent=jnp.int32(4),
                            acks_sent=jnp.int32(1))
    s = add_counters(a, b)
    assert int(s.pings_sent) == 7 and int(s.acks_sent) == 1
    k = scale_counters(b, 5)
    assert int(k.pings_sent) == 20 and int(k.acks_sent) == 5
    assert k.gossip_bytes.dtype == jnp.uint32


def test_leap_counters_closed_form():
    c = leap_counters(n_alive=12, k=7)
    t = counters_totals(c)
    assert t["pings_sent"] == 84 and t["acks_sent"] == 84
    assert all(
        v == 0 for name, v in t.items() if name not in ("pings_sent", "acks_sent")
    )


def test_counters_table_layout():
    stacked = jax.tree.map(
        lambda x: jnp.stack([x, x + 2]), zero_counters()
    )
    table = counters_table(stacked)
    assert table.shape == (2,)
    np.testing.assert_array_equal(table["tick"], [0, 1])
    assert table["gossip_bytes"].dtype == np.uint32
    np.testing.assert_array_equal(table["pings_sent"], [0, 2])


# ---- the pure-derived-values contract --------------------------------------


@pytest.mark.slow
def test_state_trajectory_identical_with_telemetry_on():
    """telemetry=True only ADDS outputs: states and metrics are bit-equal
    to the plain build's every tick, and the fp digest plane equals the
    metrics' min/max envelope."""
    n = 12
    plain = jax.jit(make_tick_fn(CFG, faulty=True))
    telem = jax.jit(make_tick_fn(CFG, faulty=True, telemetry=True))
    sa = sb = init_state(n, seed=3)
    rng = np.random.default_rng(0)
    for t in range(8):
        kill = rng.random(n) < 0.1
        inp = dataclasses.replace(
            idle_inputs(n), kill=jnp.asarray(kill)
        )
        sa, m = plain(sa, inp)
        sb, out = telem(sb, inp)
        assert isinstance(out, TickTelemetry)
        for x, y in zip(jax.tree.leaves(sa), jax.tree.leaves(sb)):
            xv, yv = np.asarray(x), np.asarray(y)
            if xv.dtype == np.float32:
                assert ((xv == yv) | (np.isnan(xv) & np.isnan(yv))).all()
            else:
                assert (xv == yv).all()
        for x, y in zip(jax.tree.leaves(m), jax.tree.leaves(out.metrics)):
            assert (np.asarray(x) == np.asarray(y)).all()
        fp = np.asarray(out.fp)
        alive = np.asarray(sb.alive)
        assert fp.dtype == np.uint32 and fp.shape == (n,)
        assert fp[alive].min() == int(np.asarray(out.metrics.fingerprint_min))
        assert fp[alive].max() == int(np.asarray(out.metrics.fingerprint_max))


def test_telemetry_rejects_cut_probe():
    with pytest.raises(ValueError, match="_cut"):
        make_tick_fn(CFG, telemetry=True, _cut="A")


# ---- flight recorder -------------------------------------------------------


def _fake_out(msgs: int, tick: int) -> TickTelemetry:
    return TickTelemetry(
        metrics=TickMetrics(
            messages_delivered=jnp.int32(msgs),
            converged=jnp.asarray(tick % 2 == 0),
            agree_fraction=jnp.float32(1.0),
            mean_membership=jnp.float32(4.0),
            fingerprint_min=jnp.uint32(tick),
            fingerprint_max=jnp.uint32(tick + 1),
        ),
        counters=dataclasses.replace(
            zero_counters(), pings_sent=jnp.int32(msgs)
        ),
        fp=jnp.full((4,), tick, jnp.uint32),
    )


def test_recorder_partial_fill():
    rec = init_recorder(8, 4)
    for t in range(3):
        rec = record_tick(rec, t, _fake_out(10 + t, t))
    rows = recorder_rows(rec)
    assert rows["table"].shape == (3,)
    np.testing.assert_array_equal(rows["table"]["tick"], [0, 1, 2])
    np.testing.assert_array_equal(rows["table"]["pings_sent"], [10, 11, 12])
    assert rows["fp"].shape == (3, 4)


def test_recorder_ring_wraparound():
    """Writing 11 ticks into a 4-slot ring keeps exactly the last 4, in
    chronological order."""
    rec = init_recorder(4, 4)
    record = jax.jit(record_tick)
    for t in range(11):
        rec = record(rec, t, _fake_out(100 + t, t))
    rows = recorder_rows(rec)
    np.testing.assert_array_equal(rows["table"]["tick"], [7, 8, 9, 10])
    np.testing.assert_array_equal(
        rows["table"]["pings_sent"], [107, 108, 109, 110]
    )
    np.testing.assert_array_equal(rows["fp"][:, 0], [7, 8, 9, 10])
    assert int(rec.head) == 11


def test_recorder_rejects_zero_capacity():
    with pytest.raises(ValueError):
        init_recorder(0, 4)


@pytest.mark.slow
def test_run_until_converged_telemetry_matches_plain():
    from kaboodle_tpu.sim.runner import (
        run_until_converged,
        run_until_converged_telemetry,
    )

    n = 12
    st = init_state(n, seed=1)
    s0, t0, c0 = run_until_converged(st, CFG, max_ticks=32)
    s1, t1, c1, totals, rec = run_until_converged_telemetry(
        st, CFG, max_ticks=32, recorder_len=8
    )
    assert int(t0) == int(t1) and bool(c0) == bool(c1)
    for x, y in zip(jax.tree.leaves(s0), jax.tree.leaves(s1)):
        xv, yv = np.asarray(x), np.asarray(y)
        if xv.dtype == np.float32:
            assert ((xv == yv) | (np.isnan(xv) & np.isnan(yv))).all()
        else:
            assert (xv == yv).all()
    rows = recorder_rows(rec)
    assert rows["table"].shape[0] == min(int(t1), 8)
    if rows["table"].shape[0]:
        assert bool(rows["table"]["converged"][-1]) == bool(c1)
    # Entry agreement short-circuits at zero ticks: recorder stays empty,
    # totals stay zero (the zero-denominator regime profiling guards).
    s2, t2, c2, totals2, rec2 = run_until_converged_telemetry(
        s1, CFG, max_ticks=32, recorder_len=8
    )
    assert int(t2) == 0 and bool(c2)
    assert recorder_rows(rec2)["table"].shape[0] == 0
    assert all(v == 0 for v in counters_totals(totals2).values())


@pytest.mark.slow
def test_simulate_with_telemetry_counts_and_recorder_agree():
    from kaboodle_tpu.sim.runner import simulate_with_telemetry

    n, ticks, k = 10, 9, 4
    st = init_state(n, seed=2)
    final, metrics, counters, rec = simulate_with_telemetry(
        st, idle_inputs(n, ticks=ticks), CFG, recorder_len=k
    )
    assert np.asarray(counters.pings_sent).shape == (ticks,)
    rows = recorder_rows(rec)
    np.testing.assert_array_equal(
        rows["table"]["tick"], np.arange(ticks - k, ticks)
    )
    # Ring slots hold exactly the stacked counters' tail rows.
    table = counters_table(counters)
    for name in FIELDS:
        np.testing.assert_array_equal(
            rows["table"][name], table[name][ticks - k:], err_msg=name
        )


# ---- manifests -------------------------------------------------------------


def test_run_record_and_validate():
    rec = run_record("run", metric="x", value=np.int32(3),
                     arr=np.arange(2, dtype=np.uint32))
    assert validate_record(rec) is rec
    assert rec["value"] == 3 and rec["arr"] == [0, 1]
    json.dumps(rec)  # JSON-serializable end to end
    with pytest.raises(ValueError, match="schema"):
        validate_record({"kind": "run"})
    with pytest.raises(ValueError, match="kind"):
        validate_record({"schema": "kaboodle-telemetry/1"})
    with pytest.raises(ValueError, match="tick"):
        validate_record(
            {"schema": "kaboodle-telemetry/1", "kind": "tick", "tick": "no"}
        )


def test_manifest_writer_roundtrip(tmp_path):
    path = str(tmp_path / "m.jsonl")
    with ManifestWriter(path) as w:
        w.write("run", metric="t", value=1)
        w.write("tick", tick=0, pings_sent=4)
        assert w.records_written == 2
    recs = list(read_manifest(path))
    assert [r["kind"] for r in recs] == ["run", "tick"]
    # Default mode REPLACES: re-running a lane with the same path must not
    # merge two runs (doubled totals, duplicate ticks).
    with ManifestWriter(path) as w:
        w.write("tick", tick=1)
    assert [r["tick"] for r in read_manifest(path)] == [1]
    # append=True opts into accumulation (bench.py --manifest).
    with ManifestWriter(path, append=True) as w:
        w.write("tick", tick=2)
    assert [r["tick"] for r in read_manifest(path)] == [1, 2]


def test_read_manifest_rejects_garbage(tmp_path):
    path = tmp_path / "bad.jsonl"
    path.write_text('{"schema": "nope", "kind": "run"}\n')
    with pytest.raises(ValueError, match="schema"):
        list(read_manifest(str(path)))
    path.write_text("not json\n")
    with pytest.raises(ValueError, match="not JSON"):
        list(read_manifest(str(path)))


def test_write_tick_metrics_zero_ticks_is_empty(tmp_path):
    path = str(tmp_path / "m.jsonl")
    empty = TickMetrics(
        messages_delivered=np.zeros((0,), np.int32),
        converged=np.zeros((0,), bool),
        agree_fraction=np.zeros((0,), np.float32),
        mean_membership=np.zeros((0,), np.float32),
        fingerprint_min=np.zeros((0,), np.uint32),
        fingerprint_max=np.zeros((0,), np.uint32),
    )
    with ManifestWriter(path) as w:
        assert w.write_tick_metrics(empty) == 0
    assert list(read_manifest(path)) == []


def test_write_tick_metrics_with_counters_and_tick_override(tmp_path):
    path = str(tmp_path / "m.jsonl")
    m = TickMetrics(
        messages_delivered=np.asarray([5, 6], np.int32),
        converged=np.asarray([False, True]),
        agree_fraction=np.asarray([0.5, 1.0], np.float32),
        mean_membership=np.asarray([3.0, 3.0], np.float32),
        fingerprint_min=np.asarray([1, 2], np.uint32),
        fingerprint_max=np.asarray([8, 2], np.uint32),
    )
    counters = jax.tree.map(
        lambda x: jnp.stack([x + 1, x + 2]), zero_counters()
    )
    with ManifestWriter(path) as w:
        w.write_tick_metrics(m, counters=counters, ticks=np.asarray([4, 9]))
    recs = list(read_manifest(path))
    assert [r["tick"] for r in recs] == [4, 9]
    assert recs[0]["pings_sent"] == 1 and recs[1]["pings_sent"] == 2
    assert recs[1]["converged"] is True


def test_write_recorder_record(tmp_path):
    rec = init_recorder(3, 4)
    for t in range(5):
        rec = record_tick(rec, t, _fake_out(20 + t, t))
    path = str(tmp_path / "m.jsonl")
    with ManifestWriter(path) as w:
        w.write_recorder(rec)
    (r,) = list(read_manifest(path))
    assert r["kind"] == "recorder"
    assert [row["tick"] for row in r["rows"]] == [2, 3, 4]
    assert len(r["fp_unique"]) == 3


# ---- trace export ----------------------------------------------------------


def test_chrome_trace_leap_gap_and_counters():
    rows = [
        {"tick": 0, "pings_sent": 4, "converged": False},
        {"tick": 1, "pings_sent": 4, "converged": True},
        # ticks 2..9 leaped
        {"tick": 10, "pings_sent": 5, "converged": True},
    ]
    events = chrome_trace_events(rows)
    leaps = [e for e in events if e["name"] == "leap"]
    assert len(leaps) == 1
    assert leaps[0]["ts"] == 2 * 1000 and leaps[0]["dur"] == 8 * 1000
    assert leaps[0]["args"]["leaped_ticks"] == 8
    ticks = [e for e in events if e["name"] == "tick"]
    assert len(ticks) == 3
    series = [e for e in events if e["name"] == "pings_sent" and e["ph"] == "C"]
    assert [e["args"]["pings_sent"] for e in series] == [4, 4, 5]


def test_write_chrome_trace_loads_as_json(tmp_path):
    path = str(tmp_path / "trace.json")
    n = write_chrome_trace(path, [{"tick": 0, "acks_sent": 1}],
                           metadata={"lane": "test"})
    with open(path) as f:
        doc = json.load(f)
    assert len(doc["traceEvents"]) == n
    assert doc["otherData"]["lane"] == "test"


def test_write_chrome_trace_groups_keep_runs_on_separate_tracks(tmp_path):
    """A {label: rows} mapping puts each run on its own pid, so one run's
    ticks can neither overlap another's slices nor mask its leap gaps."""
    path = str(tmp_path / "trace.json")
    dense = [{"tick": t, "pings_sent": 8} for t in range(4)]
    warped = [{"tick": 0, "pings_sent": 8}, {"tick": 10, "pings_sent": 8}]
    write_chrome_trace(path, {"dense.jsonl": dense, "warp.jsonl": warped})
    with open(path) as f:
        events = json.load(f)["traceEvents"]
    pids = {e["pid"] for e in events}
    assert pids == {1, 2}
    leaps = [e for e in events if e["name"] == "leap"]
    # Only the warped run has a gap — and it survives the dense run's
    # presence (pooled onto one track, dense ticks 1..3 would mask it).
    assert len(leaps) == 1 and leaps[0]["pid"] == 2
    assert leaps[0]["args"]["leaped_ticks"] == 9
    names = {e["args"]["name"] for e in events if e["name"] == "process_name"}
    assert names == {"dense.jsonl", "warp.jsonl"}


def test_phase_slice_events_show_fused_pass_membership():
    """The per-pass track is sourced from the planner: every fused-program
    tail op lands in exactly the draw or update pass, pruned rare-phase ops
    appear once with the predicate terms that exclude them, and each tick
    gets one slice per executable pass."""
    from kaboodle_tpu.config import SwimConfig
    from kaboodle_tpu.phasegraph import build_graph, plan
    from kaboodle_tpu.telemetry.trace import phase_slice_events

    prog = plan(build_graph(SwimConfig(deterministic=True), faulty=True), "fused")
    rows = [{"tick": 0}, {"tick": 1}]
    events = phase_slice_events(prog, rows)
    slices = [e for e in events if e["ph"] == "X"]
    # one slice per (tick, pass), all on the dedicated phases thread
    assert len(slices) == 2 * len(prog.passes)
    assert {e["tid"] for e in slices} == {2}
    by_name = {e["name"]: e["args"]["ops"] for e in slices}
    assert "probe_draw" in by_name["tail:draw"]
    assert "call1" in by_name["tail:update"] and "call2" in by_name["tail:update"]
    # the pruned instant event names the dispatch-pred terms
    pruned = [e for e in events if e["name"] == "pruned"]
    assert len(pruned) == 1
    assert "suspicion" in pruned[0]["args"]["ops"]
    assert set(pruned[0]["args"]["pred_terms"]) == set(prog.pred_terms)


def test_write_chrome_trace_with_program_embeds_and_annotates(tmp_path):
    from kaboodle_tpu.config import SwimConfig
    from kaboodle_tpu.phasegraph import build_graph, plan

    prog = plan(build_graph(SwimConfig(deterministic=True), faulty=True), "fused")
    path = str(tmp_path / "trace.json")
    write_chrome_trace(path, [{"tick": 0, "acks_sent": 1}], program=prog)
    with open(path) as f:
        doc = json.load(f)
    assert doc["otherData"]["phase_program"]["mode"] == "fused"
    pass_slices = [e for e in doc["traceEvents"]
                   if e.get("tid") == 2 and e["ph"] == "X"]
    assert len(pass_slices) == len(prog.passes)


# ---- summarizer CLI --------------------------------------------------------


def _write_sample_manifest(path: str) -> None:
    with ManifestWriter(path) as w:
        w.write("run", metric="sim_run", n_peers=8, ticks=3, wall_s=0.1)
        for t in range(3):
            w.write("tick", tick=t, pings_sent=8, acks_sent=8,
                    converged=t > 0)


def test_summary_main_summarizes_and_exports(tmp_path, capsys):
    from kaboodle_tpu.telemetry.summary import main

    mpath = str(tmp_path / "m.jsonl")
    tpath = str(tmp_path / "t.json")
    _write_sample_manifest(mpath)
    assert main([mpath, "--trace", tpath, "--check"]) == 0
    out = capsys.readouterr().out
    tail = json.loads(out.strip().splitlines()[-1])
    assert tail["records"] == 4
    assert tail["counter_totals"]["pings_sent"] == 24
    assert tail["first_converged_tick"] == 1
    assert tail["final_converged"] is True
    with open(tpath) as f:
        assert json.load(f)["traceEvents"]


def test_summary_main_check_fails_on_empty_and_invalid(tmp_path, capsys):
    from kaboodle_tpu.telemetry.summary import main

    empty = tmp_path / "empty.jsonl"
    empty.write_text("")
    assert main([str(empty), "--check"]) == 1
    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"schema": "wrong/9", "kind": "run"}\n')
    assert main([str(bad)]) == 1


def test_cli_dispatches_telemetry_subcommand(tmp_path, capsys):
    from kaboodle_tpu.cli import main

    mpath = str(tmp_path / "m.jsonl")
    _write_sample_manifest(mpath)
    assert main(["telemetry", mpath]) == 0
    assert "telemetry:" in capsys.readouterr().out


# ---- CLI sim lanes ---------------------------------------------------------


@pytest.mark.slow
def test_cli_sim_telemetry_and_metrics_jsonl(tmp_path, capsys):
    from kaboodle_tpu.cli import main

    tpath = str(tmp_path / "run.jsonl")
    mpath = str(tmp_path / "metrics.jsonl")
    assert main(["--sim", "8", "--ticks", "4", "--telemetry", tpath,
                 "--metrics-jsonl", mpath]) == 0
    tail = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert "counter_totals" in tail
    recs = list(read_manifest(tpath))
    kinds = {r["kind"] for r in recs}
    assert {"run", "tick", "recorder"} <= kinds
    ticks = [r for r in recs if r["kind"] == "tick"]
    assert len(ticks) == 4 and "pings_sent" in ticks[0]
    assert tail["counter_totals"]["pings_sent"] == sum(
        r["pings_sent"] for r in ticks
    )
    mrecs = list(read_manifest(mpath))
    assert len(mrecs) == 4 and "pings_sent" not in mrecs[0]
    assert "messages_delivered" in mrecs[0]


@pytest.mark.slow
def test_cli_sim_warp_telemetry(tmp_path, capsys):
    from kaboodle_tpu.cli import main

    tpath = str(tmp_path / "warp.jsonl")
    assert main(["--sim", "8", "--ticks", "24", "--warp",
                 "--telemetry", tpath]) == 0
    tail = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert "counter_totals" in tail
    recs = list(read_manifest(tpath))
    runs = [r for r in recs if r["kind"] == "run"]
    assert runs and runs[0]["warp"] is True
    # The boot isn't quiescent at tick 0, so some dense ticks exist; their
    # manifest rows carry the REAL tick indices (gaps = leaped spans).
    ticks = [r["tick"] for r in recs if r["kind"] == "tick"]
    assert ticks == sorted(ticks)
    assert runs[0]["counter_totals"]["pings_sent"] > 0


# ---- fleet telemetry -------------------------------------------------------


@pytest.mark.slow
def test_fleet_member_counters_match_standalone():
    """Member e of a telemetry fleet run carries bit-exactly the counters a
    standalone telemetry run from the same seed produces (the vmap half of
    the counter-parity contract)."""
    from kaboodle_tpu.fleet.core import (
        fleet_idle_inputs,
        init_fleet,
        member_state,
        simulate_fleet,
    )
    from kaboodle_tpu.sim.runner import simulate_with_telemetry

    n, e_n, ticks = 10, 3, 6
    fleet = init_fleet(n, e_n)
    f2, tel = simulate_fleet(
        fleet, fleet_idle_inputs(n, e_n, ticks=ticks), CFG,
        faulty=True, telemetry=True,
    )
    assert np.asarray(tel.counters.pings_sent).shape == (ticks, e_n)
    assert np.asarray(tel.fp).shape == (ticks, e_n, n)
    for e in range(e_n):
        _, _, counters, _ = simulate_with_telemetry(
            member_state(fleet, e), idle_inputs(n, ticks), CFG
        )
        for name in FIELDS:
            np.testing.assert_array_equal(
                np.asarray(getattr(tel.counters, name))[:, e],
                np.asarray(getattr(counters, name)),
                err_msg=f"member {e} {name}",
            )


# ---- counter dtype discipline ---------------------------------------------


@pytest.mark.slow
def test_gossip_bytes_is_modular_uint32():
    """RECORD_BYTES scaling stays in uint32 (the documented modular model)
    and the emitted leaf is uint32 on a real tick."""
    assert RECORD_BYTES == 8
    tick = jax.jit(make_tick_fn(CFG, faulty=True, telemetry=True))
    st = init_state(8, seed=0)
    _, out = tick(st, idle_inputs(8))
    assert out.counters.gossip_bytes.dtype == jnp.uint32
    assert isinstance(out.counters, ProtocolCounters)
