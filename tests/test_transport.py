"""Real-network transport: wire codec parity, native engine, live interop.

The wire format is bincode 1.3's legacy encoding of the reference structs
(structs.rs:64-116): little-endian fixed-width ints, u64 lengths, u32 enum
tags, serde's binary SocketAddr form. Golden vectors below are hand-derived
from those rules; the Python codec, the C++ codec, and a live socket exchange
are all pinned against them and each other.

Live tests run the full protocol at ~20x speed (millisecond timing knobs) on
the host's real interface — the reference's 2x2 demo (SURVEY.md §4) as an
assertable test, which the reference itself never had.
"""

import itertools
import socket
import time
import zlib

import pytest

from kaboodle_tpu.oracle.fingerprint import crc_fingerprint
from kaboodle_tpu.transport import codec
from kaboodle_tpu.transport.native import (
    NativeEngine,
    codec_roundtrip_broadcast,
    codec_roundtrip_envelope,
    list_interfaces,
    native_crc32,
    probe_mesh,
)

_PORTS = itertools.count(17500)
_FAST = dict(period_ms=50, ping_timeout_ms=100, share_age_ms=500, rebroadcast_ms=500)


@pytest.fixture(scope="module")
def iface4():
    for i in list_interfaces():
        if i["family"] == 4 and i["broadcast"]:
            return i
    pytest.skip("no broadcast-capable IPv4 interface")


def _mesh(iface4, n, port, **overrides):
    kw = {**_FAST, **overrides}
    engines = [
        NativeEngine(
            iface4["ip"],
            iface4["broadcast"],
            port,
            identity=f"pane-{i}".encode(),
            rng_seed=i + 1,
            **kw,
        )
        for i in range(n)
    ]
    for e in engines:
        e.start()
    return engines


def _wait(pred, timeout_s=10.0):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.05)
    return pred()


# --- golden wire vectors ---------------------------------------------------


def test_codec_golden_vectors():
    # SwimEnvelope{identity: b"ab", msg: Ping}
    assert codec.encode_envelope(b"ab", {"kind": "PING"}).hex() == (
        "0200000000000000" + "6162" + "00000000"
    )
    # Ack{peer: 1.2.3.4:5, fp: 0xDEADBEEF, n: 7} in envelope with empty identity
    assert codec.encode_envelope(
        b"", {"kind": "ACK", "peer": "1.2.3.4:5", "fingerprint": 0xDEADBEEF, "num_peers": 7}
    ).hex() == (
        "0000000000000000"  # identity len 0
        + "02000000"  # variant Ack
        + "00000000" + "01020304" + "0500"  # SocketAddr::V4(1.2.3.4:5)
        + "efbeadde" + "07000000"
    )
    # SwimBroadcast::Join{addr: [::1]:9, identity: b"x"}
    assert codec.encode_broadcast(
        {"kind": "JOIN", "addr": "[::1]:9", "identity": b"x"}
    ).hex() == (
        "00000000" + "01000000" + "00" * 15 + "01" + "0900" + "0100000000000000" + "78"
    )
    # SwimBroadcast::Probe(192.0.2.2:17475)
    assert codec.encode_broadcast({"kind": "PROBE", "addr": "192.0.2.2:17475"}).hex() == (
        "02000000" + "00000000" + "c0000202" + "43" + "44"
    )


def test_codec_python_roundtrip():
    msgs = [
        {"kind": "PING"},
        {"kind": "PING_REQUEST", "peer": "10.0.0.1:9999"},
        {"kind": "ACK", "peer": "[fe80::1]:2", "fingerprint": 1, "num_peers": 2},
        {"kind": "KNOWN_PEERS", "peers": {"1.1.1.1:1": b"a", "[::2]:3": b"bb"}},
        {"kind": "KNOWN_PEERS_REQUEST", "fingerprint": 42, "num_peers": 3},
    ]
    for m in msgs:
        ident, back = codec.decode_envelope(codec.encode_envelope(b"idy", m))
        assert ident == b"idy" and back == m
    for b in [
        {"kind": "JOIN", "addr": "4.3.2.1:8", "identity": b"q"},
        {"kind": "FAILED", "addr": "4.3.2.1:8"},
        {"kind": "PROBE", "addr": "[fd00::2]:1"},
    ]:
        assert codec.decode_broadcast(codec.encode_broadcast(b)) == b


def test_codec_prefix_tolerance_q2_q4():
    """Q2: decoders read a prefix of the zero-padded buffer; Q4: a raw
    ProbeResponse + zero tail parses as an envelope carrying Ping."""
    wire = codec.encode_envelope(b"id", {"kind": "PING"}) + b"\x00" * 100
    assert codec.decode_envelope(wire) == (b"id", {"kind": "PING"})
    probe_reply = codec.encode_probe_response(b"who-am-i") + b"\x00" * 64
    ident, msg = codec.decode_envelope(probe_reply)
    assert ident == b"who-am-i" and msg == {"kind": "PING"}


def test_codec_cross_language():
    """The C++ codec decodes and re-encodes Python-encoded bytes unchanged."""
    env = codec.encode_envelope(
        b"xyz",
        {"kind": "KNOWN_PEERS", "peers": {"1.2.3.4:5": b"a", "[::1]:2": b"bb"}},
    )
    # NB: C++ re-encodes maps in address-sorted order; v4 sorts before v6 and
    # the Python dict above is already in that order.
    assert codec_roundtrip_envelope(env) == env
    bc = codec.encode_broadcast(
        {"kind": "JOIN", "addr": "[fd00::2]:777", "identity": b"node"}
    )
    assert codec_roundtrip_broadcast(bc) == bc
    assert codec_roundtrip_broadcast(b"\xff\xff\xff\xff") is None


def test_native_crc32_matches_zlib():
    for data in [b"", b"a", b"hello kaboodle", bytes(range(256))]:
        assert native_crc32(data) == zlib.crc32(data)


# --- live network tests ----------------------------------------------------


def test_4peer_demo_converges(iface4):
    """BASELINE config 1: the 2x2 demo — join, converge, matching CRC-32
    fingerprints, full peer maps with identities."""
    engines = _mesh(iface4, 4, next(_PORTS))
    try:
        assert _wait(
            lambda: len({e.fingerprint() for e in engines}) == 1
            and all(len(e.peers()) == 4 for e in engines)
        )
        # The fingerprint is reference-exact: recompute host-side from the
        # snapshot with the CRC/sort semantics of kaboodle.rs:71-83.
        snap = engines[0].peers()
        want = crc_fingerprint({a: e["identity"] for a, e in snap.items()})
        assert engines[0].fingerprint() == want
        idents = {e["identity"] for e in snap.values()}
        assert idents == {b"pane-0", b"pane-1", b"pane-2", b"pane-3"}
    finally:
        for e in engines:
            e.stop()
            e.close()


def test_departure_detection_and_events(iface4):
    engines = _mesh(iface4, 3, next(_PORTS))
    try:
        assert _wait(lambda: all(len(e.peers()) == 3 for e in engines))
        victim_addr = engines[2].self_addr()
        engines[2].stop()
        assert _wait(
            lambda: all(victim_addr not in e.peers() for e in engines[:2]), 15.0
        )
        evs = engines[0].drain_events()
        assert any(
            e["type"] == "departed" and e["addr"] == victim_addr for e in evs
        )
        assert len({e.fingerprint() for e in engines[:2]}) == 1
    finally:
        for e in engines:
            e.stop()
            e.close()


def test_probe_discovers_member_without_joining(iface4):
    port = next(_PORTS)
    engines = _mesh(iface4, 2, port)
    try:
        assert _wait(lambda: all(len(e.peers()) == 2 for e in engines))
        res = probe_mesh(
            iface4["ip"], iface4["broadcast"], port, start_ms=100, total_timeout_ms=8000
        )
        assert res is not None
        addr, ident = res
        assert addr in {e.self_addr() for e in engines}
        assert ident in {b"pane-0", b"pane-1"}
        # The prober did not join: peer counts unchanged.
        assert all(len(e.peers()) == 2 for e in engines)
        # total_timeout_ms=0 = the reference's retry-forever mode
        # (discovery.rs:51-72); with a member up it returns on the first
        # backoff round, so this exercises the no-deadline path hang-free.
        res = probe_mesh(
            iface4["ip"], iface4["broadcast"], port, start_ms=100,
            total_timeout_ms=0,
        )
        assert res is not None
    finally:
        for e in engines:
            e.stop()
            e.close()


def test_wire_interop_with_independent_python_socket(iface4):
    """A plain Python socket speaking the Python codec is a valid mesh peer:
    send Ping, get a well-formed Ack back (kaboodle.rs:513-532)."""
    engines = _mesh(iface4, 1, next(_PORTS))
    try:
        target = engines[0].self_addr()
        host, _, port = target.rpartition(":")
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.bind((iface4["ip"], 0))
            s.settimeout(5.0)
            s.sendto(codec.encode_envelope(b"py-peer", {"kind": "PING"}), (host, int(port)))
            data, _ = s.recvfrom(10240)
            my_port = s.getsockname()[1]
        ident, msg = codec.decode_envelope(data + b"\x00" * 16)
        assert ident == b"pane-0"
        assert msg["kind"] == "ACK"
        assert msg["peer"] == target  # the engine acks with its own address
        assert msg["num_peers"] == 2  # self + the python peer (Q1 marked us)
        # Q1: our datagram made us a member; the fingerprint must now cover us.
        me = f"{iface4['ip']}:{my_port}"
        assert me in engines[0].peers()
        assert engines[0].peers()[me]["identity"] == b"py-peer"
    finally:
        for e in engines:
            e.stop()
            e.close()


def test_ipv6_multicast_path():
    v6 = [i for i in list_interfaces() if i["family"] == 6 and not i["ip"].startswith("fe80")]
    if not v6:
        pytest.skip("no global IPv6 interface")
    port = next(_PORTS)
    engines = [
        NativeEngine(
            v6[0]["ip"],
            "ff02::1213:1989",  # the reference group (networking.rs:86)
            port,
            iface_index=v6[0]["ifindex"],
            identity=f"v6-{i}".encode(),
            rng_seed=i + 1,
            **_FAST,
        )
        for i in range(2)
    ]
    for e in engines:
        e.start()
    try:
        assert _wait(
            lambda: len({e.fingerprint() for e in engines}) == 1
            and all(len(e.peers()) == 2 for e in engines)
        )
    finally:
        for e in engines:
            e.stop()
            e.close()
