"""warp — event-horizon fast-forward: bit-exactness + contracts.

The leap kernel's whole value is that it is NOT a new simulator: a warped
run must be indistinguishable, bit for bit, from dense tick-by-tick
execution on every parity config the repo already pins — full state,
lean+int16, sharded (GSPMD), and fleet members — plus the runner contracts
(exact tick budgets, boundary metrics, the converge-loop entry check this
PR's satellite adds). The randomized whole-schedule arm lives in
tests/test_fuzz_parity.py.
"""

from __future__ import annotations

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kaboodle_tpu.config import SwimConfig
from kaboodle_tpu.sim.kernel import make_tick_fn
from kaboodle_tpu.sim.runner import run_until_converged, simulate
from kaboodle_tpu.sim.scenario import Scenario
from kaboodle_tpu.sim.state import idle_inputs, init_state
from kaboodle_tpu.warp.horizon import (
    decode_signature,
    earliest_timer_expiry,
    make_expiry_fn,
    make_quiescence_fn,
    make_signature_fn,
    next_static_event,
    static_event_ticks,
)
from kaboodle_tpu.warp.leap import make_leap_fn
from kaboodle_tpu.warp.runner import (
    CHUNK_BUCKETS,
    MIN_LEAP,
    WarpLedger,
    fleet_quiescence_mask,
    leap_cache,
    run_fleet_warped,
    run_warped,
    simulate_warped,
)


def _assert_leaves_equal(tree_a, tree_b, ctx=""):
    for a, b in zip(jax.tree.leaves(tree_a), jax.tree.leaves(tree_b)):
        av, bv = np.asarray(a), np.asarray(b)
        if av.dtype == np.float32:  # latency plane carries NaNs (no sample)
            assert ((av == bv) | (np.isnan(av) & np.isnan(bv))).all(), ctx
        else:
            assert (av == bv).all(), (ctx, av.dtype)


def _dense_trajectory(st, cfg, ticks, faulty=False, inputs=None):
    tick = jax.jit(make_tick_fn(cfg, faulty=faulty))
    idle = idle_inputs(st.n)
    states = []
    for t in range(ticks):
        inp = idle if inputs is None else jax.tree.map(lambda x: x[t], inputs)
        st, m = tick(st, inp)
        states.append(st)
    return st, states


def _converged_init(n, seed=0, **kw):
    return init_state(n, seed=seed, ring_contacts=n - 1, announced=True, **kw)


# ---------------------------------------------------------------------------
# leap vs dense, per parity config


@pytest.mark.parametrize("det", [True, False])
def test_leap_matches_dense_full_state(det):
    """Full state (latency EWMA + identity views), int32 timers: a 9-tick
    leap equals 9 dense fault-free ticks on every leaf."""
    n, k = 32, 9
    cfg = SwimConfig(deterministic=det)
    st = _converged_init(n, seed=3)
    dense, _ = _dense_trajectory(st, cfg, k)
    _assert_leaves_equal(dense, jax.jit(make_leap_fn(cfg, k))(st), f"det={det}")


def test_leap_matches_dense_lean_int16():
    """The bench state variant (no latency, instant identity, int16 timers)
    with a span long enough that refreshed entries re-enter the oldest-5
    rotation (k > n)."""
    n, k = 48, 70
    cfg = SwimConfig()
    st = _converged_init(n, seed=5, track_latency=False, instant_identity=True,
                         timer_dtype=jnp.int16)
    dense, _ = _dense_trajectory(st, cfg, k)
    _assert_leaves_equal(dense, jax.jit(make_leap_fn(cfg, k))(st), "lean+int16")


def test_leap_matches_dense_with_dead_rows():
    """Quiescent steady state AFTER churn: a fully-purged dead peer (absent
    from every survivor's map) leaves frozen dead rows the leap must carry
    untouched while survivors keep pinging."""
    n = 24
    cfg = SwimConfig()
    st = _converged_init(n, seed=1)
    # Kill one peer, then give the survivors the full purge window (the
    # detection-completeness bound is ~2N ticks; right after the kill the
    # mesh is only TRANSIENTLY converged — everyone still agrees on the
    # not-yet-purged dead peer).
    inp = Scenario(n, 1, seed=0).kill_at(0, [n // 2]).build()
    tick = jax.jit(make_tick_fn(cfg, faulty=True))
    st, _m = tick(st, jax.tree.map(lambda x: x[0], inp))
    st, _ = _dense_trajectory(st, cfg, 4 * n)
    alive = np.asarray(st.alive)
    assert not (np.asarray(st.state)[alive][:, n // 2] > 0).any(), "not purged"
    # The purged steady state may still owe a dense tick or two (stale
    # anti-entropy ledger); run_warped handles that, then leaps.
    k = 12
    dense, _ = _dense_trajectory(st, cfg, k)
    warped, ticks_run, wconv = run_warped(st, cfg, k, recheck_every=2)
    assert int(ticks_run) == k and bool(wconv)
    _assert_leaves_equal(dense, warped, "dead rows")
    assert not bool(np.asarray(warped.alive)[n // 2])


def test_run_warped_from_unconverged_matches_dense():
    """An unconverged boot runs dense until quiescence then leaps; the whole
    budget must still be bit-exact with pure dense ticking."""
    n, ticks = 32, 24
    cfg = SwimConfig()
    st = init_state(n, seed=2, ring_contacts=2)
    dense, _ = _dense_trajectory(st, cfg, ticks)
    warped, ticks_run, conv = run_warped(st, cfg, ticks, recheck_every=4)
    assert int(ticks_run) == ticks
    _assert_leaves_equal(dense, warped, "unconverged entry")


# ---------------------------------------------------------------------------
# scenario runner: boundaries + metrics


def test_simulate_warped_scenario_boundaries_and_metrics():
    """Sparse-fault schedule (manual pings + a kill): the warped run equals
    dense at every event-horizon boundary and at termination, and the
    densely-executed ticks' metrics equal the dense scan's rows."""
    n, T = 24, 48
    cfg = SwimConfig()
    sc = (Scenario(n, T, seed=0)
          .manual_ping_at(8, 0, 2)
          .kill_at(16, [3])
          .manual_ping_at(40, 1, 5))
    st = _converged_init(n, seed=1)
    inp = sc.build()

    dense_final, dense_m = jax.jit(
        lambda s, i: simulate(s, i, cfg, faulty=True)
    )(st, inp)
    _, dense_states = _dense_trajectory(st, cfg, T, faulty=True, inputs=inp)

    boundaries = []
    warped, dense_ticks, warped_m = simulate_warped(
        st, inp, cfg, faulty=True, recheck_every=4,
        on_boundary=lambda t, s: boundaries.append((t, s)),
    )
    _assert_leaves_equal(dense_final, warped, "final state")
    assert len(boundaries) >= 2  # at least one leap happened
    for t, s in boundaries:
        if t == 0:
            _assert_leaves_equal(st, s, "boundary 0")
        else:
            _assert_leaves_equal(dense_states[t - 1], s, f"boundary {t}")
    # Metrics of every densely executed tick match the dense scan's rows.
    for j, t in enumerate(dense_ticks):
        _assert_leaves_equal(
            jax.tree.map(lambda x: x[t], dense_m),
            jax.tree.map(lambda x: x[j], warped_m),
            f"metrics at tick {t}",
        )
    # The scheduled events themselves always run dense.
    assert {8, 16, 40} <= set(int(t) for t in dense_ticks)


def test_simulate_warped_all_quiescent_no_dense_ticks():
    """A fault-free schedule from a converged init leaps end to end: zero
    dense ticks, empty metrics, exact final state."""
    n, T = 24, 32
    cfg = SwimConfig()
    st = _converged_init(n, seed=4)
    inp = Scenario(n, T, seed=0).build()
    dense_final, _ = jax.jit(lambda s, i: simulate(s, i, cfg, faulty=True))(st, inp)
    warped, dense_ticks, metrics = simulate_warped(st, inp, cfg, faulty=True)
    assert dense_ticks.size == 0 and metrics is None
    _assert_leaves_equal(dense_final, warped, "all-leap")


# ---------------------------------------------------------------------------
# horizon pieces


def test_static_event_ticks_classification():
    n, T = 8, 12
    sc = (Scenario(n, T, seed=0)
          .kill_at(2, [1]).revive_at(5, [1])
          .drop(0.1, start=7, stop=8)
          .manual_ping_at(9, 0, 3))
    ev = static_event_ticks(sc.build())
    assert list(np.nonzero(ev)[0]) == [2, 5, 7, 9]
    assert next_static_event(ev, 0) == 2
    assert next_static_event(ev, 3) == 5
    assert next_static_event(ev, 10) == T
    # All-True drop_ok and a uniform nonzero partition gate nothing.
    idle = idle_inputs(n, ticks=T)
    quiet = dataclasses.replace(
        idle,
        drop_ok=jnp.ones((T, n, n), dtype=bool),
        partition=jnp.full((T, n), 3, dtype=jnp.int32),
    )
    assert not static_event_ticks(quiet).any()


def test_quiescence_predicate():
    n = 16
    cfg = SwimConfig()
    q = make_quiescence_fn(cfg)
    assert bool(q(_converged_init(n)))
    # Unconverged boot: not quiescent.
    assert not bool(q(init_state(n, seed=0, ring_contacts=2)))
    # A waiting cell arms a suspicion timer: not quiescent, expiry reported.
    st = _converged_init(n)
    state = np.asarray(st.state).copy()
    timer = np.asarray(st.timer).copy()
    state[0, 1] = 2  # WAITING_FOR_PING
    timer[0, 1] = 0
    st_w = dataclasses.replace(
        st, state=jnp.asarray(state), timer=jnp.asarray(timer)
    )
    assert not bool(q(st_w))
    assert int(make_expiry_fn(cfg)(st_w)) == cfg.ping_timeout_ticks
    assert int(make_expiry_fn(cfg)(st)) == np.iinfo(np.int32).max


# ---------------------------------------------------------------------------
# sharded + fleet integration


def test_run_warped_sharded_matches_dense():
    """The leap under GSPMD (row-sharded scan carries, cross-shard scatter)
    equals the sharded dense trajectory and stays sharded."""
    from kaboodle_tpu.parallel import make_mesh, make_sharded_tick, shard_state

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    n, ticks = 64, 16
    mesh = make_mesh(8)
    cfg = SwimConfig()
    st = shard_state(_converged_init(n, seed=5), mesh)
    stick = jax.jit(make_sharded_tick(cfg, mesh, faulty=False))
    idle = idle_inputs(n)
    dense = st
    for _ in range(ticks):
        dense, _m = stick(dense, idle)
    warped, ticks_run, conv = run_warped(st, cfg, ticks, mesh=mesh)
    assert int(ticks_run) == ticks and bool(conv)
    _assert_leaves_equal(dense, warped, "sharded")
    assert len(warped.state.sharding.device_set) == 8


def test_fleet_warp_per_member_mask_and_parity():
    """A mixed fleet (one converged member, one mid-boot) reports a mixed
    horizon mask, and every member's warped trajectory is bit-exact with its
    standalone run — whether it leaped or rode the dense lockstep."""
    from kaboodle_tpu.fleet.core import FleetState, member_state

    n, ticks = 16, 12
    cfg = SwimConfig()
    members = [_converged_init(n, seed=0), init_state(n, seed=1, ring_contacts=2)]
    mesh_state = jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *members)
    fleet = FleetState(mesh=mesh_state, drop_rate=jnp.zeros((2,), jnp.float32))
    mask = np.asarray(fleet_quiescence_mask(fleet, cfg))
    assert mask.tolist() == [True, False]

    out, ticks_run, conv = run_fleet_warped(fleet, cfg, ticks, recheck_every=4)
    assert int(ticks_run) == ticks
    for e in range(2):
        ref, _ = _dense_trajectory(members[e], cfg, ticks)
        _assert_leaves_equal(ref, member_state(out, e), f"member {e}")
    assert bool(np.asarray(conv).all())


def test_fleet_warp_all_quiescent_leaps():
    """An all-converged fleet leaps as one vmapped program; member k equals
    the standalone warped (== dense) run."""
    from kaboodle_tpu.fleet.core import init_fleet, member_state

    n, e, ticks = 16, 4, 10
    cfg = SwimConfig()
    fleet = init_fleet(n, e, ring_contacts=n - 1, announced=True)
    assert np.asarray(fleet_quiescence_mask(fleet, cfg)).all()
    out, ticks_run, conv = run_fleet_warped(fleet, cfg, ticks)
    assert int(ticks_run) == ticks and bool(np.asarray(conv).all())
    for k in range(e):
        ref, _, _ = run_warped(member_state(fleet, k), cfg, ticks)
        _assert_leaves_equal(ref, member_state(out, k), f"member {k}")


# ---------------------------------------------------------------------------
# Warp 2.0: activity signature + hybrid (near-quiescent) spans


def _drain_state(n, cfg, victims, seed=3, max_dense=80, **state_kw):
    """A mid-drain near-quiescent state, built by running the REAL engine:
    kill ``victims``, then tick densely until the signature classes the
    state ``hybrid`` (every survivor's cell for the dead peers armed)."""
    st = _converged_init(n, seed=seed, **state_kw)
    inp = Scenario(n, 1, seed=0).kill_at(0, victims).build()
    st, _ = jax.jit(make_tick_fn(cfg, faulty=True))(
        st, jax.tree.map(lambda x: x[0], inp)
    )
    tick = jax.jit(make_tick_fn(cfg, faulty=False))
    sig = make_signature_fn(cfg)
    idle = idle_inputs(n)
    for _ in range(max_dense):
        if decode_signature(sig(st)).mode == "hybrid":
            return st
        st, _ = tick(st, idle)
    raise AssertionError("drain never reached the hybrid class")


def test_signature_classes_and_quiescence_equivalence():
    """Class decode: converged -> leap, mid-boot -> dense, armed drain ->
    hybrid; and bits == 0 is exactly the strict quiescence predicate."""
    n = 20
    cfg = SwimConfig(ping_timeout_ticks=40)
    sig = make_signature_fn(cfg)
    q = make_quiescence_fn(cfg)

    conv = _converged_init(n)
    c = decode_signature(sig(conv))
    assert c.mode == "leap" and c.bits == 0 and c.describe()["terms"] == []

    boot = init_state(n, seed=0, ring_contacts=2)
    cb = decode_signature(sig(boot))
    assert cb.mode == "dense" and "missing_alive" in cb.describe()["terms"]

    drain = _drain_state(n, cfg, [n // 2])
    cd = decode_signature(sig(drain))
    assert cd.mode == "hybrid" and "armed" in cd.describe()["terms"]
    assert cd.expiry > cd.tick  # the hybrid class always has a window
    assert cd.bucket >= 1  # active rows counted
    assert earliest_timer_expiry(drain, cfg) == cd.expiry

    # A waiting cell on an ALIVE peer is refutable -> dense, never hybrid.
    state = np.asarray(conv.state).copy()
    state[0, 1] = 2  # WAITING_FOR_PING on an alive peer
    wa = dataclasses.replace(conv, state=jnp.asarray(state))
    cwa = decode_signature(sig(wa))
    assert cwa.mode == "dense" and "waiting_on_alive" in cwa.describe()["terms"]

    for st in (conv, boot, drain, wa):
        assert (decode_signature(sig(st)).bits == 0) == bool(q(st))


@pytest.mark.parametrize("det,lean", [(True, False), (False, False), (False, True)])
def test_hybrid_leap_matches_dense_on_drain(det, lean):
    """The hybrid span program vs dense over a real mid-drain state (armed
    timers on dead peers), per state variant — including the masked
    (traced-k) build at k_m == k and k_m == 0."""
    n, k = 24, 8
    cfg = SwimConfig(deterministic=det, ping_timeout_ticks=48)
    kw = dict(track_latency=not lean, instant_identity=lean,
              timer_dtype=jnp.int16 if lean else jnp.int32)
    st = _drain_state(n, cfg, [5, 11], **kw)
    dense, _ = _dense_trajectory(st, cfg, k)
    _assert_leaves_equal(
        dense, jax.jit(make_leap_fn(cfg, k, hybrid=True))(st), "hybrid"
    )
    masked = jax.jit(make_leap_fn(cfg, 16, hybrid=True, masked=True))
    _assert_leaves_equal(dense, masked(st, jnp.int32(k)), "masked k_m=k")
    _assert_leaves_equal(st, masked(st, jnp.int32(0)), "masked k_m=0")


def test_hybrid_leap_sterile_ae_fires_and_matches_dense():
    """A drain state with DISAGREEING fingerprints (half the rows already
    removed a victim): anti-entropy candidates fire every tick — the
    sterile-AE machinery (partner selection, request/reply timer marks,
    kpr ledger) must reproduce dense bit-for-bit, and the kpr ledger must
    show live partners (proving the path was actually exercised)."""
    n, k = 24, 10
    cfg = SwimConfig(ping_timeout_ticks=64)
    st = _drain_state(n, cfg, [5, 11])
    # Half the survivors have already worked victim 5 out of their map.
    S = np.asarray(st.state).copy()
    alive = np.asarray(st.alive)
    rows = np.arange(n) >= n // 2
    S[alive & rows, 5] = 0
    st = dataclasses.replace(st, state=jnp.asarray(S))
    sig = decode_signature(make_signature_fn(cfg)(st))
    assert sig.mode == "hybrid"
    assert "fp_disagree" in sig.describe()["terms"]
    dense, _ = _dense_trajectory(st, cfg, k)
    hyb = jax.jit(make_leap_fn(cfg, k, hybrid=True))(st)
    _assert_leaves_equal(dense, hyb, "sterile AE")
    assert (np.asarray(hyb.kpr_partner) >= 0).any(), "AE never fired"


def test_run_warped_drain_crosses_expiry_bit_exact():
    """run_warped over a budget that crosses the first timer expiry: hybrid
    spans leap the waiting window, the expiry/escalation season runs
    dense, and the whole budget is bit-exact with dense ticking. The
    ledger records hybrid spans."""
    n = 24
    cfg = SwimConfig(ping_timeout_ticks=32)
    st = _drain_state(n, cfg, [7])
    ticks = (earliest_timer_expiry(st, cfg) - int(st.tick)) + 24
    dense, _ = _dense_trajectory(st, cfg, ticks)
    ledger = WarpLedger()
    out, ticks_run, _ = run_warped(st, cfg, ticks, recheck_every=4,
                                   ledger=ledger)
    assert int(ticks_run) == ticks
    _assert_leaves_equal(dense, out, "drain crossing expiry")
    assert any(r["engine"] == "hybrid" for r in ledger.spans)


def test_hybrid_disabled_knob_still_bit_exact():
    """hybrid=False (the --no-warp-hybrid knob) demotes hybrid-class spans
    to dense — slower, never wrong."""
    n = 20
    cfg = SwimConfig(ping_timeout_ticks=32)
    st = _drain_state(n, cfg, [9])
    ticks = 16
    dense, _ = _dense_trajectory(st, cfg, ticks)
    ledger = WarpLedger()
    out, _, _ = run_warped(st, cfg, ticks, hybrid=False, ledger=ledger)
    _assert_leaves_equal(dense, out, "hybrid off")
    assert not any(r["engine"] == "hybrid" for r in ledger.spans)


# ---------------------------------------------------------------------------
# satellite: earliest_timer_expiry boundary cases


def _arm_cell(st, row, col, timer_val):
    """Kill ``col`` and leave exactly ONE armed waiting cell on it (at
    ``row``); every other survivor has already purged it — the minimal
    hybrid-class state with a single timer horizon."""
    state = np.asarray(st.state).copy()
    timer = np.asarray(st.timer).copy()
    alive = np.asarray(st.alive).copy()
    alive[col] = False  # waiting cells must point at dead peers (hybrid class)
    state[:, col] = 0  # everyone else already purged the dead peer
    state[row, col] = 2  # WAITING_FOR_PING
    timer[row, col] = timer_val
    state[col] = 0  # dead row's map frozen empty (post-purge shape)
    state[col, col] = 1
    return dataclasses.replace(
        st, state=jnp.asarray(state), timer=jnp.asarray(timer),
        alive=jnp.asarray(alive),
    )


@pytest.mark.parametrize("offset", [0, 1])
def test_expiry_on_span_last_tick_and_first_after(offset):
    """A timer expiring exactly on the span's last tick (the span must
    shrink so the expiry tick runs dense) vs on the first tick after the
    span (the whole span leaps) — each pinned bit-exact against dense.

    With expiry at entry_tick + span - offset: offset=1 puts the A2 fire
    INSIDE the naive span, offset=0 exactly at its end (first tick after
    the span's last leaped tick)."""
    n, span = 20, 12
    cfg = SwimConfig(ping_timeout_ticks=64)
    st = _converged_init(n, seed=2)
    t0 = int(st.tick)
    # deadline = timer + timeout; place it at t0 + span - offset.
    st = _arm_cell(st, 3, 8, t0 + span - offset - cfg.ping_timeout_ticks)
    assert earliest_timer_expiry(st, cfg) == t0 + span - offset
    dense, _ = _dense_trajectory(st, cfg, span)
    ledger = WarpLedger()
    out, ticks_run, _ = run_warped(st, cfg, span, recheck_every=2,
                                   ledger=ledger)
    assert int(ticks_run) == span
    _assert_leaves_equal(dense, out, f"expiry offset {offset}")
    # The leaped portion never covers the expiry tick itself.
    leaped = sum(r["ticks"] for r in ledger.spans)
    assert leaped <= span - offset


def test_expiry_interleaved_with_scheduled_event():
    """A scheduled manual ping INSIDE the waiting window: the span must
    stop at the event even though the timer horizon is further out, and
    the whole schedule stays bit-exact with dense."""
    n, T = 20, 24
    cfg = SwimConfig(ping_timeout_ticks=18)
    st = _converged_init(n, seed=4)
    t0 = int(st.tick)
    st = _arm_cell(st, 2, 9, t0)  # expiry at t0 + 18
    sc = Scenario(n, T, seed=0).manual_ping_at(6, 0, 3)  # event before expiry
    inp = sc.build()
    tick = jax.jit(make_tick_fn(cfg, faulty=True))
    sd = st
    for t in range(T):
        sd, _ = tick(sd, jax.tree.map(lambda x: x[t], inp))
    wf, dense_ticks, _ = simulate_warped(st, inp, cfg, faulty=True,
                                         recheck_every=4)
    _assert_leaves_equal(sd, wf, "event inside waiting window")
    dense_set = set(int(t) for t in dense_ticks)
    assert 6 in dense_set  # the scheduled event ran dense
    assert 18 in dense_set  # the expiry tick ran dense too


# ---------------------------------------------------------------------------
# satellite: the bounded program cache


def test_program_cache_rejects_non_bucket_chunks():
    with pytest.raises(ValueError, match="power-of-two bucket"):
        leap_cache.get(("fam",), "strict", 12, lambda: None)
    with pytest.raises(ValueError, match="power-of-two bucket"):
        leap_cache.get(("fam",), "strict", MIN_LEAP // 2, lambda: None)


def test_program_cache_bounded_across_irregular_span_lengths():
    """Irregular event schedules (many distinct span lengths) compile at
    most len(CHUNK_BUCKETS) programs per family — the regression this
    satellite fixes is one compiled program per distinct span length."""
    n = 16
    cfg = SwimConfig()
    st = _converged_init(n, seed=6)
    before = {k for k in leap_cache._programs if k[0] == (cfg, None)}
    for ticks in (9, 11, 13, 17, 21, 27, 33, 41, 53, 61):
        out, ticks_run, _ = run_warped(st, cfg, ticks)
        assert int(ticks_run) == ticks
    after = {k for k in leap_cache._programs if k[0] == (cfg, None)}
    new = after - before
    # every new program is a bucket, and far fewer than distinct lengths
    assert all(k[2] in CHUNK_BUCKETS for k in new)
    assert len(new) <= len(CHUNK_BUCKETS)
    stats = leap_cache.stats()
    assert stats["max_family_programs"] <= stats["per_family_bound"]


# ---------------------------------------------------------------------------
# Warp 2.0 fleet: per-member horizons


def test_fleet_per_member_horizons_heterogeneous_parity():
    """A 3-member fleet — converged, mid-drain (hybrid class), mid-boot
    (dense class) — advances each member bit-exactly to its standalone
    dense trajectory, with the leapable members actually leaping (ledger)
    while the boot member rides dense: the lockstep tax is gone."""
    from kaboodle_tpu.fleet.core import FleetState, member_state

    n, ticks = 20, 24
    cfg = SwimConfig(ping_timeout_ticks=64)
    members = [
        _converged_init(n, seed=0),
        _drain_state(n, cfg, [n // 2], seed=1),
        init_state(n, seed=2, ring_contacts=2),
    ]
    # Align tick counters? No — members keep their own clocks; the runner
    # targets each member's entry tick + budget independently.
    mesh_state = jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *members)
    fleet = FleetState(mesh=mesh_state, drop_rate=jnp.zeros((3,), jnp.float32))
    ledger = WarpLedger()
    out, ticks_run, conv = run_fleet_warped(fleet, cfg, ticks,
                                            recheck_every=4, ledger=ledger)
    assert int(ticks_run) == ticks
    for e in range(3):
        ref, _ = _dense_trajectory(members[e], cfg, ticks)
        _assert_leaves_equal(ref, member_state(out, e), f"member {e}")
    engines = {r["engine"] for r in ledger.spans}
    assert engines & {"fleet-leap", "fleet-hybrid"}, engines


def test_fleet_per_member_matches_standalone_run_warped():
    """Member k of a warped fleet == the standalone run_warped result (both
    equal dense, transitively — pinned directly here)."""
    from kaboodle_tpu.fleet.core import FleetState, member_state

    n, ticks = 16, 20
    cfg = SwimConfig(ping_timeout_ticks=48)
    members = [_converged_init(n, seed=0), _drain_state(n, cfg, [3], seed=5)]
    mesh_state = jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *members)
    fleet = FleetState(mesh=mesh_state, drop_rate=jnp.zeros((2,), jnp.float32))
    out, _, _ = run_fleet_warped(fleet, cfg, ticks)
    for e in range(2):
        ref, _, _ = run_warped(members[e], cfg, ticks)
        _assert_leaves_equal(ref, member_state(out, e), f"member {e}")


# ---------------------------------------------------------------------------
# satellite: converge-loop entry check


def test_converge_loop_entry_converged_runs_zero_ticks():
    """An already-converged mesh reports ticks_run == 0 with its state
    untouched (the satellite regression: it used to always execute one
    tick)."""
    n = 24
    cfg = SwimConfig()
    st = _converged_init(n, seed=0)
    out, ticks, conv = run_until_converged(st, cfg, max_ticks=16)
    assert int(ticks) == 0 and bool(conv)
    _assert_leaves_equal(st, out, "entry state")


def test_converge_loop_entry_check_sharded():
    from kaboodle_tpu.parallel import make_mesh, run_until_converged_sharded, shard_state

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 (virtual) devices")
    n = 32
    mesh = make_mesh(8)
    cfg = SwimConfig()
    st = shard_state(_converged_init(n, seed=0), mesh)
    out, ticks, conv = run_until_converged_sharded(st, cfg, mesh, max_ticks=16)
    assert int(ticks) == 0 and bool(conv)
    _assert_leaves_equal(st, out, "sharded entry state")


def test_fleet_converge_loop_entry_converged_member():
    """A fleet whose members are all converged at entry freezes immediately:
    conv_tick all zero, states untouched — matching the standalone loop."""
    from kaboodle_tpu.fleet.core import init_fleet, member_state, run_fleet_until_converged

    n, e = 16, 3
    cfg = SwimConfig()
    fleet = init_fleet(n, e, ring_contacts=n - 1, announced=True)
    out, conv_tick, done = run_fleet_until_converged(fleet, cfg, max_ticks=8)
    assert np.asarray(done).all()
    assert np.asarray(conv_tick).tolist() == [0] * e
    for k in range(e):
        _assert_leaves_equal(
            member_state(fleet, k), member_state(out, k), f"member {k}"
        )
